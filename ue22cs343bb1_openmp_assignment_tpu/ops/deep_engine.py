"""Deep-window transactional engine: dense own-entry chains plus
absorbed remote requests.

Round 2's device calibration (scripts/prof_backedge*.py, PERF.md)
overturned the round-1 cost model: per-kernel dispatch inside a
compiled loop is ~free; the binding cost is **scatter/gather index
count** (~5-6 us per 1K indices per pass). The multi-transaction window
engine (ops/sync_engine._round_step_multi) pays gather/scatter indices
for *every* transaction, and its window algebra truncates at the
second touch of any directory entry, committing ~2.2 of a K=3 budget.

This engine re-partitions the round by *locality*, exploiting the dm
table layout (row index == packed address): reshaped ``[N, S, cols]``,
node n's own directory entries ARE row n — **a node's transactions on
its own entries need no gather, no scatter, and no claim**. The fold
composes arbitrarily deep chains on own entries (fill -> evict ->
refill -> upgrade -> ...) as pure dense arithmetic, and only *remote*
touches (fill requests and eviction notices to other homes) pay
indices. At the bench workload's 80% locality this retires most of a
W-instruction window per node per round instead of ~2.2.

Protocol semantics are the reference's 13-handler contract collapsed
into atomic transactions, exactly as ops/sync_engine (SURVEY §3.2-3.5;
``assignment.c:190-618`` is the message-level original): same MESI +
EM/S/U directory transitions, same quirks where they are observable at
transaction granularity (e.g. the UPGRADE handler's unconditional
dir->EM{requester} regardless of directory state,
``assignment.c:325-349`` — see the UP composition below).

Round serialization argument (why every committed round is a legal
serialization of the reference machine):

1. **Phase H** — every node's pre-first-transaction hit prefix.
   Node-local, serialized first (as in _round_step_multi).
2. **Chain phase** — each node's committed window segment: hits and
   own-entry transactions. Chains of two nodes touch disjoint
   directory rows (own entries only), so any relative order works;
   program order within each node is preserved by construction.
   Mid-window hits on *own* entries are unconditionally safe: foreign
   effects on an own entry can only arrive as requests, and requests
   serialize after all chains. Mid-window hits on *remote* lines are
   safe unless that entry's home chain-transacted on it this round —
   detected via the home's dense **marker** flag (gathered per hit);
   a fresh marker truncates the window at the hit (the home's kill or
   downgrade may not admit a consistent order with our later reads).
3. **Request phase** — remote fill requests (RD/WR/UP) and eviction
   notices (EV_S/EV_M) compose *after* the chains: a wave-0 winner
   per entry (scatter-min lane on DM_CLAIM, priority-first: a node
   that wins one of its wave-0 events wins all of them, so crossed
   evict/fill pairs cannot starve each other), then with
   ``cfg.deep_waves > 1`` up to deep_waves - 1 further fill requests
   per entry, each composing against the previous wave's committed
   row (mixed read/write sequences included — per-line outcomes stay
   exact through the wave-stamp fan-out encoding below). Waves
   arbitrate sequentially under the same strict priority keys, so a
   winning node keeps winning its later slots (whole windows commit
   together) and a node's own same-entry events (re-touches) win in
   program order by their slot-index key bits (measured: reshuffled
   per-wave priorities, though fusable into one scatter, scatter the
   wins across nodes and truncate everyone's window — strictly worse).
   A winning fill request reads the latest row and
   writes the composed row back; this absorbs the common collision
   (home chain + foreign requests all committing in one round). Owner
   values are read from the owner's **cv_req snapshot** (its cache as
   of its own first fill-request attempt) — or, when the owner
   acquired the line THIS round, from the round-value channel packed
   into DM_REQ's high bits by the earlier wave's commit. Conflicts
   between a home's chain and foreign events on its entries are
   resolved by a **priority total order** — the lower-priority side
   gives way, mutually consistently, so the global-minimum-priority
   node always advances (the progress guarantee):

   * **marker vs notice** — a notice's evictor was a holder, so a
     same-round chain touch of its entry always set the home's dense
     *marker* flag. If the home's priority wins, the notice aborts;
     otherwise the chain yields (truncates) at its touch and the
     notice composes on the untouched row.
   * **poison vs request** — a request must not observe chain ops the
     home executed at or after the home's own first fill-request
     attempt (else two windows can require each other's later
     segments to precede their own earlier ones — an order cycle).
     Such entries carry the home's dense *poison* flag: the
     lower-priority side (request, or the home's post-request touch)
     gives way.
   * **pending rows compose, no abort** — a chain that evicts a
     SHARED own line leaving one sharer promotes an owner it cannot
     name (the engine is bitvector-free; the promoted line
     self-reports in the fan-out) and records owner = -1. SHARED
     lines are clean in this protocol (every downgrade/flush writes
     memory), so the promoted line's value equals the row's memory —
     requests and notices compose on pending rows using mem, with a
     promote-then-X action override (read nets DOWNGRADE, write
     KILLs, the promotee's own notice cancels).

   Marker and poison are *fold outputs of the home*, dense over its
   own slice — reshaping ``[N, S] -> [E]`` makes them gatherable with
   zero scatters; they are attempt-based (conservative), costing only
   retries, never soundness — with one sound relaxation: a requester
   with NO attempted post-request own-row touches ("clean") cannot
   sit inside any composition-order cycle, so its requests compose on
   poisoned rows even when the home's priority wins. A lost lane,
   losing-priority abort, or unsafe hit truncates retirement at its
   window position, so the retired stream is always a program-order
   prefix.
4. **Fan-out** — kills/downgrades/promotions apply to holder lines by
   tag at round end, exactly like ops/sync_engine (the vectorized
   INV / WRITEBACK_INT / EVICT_SHARED-promotion fan-outs). With
   multiple winners per entry the single blanket action is replaced
   by **wave stamps**: each entry records the wave of its last
   committed write (kw) and last owner-downgrading read (dw), each
   line records the wave it acquired in (aw; pre-round lines 0, the
   chain 1, wave j at j + 2), and a line dies iff aw < kw, downgrades
   iff aw < dw — so mixed read/write wave sequences resolve exactly
   per line (a read after a write spares the flushed writer as SHARED
   while pre-write holders die). The home's own line keeps an exact
   2-bit composed action (act_h); promotions keep a pending bit with
   promote-then-X overrides.

   * **Read storms** (``cfg.deep_read_storm``): after the waves, ALL
     still-losing READ requests commit together as one terminal
     pseudo-wave — reads commute, so k same-round readers compose in
     a single k-aggregated step against the post-wave row (S count +=
     k; an EM owner flushes once and downgrades via the dw stamp; a U
     row grants E to a lone reader, all-SHARED to two or more —
     exactly the reference's read-after-read serialization end state,
     ``assignment.c:211-236``). From its first losing read onward a
     node is in the storm ZONE: every further non-aborted read joins
     the storm point (wave wins revoked, so the node's committed
     slots stay serialization-ordered), and any other slot kind
     truncates the window there — nothing may serialize after the
     storm point.

Progress: a node's own-entry chains never lose arbitration, and the
per-round reshuffled lane priority guarantees some requester wins each
contended entry, so every trace drains (the runners assert the same
claim-key round budget as ops/sync_engine).

Remaining simplifications (each truncates the window, costing rounds,
never correctness): a write to a line the window filled by a remote
*read* stops the window (the E/S fill ambiguity resolves in the
committed cache by next round); slot-budget overflows stop the
window; with ``deep_waves == 1`` re-touching a remote entry stops the
window (with waves, slot-indexed lane keys order same-entry
re-touches across waves and the window proceeds).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp

from ue22cs343bb1_openmp_assignment_tpu import codec
from ue22cs343bb1_openmp_assignment_tpu.config import SystemConfig
from ue22cs343bb1_openmp_assignment_tpu.procedural import procedural_instr
from ue22cs343bb1_openmp_assignment_tpu.types import CacheState, DirState, Op
from ue22cs343bb1_openmp_assignment_tpu.ops import deep_fold
from ue22cs343bb1_openmp_assignment_tpu.ops.sync_engine import (
    DM_ACT, DM_CLAIM, DM_COLS, DM_COUNT, DM_MEM, DM_OWNER, DM_REQ,
    DM_STATE, SyncState, _assert_round_budget, _pack_outside,
    _round_key_rs, claim_max_rounds, slot_bits)

# slot kinds (remote events): fill requests and eviction notices
K_NONE, K_RD, K_WR, K_UP, K_EVS, K_EVM, K_PROBE = 0, 1, 2, 3, 4, 5, 6

# dense per-own-entry flag bits (fold output, reshaped [E], gathered by
# remote events — never scattered)
F_MARK, F_POISON = 1, 2

# fan-out actions; matching sync_engine codes. Deep rounds pack DM_ACT
# as (round << 11) | (act_h << 9) | (promo << 8) | (kw << 4) | dw —
# act_h is the exact 2-bit action for the home's own line, kw/dw are
# the wave-stamp kill/downgrade thresholds, promo the pending-promotion
# bit (see the dense-merge comment in round_step_deep)
ACT_NONE, ACT_DOWN, ACT_KILL, ACT_PROMOTE = 0, 1, 2, 3

_INT_MAX = jnp.iinfo(jnp.int32).max

#: column order of the per-address / per-node abort-attribution planes
#: (round_step_deep(return_profile=True) / run_deep_profile): a slot
#: that failed to commit did so because a poison flag aborted it — a
#: GHOST flag (the committed replay never confirmed the home touch
#:   that raised it) or a REAL one — or a mark aborted its eviction
#: notice, or it lost its arbitration lane, or its cache-hit probe was
#: unsafe. obs/cohprof.py turns these into the measured abort anatomy
#: (the ghost fraction PERF.md previously hand-estimated at ~2/3).
PROFILE_ABORT_CLASSES = ("poison_ghost", "poison_real", "mark",
                         "lane_loss", "probe")

#: column order of the per-node window-stop counters in the same plane
#: (the replay fold's s_* reasons: slot-budget overflow, ownerval-slot
#: overflow, same-entry re-touch, cross-slot dependency, liveness cap)
PROFILE_STOP_CLASSES = ("over_q", "over_g", "dup", "dep", "live")


def state_tiles(cfg: SystemConfig, st: SyncState):
    """Transposed state views both fold backends consume: cache planes
    [C, N], own-directory planes [S, N] (state/count/owner/mem)."""
    N, S = cfg.num_nodes, 1 << cfg.block_bits
    dm_own = st.dm.reshape(N, S, DM_COLS)
    dm_t4 = tuple(dm_own[:, :, col].T
                  for col in (DM_STATE, DM_COUNT, DM_OWNER, DM_MEM))
    return st.cache_addr.T, st.cache_val.T, st.cache_state.T, dm_t4


def _fold_deep(cfg: SystemConfig, st: SyncState, tiles, w_oa, w_val,
               w_live, bad=None, ocode=None):
    """Drive the layout-neutral fold (ops.deep_fold) with a lax.scan
    over window steps, in [N]-vec layout. Inputs and outputs use the
    TRANSPOSED tile layout shared with the Pallas kernels (cache
    [C, N], own-slice [S, N], slots [Q, N], window [W, N]) so neither
    backend pays per-field transposes in the round middle.

    Pre-pass: bad/ocode None (attempt-everything, no truncation);
    replay: bad [Q, N] slot verdicts + ocode [S, N] own-lane codes.
    Returns the final carry with list fields stacked back to [rows, N]
    arrays. A scan keeps the traced graph W-independent (in-loop
    backedges are ~free on the bench device, while an unrolled fold's
    XLA compile time exploded with W)."""
    N, C, S = cfg.num_nodes, cfg.cache_size, 1 << cfg.block_bits
    W = cfg.drain_depth + cfg.txn_width
    Q = cfg.deep_slots
    ca_t, cv_t, cs_t, dm_t4 = tiles
    rows = jnp.arange(N, dtype=jnp.int32)
    zero = jnp.zeros((N,), jnp.int32)
    false = jnp.zeros((N,), bool)
    carry0 = deep_fold.fold_carry0(
        cfg,
        ca=[ca_t[i] for i in range(C)],
        cv=[cv_t[i] for i in range(C)],
        cs=[cs_t[i] for i in range(C)],
        dm_rows=dict(
            dms=[dm_t4[0][s] for s in range(S)],
            dmc=[dm_t4[1][s] for s in range(S)],
            dmo=[dm_t4[2][s] for s in range(S)],
            dmm=[dm_t4[3][s] for s in range(S)]),
        zero=zero, false=false)
    badL = [zero] * Q if bad is None else [bad[q] for q in range(Q)]
    ocodeL = ([zero] * S if ocode is None
              else [ocode[s] for s in range(S)])
    horizon = st.horizon

    def body(c, x):
        oa, val, live, k = x
        return deep_fold.fold_step(cfg, c, rows, oa, val, live, k,
                                   horizon, badL, ocodeL), None

    xs = (w_oa, w_val, w_live, jnp.arange(W, dtype=jnp.int32))
    fin, _ = jax.lax.scan(body, carry0, xs, length=W)
    out = dict(fin)
    for f in ("ca", "cv", "cs", "cv_src", "rrf", "wf", "lwh", "cv_req",
              "cv_req_src", "dms", "dmc", "dmo", "dmm", "dmm_src",
              "touched", "act_acc", "mark", "poison", "kind", "ent",
              "sval", "pos", "comm", "rel", "relv", "reld", "g_owner",
              "g_ci"):
        out[f] = jnp.stack(fin[f], axis=0)
    out["cnt"] = dict(rd_miss=fin["c_rd"], wr_miss=fin["c_wr"],
                      upg=fin["c_up"], ev=fin["c_ev"])
    return out


class XlaIndexOps:
    """The round middle's index-op seam: the 7 scatter/gather families
    between the folds, as native XLA ops (gather/scatter HLOs).

    ``deep_round_core`` routes EVERY dynamic memory access through one
    of these methods; everything else in the middle is dense. The
    fused Pallas round kernel (ops/pallas_round) substitutes
    ``RoutedIndexOps`` — the same seven ops as exact one-hot f32
    matmuls, which Mosaic can lower (TPU Pallas has no vector
    gather/scatter) — and inherits the rest of the middle verbatim, so
    the two paths are bit-identical by construction up to the routed
    ops, whose exactness the parity tests pin.

    Contracts: gather indices are in-range (callers clip); scatter
    indices use the one-past-the-end sentinel for dropped lanes
    (``mode="drop"`` here, zero one-hot rows in the routed version);
    ``scatter_rows``/``scatter_col`` indices are unique among
    non-dropped lanes (at most one committed slot per entry per wave —
    the read-storm's duplicate-row commits are the one exception, and
    the fused path refuses storm configs for exactly that reason)."""
    native = True

    def scatter_min(self, dest, idx, vals):
        """dest[idx] = min(dest[idx], vals) with drop semantics."""
        return dest.at[idx].min(vals, mode="drop")

    def gather(self, plane, idx):
        """plane[idx] for a 1-D plane; idx any shape, in-range."""
        return plane[idx]

    def gather_rows(self, mat, idx):
        """mat[idx] for [M, K] mat -> [*idx.shape, K]."""
        return mat[idx]

    def scatter_rows(self, mat, idx, rows_):
        """mat[idx] = rows_ with drop semantics; idx unique."""
        return mat.at[idx].set(rows_, mode="drop")

    def scatter_col(self, mat, idx, col, vals):
        """mat[idx, col] = vals with drop semantics; idx unique."""
        return mat.at[idx, col].set(vals, mode="drop")


def round_step_deep(cfg: SystemConfig, st: SyncState,
                    with_events: bool = False,
                    return_stats: bool = False,
                    fold_impl: str = "xla",
                    index_ops=None,
                    return_profile: bool = False):
    """One deep-window round. See module docstring for the design.

    ``fold_impl`` selects how the two W-step folds execute: ``"xla"``
    (a lax.scan over deep_fold.fold_step in [N]-vec layout) or
    ``"pallas"`` (ops.pallas_deep's fused TPU kernels in [1, T]
    lane-row layout). The arbitration/composition/fan-out middle is
    THIS function either way — the fold backends are bit-identical
    (tests/test_pallas_deep.py), so the rounds are too. The middle
    runs in the folds' transposed tile layout (slots [Q, N], own
    slices [S, N], cache [C, N]) so neither backend pays per-field
    transposes.

    ``with_events=True`` additionally returns the round's retirement
    record — per-node, per-window-step (op, addr, value, retired), the
    same contract as ``_round_step_multi`` — and the return becomes
    ``(state, events)``. The retired stream is always a program-order
    prefix (module docstring), so the record is simply the first
    ``n_ret`` window steps.

    ``return_stats=True`` instead returns ``(state, stats)`` with the
    round's anatomy as scalar sums (attempted/committed slots by kind,
    lane losses, priority aborts, truncated/stopped node counts) — the
    measurement surface behind scripts/prof_deepstats.py.

    ``return_profile=True`` instead returns ``(state, prof_delta)``:
    the round's coherence-profiler contribution as ADDITIVE planes
    (per-(node, address) retired accesses, the per-address /
    per-node abort attribution split poison-ghost / poison-real /
    mark / lane-loss / probe, window-stop reasons, and the raised-vs-
    committed poison-flag pair behind the measured ghost fraction) —
    run_deep_profile sums them across rounds; obs/cohprof.py reduces
    the total into the ``cache-sim/profile/v1`` doc. XLA fold only,
    like return_stats."""
    if with_events and return_stats:
        raise ValueError("with_events and return_stats are mutually "
                         "exclusive (one round returns one extra value)")
    if return_profile and (with_events or return_stats):
        raise ValueError("return_profile is exclusive with with_events/"
                         "return_stats (one round returns one extra "
                         "value)")
    if (return_stats or return_profile) and fold_impl != "xla":
        raise ValueError("return_stats/return_profile need the XLA fold "
                         "(the Pallas kernels do not export the anatomy "
                         "fields)")
    N, C, S = cfg.num_nodes, cfg.cache_size, 1 << cfg.block_bits
    E = N * S
    W = cfg.drain_depth + cfg.txn_width
    Q = cfg.deep_slots
    G = cfg.deep_ownerval_slots
    T = st.instr_pack.shape[1]
    INV = int(CacheState.INVALID)
    MOD = int(CacheState.MODIFIED)
    EXC = int(CacheState.EXCLUSIVE)
    SHD = int(CacheState.SHARED)
    D_U, D_S, D_EM = int(DirState.U), int(DirState.S), int(DirState.EM)
    rows = jnp.arange(N, dtype=jnp.int32)
    dm_own = st.dm.reshape(N, S, DM_COLS)
    tiles = state_tiles(cfg, st)

    # ---- instruction window, [W, N] (shared with the Pallas kernels) ----
    offs_w = jnp.arange(W, dtype=jnp.int32)[:, None]
    w_idx = st.idx[None, :] + offs_w
    w_live = w_idx < st.instr_count[None, :]
    if cfg.procedural:
        w_oa, w_val = procedural_instr(cfg, rows[None, :], w_idx)
    else:
        w_flat = rows[None, :] * T + jnp.minimum(w_idx, T - 1)
        w = st.instr_pack.reshape(N * T, 2)[w_flat]
        w_oa, w_val = w[..., 0], w[..., 1]

    # ---- pre-pass fold (attempt everything) ------------------------------
    if fold_impl == "pallas":
        from ue22cs343bb1_openmp_assignment_tpu.ops import pallas_deep
        pre = pallas_deep.fold_pre(cfg, st, tiles, w_oa, w_val, w_live)

        def fold_flags_fn(oc):
            return pallas_deep.fold_flags(cfg, st, tiles, w_oa, w_val,
                                          w_live, oc)

        def fold_replay_fn(bad, oc):
            return pallas_deep.fold_replay(cfg, st, tiles, w_oa, w_val,
                                           w_live, bad, oc)
    else:
        pre = _fold_deep(cfg, st, tiles, w_oa, w_val, w_live)

        def fold_flags_fn(oc):
            return _fold_deep(cfg, st, tiles, w_oa, w_val, w_live,
                              bad=None, ocode=oc)

        def fold_replay_fn(bad, oc):
            return _fold_deep(cfg, st, tiles, w_oa, w_val, w_live,
                              bad=bad, ocode=oc)
    core = deep_round_core(cfg, st.dm, st.round, st.seed, pre,
                           fold_flags_fn, fold_replay_fn,
                           index_ops if index_ops is not None
                           else XlaIndexOps())
    return _finish_round_deep(cfg, st, core, w_oa, w_val, with_events,
                              return_stats, return_profile)


def deep_round_core(cfg: SystemConfig, dm0, round_, seed, pre,
                    fold_flags_fn, fold_replay_fn, ix):
    """The deep round's arbitration/composition/fan-out middle — from
    the pre-pass fold's slots through the fan-out, i.e. everything
    between the window build and the metrics update — with every
    dynamic memory access routed through ``ix`` (XlaIndexOps, or the
    fused kernel's RoutedIndexOps) and the two later folds injected as
    callbacks (their backend differs per caller: lax.scan, the Pallas
    fold kernels, or in-kernel array folds inside the fused round).

    Pure array-in/array-out (``dm0`` [E, DM_COLS]; round/seed traced
    scalars), so the IDENTICAL middle runs as the XLA reference path
    AND inside ops/pallas_round's fused kernel — bit-identity of the
    two paths reduces to exactness of the routed index ops, which the
    parity tests pin. Returns a dict: post-round cache planes [C, N],
    directory [E, DM_COLS], per-node metric delta rows [10, N], the
    replay-fold output, and the dense internals the stats/events
    tails consume."""
    N, C, S = cfg.num_nodes, cfg.cache_size, 1 << cfg.block_bits
    E = N * S
    Q = cfg.deep_slots
    G = cfg.deep_ownerval_slots
    INV = int(CacheState.INVALID)
    EXC = int(CacheState.EXCLUSIVE)
    SHD = int(CacheState.SHARED)
    D_U, D_S, D_EM = int(DirState.U), int(DirState.S), int(DirState.EM)
    rows = jnp.arange(N, dtype=jnp.int32)
    dm_own = dm0.reshape(N, S, DM_COLS)
    # identity test: ix.native is a host bool class attribute
    if cfg.deep_read_storm and ix.native is not True:
        raise ValueError("deep_read_storm needs native index ops: the "
                         "storm's duplicate-row commits are outside "
                         "the routed scatters' uniqueness contract")
    kind, ent, sval = pre["kind"], pre["ent"], pre["sval"]   # [Q, N]
    is_req = (kind == K_RD) | (kind == K_WR) | (kind == K_UP)
    is_ev = (kind == K_EVS) | (kind == K_EVM)
    is_probe = kind == K_PROBE

    # ---- lane scatter (requests + notices only) --------------------------
    # lane key layout: [countdown | prio | slot | ev_bit] — arbitration
    # among same-round events is priority-first (a node that wins one
    # of its events wins all of them, so crossed evict/fill pairs
    # cannot starve each other). The slot bits (present only when
    # deep_waves > 1) order a node's OWN same-entry events by program
    # position, which is what makes same-entry re-touches (the old dup
    # window stop) composable across waves; the ev bit is a tiebreak
    # tag that lets the chain-yield and probe rules tell notices from
    # fill requests.
    prio_bits = max(1, (N - 1).bit_length())
    SB = slot_bits(cfg)
    rk = _round_key_rs(cfg, round_, seed, rows)
    prio = rk & ((1 << prio_bits) - 1)
    countdown = rk >> prio_bits
    # read-storm key layout (cfg.deep_read_storm): one extra is_rd bit
    # ABOVE the priority bits, so ANY non-read event beats ANY read —
    # reads never win contested lanes and always compose at the
    # terminal storm point instead. This is what lets eviction notices
    # and writes through entries that straggler reads would otherwise
    # camp on (lu's old-pivot entries), and it makes "the lane minimum
    # is a notice" imply "no fill commits on this entry this round"
    # (the notice-storm soundness gate). Costs one countdown bit
    # (claim_max_rounds accounts for it).
    ST = 1 if cfg.deep_read_storm else 0
    key = ((countdown << (prio_bits + 1 + SB + ST))
           | (prio << (1 + SB)))                             # fill key
    key_q = key[None, :]
    if SB:
        key_q = key_q | (jnp.arange(Q, dtype=jnp.int32)[:, None] << 1)
    key_q = jnp.where(is_ev, key_q | 1,
                      jnp.broadcast_to(key_q, (Q, N)))       # [Q, N]
    if ST:
        key_q = jnp.where(kind == K_RD,
                          key_q | (1 << (prio_bits + 1 + SB)), key_q)
    lane_idx = jnp.where(is_req | is_ev, ent, E).reshape(-1)
    claim = ix.scatter_min(dm0[:, DM_CLAIM], lane_idx,
                           key_q.reshape(-1))                 # [E]

    safe_ent = jnp.clip(ent, 0, E - 1)
    # fresh lane keys this round sit strictly below every stale key (the
    # DM_CLAIM countdown invariant, ops/sync_engine)
    thresh = (jnp.maximum(claim_max_rounds(cfg) - round_, 0) + 1) \
        << (prio_bits + 1 + SB + ST)
    pmask = (1 << prio_bits) - 1
    prio_self = prio[None, :]                                # [1, N]
    # chain-yield codes (dense own-slice reads — own entries are never
    # our own lane targets, so any fresh key there is foreign). The
    # yield rules themselves run inside the replay fold
    # (deep_fold.fold_step, the y_bad section): a chain TXN touch
    # yields to a winning fresh notice at any position and to a winning
    # fresh fill request after our first request attempt; post-request
    # own HITS yield to fresh fill requests. Flag-free (lane keys
    # only), so the flag-pass fold below can consume it too.
    own_lane = claim.reshape(N, S).T
    o_fresh = own_lane < thresh                              # [S, N]
    o_ev = (own_lane & 1) == 1
    o_beats = ((own_lane >> (1 + SB)) & pmask) < prio[None, :]  # sender wins
    # per-entry code bits, deep_fold.OC_*: 1 = fresh, 2 = fresh EV,
    # 4 = fresh & sender beats the home's priority
    o_code = (o_fresh.astype(jnp.int32) * deep_fold.OC_FRESH
              | (o_fresh & o_ev).astype(jnp.int32) * deep_fold.OC_EV
              | (o_fresh & o_beats).astype(jnp.int32)
              * deep_fold.OC_BEATS)                          # [S, N]

    # ---- flag-pass fold: commit-prefix-sharp marker/poison (round 5) ----
    # The round-4 flags were attempt-based over the full horizon W; at
    # committed depth ~4.6 vs horizon ~13, ~2/3 of poison flags were
    # GHOSTS from attempts beyond the committed prefix, and the
    # resulting aborts pinned depth (the ghost-abort feedback loop,
    # PERF.md). Here a third fold pass re-runs the window truncated by
    # the DENSE flag-free verdicts only — in-fold stops and chain
    # yields (o_code) — and its retirement-gated mark/poison outputs
    # flag only the touches inside that prefix. Soundness: the flag
    # pass's truncation set is pointwise a SUBSET of the final
    # replay's (the final adds slot verdicts — lane losses and
    # flag-based aborts — on top), so the flag-pass prefix is a
    # SUPERSET of the final committed prefix, and the sharper flags
    # still over-approximate every committed touch — the same
    # conservativity contract as the round-4 flags, minus the ghosts
    # beyond yield/stop points. No circularity: o_code depends only on
    # the lane scatter, never on other homes' flags. Using ONLY dense
    # verdicts (no per-slot bad) keeps the flag gather fusable with
    # the lane gather below — the whole pass costs one extra fold and
    # zero extra index ops (measured: the slot-verdict variant's extra
    # [Q, N] gather cost more than its sharper flags bought back).
    if cfg.deep_exact_flags:
        fpass = fold_flags_fn(o_code)
        flag_mark, flag_poison = fpass["mark"], fpass["poison"]
    else:
        flag_mark, flag_poison = pre["mark"], pre["poison"]
    poison_src = flag_poison

    # ---- gathers: lane-back + dense home flags (ONE fused gather) --------
    flags_arr = (flag_mark.astype(jnp.int32) * F_MARK
                 + flag_poison.astype(jnp.int32)
                 * F_POISON).T.reshape(E)
    side = jnp.stack([claim, flags_arr], axis=-1)
    got2 = ix.gather_rows(side, safe_ent)                    # [Q, N, 2]
    lane_got, got_flags = got2[..., 0], got2[..., 1]

    # ---- truncation ------------------------------------------------------
    lane_fresh = lane_got < thresh
    lane_is_ev = (lane_got & 1) == 1
    won = lane_got == key_q
    # priority symmetry-breaking between a home's chain and foreign
    # events on its entries: the lower-priority side gives way, and the
    # global-minimum-priority node never yields, aborts, or loses — so
    # every round someone (in practice almost everyone) advances. The
    # per-node priority is a pure bijection of the node id, so the
    # home's priority needs no gather. Marks/poison over-approximate
    # committed touches (conservative): aborting on a ghost touch
    # costs a retry, never soundness.
    prio_home = (_round_key_rs(cfg, round_, seed,
                               safe_ent >> cfg.block_bits) & pmask)
    home_wins = prio_home < prio_self                        # [Q, N]
    # the clean-requester relaxation (round 4): the poison rule exists
    # to break composition-order cycles, and every node in such a cycle
    # must have an own-row touch at-or-after its own first fill-request
    # attempt (the cycle's incoming edge composes on that touch). A
    # node with NO such attempted touch — "clean" — cannot be inside
    # any cycle, so its requests may compose on poisoned rows even when
    # the home's priority wins. Computed from flags that
    # over-approximate the committed touches (the final replay prefix
    # is contained in both the pre-pass and the flag pass), so clean
    # is sound, not just heuristic.
    clean_self = ~jnp.any(poison_src, axis=0)                # [N]
    req_abort = (is_req & ((got_flags & F_POISON) != 0) & home_wins
                 & ~clean_self[None, :])
    aborting = (req_abort
                | (is_ev & ((got_flags & F_MARK) != 0) & home_wins))
    # ---- absorption waves (cfg.deep_waves > 1) ---------------------------
    # extra per-entry winners: after the wave-0 lane, up to
    # deep_waves-1 additional FILL REQUESTS commit per entry, each
    # composing against the previous wave's row (mixed read/write
    # sequences included — the wave-stamp fan-out encoding below keeps
    # per-line outcomes exact for any class sequence). Eligibility is
    # exactly "not poison-aborted": a poisoned entry's ~home_wins
    # candidates are safe because the chain-yield signal rides the
    # wave-0 lane MINIMUM key, which bounds every candidate's priority
    # from below — if any candidate beats the home, so does the lane
    # minimum, and the chain yields; home_wins candidates compose only
    # when clean (no cycle, see above). Notices stay single-wave (a
    # notice composing after a same-round foreign event has no legal
    # serialization). Lost-in-all-waves feeds the replay fold's
    # truncation exactly like a wave-0 loss.
    won_list = [won]
    won_any = won
    for _ in range(cfg.deep_waves - 1):
        # sequential wave arbitration under the SAME strict priority
        # keys: each wave's min over the not-yet-won candidates picks
        # the next winner per entry, so a high-priority node still
        # wins ALL its slots across consecutive waves (the window
        # coherence that lets whole windows commit together), and a
        # node's own same-entry events win in program order by their
        # slot-index key bits alone (same node => same priority, so
        # the earlier slot's lower key wins the earlier wave).
        cand = is_req & ~req_abort & ~won_any
        wave_idx = jnp.where(cand, ent, E).reshape(-1)
        lane_j = ix.scatter_min(jnp.full((E,), _INT_MAX, jnp.int32),
                                wave_idx, key_q.reshape(-1))
        won_j = cand & (ix.gather(lane_j, safe_ent) == key_q)
        won_list.append(won_j)
        won_any = won_any | won_j
    # ---- read-storm bulk grant (cfg.deep_read_storm) ---------------------
    # After the waves, ALL still-losing READ requests commit together
    # as one final pseudo-wave: reads commute, so any number of
    # same-round readers compose in a single k-aggregated step against
    # the post-wave row (the many-readers-one-entry serialization the
    # per-entry claim lane otherwise spreads over k rounds — lu's
    # pivot rows, hotspot's read half; assignment.c:211-236 is the
    # message-level original being batched). Soundness: a storm slot
    # is exactly a wave candidate (same poison/abort gating, same
    # chain-yield lane-minimum argument), serialized after every wave.
    # From its first storm slot onward a node is in the storm ZONE:
    # every further storm-eligible slot (reads; gated EVS notices)
    # joins the SAME terminal serialization point — commuting ops at
    # one point respect program order trivially — and any other slot
    # kind is marked bad, truncating the window there, which keeps
    # the committed stream a program-order prefix and cross-entry
    # serialization acyclic.
    ev_abort = is_ev & ((got_flags & F_MARK) != 0) & home_wins
    if cfg.deep_read_storm:
        # storm ZONE: from the node's first losing (non-aborted) read
        # or EVICT_SHARED notice onward. Inside the zone every further
        # read and EVS notice — lane winners included, their wave wins
        # revoked below — joins the storm point; any OTHER slot kind
        # (write, upgrade, EVICT_MODIFIED, probe) truncates the window
        # there: nothing non-commutative may serialize after the storm
        # point. Reads add sharers, EVS notices remove them — both
        # commute per entry up to the promotion/uncached endpoints,
        # which the k-aggregated composition in the commit loop
        # resolves in a fixed readers-first order (any fixed order of
        # individually-legal granted ops is a legal serialization).
        # With the is_rd key bit, EVERY read composes at the storm
        # point (a read can top a lane only when nothing non-read
        # claimed it, and such wins are revoked below), so the zone
        # opens at the node's FIRST read. An eviction notice may NOT
        # serialize after a same-round KILL-like event on its entry
        # (the evictor's line would have died before it could evict —
        # no legal order). Reads never invalidate, so read storms
        # compose over anything; notice storms are gated on "no fill
        # winner exists on this entry": the is_rd bit makes that
        # exactly "the lane minimum is itself a notice" at a single
        # wave (reads rank below notices, and losing writes/upgrades
        # retry), and with waves > 1 extra fill winners are possible,
        # so notice storms are off there.
        evs_ok = (lane_is_ev if cfg.deep_waves == 1
                  else jnp.zeros((Q, N), bool))
        opener = ((kind == K_RD)
                  | ((kind == K_EVS) & ~ev_abort & evs_ok & ~won))
        zone = jnp.cumsum(opener.astype(jnp.int32), axis=0) >= 1
        # releases are disabled in storm mode (deep_fold.fold_step),
        # so every non-aborted read is storm-eligible — the progress
        # guarantee needs this: under the is_rd bit a read can never
        # win a contested lane, so storming must be unconditional
        storm_slot = ((((kind == K_RD) & ~req_abort)
                       | ((kind == K_EVS) & ~ev_abort & evs_ok))
                      & zone)                                 # [Q, N]
        zone_bad = zone & ~storm_slot
        req_bad = is_req & ((~won_any & ~storm_slot) | req_abort)
        ev_bad = is_ev & ((~won & ~storm_slot) | ev_abort)
    else:
        storm_slot = jnp.zeros((Q, N), bool)
        zone_bad = jnp.zeros((Q, N), bool)
        req_bad = is_req & (~won_any | req_abort)
        ev_bad = is_ev & (~won | ev_abort)
    # probes: a fresh marker (the entry's home chain-transacted on it)
    # is always unsafe; a fresh foreign FILL request is unsafe only for
    # hits after the node's own first fill request (pre-request hits
    # serialize before all requests — sval carries the stratum bit);
    # eviction notices never endanger a hit
    probe_bad = is_probe & (((got_flags & F_MARK) != 0)
                            | ((sval != 0) & lane_fresh & ~lane_is_ev))
    bad = (req_bad | ev_bad | probe_bad
           | zone_bad).astype(jnp.int32)                     # [Q, N]

    # ---- replay fold (committed prefix) ----------------------------------
    # the fold truncates retirement at the first bad slot or
    # yield-unsafe own touch; rp["comm"] marks the slots that committed
    rp = fold_replay_fn(bad, o_code)

    # ---- dense merge of own rows -----------------------------------------
    # DM_ACT packing (round 4, wave-stamp fan-out): (round << 11) |
    # (act_h << 9) | (promo << 8) | (kw << 4) | dw. act_h is the 2-bit
    # composed action for the HOME's own line (exact, per-line); kw/dw
    # are wave STAMPS — a tag-matching holder line dies iff it acquired
    # before stamp kw (aw < kw), downgrades to SHARED iff aw < dw.
    # Stamps: 0 = none, 1 = the home's chain, j + 2 = absorption wave
    # j. Per-line acquisition stamps aw live in a round-local [C, N]
    # array (pre-round lines 0, wave-j fills j + 2), so mixed
    # read/write wave sequences resolve exactly: each holder compares
    # its own acquisition against the stamps instead of sharing one
    # blanket action.
    rtag = round_ << 11
    acc = rp["act_acc"]                                      # [S, N]
    touched = rp["touched"]
    act_col = jnp.where(
        touched,
        rtag
        | (acc == ACT_PROMOTE).astype(jnp.int32) << 8
        | (acc == ACT_KILL).astype(jnp.int32) << 4
        | (acc == ACT_DOWN).astype(jnp.int32),
        dm_own[:, :, DM_ACT].T)
    # g-slot owner values from the committed cache (phase-H writes only
    # can precede — mid-window foreign hit-writes on marked entries
    # truncate, so cv_post is the serialization-consistent source)
    g_flat = rp["g_ci"] * N + jnp.clip(rp["g_owner"], 0, N - 1)
    g_vals = ix.gather(rp["cv_req"].reshape(-1), g_flat)     # [G, N]
    dmm_m = rp["dmm"]
    cv_m = rp["cv"]
    cv_req_m = rp["cv_req"]
    for g in range(G):
        dmm_m = jnp.where(rp["dmm_src"] == g, g_vals[g:g + 1], dmm_m)
        cv_m = jnp.where(rp["cv_src"] == g, g_vals[g:g + 1], cv_m)
        cv_req_m = jnp.where(rp["cv_req_src"] == g, g_vals[g:g + 1],
                             cv_req_m)
    merged = jnp.stack([
        jnp.where(touched, rp["dms"], dm_own[:, :, DM_STATE].T).T,
        jnp.where(touched, rp["dmc"], dm_own[:, :, DM_COUNT].T).T,
        jnp.where(touched, rp["dmo"], dm_own[:, :, DM_OWNER].T).T,
        jnp.where(touched, dmm_m, dm_own[:, :, DM_MEM].T).T,
        act_col.T,
        jnp.where(touched, jnp.broadcast_to(rows[None, :], (S, N)),
                  dm_own[:, :, DM_REQ].T).T,
        claim.reshape(N, S),
    ], axis=-1).reshape(E, DM_COLS)
    dm = merged

    # ---- request composition (post-merge, per committed slot) ------------
    # one pass per absorption wave: wave j's winners compose against
    # the row as left by wave j-1 (re-gathered after its commit
    # scatter). W-like winners record their written value in a dense
    # round-value array `rv` so later-wave reads/writes on the same
    # entry source the in-flight value (memory is NOT written by
    # write-allocate, quirk; cv_req cannot see this round's fills).
    r_ci = codec.cache_index(cfg, safe_ent)                  # [Q, N]
    req_id = jnp.broadcast_to(rows[None, :], (Q, N))
    commit_acc = jnp.zeros((Q, N), bool)
    rel_acc = jnp.zeros((Q, N), bool)
    patch_acc = jnp.zeros((Q, N), bool)
    fille_acc = jnp.zeros((Q, N), bool)
    fillv_acc = jnp.zeros((Q, N), jnp.int32)
    aw_acc = jnp.zeros((Q, N), jnp.int32)   # per-slot acquisition stamp
    # wave winners inside the storm zone are REVOKED (& ~storm_slot):
    # they re-commit at the storm point instead, so a node's committed
    # slots stay serialization-ordered (waves in slot order, then one
    # terminal storm point for all its zone reads)
    passes = [((is_req | is_ev) & won_j & ~storm_slot, j + 2, False)
              for j, won_j in enumerate(won_list)]
    if cfg.deep_read_storm:
        # the storm pseudo-wave: stamp one past the last wave,
        # k-aggregated composition below
        passes.append((storm_slot, len(won_list) + 2, True))
    storm_committed = jnp.zeros((Q, N), bool)
    for mask_j, stamp, is_storm in passes:
        commit = mask_j & rp["comm"]
        commit_acc = commit_acc | commit
        if is_storm:
            storm_committed = commit
            # aggregated per-entry reader/evictor counts (committed
            # storm slots only), packed into ONE scatter-add and fused
            # into the row gather as an extra column
            packed = ((commit & (kind == K_EVS)).astype(jnp.int32)
                      << 16) | (commit & (kind == K_RD)).astype(
                          jnp.int32)
            cnt_storm = jnp.zeros((E,), jnp.int32).at[
                jnp.where(commit, safe_ent, E).reshape(-1)].add(
                packed.reshape(-1), mode="drop")
            g_rows8 = jnp.concatenate(
                [dm, cnt_storm[:, None]], axis=-1)[safe_ent]
            g_rows = g_rows8[..., :DM_COLS]                  # [Q, N, cols]
            kr = g_rows8[..., DM_COLS] & 0xFFFF              # [Q, N]
            ke = g_rows8[..., DM_COLS] >> 16
        else:
            g_rows = ix.gather_rows(dm, safe_ent)            # [Q, N, cols]
        r_state = g_rows[..., DM_STATE]
        r_cnt = g_rows[..., DM_COUNT]
        r_own = g_rows[..., DM_OWNER]
        r_mem = g_rows[..., DM_MEM]
        r_act = g_rows[..., DM_ACT]
        # a pending row (same-round promotion, owner == -1) serves its
        # memory as the owner value: SHARED lines are clean in this
        # protocol, and the promoted-E line's value equals mem
        r_pend = (r_state == D_EM) & (r_own == -1)
        prev_fresh = (r_act >> 11) == round_
        # the round-value channel rides DM_REQ's high bits (written by
        # earlier waves' commit scatters): bit 8 = owner wrote this
        # round (bits 0-7 its value — write-allocate leaves memory
        # stale, and cv_req cannot see this round's fills), bit 9 =
        # memory already holds the owner's current value (clean
        # acquisition or a flushed release)
        rv_got = jnp.where(prev_fresh,
                           (g_rows[..., DM_REQ] >> 16) & 0x3FF, 0)
        own_val = jnp.where(
            r_pend, r_mem,
            ix.gather(cv_req_m.reshape(-1),
                      r_ci * N + jnp.clip(r_own, 0, N - 1)))
        own_val = jnp.where((rv_got & 0x200) != 0, r_mem, own_val)
        own_val = jnp.where((rv_got & 0x100) != 0, rv_got & 0xFF,
                            own_val)
        r_u = r_state == D_U
        r_s = r_state == D_S
        r_em = r_state == D_EM
        k_rd = commit & (kind == K_RD)
        k_wr = commit & (kind == K_WR)
        k_up = commit & (kind == K_UP)
        k_evs = commit & (kind == K_EVS)
        k_evm = commit & (kind == K_EVM)
        wlike = k_wr | k_up
        prev_ah = jnp.where(prev_fresh, (r_act >> 9) & 3, ACT_NONE)
        prev_promo = prev_fresh & (((r_act >> 8) & 1) == 1)
        prev_kw = jnp.where(prev_fresh, (r_act >> 4) & 15, 0)
        prev_dw = jnp.where(prev_fresh, r_act & 15, 0)
        tgt_home = r_own == (safe_ent >> cfg.block_bits)
        if is_storm:
            # ---- k-aggregated storm composition -------------------------
            # Every committed storm slot on an entry writes the SAME
            # composed row (duplicate scatters must be bit-identical),
            # derived from the aggregate (kr readers, ke evictors)
            # against the post-wave row, serialized READERS-FIRST: any
            # fixed order of the individually-granted commuting ops is
            # a legal serialization, and readers-first keeps the
            # single-reader-on-U exclusive grant (which can only arise
            # with ke == 0, i.e. a true solo slot that may name
            # itself). Evictors must be current holders, so ke <= held
            # and U rows have ke == 0.
            held = jnp.where(r_u, 0, jnp.where(r_em, 1, r_cnt))
            c2 = held + kr - ke
            solo_u = r_u & (kr == 1) & (ke == 0)
            # an EM owner flushes once to serve the readers; pending
            # rows serve memory (own_val handles both)
            flush = r_em & ~r_pend & (kr >= 1)
            n_state = jnp.where(c2 == 0, D_U,
                                jnp.where(c2 >= 2, D_S, D_EM))
            n_cnt = c2
            promo_end = (c2 == 1) & (ke >= 1)
            n_own = jnp.where(solo_u, req_id,
                              jnp.where(promo_end, -1, r_own))
            n_mem = jnp.where(flush, own_val, r_mem)
            rel = jnp.zeros((Q, N), bool)   # pre-released slots excluded
            # home-line action: a flushed owner that is the home's own
            # line downgrades; the promotion endpoint promotes (the
            # home's line is the survivor iff it holds the tag); a
            # pending PROMOTE from an earlier wave downgraded by storm
            # readers nets DOWN; earlier KILL/DOWN persist by max
            my_h = jnp.where(flush & tgt_home, ACT_DOWN,
                             jnp.where(promo_end, ACT_PROMOTE,
                                       ACT_NONE))
            act_h = jnp.where(prev_ah == ACT_PROMOTE,
                              jnp.where(kr >= 1, ACT_DOWN,
                                        jnp.where(c2 == 0, ACT_NONE,
                                                  prev_ah)),
                              jnp.maximum(prev_ah, my_h))
            n_kw = prev_kw
            n_dw = jnp.where(flush, stamp, prev_dw)
            n_promo = jnp.where(commit, promo_end, prev_promo)
            n_act = (rtag | (act_h << 9)
                     | (n_promo.astype(jnp.int32) << 8)
                     | (n_kw << 4) | n_dw)
            # rv is consumed only by later passes; the storm is last
            rv_new = jnp.zeros((Q, N), jnp.int32)
        else:
            # release: the requester displaced its own window fill of
            # this entry later in the window (replay-gated, so only
            # committed displacements count); the slot commits the
            # fill+evict NET row
            rel = rp["rel"] & (k_rd | wlike)
            relv = rp["relv"]
            # new row from composition. An EVICT_SHARED from an E-line
            # holder finds the row EM{evictor} (exactness) and leaves
            # it Uncached — the reference's clear-bit -> 0 sharers
            # path (assignment.c:560-570)
            evs_cnt = jnp.where(r_s, r_cnt - 1, r_cnt)
            n_state = jnp.where(wlike, D_EM,
                       jnp.where(k_rd, jnp.where(r_u, D_EM, D_S),
                        jnp.where(k_evm | (k_evs & r_em), D_U,
                         jnp.where(k_evs & r_s,
                                   jnp.where(evs_cnt == 0, D_U,
                                             jnp.where(evs_cnt == 1,
                                                       D_EM, D_S)),
                                   r_state))))
            n_cnt = jnp.where(wlike | (k_rd & r_u), 1,
                     jnp.where(k_rd & r_em, 2,
                      jnp.where(k_rd & r_s, r_cnt + 1,
                       jnp.where(k_evm | (k_evs & r_em), 0,
                        jnp.where(k_evs & r_s, evs_cnt, r_cnt)))))
            n_own = jnp.where(wlike | (k_rd & r_u), req_id,
                     jnp.where(k_evs & r_s & (evs_cnt == 1), -1, r_own))
            n_mem = jnp.where((k_rd | k_wr) & r_em, own_val,
                              jnp.where(k_evm, sval, r_mem))
            # release net-row overrides: a released read leaves the row
            # as it was (EM keeps its owner, memory takes the owner's
            # flushed value); a released write nets Uncached with our
            # final value
            n_state = jnp.where(rel, jnp.where(wlike, D_U,
                                               jnp.where(r_em, D_EM,
                                                         r_state)),
                                n_state)
            n_cnt = jnp.where(rel, jnp.where(wlike, 0,
                                             jnp.where(r_em, 1, r_cnt)),
                              n_cnt)
            n_own = jnp.where(rel, r_own, n_own)
            n_mem = jnp.where(rel, jnp.where(wlike, relv,
                                             jnp.where(r_em, own_val,
                                                       r_mem)),
                              n_mem)
            # ---- wave-stamp act composition (dense-merge comment) -------
            plain_rd = k_rd & ~rel
            # the home's own line keeps an exact 2-bit composed action
            # (unique line, so promote-then-X composition is explicit)
            my_h = jnp.where(wlike, ACT_KILL,
                    jnp.where(k_rd & r_em & tgt_home,
                              jnp.where(rel, ACT_PROMOTE, ACT_DOWN),
                     jnp.where(k_evs & r_s & (evs_cnt == 1),
                               ACT_PROMOTE, ACT_NONE)))
            act_h = jnp.where(
                prev_ah == ACT_PROMOTE,
                jnp.where(wlike, ACT_KILL,
                          jnp.where(k_rd & rel, ACT_PROMOTE,
                                    jnp.where(k_rd, ACT_DOWN,
                                              ACT_NONE))),
                jnp.maximum(prev_ah, my_h))
            # all other holders resolve against wave stamps: a
            # committed write kills every line acquired before it
            # (aw < kw); a plain read of an EM row downgrades every
            # earlier acquirer (aw < dw) — exactly the current owner
            # plus already-dead lines; promote persists until a later
            # event overrides it (promote-then-read nets a downgrade
            # of the unnamed promotee, promote-then-write kills it, a
            # notice cancels it)
            n_kw = jnp.where(wlike, stamp, prev_kw)
            n_dw = jnp.where(plain_rd & r_em & ~tgt_home, stamp,
                             prev_dw)
            promo_set = ((k_evs & r_s & (evs_cnt == 1))
                         | (k_rd & rel & r_em & ~tgt_home))
            promo_clr = wlike | k_evs | k_evm | (plain_rd & r_em)
            n_promo = jnp.where(promo_set, True,
                                jnp.where(promo_clr, False, prev_promo))
            n_act = (rtag | (act_h << 9)
                     | (n_promo.astype(jnp.int32) << 8)
                     | (n_kw << 4) | n_dw)
            rv_new = jnp.where(wlike & ~rel, 0x100 | (sval & 0xFF),
                      jnp.where((k_rd & r_u & ~rel)
                                | (k_rd & rel & r_em), 0x200, 0))
        rel_acc = rel_acc | rel
        t_idx = jnp.where(commit, safe_ent, E).reshape(-1)
        # multi-slot storm commits write a canonical requester id and
        # the entry's lane key so duplicate scatter rows stay
        # bit-identical. The id sentinel is 0xFFFF: the promo fan-out's
        # not_self test must exclude NO real holder (any tag-matching
        # valid line is a legitimate survivor of a storm promotion).
        # Config caps storm runs at num_nodes <= 32767 — the binding
        # constraint is the evictor count packed as ke << 16 in an
        # int32 scatter-add (sign bit at ke = 32768), which also keeps
        # the sentinel matching nobody.
        if is_storm:
            multi = (kr + ke) >= 2
            req_col = jnp.where(multi, 0xFFFF, req_id)
            key_col = jnp.where(multi, g_rows[..., DM_CLAIM], key_q)
        else:
            req_col, key_col = req_id, key_q
        t_rows = jnp.stack(
            [n_state, n_cnt, n_own, n_mem, n_act,
             req_col | (rv_new << 16), key_col],
            axis=-1).reshape(-1, DM_COLS)
        dm = ix.scatter_rows(dm, t_idx, t_rows)

        # reply patches on the requester's cache: committed remote rd
        # fills resolve E vs S and the fill value here. Accumulated
        # across waves (commits are slot-disjoint) and applied after
        # the loop in WINDOW-SLOT order — a node may commit fills on
        # the same cache index in different waves, and the later
        # window slot must land last. aw_acc records each committed
        # fill slot's acquisition stamp for the fan-out.
        fill_e = k_rd & r_u & (solo_u if is_storm else True)
        fill_val = jnp.where(wlike, sval,
                             jnp.where(r_em, own_val, r_mem))
        # write-like slots patch their own written value too (equal to
        # the fold's — idempotent) so that, applied in window-slot
        # order, they cancel any EARLIER read-fill patch on the same
        # line (rd-then-upgrade pairs on one entry, the speculative-
        # upgrade path); released slots' lines were displaced
        patch = (k_rd | wlike) & ~rel
        patch_acc = patch_acc | patch
        fille_acc = fille_acc | fill_e
        fillv_acc = jnp.where(patch, fill_val, fillv_acc)
        aw_acc = jnp.where(commit & is_req & ~rel, stamp, aw_acc)
    ca_rows = [rp["ca"][c:c + 1] for c in range(C)]
    cv_rows = [cv_m[c:c + 1] for c in range(C)]
    cs_rows = [rp["cs"][c:c + 1] for c in range(C)]
    aw_rows = [jnp.zeros((1, N), jnp.int32) for _ in range(C)]
    for q in range(Q):
        m_q = patch_acc[q:q + 1]
        rci_q = r_ci[q:q + 1]
        fe_q, fv_q = fille_acc[q:q + 1], fillv_acc[q:q + 1]
        s_q = (aw_acc[q] > 0)[None, :]
        st_q = aw_acc[q:q + 1]
        for c in range(C):
            # lwh: a write HIT followed the line's last fill, so the
            # fold's value is newest — no patch may touch it
            oh = (rci_q == c) & m_q & ~rp["lwh"][c:c + 1]
            cs_rows[c] = jnp.where(oh & fe_q, EXC, cs_rows[c])
            cv_rows[c] = jnp.where(oh, fv_q, cv_rows[c])
            ohs = (rci_q == c) & s_q
            aw_rows[c] = jnp.where(ohs, st_q, aw_rows[c])
    ca_c = jnp.concatenate(ca_rows, axis=0)                  # [C, N]
    cv_c = jnp.concatenate(cv_rows, axis=0)
    cs_c = jnp.concatenate(cs_rows, axis=0)
    aw = jnp.concatenate(aw_rows, axis=0)

    # ---- fan-out ---------------------------------------------------------
    # per-entry packed word, gathered once per cached line: bit 27
    # fresh, 25-26 act_h, 24 promo, 20-23 kw, 16-19 dw, 0-15 requester
    # id (num_nodes <= 65536 by the deep-window address-width cap).
    # Non-home lines compare their acquisition stamp aw against kw/dw;
    # the home's line applies the exact act_h.
    line_e = jnp.clip(ca_c, 0, E - 1)                        # [C, N]
    fan_fresh = (dm[:, DM_ACT] >> 11) == round_
    fan_packed = (jnp.where(fan_fresh,
                            ((dm[:, DM_ACT] & 0x7FF) | 0x800) << 16, 0)
                  | (dm[:, DM_REQ] & 0xFFFF))
    line_f = ix.gather(fan_packed, line_e)                   # [C, N]
    fresh = ((line_f >> 27) & 1) == 1
    l_ah = jnp.where(fresh, (line_f >> 25) & 3, ACT_NONE)
    l_promo = fresh & (((line_f >> 24) & 1) == 1)
    l_kw = jnp.where(fresh, (line_f >> 20) & 15, 0)
    l_dw = jnp.where(fresh, (line_f >> 16) & 15, 0)
    l_req = line_f & 0xFFFF
    l_home = line_e >> cfg.block_bits
    i_am_home = l_home == rows[None, :]
    valid = cs_c != INV
    not_self = l_req != rows[None, :]
    kill = valid & jnp.where(i_am_home, l_ah == ACT_KILL, aw < l_kw)
    promo = valid & ~kill & jnp.where(i_am_home, l_ah == ACT_PROMOTE,
                                      l_promo & not_self)
    down = valid & ~kill & ~promo & jnp.where(i_am_home,
                                              l_ah == ACT_DOWN,
                                              aw < l_dw)
    cs_c = jnp.where(kill, INV,
                     jnp.where(promo, EXC,
                               jnp.where(down, SHD, cs_c)))
    dm = ix.scatter_col(dm, jnp.where(promo, line_e, E).reshape(-1),
                        DM_OWNER,
                        jnp.broadcast_to(rows[None, :],
                                         (C, N)).reshape(-1))

    # ---- bookkeeping -----------------------------------------------------
    # replay counters already include retired *remote* transactions (a
    # remote txn retires iff its slots committed — both encoded in
    # trunc), so the committed-slot sums are not added again
    cntr = rp["cnt"]
    delta_rows = jnp.stack([
        rp["n_ret"], rp["rh"], rp["wh"],
        cntr["rd_miss"],
        cntr["wr_miss"],
        cntr["upg"],
        jnp.sum((is_req | is_ev) & ~won_any & ~storm_committed, axis=0,
                dtype=jnp.int32),
        cntr["ev"],
        jnp.sum(kill, axis=0, dtype=jnp.int32),
        jnp.sum(promo, axis=0, dtype=jnp.int32),
    ])                                                       # [10, N]
    return dict(
        ca_c=ca_c, cv_c=cv_c, cs_c=cs_c, dm=dm, rp=rp,
        delta_rows=delta_rows,
        # dense internals for the stats tail (all [Q, N]/[N] bools)
        kind=kind, is_req=is_req, is_ev=is_ev, won_any=won_any,
        aborting=aborting, probe_bad=probe_bad,
        commit_acc=commit_acc, rel_acc=rel_acc,
        clean_self=clean_self, storm_committed=storm_committed,
        # profile-tail extras (return_profile, XLA fold only — the
        # fused-kernel core dict, ops/pallas_round, omits them like the
        # other anatomy fields): slot entry ids, the abort-driving
        # poison source flags, and the poison-side abort mask
        ent=ent, poison_src=poison_src, req_abort=req_abort)


def _finish_round_deep(cfg: SystemConfig, st: SyncState, core,
                       w_oa, w_val, with_events: bool,
                       return_stats: bool,
                       return_profile: bool = False):
    """Fold a deep_round_core result back into the SyncState: metrics
    from the per-node delta rows, window-cursor/horizon advance, and
    the optional stats/events extras. Shared by the XLA reference path
    and the fused-kernel path (ops/pallas_round), which produces the
    same core output dict from the kernel's output buffers."""
    W = cfg.drain_depth + cfg.txn_width
    rp = core["rp"]
    kind = core["kind"]
    deltas = jnp.sum(core["delta_rows"], axis=1)
    mt = st.metrics
    metrics = mt.replace(
        rounds=mt.rounds + 1,
        instrs_retired=mt.instrs_retired + deltas[0],
        read_hits=mt.read_hits + deltas[1],
        write_hits=mt.write_hits + deltas[2],
        read_misses=mt.read_misses + deltas[3],
        write_misses=mt.write_misses + deltas[4],
        upgrades=mt.upgrades + deltas[5],
        conflicts=mt.conflicts + deltas[6],
        evictions=mt.evictions + deltas[7],
        invalidations=mt.invalidations + deltas[8],
        promotions=mt.promotions + deltas[9],
    )
    out = st.replace(cache_addr=core["ca_c"].T, cache_val=core["cv_c"].T,
                     cache_state=core["cs_c"].T,
                     dm=core["dm"], idx=st.idx + rp["n_ret"],
                     horizon=jnp.clip(
                         rp["n_ret"] + cfg.deep_horizon_slack, 2,
                         1 << 20),
                     round=st.round + 1, metrics=metrics)
    if return_stats:
        s_ = lambda x: jnp.sum(x, dtype=jnp.int32)
        is_req, is_ev = core["is_req"], core["is_ev"]
        stats = dict(
            n_ret=s_(rp["n_ret"]), truncated=s_(rp["truncated"]),
            stopped=s_(rp["stopped"]), seen_req=s_(rp["seen_req"]),
            n_slot=s_(rp["n_slot"]), horizon_sum=s_(st.horizon),
            att_rd=s_(kind == K_RD), att_wr=s_(kind == K_WR),
            att_up=s_(kind == K_UP), att_evs=s_(kind == K_EVS),
            att_evm=s_(kind == K_EVM), att_probe=s_(kind == K_PROBE),
            lost=s_((is_req | is_ev) & ~core["won_any"]
                    & ~core["aborting"] & ~core["storm_committed"]),
            abort_poison=s_(core["aborting"] & is_req),
            abort_mark=s_(core["aborting"] & is_ev),
            probe_bad=s_(core["probe_bad"]),
            committed=s_(core["commit_acc"]),
            released=s_(core["rel_acc"]),
            clean=s_(core["clean_self"]),
            storm=s_(core["storm_committed"]),
            stop_overq=s_(rp["s_overq"]), stop_overg=s_(rp["s_overg"]),
            stop_dup=s_(rp["s_dup"]), stop_dep=s_(rp["s_dep"]),
            stop_live=s_(rp["s_live"]))
        return out, stats
    if return_profile:
        # additive profiler deltas (run_deep_profile sums them): the
        # abort attribution distinguishes poison flags the committed
        # replay confirmed (rp["poison"], retirement-gated) from ghosts
        # the speculative flag source raised beyond the committed
        # prefix — the measured form of PERF.md's ghost estimate
        N, S = cfg.num_nodes, 1 << cfg.block_bits
        E = N * S
        rows = jnp.arange(N, dtype=jnp.int32)
        is_req, is_ev = core["is_req"], core["is_ev"]
        real_arr = core["rp"]["poison"].T.reshape(E)          # [E] bool
        ent = jnp.clip(core["ent"], 0, E - 1)                 # [Q, N]
        flag_real = real_arr[ent]
        lost = (is_req | is_ev) & ~core["won_any"] & ~core["aborting"] \
            & ~core["storm_committed"]
        classes = jnp.stack([                                 # [Q, N, 5]
            core["req_abort"] & ~flag_real,
            core["req_abort"] & flag_real,
            core["aborting"] & is_ev,
            lost,
            core["probe_bad"]], axis=-1).astype(jnp.int32)
        any_ab = jnp.sum(classes, axis=-1) > 0
        abort_addr = jnp.zeros((E, 5), jnp.int32).at[
            jnp.where(any_ab, ent, E)].add(classes, mode="drop")
        # retired-access planes from the committed window prefix
        offs = jnp.arange(W, dtype=jnp.int32)[:, None]        # [W, 1]
        ret = offs < rp["n_ret"][None, :]                     # [W, N]
        opw = w_oa >> 28
        addrw = jnp.clip(w_oa & 0x0FFFFFFF, 0, E - 1)
        flat = rows[None, :] * E + addrw                      # [W, N]
        rd = jnp.zeros((N * E,), jnp.int32).at[
            jnp.where(ret & (opw == int(Op.READ)), flat, N * E)].add(
            1, mode="drop").reshape(N, E)
        wr = jnp.zeros((N * E,), jnp.int32).at[
            jnp.where(ret & (opw == int(Op.WRITE)), flat, N * E)].add(
            1, mode="drop").reshape(N, E)
        i32 = jnp.int32
        prof = dict(
            rd=rd, wr=wr,
            abort_addr=abort_addr,                            # [E, 5]
            abort_node=jnp.sum(classes, axis=0),              # [N, 5]
            stops=jnp.stack(                                  # [5, N]
                [rp["s_overq"], rp["s_overg"], rp["s_dup"],
                 rp["s_dep"], rp["s_live"]]).astype(i32),
            poison_raised=jnp.sum(core["poison_src"], dtype=i32),
            poison_committed=jnp.sum(rp["poison"], dtype=i32),
            n_ret=rp["n_ret"].astype(i32))                    # [N]
        return out, prof
    if not with_events:
        return out
    offs_w = jnp.arange(W, dtype=jnp.int32)[:, None]
    events = {"retired": offs_w.T < rp["n_ret"][:, None],   # [N, W]
              "op": w_oa.T >> 28, "addr": w_oa.T & 0x0FFFFFFF,
              "value": w_val.T}
    return out, events


def deep_profile_zeros(cfg: SystemConfig):
    """Zero-initialised accumulator matching round_step_deep's
    return_profile delta dict (see _finish_round_deep) — the scan carry
    of run_deep_profile."""
    N, S = cfg.num_nodes, 1 << cfg.block_bits
    E = N * S
    z = functools.partial(jnp.zeros, dtype=jnp.int32)
    return dict(rd=z((N, E)), wr=z((N, E)),
                abort_addr=z((E, 5)), abort_node=z((N, 5)),
                stops=z((5, N)),
                poison_raised=z(()), poison_committed=z(()),
                n_ret=z((N,)))


def run_deep_profile(cfg: SystemConfig, st: SyncState, n: int):
    """Scan n deep rounds accumulating the coherence-profiler planes.

    Returns ``(state, prof)`` with ``prof`` a deep_profile_zeros dict
    after summation: retired per-(node, address) accesses, the
    PROFILE_ABORT_CLASSES per-address/per-node abort attribution, the
    PROFILE_STOP_CLASSES window-stop counters, and the raised-vs-
    committed poison-flag totals whose ratio is the measured
    ghost-poison fraction (obs/cohprof.py). XLA fold only (the
    return_profile contract); the accumulation rides the scan carry,
    so capture cost is independent of n.
    """
    _assert_round_budget(cfg, st.round, n)
    return _run_deep_profile_jit(cfg, st, n)


@functools.partial(jax.jit, static_argnums=(0, 2))
def _run_deep_profile_jit(cfg: SystemConfig, st: SyncState, n: int):
    carry0, pack = _pack_outside(st)
    prof0 = deep_profile_zeros(cfg)

    def body(carry, _):
        s, p = carry
        out, d = round_step_deep(cfg, s.replace(instr_pack=pack),
                                 return_profile=True)
        p2 = jax.tree.map(lambda a, b: a + b, p, d)
        return (out.replace(instr_pack=carry0.instr_pack), p2), None

    (final, prof), _ = jax.lax.scan(body, (carry0, prof0), None, length=n)
    return final.replace(instr_pack=pack), prof
