"""Deep-window transactional engine: dense own-entry chains plus
absorbed remote requests.

Round 2's device calibration (scripts/prof_backedge*.py, PERF.md)
overturned the round-1 cost model: per-kernel dispatch inside a
compiled loop is ~free; the binding cost is **scatter/gather index
count** (~5-6 us per 1K indices per pass). The multi-transaction window
engine (ops/sync_engine._round_step_multi) pays gather/scatter indices
for *every* transaction, and its window algebra truncates at the
second touch of any directory entry, committing ~2.2 of a K=3 budget.

This engine re-partitions the round by *locality*, exploiting the dm
table layout (row index == packed address): reshaped ``[N, S, cols]``,
node n's own directory entries ARE row n — **a node's transactions on
its own entries need no gather, no scatter, and no claim**. The fold
composes arbitrarily deep chains on own entries (fill -> evict ->
refill -> upgrade -> ...) as pure dense arithmetic, and only *remote*
touches (fill requests and eviction notices to other homes) pay
indices. At the bench workload's 80% locality this retires most of a
W-instruction window per node per round instead of ~2.2.

Protocol semantics are the reference's 13-handler contract collapsed
into atomic transactions, exactly as ops/sync_engine (SURVEY §3.2-3.5;
``assignment.c:190-618`` is the message-level original): same MESI +
EM/S/U directory transitions, same quirks where they are observable at
transaction granularity (e.g. the UPGRADE handler's unconditional
dir->EM{requester} regardless of directory state,
``assignment.c:325-349`` — see the UP composition below).

Round serialization argument (why every committed round is a legal
serialization of the reference machine):

1. **Phase H** — every node's pre-first-transaction hit prefix.
   Node-local, serialized first (as in _round_step_multi).
2. **Chain phase** — each node's committed window segment: hits and
   own-entry transactions. Chains of two nodes touch disjoint
   directory rows (own entries only), so any relative order works;
   program order within each node is preserved by construction.
   Mid-window hits on *own* entries are unconditionally safe: foreign
   effects on an own entry can only arrive as requests, and requests
   serialize after all chains. Mid-window hits on *remote* lines are
   safe unless that entry's home chain-transacted on it this round —
   detected via the home's dense **marker** flag (gathered per hit);
   a fresh marker truncates the window at the hit (the home's kill or
   downgrade may not admit a consistent order with our later reads).
3. **Request phase** — remote fill requests (RD/WR/UP) and eviction
   notices (EV_S/EV_M) compose *after* the chains, at most one per
   entry per round (scatter-min lane on DM_CLAIM, priority-first: a
   node that wins one of its events this round wins all of them, so
   crossed evict/fill pairs cannot starve each other). A winning fill
   request reads the post-chain row and writes the composed row back;
   this absorbs the common collision (home chain + one foreign
   request both commit in one round). Owner values are read from the
   owner's **cv_req snapshot** (its cache as of its own first
   fill-request attempt), which keeps every observed value inside the
   owner's pre-request stratum. Conflicts between a home's chain and
   foreign events on its entries are resolved by a **priority total
   order** — the lower-priority side gives way, mutually
   consistently, so the global-minimum-priority node always advances
   (the progress guarantee):

   * **marker vs notice** — a notice's evictor was a holder, so a
     same-round chain touch of its entry always set the home's dense
     *marker* flag. If the home's priority wins, the notice aborts;
     otherwise the chain yields (truncates) at its touch and the
     notice composes on the untouched row.
   * **poison vs request** — a request must not observe chain ops the
     home executed at or after the home's own first fill-request
     attempt (else two windows can require each other's later
     segments to precede their own earlier ones — an order cycle).
     Such entries carry the home's dense *poison* flag: the
     lower-priority side (request, or the home's post-request touch)
     gives way.
   * **pending rows compose, no abort** — a chain that evicts a
     SHARED own line leaving one sharer promotes an owner it cannot
     name (the engine is bitvector-free; the promoted line
     self-reports in the fan-out) and records owner = -1. SHARED
     lines are clean in this protocol (every downgrade/flush writes
     memory), so the promoted line's value equals the row's memory —
     requests and notices compose on pending rows using mem, with a
     promote-then-X action override (read nets DOWNGRADE, write
     KILLs, the promotee's own notice cancels).

   Marker and poison are *fold outputs of the home*, dense over its
   own slice — reshaping ``[N, S] -> [E]`` makes them gatherable with
   zero scatters; they are attempt-based (conservative), costing only
   retries, never soundness. A lost lane, losing-priority abort, or
   unsafe hit truncates retirement at its window position, so the
   retired stream is always a program-order prefix.
4. **Fan-out** — kills/downgrades/promotions apply to holder lines by
   tag at round end, exactly like ops/sync_engine (the vectorized
   INV / WRITEBACK_INT / EVICT_SHARED-promotion fan-outs). A request
   composing after a chain merges the two actions by severity; the
   request's effect on the home's own line is carried separately
   (act_home) since the home is excluded from its own action.

Progress: a node's own-entry chains never lose arbitration, and the
per-round reshuffled lane priority guarantees some requester wins each
contended entry, so every trace drains (the runners assert the same
claim-key round budget as ops/sync_engine).

v1 simplifications (each truncates the window, costing rounds, never
correctness): a write to a line the window filled by a remote *read*
stops the window (the E/S fill ambiguity resolves in the committed
cache by next round); re-touching a remote entry stops the window
(own entries may be re-touched freely); slot-budget overflows stop
the window.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from ue22cs343bb1_openmp_assignment_tpu import codec
from ue22cs343bb1_openmp_assignment_tpu.config import SystemConfig
from ue22cs343bb1_openmp_assignment_tpu.procedural import procedural_instr
from ue22cs343bb1_openmp_assignment_tpu.types import CacheState, DirState, Op
from ue22cs343bb1_openmp_assignment_tpu.ops import deep_fold
from ue22cs343bb1_openmp_assignment_tpu.ops.sync_engine import (
    DM_ACT, DM_CLAIM, DM_COLS, DM_COUNT, DM_MEM, DM_OWNER, DM_REQ,
    DM_STATE, SyncState, _round_key, claim_max_rounds)

# slot kinds (remote events): fill requests and eviction notices
K_NONE, K_RD, K_WR, K_UP, K_EVS, K_EVM, K_PROBE = 0, 1, 2, 3, 4, 5, 6

# dense per-own-entry flag bits (fold output, reshaped [E], gathered by
# remote events — never scattered)
F_MARK, F_POISON = 1, 2

# fan-out actions; matching sync_engine codes, packed for deep rounds as
# DM_ACT = (round << 4) | (act_home << 2) | act_other
ACT_NONE, ACT_DOWN, ACT_KILL, ACT_PROMOTE = 0, 1, 2, 3

_INT_MAX = jnp.iinfo(jnp.int32).max


def _fold_deep(cfg: SystemConfig, st: SyncState, w_oa, w_val, w_live,
               bad=None, ocode=None):
    """Drive the layout-neutral fold (ops.deep_fold) with a lax.scan
    over window steps, in [N]-vec layout.

    Pre-pass: bad/ocode None (attempt-everything, no truncation);
    replay: bad [N, Q] slot verdicts + ocode [N, S] own-lane codes.
    Returns the final carry with list fields stacked back to arrays.
    A scan keeps the traced graph W-independent (in-loop backedges are
    ~free on the bench device, while an unrolled fold's XLA compile
    time exploded with W)."""
    N, C, S = cfg.num_nodes, cfg.cache_size, 1 << cfg.block_bits
    W = cfg.drain_depth + cfg.txn_width
    Q = cfg.deep_slots
    rows = jnp.arange(N, dtype=jnp.int32)
    zero = jnp.zeros((N,), jnp.int32)
    false = jnp.zeros((N,), bool)
    dm_own = st.dm.reshape(N, S, DM_COLS)
    carry0 = deep_fold.fold_carry0(
        cfg,
        ca=[st.cache_addr[:, i] for i in range(C)],
        cv=[st.cache_val[:, i] for i in range(C)],
        cs=[st.cache_state[:, i] for i in range(C)],
        dm_rows=dict(
            dms=[dm_own[:, s, DM_STATE] for s in range(S)],
            dmc=[dm_own[:, s, DM_COUNT] for s in range(S)],
            dmo=[dm_own[:, s, DM_OWNER] for s in range(S)],
            dmm=[dm_own[:, s, DM_MEM] for s in range(S)]),
        zero=zero, false=false)
    badL = [zero] * Q if bad is None else [bad[:, q] for q in range(Q)]
    ocodeL = ([zero] * S if ocode is None
              else [ocode[:, s] for s in range(S)])
    horizon = st.horizon

    def body(c, x):
        oa, val, live, k = x
        return deep_fold.fold_step(cfg, c, rows, oa, val, live, k,
                                   horizon, badL, ocodeL), None

    xs = (w_oa.T, w_val.T, w_live.T, jnp.arange(W, dtype=jnp.int32))
    fin, _ = jax.lax.scan(body, carry0, xs, length=W)
    out = dict(fin)
    for f in ("ca", "cv", "cs", "cv_src", "rrf", "wf", "cv_req",
              "cv_req_src", "dms", "dmc", "dmo", "dmm", "dmm_src",
              "touched", "act_acc", "mark", "poison", "kind", "ent",
              "sval", "pos", "comm", "rel", "relv", "reld", "g_owner",
              "g_ci"):
        out[f] = jnp.stack(fin[f], axis=1)
    out["cnt"] = dict(rd_miss=fin["c_rd"], wr_miss=fin["c_wr"],
                      upg=fin["c_up"], ev=fin["c_ev"])
    return out


def round_step_deep(cfg: SystemConfig, st: SyncState,
                    with_events: bool = False,
                    return_stats: bool = False):
    """One deep-window round. See module docstring for the design.

    ``with_events=True`` additionally returns the round's retirement
    record — per-node, per-window-step (op, addr, value, retired), the
    same contract as ``_round_step_multi`` — and the return becomes
    ``(state, events)``. The retired stream is always a program-order
    prefix (module docstring), so the record is simply the first
    ``n_ret`` window steps.

    ``return_stats=True`` instead returns ``(state, stats)`` with the
    round's anatomy as scalar sums (attempted/committed slots by kind,
    lane losses, priority aborts, truncated/stopped node counts) — the
    measurement surface behind scripts/prof_deepstats.py."""
    if with_events and return_stats:
        raise ValueError("with_events and return_stats are mutually "
                         "exclusive (one round returns one extra value)")
    N, C, S = cfg.num_nodes, cfg.cache_size, 1 << cfg.block_bits
    E = N * S
    W = cfg.drain_depth + cfg.txn_width
    Q = cfg.deep_slots
    G = cfg.deep_ownerval_slots
    T = st.instr_pack.shape[1]
    INV = int(CacheState.INVALID)
    MOD = int(CacheState.MODIFIED)
    EXC = int(CacheState.EXCLUSIVE)
    SHD = int(CacheState.SHARED)
    D_U, D_S, D_EM = int(DirState.U), int(DirState.S), int(DirState.EM)
    rows = jnp.arange(N, dtype=jnp.int32)

    # ---- instruction window ---------------------------------------------
    offs = jnp.arange(W, dtype=jnp.int32)[None, :]
    w_idx = st.idx[:, None] + offs
    w_live = w_idx < st.instr_count[:, None]
    if cfg.procedural:
        w_oa, w_val = procedural_instr(cfg, rows[:, None], w_idx)
    else:
        w_flat = rows[:, None] * T + jnp.minimum(w_idx, T - 1)
        w = st.instr_pack.reshape(N * T, 2)[w_flat]
        w_oa, w_val = w[..., 0], w[..., 1]

    # ---- pre-pass fold (attempt everything) ------------------------------
    pre = _fold_deep(cfg, st, w_oa, w_val, w_live)
    kind, ent, sval = pre["kind"], pre["ent"], pre["sval"]
    is_req = (kind == K_RD) | (kind == K_WR) | (kind == K_UP)
    is_ev = (kind == K_EVS) | (kind == K_EVM)
    is_probe = kind == K_PROBE

    # ---- lane scatter (requests + notices only) --------------------------
    # lane key layout: [countdown | prio | ev_bit] — arbitration among
    # same-round events is priority-first (a node that wins one of its
    # events wins all of them, so crossed evict/fill pairs cannot
    # starve each other), with the ev bit as a tiebreak tag that lets
    # the chain-yield and probe rules tell notices from fill requests
    prio_bits = max(1, (N - 1).bit_length())
    rk = _round_key(cfg, st, rows)
    prio = rk & ((1 << prio_bits) - 1)
    countdown = rk >> prio_bits
    key = (countdown << (prio_bits + 1)) | (prio << 1)       # fill key
    key_q = jnp.where(is_ev, key[:, None] | 1, key[:, None])  # [N, Q]
    lane_idx = jnp.where(is_req | is_ev, ent, E).reshape(-1)
    dm_claimed = st.dm.at[lane_idx, DM_CLAIM].min(
        key_q.reshape(-1), mode="drop")

    # ---- gathers: lane-back + dense home flags (ONE fused gather) --------
    safe_ent = jnp.clip(ent, 0, E - 1)
    flags_arr = (pre["mark"].astype(jnp.int32) * F_MARK
                 + pre["poison"].astype(jnp.int32) * F_POISON).reshape(E)
    side = jnp.stack([dm_claimed[:, DM_CLAIM], flags_arr], axis=-1)
    got2 = side[safe_ent]                                    # [N, Q, 2]
    lane_got, got_flags = got2[..., 0], got2[..., 1]

    # ---- truncation ------------------------------------------------------
    # fresh lane keys this round sit strictly below every stale key (the
    # DM_CLAIM countdown invariant, ops/sync_engine)
    thresh = (jnp.maximum(claim_max_rounds(cfg) - st.round, 0) + 1) \
        << (prio_bits + 1)
    lane_fresh = lane_got < thresh
    lane_is_ev = (lane_got & 1) == 1
    won = lane_got == key_q
    # priority symmetry-breaking between a home's chain and foreign
    # events on its entries: the lower-priority side gives way, and the
    # global-minimum-priority node never yields, aborts, or loses — so
    # every round someone (in practice almost everyone) advances. The
    # per-node priority is a pure bijection of the node id, so the
    # home's priority needs no gather. Marks/poison are attempt-based
    # (conservative): aborting on a ghost touch costs a retry, never
    # soundness.
    pmask = (1 << prio_bits) - 1
    prio_self = prio                                          # [N]
    prio_home = _round_key(cfg, st, safe_ent >> cfg.block_bits) & pmask
    home_wins = prio_home < prio_self[:, None]               # [N, Q]
    aborting = ((is_req & ((got_flags & F_POISON) != 0) & home_wins)
                | (is_ev & ((got_flags & F_MARK) != 0) & home_wins))
    # ---- absorption waves (cfg.deep_waves > 1) ---------------------------
    # extra per-entry winners: after the wave-0 lane, up to
    # deep_waves-1 additional FILL REQUESTS commit per entry, each
    # composing against the previous wave's row. Restricted to
    # flag-clean entries (no chain conflict -> no order-cycle risk; a
    # chain-touched entry with any foreign interest always carries
    # mark/poison, so clean == chain-untouched) and to requests
    # (notices stay single-wave: a notice composing after a same-round
    # foreign event has no legal serialization). Lost-in-all-waves
    # feeds the replay fold's truncation exactly like a wave-0 loss.
    won_list = [won]
    won_any = won
    if cfg.deep_waves > 1:
        # class homogeneity: all of an entry's wave commits must be the
        # same class as its wave-0 winner — write-like chains (each
        # write kills every earlier holder, so the single composed KILL
        # act is exact) or read-like chains (downgrades only). A MIXED
        # sequence (write then read) has no single-act fan-out
        # encoding: the flushed writer must survive as SHARED while
        # pre-write holders die. Mixed pairs keep wave-0-only behavior.
        wlike_kind = (kind == K_WR) | (kind == K_UP)
        wclass = jnp.zeros((E,), jnp.int32).at[
            jnp.where(won & (is_req | is_ev), ent, E).reshape(-1)].set(
            jnp.where(wlike_kind, 2, 1).reshape(-1), mode="drop")
        got_class = wclass[safe_ent]
        for _ in range(cfg.deep_waves - 1):
            cand = (is_req & (got_flags == 0) & ~won_any
                    & (jnp.where(wlike_kind, 2, 1) == got_class))
            wave_idx = jnp.where(cand, ent, E).reshape(-1)
            lane_j = jnp.full((E,), _INT_MAX, jnp.int32).at[
                wave_idx].min(key_q.reshape(-1), mode="drop")
            won_j = cand & (lane_j[safe_ent] == key_q)
            won_list.append(won_j)
            won_any = won_any | won_j
    req_bad = is_req & (~won_any | (((got_flags & F_POISON) != 0)
                                    & home_wins))
    ev_bad = is_ev & (~won | (((got_flags & F_MARK) != 0)
                              & home_wins))
    # probes: a fresh marker (the entry's home chain-transacted on it)
    # is always unsafe; a fresh foreign FILL request is unsafe only for
    # hits after the node's own first fill request (pre-request hits
    # serialize before all requests — sval carries the stratum bit);
    # eviction notices never endanger a hit
    probe_bad = is_probe & (((got_flags & F_MARK) != 0)
                            | ((sval != 0) & lane_fresh & ~lane_is_ev))
    bad = (req_bad | ev_bad | probe_bad).astype(jnp.int32)   # [N, Q]
    # chain-yield codes (dense own-slice reads — own entries are never
    # our own lane targets, so any fresh key there is foreign). The
    # yield rules themselves run inside the replay fold
    # (deep_fold.fold_step, the y_bad section): a chain TXN touch
    # yields to a winning fresh notice at any position and to a winning
    # fresh fill request after our first request attempt; post-request
    # own HITS yield to fresh fill requests.
    own_lane = dm_claimed.reshape(N, S, DM_COLS)[:, :, DM_CLAIM]
    o_fresh = own_lane < thresh                              # [N, S]
    o_ev = (own_lane & 1) == 1
    o_beats = ((own_lane >> 1) & pmask) < prio_self[:, None]  # sender wins
    # per-entry code bits, deep_fold.OC_*: 1 = fresh, 2 = fresh EV,
    # 4 = fresh & sender beats the home's priority
    o_code = (o_fresh.astype(jnp.int32) * deep_fold.OC_FRESH
              | (o_fresh & o_ev).astype(jnp.int32) * deep_fold.OC_EV
              | (o_fresh & o_beats).astype(jnp.int32)
              * deep_fold.OC_BEATS)                          # [N, S]

    # ---- replay fold (committed prefix) ----------------------------------
    # the fold truncates retirement at the first bad slot or
    # yield-unsafe own touch; rp["comm"] marks the slots that committed
    rp = _fold_deep(cfg, st, w_oa, w_val, w_live, bad=bad, ocode=o_code)

    # ---- dense merge of own rows -----------------------------------------
    rtag = st.round << 4
    act_col = jnp.where(
        rp["touched"],
        rtag | rp["act_acc"],                 # act_home=0 for chain rows
        dm_own_col(st, DM_ACT, N, S))
    # g-slot owner values from the committed cache (phase-H writes only
    # can precede — mid-window foreign hit-writes on marked entries
    # truncate, so cv_post is the serialization-consistent source)
    g_flat = jnp.clip(rp["g_owner"], 0, N - 1) * C + rp["g_ci"]
    g_vals = rp["cv_req"].reshape(-1)[g_flat]                # [N, G]
    dmm_m = rp["dmm"]
    cv_m = rp["cv"]
    cv_req_m = rp["cv_req"]
    for g in range(G):
        dmm_m = jnp.where(rp["dmm_src"] == g, g_vals[:, g:g + 1], dmm_m)
        cv_m = jnp.where(rp["cv_src"] == g, g_vals[:, g:g + 1], cv_m)
        cv_req_m = jnp.where(rp["cv_req_src"] == g, g_vals[:, g:g + 1],
                             cv_req_m)
    merged = jnp.stack([
        jnp.where(rp["touched"], rp["dms"],
                  dm_own_col(st, DM_STATE, N, S)),
        jnp.where(rp["touched"], rp["dmc"],
                  dm_own_col(st, DM_COUNT, N, S)),
        jnp.where(rp["touched"], rp["dmo"],
                  dm_own_col(st, DM_OWNER, N, S)),
        jnp.where(rp["touched"], dmm_m, dm_own_col(st, DM_MEM, N, S)),
        act_col,
        jnp.where(rp["touched"], jnp.broadcast_to(rows[:, None], (N, S)),
                  dm_own_col(st, DM_REQ, N, S)),
        dm_claimed.reshape(N, S, DM_COLS)[:, :, DM_CLAIM],
    ], axis=-1).reshape(E, DM_COLS)
    dm = merged

    # ---- request composition (post-merge, per committed slot) ------------
    # one pass per absorption wave: wave j's winners compose against
    # the row as left by wave j-1 (re-gathered after its commit
    # scatter). W-like winners record their written value in a dense
    # round-value array `rv` so later-wave reads/writes on the same
    # entry source the in-flight value (memory is NOT written by
    # write-allocate, quirk; cv_req cannot see this round's fills).
    r_ci = codec.cache_index(cfg, safe_ent)
    req_id = jnp.broadcast_to(rows[:, None], (N, Q))
    c_iota = jnp.arange(C, dtype=jnp.int32)[None, :]
    ca_c, cv_c, cs_c = rp["ca"], cv_m, rp["cs"]
    # round-value array: bit 8 = owner wrote this round (bits 0-7 the
    # value); bit 9 = owner acquired CLEAN this round (read fill — its
    # value IS the row's memory). Later waves source owner values from
    # here; cv_req cannot see this round's fills.
    rv = jnp.zeros((E,), jnp.int32)
    commit_acc = jnp.zeros((N, Q), bool)
    rel_acc = jnp.zeros((N, Q), bool)
    patch_acc = jnp.zeros((N, Q), bool)
    fille_acc = jnp.zeros((N, Q), bool)
    fillv_acc = jnp.zeros((N, Q), jnp.int32)
    for j, won_j in enumerate(won_list):
        commit = (is_req | is_ev) & won_j & rp["comm"]
        commit_acc = commit_acc | commit
        g_rows = dm[safe_ent]                                # [N, Q, cols]
        r_state = g_rows[..., DM_STATE]
        r_cnt = g_rows[..., DM_COUNT]
        r_own = g_rows[..., DM_OWNER]
        r_mem = g_rows[..., DM_MEM]
        r_act = g_rows[..., DM_ACT]
        # a pending row (same-round promotion, owner == -1) serves its
        # memory as the owner value: SHARED lines are clean, and the
        # promoted-E line's value equals mem
        r_pend = (r_state == D_EM) & (r_own == -1)
        own_val = jnp.where(
            r_pend, r_mem,
            cv_req_m.reshape(-1)[jnp.clip(r_own, 0, N - 1) * C + r_ci])
        if j > 0:
            rv_got = rv[safe_ent]
            own_val = jnp.where((rv_got & 0x200) != 0, r_mem, own_val)
            own_val = jnp.where((rv_got & 0x100) != 0, rv_got & 0xFF,
                                own_val)
        r_u = r_state == D_U
        r_s = r_state == D_S
        r_em = r_state == D_EM
        k_rd = commit & (kind == K_RD)
        k_wr = commit & (kind == K_WR)
        k_up = commit & (kind == K_UP)
        k_evs = commit & (kind == K_EVS)
        k_evm = commit & (kind == K_EVM)
        wlike = k_wr | k_up
        # release: the requester displaced its own window fill of this
        # entry later in the window (replay-gated, so only committed
        # displacements count); the slot commits the fill+evict NET row
        rel = rp["rel"] & (k_rd | wlike)
        rel_acc = rel_acc | rel
        relv = rp["relv"]
        # new row from composition. An EVICT_SHARED from an E-line
        # holder finds the row EM{evictor} (exactness) and leaves it
        # Uncached — the reference's clear-bit -> 0 sharers path
        # (assignment.c:560-570)
        evs_cnt = jnp.where(r_s, r_cnt - 1, r_cnt)
        n_state = jnp.where(wlike, D_EM,
                   jnp.where(k_rd, jnp.where(r_u, D_EM, D_S),
                    jnp.where(k_evm | (k_evs & r_em), D_U,
                     jnp.where(k_evs & r_s,
                               jnp.where(evs_cnt == 0, D_U,
                                         jnp.where(evs_cnt == 1, D_EM,
                                                   D_S)),
                               r_state))))
        n_cnt = jnp.where(wlike | (k_rd & r_u), 1,
                 jnp.where(k_rd & r_em, 2,
                  jnp.where(k_rd & r_s, r_cnt + 1,
                   jnp.where(k_evm | (k_evs & r_em), 0,
                    jnp.where(k_evs & r_s, evs_cnt, r_cnt)))))
        n_own = jnp.where(wlike | (k_rd & r_u), req_id,
                 jnp.where(k_evs & r_s & (evs_cnt == 1), -1, r_own))
        n_mem = jnp.where((k_rd | k_wr) & r_em, own_val,
                          jnp.where(k_evm, sval, r_mem))
        # release net-row overrides: a released read leaves the row as
        # it was (EM keeps its owner, memory takes the owner's flushed
        # value); a released write nets Uncached with our final value
        n_state = jnp.where(rel, jnp.where(wlike, D_U,
                                           jnp.where(r_em, D_EM,
                                                     r_state)),
                            n_state)
        n_cnt = jnp.where(rel, jnp.where(wlike, 0,
                                         jnp.where(r_em, 1, r_cnt)),
                          n_cnt)
        n_own = jnp.where(rel, r_own, n_own)
        n_mem = jnp.where(rel, jnp.where(wlike, relv,
                                         jnp.where(r_em, own_val,
                                                   r_mem)),
                          n_mem)
        # fan-out action composition, split by target: the home's own
        # line takes act_h, every other tag-matching holder act_o.
        # Downgrade/promote target the row's recorded owner, which may
        # or may not be the home's line.
        tgt_home = r_own == (safe_ent >> cfg.block_bits)
        my_h = jnp.where(wlike, ACT_KILL,
                jnp.where(k_rd & r_em & tgt_home,
                          jnp.where(rel, ACT_PROMOTE, ACT_DOWN),
                 jnp.where(k_evs & r_s & (evs_cnt == 1), ACT_PROMOTE,
                           ACT_NONE)))
        my_o = jnp.where(wlike, ACT_KILL,
                jnp.where(k_rd & r_em & ~tgt_home,
                          jnp.where(rel, ACT_PROMOTE, ACT_DOWN),
                 jnp.where(k_evs & r_s & (evs_cnt == 1), ACT_PROMOTE,
                           ACT_NONE)))
        chain_fresh = (r_act >> 4) == st.round
        chain_act = jnp.where(chain_fresh, r_act & 3, ACT_NONE)
        prev_ah = jnp.where(chain_fresh, (r_act >> 2) & 3, ACT_NONE)
        # promote-then-X overrides: a plain read nets a DOWNGRADE (the
        # promotee may be an old E/M owner — the one composed action
        # must still take its line to SHARED); a released read
        # re-promotes; a write kills; a notice means the promotee
        # itself evicted. The same composition applies to the home's
        # own action across waves (prev_ah is 0 for chain rows, so
        # wave 0 reduces to act_h = my_h).
        def _compose(prev, mine):
            return jnp.where(prev == ACT_PROMOTE,
                             jnp.where(wlike, ACT_KILL,
                                       jnp.where(k_rd & rel, ACT_PROMOTE,
                                                 jnp.where(k_rd, ACT_DOWN,
                                                           ACT_NONE))),
                             jnp.maximum(prev, mine))
        act_o = _compose(chain_act, my_o)
        act_h = _compose(prev_ah, my_h)
        n_act = rtag | (act_h << 2) | act_o
        t_idx = jnp.where(commit, safe_ent, E).reshape(-1)
        t_rows = jnp.stack(
            [n_state, n_cnt, n_own, n_mem, n_act, req_id, key_q],
            axis=-1).reshape(-1, DM_COLS)
        dm = dm.at[t_idx].set(t_rows, mode="drop")
        if j + 1 < len(won_list):
            rv = rv.at[jnp.where(wlike, safe_ent, E).reshape(-1)].set(
                (0x100 | (sval & 0xFF)).reshape(-1), mode="drop")
            rv = rv.at[jnp.where(k_rd & r_u & ~rel, safe_ent,
                                 E).reshape(-1)].set(0x200, mode="drop")

        # reply patches on the requester's cache: committed remote rd
        # fills resolve E vs S and the fill value here. Accumulated
        # across waves (commits are slot-disjoint) and applied after
        # the loop in WINDOW-SLOT order — a node may commit fills on
        # the same cache index in different waves, and the later
        # window slot must land last.
        fill_e = k_rd & r_u
        fill_val = jnp.where(r_em, own_val, r_mem)
        patch = k_rd & ~rel      # a released fill's line was displaced
        patch_acc = patch_acc | patch
        fille_acc = fille_acc | fill_e
        fillv_acc = jnp.where(patch, fill_val, fillv_acc)
    for q in range(Q):
        oh = (r_ci[:, q][:, None] == c_iota) & patch_acc[:, q][:, None]
        cs_c = jnp.where(oh & fille_acc[:, q][:, None], EXC, cs_c)
        cv_c = jnp.where(oh, fillv_acc[:, q][:, None], cv_c)

    # ---- fan-out ---------------------------------------------------------
    # act + req pack into ONE dense [E] column (bit 20 = fresh, bits
    # 16-19 = act nibble, bits 0-15 = requester id; num_nodes <= 65536
    # by the deep-window address-width cap), so the per-line gather
    # reads 1 column instead of the 7-column row
    line_e = jnp.clip(ca_c, 0, E - 1)
    fan_fresh = (dm[:, DM_ACT] >> 4) == st.round
    fan_packed = (jnp.where(fan_fresh,
                            ((dm[:, DM_ACT] & 15) | 16) << 16, 0)
                  | dm[:, DM_REQ])
    line_f = fan_packed[line_e]                              # [N, C]
    fresh = ((line_f >> 20) & 1) == 1
    l_act_h = jnp.where(fresh, (line_f >> 18) & 3, ACT_NONE)
    l_act_o = jnp.where(fresh, (line_f >> 16) & 3, ACT_NONE)
    l_req = line_f & 0xFFFF
    l_home = line_e >> cfg.block_bits
    i_am_home = l_home == rows[:, None]
    a_code = jnp.where(i_am_home, l_act_h, l_act_o)
    valid = cs_c != INV
    not_self = l_req != rows[:, None]
    kill = valid & not_self & (a_code == ACT_KILL)
    down = valid & not_self & (a_code == ACT_DOWN)
    promo = valid & not_self & (a_code == ACT_PROMOTE)
    cs_c = jnp.where(kill, INV,
                     jnp.where(down, SHD,
                               jnp.where(promo, EXC, cs_c)))
    dm = dm.at[jnp.where(promo, line_e, E).reshape(-1), DM_OWNER].set(
        jnp.broadcast_to(rows[:, None], (N, C)).reshape(-1), mode="drop")

    # ---- bookkeeping -----------------------------------------------------
    # replay counters already include retired *remote* transactions (a
    # remote txn retires iff its slots committed — both encoded in
    # trunc), so the committed-slot sums are not added again
    cntr = rp["cnt"]
    deltas = jnp.sum(jnp.stack([
        rp["n_ret"], rp["rh"], rp["wh"],
        cntr["rd_miss"],
        cntr["wr_miss"],
        cntr["upg"],
        jnp.sum((is_req | is_ev) & ~won_any, axis=1, dtype=jnp.int32),
        cntr["ev"],
        jnp.sum(kill, axis=1, dtype=jnp.int32),
        jnp.sum(promo, axis=1, dtype=jnp.int32),
    ]), axis=1)
    mt = st.metrics
    metrics = mt.replace(
        rounds=mt.rounds + 1,
        instrs_retired=mt.instrs_retired + deltas[0],
        read_hits=mt.read_hits + deltas[1],
        write_hits=mt.write_hits + deltas[2],
        read_misses=mt.read_misses + deltas[3],
        write_misses=mt.write_misses + deltas[4],
        upgrades=mt.upgrades + deltas[5],
        conflicts=mt.conflicts + deltas[6],
        evictions=mt.evictions + deltas[7],
        invalidations=mt.invalidations + deltas[8],
        promotions=mt.promotions + deltas[9],
    )
    out = st.replace(cache_addr=ca_c, cache_val=cv_c, cache_state=cs_c,
                     dm=dm, idx=st.idx + rp["n_ret"],
                     horizon=jnp.clip(
                         rp["n_ret"] + cfg.deep_horizon_slack, 2,
                         1 << 20),
                     round=st.round + 1, metrics=metrics)
    if return_stats:
        s_ = lambda x: jnp.sum(x, dtype=jnp.int32)
        stats = dict(
            n_ret=s_(rp["n_ret"]), truncated=s_(rp["truncated"]),
            stopped=s_(rp["stopped"]), seen_req=s_(rp["seen_req"]),
            n_slot=s_(rp["n_slot"]), horizon_sum=s_(st.horizon),
            att_rd=s_(kind == K_RD), att_wr=s_(kind == K_WR),
            att_up=s_(kind == K_UP), att_evs=s_(kind == K_EVS),
            att_evm=s_(kind == K_EVM), att_probe=s_(kind == K_PROBE),
            lost=s_((is_req | is_ev) & ~won_any & ~aborting),
            abort_poison=s_(aborting & is_req),
            abort_mark=s_(aborting & is_ev),
            probe_bad=s_(probe_bad),
            committed=s_(commit_acc), released=s_(rel_acc))
        return out, stats
    if not with_events:
        return out
    events = {"retired": offs < rp["n_ret"][:, None],   # [N, W]
              "op": w_oa >> 28, "addr": w_oa & 0x0FFFFFFF,
              "value": w_val}
    return out, events


def dm_own_col(st: SyncState, col: int, N: int, S: int):
    return st.dm.reshape(N, S, DM_COLS)[:, :, col]
