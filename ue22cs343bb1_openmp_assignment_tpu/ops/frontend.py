"""Instruction-side protocol: fetch, hit/miss classification, requests.

Reference: the instruction half of the event loop
(``assignment.c:632-735``). A node fetches its next instruction only when
its mailbox is empty and it is not blocked on an outstanding request —
exactly the reference's drain-messages-first priority
(``assignment.c:165-177,624-629``) expressed cycle-synchronously.

Hit rule (``assignment.c:662-664``): tag match AND state != INVALID.
* read hit — no work;
* read miss — READ_REQUEST to home, block;
* write hit on M/E — write through the cache line, state -> MODIFIED
  (``assignment.c:705-710``);
* write hit on S — UPGRADE to home, block (``assignment.c:711-724``);
* write miss — WRITE_REQUEST (with the value) to home, block.

The issue gate (issue_delay/issue_period) is the *schedule knob* that
replaces OS thread timing for realizing alternative interleavings on the
racy suites (test_3/test_4); with delay=0, period=1 it is inert.
"""

from __future__ import annotations

import jax.numpy as jnp

from ue22cs343bb1_openmp_assignment_tpu import codec
from ue22cs343bb1_openmp_assignment_tpu.config import SystemConfig
from ue22cs343bb1_openmp_assignment_tpu.state import SimState
from ue22cs343bb1_openmp_assignment_tpu.types import CacheState, Msg, Op


def instruction_phase(cfg: SystemConfig, state: SimState, may_issue):
    """Compute instruction-fetch effects for nodes in `may_issue`.

    Returns (updates, request_part, stats). `updates` carries the same
    write-intent layout as handlers.message_phase; `request_part` is the
    slot-0 candidate contribution (READ_REQUEST / UPGRADE / WRITE_REQUEST).
    """
    N = cfg.num_nodes
    rows = jnp.arange(N, dtype=jnp.int32)

    # schedule gate (inert at delay=0, period=1)
    since = state.cycle - state.issue_delay
    gate = (since >= 0) & (since % jnp.maximum(state.issue_period, 1) == 0)
    if state.order_rank.shape[-1]:
        # interleaving replay (utils.order_replay): instruction i of
        # node n issues only when order_rank[n, i] instructions have
        # RETIRED machine-wide (metrics.instrs_retired counts
        # completions, not issues). Gating on the retired count means
        # at most one instruction is in flight machine-wide, which
        # serializes execution: the recorded global order is
        # reproduced exactly, but the replayed run's concurrency and
        # cycle counts are NOT faithful to the recorded run's timing
        nxt = jnp.clip(state.instr_idx + 1, 0,
                       state.order_rank.shape[-1] - 1)
        gate = gate & (state.order_rank[rows, nxt]
                       == state.metrics.instrs_retired)

    has_more = state.instr_idx < state.instr_count - 1  # assignment.c:632
    fetch = may_issue & gate & has_more

    idx = jnp.where(fetch, state.instr_idx + 1, 0)
    op = state.instr_op[rows, idx]
    addr = state.instr_addr[rows, idx]
    val = state.instr_val[rows, idx]

    i_home = codec.home_node(cfg, addr)
    i_cidx = codec.cache_index(cfg, addr)
    cl_addr = state.cache_addr[rows, i_cidx]
    cl_state = state.cache_state[rows, i_cidx]

    is_read = fetch & (op == int(Op.READ))
    is_write = fetch & (op == int(Op.WRITE))
    hit = (cl_addr == addr) & (cl_state != int(CacheState.INVALID))

    read_hit = is_read & hit
    read_miss = is_read & ~hit
    write_hit_me = is_write & hit & (
        (cl_state == int(CacheState.MODIFIED))
        | (cl_state == int(CacheState.EXCLUSIVE)))
    write_hit_s = is_write & hit & ~write_hit_me  # DEBUG-asserted SHARED
    write_miss = is_write & ~hit

    # Admission control (backpressure): cap simultaneously outstanding
    # request transactions so bounded mailboxes can never overflow — the
    # explicit policy replacing the reference's silent drop (SURVEY §5
    # "failure detection"). A gated node simply retries the fetch next
    # cycle (no instr_idx advance, no latch).
    if cfg.admission_window is not None:
        wants = read_miss | write_hit_s | write_miss
        inflight = jnp.sum(state.waiting).astype(jnp.int32)
        rank = (jnp.cumsum(wants.astype(jnp.int32))
                - wants.astype(jnp.int32))  # exclusive prefix in node order
        admit = inflight + rank < cfg.admission_window
        keep = ~wants | admit
        fetch = fetch & keep
        read_miss &= admit
        write_hit_s &= admit
        write_miss &= admit
        read_hit &= keep
        write_hit_me &= keep

    # local write-through on M/E hit (assignment.c:708-710)
    cw_mask = write_hit_me
    updates = dict(
        cache_idx=i_cidx,
        cache_state=(cw_mask,
                     jnp.full((N,), int(CacheState.MODIFIED), jnp.int32)),
        cache_addr=(jnp.zeros((N,), bool), addr),   # no addr change on hit
        cache_val=(cw_mask, val),
        wait_set=read_miss | write_hit_s | write_miss,
        fetch=fetch,
        new_idx=jnp.where(fetch, state.instr_idx + 1, state.instr_idx),
        latch=(fetch, op, addr, val),
    )

    req_type = jnp.select(
        [read_miss, write_hit_s, write_miss],
        [jnp.full((N,), int(Msg.READ_REQUEST), jnp.int32),
         jnp.full((N,), int(Msg.UPGRADE), jnp.int32),
         jnp.full((N,), int(Msg.WRITE_REQUEST), jnp.int32)],
        default=jnp.full((N,), int(Msg.NONE), jnp.int32))
    # UPGRADE and WRITE_REQUEST carry the value (assignment.c:716-731);
    # READ_REQUEST does not.
    req_value = jnp.where(is_write, val, 0)
    request_part = (req_type, i_home, addr, req_value)

    # per-node masks; ops.step folds them into ONE stacked reduction
    # (separate jnp.sum calls each cost a kernel dispatch, PERF.md)
    stats = dict(
        read_hits=read_hit,
        write_hits=write_hit_me | write_hit_s,
        read_misses=read_miss,
        write_misses=write_miss,
        upgrades=write_hit_s,
        issued=fetch,
    )
    return updates, request_part, stats
