"""The 13 protocol message handlers, vectorized over all nodes.

Reference: the ``switch(msg.type)`` at ``assignment.c:190-618``. Every
handler there mutates only the *processing thread's own* node state and
communicates exclusively via ``sendMessage`` — which is what makes the
message phase perfectly data-parallel: one gathered head message per
node, branch-free masked updates, candidate out-messages in static slots.

Faithfully encodes the reference's behavioral quirks (SURVEY §2):

1. ``REPLY_ID``/``REPLY_WR``/``FLUSH_INVACK`` fill the cache from the
   node's *latched in-flight instruction value* (``instr.value``,
   ``assignment.c:383,470,531``), not from the message.
2. ``FLUSH``/``FLUSH_INVACK`` clear ``waitingForReply`` unconditionally,
   even on a pure-home receiver (``assignment.c:322,535``).
3. ``WRITEBACK_INT`` dedups the home==requester double-send
   (``assignment.c:281``); ``WRITEBACK_INV`` does not
   (``assignment.c:492-498``).
4. Read-miss-on-EM leaves the directory untouched until the ``FLUSH``
   returns (``assignment.c:199-210``); write-miss updates it immediately
   and unconditionally (``assignment.c:455-457``).
5. ``EVICT_SHARED`` at a non-home receiver and the home self-promotion
   path write EXCLUSIVE *without a tag check* (``assignment.c:558,586``),
   and ``WRITEBACK_INT``/``WRITEBACK_INV`` read/flush the cache line
   without a tag check — blind-by-index exactly like the C.
"""

from __future__ import annotations

import jax.numpy as jnp

from ue22cs343bb1_openmp_assignment_tpu import codec
from ue22cs343bb1_openmp_assignment_tpu.config import SystemConfig
from ue22cs343bb1_openmp_assignment_tpu.ops.mailbox import MsgView
from ue22cs343bb1_openmp_assignment_tpu.state import (SimState, bit_single,
                                                      ctz, popcount)
from ue22cs343bb1_openmp_assignment_tpu.types import CacheState, DirState, Msg


def message_phase(cfg: SystemConfig, state: SimState, mv: MsgView):
    """Compute all message-handler effects for this cycle.

    Returns (updates, cand_parts, inv_scatter, stats):
      updates: dict of per-node write intents (masks + values),
      cand_parts: dict with primary/secondary/inv/evict candidate fields,
      inv_scatter: (mask, addr, bitvec) for cfg.inv_mode == 'scatter',
      stats: dict of metric deltas.
    """
    N, W = cfg.num_nodes, cfg.bitvec_words
    rows = jnp.arange(N, dtype=jnp.int32)
    has, t = mv.has_msg, mv.type

    # decode (assignment.c:186-188)
    p_home = codec.home_node(cfg, mv.addr)
    p_block = codec.block_index(cfg, mv.addr)
    p_cidx = codec.cache_index(cfg, mv.addr)

    # own-state gathers
    dirst = state.dir_state[rows, p_block]
    dirbv = state.dir_bitvec[rows, p_block]          # [N, W]
    memv = state.memory[rows, p_block]
    cl_addr = state.cache_addr[rows, p_cidx]
    cl_val = state.cache_val[rows, p_cidx]
    cl_state = state.cache_state[rows, p_cidx]

    def m(ty: int):
        return has & (t == int(ty))

    is_rr = m(Msg.READ_REQUEST)
    is_rrd = m(Msg.REPLY_RD)
    is_wbint = m(Msg.WRITEBACK_INT)
    is_flush = m(Msg.FLUSH)
    is_upg = m(Msg.UPGRADE)
    is_rid = m(Msg.REPLY_ID)
    is_inv = m(Msg.INV)
    is_wreq = m(Msg.WRITE_REQUEST)
    is_rwr = m(Msg.REPLY_WR)
    is_wbinv = m(Msg.WRITEBACK_INV)
    is_fia = m(Msg.FLUSH_INVACK)
    is_es = m(Msg.EVICT_SHARED)
    is_em = m(Msg.EVICT_MODIFIED)

    at_home = rows == p_home
    sender_bit = bit_single(W, mv.sender)            # [N, W]
    second_bit = bit_single(W, mv.second)
    d_em = dirst == int(DirState.EM)
    d_s = dirst == int(DirState.S)
    d_u = dirst == int(DirState.U)
    owner = ctz(dirbv)                               # current owner if EM

    flush_home = is_flush & at_home
    flush_second = is_flush & (rows == mv.second)
    fia_home = is_fia & at_home
    fia_second = is_fia & (rows == mv.second)

    # EVICT_SHARED home bookkeeping (assignment.c:559-589)
    es_home = is_es & at_home
    es_bv2 = dirbv & ~sender_bit
    es_nsh = popcount(es_bv2)
    es_new_owner = ctz(es_bv2)
    es_promote_self = es_home & (es_nsh == 1) & (es_new_owner == rows)
    es_notify = es_home & (es_nsh == 1) & (es_new_owner != rows)

    # ---- cache fills (REPLY_RD / FLUSH@req / REPLY_ID / REPLY_WR /
    #      FLUSH_INVACK@req) ------------------------------------------------
    fill = is_rrd | flush_second | is_rid | is_rwr | fia_second
    fill_val = jnp.where(is_rrd | flush_second, mv.value, state.cur_val)
    fill_state = jnp.where(
        is_rrd,
        jnp.where(mv.dirstate == int(DirState.S), int(CacheState.SHARED),
                  int(CacheState.EXCLUSIVE)),
        jnp.where(flush_second, int(CacheState.SHARED),
                  int(CacheState.MODIFIED)))

    # eviction of the displaced line (assignment.c:246-249,313-316,376-379,
    # 467,526-529): tag-mismatch check everywhere except REPLY_WR, which
    # calls handleCacheReplacement unconditionally (no-op only on INVALID).
    evict_checked = (is_rrd | flush_second | is_rid | fia_second)
    evict_fire = ((evict_checked & (cl_addr != mv.addr)
                   & (cl_state != int(CacheState.INVALID)))
                  | (is_rwr & (cl_state != int(CacheState.INVALID))))

    # ---- cache state writes ----------------------------------------------
    inv_hits = is_inv & (cl_addr == mv.addr)
    cs_mask = (is_wbint | inv_hits | is_wbinv | (is_es & ~at_home)
               | es_promote_self | fill)
    cs_val = jnp.select(
        [fill, is_wbint, inv_hits | is_wbinv],
        [fill_state,
         jnp.full((N,), int(CacheState.SHARED), jnp.int32),
         jnp.full((N,), int(CacheState.INVALID), jnp.int32)],
        default=jnp.full((N,), int(CacheState.EXCLUSIVE), jnp.int32))

    # ---- directory writes -------------------------------------------------
    ds_mask = ((is_rr & d_u) | flush_home | is_upg | is_wreq
               | (es_home & (es_nsh <= 1)) | is_em)
    ds_val = jnp.select(
        [flush_home,
         (es_home & (es_nsh == 0)) | is_em],
        [jnp.full((N,), int(DirState.S), jnp.int32),
         jnp.full((N,), int(DirState.U), jnp.int32)],
        default=jnp.full((N,), int(DirState.EM), jnp.int32))

    dbv_mask = ((is_rr & (d_s | d_u)) | flush_home | is_upg | is_wreq
                | fia_home | es_home | is_em)
    dbv_val = jnp.select(
        [(is_rr & d_s)[:, None] | flush_home[:, None],
         (is_rr & d_u)[:, None] | is_upg[:, None] | is_wreq[:, None],
         fia_home[:, None],
         es_home[:, None]],
        [dirbv | jnp.where(flush_home[:, None], second_bit, sender_bit),
         sender_bit,
         second_bit,
         es_bv2],
        default=jnp.zeros_like(dirbv))

    # ---- memory writes (assignment.c:307,520,602) -------------------------
    mem_mask = flush_home | fia_home | is_em
    mem_val = mv.value

    # ---- waiting flag (quirk 2: FLUSH/FLUSH_INVACK unconditional) ---------
    wait_clear = is_rrd | is_flush | is_rid | is_rwr | is_fia

    updates = dict(
        cache_idx=p_cidx, cache_state=(cs_mask, cs_val),
        cache_addr=(fill, mv.addr), cache_val=(fill, fill_val),
        mem=(mem_mask, p_block, mem_val),
        dir_state=(ds_mask, p_block, ds_val),
        dir_bv=(dbv_mask, p_block, dbv_val),
        wait_clear=wait_clear,
    )

    # ---- candidate out-messages ------------------------------------------
    none = jnp.full((N,), int(Msg.NONE), jnp.int32)
    zero = jnp.zeros((N,), jnp.int32)
    zbv = jnp.zeros((N, cfg.msg_bitvec_words), jnp.uint32)
    others_bv = dirbv & ~sender_bit  # UPGRADE / WRITE_REQUEST@S sharer list
    grants_em = is_upg | (is_wreq & d_s)  # handlers that answer REPLY_ID

    # primary send (slot 0) — each handler's first sendMessage
    pri_mask = is_rr | is_wbint | is_upg | is_wreq | is_wbinv | es_notify
    pri_type = jnp.select(
        [is_rr & d_em, is_rr, is_wbint,
         grants_em, is_wreq & d_u, is_wreq,
         is_wbinv, es_notify],
        [jnp.full((N,), int(Msg.WRITEBACK_INT), jnp.int32),
         jnp.full((N,), int(Msg.REPLY_RD), jnp.int32),
         jnp.full((N,), int(Msg.FLUSH), jnp.int32),
         jnp.full((N,), int(Msg.REPLY_ID), jnp.int32),
         jnp.full((N,), int(Msg.REPLY_WR), jnp.int32),
         jnp.full((N,), int(Msg.WRITEBACK_INV), jnp.int32),
         jnp.full((N,), int(Msg.FLUSH_INVACK), jnp.int32),
         jnp.full((N,), int(Msg.EVICT_SHARED), jnp.int32)],
        default=none)
    pri_type = jnp.where(pri_mask, pri_type, none)
    pri_recv = jnp.select(
        [is_rr & d_em, is_rr | is_upg, is_wbint | is_wbinv,
         is_wreq & d_em, is_wreq, es_notify],
        [owner, mv.sender, p_home, owner, mv.sender, es_new_owner],
        default=zero)
    pri_value = jnp.select(
        [is_rr & d_em, is_rr, is_wbint | is_wbinv, is_wreq & d_em, es_notify],
        [zero, memv, cl_val, mv.value, memv], default=zero)
    pri_second = jnp.select(
        [is_rr & d_em, is_wreq & d_em, is_wbint | is_wbinv],
        [mv.sender, mv.sender, mv.second], default=zero)
    pri_dirstate = jnp.where(is_rr & d_s, int(DirState.S), int(DirState.EM))
    if cfg.inv_mode == "mailbox":
        # REPLY_ID carries the sharers-minus-requester set for the
        # requester's INV fan-out (assignment.c:345,364-373).
        pri_bitvec = jnp.where(grants_em[:, None], others_bv, zbv)
    else:
        # scatter mode: the home applies the invalidations itself (below),
        # so REPLY_ID carries no payload and mailbox slots stay 1 word.
        pri_bitvec = zbv

    # secondary send (slot 1): FLUSH / FLUSH_INVACK to the secondReceiver.
    # WRITEBACK_INT dedups home==requester; WRITEBACK_INV does not (quirk 3).
    sec_mask = (is_wbint & (p_home != mv.second)) | is_wbinv
    sec_type = jnp.where(
        sec_mask,
        jnp.where(is_wbint, int(Msg.FLUSH), int(Msg.FLUSH_INVACK)), none)
    sec_recv = mv.second
    sec_value = cl_val
    sec_second = mv.second

    # INV fan-out (assignment.c:364-373): mailbox mode materializes one
    # slot per potential target, sourced at the requester processing
    # REPLY_ID exactly like the reference; scatter mode sources the
    # invalidation at the *home* processing the UPGRADE/WRITE_REQUEST —
    # the reference tracks no INV-acks (assignment.c:358-361), so the
    # only observable difference is that INVs land 2 hops earlier, and
    # messages need not carry sharer sets at all. A home processes at
    # most one message per cycle, so each home has at most one broadcast
    # in flight per cycle — which is what lets the step apply all kills
    # with one O(N*C) gather keyed by each line's home (ops/step.py).
    if cfg.inv_mode == "mailbox":
        targets = jnp.arange(N, dtype=jnp.int32)
        tw, tb = targets // 32, (targets % 32).astype(jnp.uint32)
        bits = (mv.bitvec[:, tw] >> tb[None, :]) & 1        # [N, N]
        inv_mask = is_rid[:, None] & (bits == 1)
        inv_type = jnp.where(inv_mask, int(Msg.INV), int(Msg.NONE))
        inv_recv = jnp.broadcast_to(targets[None, :], (N, N))
        inv_addr = jnp.broadcast_to(mv.addr[:, None], (N, N))
        inv_scatter = None
    else:
        inv_type = inv_recv = inv_addr = None
        inv_scatter = (grants_em, mv.addr, others_bv)  # always at home

    # eviction notice (last slot) — handleCacheReplacement
    # (assignment.c:767-804): EVICT_MODIFIED carries the dirty value.
    ev_mod = evict_fire & (cl_state == int(CacheState.MODIFIED))
    ev_type = jnp.where(
        evict_fire,
        jnp.where(ev_mod, int(Msg.EVICT_MODIFIED), int(Msg.EVICT_SHARED)),
        none)
    ev_recv = codec.home_node(cfg, cl_addr)
    ev_addr = cl_addr
    ev_value = jnp.where(ev_mod, cl_val, 0)

    cand_parts = dict(
        pri=(pri_type, pri_recv, mv.addr, pri_value, pri_second,
             pri_dirstate, pri_bitvec),
        sec=(sec_type, sec_recv, mv.addr, sec_value, sec_second),
        inv=(inv_type, inv_recv, inv_addr),
        ev=(ev_type, ev_recv, ev_addr, ev_value),
    )

    stats = dict(
        msg_type_onehot=(has, t),
        invalidations=inv_hits,     # [N] masks; reduced with the other
        evictions=evict_fire,       # counters in one stacked sum (step)
        unblocked=wait_clear & state.waiting,
    )
    return updates, cand_parts, inv_scatter, stats


# ---------------------------------------------------------------------------
# Row-extraction registry for analysis/protocol_table.py.
#
# TRANSITION_ANCHORS names, per message type, the assignment.c line
# ranges the vectorized handler above transcribes — the same anchors
# each declarative table Row must cite, so verify_table's anchor pass
# can prove the table and this module describe the same reference code
# (a renamed/renumbered handler breaks the cross-check loudly instead
# of silently drifting). QUIRKS is the machine-readable index of the
# five behavioral quirks documented in the module docstring; table rows
# reference them by id.
# ---------------------------------------------------------------------------

TRANSITION_ANCHORS = {
    "READ_REQUEST": ("assignment.c:199-210", "assignment.c:211-236"),
    "WRITE_REQUEST": ("assignment.c:407-421", "assignment.c:423-437",
                      "assignment.c:440-457"),
    "REPLY_RD": ("assignment.c:240-258",),
    "REPLY_WR": ("assignment.c:461-470",),
    "REPLY_ID": ("assignment.c:352-384",),
    "INV": ("assignment.c:389-399",),
    "UPGRADE": ("assignment.c:326-348",),
    "WRITEBACK_INV": ("assignment.c:474-498",),
    "WRITEBACK_INT": ("assignment.c:262-281", "assignment.c:262-286"),
    "FLUSH": ("assignment.c:301-322", "assignment.c:310-322",
              "assignment.c:322"),
    "FLUSH_INVACK": ("assignment.c:510-535", "assignment.c:522-535",
                     "assignment.c:535"),
    "EVICT_SHARED": ("assignment.c:549-558", "assignment.c:559-565",
                     "assignment.c:559-589", "assignment.c:566-589"),
    "EVICT_MODIFIED": ("assignment.c:596-616",),
}

QUIRKS = {
    1: "replies fill from the latched instruction value, not the message",
    2: "FLUSH/FLUSH_INVACK clear waitingForReply unconditionally",
    3: "WRITEBACK_INT dedups the home==requester double-send; "
       "WRITEBACK_INV does not",
    4: "read-miss-on-EM defers the directory update to the FLUSH; "
       "write-miss updates it immediately",
    5: "blind-by-index cache writes (no tag check)",
}
