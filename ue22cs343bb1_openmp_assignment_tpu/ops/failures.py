"""Failure detection — the stall watchdog.

The reference has none (SURVEY §5): a node whose reply is lost to the
silent overflow drop (``assignment.c:754-762``) spins in its waiting
loop forever (``assignment.c:624-629``), and only the harness's external
``kill -9`` ends the process. Here blocking is explicit state
(``waiting`` / ``waiting_since``), so detection is a reduction:

* a node is **stalled** when it has been waiting on its one outstanding
  request for more than `threshold` cycles — far beyond the protocol's
  worst-case transaction latency (a 3-hop ownership transfer resolves in
  ~4 cycles on an uncongested machine; queueing behind a hot home node
  adds at most the queue depth),
* the recovery path is deliberate: checkpoint → adjust schedule/admission
  (backpressure prevents the drops in the first place: with an admission
  window ≤ Q/6 no ring can overflow, config.admission_window) → resume.
  Blind request re-issue is NOT offered — replaying a request whose
  transaction half-completed corrupts the home directory, because the
  protocol's handlers assume exactly-once delivery (e.g. a retried
  WRITE_REQUEST on dir EM would WRITEBACK_INV the requester itself,
  ``assignment.c:435-453``).

Fault injection (cfg.drop_prob, ops.mailbox.deliver) exists to exercise
exactly this surface.
"""

from __future__ import annotations

from typing import List

import jax.numpy as jnp

from ue22cs343bb1_openmp_assignment_tpu.config import SystemConfig
from ue22cs343bb1_openmp_assignment_tpu.state import SimState

DEFAULT_THRESHOLD = 100


def stalled_mask(cfg: SystemConfig, state: SimState,
                 threshold: int = DEFAULT_THRESHOLD) -> jnp.ndarray:
    """[N] bool: waiting on one request for > threshold cycles."""
    age = state.cycle - state.waiting_since
    return state.waiting & (state.waiting_since >= 0) & (age > threshold)


def stalled_count(cfg: SystemConfig, state: SimState,
                  threshold: int = DEFAULT_THRESHOLD) -> jnp.ndarray:
    return jnp.sum(stalled_mask(cfg, state, threshold)).astype(jnp.int32)


def stall_report(cfg: SystemConfig, state: SimState,
                 threshold: int = DEFAULT_THRESHOLD,
                 limit: int = 16) -> dict:
    """Host-side report from ONE device evaluation of the mask:
    {"count": total stalled, "nodes": up to `limit` entries with the
    stuck request (node, since-cycle, op, addr)}."""
    import numpy as np

    mask = np.asarray(stalled_mask(cfg, state, threshold))
    ids = np.nonzero(mask)[0]
    since = np.asarray(state.waiting_since)
    op = np.asarray(state.cur_op)
    addr = np.asarray(state.cur_addr)
    return {"count": int(mask.sum()),
            "nodes": [{"node": int(n), "since_cycle": int(since[n]),
                       "op": "W" if int(op[n]) else "R",
                       "addr": int(addr[n])} for n in ids[:limit]]}


def stalled_nodes(cfg: SystemConfig, state: SimState,
                  threshold: int = DEFAULT_THRESHOLD,
                  limit: int = 16) -> List[dict]:
    """Back-compat list form of :func:`stall_report`."""
    return stall_report(cfg, state, threshold, limit)["nodes"]
