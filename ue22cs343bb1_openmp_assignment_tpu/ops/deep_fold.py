"""The deep-window fold, layout-neutral: one source for XLA and Pallas.

Every per-node scalar is a "vec" — shape [N] under the XLA engine, a
[1, T] lane-tile row inside the Pallas kernels — and every per-node
table ([N, S] own-directory slice, [N, C] cache, [N, Q] slots) is a
python LIST of vecs. All array code below is elementwise on vecs plus
where-chains over lists, so the identical function traces correctly in
both layouts; `ops.deep_engine` drives it with jax.lax.scan over window
steps, `ops.pallas_deep` with an in-kernel fori_loop.

Truncation is computed *inside* the fold: the replay pass receives the
per-slot badness verdicts (lane losses and priority aborts, from the
XLA middle section) and the dense own-lane codes, and stops retirement
at the first bad slot or yield-unsafe own touch. The pre-pass passes
zeros for both, which disables truncation (attempt-everything).

Protocol semantics and the serialization argument live in
ops/deep_engine's module docstring.
"""

from __future__ import annotations

import jax.numpy as jnp

from ue22cs343bb1_openmp_assignment_tpu.config import SystemConfig
from ue22cs343bb1_openmp_assignment_tpu.types import CacheState, DirState, Op

# slot kinds (remote events): fill requests, eviction notices, probes
K_NONE, K_RD, K_WR, K_UP, K_EVS, K_EVM, K_PROBE = 0, 1, 2, 3, 4, 5, 6

# dense own-lane code bits (XLA middle section -> replay fold): entry
# has a fresh foreign lane key / that key is an eviction notice / its
# sender's priority beats ours
OC_FRESH, OC_EV, OC_BEATS = 1, 2, 4

# fan-out actions, packed into DM_ACT as (round << 4)|(act_h << 2)|act_o
ACT_NONE, ACT_DOWN, ACT_KILL, ACT_PROMOTE = 0, 1, 2, 3


def _sel(lst, idx):
    """where-chain select: lst[idx] per node, idx a vec of list indices.

    Bool lists use mask algebra instead of select: mosaic lowers
    `arith.select` on i1 vectors through i8 and fails with an
    unsupported-truncation error, so kernels must never select bools.
    """
    out = lst[0]
    for i in range(1, len(lst)):
        m = idx == i
        if out.dtype == jnp.bool_:
            out = (m & lst[i]) | (~m & out)
        else:
            out = jnp.where(m, lst[i], out)
    return out


def _upd(lst, idx, mask, val):
    """lst[idx] = val where mask, per node (bools via mask algebra —
    see _sel)."""
    if lst[0].dtype == jnp.bool_:
        return [((mask & (idx == i)) & val)
                | (~(mask & (idx == i)) & r) for i, r in enumerate(lst)]
    return [jnp.where(mask & (idx == i), val, r) for i, r in enumerate(lst)]


def fold_carry0(cfg: SystemConfig, ca, cv, cs, dm_rows, zero, false):
    """Initial fold carry. ca/cv/cs: C-lists of vecs; dm_rows: dict of
    S-lists (dms/dmc/dmo/dmm); zero/false: a zero int vec / false vec
    in the target layout."""
    C, S = cfg.cache_size, 1 << cfg.block_bits
    Q, G = cfg.deep_slots, cfg.deep_ownerval_slots
    neg1 = zero - 1
    W = cfg.drain_depth + cfg.txn_width
    return dict(
        ca=list(ca), cv=list(cv), cs=list(cs),
        cv_src=[neg1] * C, rrf=[false] * C, wf=[false] * C,
        lwh=[false] * C,
        dms=list(dm_rows["dms"]), dmc=list(dm_rows["dmc"]),
        dmo=list(dm_rows["dmo"]), dmm=list(dm_rows["dmm"]),
        dmm_src=[neg1] * S,
        touched=[false] * S, act_acc=[zero] * S,
        mark=[false] * S, poison=[false] * S,
        cv_req=list(cv), cv_req_src=[neg1] * C,
        stopped=false, frozen=false, truncated=false,
        n_slot=zero, n_g=zero, seen_req=false,
        n_ret=zero, rh=zero, wh=zero,
        c_rd=zero, c_wr=zero, c_up=zero, c_ev=zero,
        s_overq=false, s_overg=false, s_dup=false, s_dep=false,
        s_live=false,
        kind=[zero] * Q, ent=[zero] * Q, sval=[zero] * Q,
        pos=[zero + W] * Q, comm=[false] * Q,
        rel=[false] * Q, relv=[zero] * Q, reld=[false] * Q,
        g_owner=[zero] * G, g_ci=[zero] * G,
    )


def fold_step(cfg: SystemConfig, c, node, oa, val, live, k, horizon,
              bad, ocode):
    """One window step. c: carry dict (lists of vecs); node: vec of node
    ids; oa/val/live: this step's instruction; k: int step index;
    horizon: attempt-cap vec; bad: Q-list of slot-badness vecs (zeros in
    the pre-pass); ocode: S-list of own-lane code vecs (zeros in the
    pre-pass). Returns the next carry."""
    C, S = cfg.cache_size, 1 << cfg.block_bits
    Q, G = cfg.deep_slots, cfg.deep_ownerval_slots
    INV = int(CacheState.INVALID)
    MOD = int(CacheState.MODIFIED)
    EXC = int(CacheState.EXCLUSIVE)
    SHD = int(CacheState.SHARED)
    D_U, D_S, D_EM = int(DirState.U), int(DirState.S), int(DirState.EM)
    bmask = S - 1

    live = live & (k < horizon)
    # cache values as of the node's first fill-request attempt: foreign
    # requests read owner values from THIS snapshot, which keeps every
    # observed value inside the owner's pre-request stratum
    cv_req = [jnp.where(c["seen_req"], rq, v)
              for rq, v in zip(c["cv_req"], c["cv"])]
    cv_req_src = [jnp.where(c["seen_req"], rq, v)
                  for rq, v in zip(c["cv_req_src"], c["cv_src"])]
    op, addr = oa >> 28, oa & 0x0FFFFFFF
    home = addr >> cfg.block_bits
    block = addr & bmask
    is_own = home == node
    ci = block % C           # direct-mapped (codec.cache_index)
    l_addr = _sel(c["ca"], ci)
    l_val = _sel(c["cv"], ci)
    l_state = _sel(c["cs"], ci)
    l_src = _sel(c["cv_src"], ci)
    l_rrf = _sel(c["rrf"], ci)
    l_wf = _sel(c["wf"], ci)
    tag_ok = (l_addr == addr) & (l_state != INV)
    is_rd, is_wr = op == int(Op.READ), op == int(Op.WRITE)
    rd_hit = live & is_rd & tag_ok
    wr_hit = live & is_wr & tag_ok & ((l_state == MOD) | (l_state == EXC))
    wr_sh = live & is_wr & tag_ok & (l_state == SHD)
    nop = live & (op == int(Op.NOP))
    if cfg.deep_waves == 1:
        # single-wave: a write on a line this window filled by a
        # remote READ stops the window (the E/S fill resolution lands
        # in the committed cache next round)
        dep_stop = wr_sh & l_rrf
        upg = wr_sh & ~l_rrf
    else:
        # speculative upgrade (waves >= 2): issue an UPGRADE slot
        # regardless of the unresolved E/S fill — on an S row it is
        # the normal upgrade; on an EM{self} row it composes to the
        # exact state the reference's silent E-write leaves (the
        # UPGRADE handler's unconditional dir -> EM{requester},
        # assignment.c:325-349), costing one slot. Needs waves: the
        # slot shares its entry with the window's own read-fill slot,
        # which only the slot-indexed wave keys can order.
        dep_stop = jnp.zeros_like(wr_sh)
        upg = wr_sh
    rd_miss = live & is_rd & ~tag_ok
    wr_miss = live & is_wr & ~tag_ok
    is_txn = (upg | rd_miss | wr_miss) & ~dep_stop
    hit = rd_hit | wr_hit | nop

    has_victim = is_txn & ~tag_ok & (l_state != INV) & (l_addr != addr)
    v_block = l_addr & bmask
    v_own = (l_addr >> cfg.block_bits) == node
    v_mod = l_state == MOD

    own_txn = is_txn & is_own
    rem_txn = is_txn & ~is_own
    own_vic = has_victim & v_own
    rem_vic = has_victim & ~v_own
    probe = hit & c["frozen"] & ~is_own & ~l_wf

    # --- own register reads ----------------------------------------------
    t_dms = _sel(c["dms"], block)
    t_dmc = _sel(c["dmc"], block)
    t_dmo = _sel(c["dmo"], block)
    t_dmm = _sel(c["dmm"], block)
    t_dmm_src = _sel(c["dmm_src"], block)
    t_act = _sel(c["act_acc"], block)
    v_dmc = _sel(c["dmc"], v_block)
    v_act = _sel(c["act_acc"], v_block)

    # --- stop conditions ---------------------------------------------------
    if cfg.deep_read_storm:
        # storm mode forfeits release netting: a released read would
        # commit a different (net) row than its co-readers at the
        # storm point, breaking the identical-duplicate-scatter
        # property — and a never-releasable read that can also never
        # win a lane (reads rank below all non-read claims under the
        # is_rd key bit) would starve forever. With releases off, the
        # displacement of an own-window fill hits the dup stop
        # (waves == 1) or the storm-zone truncation instead, and
        # EVERY non-aborted read is storm-eligible. Config-static, so
        # pre/flag/replay folds keep identical slot layouts.
        rel_hit = [jnp.zeros_like(live) for _ in c["kind"]]
    else:
        rel_hit = [((kk >= K_RD) & (kk <= K_UP)) & (ee == l_addr)
                   for kk, ee in zip(c["kind"], c["ent"])]
    rel_any_all = rel_hit[0]
    for rh_ in rel_hit[1:]:
        rel_any_all = rel_any_all | rh_
    rel_any = rel_any_all & rem_vic
    dup_t = dup_v = jnp.zeros_like(live)
    if cfg.deep_waves == 1:
        # single-wave rounds: a second remote event on an already-
        # slotted entry cannot commit (one winner per entry), so stop
        # the window there. With waves > 1 the slot-indexed lane keys
        # order a node's same-entry events across waves
        # (ops/deep_engine), so re-touches proceed.
        for kk, ee in zip(c["kind"], c["ent"]):
            isrem = (kk >= K_RD) & (kk <= K_EVM)
            dup_t = dup_t | (isrem & (ee == addr))
            dup_v = dup_v | (isrem & (ee == l_addr))
    dup = (dup_t & rem_txn) | (dup_v & rem_vic & ~rel_any)
    n_need = (rem_txn.astype(jnp.int32)
              + (rem_vic & ~rel_any_all).astype(jnp.int32)
              + probe.astype(jnp.int32))
    over_q = (c["n_slot"] + n_need) > Q
    # EM-with-unresolved-owner (same-round promotion, owner == -1)
    # composes via the row's memory: SHARED lines are clean in this
    # protocol, so a promoted-E line's value equals mem
    t_em_o = (t_dms == D_EM) & (t_dmo != node) & (t_dmo >= 0)
    t_em_p = (t_dms == D_EM) & (t_dmo == -1)
    t_em = t_em_o | t_em_p
    g_need = own_txn & (rd_miss | wr_miss) & t_em_o
    over_g = g_need & (c["n_g"] >= G)
    stop_now = (~c["stopped"]) & (live & ~nop) & (
        dep_stop | over_q | over_g | dup | ~(hit | is_txn))
    stop_now = stop_now | ((~c["stopped"]) & ~live)
    act = ~c["stopped"] & ~stop_now & (hit | is_txn)
    # stop-reason flags (anatomy; priority order mirrors stop_now)
    was = ~c["stopped"]
    s_live = c["s_live"] | (was & stop_now & ~live)
    s_dep = c["s_dep"] | (was & stop_now & live & dep_stop)
    s_overq = c["s_overq"] | (was & stop_now & live & ~dep_stop & over_q)
    s_overg = c["s_overg"] | (was & stop_now & live & ~dep_stop
                              & ~over_q & over_g)
    s_dup = c["s_dup"] | (was & stop_now & live & ~dep_stop & ~over_q
                          & ~over_g & dup)

    # --- truncation (replay only; pre-pass gets zero bad/ocode) ------------
    o1 = c["n_slot"]
    o2 = o1 + (rem_vic & ~rel_any_all).astype(jnp.int32)
    bad1 = _sel(bad, o1)
    bad2 = _sel(bad, o2)
    slot_bad = ((rem_vic & ~rel_any_all) & act & (bad1 != 0)) \
        | ((rem_txn | probe) & act & (bad2 != 0))
    # chain-yield checks against the own-lane codes: a chain TXN touch
    # yields to a winning fresh notice at any position and to any
    # winning fresh event after our first fill-request attempt; own
    # hits after the first request yield to fresh fill requests
    tc = _sel(ocode, block)
    vc = _sel(ocode, v_block)
    post = c["seen_req"]
    y_bad = own_txn & ((((tc & OC_EV) != 0) & ((tc & OC_BEATS) != 0))
                       | (post & ((tc & OC_FRESH) != 0)
                          & ((tc & OC_BEATS) != 0)))
    y_bad = y_bad | (own_vic
                     & ((((vc & OC_EV) != 0) & ((vc & OC_BEATS) != 0))
                        | (post & ((vc & OC_FRESH) != 0)
                           & ((vc & OC_BEATS) != 0))))
    y_bad = y_bad | ((rd_hit | wr_hit) & is_own & post
                     & ((tc & OC_FRESH) != 0) & ((tc & OC_EV) == 0))
    truncated = c["truncated"] | ((slot_bad | y_bad) & act)
    r = act & ~truncated

    own_txn_a, rem_txn_a = own_txn & act, rem_txn & act
    own_vic_a, rem_vic_a = own_vic & act, rem_vic & act
    probe_a = probe & act
    g_take = g_need & act
    own_txn_r = own_txn & r
    own_vic_r = own_vic & r
    fill_r = (own_txn | rem_txn) & r

    # --- slot emission (attempt-based) -------------------------------------
    rem_vic_slot = rem_vic_a & ~rel_any_all
    kind, ent, sval, pos = c["kind"], c["ent"], c["sval"], c["pos"]
    comm = c["comm"]
    # release marking is retirement-gated: a displacement past the
    # truncation point must not release its fill slot
    mrel_m = rem_vic & r
    rel = [rr | (rh_ & mrel_m) for rr, rh_ in zip(c["rel"], rel_hit)]
    relv = [jnp.where(rh_ & mrel_m, l_val, rv)
            for rv, rh_ in zip(c["relv"], rel_hit)]
    reld = [rd_ | (rh_ & mrel_m & v_mod)
            for rd_, rh_ in zip(c["reld"], rel_hit)]
    vic_kind = jnp.where(v_mod, K_EVM, K_EVS)
    kind = _upd(kind, o1, rem_vic_slot, vic_kind)
    ent = _upd(ent, o1, rem_vic_slot, jnp.clip(l_addr, 0, None))
    sval = _upd(sval, o1, rem_vic_slot, l_val)
    pos = _upd(pos, o1, rem_vic_slot, jnp.zeros_like(o1) + k)
    comm = _upd(comm, o1, rem_vic_slot & r, r)
    fp = rem_txn_a | probe_a
    fill_kind = jnp.where(probe, K_PROBE,
                          jnp.where(rd_miss, K_RD,
                                    jnp.where(wr_miss, K_WR, K_UP)))
    slot_v = jnp.where(probe, c["seen_req"].astype(jnp.int32), val)
    kind = _upd(kind, o2, fp, fill_kind)
    ent = _upd(ent, o2, fp, jnp.clip(addr, 0, None))
    sval = _upd(sval, o2, fp, slot_v)
    pos = _upd(pos, o2, fp, jnp.zeros_like(o2) + k)
    comm = _upd(comm, o2, (rem_txn_a & r), r)
    n_slot = c["n_slot"] + jnp.where(act, n_need, 0)
    seen_req = c["seen_req"] | rem_txn_a

    # --- g-slot (own-EM owner value) ---------------------------------------
    g_owner = _upd(c["g_owner"], c["n_g"], g_take,
                   jnp.clip(t_dmo, 0, None))
    g_ci = _upd(c["g_ci"], c["n_g"], g_take, ci)
    g_id = c["n_g"]
    n_g = c["n_g"] + g_take.astype(jnp.int32)

    # --- counters ----------------------------------------------------------
    n_ret = c["n_ret"] + r
    rh = c["rh"] + (rd_hit & r)
    wh = c["wh"] + (wr_hit & r)
    c_rd = c["c_rd"] + (rd_miss & r)
    c_wr = c["c_wr"] + (wr_miss & r)
    c_up = c["c_up"] + (upg & r)
    c_ev = c["c_ev"] + (has_victim & r)

    # --- hit write effects -------------------------------------------------
    wm = wr_hit & r
    cv = _upd(c["cv"], ci, wm, val)
    cv_src = _upd(c["cv_src"], ci, wm, jnp.zeros_like(val) - 1)
    cs = _upd(c["cs"], ci, wm, jnp.zeros_like(val) + MOD)

    # --- own victim composition --------------------------------------------
    vo = own_vic_r
    ev_m = vo & v_mod
    ev_s = vo & ~v_mod & (l_state == SHD)
    nvc = jnp.where(ev_s, v_dmc - 1, 0)
    nvs = jnp.where(ev_s & (nvc >= 2), D_S,
                    jnp.where(ev_s & (nvc == 1), D_EM, D_U))
    promote = ev_s & (nvc == 1)
    dms = _upd(c["dms"], v_block, vo, nvs)
    dmc = _upd(c["dmc"], v_block, vo, nvc)
    dmo = _upd(c["dmo"], v_block, vo & promote, jnp.zeros_like(nvc) - 1)
    dmm = _upd(c["dmm"], v_block, ev_m, l_val)
    dmm_src = _upd(c["dmm_src"], v_block, ev_m, l_src)
    touched = _upd(c["touched"], v_block, vo, vo)
    act_acc = _upd(c["act_acc"], v_block, vo,
                   jnp.maximum(v_act, jnp.where(promote, ACT_PROMOTE,
                                                ACT_NONE)))
    v_foreign = ev_s & (v_dmc > 1)
    mark = _upd(c["mark"], v_block, vo & v_foreign, vo & v_foreign)
    poison = _upd(c["poison"], v_block, vo & c["seen_req"],
                  vo & c["seen_req"])

    # --- own target composition --------------------------------------------
    to = own_txn_r
    t_u_eff = (t_dms == D_U) | ((t_dms == D_EM) & (t_dmo == node))
    t_s = t_dms == D_S
    o_rd, o_wr, o_up = to & rd_miss, to & wr_miss, to & upg
    wlike = o_wr | o_up
    nts = jnp.where(wlike | (o_rd & t_u_eff), D_EM, D_S)
    ntc = jnp.where(wlike | (o_rd & t_u_eff), 1,
                    jnp.where(o_rd & t_em, 2, t_dmc + 1))
    nto = jnp.where(wlike | (o_rd & t_u_eff), node, t_dmo)
    flush = (o_rd | o_wr) & t_em_o
    ntm_src = jnp.where(flush, g_id, t_dmm_src)
    new_act = jnp.where(wlike & ~t_u_eff, ACT_KILL,
                        jnp.where(o_rd & t_em, ACT_DOWN, ACT_NONE))
    # touching a pending entry overrides the accumulated PROMOTE
    act_override = to & t_em_p
    dms = _upd(dms, block, to, nts)
    dmc = _upd(dmc, block, to, ntc)
    dmo = _upd(dmo, block, to, nto)
    dmm_src = _upd(dmm_src, block, to, ntm_src)
    touched = _upd(touched, block, to, to)
    act_acc = _upd(act_acc, block, to,
                   jnp.where(act_override, new_act,
                             jnp.maximum(t_act, new_act)))
    t_foreign = (t_s & (t_dmc > jnp.where(upg, 1, 0))) | t_em
    mark = _upd(mark, block, to & t_foreign, to & t_foreign)
    poison = _upd(poison, block, to & c["seen_req"],
                  to & c["seen_req"])

    # --- fills -------------------------------------------------------------
    fstate = jnp.where(is_wr, MOD,
                       jnp.where(own_txn & t_u_eff, EXC, SHD))
    f_val = jnp.where(is_wr, val, jnp.where(t_em_o, 0, t_dmm))
    f_src = jnp.where(is_wr | ~is_own, -1,
                      jnp.where(t_em_o, g_id, t_dmm_src))
    ca = _upd(c["ca"], ci, fill_r, addr)
    cv = _upd(cv, ci, fill_r, f_val)
    cv_src = _upd(cv_src, ci, fill_r, f_src)
    cs = _upd(cs, ci, fill_r, fstate)
    rrf = [((fill_r & (ci == i)) & rem_txn & rd_miss)
           | (~(fill_r & (ci == i)) & x) for i, x in enumerate(c["rrf"])]
    wf = [x | (fill_r & (ci == i)) for i, x in enumerate(c["wf"])]
    # write-hit-after-last-fill: the fold's value for this line is
    # newer than any slot fill, so the round middle must not apply
    # reply patches to it (set on hit writes, cleared by fills; a step
    # is either a hit or a fill, never both)
    lwh = [(~(fill_r & (ci == i)))
           & (x | (wm & (ci == i))) for i, x in enumerate(c["lwh"])]

    frozen = c["frozen"] | (is_txn & ~c["stopped"] & ~stop_now)
    stopped = c["stopped"] | stop_now
    return dict(ca=ca, cv=cv, cs=cs, cv_src=cv_src, rrf=rrf, wf=wf,
                lwh=lwh,
                dms=dms, dmc=dmc, dmo=dmo, dmm=dmm, dmm_src=dmm_src,
                touched=touched, act_acc=act_acc, mark=mark,
                poison=poison, cv_req=cv_req, cv_req_src=cv_req_src,
                stopped=stopped, frozen=frozen, truncated=truncated,
                n_slot=n_slot, n_g=n_g, seen_req=seen_req,
                n_ret=n_ret, rh=rh, wh=wh,
                c_rd=c_rd, c_wr=c_wr, c_up=c_up, c_ev=c_ev,
                s_overq=s_overq, s_overg=s_overg, s_dup=s_dup,
                s_dep=s_dep, s_live=s_live,
                kind=kind, ent=ent, sval=sval, pos=pos, comm=comm,
                rel=rel, relv=relv, reld=reld,
                g_owner=g_owner, g_ci=g_ci)
