"""Pallas TPU kernels for the deep-window round's fold passes.

The deep engine's round is **fold-bound**: two W-step folds (pre-pass
and replay, ops/deep_engine._fold_deep) of dense per-node arithmetic,
traced as a `lax.scan` whose every step is a separate XLA fusion over
~250 small [N] vectors. On the bench device the scatter/gather middle
of the round costs ~0.3-0.5 ms while the two folds cost ~1.3 ms
(scripts/prof_deep.py, round 2) — the fold is pure VPU work that
belongs in a kernel.

Because `ops.deep_fold` is layout-neutral (every per-node scalar is a
"vec", every table a python list of vecs), the IDENTICAL fold code runs
here with vecs as [1, T] lane rows — the node axis fills the 128-wide
lanes — as an unrolled W-step loop (mosaic constraints: no bool
vector loop carries, no `arith.select` on i1 vectors — the fold's
helpers use mask algebra for bools). The instruction window is built
in XLA ([W, N]: procedural hash or stored-trace gather, identical to
the XLA path) and read with static row indices, so the kernel body
performs no dynamic memory access and serves EVERY workload kind.
One kernel instance owns a node tile; the live fold state (~250
[1, T] vecs, ~1 MB at T=1024) stays in vector registers/VMEM.

The claim scatter-min, lane/flag gathers, and commit scatters between
and after the folds stay in XLA (TPU Pallas has no vector gather) and
are computed in the kernels' transposed [Q, N]/[S, N] layout so only a
handful of small per-round transposes appear.

`round_step_deep_pallas` is bit-identical to
`deep_engine.round_step_deep` (tests/test_pallas_deep.py); enabled for
procedural workloads on tileable node counts via cfg.pallas_burst,
exactly like ops/pallas_window.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

from ue22cs343bb1_openmp_assignment_tpu import codec
from ue22cs343bb1_openmp_assignment_tpu.config import SystemConfig
from ue22cs343bb1_openmp_assignment_tpu.procedural import procedural_instr
from ue22cs343bb1_openmp_assignment_tpu.types import CacheState, DirState
from ue22cs343bb1_openmp_assignment_tpu.ops import deep_fold
from ue22cs343bb1_openmp_assignment_tpu.ops.deep_engine import (
    ACT_DOWN, ACT_KILL, ACT_NONE, ACT_PROMOTE, F_MARK, F_POISON,
    K_EVM, K_EVS, K_PROBE, K_RD, K_UP, K_WR)
from ue22cs343bb1_openmp_assignment_tpu.ops.pallas_burst import (
    _interpret, _tile)
from ue22cs343bb1_openmp_assignment_tpu.ops.sync_engine import (
    DM_ACT, DM_CLAIM, DM_COLS, DM_COUNT, DM_MEM, DM_OWNER, DM_REQ,
    DM_STATE, SyncState, _round_key, claim_max_rounds)


def _run_fold(cfg: SystemConfig, T: int, ca_ref, cv_ref, cs_ref,
              dms_ref, dmc_ref, dmo_ref, dmm_ref, woa_ref, wval_ref,
              wlive_ref, hor_ref, bad_refs, ocode_ref):
    """Trace the W-step deep fold on [1, T] lane rows; returns the
    final carry (deep_fold.fold_step contract).

    The instruction window arrives as [W, T] blocks (built in XLA —
    procedural hash or stored-trace gather, exactly as the XLA path
    builds it), so the unrolled loop reads each step with a *static*
    row index and the kernel works for every workload kind."""
    C, S = cfg.cache_size, 1 << cfg.block_bits
    Q = cfg.deep_slots
    W = cfg.drain_depth + cfg.txn_width
    pid = pl.program_id(0)
    node = jax.lax.broadcasted_iota(jnp.int32, (1, T), 1) + pid * T
    zero = jnp.zeros((1, T), jnp.int32)
    false = jnp.zeros((1, T), bool)
    hor = hor_ref[...]
    carry0 = deep_fold.fold_carry0(
        cfg,
        ca=[ca_ref[c:c + 1, :] for c in range(C)],
        cv=[cv_ref[c:c + 1, :] for c in range(C)],
        cs=[cs_ref[c:c + 1, :] for c in range(C)],
        dm_rows=dict(
            dms=[dms_ref[s:s + 1, :] for s in range(S)],
            dmc=[dmc_ref[s:s + 1, :] for s in range(S)],
            dmo=[dmo_ref[s:s + 1, :] for s in range(S)],
            dmm=[dmm_ref[s:s + 1, :] for s in range(S)]),
        zero=zero, false=false)
    badL = ([zero] * Q if bad_refs is None
            else [bad_refs[q:q + 1, :] for q in range(Q)])
    ocodeL = ([zero] * S if ocode_ref is None
              else [ocode_ref[s:s + 1, :] for s in range(S)])

    # unrolled python loop: a fori_loop's bool vector carries hit an
    # unsupported mosaic lowering (trunci i8->i1); the unrolled fold is
    # the proven pattern (ops/pallas_window, scripts/prof_deepcost K)
    c = carry0
    for k in range(W):
        oa = woa_ref[k:k + 1, :]
        val = wval_ref[k:k + 1, :]
        live = wlive_ref[k:k + 1, :] != 0
        c = deep_fold.fold_step(cfg, c, node, oa, val, live, k,
                                hor, badL, ocodeL)
    return c


def _cat(rows):
    return jnp.concatenate([r.astype(jnp.int32) for r in rows], axis=0)


def _pre_kernel(cfg, T, ca_ref, cv_ref, cs_ref, dms_ref, dmc_ref,
                dmo_ref, dmm_ref, woa_ref, wval_ref, wlive_ref,
                hor_ref, slot_ref, flag_ref):
    fin = _run_fold(cfg, T, ca_ref, cv_ref, cs_ref, dms_ref, dmc_ref,
                    dmo_ref, dmm_ref, woa_ref, wval_ref, wlive_ref,
                    hor_ref, None, None)
    slot_ref[...] = _cat(fin["kind"] + fin["ent"] + fin["sval"])
    flag_ref[...] = _cat(
        [m.astype(jnp.int32) * F_MARK + p.astype(jnp.int32) * F_POISON
         for m, p in zip(fin["mark"], fin["poison"])])


def _replay_kernel(cfg, T, ca_ref, cv_ref, cs_ref, dms_ref, dmc_ref,
                   dmo_ref, dmm_ref, woa_ref, wval_ref, wlive_ref,
                   hor_ref, bad_ref, ocode_ref,
                   cache_ref, dm_ref, slot_ref, g_ref, cnt_out_ref):
    fin = _run_fold(cfg, T, ca_ref, cv_ref, cs_ref, dms_ref, dmc_ref,
                    dmo_ref, dmm_ref, woa_ref, wval_ref, wlive_ref,
                    hor_ref, bad_ref, ocode_ref)
    cache_ref[...] = _cat(fin["ca"] + fin["cv"] + fin["cs"]
                          + fin["cv_src"] + fin["cv_req"]
                          + fin["cv_req_src"])
    dm_ref[...] = _cat(fin["dms"] + fin["dmc"] + fin["dmo"] + fin["dmm"]
                       + fin["dmm_src"] + fin["touched"]
                       + fin["act_acc"])
    slot_ref[...] = _cat(fin["comm"] + fin["rel"] + fin["relv"]
                         + fin["reld"])
    g_ref[...] = _cat(fin["g_owner"] + fin["g_ci"])
    cnt_out_ref[...] = _cat([fin["n_ret"], fin["rh"], fin["wh"],
                             fin["c_rd"], fin["c_wr"], fin["c_up"],
                             fin["c_ev"]])


def _call_pre(cfg, ca_t, cv_t, cs_t, dm_t4, win_t3, hor2):
    C, S = cfg.cache_size, 1 << cfg.block_bits
    Q, N = cfg.deep_slots, cfg.num_nodes
    W = cfg.drain_depth + cfg.txn_width
    T = _tile(N)
    vec = pl.BlockSpec((1, T), lambda i: (0, i))
    matC = pl.BlockSpec((C, T), lambda i: (0, i))
    matS = pl.BlockSpec((S, T), lambda i: (0, i))
    matW = pl.BlockSpec((W, T), lambda i: (0, i))
    blk = lambda rows: (pl.BlockSpec((rows, T), lambda i: (0, i)),
                        jax.ShapeDtypeStruct((rows, N), jnp.int32))
    slot_spec, slot_shape = blk(3 * Q)
    flag_spec, flag_shape = blk(S)
    return pl.pallas_call(
        functools.partial(_pre_kernel, cfg, T),
        grid=(N // T,),
        in_specs=[matC] * 3 + [matS] * 4 + [matW] * 3 + [vec],
        out_specs=[slot_spec, flag_spec],
        out_shape=[slot_shape, flag_shape],
        interpret=_interpret(),
    )(ca_t, cv_t, cs_t, *dm_t4, *win_t3, hor2)


def _call_replay(cfg, ca_t, cv_t, cs_t, dm_t4, win_t3, hor2,
                 bad_t, ocode_t):
    C, S = cfg.cache_size, 1 << cfg.block_bits
    Q, G, N = cfg.deep_slots, cfg.deep_ownerval_slots, cfg.num_nodes
    W = cfg.drain_depth + cfg.txn_width
    T = _tile(N)
    vec = pl.BlockSpec((1, T), lambda i: (0, i))
    matC = pl.BlockSpec((C, T), lambda i: (0, i))
    matS = pl.BlockSpec((S, T), lambda i: (0, i))
    matQ = pl.BlockSpec((Q, T), lambda i: (0, i))
    matW = pl.BlockSpec((W, T), lambda i: (0, i))
    blk = lambda rows: (pl.BlockSpec((rows, T), lambda i: (0, i)),
                        jax.ShapeDtypeStruct((rows, N), jnp.int32))
    specs_shapes = [blk(6 * C), blk(7 * S), blk(4 * Q), blk(2 * G),
                    blk(7)]
    return pl.pallas_call(
        functools.partial(_replay_kernel, cfg, T),
        grid=(N // T,),
        in_specs=[matC] * 3 + [matS] * 4 + [matW] * 3 + [vec]
        + [matQ, matS],
        out_specs=[s for s, _ in specs_shapes],
        out_shape=[sh for _, sh in specs_shapes],
        interpret=_interpret(),
    )(ca_t, cv_t, cs_t, *dm_t4, *win_t3, hor2, bad_t, ocode_t)


def round_step_deep_pallas(cfg: SystemConfig, st: SyncState) -> SyncState:
    """One deep-window round with both folds as Pallas kernels.

    Bit-identical to `deep_engine.round_step_deep`
    (tests/test_pallas_deep.py); requires a tileable node count (any
    workload kind — the window is built in XLA). The scatter/gather
    middle runs in the kernels' transposed [Q, N]/[S, N] layout.
    """
    N, C, S = cfg.num_nodes, cfg.cache_size, 1 << cfg.block_bits
    E = N * S
    Q = cfg.deep_slots
    G = cfg.deep_ownerval_slots
    INV = int(CacheState.INVALID)
    EXC = int(CacheState.EXCLUSIVE)
    SHD = int(CacheState.SHARED)
    D_U, D_S, D_EM = int(DirState.U), int(DirState.S), int(DirState.EM)
    rows0 = jnp.arange(N, dtype=jnp.int32)                   # [N]

    dm_own = st.dm.reshape(N, S, DM_COLS)
    dm_t4 = tuple(dm_own[:, :, col].T
                  for col in (DM_STATE, DM_COUNT, DM_OWNER, DM_MEM))
    ca_t, cv_t, cs_t = (st.cache_addr.T, st.cache_val.T,
                        st.cache_state.T)
    hor2 = st.horizon[None, :]

    # ---- instruction window, [W, N] (kernels read static rows) -----------
    W = cfg.drain_depth + cfg.txn_width
    w_idx = jnp.arange(W, dtype=jnp.int32)[:, None] + st.idx[None, :]
    w_live = w_idx < st.instr_count[None, :]
    if cfg.procedural:
        w_oa, w_val = procedural_instr(cfg, rows0[None, :], w_idx)
    else:
        T_ = st.instr_pack.shape[1]
        w_flat = rows0[None, :] * T_ + jnp.minimum(w_idx, T_ - 1)
        w = st.instr_pack.reshape(N * T_, 2)[w_flat]
        w_oa, w_val = w[..., 0], w[..., 1]
    win_t3 = (w_oa, w_val, w_live.astype(jnp.int32))

    # ---- pre-pass fold (attempt everything) ------------------------------
    slotmat, flag_t = _call_pre(cfg, ca_t, cv_t, cs_t, dm_t4, win_t3,
                                hor2)
    kind, ent, sval = (slotmat[:Q], slotmat[Q:2 * Q],
                       slotmat[2 * Q:])                      # [Q, N]
    is_req = (kind == K_RD) | (kind == K_WR) | (kind == K_UP)
    is_ev = (kind == K_EVS) | (kind == K_EVM)
    is_probe = kind == K_PROBE

    # ---- lane scatter (requests + notices only) --------------------------
    prio_bits = max(1, (N - 1).bit_length())
    rk = _round_key(cfg, st, rows0)
    prio = rk & ((1 << prio_bits) - 1)
    countdown = rk >> prio_bits
    key = (countdown << (prio_bits + 1)) | (prio << 1)       # [N]
    key_q = jnp.where(is_ev, key[None, :] | 1, key[None, :])  # [Q, N]
    lane_idx = jnp.where(is_req | is_ev, ent, E).reshape(-1)
    dm_claimed = st.dm.at[lane_idx, DM_CLAIM].min(
        key_q.reshape(-1), mode="drop")

    # ---- gathers: lane-back + dense home flags (ONE fused gather) --------
    safe_ent = jnp.clip(ent, 0, E - 1)
    flags_arr = flag_t.T.reshape(E)
    side = jnp.stack([dm_claimed[:, DM_CLAIM], flags_arr], axis=-1)
    got2 = side[safe_ent]                                    # [Q, N, 2]
    lane_got, got_flags = got2[..., 0], got2[..., 1]

    # ---- slot verdicts + chain-yield codes (deep_engine semantics) -------
    thresh = (jnp.maximum(claim_max_rounds(cfg) - st.round, 0) + 1) \
        << (prio_bits + 1)
    lane_fresh = lane_got < thresh
    lane_is_ev = (lane_got & 1) == 1
    won = lane_got == key_q
    pmask = (1 << prio_bits) - 1
    prio_home = _round_key(cfg, st, safe_ent >> cfg.block_bits) & pmask
    home_wins = prio_home < prio[None, :]                    # [Q, N]
    req_bad = is_req & (~won | (((got_flags & F_POISON) != 0)
                                & home_wins))
    ev_bad = is_ev & (~won | (((got_flags & F_MARK) != 0)
                              & home_wins))
    probe_bad = is_probe & (((got_flags & F_MARK) != 0)
                            | ((sval != 0) & lane_fresh & ~lane_is_ev))
    bad_t = (req_bad | ev_bad | probe_bad).astype(jnp.int32)  # [Q, N]
    own_lane = dm_claimed.reshape(N, S, DM_COLS)[:, :, DM_CLAIM]
    o_fresh = own_lane < thresh                              # [N, S]
    o_ev = (own_lane & 1) == 1
    o_beats = ((own_lane >> 1) & pmask) < prio[:, None]
    o_code = (o_fresh.astype(jnp.int32) * deep_fold.OC_FRESH
              | (o_fresh & o_ev).astype(jnp.int32) * deep_fold.OC_EV
              | (o_fresh & o_beats).astype(jnp.int32)
              * deep_fold.OC_BEATS)

    # ---- replay fold (committed prefix) ----------------------------------
    cachemat, dmmat, slotmat2, gmat, cntmat = _call_replay(
        cfg, ca_t, cv_t, cs_t, dm_t4, win_t3, hor2, bad_t,
        o_code.T)
    ca_c = cachemat[:C]                                      # [C, N]
    cv_r = cachemat[C:2 * C]
    cs_c = cachemat[2 * C:3 * C]
    cv_src = cachemat[3 * C:4 * C]
    cv_req = cachemat[4 * C:5 * C]
    cv_req_src = cachemat[5 * C:]
    dms_r, dmc_r, dmo_r, dmm_r, dmm_src_r = (
        dmmat[:S], dmmat[S:2 * S], dmmat[2 * S:3 * S],
        dmmat[3 * S:4 * S], dmmat[4 * S:5 * S])              # [S, N]
    touched_t = dmmat[5 * S:6 * S] != 0
    act_acc_t = dmmat[6 * S:]
    comm = slotmat2[:Q] != 0                                 # [Q, N]
    rel_q = slotmat2[Q:2 * Q] != 0
    relv = slotmat2[2 * Q:3 * Q]
    reld = slotmat2[3 * Q:] != 0
    g_owner, g_ci = gmat[:G], gmat[G:]                       # [G, N]
    n_ret, rh, wh = cntmat[0], cntmat[1], cntmat[2]          # [N]
    c_rd, c_wr, c_up, c_ev = (cntmat[3], cntmat[4], cntmat[5],
                              cntmat[6])

    # ---- dense merge of own rows (same formulas, transposed) -------------
    rtag = st.round << 4
    g_flat = g_ci * N + jnp.clip(g_owner, 0, N - 1)          # [C,N] flat
    g_vals = cv_req.reshape(-1)[g_flat]                      # [G, N]
    dmm_m, cv_m, cv_req_m = dmm_r, cv_r, cv_req
    for g in range(G):
        dmm_m = jnp.where(dmm_src_r == g, g_vals[g:g + 1, :], dmm_m)
        cv_m = jnp.where(cv_src == g, g_vals[g:g + 1, :], cv_m)
        cv_req_m = jnp.where(cv_req_src == g, g_vals[g:g + 1, :],
                             cv_req_m)
    touched = touched_t.T                                    # [N, S]
    act_col = jnp.where(touched, rtag | act_acc_t.T,
                        dm_own[:, :, DM_ACT])
    merged = jnp.stack([
        jnp.where(touched, dms_r.T, dm_own[:, :, DM_STATE]),
        jnp.where(touched, dmc_r.T, dm_own[:, :, DM_COUNT]),
        jnp.where(touched, dmo_r.T, dm_own[:, :, DM_OWNER]),
        jnp.where(touched, dmm_m.T, dm_own[:, :, DM_MEM]),
        act_col,
        jnp.where(touched, rows0[:, None], dm_own[:, :, DM_REQ]),
        dm_claimed.reshape(N, S, DM_COLS)[:, :, DM_CLAIM],
    ], axis=-1).reshape(E, DM_COLS)
    dm = merged

    # ---- request composition (post-merge, per committed slot) ------------
    commit = (is_req | is_ev) & won & comm
    g_rows = dm[safe_ent]                                    # [Q, N, cols]
    r_state = g_rows[..., DM_STATE]
    r_cnt = g_rows[..., DM_COUNT]
    r_own = g_rows[..., DM_OWNER]
    r_mem = g_rows[..., DM_MEM]
    r_act = g_rows[..., DM_ACT]
    r_ci = codec.cache_index(cfg, safe_ent)
    r_pend = (r_state == D_EM) & (r_own == -1)
    own_val = jnp.where(
        r_pend, r_mem,
        cv_req_m.reshape(-1)[r_ci * N + jnp.clip(r_own, 0, N - 1)])
    r_u = r_state == D_U
    r_s = r_state == D_S
    r_em = r_state == D_EM
    k_rd = commit & (kind == K_RD)
    k_wr = commit & (kind == K_WR)
    k_up = commit & (kind == K_UP)
    k_evs = commit & (kind == K_EVS)
    k_evm = commit & (kind == K_EVM)
    wlike = k_wr | k_up
    rel = rel_q & (k_rd | wlike)
    evs_cnt = jnp.where(r_s, r_cnt - 1, r_cnt)
    n_state = jnp.where(wlike, D_EM,
               jnp.where(k_rd, jnp.where(r_u, D_EM, D_S),
                jnp.where(k_evm | (k_evs & r_em), D_U,
                 jnp.where(k_evs & r_s,
                           jnp.where(evs_cnt == 0, D_U,
                                     jnp.where(evs_cnt == 1, D_EM, D_S)),
                           r_state))))
    n_cnt = jnp.where(wlike | (k_rd & r_u), 1,
             jnp.where(k_rd & r_em, 2,
              jnp.where(k_rd & r_s, r_cnt + 1,
               jnp.where(k_evm | (k_evs & r_em), 0,
                jnp.where(k_evs & r_s, evs_cnt, r_cnt)))))
    req_id = jnp.broadcast_to(rows0[None, :], (Q, N))
    n_own = jnp.where(wlike | (k_rd & r_u), req_id,
             jnp.where(k_evs & r_s & (evs_cnt == 1), -1, r_own))
    n_mem = jnp.where((k_rd | k_wr) & r_em, own_val,
                      jnp.where(k_evm, sval, r_mem))
    n_state = jnp.where(rel, jnp.where(wlike, D_U,
                                       jnp.where(r_em, D_EM, r_state)),
                        n_state)
    n_cnt = jnp.where(rel, jnp.where(wlike, 0,
                                     jnp.where(r_em, 1, r_cnt)), n_cnt)
    n_own = jnp.where(rel, r_own, n_own)
    n_mem = jnp.where(rel, jnp.where(wlike, relv,
                                     jnp.where(r_em, own_val, r_mem)),
                      n_mem)
    tgt_home = r_own == (safe_ent >> cfg.block_bits)
    my_h = jnp.where(wlike, ACT_KILL,
            jnp.where(k_rd & r_em & tgt_home,
                      jnp.where(rel, ACT_PROMOTE, ACT_DOWN),
             jnp.where(k_evs & r_s & (evs_cnt == 1), ACT_PROMOTE,
                       ACT_NONE)))
    my_o = jnp.where(wlike, ACT_KILL,
            jnp.where(k_rd & r_em & ~tgt_home,
                      jnp.where(rel, ACT_PROMOTE, ACT_DOWN),
             jnp.where(k_evs & r_s & (evs_cnt == 1), ACT_PROMOTE,
                       ACT_NONE)))
    chain_fresh = (r_act >> 4) == st.round
    chain_act = jnp.where(chain_fresh, r_act & 3, ACT_NONE)
    act_o = jnp.where(chain_act == ACT_PROMOTE,
                      jnp.where(wlike, ACT_KILL,
                                jnp.where(k_rd & rel, ACT_PROMOTE,
                                          jnp.where(k_rd, ACT_DOWN,
                                                    ACT_NONE))),
                      jnp.maximum(chain_act, my_o))
    n_act = rtag | (my_h << 2) | act_o
    t_idx = jnp.where(commit, safe_ent, E).reshape(-1)
    t_rows = jnp.stack(
        [n_state, n_cnt, n_own, n_mem, n_act, req_id, key_q],
        axis=-1).reshape(-1, DM_COLS)
    dm = dm.at[t_idx].set(t_rows, mode="drop")

    # ---- reply patches on the requester's cache --------------------------
    fill_e = k_rd & r_u
    fill_val = jnp.where(r_em, own_val, r_mem)
    patch = k_rd & ~rel
    ca_rows = [ca_c[c:c + 1, :] for c in range(C)]
    cv_rows = [cv_m[c:c + 1, :] for c in range(C)]
    cs_rows = [cs_c[c:c + 1, :] for c in range(C)]
    for q in range(Q):
        m_q = patch[q:q + 1, :]
        rci_q = r_ci[q:q + 1, :]
        fe_q, fv_q = fill_e[q:q + 1, :], fill_val[q:q + 1, :]
        for c in range(C):
            oh = (rci_q == c) & m_q
            cs_rows[c] = jnp.where(oh & fe_q, EXC, cs_rows[c])
            cv_rows[c] = jnp.where(oh, fv_q, cv_rows[c])
    ca_c = jnp.concatenate(ca_rows, axis=0)
    cv_c = jnp.concatenate(cv_rows, axis=0)
    cs_c = jnp.concatenate(cs_rows, axis=0)

    # ---- fan-out ---------------------------------------------------------
    # act + req packed into ONE dense [E] column (see deep_engine)
    line_e = jnp.clip(ca_c, 0, E - 1)                        # [C, N]
    fan_fresh = (dm[:, DM_ACT] >> 4) == st.round
    fan_packed = (jnp.where(fan_fresh,
                            ((dm[:, DM_ACT] & 15) | 16) << 16, 0)
                  | dm[:, DM_REQ])
    line_f = fan_packed[line_e]                              # [C, N]
    fresh = ((line_f >> 20) & 1) == 1
    l_act_h = jnp.where(fresh, (line_f >> 18) & 3, ACT_NONE)
    l_act_o = jnp.where(fresh, (line_f >> 16) & 3, ACT_NONE)
    l_req = line_f & 0xFFFF
    l_home = line_e >> cfg.block_bits
    i_am_home = l_home == rows0[None, :]
    a_code = jnp.where(i_am_home, l_act_h, l_act_o)
    valid = cs_c != INV
    not_self = l_req != rows0[None, :]
    kill = valid & not_self & (a_code == ACT_KILL)
    down = valid & not_self & (a_code == ACT_DOWN)
    promo = valid & not_self & (a_code == ACT_PROMOTE)
    cs_c = jnp.where(kill, INV,
                     jnp.where(down, SHD,
                               jnp.where(promo, EXC, cs_c)))
    dm = dm.at[jnp.where(promo, line_e, E).reshape(-1), DM_OWNER].set(
        jnp.broadcast_to(rows0[None, :], (C, N)).reshape(-1),
        mode="drop")

    # ---- bookkeeping -----------------------------------------------------
    deltas = [jnp.sum(x, dtype=jnp.int32) for x in
              (n_ret, rh, wh, c_rd, c_wr, c_up,
               (is_req | is_ev) & ~won, c_ev, kill, promo)]
    mt = st.metrics
    metrics = mt.replace(
        rounds=mt.rounds + 1,
        instrs_retired=mt.instrs_retired + deltas[0],
        read_hits=mt.read_hits + deltas[1],
        write_hits=mt.write_hits + deltas[2],
        read_misses=mt.read_misses + deltas[3],
        write_misses=mt.write_misses + deltas[4],
        upgrades=mt.upgrades + deltas[5],
        conflicts=mt.conflicts + deltas[6],
        evictions=mt.evictions + deltas[7],
        invalidations=mt.invalidations + deltas[8],
        promotions=mt.promotions + deltas[9],
    )
    return st.replace(cache_addr=ca_c.T, cache_val=cv_c.T,
                      cache_state=cs_c.T, dm=dm, idx=st.idx + n_ret,
                      horizon=jnp.clip(
                          n_ret + cfg.deep_horizon_slack, 2, 1 << 20),
                      round=st.round + 1, metrics=metrics)
