"""Pallas TPU kernels for the deep-window round's fold passes.

The deep engine's round is **fold-bound**: two W-step folds (pre-pass
and replay, ops/deep_engine._fold_deep) of dense per-node arithmetic,
traced as a `lax.scan` whose every step is a separate XLA fusion over
~250 small [N] vectors. On the bench device the scatter/gather middle
of the round costs ~0.3-0.5 ms while the two folds cost ~1.3 ms
(scripts/prof_deep.py, round 2) — the fold is pure VPU work that
belongs in a kernel.

Because `ops.deep_fold` is layout-neutral (every per-node scalar is a
"vec", every table a python list of vecs), the IDENTICAL fold code runs
here with vecs as [1, T] lane rows — the node axis fills the 128-wide
lanes — as an unrolled W-step loop (mosaic constraints: no bool
vector loop carries, no `arith.select` on i1 vectors — the fold's
helpers use mask algebra for bools). The instruction window is built
in XLA ([W, N]: procedural hash or stored-trace gather, identical to
the XLA path) and read with static row indices, so the kernel body
performs no dynamic memory access and serves EVERY workload kind.
One kernel instance owns a node tile; the live fold state (~250
[1, T] vecs, ~1 MB at T=1024) stays in vector registers/VMEM.

The claim scatter-min, lane/flag gathers, and commit scatters between
and after the folds stay in XLA (TPU Pallas has no vector gather) and
are computed in the kernels' transposed [Q, N]/[S, N] layout so only a
handful of small per-round transposes appear.

`round_step_deep_pallas` is bit-identical to
`deep_engine.round_step_deep` (tests/test_pallas_deep.py); enabled for
procedural workloads on tileable node counts via cfg.pallas_burst,
exactly like ops/pallas_window.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

from ue22cs343bb1_openmp_assignment_tpu.config import SystemConfig
from ue22cs343bb1_openmp_assignment_tpu.ops import deep_fold
from ue22cs343bb1_openmp_assignment_tpu.ops.deep_engine import (
    F_MARK, F_POISON)
from ue22cs343bb1_openmp_assignment_tpu.ops.pallas_burst import (
    _interpret, _tile)
from ue22cs343bb1_openmp_assignment_tpu.ops.sync_engine import (
    DM_COLS, DM_COUNT, DM_MEM, DM_OWNER, DM_STATE, SyncState)


def _run_fold(cfg: SystemConfig, T: int, ca_ref, cv_ref, cs_ref,
              dms_ref, dmc_ref, dmo_ref, dmm_ref, woa_ref, wval_ref,
              wlive_ref, hor_ref, bad_refs, ocode_ref, pid=None):
    """Trace the W-step deep fold on [1, T] lane rows; returns the
    final carry (deep_fold.fold_step contract).

    The instruction window arrives as [W, T] blocks (built in XLA —
    procedural hash or stored-trace gather, exactly as the XLA path
    builds it), so the unrolled loop reads each step with a *static*
    row index and the kernel works for every workload kind.

    ``pid`` overrides the grid coordinate (default: the pallas program
    id). The fused round body runs at grid (1,) and passes 0, which
    keeps it traceable outside a kernel context — that is how
    analysis/kernelcheck audits it statically."""
    C, S = cfg.cache_size, 1 << cfg.block_bits
    Q = cfg.deep_slots
    W = cfg.drain_depth + cfg.txn_width
    if pid is None:
        pid = pl.program_id(0)
    node = jax.lax.broadcasted_iota(jnp.int32, (1, T), 1) + pid * T
    zero = jnp.zeros((1, T), jnp.int32)
    false = jnp.zeros((1, T), bool)
    hor = hor_ref[...]
    carry0 = deep_fold.fold_carry0(
        cfg,
        ca=[ca_ref[c:c + 1, :] for c in range(C)],
        cv=[cv_ref[c:c + 1, :] for c in range(C)],
        cs=[cs_ref[c:c + 1, :] for c in range(C)],
        dm_rows=dict(
            dms=[dms_ref[s:s + 1, :] for s in range(S)],
            dmc=[dmc_ref[s:s + 1, :] for s in range(S)],
            dmo=[dmo_ref[s:s + 1, :] for s in range(S)],
            dmm=[dmm_ref[s:s + 1, :] for s in range(S)]),
        zero=zero, false=false)
    badL = ([zero] * Q if bad_refs is None
            else [bad_refs[q:q + 1, :] for q in range(Q)])
    ocodeL = ([zero] * S if ocode_ref is None
              else [ocode_ref[s:s + 1, :] for s in range(S)])

    # unrolled python loop: a fori_loop's bool vector carries hit an
    # unsupported mosaic lowering (trunci i8->i1); the unrolled fold is
    # the proven pattern (ops/pallas_window, scripts/prof_deepcost K)
    c = carry0
    for k in range(W):
        oa = woa_ref[k:k + 1, :]
        val = wval_ref[k:k + 1, :]
        live = wlive_ref[k:k + 1, :] != 0
        c = deep_fold.fold_step(cfg, c, node, oa, val, live, k,
                                hor, badL, ocodeL)
    return c


def _cat(rows):
    return jnp.concatenate([r.astype(jnp.int32) for r in rows], axis=0)


def _pre_kernel(cfg, T, ca_ref, cv_ref, cs_ref, dms_ref, dmc_ref,
                dmo_ref, dmm_ref, woa_ref, wval_ref, wlive_ref,
                hor_ref, slot_ref, flag_ref):
    fin = _run_fold(cfg, T, ca_ref, cv_ref, cs_ref, dms_ref, dmc_ref,
                    dmo_ref, dmm_ref, woa_ref, wval_ref, wlive_ref,
                    hor_ref, None, None)
    slot_ref[...] = _cat(fin["kind"] + fin["ent"] + fin["sval"])
    flag_ref[...] = _cat(
        [m.astype(jnp.int32) * F_MARK + p.astype(jnp.int32) * F_POISON
         for m, p in zip(fin["mark"], fin["poison"])])


def _replay_kernel(cfg, T, ca_ref, cv_ref, cs_ref, dms_ref, dmc_ref,
                   dmo_ref, dmm_ref, woa_ref, wval_ref, wlive_ref,
                   hor_ref, bad_ref, ocode_ref,
                   cache_ref, dm_ref, slot_ref, g_ref, cnt_out_ref):
    fin = _run_fold(cfg, T, ca_ref, cv_ref, cs_ref, dms_ref, dmc_ref,
                    dmo_ref, dmm_ref, woa_ref, wval_ref, wlive_ref,
                    hor_ref, bad_ref, ocode_ref)
    cache_ref[...] = _cat(fin["ca"] + fin["cv"] + fin["cs"]
                          + fin["cv_src"] + fin["cv_req"]
                          + fin["cv_req_src"] + fin["lwh"])
    dm_ref[...] = _cat(fin["dms"] + fin["dmc"] + fin["dmo"] + fin["dmm"]
                       + fin["dmm_src"] + fin["touched"]
                       + fin["act_acc"])
    slot_ref[...] = _cat(fin["comm"] + fin["rel"] + fin["relv"]
                         + fin["reld"])
    g_ref[...] = _cat(fin["g_owner"] + fin["g_ci"])
    cnt_out_ref[...] = _cat([fin["n_ret"], fin["rh"], fin["wh"],
                             fin["c_rd"], fin["c_wr"], fin["c_up"],
                             fin["c_ev"]])


def _flags_kernel(cfg, T, ca_ref, cv_ref, cs_ref, dms_ref, dmc_ref,
                  dmo_ref, dmm_ref, woa_ref, wval_ref, wlive_ref,
                  hor_ref, ocode_ref, flag_ref):
    """Flag-pass fold (round 5): the yield/stop-truncated fold whose
    ONLY output is the retirement-gated mark/poison matrix — the
    commit-prefix-sharp flags the round middle gathers (deep_engine,
    the ghost-abort elimination). Slot verdicts are always zero in
    this pass (the dense o_code yields are the only truncation), so
    there is no bad input."""
    fin = _run_fold(cfg, T, ca_ref, cv_ref, cs_ref, dms_ref, dmc_ref,
                    dmo_ref, dmm_ref, woa_ref, wval_ref, wlive_ref,
                    hor_ref, None, ocode_ref)
    flag_ref[...] = _cat(
        [m.astype(jnp.int32) * F_MARK + p.astype(jnp.int32) * F_POISON
         for m, p in zip(fin["mark"], fin["poison"])])


def _call_flags(cfg, ca_t, cv_t, cs_t, dm_t4, win_t3, hor2, ocode_t):
    C, S = cfg.cache_size, 1 << cfg.block_bits
    N = cfg.num_nodes
    W = cfg.drain_depth + cfg.txn_width
    T = _tile(N)
    vec = pl.BlockSpec((1, T), lambda i: (0, i))
    matC = pl.BlockSpec((C, T), lambda i: (0, i))
    matS = pl.BlockSpec((S, T), lambda i: (0, i))
    matW = pl.BlockSpec((W, T), lambda i: (0, i))
    return pl.pallas_call(
        functools.partial(_flags_kernel, cfg, T),
        grid=(N // T,),
        in_specs=[matC] * 3 + [matS] * 4 + [matW] * 3 + [vec, matS],
        out_specs=pl.BlockSpec((S, T), lambda i: (0, i)),
        out_shape=jax.ShapeDtypeStruct((S, N), jnp.int32),
        interpret=_interpret(),
    )(ca_t, cv_t, cs_t, *dm_t4, *win_t3, hor2, ocode_t)


def _call_pre(cfg, ca_t, cv_t, cs_t, dm_t4, win_t3, hor2):
    C, S = cfg.cache_size, 1 << cfg.block_bits
    Q, N = cfg.deep_slots, cfg.num_nodes
    W = cfg.drain_depth + cfg.txn_width
    T = _tile(N)
    vec = pl.BlockSpec((1, T), lambda i: (0, i))
    matC = pl.BlockSpec((C, T), lambda i: (0, i))
    matS = pl.BlockSpec((S, T), lambda i: (0, i))
    matW = pl.BlockSpec((W, T), lambda i: (0, i))
    blk = lambda rows: (pl.BlockSpec((rows, T), lambda i: (0, i)),
                        jax.ShapeDtypeStruct((rows, N), jnp.int32))
    slot_spec, slot_shape = blk(3 * Q)
    flag_spec, flag_shape = blk(S)
    return pl.pallas_call(
        functools.partial(_pre_kernel, cfg, T),
        grid=(N // T,),
        in_specs=[matC] * 3 + [matS] * 4 + [matW] * 3 + [vec],
        out_specs=[slot_spec, flag_spec],
        out_shape=[slot_shape, flag_shape],
        interpret=_interpret(),
    )(ca_t, cv_t, cs_t, *dm_t4, *win_t3, hor2)


def _call_replay(cfg, ca_t, cv_t, cs_t, dm_t4, win_t3, hor2,
                 bad_t, ocode_t):
    C, S = cfg.cache_size, 1 << cfg.block_bits
    Q, G, N = cfg.deep_slots, cfg.deep_ownerval_slots, cfg.num_nodes
    W = cfg.drain_depth + cfg.txn_width
    T = _tile(N)
    vec = pl.BlockSpec((1, T), lambda i: (0, i))
    matC = pl.BlockSpec((C, T), lambda i: (0, i))
    matS = pl.BlockSpec((S, T), lambda i: (0, i))
    matQ = pl.BlockSpec((Q, T), lambda i: (0, i))
    matW = pl.BlockSpec((W, T), lambda i: (0, i))
    blk = lambda rows: (pl.BlockSpec((rows, T), lambda i: (0, i)),
                        jax.ShapeDtypeStruct((rows, N), jnp.int32))
    specs_shapes = [blk(7 * C), blk(7 * S), blk(4 * Q), blk(2 * G),
                    blk(7)]
    return pl.pallas_call(
        functools.partial(_replay_kernel, cfg, T),
        grid=(N // T,),
        in_specs=[matC] * 3 + [matS] * 4 + [matW] * 3 + [vec]
        + [matQ, matS],
        out_specs=[s for s, _ in specs_shapes],
        out_shape=[sh for _, sh in specs_shapes],
        interpret=_interpret(),
    )(ca_t, cv_t, cs_t, *dm_t4, *win_t3, hor2, bad_t, ocode_t)


def fold_pre(cfg: SystemConfig, st: SyncState, tiles, w_oa, w_val,
             w_live):
    """Pre-pass fold via the Pallas kernel, in the shared transposed
    tile layout (deep_engine.state_tiles): kind/ent/sval [Q, N],
    mark/poison [S, N]. Window arrives [W, N]. No transposes — the
    round middle consumes exactly the kernels' output layout."""
    Q = cfg.deep_slots
    ca_t, cv_t, cs_t, dm_t4 = tiles
    win_t3 = (w_oa, w_val, w_live.astype(jnp.int32))
    slotmat, flag_t = _call_pre(cfg, ca_t, cv_t, cs_t, dm_t4, win_t3,
                                st.horizon[None, :])
    return dict(kind=slotmat[:Q], ent=slotmat[Q:2 * Q],
                sval=slotmat[2 * Q:],
                mark=(flag_t & F_MARK) != 0,
                poison=(flag_t & F_POISON) != 0)


def fold_flags(cfg: SystemConfig, st: SyncState, tiles, w_oa, w_val,
               w_live, ocode):
    """Flag-pass fold via the Pallas kernel: mark/poison [S, N] only
    (deep_engine's commit-prefix-sharp flag pass, round 5)."""
    ca_t, cv_t, cs_t, dm_t4 = tiles
    win_t3 = (w_oa, w_val, w_live.astype(jnp.int32))
    flag_t = _call_flags(cfg, ca_t, cv_t, cs_t, dm_t4, win_t3,
                         st.horizon[None, :], ocode)
    return dict(mark=(flag_t & F_MARK) != 0,
                poison=(flag_t & F_POISON) != 0)


def fold_replay(cfg: SystemConfig, st: SyncState, tiles, w_oa, w_val,
                w_live, bad, ocode):
    """Replay fold via the Pallas kernel; bad [Q, N] slot verdicts and
    ocode [S, N] own-lane codes as in deep_engine._fold_deep. Returns
    the transposed-tile-layout subset of the final carry the round
    middle consumes."""
    C, S = cfg.cache_size, 1 << cfg.block_bits
    Q, G = cfg.deep_slots, cfg.deep_ownerval_slots
    ca_t, cv_t, cs_t, dm_t4 = tiles
    win_t3 = (w_oa, w_val, w_live.astype(jnp.int32))
    cachemat, dmmat, slotmat2, gmat, cntmat = _call_replay(
        cfg, ca_t, cv_t, cs_t, dm_t4, win_t3, st.horizon[None, :],
        bad, ocode)
    return dict(
        ca=cachemat[:C], cv=cachemat[C:2 * C],
        cs=cachemat[2 * C:3 * C], cv_src=cachemat[3 * C:4 * C],
        cv_req=cachemat[4 * C:5 * C],
        cv_req_src=cachemat[5 * C:6 * C],
        lwh=cachemat[6 * C:] != 0,
        dms=dmmat[:S], dmc=dmmat[S:2 * S], dmo=dmmat[2 * S:3 * S],
        dmm=dmmat[3 * S:4 * S], dmm_src=dmmat[4 * S:5 * S],
        touched=dmmat[5 * S:6 * S] != 0, act_acc=dmmat[6 * S:],
        comm=slotmat2[:Q] != 0, rel=slotmat2[Q:2 * Q] != 0,
        relv=slotmat2[2 * Q:3 * Q], reld=slotmat2[3 * Q:] != 0,
        g_owner=gmat[:G], g_ci=gmat[G:],
        n_ret=cntmat[0], rh=cntmat[1], wh=cntmat[2],
        cnt=dict(rd_miss=cntmat[3], wr_miss=cntmat[4], upg=cntmat[5],
                 ev=cntmat[6]))


def round_step_deep_pallas(cfg: SystemConfig, st: SyncState) -> SyncState:
    """One deep-window round with both folds as Pallas kernels —
    deep_engine.round_step_deep with fold_impl="pallas" (the
    arbitration/composition/fan-out middle is shared code, so the
    rounds are bit-identical by construction given bit-identical
    folds, which tests/test_pallas_deep.py pins). Requires a tileable
    node count (any workload kind — the window is built in XLA)."""
    from ue22cs343bb1_openmp_assignment_tpu.ops.deep_engine import (
        round_step_deep)
    return round_step_deep(cfg, st, fold_impl="pallas")
