"""Seeded handler bugs: the model checker's own regression suite.

Each mutation wraps the real :func:`..ops.handlers.message_phase` and
perturbs exactly one transition effect — the classic protocol-bug
shapes a hand-written MESI implementation gets wrong. `cache-sim
analyze` must exit 0 on the shipped handlers and 1 under every one of
these (tests/test_static_analysis.py); a checker that misses any of
them is not trusted in CI.

Every wrapper keeps the `message_phase` contract (updates, cand_parts,
inv_scatter, stats) and is injected through ops/step.cycle's
``message_phase`` hook, so the surrounding engine — merge, delivery,
arbitration — stays the shipped code.

:data:`TABLE_MUTATIONS` is the same idea one level up: seeded bugs in
the declarative protocol table, each caught *statically* by
analysis/verify_table.py without running a single cycle — and the
handler mutants above double as conformance-gate mutants, since any of
them makes the live phase diverge from the MESI table
(analysis/conformance.py, tests/test_protocol_table.py).
"""

from __future__ import annotations

import jax.numpy as jnp

from ue22cs343bb1_openmp_assignment_tpu import codec
from ue22cs343bb1_openmp_assignment_tpu.ops import handlers
from ue22cs343bb1_openmp_assignment_tpu.state import bit_single
from ue22cs343bb1_openmp_assignment_tpu.types import DirState, Msg, Op


def _is(mv, ty):
    return mv.has_msg & (mv.type == int(ty))


def skip_em_bitvec_clear(cfg, state, mv):
    """EVICT_MODIFIED still sets the directory Unowned but forgets to
    clear the owner's sharer bit (the reference clears it at
    ``assignment.c:615-616``). Expected: `unowned_with_sharers`
    engine-tier violation on every post-eviction state."""
    upd, cand, inv, stats = handlers.message_phase(cfg, state, mv)
    m, i, v = upd["dir_bv"]
    upd = dict(upd, dir_bv=(m & ~_is(mv, Msg.EVICT_MODIFIED), i, v))
    return upd, cand, inv, stats


def upgrade_keeps_other_sharers(cfg, state, mv):
    """UPGRADE grants EM ownership without shrinking the sharer set to
    the new owner (the reference overwrites the bitvector with the
    requester's bit, ``assignment.c:346-348``). Expected:
    `em_not_single_owner` engine-tier violation."""
    upd, cand, inv, stats = handlers.message_phase(cfg, state, mv)
    rows = jnp.arange(cfg.num_nodes, dtype=jnp.int32)
    dirbv = state.dir_bitvec[rows, codec.block_index(cfg, mv.addr)]
    m, i, v = upd["dir_bv"]
    keep = _is(mv, Msg.UPGRADE)[:, None]
    upd = dict(upd, dir_bv=(m, i, jnp.where(keep, v | dirbv, v)))
    return upd, cand, inv, stats


def no_wait_clear_on_reply_rd(cfg, state, mv):
    """REPLY_RD delivers the fill but never unblocks the requester
    (the reference clears ``waitingForReply`` in every reply handler,
    ``assignment.c:384``). Expected: `deadlock` — a terminal state
    with the reader still blocked. Must run on the read-only scope
    ``2n1a_r``: in the write scopes quirk 2 (FLUSH/FLUSH_INVACK clear
    `waiting` unconditionally) rescues the stranded reader on every
    interleaving and masks the bug."""
    upd, cand, inv, stats = handlers.message_phase(cfg, state, mv)
    upd = dict(upd,
               wait_clear=upd["wait_clear"] & ~_is(mv, Msg.REPLY_RD))
    return upd, cand, inv, stats


def drop_evict_modified(cfg, state, mv):
    """EVICT_MODIFIED is dequeued and then ignored entirely — no
    memory write-back, no directory update (the reference's handler at
    ``assignment.c:596-616``). Expected: `unhandled_pair` from the
    handler-engagement probe."""
    upd, cand, inv, stats = handlers.message_phase(cfg, state, mv)
    dead = _is(mv, Msg.EVICT_MODIFIED)
    keep = ~dead
    cs_m, cs_v = upd["cache_state"]
    fl_m, fl_v = upd["cache_addr"]
    cv_m, cv_v = upd["cache_val"]
    mm, mi, mval = upd["mem"]
    dm, di, dv = upd["dir_state"]
    bm, bi, bv = upd["dir_bv"]
    upd = dict(upd,
               cache_state=(cs_m & keep, cs_v),
               cache_addr=(fl_m & keep, fl_v),
               cache_val=(cv_m & keep, cv_v),
               mem=(mm & keep, mi, mval),
               dir_state=(dm & keep, di, dv),
               dir_bv=(bm & keep, bi, bv),
               wait_clear=upd["wait_clear"] & keep)
    return upd, cand, inv, stats


def stale_owner_forward(cfg, state, mv):
    """READ_REQUEST on a dirty (EM) line replies straight from memory
    instead of forwarding WRITEBACK_INT to the owner (the reference
    forwards at ``assignment.c:277-286``), and registers the requester
    as a sharer while the directory still says EM. Expected:
    `em_not_single_owner` — two presence bits under an EM entry."""
    upd, cand, inv, stats = handlers.message_phase(cfg, state, mv)
    rows = jnp.arange(cfg.num_nodes, dtype=jnp.int32)
    p_block = codec.block_index(cfg, mv.addr)
    dirst = state.dir_state[rows, p_block]
    dirbv = state.dir_bitvec[rows, p_block]
    memv = state.memory[rows, p_block]
    rr_em = (_is(mv, Msg.READ_REQUEST)
             & (rows == codec.home_node(cfg, mv.addr))
             & (dirst == int(DirState.EM)))
    ty, recv, ad, val, sec, ds, bv = cand["pri"]
    cand = dict(cand, pri=(
        jnp.where(rr_em, int(Msg.REPLY_RD), ty),
        jnp.where(rr_em, mv.sender, recv), ad,
        jnp.where(rr_em, memv, val),
        jnp.where(rr_em, 0, sec), ds, bv))
    m, i, v = upd["dir_bv"]
    sender_bit = bit_single(cfg.bitvec_words, mv.sender)
    upd = dict(upd, dir_bv=(
        m | rr_em, i,
        jnp.where(rr_em[:, None], dirbv | sender_bit, v)))
    return upd, cand, inv, stats


def evict_shared_keeps_bit(cfg, state, mv):
    """EVICT_SHARED at the home updates the directory state but never
    clears the evictor's presence bit (the reference drops it at
    ``assignment.c:566``) — the sharer-count decrement is lost, like a
    dropped invalidation ack. Expected: `unowned_with_sharers` when the
    last sharer leaves (U entry with bits set) or
    `em_not_single_owner` when the survivor is promoted."""
    upd, cand, inv, stats = handlers.message_phase(cfg, state, mv)
    rows = jnp.arange(cfg.num_nodes, dtype=jnp.int32)
    p_block = codec.block_index(cfg, mv.addr)
    dirbv = state.dir_bitvec[rows, p_block]
    es_home = (_is(mv, Msg.EVICT_SHARED)
               & (rows == codec.home_node(cfg, mv.addr)))
    m, i, v = upd["dir_bv"]
    upd = dict(upd, dir_bv=(
        m, i, jnp.where(es_home[:, None], dirbv, v)))
    return upd, cand, inv, stats


# ---------------------------------------------------------------------------
# Consistency mutants: bugs that keep every per-state invariant happy —
# the directory, the bitvecs, the line states all stay self-consistent —
# and corrupt only the *values a program observes*. They are invisible
# to the invariant/coherence tiers and to per-location axioms (a stale
# reload of an old value per-location just looks like "the write came
# last"); the referees with teeth are the litmus enumeration
# (analysis/litmus.py — the ``mp_reload`` shape) and the fuzzer's
# consistency oracle (analysis/axioms.py — the gated full-SC check and
# the litmus outcome-membership check).
# ---------------------------------------------------------------------------


def stale_fill_from_invalid(cfg, state, mv):
    """A read fill (REPLY_RD from the home, or the owner-forwarded
    FLUSH) that lands on a tag-matching (invalidated) resident line
    serves the *stale local copy* instead of the reply's data — the
    classic forgot-to-actually-use-the-fill bug: first fills are
    clean, but a reload after an INV resurrects the dead value.
    Expected: `sc_cycle` (a reader that saw the flag write falls back
    to pre-invalidation data) and a forbidden ``mp_reload`` outcome."""
    upd, cand, inv, stats = handlers.message_phase(cfg, state, mv)
    rows = jnp.arange(cfg.num_nodes, dtype=jnp.int32)
    cidx = codec.cache_index(cfg, mv.addr)
    stale = ((_is(mv, Msg.REPLY_RD) | _is(mv, Msg.FLUSH))
             & (state.cache_addr[rows, cidx] == mv.addr)
             & state.waiting & (state.cur_addr == mv.addr)
             & (state.cur_op == int(Op.READ)))
    cv_m, cv_v = upd["cache_val"]
    upd = dict(upd, cache_val=(
        cv_m, jnp.where(stale, state.cache_val[rows, cidx], cv_v)))
    return upd, cand, inv, stats


def skip_inv_fanout(cfg, state, mv):
    """The write commits without its invalidation fan-out: REPLY_ID
    still grants EM ownership, but the sharer-set INVs are never sent
    (mailbox mode) / never applied (scatter mode) — a write commit
    reordered past its pending invalidation acks. Old sharers keep
    VALID stale copies and *hit* on them. Expected: `sc_cycle` and a
    forbidden ``mp_upgrade`` outcome (the stale-SHARED-copy shape —
    MESI's first-reader-EXCLUSIVE means only a shape where BOTH nodes
    read x before the write ever takes the UPGRADE path)."""
    upd, cand, inv, stats = handlers.message_phase(cfg, state, mv)
    if cand.get("inv") is not None and cand["inv"][0] is not None:
        ty, recv, ad = cand["inv"]
        cand = dict(cand, inv=(
            jnp.full_like(ty, int(Msg.NONE)), recv, ad))
    if inv is not None:
        m, a, bv = inv
        inv = (m & False, a, bv)
    return upd, cand, inv, stats


#: name -> (wrapper, litmus test whose enumeration kills it, axioms
#: check the consistency oracle must raise, kill delays, kill periods).
#: The delay/period pins are a concrete interleaving (found by sweep,
#: frozen here) on which the litmus seed case run under the mutant
#: produces the forbidden outcome — so the axiomatic oracle has a
#: deterministic witness run, not just the exhaustive enumeration.
CONSISTENCY_MUTATIONS = {
    "stale_fill_from_invalid": (stale_fill_from_invalid, "mp_reload",
                                "sc_cycle", (2, 0), (0, 4)),
    "skip_inv_fanout": (skip_inv_fanout, "mp_upgrade",
                        "sc_cycle", (0, 0), (0, 12)),
}


# ---------------------------------------------------------------------------
# Table-level mutants: seeded bugs in the DECLARATIVE protocol table
# (analysis/protocol_table.py), caught statically by verify_table with
# no simulation at all — the verify passes' own regression suite,
# mirroring what MUTATIONS is for the model checker. Each takes a
# ProtocolTable and returns a perturbed copy.
# ---------------------------------------------------------------------------

def table_guard_overlap(table):
    """Widen ``es_home_last``'s guard to ALL of EVICT_SHARED@home (drop
    the others=0 key): it now overlaps every other es_home_* row — the
    classic copy-paste-a-row-and-forget-the-key bug. Expected:
    `determinism_overlap` from the totality/determinism pass."""
    import dataclasses
    from ue22cs343bb1_openmp_assignment_tpu.analysis.protocol_table import \
        Guard
    rows = tuple(
        dataclasses.replace(r, guard=Guard(at_home=True))
        if r.name == "es_home_last" else r for r in table.rows)
    return dataclasses.replace(table, name=table.name + "+guard_overlap",
                               rows=rows)


def table_drop_row(table):
    """Delete the EVICT_MODIFIED row outright — a dirty eviction
    arrives and no rule fires, the message-vocabulary analogue of
    `drop_evict_modified`. Expected: `totality_hole`."""
    import dataclasses
    rows = tuple(r for r in table.rows if r.name != "evict_modified")
    return dataclasses.replace(table, name=table.name + "+drop_row",
                               rows=rows)


# name -> (mutator, verify_table finding kind it must trigger)
TABLE_MUTATIONS = {
    "table_guard_overlap": (table_guard_overlap, "determinism_overlap"),
    "table_drop_row": (table_drop_row, "totality_hole"),
}


# ---------------------------------------------------------------------------
# Kernel-contract mutants: seeded bugs in the fused Pallas round's
# arithmetic contracts, each caught *statically* by the kernel-contract
# verifier (analysis/kernelcheck, `cache-sim analyze --kernel`) with no
# trace and no execution — the verifier's own regression suite. Each is
# a context manager that perturbs the real module-level parameter the
# kernel routes with (ops/pallas_round reads these constants at trace
# time, and kernelcheck derives its caps from the same names, so the
# mutation hits both the kernel and its proof obligation).
# ---------------------------------------------------------------------------

import contextlib


@contextlib.contextmanager
def widen_min_chunk():
    """Widen the scatter-min ladder chunk from 4 to 5 bits — "fewer
    passes, same ladder" looks like a free optimization, but the
    32-value ladder's lowest rung becomes 2**(100 - 15*31) = 2**-365,
    far below f32's 2**-126 minimum normal: the deep rungs flush to
    zero and the min-chunk readout silently loses deep contenders.
    Expected: `ladder_range` from the exactness pass."""
    from ue22cs343bb1_openmp_assignment_tpu.ops import pallas_round as pr
    old = pr._MIN_CHUNK_BITS
    pr._MIN_CHUNK_BITS = 5
    try:
        yield
    finally:
        pr._MIN_CHUNK_BITS = old


@contextlib.contextmanager
def narrow_ladder_gap():
    """Shrink the weight-exponent gap G from 15 to 11 — the ladder
    still spans comfortably inside f32 range (tempting if someone
    wants headroom for more chunks), but adjacent-threshold separation
    collapses to 2**11, so the certified contender cap drops to 2**10
    = 1024, under the headline's 4096 per-entry contenders. Expected:
    `contender_cap` from the exactness pass."""
    from ue22cs343bb1_openmp_assignment_tpu.ops import pallas_round as pr
    old = pr._MIN_G
    pr._MIN_G = 11
    try:
        yield
    finally:
        pr._MIN_G = old


@contextlib.contextmanager
def lift_storm_gate():
    """Drop the read-storm structural gate from
    ``pallas_round.supported`` — the contender arithmetic happily
    admits small storm configs, but duplicate-row storm commits break
    the routed scatters' uniqueness contract, which no rounding margin
    covers. Expected: `gate_divergence` from the gate-consistency pass
    (supported() says yes on the storm probe; the analyzer says no)."""
    from ue22cs343bb1_openmp_assignment_tpu.analysis import kernelcheck
    from ue22cs343bb1_openmp_assignment_tpu.ops import pallas_round as pr
    old = pr.supported

    def patched(cfg):
        if not cfg.deep_window:
            return False
        b = kernelcheck.derived_bounds(cfg)
        return b["max_contenders"] < b["cap_limit"]

    pr.supported = patched
    try:
        yield
    finally:
        pr.supported = old


#: name -> (context manager seeding the bug, kernelcheck finding kind
#: the --kernel prong must raise). All killed with trace=False: the
#: exactness/gates passes are pure arithmetic.
KERNEL_MUTATIONS = {
    "widen_min_chunk": (widen_min_chunk, "ladder_range"),
    "narrow_ladder_gap": (narrow_ladder_gap, "contender_cap"),
    "lift_storm_gate": (lift_storm_gate, "gate_divergence"),
}


@contextlib.contextmanager
def split_packed_scatter():
    """Re-split the round-8 packed row commits back into per-plane
    scatters (ops/step._PACKED_COMMIT seam): cache_state/addr/val and
    memory/dir_state/dir_bitvec each get their own gather+scatter
    again, every split scatter sharing the family's one index vector
    and unset columns writing back their own old value. Bit-identical
    to the shipped packed commit — the model checker, fuzzer,
    conformance gate and every golden dump stay green — but index
    sites in step.cycle jump 27 -> 35 and the per-plane scatters
    re-form exactly the shared-index/disjoint-dest pattern the merge
    detector names. Expected: `index_budget` from the --index prong's
    budget pass, plus merge-candidate findings listing the re-split
    planes. Only the static index audit can see this mutant."""
    from ue22cs343bb1_openmp_assignment_tpu.ops import step
    old = step._PACKED_COMMIT
    step._PACKED_COMMIT = False
    try:
        yield
    finally:
        step._PACKED_COMMIT = old


#: name -> (context manager seeding the bug, indexcheck finding kind
#: the --index prong must raise). Semantics-preserving by
#: construction: killed by the static inventory alone.
INDEX_MUTATIONS = {
    "split_packed_scatter": (split_packed_scatter, "index_budget"),
}


# name -> (wrapper, scope that exposes it, finding the checker must raise)
MUTATIONS = {
    "skip_em_bitvec_clear": (skip_em_bitvec_clear, "2n2a",
                             "unowned_with_sharers"),
    "upgrade_keeps_other_sharers": (upgrade_keeps_other_sharers, "2n1a",
                                    "em_not_single_owner"),
    "no_wait_clear_on_reply_rd": (no_wait_clear_on_reply_rd, "2n1a_r",
                                  "deadlock"),
    "drop_evict_modified": (drop_evict_modified, "2n2a",
                            "unhandled_pair"),
    "stale_owner_forward": (stale_owner_forward, "2n1a",
                            "em_not_single_owner"),
    "evict_shared_keeps_bit": (evict_shared_keeps_bit, "2n2a",
                               "unowned_with_sharers"),
}
