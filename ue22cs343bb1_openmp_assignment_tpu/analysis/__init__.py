"""Static-analysis subsystem: protocol model checking + JAX trace lint.

Two passes, both CI-gating (``cache-sim analyze``, ``scripts/check.sh``):

* :mod:`.model_check` — small-scope explicit-state model checker that
  drives the real vectorized handlers (ops/handlers, ops/frontend) as a
  transition oracle over every message interleaving of tiny
  configurations, verifying handler coverage, the engine-tier
  invariants everywhere, the coherence contract at every quiescent
  state, and deadlock/livelock freedom.
* :mod:`.lint_trace` — AST linter for the traced JAX modules (ops/,
  parallel/, models/): Python branching on traced values, host syncs
  and callbacks inside traced code, implicit integer dtypes, banned
  nondeterminism sources.

:mod:`.mutations` holds seeded handler bugs that the checker must
catch (the checker's own regression suite), :mod:`.runner` the CLI.
"""

from ue22cs343bb1_openmp_assignment_tpu.analysis.model_check import (  # noqa: F401
    ModelChecker, Scope, builtin_scopes, check_scope)
