"""Static-analysis subsystem: exploration, fuzzing, and IR lint.

Three prongs, all surfaced by ``cache-sim analyze`` and gated in CI
(``scripts/check.sh``):

* **Exploration** — :mod:`.model_check`, a small-scope explicit-state
  model checker that drives the real vectorized handlers (ops/handlers,
  ops/frontend) as a transition oracle over every message interleaving
  of tiny configurations, with node/address-permutation symmetry
  reduction and SCC-based livelock detection; verifies handler
  coverage, the engine-tier invariants everywhere, the coherence
  contract at every quiescent state, and deadlock/livelock freedom,
  rendering concrete (un-permuted) counterexample witnesses.
* **Fuzzing** — :mod:`.fuzz`, coverage-guided differential fuzzing of
  seeded random traces across the async/sync/native engines (coverage
  signal from the obs/ metrics schema), and :mod:`.shrink`, ddmin
  trace minimization emitting ready-to-run fixture repros plus
  Perfetto traces.
* **IR lint** — :mod:`.lint_trace`, the AST linter for the traced JAX
  modules (ops/, parallel/, models/), and :mod:`.lint_jaxpr`, the
  jaxpr-level audit of what XLA actually traces (64-bit widening,
  dynamic shapes, primitive budget, host callbacks) plus the
  three-engine recompilation guard.

:mod:`.mutations` holds seeded handler bugs that the checker *and*
fuzzer must catch (the gate's own regression suite), :mod:`.runner`
the CLI.
"""

from ue22cs343bb1_openmp_assignment_tpu.analysis.model_check import (  # noqa: F401
    ModelChecker, Scope, builtin_scopes, check_scope)
