"""Index-pressure auditor: static gather/scatter attribution per engine.

PERF.md calls the engines *index-bound* and, until this module, backed
that with one hand count ("~9 scatter/gather indices per retired
instruction") that no tool derived, tracked, or gated. This is the
seventh analyze prong (``cache-sim analyze --index``): it traces every
hot body with ``jax.make_jaxpr`` — the async cycle and its scan
runner, the wave chunk the daemon drives, the sync and deep rounds,
the fused Pallas round body, the sharded/RDMA parallel variants — and
walks the closed jaxprs for every *index equation* (``gather``,
``scatter*``, ``dynamic_slice``, ``dynamic_update_slice``), recording:

* **shape inventory** — operand / index-vector / update shapes, plus a
  trip weight (product of enclosing ``scan`` lengths) so an index op
  inside the deep round's drain folds counts once per executed
  iteration, not once per source line;
* **plane attribution** — each op's array operand is walked back
  through the producing equations to the state leaves that feed it
  (operand-0 chains through scatters/reshapes/converts, unions at
  genuine fan-in), and the root names map onto the semantic planes:
  cache / directory / mailbox / arbitration / telemetry / frontend /
  window;
* **indices per retired instruction** — a small deterministic probe
  run per engine (uniform workload, fixed seed) pins (steps, retired),
  and the hot body's weighted index count per step divides through:
  the machine-checked replacement for PERF.md's hand estimate;
* **mergeable-scatter candidates** — scatter pairs in the same scope
  whose index operands have identical *structural signatures* (the
  producing sub-DAG hashed down to input names and literals — var
  names never enter, so the signature is stable across traces) but
  pairwise-disjoint destination roots: exactly the shape PR 8
  consolidated by hand (five per-plane scatters sharing one index
  vector -> two packed row scatters, -55.56% median). The detector
  emits the next consolidation worklist instead of a reading session.

Per-target ceilings live in :data:`INDEX_BUDGETS` (index *sites*, not
weighted indices — stable across N and loop lengths) and are enforced
both here and in the always-on ``--jaxpr`` prong (analysis/lint_jaxpr),
so index-traffic regressions fail CI exactly like eqn-count and
bytes/instr regressions do. The seeded mutation
``INDEX_MUTATIONS.split_packed_scatter`` (analysis/mutations.py) flips
``ops.step._PACKED_COMMIT`` to the bit-identical de-consolidated
commit: every dynamic oracle stays green and only this prong — budget
breach plus merge candidates naming the re-split planes — can see it.

House pattern per analysis/kernelcheck.py: ``check()`` returns a
findings-aggregated dict under :data:`SCHEMA`, ``render_text`` the
human report, exit codes ride ``cache-sim analyze``'s 0/1/3 contract
(the probe hitting its cycle budget before quiescence is the prong's
"budget exhausted, nothing proven" case).
"""

from __future__ import annotations

import dataclasses
import hashlib
from typing import Dict, List, Optional, Sequence

import jax

from ue22cs343bb1_openmp_assignment_tpu.config import SystemConfig
from ue22cs343bb1_openmp_assignment_tpu.state import init_state

SCHEMA = "cache-sim/indexcheck/v1"

#: engines the auditor covers; ``async`` additionally carries the
#: parallel variants (sharded cycle on a 1-device mesh, RDMA router)
ENGINES = ("async", "sync", "deep", "wave", "fused")

#: canonical audit size: budgets are pinned at this node count (index
#: *sites* are N-independent in the vectorized design — audited by
#: test_indexcheck — so the pin holds at any N; the report still notes
#: when a non-default N was used)
DEFAULT_NODES = 8

#: per-target index-SITE ceilings, pinned to the measured shipped
#: counts (exact: any new gather/scatter/dynamic-slice site fails CI;
#: regenerate deliberately when index traffic changes on purpose).
#: Shared with analysis/lint_jaxpr's always-on --jaxpr prong for the
#: targets both walk.  Pinned at ``inv_mode="scatter"`` (the scale
#: configs the auditor traces); resolve through :func:`index_budget`
#: when the traced config may differ.
INDEX_BUDGETS = {
    "step.cycle": 27,
    "step.run_cycles[8]": 27,
    "step.run_wave_chunk[2x4]": 27,
    "sync_engine.round_step": 7,
    "sync_engine.round_step[deep]": 9,
    "pallas_round.round_body": 8,
    "rdma_comm.route": 9,
    "parallel.sharded_cycle": 27,
}

#: sites are N-independent (the vectorized design indexes whole
#: planes) but NOT inv_mode-independent: ``inv_mode="mailbox"`` (the
#: reference config lint_jaxpr audits at) replaces the async cycle's
#: scatter-based invalidation fan-out with mailbox enqueues, which
#: costs 2 fewer index sites per cycle trace.  Measured deltas, same
#: exact-pin discipline as the table above.
_MAILBOX_DELTA = {
    "step.cycle": -2,
    "step.run_cycles[8]": -2,
    "step.run_wave_chunk[2x4]": -2,
    "parallel.sharded_cycle": -2,
}


def index_budget(target: str, inv_mode: str = "scatter"):
    """Pinned index-site count for ``target`` under ``inv_mode``, or
    None when the target has no pin."""
    b = INDEX_BUDGETS.get(target)
    if b is not None and inv_mode == "mailbox":
        b += _MAILBOX_DELTA.get(target, 0)
    return b

_INDEX_PRIMS = ("gather", "dynamic_slice", "dynamic_update_slice")

#: operand-0 passthrough primitives for the provenance walk: the
#: output *is* (a view/rewrite of) the first operand
_CHAIN_PRIMS = ("convert_element_type", "bitcast_convert_type",
                "reshape", "transpose", "copy", "squeeze", "rev",
                "slice", "expand_dims", "gather", "dynamic_slice",
                "dynamic_update_slice")

_PLANE_EXACT = {
    "memory": "directory", "dir_state": "directory",
    "dir_bitvec": "directory", "dm": "directory", "dm0": "directory",
    "arb_rank": "arbitration", "order_rank": "arbitration",
    "seed": "arbitration", "issue_delay": "arbitration",
    "issue_period": "arbitration",
    "hor": "window", "horizon": "window",
}

_PLANE_PREFIX = (
    ("cache", "cache"), ("ca_t", "cache"), ("cv_t", "cache"),
    ("cs_t", "cache"),
    ("mb_", "mailbox"), ("msg", "mailbox"),
    ("metrics", "telemetry"), ("obs", "telemetry"),
    ("lat", "telemetry"),
    ("instr", "frontend"), ("cur_", "frontend"), ("idx", "frontend"),
    ("waiting", "frontend"),
    ("w_", "window"),
)


def _is_index(name: str) -> bool:
    return name in _INDEX_PRIMS or name.startswith("scatter")


def _subjaxprs(v):
    vs = v if isinstance(v, (list, tuple)) else [v]
    for s in vs:
        if hasattr(s, "jaxpr"):        # ClosedJaxpr
            yield s.jaxpr
        elif hasattr(s, "eqns"):       # raw Jaxpr
            yield s


def plane_of(root: str) -> str:
    head = root.lstrip(".").split(".", 1)[0].split("[", 1)[0]
    if head in _PLANE_EXACT:
        return _PLANE_EXACT[head]
    for prefix, plane in _PLANE_PREFIX:
        if head.startswith(prefix):
            return plane
    return "other"


def leaf_names(*trees) -> List[str]:
    """Flattened leaf names of pytree args, in ``make_jaxpr`` invar
    order (jax.tree_util paths; '.metrics.cycles' -> 'metrics.cycles')."""
    names: List[str] = []
    for t in trees:
        for path, _ in jax.tree_util.tree_flatten_with_path(t)[0]:
            nm = jax.tree_util.keystr(path).lstrip(".") or "arg"
            names.append(nm)
    return names


def _shape_str(aval) -> str:
    dt = getattr(aval, "dtype", None)
    shape = getattr(aval, "shape", None)
    if shape is None:
        return "?"
    short = {"int32": "i32", "uint32": "u32", "int8": "i8",
             "uint8": "u8", "bool": "b1", "float32": "f32",
             "int16": "i16", "uint16": "u16"}.get(str(dt), str(dt))
    return f"{short}[{','.join(str(d) for d in shape)}]"


def _index_vectors(eqn) -> int:
    """Number of index vectors one execution of this eqn consumes."""
    name = eqn.primitive.name
    if name == "gather" or name.startswith("scatter"):
        shape = getattr(eqn.invars[1].aval, "shape", ())
        n = 1
        for d in shape[:-1]:
            n *= int(d)
        return n if shape else 1
    return 1   # dynamic_slice / dynamic_update_slice: one start tuple


class _Scope:
    """One (sub)jaxpr under the walk: producer map, invar names, memo
    tables for provenance roots and structural signatures."""

    def __init__(self, jaxpr, names: Sequence[str], label: str):
        self.jaxpr = jaxpr
        self.label = label
        self.names: Dict[object, str] = {}
        for v, nm in zip(jaxpr.invars, names):
            self.names[v] = nm
        for v in jaxpr.constvars:
            self.names[v] = "const"
        self.prod: Dict[object, tuple] = {}
        for eqn in jaxpr.eqns:
            for pos, ov in enumerate(eqn.outvars):
                self.prod[ov] = (eqn, pos)
        self._roots: Dict[object, frozenset] = {}
        self._sigs: Dict[object, tuple] = {}
        self._anchors: Dict[object, int] = {}

    # -- provenance --------------------------------------------------------
    def roots(self, v, depth: int = 0) -> frozenset:
        from jax.core import Literal
        if isinstance(v, Literal):
            return frozenset()
        if v in self.names:
            return frozenset([self.names[v]])
        got = self._roots.get(v)
        if got is not None:
            return got
        self._roots[v] = frozenset(["..."])   # cycle/depth guard
        out: frozenset
        if depth > 64 or v not in self.prod:
            out = frozenset(["?"])
        else:
            eqn, _ = self.prod[v]
            prim = eqn.primitive.name
            if prim in _CHAIN_PRIMS or prim.startswith("scatter"):
                out = self.roots(eqn.invars[0], depth + 1)
            else:
                ins = eqn.invars
                if prim == "select_n" and len(ins) > 1:
                    ins = ins[1:]       # predicate origins are noise
                acc = frozenset()
                for iv in ins:
                    acc = acc | self.roots(iv, depth + 1)
                out = acc
        self._roots[v] = out
        return out

    def root_label(self, v, limit: int = 4) -> str:
        rs = sorted(self.roots(v))
        if len(rs) > limit:
            rs = rs[:limit] + ["..."]
        return "+".join(rs) if rs else "lit"

    def planes(self, v) -> List[str]:
        ps = sorted({plane_of(r) for r in self.roots(v)
                     if r not in ("...", "?", "const", "lit")})
        if len(ps) > 3:
            return ["mixed"]       # genuine fan-in of most of the state
        return ps or ["other"]

    # -- destination anchoring --------------------------------------------
    def dest_token(self, v) -> str:
        """Deterministic identity of a scatter's destination array:
        follow operand-0 chains to the terminal var (a state leaf, a
        constvar, or a freshly built buffer) and label it by root name
        plus first-appearance ordinal — chained scatters into one
        array share a token; distinct buffers never do. Var names/ids
        never enter the label."""
        from jax.core import Literal
        seen = 0
        while not isinstance(v, Literal) and v not in self.names \
                and v in self.prod and seen < 256:
            eqn, _ = self.prod[v]
            prim = eqn.primitive.name
            if not (prim in _CHAIN_PRIMS or prim.startswith("scatter")):
                break
            v = eqn.invars[0]
            seen += 1
        if isinstance(v, Literal):
            base = "lit"
        elif v in self.names:
            base = self.names[v]
        else:
            base = self.root_label(v)
        key = v if not isinstance(v, Literal) else repr(v.val)
        ordinal = self._anchors.get(key)
        if ordinal is None:
            ordinal = len(self._anchors)
            self._anchors[key] = ordinal
        return f"{base}#{ordinal}"

    # -- structural index signature ---------------------------------------
    def sig_hash(self, v) -> str:
        """Merkle hash of the producing sub-DAG: per-node digest over
        (primitive, out position, non-jaxpr params, child digests),
        bottoming out at input NAMES and literal values — jaxpr var
        names never enter, so the signature is identical across
        retraces; memoized per var, so shared subexpressions hash once
        (linear in DAG size)."""
        from jax.core import Literal

        def h(parts) -> str:
            return hashlib.sha256(
                "\x1f".join(parts).encode()).hexdigest()[:12]

        def rec(x, depth: int) -> str:
            if isinstance(x, Literal):
                return h(["lit", repr(x.val)])
            if x in self.names:
                return h(["in", self.names[x]])
            got = self._sigs.get(x)
            if got is not None:
                return got
            self._sigs[x] = h(["cyc"])
            if depth > 512 or x not in self.prod:
                out = h(["free", _shape_str(x.aval)])
            else:
                eqn, pos = self.prod[x]
                parts = [eqn.primitive.name, str(pos)]
                for k in sorted(eqn.params):
                    pv = eqn.params[k]
                    parts.append(k)
                    parts.append("<jaxpr>" if list(_subjaxprs(pv))
                                 else repr(pv))
                parts.extend(rec(iv, depth + 1) for iv in eqn.invars)
                out = h(parts)
            self._sigs[x] = out
            return out

        return rec(v, 0)


def inventory(closed, invar_names: Sequence[str],
              target: str) -> List[dict]:
    """Walk one closed jaxpr; returns the ordered list of index-op
    records (no jaxpr var names anywhere — byte-stable across traces)."""
    ops: List[dict] = []
    scopes = 0

    def walk(jaxpr, names, label, weight):
        nonlocal scopes
        sc = _Scope(jaxpr, names, label)
        for eqn in jaxpr.eqns:
            prim = eqn.primitive.name
            if _is_index(prim):
                rec = {
                    "primitive": prim,
                    "scope": label,
                    "plane": "+".join(sc.planes(eqn.invars[0])),
                    "operand": _shape_str(eqn.invars[0].aval),
                    "trip_weight": weight,
                    "indices": _index_vectors(eqn) * weight,
                }
                if prim == "gather" or prim.startswith("scatter"):
                    rec["index_shape"] = _shape_str(eqn.invars[1].aval)
                    rec["index_sig"] = sc.sig_hash(eqn.invars[1])
                if prim.startswith("scatter"):
                    rec["update"] = _shape_str(eqn.invars[2].aval)
                    rec["roots"] = sorted(sc.roots(eqn.invars[0]))[:6]
                    rec["dest"] = sc.dest_token(eqn.invars[0])
                elif prim == "dynamic_update_slice":
                    rec["update"] = _shape_str(eqn.invars[1].aval)
                ops.append(rec)
            for pv in eqn.params.values():
                subs = list(_subjaxprs(pv))
                if not subs:
                    continue
                w = weight
                if prim == "scan":
                    w = weight * int(eqn.params.get("length", 1))
                for sub in subs:
                    scopes += 1
                    k = len(sub.invars)
                    tail = eqn.invars[-k:] if k else []
                    sub_names = [sc.root_label(iv) for iv in tail]
                    sub_names += ["arg"] * (k - len(sub_names))
                    walk(sub, sub_names, f"{label}/{prim}{scopes}", w)

    walk(closed.jaxpr, list(invar_names), target, 1)
    return ops


def count_index_sites(jaxpr) -> int:
    """Flattened count of index equations (unweighted sites) — the
    quantity :data:`INDEX_BUDGETS` bounds; used by lint_jaxpr too."""
    n = 0
    stack = [jaxpr]
    while stack:
        j = stack.pop()
        for eqn in j.eqns:
            if _is_index(eqn.primitive.name):
                n += 1
            for v in eqn.params.values():
                stack.extend(_subjaxprs(v))
    return n


def merge_candidates(ops: List[dict]) -> List[dict]:
    """Scatter pairs sharing one structural index signature in one
    scope, writing pairwise-disjoint destination roots: pack the
    planes and commit one row scatter (the PR-8 consolidation shape).
    Chained scatters into the same array share roots and are excluded
    (a chain is already one logical write stream, not a merge)."""
    groups: Dict[tuple, List[dict]] = {}
    for rec in ops:
        if not rec["primitive"].startswith("scatter"):
            continue
        key = (rec["scope"], rec.get("index_sig"), rec.get("update"))
        groups.setdefault(key, []).append(rec)
    out = []
    for (scope, sig, update), members in sorted(groups.items()):
        if sig is None or len(members) < 2:
            continue
        kept, seen_dests = [], set()
        for m in members:
            dest = m.get("dest", "?")
            if dest in seen_dests:
                continue              # chained write into the same dest
            seen_dests.add(dest)
            kept.append(m)
        if len(kept) < 2:
            continue
        planes = sorted({m["plane"] for m in kept})
        dests = sorted(m.get("dest", "?") for m in kept)
        out.append({
            "kind": "merge_candidate", "scope": scope,
            "index_sig": sig, "count": len(kept),
            "planes": planes, "dests": dests, "update": update,
            "detail": (f"{len(kept)} scatters in {scope} share index "
                       f"sig {sig} with disjoint dests "
                       f"[{', '.join(dests)}] — pack the planes and "
                       f"commit one row scatter (PR-8 shape)"),
        })
    return out


# ---------------------------------------------------------------------------
# engine targets + probes
# ---------------------------------------------------------------------------

def _unjitted(fn):
    return getattr(fn, "__wrapped__", fn)


def engine_config(engine: str, nodes: int) -> SystemConfig:
    if engine in ("async", "wave"):
        return SystemConfig.scale(num_nodes=nodes)
    if engine == "sync":
        return SystemConfig.scale(num_nodes=nodes, drain_depth=4,
                                  txn_width=3)
    # deep / fused: the lint_jaxpr probe family (valid at small N)
    return dataclasses.replace(
        SystemConfig.scale(num_nodes=nodes, drain_depth=2,
                           txn_width=2),
        deep_window=True, deep_slots=4, deep_ownerval_slots=2)


def _trivial_traces(cfg):
    return [[(0, 1, 0)]] * cfg.num_nodes


def trace_targets(engine: str, nodes: int) -> Dict[str, tuple]:
    """name -> (closed_jaxpr, invar_names) for one engine. Jitted
    entry points are traced through their unjitted bodies so a seeded
    mutation (fresh module-flag state) is always visible — jit trace
    caches would otherwise pin whichever variant traced first."""
    from ue22cs343bb1_openmp_assignment_tpu import state as state_mod
    from ue22cs343bb1_openmp_assignment_tpu.ops import step

    cfg = engine_config(engine, nodes)
    out: Dict[str, tuple] = {}

    if engine == "async":
        st = init_state(cfg, _trivial_traces(cfg))
        names = leaf_names(st)
        out["step.cycle"] = (
            jax.make_jaxpr(lambda s: step.cycle(cfg, s))(st), names)
        run_cycles = _unjitted(step.run_cycles)
        out["step.run_cycles[8]"] = (
            jax.make_jaxpr(lambda s: run_cycles(cfg, s, 8))(st), names)
        out.update(_parallel_targets(cfg, st, names))
    elif engine == "wave":
        st = init_state(cfg, _trivial_traces(cfg))
        b = state_mod.stack_states([st, init_state(cfg)])
        out["step.run_wave_chunk[2x4]"] = (
            jax.make_jaxpr(
                lambda s: step.batched_wave_chunk(cfg, s, 4, 64))(b),
            leaf_names(b))
    elif engine in ("sync", "deep"):
        from ue22cs343bb1_openmp_assignment_tpu.ops import (
            sync_engine as se)
        sst = se.from_sim_state(cfg, init_state(cfg,
                                                _trivial_traces(cfg)))
        name = ("sync_engine.round_step" if engine == "sync"
                else "sync_engine.round_step[deep]")
        out[name] = (
            jax.make_jaxpr(lambda s: se.round_step(cfg, s))(sst),
            leaf_names(sst))
    elif engine == "fused":
        from ue22cs343bb1_openmp_assignment_tpu.analysis import (
            kernelcheck)
        out["pallas_round.round_body"] = (
            kernelcheck.trace_round_body(cfg),
            ["params", "dm0", "ca_t", "cv_t", "cs_t", "w_oa", "w_val",
             "w_live", "hor"])
    else:
        raise ValueError(f"unknown engine {engine!r}")
    return out


def _parallel_targets(cfg, st, names):
    """The parallel variants ride the async engine: the GSPMD-sharded
    cycle on a 1-device mesh (fresh jit wrapper per call — no shared
    trace cache) and the RDMA lane router in interpret mode."""
    import jax.numpy as jnp

    from ue22cs343bb1_openmp_assignment_tpu.parallel import (
        mesh as pmesh, rdma_comm, sharded_step)

    mesh = pmesh.make_mesh(jax.devices()[:1])
    f = sharded_step.make_sharded_cycle(cfg, mesh, st)
    out = {"parallel.sharded_cycle": (jax.make_jaxpr(f)(st), names)}

    router = rdma_comm.make_rdma_router(cfg, mesh, interpret=True)
    N, S, Fw = cfg.num_nodes, cfg.out_slots, 6 + cfg.msg_bitvec_words
    ctype = jnp.ones((N, S), jnp.int32)
    recv = jnp.tile(jnp.arange(N, dtype=jnp.int32)[:, None], (1, S))
    prio = jnp.arange(N * S, dtype=jnp.int32).reshape(N, S)
    fields = jnp.zeros((N, S, Fw), jnp.int32)
    out["rdma_comm.route"] = (
        jax.make_jaxpr(router)(ctype, recv, prio, fields),
        ["msg_type", "msg_recv", "msg_prio", "msg_fields"])
    return out


#: the hot body whose per-step index count defines each engine's
#: indices/instr headline
HOT_BODY = {
    "async": "step.cycle",
    "wave": "step.run_wave_chunk[2x4]",
    "sync": "sync_engine.round_step",
    "deep": "sync_engine.round_step[deep]",
    "fused": "pallas_round.round_body",
}


def _probe(engine: str, nodes: int, budget: int) -> dict:
    """One deterministic small run (uniform workload, seed 0): pins
    (steps, retired, quiesced) for the indices/instr denominator."""
    import jax.numpy as jnp

    from ue22cs343bb1_openmp_assignment_tpu import state as state_mod
    from ue22cs343bb1_openmp_assignment_tpu.models.system import (
        CoherenceSystem)
    from ue22cs343bb1_openmp_assignment_tpu.ops import step

    cfg = engine_config(engine, nodes)
    sys_ = CoherenceSystem.from_workload(cfg, "uniform", trace_len=16,
                                         seed=0)
    if engine == "async":
        final = step.run_to_quiescence(cfg, sys_.state, budget)
        return {"steps": int(final.cycle),
                "retired": int(final.metrics.instrs_retired),
                "quiesced": bool(final.quiescent())}
    if engine == "wave":
        other = CoherenceSystem.from_workload(cfg, "uniform",
                                              trace_len=16, seed=1)
        b = state_mod.stack_states([sys_.state, other.state])
        chunks, done = 0, False
        while not done and chunks * 4 < budget:
            b, quiet, done_v = step.run_wave_chunk(cfg, b, 4, budget)
            done = bool(jnp.all(done_v))
            chunks += 1
        return {"steps": chunks,
                "retired": int(jnp.sum(b.metrics.instrs_retired)),
                "quiesced": done}
    # sync / deep / fused share the round engine's retire rate (the
    # fused body IS one deep round; its probe run uses the same core)
    from ue22cs343bb1_openmp_assignment_tpu.ops import sync_engine as se
    sst = se.from_sim_state(cfg, sys_.state)
    out = se.run_sync_to_quiescence(cfg, sst, chunk=8,
                                    max_rounds=max(budget, 8))
    rounds = int(out.round)
    return {"steps": rounds,
            "retired": int(out.metrics.instrs_retired),
            "quiesced": rounds < max(budget, 8)}


# ---------------------------------------------------------------------------
# the prong
# ---------------------------------------------------------------------------

def check(engines: Optional[Sequence[str]] = None,
          nodes: int = DEFAULT_NODES, probe: bool = True,
          probe_budget: int = 4096) -> dict:
    """Run the audit; returns the findings-aggregated report dict."""
    engines = list(ENGINES) if engines is None else list(engines)
    findings: List[dict] = []
    exhausted = False
    eng_out: Dict[str, dict] = {}
    cross: Dict[str, Dict[str, int]] = {}

    for engine in engines:
        targets = {}
        candidates: List[dict] = []
        for name, (closed, invar_names) in \
                trace_targets(engine, nodes).items():
            ops = inventory(closed, invar_names, name)
            sites = count_index_sites(closed.jaxpr)
            by_plane: Dict[str, dict] = {}
            for rec in ops:
                row = by_plane.setdefault(rec["plane"],
                                          {"ops": 0, "indices": 0})
                row["ops"] += 1
                row["indices"] += rec["indices"]
            cands = merge_candidates(ops)
            candidates.extend(cands)
            targets[name] = {
                "index_sites": sites,
                "indices_per_call": sum(r["indices"] for r in ops),
                "by_plane": by_plane,
                "ops": ops,
            }
            budget = INDEX_BUDGETS.get(name)
            if budget is not None and nodes == DEFAULT_NODES \
                    and sites > budget:
                findings.append({
                    "pass": "budget", "kind": "index_budget",
                    "target": name,
                    "detail": f"{sites} index sites > budget {budget} "
                              f"(gather/scatter/dynamic-slice eqns; "
                              f"INDEX_BUDGETS pins the shipped count "
                              f"exactly)"})
        hot = HOT_BODY[engine]
        per_step = targets[hot]["indices_per_call"] if hot in targets \
            else 0
        rec = {"config": {"num_nodes": nodes}, "targets": targets,
               "merge_candidates": candidates,
               "hot_body": hot, "indices_per_step": per_step,
               "probe": None, "indices_per_instr": None}
        if probe:
            pr = _probe(engine, nodes, probe_budget)
            rec["probe"] = pr
            if not pr["quiesced"]:
                exhausted = True
            elif pr["retired"]:
                rec["indices_per_instr"] = round(
                    per_step * pr["steps"] / pr["retired"], 3)
        for plane, row in targets.get(hot, {}).get("by_plane",
                                                   {}).items():
            cross.setdefault(plane, {})[engine] = row["indices"]
        eng_out[engine] = rec

    return {"schema": SCHEMA, "nodes": nodes,
            "default_nodes": DEFAULT_NODES,
            "budgets": {k: INDEX_BUDGETS[k]
                        for k in sorted(INDEX_BUDGETS)},
            "budgets_enforced": nodes == DEFAULT_NODES,
            "engines": eng_out, "cross_engine": cross,
            "findings": findings, "budget_exhausted": exhausted,
            "ok": not findings}


def render_text(rep: dict) -> List[str]:
    verdict = "ok" if rep["ok"] else "FAIL"
    if rep["ok"] and rep.get("budget_exhausted"):
        verdict = "BUDGET EXHAUSTED (probe never quiesced — not a pass)"
    lines = [f"== index audit: {verdict} [N={rep['nodes']}, "
             f"engines: {', '.join(rep['engines'])}]"]
    for engine, er in rep["engines"].items():
        ipi = er["indices_per_instr"]
        ipi_s = "n/a" if ipi is None else f"{ipi:.3f}"
        pr = er.get("probe") or {}
        lines.append(
            f"   {engine}: {er['indices_per_step']} indices/step "
            f"({er['hot_body']}), {ipi_s} indices/instr"
            + (f" [{pr['steps']} steps, {pr['retired']} retired]"
               if pr else ""))
        for name, t in er["targets"].items():
            planes = ", ".join(
                f"{p}={v['indices']}" for p, v in
                sorted(t["by_plane"].items()))
            lines.append(f"      {name}: {t['index_sites']} sites, "
                         f"{t['indices_per_call']} indices/call "
                         f"[{planes}]")
        for c in er["merge_candidates"]:
            lines.append(f"   ~ merge candidate: {c['detail']}")
        if not er["merge_candidates"]:
            lines.append(f"   {engine}: no mergeable-scatter pairs "
                         "under the shared-index/disjoint-dest "
                         "pattern")
    for f in rep["findings"]:
        lines.append(f"  ! {f['pass']}/{f['kind']}: "
                     f"[{f.get('target', '?')}] {f['detail']}")
    return lines


def index_row(engine: str = "async",
              nodes: int = DEFAULT_NODES) -> dict:
    """The deterministic perf-report block (obs/cli embeds this as
    doc['index']; obs/roofline renders it): the hot body's static
    per-step inventory plus plane split — no probe run, the perf
    report already pins (steps, retired) from its own measured run."""
    rep = check(engines=[engine], nodes=nodes, probe=False)
    er = rep["engines"][engine]
    hot = er["hot_body"]
    return {"engine": engine, "target": hot, "nodes": nodes,
            "indices_per_step": er["indices_per_step"],
            "index_sites": er["targets"][hot]["index_sites"],
            "by_plane": {p: v["indices"] for p, v in
                         er["targets"][hot]["by_plane"].items()},
            "merge_candidates": len(er["merge_candidates"])}
