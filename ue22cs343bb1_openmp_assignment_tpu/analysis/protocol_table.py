"""Declarative protocol transition table (Murphi-style rule rows).

``ops/handlers.py`` is the reference's ``switch(msg.type)``
(``assignment.c:190-618``) transcribed into vectorized masked updates —
correct, but *code*: every protocol property is only checkable by
running it. This module lifts the same transition relation into *data*:
a table of :class:`Row` entries, each a guarded command

    ``(message type, guard over receiver-local predicates) -> effects``

in the rule-table style of Dill's Murphi (PAPERS.md). Three consumers:

* :mod:`.verify_table` — pure table-level static passes (totality,
  determinism, ownership conservation, stability, anchor cross-check)
  that need no simulation at all;
* :func:`table_message_phase` — compiles a table back into a JAX
  ``message_phase`` with the exact contract of
  :func:`..ops.handlers.message_phase`, so the model checker, fuzzer
  and engines run *table-driven* protocols through the unmodified
  engine (ROADMAP item 4: MESI/MOESI/MESIF as configs);
* :mod:`.conformance` — the gate that proves :func:`mesi_table` is
  bit-equivalent to the live handlers over whole small-scope state
  spaces, so the table is a verified artifact, not an assertion.

The MESI table encodes the reference *including* its five documented
quirks (handlers.py docstring, SURVEY §2): every row carries the
``assignment.c`` anchor it transcribes plus the quirk ids it embodies,
cross-checked against :data:`..ops.handlers.TRANSITION_ANCHORS`.

**Variant tables.** :func:`moesi_table` demotes a ``WRITEBACK_INT``-ed
owner to OWNED instead of SHARED; :func:`mesif_table` fills the
requester of a dirty line as FORWARD instead of SHARED. Both keep the
reference's write-through demotion (``FLUSH`` updates home memory,
``assignment.c:307``), so OWNED/FORWARD lines are clean and evict via
the ordinary ``EVICT_SHARED`` path — the variants exercise the extra
states through every table pass, the protocol-aware range invariant
(``ops/invariants.py``) and the model checker, while dirty-sharing
(memory left stale under O) is out of scope for the reference engine.

Guard atoms are *receiver-local* — exactly the predicates the
vectorized handlers branch on (home/second role, tag match, directory
state, post-drop sharer count) — so compiling a row never needs
information a node doesn't have.
"""

from __future__ import annotations

import dataclasses
import functools

import jax.numpy as jnp

from ue22cs343bb1_openmp_assignment_tpu import codec
from ue22cs343bb1_openmp_assignment_tpu.config import SystemConfig
from ue22cs343bb1_openmp_assignment_tpu.state import (bit_single, ctz,
                                                      popcount)
from ue22cs343bb1_openmp_assignment_tpu.types import CacheState, DirState, Msg

_M, _E, _S, _I = (int(CacheState.MODIFIED), int(CacheState.EXCLUSIVE),
                  int(CacheState.SHARED), int(CacheState.INVALID))
_O, _F = int(CacheState.OWNED), int(CacheState.FORWARD)
_EM, _DS, _U = int(DirState.EM), int(DirState.S), int(DirState.U)


# ---------------------------------------------------------------------------
# guards
# ---------------------------------------------------------------------------

@dataclasses.dataclass(frozen=True)
class Guard:
    """Conjunction of receiver-local predicates; ``None`` = don't-care.

    Set-valued atoms (``cache_state``/``dir_state``/``msg_dirstate``/
    ``others``) match membership; bool atoms match equality.
    ``others`` classifies the post-drop sharer count
    ``popcount(dir_bv & ~sender_bit)`` into ``"0"``/``"1"``/``"2+"``
    (the EVICT_SHARED home bookkeeping, ``assignment.c:559-589``);
    ``new_owner_self`` asks whether ``ctz`` of that set names the
    receiver itself (the self-promotion path, ``assignment.c:586``).
    """

    at_home: bool | None = None
    at_second: bool | None = None
    tag_match: bool | None = None
    home_is_second: bool | None = None
    new_owner_self: bool | None = None
    cache_state: tuple | None = None
    dir_state: tuple | None = None
    msg_dirstate: tuple | None = None
    others: tuple | None = None

    def atoms(self) -> tuple:
        """Names of the atoms this guard constrains."""
        return tuple(f.name for f in dataclasses.fields(self)
                     if getattr(self, f.name) is not None)


# enumeration domain per guard atom (verify_table's product spaces);
# cache_state's domain comes from ProtocolTable.cache_states
_BOOLS = (False, True)
ATOM_DOMAINS = {
    "at_home": _BOOLS,
    "at_second": _BOOLS,
    "tag_match": _BOOLS,
    "home_is_second": _BOOLS,
    "new_owner_self": _BOOLS,
    "dir_state": (_EM, _DS, _U),
    "msg_dirstate": (_EM, _DS, _U),
    "others": ("0", "1", "2+"),
}


# ---------------------------------------------------------------------------
# effects (action atoms)
# ---------------------------------------------------------------------------

@dataclasses.dataclass(frozen=True)
class CacheWrite:
    """Write the line at ``cache_index(addr)`` — blind by index, no tag
    check, exactly like the C (quirk 5). ``fill=True`` additionally
    installs the message address and a value (``value`` expr)."""

    state: int
    fill: bool = False
    value: str | None = None    # fill value expr: "msg.value" | "cur_val"


@dataclasses.dataclass(frozen=True)
class Replace:
    """Run handleCacheReplacement on the displaced line before a fill
    (``assignment.c:767-804``): emits EVICT_SHARED/EVICT_MODIFIED to the
    victim's home. ``checked=True`` fires only on a tag mismatch;
    ``False`` is REPLY_WR's unconditional call (``assignment.c:467``)."""

    checked: bool = True


@dataclasses.dataclass(frozen=True)
class DirWrite:
    """Write the directory entry for ``block_index(addr)`` in the
    receiver's own directory. ``state`` in {"EM","S","U"} or None
    (keep); ``bv`` a bitvector expr or None (keep)."""

    state: str | None = None
    bv: str | None = None


@dataclasses.dataclass(frozen=True)
class MemWrite:
    """home memory[block] := msg.value (assignment.c:307,520,602)."""


@dataclasses.dataclass(frozen=True)
class ClearWait:
    """Clear waitingForReply — unconditional where quirk 2 says so."""


@dataclasses.dataclass(frozen=True)
class Send:
    """Emit one candidate message. ``slot`` is the engine out-slot
    ("pri" = first sendMessage, "sec" = the secondReceiver copy);
    ``bitvec="others"`` attaches the sharers-minus-requester set
    (REPLY_ID payload in mailbox INV mode; the scatter-mode
    invalidation in scatter INV mode)."""

    slot: str
    type: int
    to: str
    value: str = "0"
    second: str = "0"
    dirstate: str = "EM"
    bitvec: str | None = None


@dataclasses.dataclass(frozen=True)
class InvFanout:
    """Source one INV per set bit of the message's carried sharer set
    (REPLY_ID at the requester, ``assignment.c:364-373``; mailbox INV
    mode only — scatter mode invalidates at the grant, handlers.py)."""


@dataclasses.dataclass(frozen=True)
class CountInval:
    """Count this firing in metrics.invalidations."""


@dataclasses.dataclass(frozen=True)
class Row:
    """One guarded command. ``anchor`` names the assignment.c lines the
    row transcribes (validated against handlers.TRANSITION_ANCHORS);
    ``quirks`` the reference-quirk ids it embodies (handlers.QUIRKS);
    ``assumes`` a precondition the row relies on for invariant
    preservation — not part of the match, verified dynamically by the
    conformance gate on every explored transition."""

    name: str
    msg: int
    guard: Guard
    effects: tuple
    anchor: str
    quirks: tuple = ()
    assumes: Guard = Guard()


@dataclasses.dataclass(frozen=True)
class ProtocolTable:
    """A complete protocol: rows + per-message guard domains.

    ``domains[msg]`` names the atoms that message's rows may key on —
    the product of their :data:`ATOM_DOMAINS` is the totality/
    determinism enumeration space (verify_table).
    """

    name: str
    protocol: str               # SystemConfig.protocol value
    rows: tuple
    domains: dict

    @property
    def cache_states(self) -> tuple:
        base = (_M, _E, _S, _I)
        if self.protocol == "moesi":
            return base + (_O,)
        if self.protocol == "mesif":
            return base + (_F,)
        return base

    def rows_for(self, msg: int) -> tuple:
        return tuple(r for r in self.rows if r.msg == int(msg))


# ---------------------------------------------------------------------------
# the MESI table: ops/handlers.py row by row
# ---------------------------------------------------------------------------

_DOMAINS = {
    int(Msg.READ_REQUEST): ("dir_state",),
    int(Msg.WRITE_REQUEST): ("dir_state",),
    int(Msg.REPLY_RD): ("msg_dirstate",),
    int(Msg.REPLY_WR): (),
    int(Msg.REPLY_ID): (),
    int(Msg.UPGRADE): (),
    int(Msg.INV): ("tag_match",),
    int(Msg.WRITEBACK_INT): ("home_is_second",),
    int(Msg.WRITEBACK_INV): (),
    int(Msg.FLUSH): ("at_home", "at_second"),
    int(Msg.FLUSH_INVACK): ("at_home", "at_second"),
    int(Msg.EVICT_SHARED): ("at_home", "others", "new_owner_self"),
    int(Msg.EVICT_MODIFIED): (),
}


def _mesi_rows(demote_state: int = _S, dirty_fill_state: int = _S) -> tuple:
    """The 29 rows of the reference protocol. ``demote_state`` is what a
    WRITEBACK_INT-ed owner drops to (SHARED; OWNED for MOESI);
    ``dirty_fill_state`` what the FLUSH fill installs at the requester
    of a dirty line (SHARED; FORWARD for MESIF)."""
    RR, WR = int(Msg.READ_REQUEST), int(Msg.WRITE_REQUEST)
    RRD, RWR, RID = int(Msg.REPLY_RD), int(Msg.REPLY_WR), int(Msg.REPLY_ID)
    INV, UPG = int(Msg.INV), int(Msg.UPGRADE)
    WBINV, WBINT = int(Msg.WRITEBACK_INV), int(Msg.WRITEBACK_INT)
    FL, FIA = int(Msg.FLUSH), int(Msg.FLUSH_INVACK)
    ES, EMSG = int(Msg.EVICT_SHARED), int(Msg.EVICT_MODIFIED)
    return (
        # -- READ_REQUEST (home's own directory, read blindly) ------------
        Row("rr_dirty_forward", RR, Guard(dir_state=(_EM,)),
            (Send("pri", WBINT, to="owner", value="0", second="sender"),),
            anchor="assignment.c:199-210", quirks=(4,)),
        Row("rr_shared_grant", RR, Guard(dir_state=(_DS,)),
            (Send("pri", RRD, to="sender", value="mem", dirstate="S"),
             DirWrite(bv="bv|sender")),
            anchor="assignment.c:211-236"),
        Row("rr_unowned_grant", RR, Guard(dir_state=(_U,)),
            (Send("pri", RRD, to="sender", value="mem", dirstate="EM"),
             DirWrite(state="EM", bv="sender")),
            anchor="assignment.c:211-236"),
        # -- REPLY_RD: fill keyed on the carried dirstate -----------------
        Row("reply_rd_fill_shared", RRD, Guard(msg_dirstate=(_DS,)),
            (Replace(checked=True),
             CacheWrite(_S, fill=True, value="msg.value"), ClearWait()),
            anchor="assignment.c:240-258"),
        Row("reply_rd_fill_excl", RRD, Guard(msg_dirstate=(_EM, _U)),
            (Replace(checked=True),
             CacheWrite(_E, fill=True, value="msg.value"), ClearWait()),
            anchor="assignment.c:240-258"),
        # -- WRITEBACK_INT: blind demote + flush; home==requester dedups --
        Row("wbint_demote_dedup", WBINT, Guard(home_is_second=True),
            (CacheWrite(demote_state),
             Send("pri", FL, to="home", value="cache.val",
                  second="msg.second")),
            anchor="assignment.c:262-281", quirks=(3, 5)),
        Row("wbint_demote", WBINT, Guard(home_is_second=False),
            (CacheWrite(demote_state),
             Send("pri", FL, to="home", value="cache.val",
                  second="msg.second"),
             Send("sec", FL, to="second", value="cache.val",
                  second="msg.second")),
            anchor="assignment.c:262-286", quirks=(5,)),
        # -- FLUSH: keyed on (home, second) roles; quirk-2 bystander ------
        Row("flush_home_only", FL, Guard(at_home=True, at_second=False),
            (DirWrite(state="S", bv="bv|second"), MemWrite(), ClearWait()),
            anchor="assignment.c:301-322", quirks=(2,)),
        Row("flush_fill", FL, Guard(at_home=False, at_second=True),
            (Replace(checked=True),
             CacheWrite(dirty_fill_state, fill=True, value="msg.value"),
             ClearWait()),
            anchor="assignment.c:310-322"),
        Row("flush_home_and_second", FL, Guard(at_home=True, at_second=True),
            (DirWrite(state="S", bv="bv|second"), MemWrite(),
             Replace(checked=True),
             CacheWrite(dirty_fill_state, fill=True, value="msg.value"),
             ClearWait()),
            anchor="assignment.c:301-322"),
        Row("flush_bystander", FL, Guard(at_home=False, at_second=False),
            (ClearWait(),),
            anchor="assignment.c:322", quirks=(2,)),
        # -- UPGRADE: unconditional grant (no dir-state key in the C) -----
        Row("upgrade_grant", UPG, Guard(),
            (Send("pri", RID, to="sender", bitvec="others"),
             DirWrite(state="EM", bv="sender")),
            anchor="assignment.c:326-348"),
        # -- REPLY_ID: fill MODIFIED from the latch + INV fan-out ---------
        Row("reply_id_fill", RID, Guard(),
            (Replace(checked=True),
             CacheWrite(_M, fill=True, value="cur_val"),
             InvFanout(), ClearWait()),
            anchor="assignment.c:352-384", quirks=(1,)),
        # -- INV: tag-checked kill; mismatch is the sanctioned no-op ------
        Row("inv_kill", INV, Guard(tag_match=True),
            (CacheWrite(_I), CountInval()),
            anchor="assignment.c:389-399"),
        Row("inv_miss_noop", INV, Guard(tag_match=False), (),
            anchor="assignment.c:389-399"),
        # -- WRITE_REQUEST: immediate dir update on all three (quirk 4) ---
        Row("wreq_dirty", WR, Guard(dir_state=(_EM,)),
            (Send("pri", WBINV, to="owner", value="msg.value",
                  second="sender"),
             DirWrite(state="EM", bv="sender")),
            anchor="assignment.c:440-457", quirks=(4,)),
        Row("wreq_shared", WR, Guard(dir_state=(_DS,)),
            (Send("pri", RID, to="sender", bitvec="others"),
             DirWrite(state="EM", bv="sender")),
            anchor="assignment.c:423-437"),
        Row("wreq_unowned", WR, Guard(dir_state=(_U,)),
            (Send("pri", RWR, to="sender"),
             DirWrite(state="EM", bv="sender")),
            anchor="assignment.c:407-421"),
        # -- REPLY_WR: unconditional replacement, fill from the latch -----
        Row("reply_wr_fill", RWR, Guard(),
            (Replace(checked=False),
             CacheWrite(_M, fill=True, value="cur_val"), ClearWait()),
            anchor="assignment.c:461-470", quirks=(1,)),
        # -- WRITEBACK_INV: blind kill + DOUBLE send, never deduped -------
        Row("wbinv_flush", WBINV, Guard(),
            (CacheWrite(_I),
             Send("pri", FIA, to="home", value="cache.val",
                  second="msg.second"),
             Send("sec", FIA, to="second", value="cache.val",
                  second="msg.second")),
            anchor="assignment.c:474-498", quirks=(3, 5)),
        # -- FLUSH_INVACK: home restores only the bitvector (never the
        #    state — the exclusive_line_dir_not_em quirk source); assumes
        #    the entry is still EM/S: after an EVICT_MODIFIED race has
        #    set it U, this row would resurrect a sharer bit under U
        #    (latent reference quirk; conformance validates the assume
        #    on every explored scope) ---------------------------------
        Row("fia_home_only", FIA, Guard(at_home=True, at_second=False),
            (DirWrite(bv="second"), MemWrite(), ClearWait()),
            anchor="assignment.c:510-535", quirks=(2, 4),
            assumes=Guard(dir_state=(_EM, _DS))),
        Row("fia_fill", FIA, Guard(at_home=False, at_second=True),
            (Replace(checked=True),
             CacheWrite(_M, fill=True, value="cur_val"), ClearWait()),
            anchor="assignment.c:522-535", quirks=(1,)),
        Row("fia_home_and_second", FIA, Guard(at_home=True, at_second=True),
            (DirWrite(bv="second"), MemWrite(),
             Replace(checked=True),
             CacheWrite(_M, fill=True, value="cur_val"), ClearWait()),
            anchor="assignment.c:510-535", quirks=(1, 2, 4),
            assumes=Guard(dir_state=(_EM, _DS))),
        Row("fia_bystander", FIA, Guard(at_home=False, at_second=False),
            (ClearWait(),),
            anchor="assignment.c:535", quirks=(2,)),
        # -- EVICT_SHARED: remote blind promotion; home keyed on the
        #    post-drop sharer count -----------------------------------
        Row("es_remote_promote", ES, Guard(at_home=False),
            (CacheWrite(_E),),
            anchor="assignment.c:549-558", quirks=(5,)),
        Row("es_home_last", ES, Guard(at_home=True, others=("0",)),
            (DirWrite(state="U", bv="bv-sender"),),
            anchor="assignment.c:559-565"),
        Row("es_home_promote_self", ES,
            Guard(at_home=True, others=("1",), new_owner_self=True),
            (DirWrite(state="EM", bv="bv-sender"), CacheWrite(_E)),
            anchor="assignment.c:566-589", quirks=(5,)),
        Row("es_home_promote_other", ES,
            Guard(at_home=True, others=("1",), new_owner_self=False),
            (DirWrite(state="EM", bv="bv-sender"),
             Send("pri", ES, to="new_owner", value="mem")),
            anchor="assignment.c:566-589"),
        Row("es_home_many", ES, Guard(at_home=True, others=("2+",)),
            (DirWrite(bv="bv-sender"),),
            anchor="assignment.c:559-589"),
        # -- EVICT_MODIFIED: write back + release ------------------------
        Row("evict_modified", EMSG, Guard(),
            (DirWrite(state="U", bv="empty"), MemWrite()),
            anchor="assignment.c:596-616"),
    )


@functools.lru_cache(maxsize=None)
def mesi_table() -> ProtocolTable:
    """The reference protocol, quirks and all (the conformance gate
    proves this table bit-equivalent to ops/handlers.py)."""
    return ProtocolTable("mesi", "mesi", _mesi_rows(), dict(_DOMAINS))


@functools.lru_cache(maxsize=None)
def moesi_table() -> ProtocolTable:
    """MOESI: a WRITEBACK_INT-ed owner keeps its line as OWNED instead
    of SHARED (write-through O — see module docstring)."""
    return ProtocolTable("moesi", "moesi", _mesi_rows(demote_state=_O),
                         dict(_DOMAINS))


@functools.lru_cache(maxsize=None)
def mesif_table() -> ProtocolTable:
    """MESIF: the requester that pulls a dirty line fills as FORWARD —
    the newest copy is the designated forwarder (clean, so it evicts
    via EVICT_SHARED like SHARED does)."""
    return ProtocolTable("mesif", "mesif", _mesi_rows(dirty_fill_state=_F),
                         dict(_DOMAINS))


TABLES = {"mesi": mesi_table, "moesi": moesi_table, "mesif": mesif_table}


# ---------------------------------------------------------------------------
# host-side row matching (conformance row coverage + assumes validation)
# ---------------------------------------------------------------------------

def host_atoms(cfg: SystemConfig, a, receiver: int, msg: tuple) -> dict:
    """Guard-atom valuation for `receiver` processing `msg` in abstract
    state `a` (an analysis.model_check.AState). Pure Python — the
    reference semantics of every atom in :class:`Guard`."""
    t, sender, addr, _value, second, ds, _bv = msg
    home = codec.home_node(cfg, addr)
    cidx = codec.cache_index(cfg, addr)
    block = codec.block_index(cfg, addr)
    # the post-drop sharer set the handlers branch on is the RECEIVER'S
    # directory entry, not the message's carried bitvector (which is
    # nonzero only for REPLY_ID grants)
    others = a.dir_bitvec[receiver][block] & ~(1 << sender)
    nsh = bin(others).count("1")
    new_owner = (others & -others).bit_length() - 1 if others else -1
    return {
        "msg": t,
        "at_home": receiver == home,
        "at_second": receiver == second,
        "tag_match": a.cache_addr[receiver][cidx] == addr,
        "home_is_second": home == second,
        "new_owner_self": new_owner == receiver,
        "cache_state": a.cache_state[receiver][cidx],
        "dir_state": a.dir_state[receiver][block],
        "msg_dirstate": ds,
        "others": "0" if nsh == 0 else ("1" if nsh == 1 else "2+"),
    }


def guard_holds(g: Guard, atoms: dict) -> bool:
    for name in g.atoms():
        want = getattr(g, name)
        have = atoms[name]
        if isinstance(want, tuple):
            if have not in want:
                return False
        elif have != want:
            return False
    return True


def match_rows(table: ProtocolTable, atoms: dict) -> list:
    """All rows whose (msg, guard) match the valuation — exactly one on
    a table that passed totality+determinism."""
    return [r for r in table.rows
            if r.msg == atoms["msg"] and guard_holds(r.guard, atoms)]


# ---------------------------------------------------------------------------
# the compiler: table -> JAX message_phase
# ---------------------------------------------------------------------------

def _any(masks, template):
    if not masks:
        return jnp.zeros_like(template, dtype=bool)
    return functools.reduce(lambda x, y: x | y, masks)


def table_message_phase(table: ProtocolTable):
    """Compile `table` into a ``message_phase(cfg, state, mv)`` with the
    exact contract of :func:`..ops.handlers.message_phase`.

    Bit-exactness contract: at every *observable* position — masked
    update lanes, accepted candidate slots, stats masks — the compiled
    phase computes the same int32 values the hand-written handlers do
    (the engine never reads unmasked lanes or unaccepted slots:
    ops/step.py merge + ops/mailbox.py deliver). The conformance gate
    (analysis/conformance.py) checks this over whole scope state
    spaces.
    """
    rows = table.rows

    def phase(cfg: SystemConfig, state, mv):
        N, W = cfg.num_nodes, cfg.bitvec_words
        lanes = jnp.arange(N, dtype=jnp.int32)
        has, t = mv.has_msg, mv.type

        p_home = codec.home_node(cfg, mv.addr)
        p_block = codec.block_index(cfg, mv.addr)
        p_cidx = codec.cache_index(cfg, mv.addr)

        dirst = state.dir_state[lanes, p_block]
        dirbv = state.dir_bitvec[lanes, p_block]
        memv = state.memory[lanes, p_block]
        cl_addr = state.cache_addr[lanes, p_cidx]
        cl_val = state.cache_val[lanes, p_cidx]
        cl_state = state.cache_state[lanes, p_cidx]

        sender_bit = bit_single(W, mv.sender)
        second_bit = bit_single(W, mv.second)
        bv_others = dirbv & ~sender_bit
        nsh = popcount(bv_others)
        new_owner = ctz(bv_others)

        at_home = lanes == p_home
        at_second = lanes == mv.second
        tag_match = cl_addr == mv.addr
        home_is_second = p_home == mv.second
        new_owner_self = new_owner == lanes

        zero = jnp.zeros((N,), jnp.int32)
        none = jnp.full((N,), int(Msg.NONE), jnp.int32)
        zbv = jnp.zeros((N, cfg.msg_bitvec_words), jnp.uint32)

        def cset(values, x):
            m = jnp.zeros((N,), bool)
            for v in values:
                m = m | (x == int(v))
            return m

        def others_in(classes):
            m = jnp.zeros((N,), bool)
            for c in classes:
                m = m | ((nsh == 0) if c == "0" else
                         (nsh == 1) if c == "1" else (nsh >= 2))
            return m

        def guard_mask(row: Row):
            g = row.guard
            m = has & (t == row.msg)
            for atom, pred in (("at_home", at_home),
                               ("at_second", at_second),
                               ("tag_match", tag_match),
                               ("home_is_second", home_is_second),
                               ("new_owner_self", new_owner_self)):
                want = getattr(g, atom)
                if want is not None:
                    m = m & (pred if want else ~pred)
            if g.cache_state is not None:
                m = m & cset(g.cache_state, cl_state)
            if g.dir_state is not None:
                m = m & cset(g.dir_state, dirst)
            if g.msg_dirstate is not None:
                m = m & cset(g.msg_dirstate, mv.dirstate)
            if g.others is not None:
                m = m & others_in(g.others)
            return m

        masks = {r.name: guard_mask(r) for r in rows}

        def const(v):
            return jnp.full((N,), int(v), jnp.int32)

        val_exprs = {"0": zero, "msg.value": mv.value, "mem": memv,
                     "cache.val": cl_val, "cur_val": state.cur_val}
        recv_exprs = {"sender": mv.sender, "home": p_home,
                      "owner": ctz(dirbv), "second": mv.second,
                      "new_owner": new_owner}
        second_exprs = {"0": zero, "sender": mv.sender,
                        "msg.second": mv.second}
        ds_exprs = {"EM": const(_EM), "S": const(_DS), "U": const(_U)}
        bv_exprs = {"bv|sender": dirbv | sender_bit,
                    "bv|second": dirbv | second_bit,
                    "sender": sender_bit, "second": second_bit,
                    "bv-sender": bv_others,
                    "empty": jnp.zeros_like(dirbv)}

        def gather(kind):
            """(mask, effect, row) triples for one effect class."""
            out = []
            for r in rows:
                for e in r.effects:
                    if isinstance(e, kind):
                        out.append((masks[r.name], e, r))
            return out

        def sel(triples, value_of, default):
            conds = [m for m, _, _ in triples]
            vals = [value_of(e, r) for _, e, r in triples]
            if not conds:
                return default
            return jnp.select(conds, vals, default=default)

        false = jnp.zeros((N,), bool)

        # ---- cache writes -------------------------------------------------
        cwrites = gather(CacheWrite)
        fills = [(m, e, r) for m, e, r in cwrites if e.fill]
        fill_mask = _any([m for m, _, _ in fills], false)
        fill_val = sel(fills, lambda e, r: val_exprs[e.value], zero)
        cs_mask = _any([m for m, _, _ in cwrites], false)
        cs_val = sel(cwrites, lambda e, r: const(e.state), const(_I))

        # ---- replacement of the displaced line ---------------------------
        repl = gather(Replace)
        checked = _any([m for m, e, _ in repl if e.checked], false)
        uncond = _any([m for m, e, _ in repl if not e.checked], false)
        evict_fire = ((checked & (cl_addr != mv.addr) & (cl_state != _I))
                      | (uncond & (cl_state != _I)))

        # ---- directory writes --------------------------------------------
        dwrites = gather(DirWrite)
        ds_rows = [(m, e, r) for m, e, r in dwrites if e.state is not None]
        bv_rows = [(m, e, r) for m, e, r in dwrites if e.bv is not None]
        ds_mask = _any([m for m, _, _ in ds_rows], false)
        ds_val = sel(ds_rows, lambda e, r: ds_exprs[e.state], const(_U))
        dbv_mask = _any([m for m, _, _ in bv_rows], false)
        dbv_val = sel([(m[:, None], e, r) for m, e, r in bv_rows],
                      lambda e, r: bv_exprs[e.bv], jnp.zeros_like(dirbv))

        # ---- memory / waiting --------------------------------------------
        mem_mask = _any([m for m, _, _ in gather(MemWrite)], false)
        wait_clear = _any([m for m, _, _ in gather(ClearWait)], false)

        updates = dict(
            cache_idx=p_cidx, cache_state=(cs_mask, cs_val),
            cache_addr=(fill_mask, mv.addr), cache_val=(fill_mask, fill_val),
            mem=(mem_mask, p_block, mv.value),
            dir_state=(ds_mask, p_block, ds_val),
            dir_bv=(dbv_mask, p_block, dbv_val),
            wait_clear=wait_clear,
        )

        # ---- candidate out-messages --------------------------------------
        sends = gather(Send)
        pri = [(m, e, r) for m, e, r in sends if e.slot == "pri"]
        sec = [(m, e, r) for m, e, r in sends if e.slot == "sec"]
        pri_mask = _any([m for m, _, _ in pri], false)
        pri_type = jnp.where(pri_mask,
                             sel(pri, lambda e, r: const(e.type), none),
                             none)
        pri_recv = sel(pri, lambda e, r: recv_exprs[e.to], zero)
        pri_value = sel(pri, lambda e, r: val_exprs[e.value], zero)
        pri_second = sel(pri, lambda e, r: second_exprs[e.second], zero)
        pri_dirstate = sel(pri, lambda e, r: ds_exprs[e.dirstate],
                           const(_EM))
        grants = _any([m for m, e, _ in pri if e.bitvec == "others"], false)
        if cfg.inv_mode == "mailbox":
            pri_bitvec = jnp.where(grants[:, None], bv_others, zbv)
        else:
            pri_bitvec = zbv

        sec_mask = _any([m for m, _, _ in sec], false)
        sec_type = jnp.where(sec_mask,
                             sel(sec, lambda e, r: const(e.type), none),
                             none)
        sec_recv = sel(sec, lambda e, r: recv_exprs[e.to], zero)
        sec_value = sel(sec, lambda e, r: val_exprs[e.value], zero)
        sec_second = sel(sec, lambda e, r: second_exprs[e.second], zero)

        fan_mask = _any([m for m, _, _ in gather(InvFanout)], false)
        if cfg.inv_mode == "mailbox":
            targets = jnp.arange(N, dtype=jnp.int32)
            tw, tb = targets // 32, (targets % 32).astype(jnp.uint32)
            bits = (mv.bitvec[:, tw] >> tb[None, :]) & 1
            inv_mask = fan_mask[:, None] & (bits == 1)
            inv_type = jnp.where(inv_mask, int(Msg.INV), int(Msg.NONE))
            inv_recv = jnp.broadcast_to(targets[None, :], (N, N))
            inv_addr = jnp.broadcast_to(mv.addr[:, None], (N, N))
            inv_scatter = None
        else:
            inv_type = inv_recv = inv_addr = None
            inv_scatter = (grants, mv.addr, bv_others)

        ev_mod = evict_fire & (cl_state == _M)
        ev_type = jnp.where(
            evict_fire,
            jnp.where(ev_mod, int(Msg.EVICT_MODIFIED),
                      int(Msg.EVICT_SHARED)),
            none)
        ev_recv = codec.home_node(cfg, cl_addr)
        ev_value = jnp.where(ev_mod, cl_val, 0)

        cand_parts = dict(
            pri=(pri_type, pri_recv, mv.addr, pri_value, pri_second,
                 pri_dirstate, pri_bitvec),
            sec=(sec_type, sec_recv, mv.addr, sec_value, sec_second),
            inv=(inv_type, inv_recv, inv_addr),
            ev=(ev_type, ev_recv, cl_addr, ev_value),
        )

        stats = dict(
            msg_type_onehot=(has, t),
            invalidations=_any([m for m, _, _ in gather(CountInval)], false),
            evictions=evict_fire,
            unblocked=wait_clear & state.waiting,
        )
        return updates, cand_parts, inv_scatter, stats

    phase.__name__ = f"table_message_phase[{table.name}]"
    return phase
