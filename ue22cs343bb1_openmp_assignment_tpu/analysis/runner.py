"""`cache-sim analyze` — the static-analysis gate (host-side CLI).

Runs the protocol model checker over the builtin small scopes and the
JAX trace linter over the traced packages, prints a human report that
keeps reference-sanctioned quirks (`~`) visually distinct from genuine
violations (`!`), optionally writes the full JSON report, and exits
nonzero iff anything genuinely failed. This is the CI entry point
(scripts/check.sh); `python -m ue22cs343bb1_openmp_assignment_tpu.analysis`
is the same thing.
"""

from __future__ import annotations

import argparse
import json
import sys


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        prog="cache-sim analyze",
        description="Statically verify the coherence engine: small-scope "
                    "protocol model checking + JAX trace lint.")
    p.add_argument("--scopes", default=None,
                   help="comma-separated scope names (default: all "
                        "builtin scopes)")
    p.add_argument("--list-scopes", action="store_true",
                   help="print the builtin scopes and exit")
    p.add_argument("--skip-model-check", action="store_true")
    p.add_argument("--skip-lint", action="store_true")
    p.add_argument("--mutation", default=None,
                   help="run the model checker with this seeded handler "
                        "bug from analysis.mutations (the checker must "
                        "fail — its own regression test)")
    p.add_argument("--max-states", type=int, default=50_000,
                   help="state-count guard per scope (default 50000)")
    p.add_argument("--json", dest="json_path", default=None,
                   help="write the full JSON report here")
    p.add_argument("--lint-paths", nargs="*", default=None,
                   help="lint these files/dirs instead of the default "
                        "ops/ parallel/ models/")
    p.add_argument("-q", "--quiet", action="store_true",
                   help="only the verdict line")
    return p


def _print(quiet: bool, *a) -> None:
    if not quiet:
        print(*a)


def run_model_check(scope_names, mutation, max_states, quiet) -> dict:
    from ue22cs343bb1_openmp_assignment_tpu.analysis import model_check
    scopes = model_check.builtin_scopes()
    names = list(scopes) if scope_names is None else [
        s.strip() for s in scope_names.split(",") if s.strip()]
    unknown = [n for n in names if n not in scopes]
    if unknown:
        raise SystemExit(f"unknown scope(s): {', '.join(unknown)} "
                         f"(have: {', '.join(scopes)})")

    mp = None
    if mutation is not None:
        from ue22cs343bb1_openmp_assignment_tpu.analysis import mutations
        if mutation not in mutations.MUTATIONS:
            raise SystemExit(
                f"unknown mutation `{mutation}` "
                f"(have: {', '.join(mutations.MUTATIONS)})")
        fn, mscope, expected = mutations.MUTATIONS[mutation]
        mp = fn
        if scope_names is None:
            names = [mscope]
        _print(quiet, f"== seeded mutation `{mutation}` on scope "
                      f"{mscope} (expected finding: {expected})")

    out = {}
    for name in names:
        rep = model_check.check_scope(scopes[name], message_phase=mp,
                                      max_states=max_states)
        out[name] = rep
        st = rep["stats"]
        verdict = "ok" if rep["ok"] else "FAIL"
        _print(quiet,
               f"== scope {name}: {verdict}  "
               f"[{st['states']} states, {st['transitions']} transitions, "
               f"{st['quiescent_states']} quiescent, "
               f"{st['deadlocked_states']} deadlocked]")
        for q in rep["quirks"]:
            _print(quiet, f"  ~ {q['name']} ({q['states']} states) — "
                          f"sanctioned: {q['rationale']}")
        for n in rep["coverage"]["sanctioned_noops"]:
            _print(quiet, f"  ~ no-op {n['pair']} ({n['count']}x) — "
                          f"sanctioned: {n['rationale']}")
        for v in rep["violations"]:
            _print(quiet, f"  ! {v['check']}"
                          f"{'/' + v['name'] if v.get('name') and v['name'] != v['check'] else ''}"
                          f": {v['detail']}")
            for step in v.get("path", [])[-6:]:
                _print(quiet, f"      > {step}")
            for line in v.get("state_render", []):
                _print(quiet, f"      | {line}")
    return out


def run_lint(paths, quiet) -> dict:
    from ue22cs343bb1_openmp_assignment_tpu.analysis import lint_trace
    findings = lint_trace.lint_paths(paths)
    n_files = len({f.file for f in findings})
    if findings:
        _print(quiet, f"== lint: FAIL ({len(findings)} findings in "
                      f"{n_files} files)")
        for f in findings:
            _print(quiet, f"  ! {f.render()}")
    else:
        _print(quiet, "== lint: ok (0 findings)")
    return {"ok": not findings,
            "findings": [f.as_dict() for f in findings]}


def main(argv=None) -> int:
    args = build_parser().parse_args(argv)
    if args.list_scopes:
        from ue22cs343bb1_openmp_assignment_tpu.analysis import model_check
        for name, scope in model_check.builtin_scopes().items():
            d = scope.describe()
            print(f"{name}: {d['num_nodes']} nodes, programs "
                  f"{d['programs']}")
        return 0

    report = {"model_check": {}, "lint": None}
    ok = True
    if not args.skip_model_check:
        report["model_check"] = run_model_check(
            args.scopes, args.mutation, args.max_states, args.quiet)
        ok &= all(r["ok"] for r in report["model_check"].values())
    if not args.skip_lint:
        report["lint"] = run_lint(args.lint_paths, args.quiet)
        ok &= report["lint"]["ok"]
    report["ok"] = ok

    if args.json_path:
        with open(args.json_path, "w") as fh:
            json.dump(report, fh, indent=2, sort_keys=True)
        _print(args.quiet, f"report written to {args.json_path}")

    print("analyze:", "PASS" if ok else "FAIL")
    return 0 if ok else 1


if __name__ == "__main__":
    sys.exit(main())
