"""`cache-sim analyze` — the static-analysis gate (host-side CLI).

Runs the verification prongs: the symmetry-reduced protocol model
checker over the builtin small scopes, the linters (AST trace lint
always; jaxpr IR lint + recompilation guard behind ``--jaxpr``), the
coverage-guided differential fuzzer behind ``--fuzz N``, the
memory-consistency litmus matrix behind ``--litmus`` (exhaustive
outcome enumeration vs the declarative allowed sets,
analysis/litmus.py), the kernel-contract verifier behind
``--kernel`` (exact-arithmetic cap derivation, static VMEM footprint
vs device budget, Mosaic-lowerability lint over the fused round body;
analysis/kernelcheck.py), and the index-pressure auditor behind
``--index`` (static gather/scatter inventory with plane attribution,
per-engine indices/instr, mergeable-scatter detection and per-target
index budgets; analysis/indexcheck.py). Prints a
human report that keeps reference-sanctioned quirks (`~`) visually
distinct from genuine violations (`!`), optionally writes the full
JSON report, and exits by the code table in ``--help``. This is the CI
entry point (scripts/check.sh);
`python -m ue22cs343bb1_openmp_assignment_tpu.analysis` is the same
thing.
"""

from __future__ import annotations

import argparse
import json
import sys

_EPILOG = """\
exit codes — the one canonical contract for `cache-sim analyze`:
  0  clean pass — every requested check ran to completion and passed
  1  findings — a protocol violation, lint finding, fuzz divergence,
     table-verification failure, table/handler conformance divergence,
     kernel-contract finding (rounding lemma, VMEM budget,
     lowerability, or gate divergence), an index-budget breach, or a
     failed recompilation guard
  2  usage error (argparse's code, left untouched)
  3  budget exhausted, no finding — a scope hit --max-states before
     exhausting its state space, or the index prong's probe run hit
     its cycle budget before quiescence: nothing failed, but nothing
     was proven either; raise --max-states or shrink the scope
findings always win: a run that both finds a violation and exhausts a
budget exits 1, not 3.

related gate (documented here because the two share scripts/check.sh):
`cache-sim bench-diff` exits 0 = no regression (difference is noise),
2 = incomparable (configs/sample sizes don't support a verdict),
4 = statistically significant regression."""


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        prog="cache-sim analyze",
        description="Statically verify the coherence engine: "
                    "symmetry-reduced protocol model checking, "
                    "AST + jaxpr lint, differential fuzzing.",
        epilog=_EPILOG,
        formatter_class=argparse.RawDescriptionHelpFormatter)
    p.add_argument("--scopes", "--scope", dest="scopes", default=None,
                   help="comma-separated scope names (default: all "
                        "builtin scopes)")
    p.add_argument("--list-scopes", action="store_true",
                   help="print every scope (builtin + conformance-only) "
                        "with dimensions and programs, then exit")
    p.add_argument("--skip-model-check", action="store_true")
    p.add_argument("--skip-lint", action="store_true")
    p.add_argument("--table", action="store_true",
                   help="run the declarative-protocol-table prong: "
                        "verify_table static passes (totality, "
                        "determinism, conservation, stability, anchors) "
                        "over the MESI/MOESI/MESIF tables, then the "
                        "table-vs-handlers conformance gate on --scopes "
                        "(default 2n2h)")
    p.add_argument("--litmus", action="store_true",
                   help="run the memory-consistency litmus prong: "
                        "exhaustively enumerate each test's reachable "
                        "outcome set (model checker in litmus mode, "
                        "symmetry-reduced) and require EXACT equality "
                        "with the DSL's allowed set — any forbidden "
                        "outcome, or any allowed outcome the engine "
                        "cannot produce, is a finding")
    p.add_argument("--litmus-tests", default=None, metavar="T1,T2",
                   help="comma-separated litmus test names (default: "
                        "the full builtin suite; see analysis/litmus.py)")
    p.add_argument("--litmus-protocols", default="mesi",
                   metavar="P1,P2",
                   help="protocols for the litmus sweep (default mesi; "
                        "also moesi, mesif via the declarative tables)")
    p.add_argument("--mutation", default=None,
                   help="run the gates with this seeded bug: a handler "
                        "mutation from analysis.mutations.MUTATIONS "
                        "(checker/fuzzer/conformance must fail), a "
                        "table mutation from TABLE_MUTATIONS "
                        "(verify-table must fail), or a consistency "
                        "mutation from CONSISTENCY_MUTATIONS (litmus "
                        "enumeration must fail) — the gates' own "
                        "regression test")
    p.add_argument("--max-states", type=int, default=50_000,
                   help="state-count guard per scope (default 50000); "
                        "exceeding it without a finding exits 3")
    p.add_argument("--fuzz", type=int, default=0, metavar="N",
                   help="run N coverage-guided differential fuzz cases "
                        "(async vs native on any traffic, sync joining "
                        "on node-local); diverging traces are ddmin-"
                        "shrunk automatically")
    p.add_argument("--seed", type=int, default=0,
                   help="fuzzer PRNG seed (default 0); the seed fully "
                        "determines corpus and verdicts")
    p.add_argument("--repro-dir", default=None, metavar="DIR",
                   help="write shrunk fuzz repros here (core_<n>.txt "
                        "fixture + repro.json + Perfetto trace)")
    p.add_argument("--flight-dir", default=None, metavar="DIR",
                   help="arm the flight recorder: every fuzz finding "
                        "dumps a replayable incident_<case_id> dir "
                        "here (telemetry ring + metrics + Perfetto "
                        "trace + repro fixture; obs/flight.py)")
    p.add_argument("--jaxpr", action="store_true",
                   help="run the jaxpr IR lint over the ops/ hot paths "
                        "plus the three-engine recompilation guard")
    p.add_argument("--kernel", action="store_true",
                   help="run the kernel-contract prong: re-derive the "
                        "fused round's contender cap from (chunk bits, "
                        "weight exponents, f32 mantissa) with machine-"
                        "checked rounding lemmas, trace the kernel body "
                        "for a static VMEM footprint vs the device "
                        "budget, lint the jaxpr for non-lowerable "
                        "primitives, and cross-check pallas_round."
                        "supported() against the derived bounds")
    p.add_argument("--kernel-nodes", type=int, default=4096,
                   metavar="N",
                   help="node count for the kernel-contract headline "
                        "config (default 4096, the perf-report deep "
                        "headline)")
    p.add_argument("--kernel-static", action="store_true",
                   help="skip the kernel-body trace: exactness + gate "
                        "passes and the block-table VMEM row only "
                        "(~1s instead of ~15s; traced liveness peak "
                        "and lowerability scan are skipped)")
    p.add_argument("--index", action="store_true",
                   help="run the index-pressure prong: trace every hot "
                        "body, inventory gather/scatter/dynamic-slice "
                        "eqns with semantic-plane attribution, compute "
                        "per-engine indices per retired instruction, "
                        "flag mergeable scatter pairs (shared index "
                        "vector, disjoint destinations) and enforce "
                        "the per-target index budgets")
    p.add_argument("--index-engine", default=None,
                   choices=["async", "sync", "deep", "wave", "fused"],
                   help="restrict the index audit to one engine "
                        "(default: all five; async carries the "
                        "sharded/RDMA parallel variants)")
    p.add_argument("--index-nodes", type=int, default=None,
                   metavar="N",
                   help="node count for the index audit (default 8, "
                        "the canonical budget-pinned size; budgets "
                        "are only enforced at the default)")
    p.add_argument("--json", dest="json_path", default=None,
                   help="write the full JSON report here")
    p.add_argument("--lint-paths", nargs="*", default=None,
                   help="lint these files/dirs instead of the default "
                        "ops/ parallel/ models/")
    p.add_argument("-q", "--quiet", action="store_true",
                   help="only the verdict line")
    return p


def _print(quiet: bool, *a) -> None:
    if not quiet:
        print(*a)


def _resolve_mutation(name):
    if name is None:
        return None, None, None
    from ue22cs343bb1_openmp_assignment_tpu.analysis import mutations
    if name in mutations.TABLE_MUTATIONS:
        raise SystemExit(
            f"`{name}` is a table mutation — it seeds a bug in the "
            "declarative table, not the handlers, so it only applies to "
            "the --table prong (run with --table --skip-model-check "
            "--skip-lint)")
    if name in mutations.CONSISTENCY_MUTATIONS:
        raise SystemExit(
            f"`{name}` is a consistency mutation — it keeps every "
            "per-state invariant happy and corrupts only observed "
            "values, so the invariant prongs cannot see it; run it "
            "through the litmus prong (--litmus --skip-model-check "
            "--skip-lint) or the fuzzer's consistency oracle")
    if name in mutations.KERNEL_MUTATIONS:
        raise SystemExit(
            f"`{name}` is a kernel mutation — it perturbs the fused "
            "Pallas round's arithmetic contracts (ladder constants / "
            "support gates), which the protocol prongs never touch; "
            "run it through the kernel-contract prong (--kernel "
            "--skip-model-check --skip-lint)")
    if name in mutations.INDEX_MUTATIONS:
        raise SystemExit(
            f"`{name}` is an index mutation — it re-splits the packed "
            "commit scatters bit-identically, so every dynamic oracle "
            "(model checker, fuzzer, conformance, goldens) stays "
            "green; only the static index inventory can see it — run "
            "it through the index prong (--index --skip-model-check "
            "--skip-lint)")
    if name not in mutations.MUTATIONS:
        raise SystemExit(
            f"unknown mutation `{name}` (handler mutations: "
            f"{', '.join(mutations.MUTATIONS)}; table mutations: "
            f"{', '.join(mutations.TABLE_MUTATIONS)}; consistency "
            f"mutations: {', '.join(mutations.CONSISTENCY_MUTATIONS)}; "
            f"kernel mutations: "
            f"{', '.join(mutations.KERNEL_MUTATIONS)}; index "
            f"mutations: {', '.join(mutations.INDEX_MUTATIONS)})")
    return mutations.MUTATIONS[name]


def run_model_check(scope_names, mutation, max_states, quiet) -> dict:
    from ue22cs343bb1_openmp_assignment_tpu.analysis import model_check
    scopes = model_check.builtin_scopes()
    names = list(scopes) if scope_names is None else [
        s.strip() for s in scope_names.split(",") if s.strip()]
    unknown = [n for n in names if n not in scopes]
    if unknown:
        raise SystemExit(f"unknown scope(s): {', '.join(unknown)} "
                         f"(have: {', '.join(scopes)})")

    mp, mscope, expected = _resolve_mutation(mutation)
    if mp is not None:
        if scope_names is None:
            names = [mscope]
        _print(quiet, f"== seeded mutation `{mutation}` on scope "
                      f"{mscope} (expected finding: {expected})")

    out = {}
    for name in names:
        try:
            rep = model_check.check_scope(scopes[name], message_phase=mp,
                                          max_states=max_states)
        except model_check.ScopeTooLarge as e:
            out[name] = {"ok": None, "budget_exhausted": True,
                         "detail": str(e)}
            _print(quiet, f"== scope {name}: BUDGET EXHAUSTED ({e}) — "
                          "no finding; not a pass")
            continue
        out[name] = rep
        st = rep["stats"]
        verdict = "ok" if rep["ok"] else "FAIL"
        _print(quiet,
               f"== scope {name}: {verdict}  "
               f"[{st['states']} states, {st['transitions']} transitions, "
               f"{st['quiescent_states']} quiescent, "
               f"{st['deadlocked_states']} deadlocked, "
               f"sym x{st['symmetry_group_order']}]")
        for q in rep["quirks"]:
            _print(quiet, f"  ~ {q['name']} ({q['states']} states) — "
                          f"sanctioned: {q['rationale']}")
        for n in rep["coverage"]["sanctioned_noops"]:
            _print(quiet, f"  ~ no-op {n['pair']} ({n['count']}x) — "
                          f"sanctioned: {n['rationale']}")
        for v in rep["violations"]:
            _print(quiet, f"  ! {v['check']}"
                          f"{'/' + v['name'] if v.get('name') and v['name'] != v['check'] else ''}"
                          f": {v['detail']}")
            for step in v.get("path", [])[-6:]:
                _print(quiet, f"      > {step}")
            for step in v.get("cycle", []):
                _print(quiet, f"      @ {step}")
            for line in v.get("state_render", []):
                _print(quiet, f"      | {line}")
    return out


def run_litmus(test_names, protocol_names, mutation, max_states,
               quiet) -> dict:
    """The memory-consistency prong: enumerate every (protocol, test)
    cell of the litmus matrix and require the reachable outcome set to
    EXACTLY equal the DSL's allowed set."""
    from ue22cs343bb1_openmp_assignment_tpu.analysis import (litmus,
                                                             mutations)
    names = (None if test_names is None else
             [s.strip() for s in test_names.split(",") if s.strip()])
    protos = [s.strip() for s in protocol_names.split(",") if s.strip()]
    unknown = [n for n in (names or []) if n not in litmus.BUILTIN]
    if unknown:
        raise SystemExit(f"unknown litmus test(s): {', '.join(unknown)} "
                         f"(have: {', '.join(litmus.BUILTIN)})")

    mp = None
    cmut = mutations.CONSISTENCY_MUTATIONS.get(mutation) \
        if mutation else None
    if mutation is not None:
        if cmut is not None:
            mp = cmut[0]
            if names is None:
                names = [cmut[1]]   # the shape documented to kill it
            _print(quiet, f"== seeded consistency mutation `{mutation}` "
                          f"on litmus {cmut[1]} (a forbidden outcome "
                          "must appear)")
        elif mutation in mutations.MUTATIONS:
            mp = mutations.MUTATIONS[mutation][0]
        # other kinds already rejected by _resolve_mutation upstream

    def progress(proto, name, rep):
        if rep.get("budget_exhausted"):
            _print(quiet, f"== litmus {name} [{proto}]: BUDGET "
                          f"EXHAUSTED ({rep['detail']}) — no finding; "
                          "not a pass")
            return
        st = rep["stats"]
        verdict = "ok" if rep["ok"] else "FAIL"
        _print(quiet,
               f"== litmus {name} [{proto}]: {verdict}  "
               f"[{st['states']} states, {len(rep['observed'])} "
               f"outcomes, allowed {len(rep['allowed'])}]")
        for o in rep["unexpected"]:
            _print(quiet, f"  ! forbidden outcome observed: {tuple(o)}")
        for o in rep["unobserved"]:
            _print(quiet, f"  ! allowed outcome never reached: "
                          f"{tuple(o)}")
        for v in rep["violations"]:
            _print(quiet, f"  ! model-check violation: {v}")

    return litmus.run_suite(tests=names, protocols=protos,
                            message_phase=mp, max_states=max_states,
                            progress=progress)


def run_lint(paths, quiet) -> dict:
    from ue22cs343bb1_openmp_assignment_tpu.analysis import lint_trace
    findings = lint_trace.lint_paths(paths)
    # the no-jax boundary pass always runs over its own fixed targets
    # (the daemon wire layer), independent of --lint-paths
    findings.extend(lint_trace.lint_no_jax())
    n_files = len({f.file for f in findings})
    if findings:
        _print(quiet, f"== lint: FAIL ({len(findings)} findings in "
                      f"{n_files} files)")
        for f in findings:
            _print(quiet, f"  ! {f.render()}")
    else:
        _print(quiet, "== lint: ok (0 findings)")
    return {"ok": not findings,
            "findings": [f.as_dict() for f in findings]}


def run_jaxpr(quiet) -> dict:
    from ue22cs343bb1_openmp_assignment_tpu.analysis import lint_jaxpr
    rep = lint_jaxpr.lint()
    guard = lint_jaxpr.recompile_guard()
    rep["recompile_guard"] = guard
    rep["ok"] = bool(rep["ok"] and guard["ok"])
    counts = ", ".join(f"{k}={v}" for k, v in rep["targets"].items())
    _print(quiet, f"== jaxpr lint: {'ok' if rep['ok'] else 'FAIL'} "
                  f"[{counts}; budget {rep['budget']}]")
    for f in rep["findings"]:
        _print(quiet, f"  ! {f['target']}: {f['rule']} — {f['detail']}")
    _print(quiet, f"   recompile guard: async cache={guard['async_cache_size']} "
                  f"sync cache={guard['sync_cache_size']} "
                  f"wave cache={guard['wave_cache_size']} "
                  f"serve wave compiles={guard['serve_wave_compiles']} "
                  f"native reuse={guard['native_build_reused']}")
    return rep


def run_table(scope_names, mutation, max_states, quiet) -> dict:
    """The declarative-table prong: static verify passes over all three
    protocol tables, then the table-vs-handlers conformance gate."""
    from ue22cs343bb1_openmp_assignment_tpu.analysis import (conformance,
                                                             mutations,
                                                             protocol_table,
                                                             verify_table)
    out = {"verify": {}, "conformance": {}}
    tmut = mutations.TABLE_MUTATIONS.get(mutation) if mutation else None
    hmut = mutations.MUTATIONS.get(mutation) if mutation else None
    if mutation and tmut is None and hmut is None:
        raise SystemExit(
            f"unknown mutation `{mutation}` (handler mutations: "
            f"{', '.join(mutations.MUTATIONS)}; table mutations: "
            f"{', '.join(mutations.TABLE_MUTATIONS)})")

    for name, factory in protocol_table.TABLES.items():
        tbl = factory()
        if tmut is not None and name == "mesi":
            tbl = tmut[0](tbl)
            _print(quiet, f"== seeded table mutation `{mutation}` "
                          f"(expected finding: {tmut[1]})")
        rep = verify_table.verify(tbl)
        out["verify"][name] = rep
        passes = " ".join(f"{p}={'ok' if v == 'ok' else 'FAIL'}"
                          for p, v in rep["passes"].items())
        _print(quiet, f"== table {tbl.name}: "
                      f"{'ok' if rep['ok'] else 'FAIL'} "
                      f"[{rep['rows']} rows; {passes}]")
        for f in rep["findings"][:8]:
            _print(quiet, f"  ! {f['kind']}: {f['detail']}")

    if tmut is not None:
        # a mutated table is (intentionally) not the handlers' protocol;
        # conformance against the live phase would only restate the
        # verify findings, so the prong stops at the static passes
        return out

    scopes = conformance.conformance_scopes()
    if scope_names is not None:
        names = [s.strip() for s in scope_names.split(",") if s.strip()]
        unknown = [n for n in names if n not in scopes]
        if unknown:
            raise SystemExit(f"unknown scope(s): {', '.join(unknown)} "
                             f"(have: {', '.join(scopes)})")
    elif hmut is not None:
        names = [hmut[1]]   # the scope documented to expose the mutant
        _print(quiet, f"== seeded handler mutation `{mutation}` on scope "
                      f"{hmut[1]} (conformance vs the MESI table must "
                      "diverge)")
    else:
        names = ["2n2h"]
    mp = hmut[0] if hmut is not None else None
    tbl = protocol_table.mesi_table()
    for name in names:
        try:
            rep = conformance.check_conformance(
                scopes[name], tbl, message_phase=mp, max_states=max_states)
        except conformance.ScopeTooLarge as e:
            out["conformance"][name] = {"ok": None,
                                        "budget_exhausted": True,
                                        "detail": str(e)}
            _print(quiet, f"== conformance {name}: BUDGET EXHAUSTED "
                          f"({e}) — no finding; not a pass")
            continue
        out["conformance"][name] = rep
        st = rep["stats"]
        _print(quiet,
               f"== conformance {name}: {'ok' if rep['ok'] else 'FAIL'} "
               f"[{st['states']} states, {st['msg_events']} msg events, "
               f"rows {st['rows_covered']}/{st['rows_total']}, "
               f"sym x{st['symmetry_group_order']}]")
        for f in rep["findings"][:4]:
            _print(quiet, f"  ! {f['check']}: {f['detail']}")
            for step in f.get("path", [])[-6:]:
                _print(quiet, f"      > {step}")
            for line in f.get("ref_render", []):
                _print(quiet, f"      |ref   {line}")
            for line in f.get("table_render", []):
                _print(quiet, f"      |table {line}")
    return out


def run_kernel(nodes, static, mutation, quiet) -> dict:
    """The kernel-contract prong: exactness, VMEM, lowerability, and
    gate-consistency audits of the fused Pallas round
    (analysis/kernelcheck.py). A seeded kernel mutation forces the
    static passes only — every kernel mutant is killed by arithmetic,
    no trace needed — and the run must then FAIL with the documented
    finding kind (asserted here, so a mutant the verifier misses is
    itself a finding)."""
    from ue22cs343bb1_openmp_assignment_tpu.analysis import (kernelcheck,
                                                             mutations)
    kmut = mutations.KERNEL_MUTATIONS.get(mutation) if mutation else None
    if mutation is not None and kmut is None and \
            mutation not in mutations.MUTATIONS:
        # non-kernel mutations were rejected upstream unless they are
        # handler mutations riding along for another prong; those don't
        # touch kernel arithmetic, so the prong just runs clean
        raise SystemExit(
            f"unknown mutation `{mutation}` (kernel mutations: "
            f"{', '.join(mutations.KERNEL_MUTATIONS)})")

    cfg = kernelcheck.headline_config(num_nodes=nodes)
    trace = not static
    if kmut is not None:
        trace = False
        _print(quiet, f"== seeded kernel mutation `{mutation}` "
                      f"(expected finding: {kmut[1]})")
        with kmut[0]():
            rep = kernelcheck.check(cfg, trace=False)
        kinds = [f["kind"] for f in rep["findings"]]
        rep["expected_kind"] = kmut[1]
        rep["mutant_killed"] = (not rep["ok"]) and kmut[1] in kinds
        if not rep["mutant_killed"]:
            # the verifier MISSED a seeded bug: that is the failure
            rep["ok"] = False
            rep["findings"].append({
                "pass": "mutation", "kind": "mutant_survived",
                "detail": f"seeded kernel mutation `{mutation}` was not "
                          f"caught (expected `{kmut[1]}`, got "
                          f"{kinds or 'no findings'})"})
    else:
        rep = kernelcheck.check(cfg, trace=trace)
    for line in kernelcheck.render_text(rep):
        _print(quiet, line)
    return rep


def run_index(engine, nodes, mutation, max_states, quiet) -> dict:
    """The index-pressure prong: static gather/scatter inventory,
    plane attribution, indices/instr probes, merge detection and
    budget enforcement (analysis/indexcheck.py). A seeded index
    mutation skips the probe runs — the mutant is semantics-preserving
    by construction, so only the static pass can kill it — and the run
    must then FAIL with the documented budget breach AND name the
    re-split planes as merge candidates (asserted here: a mutant the
    auditor misses is itself a finding)."""
    from ue22cs343bb1_openmp_assignment_tpu.analysis import (indexcheck,
                                                             mutations)
    imut = mutations.INDEX_MUTATIONS.get(mutation) if mutation else None
    if mutation is not None and imut is None and \
            mutation not in mutations.MUTATIONS:
        raise SystemExit(
            f"unknown mutation `{mutation}` (index mutations: "
            f"{', '.join(mutations.INDEX_MUTATIONS)})")

    engines = None if engine is None else [engine]
    nodes = indexcheck.DEFAULT_NODES if nodes is None else nodes
    if imut is not None:
        engines = engines or ["async"]   # the seam lives in step.cycle
        _print(quiet, f"== seeded index mutation `{mutation}` "
                      f"(expected finding: {imut[1]})")
        with imut[0]():
            rep = indexcheck.check(engines=engines, nodes=nodes,
                                   probe=False)
        kinds = [f["kind"] for f in rep["findings"]]
        cands = [c for er in rep["engines"].values()
                 for c in er["merge_candidates"]
                 if c["scope"].startswith("step.cycle")]
        rep["expected_kind"] = imut[1]
        rep["mutant_killed"] = bool((not rep["ok"]) and imut[1] in kinds
                                    and cands)
        if not rep["mutant_killed"]:
            # the auditor MISSED a seeded bug: that is the failure
            rep["ok"] = False
            rep["findings"].append({
                "pass": "mutation", "kind": "mutant_survived",
                "detail": f"seeded index mutation `{mutation}` was not "
                          f"caught (expected `{imut[1]}` + merge "
                          f"candidates in step.cycle, got "
                          f"{kinds or 'no findings'} and "
                          f"{len(cands)} candidates)"})
    else:
        rep = indexcheck.check(engines=engines, nodes=nodes,
                               probe=True,
                               probe_budget=min(max_states, 4096))
    for line in indexcheck.render_text(rep):
        _print(quiet, line)
    return rep


def run_fuzz(n_cases, seed, mutation, repro_dir, quiet,
             flight_dir=None) -> dict:
    from ue22cs343bb1_openmp_assignment_tpu.analysis import fuzz as fz
    from ue22cs343bb1_openmp_assignment_tpu.analysis import shrink as sh
    mp = _resolve_mutation(mutation)[0]
    rep = fz.fuzz(n_cases, seed=seed, message_phase=mp,
                  flight_dir=flight_dir)
    _print(quiet,
           f"== fuzz: {'ok' if rep['ok'] else 'FAIL'} "
           f"[{n_cases} cases, seed {seed}, "
           f"{rep['coverage_points']} coverage points, "
           f"verdicts {rep['verdicts']}, "
           f"{rep['quirk_cases']} quirk-only cases]")
    if rep["findings"]:
        shrunk = sh.shrink_findings(rep, out_root=repro_dir,
                                    message_phase=mp, limit=2)
        rep["shrunk"] = shrunk
        for s in shrunk:
            _print(quiet,
                   f"  ! case {s['case_id']}: {s['verdict']} — "
                   f"{s['detail']}; shrunk {s['instrs_before']} -> "
                   f"{s['instrs_after']} instrs ({s['runs']} runs)")
        if repro_dir:
            _print(quiet, f"   repros written under {repro_dir}")
        if flight_dir:
            _print(quiet, f"   flight-recorder incidents under "
                          f"{flight_dir}")
    return rep


def main(argv=None) -> int:
    args = build_parser().parse_args(argv)
    if args.list_scopes:
        from ue22cs343bb1_openmp_assignment_tpu.analysis import (conformance,
                                                                 model_check)
        builtin = set(model_check.builtin_scopes())
        for name, scope in conformance.conformance_scopes().items():
            d = scope.describe()
            tag = "" if name in builtin else "  [conformance-only]"
            print(f"{name}: {d['num_nodes']} nodes, cache {d['cache_size']}"
                  f", mem {d['mem_size']} ({d['mem_init']}){tag}")
            for i, prog in enumerate(d["programs"]):
                print(f"    node {i}: "
                      + "; ".join(f"{op} a={a} v={v}" for op, a, v in prog))
        return 0

    report = {"model_check": {}, "lint": None, "jaxpr": None,
              "fuzz": None, "table": None, "litmus": None,
              "kernel": None, "index": None}
    ok, exhausted = True, False
    if not args.skip_model_check:
        report["model_check"] = run_model_check(
            args.scopes, args.mutation, args.max_states, args.quiet)
        for r in report["model_check"].values():
            if r.get("budget_exhausted"):
                exhausted = True
            else:
                ok &= r["ok"]
    if args.litmus:
        report["litmus"] = run_litmus(
            args.litmus_tests, args.litmus_protocols, args.mutation,
            args.max_states, args.quiet)
        for per_proto in report["litmus"].values():
            for r in per_proto.values():
                if r.get("budget_exhausted"):
                    exhausted = True
                else:
                    ok &= r["ok"]
    if args.table:
        report["table"] = run_table(args.scopes, args.mutation,
                                    args.max_states, args.quiet)
        for r in report["table"]["verify"].values():
            ok &= r["ok"]
        for r in report["table"]["conformance"].values():
            if r.get("budget_exhausted"):
                exhausted = True
            else:
                ok &= r["ok"]
    if not args.skip_lint:
        report["lint"] = run_lint(args.lint_paths, args.quiet)
        ok &= report["lint"]["ok"]
    if args.jaxpr:
        report["jaxpr"] = run_jaxpr(args.quiet)
        ok &= report["jaxpr"]["ok"]
    if args.kernel:
        report["kernel"] = run_kernel(args.kernel_nodes,
                                      args.kernel_static, args.mutation,
                                      args.quiet)
        ok &= report["kernel"]["ok"]
    if args.index:
        report["index"] = run_index(args.index_engine, args.index_nodes,
                                    args.mutation, args.max_states,
                                    args.quiet)
        if report["index"].get("budget_exhausted"):
            exhausted = True
        ok &= report["index"]["ok"]
    if args.fuzz > 0:
        report["fuzz"] = run_fuzz(args.fuzz, args.seed, args.mutation,
                                  args.repro_dir, args.quiet,
                                  flight_dir=args.flight_dir)
        ok &= report["fuzz"]["ok"]
    report["ok"] = bool(ok and not exhausted)

    if args.json_path:
        with open(args.json_path, "w") as fh:
            json.dump(report, fh, indent=2, sort_keys=True)
        _print(args.quiet, f"report written to {args.json_path}")

    if not ok:
        print("analyze: FAIL")
        return 1
    if exhausted:
        print("analyze: BUDGET EXHAUSTED (no finding — not a pass)")
        return 3
    print("analyze: PASS")
    return 0


if __name__ == "__main__":
    sys.exit(main())
