"""`cache-sim analyze` — the static-analysis gate (host-side CLI).

Runs the three verification prongs: the symmetry-reduced protocol model
checker over the builtin small scopes, the linters (AST trace lint
always; jaxpr IR lint + recompilation guard behind ``--jaxpr``), and
the coverage-guided differential fuzzer behind ``--fuzz N``. Prints a
human report that keeps reference-sanctioned quirks (`~`) visually
distinct from genuine violations (`!`), optionally writes the full
JSON report, and exits by the code table in ``--help``. This is the CI
entry point (scripts/check.sh);
`python -m ue22cs343bb1_openmp_assignment_tpu.analysis` is the same
thing.
"""

from __future__ import annotations

import argparse
import json
import sys

_EPILOG = """\
exit codes:
  0  clean pass — every requested check ran to completion and passed
  1  findings — a protocol violation, lint finding, fuzz divergence,
     or failed recompilation guard
  3  budget exhausted, no finding — a scope hit --max-states before
     exhausting its state space: nothing failed, but nothing was
     proven either; raise --max-states or shrink the scope
(2 is argparse's usage-error code, left untouched)"""


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        prog="cache-sim analyze",
        description="Statically verify the coherence engine: "
                    "symmetry-reduced protocol model checking, "
                    "AST + jaxpr lint, differential fuzzing.",
        epilog=_EPILOG,
        formatter_class=argparse.RawDescriptionHelpFormatter)
    p.add_argument("--scopes", "--scope", dest="scopes", default=None,
                   help="comma-separated scope names (default: all "
                        "builtin scopes)")
    p.add_argument("--list-scopes", action="store_true",
                   help="print the builtin scopes and exit")
    p.add_argument("--skip-model-check", action="store_true")
    p.add_argument("--skip-lint", action="store_true")
    p.add_argument("--mutation", default=None,
                   help="run the checker/fuzzer with this seeded handler "
                        "bug from analysis.mutations (the gate must "
                        "fail — its own regression test)")
    p.add_argument("--max-states", type=int, default=50_000,
                   help="state-count guard per scope (default 50000); "
                        "exceeding it without a finding exits 3")
    p.add_argument("--fuzz", type=int, default=0, metavar="N",
                   help="run N coverage-guided differential fuzz cases "
                        "(async vs native on any traffic, sync joining "
                        "on node-local); diverging traces are ddmin-"
                        "shrunk automatically")
    p.add_argument("--seed", type=int, default=0,
                   help="fuzzer PRNG seed (default 0); the seed fully "
                        "determines corpus and verdicts")
    p.add_argument("--repro-dir", default=None, metavar="DIR",
                   help="write shrunk fuzz repros here (core_<n>.txt "
                        "fixture + repro.json + Perfetto trace)")
    p.add_argument("--flight-dir", default=None, metavar="DIR",
                   help="arm the flight recorder: every fuzz finding "
                        "dumps a replayable incident_<case_id> dir "
                        "here (telemetry ring + metrics + Perfetto "
                        "trace + repro fixture; obs/flight.py)")
    p.add_argument("--jaxpr", action="store_true",
                   help="run the jaxpr IR lint over the ops/ hot paths "
                        "plus the three-engine recompilation guard")
    p.add_argument("--json", dest="json_path", default=None,
                   help="write the full JSON report here")
    p.add_argument("--lint-paths", nargs="*", default=None,
                   help="lint these files/dirs instead of the default "
                        "ops/ parallel/ models/")
    p.add_argument("-q", "--quiet", action="store_true",
                   help="only the verdict line")
    return p


def _print(quiet: bool, *a) -> None:
    if not quiet:
        print(*a)


def _resolve_mutation(name):
    if name is None:
        return None, None, None
    from ue22cs343bb1_openmp_assignment_tpu.analysis import mutations
    if name not in mutations.MUTATIONS:
        raise SystemExit(f"unknown mutation `{name}` "
                         f"(have: {', '.join(mutations.MUTATIONS)})")
    return mutations.MUTATIONS[name]


def run_model_check(scope_names, mutation, max_states, quiet) -> dict:
    from ue22cs343bb1_openmp_assignment_tpu.analysis import model_check
    scopes = model_check.builtin_scopes()
    names = list(scopes) if scope_names is None else [
        s.strip() for s in scope_names.split(",") if s.strip()]
    unknown = [n for n in names if n not in scopes]
    if unknown:
        raise SystemExit(f"unknown scope(s): {', '.join(unknown)} "
                         f"(have: {', '.join(scopes)})")

    mp, mscope, expected = _resolve_mutation(mutation)
    if mp is not None:
        if scope_names is None:
            names = [mscope]
        _print(quiet, f"== seeded mutation `{mutation}` on scope "
                      f"{mscope} (expected finding: {expected})")

    out = {}
    for name in names:
        try:
            rep = model_check.check_scope(scopes[name], message_phase=mp,
                                          max_states=max_states)
        except model_check.ScopeTooLarge as e:
            out[name] = {"ok": None, "budget_exhausted": True,
                         "detail": str(e)}
            _print(quiet, f"== scope {name}: BUDGET EXHAUSTED ({e}) — "
                          "no finding; not a pass")
            continue
        out[name] = rep
        st = rep["stats"]
        verdict = "ok" if rep["ok"] else "FAIL"
        _print(quiet,
               f"== scope {name}: {verdict}  "
               f"[{st['states']} states, {st['transitions']} transitions, "
               f"{st['quiescent_states']} quiescent, "
               f"{st['deadlocked_states']} deadlocked, "
               f"sym x{st['symmetry_group_order']}]")
        for q in rep["quirks"]:
            _print(quiet, f"  ~ {q['name']} ({q['states']} states) — "
                          f"sanctioned: {q['rationale']}")
        for n in rep["coverage"]["sanctioned_noops"]:
            _print(quiet, f"  ~ no-op {n['pair']} ({n['count']}x) — "
                          f"sanctioned: {n['rationale']}")
        for v in rep["violations"]:
            _print(quiet, f"  ! {v['check']}"
                          f"{'/' + v['name'] if v.get('name') and v['name'] != v['check'] else ''}"
                          f": {v['detail']}")
            for step in v.get("path", [])[-6:]:
                _print(quiet, f"      > {step}")
            for step in v.get("cycle", []):
                _print(quiet, f"      @ {step}")
            for line in v.get("state_render", []):
                _print(quiet, f"      | {line}")
    return out


def run_lint(paths, quiet) -> dict:
    from ue22cs343bb1_openmp_assignment_tpu.analysis import lint_trace
    findings = lint_trace.lint_paths(paths)
    n_files = len({f.file for f in findings})
    if findings:
        _print(quiet, f"== lint: FAIL ({len(findings)} findings in "
                      f"{n_files} files)")
        for f in findings:
            _print(quiet, f"  ! {f.render()}")
    else:
        _print(quiet, "== lint: ok (0 findings)")
    return {"ok": not findings,
            "findings": [f.as_dict() for f in findings]}


def run_jaxpr(quiet) -> dict:
    from ue22cs343bb1_openmp_assignment_tpu.analysis import lint_jaxpr
    rep = lint_jaxpr.lint()
    guard = lint_jaxpr.recompile_guard()
    rep["recompile_guard"] = guard
    rep["ok"] = bool(rep["ok"] and guard["ok"])
    counts = ", ".join(f"{k}={v}" for k, v in rep["targets"].items())
    _print(quiet, f"== jaxpr lint: {'ok' if rep['ok'] else 'FAIL'} "
                  f"[{counts}; budget {rep['budget']}]")
    for f in rep["findings"]:
        _print(quiet, f"  ! {f['target']}: {f['rule']} — {f['detail']}")
    _print(quiet, f"   recompile guard: async cache={guard['async_cache_size']} "
                  f"sync cache={guard['sync_cache_size']} "
                  f"native reuse={guard['native_build_reused']}")
    return rep


def run_fuzz(n_cases, seed, mutation, repro_dir, quiet,
             flight_dir=None) -> dict:
    from ue22cs343bb1_openmp_assignment_tpu.analysis import fuzz as fz
    from ue22cs343bb1_openmp_assignment_tpu.analysis import shrink as sh
    mp = _resolve_mutation(mutation)[0]
    rep = fz.fuzz(n_cases, seed=seed, message_phase=mp,
                  flight_dir=flight_dir)
    _print(quiet,
           f"== fuzz: {'ok' if rep['ok'] else 'FAIL'} "
           f"[{n_cases} cases, seed {seed}, "
           f"{rep['coverage_points']} coverage points, "
           f"verdicts {rep['verdicts']}, "
           f"{rep['quirk_cases']} quirk-only cases]")
    if rep["findings"]:
        shrunk = sh.shrink_findings(rep, out_root=repro_dir,
                                    message_phase=mp, limit=2)
        rep["shrunk"] = shrunk
        for s in shrunk:
            _print(quiet,
                   f"  ! case {s['case_id']}: {s['verdict']} — "
                   f"{s['detail']}; shrunk {s['instrs_before']} -> "
                   f"{s['instrs_after']} instrs ({s['runs']} runs)")
        if repro_dir:
            _print(quiet, f"   repros written under {repro_dir}")
        if flight_dir:
            _print(quiet, f"   flight-recorder incidents under "
                          f"{flight_dir}")
    return rep


def main(argv=None) -> int:
    args = build_parser().parse_args(argv)
    if args.list_scopes:
        from ue22cs343bb1_openmp_assignment_tpu.analysis import model_check
        for name, scope in model_check.builtin_scopes().items():
            d = scope.describe()
            print(f"{name}: {d['num_nodes']} nodes, programs "
                  f"{d['programs']}")
        return 0

    report = {"model_check": {}, "lint": None, "jaxpr": None,
              "fuzz": None}
    ok, exhausted = True, False
    if not args.skip_model_check:
        report["model_check"] = run_model_check(
            args.scopes, args.mutation, args.max_states, args.quiet)
        for r in report["model_check"].values():
            if r.get("budget_exhausted"):
                exhausted = True
            else:
                ok &= r["ok"]
    if not args.skip_lint:
        report["lint"] = run_lint(args.lint_paths, args.quiet)
        ok &= report["lint"]["ok"]
    if args.jaxpr:
        report["jaxpr"] = run_jaxpr(args.quiet)
        ok &= report["jaxpr"]["ok"]
    if args.fuzz > 0:
        report["fuzz"] = run_fuzz(args.fuzz, args.seed, args.mutation,
                                  args.repro_dir, args.quiet,
                                  flight_dir=args.flight_dir)
        ok &= report["fuzz"]["ok"]
    report["ok"] = bool(ok and not exhausted)

    if args.json_path:
        with open(args.json_path, "w") as fh:
            json.dump(report, fh, indent=2, sort_keys=True)
        _print(args.quiet, f"report written to {args.json_path}")

    if not ok:
        print("analyze: FAIL")
        return 1
    if exhausted:
        print("analyze: BUDGET EXHAUSTED (no finding — not a pass)")
        return 3
    print("analyze: PASS")
    return 0


if __name__ == "__main__":
    sys.exit(main())
