"""Small-scope explicit-state model checker for the coherence protocol.

The dynamic checker (ops/invariants.py) judges only the states a
particular workload happens to reach. This pass gives the complementary
*static* guarantee over tiny configurations: enumerate EVERY state a
2–3-node, 1–2-address machine can reach under ALL message
interleavings, and verify the protocol on the whole graph. The
transition oracle is the *shipped engine itself* — each explored
transition stages a concrete :class:`~..state.SimState` and runs one
real ``ops.step.cycle`` (so ``ops/handlers.py`` + ``ops/frontend.py``
are the checked artifact, never a re-model of them).

**Interleaving semantics.** One node acts per step: either it dequeues
and handles its head message, or (empty queue, not blocked, trace
remaining) it fetches one instruction. Handlers only ever write the
processing node's own state row and communicate via messages
(``assignment.c:190-618``), so every synchronous engine cycle is a
linearization of these per-node steps — the one-at-a-time graph covers
all cross-sender arbitration orders the engine's seedable ``arb_rank``
can realize, and more. Node isolation uses the engine's own schedule
gate: the acting node gets ``issue_delay=0``, everyone else
``issue_delay=BIG`` (and only the acting node's queue is staged), so
exactly one node moves per oracle call.

**Checks.**

* *handler coverage* — every dequeued (message, receiver-state) pair
  must engage the handler matrix (some masked update, wait-flag clear,
  or outgoing candidate). A silent no-op is flagged unless it is a
  reference-sanctioned one (INV on a tag mismatch,
  ``assignment.c:389-399``).
* *engine-tier invariants* — :func:`..ops.invariants.step_predicates`
  must hold on every reachable state (shared definitions, not copies).
* *coherence tier* — :func:`..ops.invariants.quiescent_predicates` at
  every quiescent terminal state. Findings whose names sit in
  :data:`QUIRK_ALLOWLIST` are reported as sanctioned reference quirks
  (SURVEY §2: the protocol tracks no INV-acks, so a racing fill can
  legally strand a stale copy); everything else is a genuine violation.
* *progress* — no deadlock (terminal state with a blocked node) and no
  livelock: Tarjan SCCs of the reachable graph, flagging every strongly
  connected component with no path to a terminal state and rendering a
  lasso witness (stem + the message cycle itself).

**Symmetry reduction (Murφ-style, Ip & Dill).** Node ids and memory
blocks are scalarsets: any node permutation σ (composed with a
cache-index-preserving block permutation β) that maps the per-node
programs and the initial state onto themselves is an automorphism of
the transition graph — the vectorized handlers only ever compare node
ids for equality (home/second/sender tests, bit masks) and never order
them, except ``ctz`` owner selection, which on reachable states is
applied to singleton sharer sets only (the `em_not_single_owner`
invariant) and therefore commutes with σ. The checker computes this
automorphism group once per scope, then stores only the lexicographic
minimum of each successor's orbit; counterexample paths un-permute
each edge on the way out so rendered witnesses are concrete runs.
Scopes whose programs need symmetric initial memory opt in via
``Scope(mem_uniform=True)`` (the reference's ``20*t + i`` pattern is
node-asymmetric and would collapse the group to the identity).

Reports are machine-readable dicts (JSON-stable ordering) with
counterexample paths from the initial state; analysis/runner.py renders
the human diff-style view. analysis/mutations.py seeds handler bugs
this checker must catch — its regression suite.
"""

from __future__ import annotations

import dataclasses
import itertools

import jax
import numpy as np

from ue22cs343bb1_openmp_assignment_tpu import codec
from ue22cs343bb1_openmp_assignment_tpu.config import SystemConfig
from ue22cs343bb1_openmp_assignment_tpu.ops import handlers, invariants, \
    mailbox, step
from ue22cs343bb1_openmp_assignment_tpu.state import (LAT_BUCKETS, MB_BV0,
                                                      MB_TYPE, Metrics,
                                                      SimState, init_state)
from ue22cs343bb1_openmp_assignment_tpu.types import (CACHE_STATE_NAMES,
                                                      DIR_STATE_NAMES,
                                                      CacheState, DirState,
                                                      Msg, Op)

# blocks the frontend issue gate for non-acting nodes (state.issue_delay)
BIG_DELAY = 1 << 20
# fixed oracle batch width: every vmapped call shares one compilation
_BATCH = 64

# Coherence-tier findings the *reference protocol itself* produces at
# quiescence — reported, never silenced, and never counted as failures.
# Root cause for all of them: the protocol tracks no INV-acks
# (``assignment.c:358-361``), so an INV that races an in-flight fill can
# be processed first (tag mismatch -> sanctioned no-op), after which the
# fill installs a copy the directory no longer knows about; the
# blind-by-index WRITEBACK handlers (quirk 5, ``assignment.c:558,586``)
# can similarly resurrect a stale line. Both orderings are legal
# reference behavior (SURVEY §4's accepted run_* variants).
QUIRK_ALLOWLIST = {
    "valid_line_unknown_to_home":
        "stale copy from the unacked-INV race: the directory dropped "
        "this sharer while its fill was in flight (assignment.c:358-361)",
    "phantom_sharers":
        "copy census vs directory popcount disagrees wherever a stale "
        "line survives the unacked-INV race",
    "owner_with_other_copies":
        "the new owner coexists with the stale copy the unacked INV "
        "failed to kill (assignment.c:358-361)",
    "clean_line_stale_value":
        "a stale SHARED copy keeps the pre-race value after home memory "
        "moved on (the reference would serve the same stale read)",
    "shared_line_dir_unowned":
        "stale SHARED copy outliving its directory entry "
        "(EVICT/INV race; blind-by-index writes, quirk 5)",
    "exclusive_line_dir_not_em":
        "directory-update timing (quirk 4): a stale FLUSH from a "
        "superseded WRITEBACK_INT demotes the directory to S after a "
        "racing write already granted EM, and the FLUSH_INVACK home "
        "handler restores only the bitvector, never the state "
        "(assignment.c:199-210,455-457,510-529)",
}


class ScopeTooLarge(RuntimeError):
    """Raised when a scope's reachable graph exceeds max_states."""


@dataclasses.dataclass(frozen=True)
class Scope:
    """One model-checking configuration: dimensions + per-node programs."""

    name: str
    cfg: SystemConfig
    programs: tuple  # per node: tuple of (Op, addr, value)
    # node-symmetric initial memory (block i of every node starts i&0xFF)
    # instead of the reference's node-asymmetric 20*t+i pattern — required
    # for any scope that wants a nontrivial symmetry group
    mem_uniform: bool = False

    def __post_init__(self):
        if self.cfg.bitvec_words != 1 or self.cfg.msg_bitvec_words != 1:
            raise ValueError("scopes assume 1-word sharer bitvectors")
        if self.cfg.inv_mode != "mailbox":
            raise ValueError("scopes drive the exact-reference mailbox "
                             "INV path")
        if len(self.programs) != self.cfg.num_nodes:
            raise ValueError("need exactly one program per node")
        if max(len(p) for p in self.programs) > self.cfg.max_instrs:
            raise ValueError("program longer than cfg.max_instrs")

    def describe(self) -> dict:
        return {
            "name": self.name,
            "num_nodes": self.cfg.num_nodes,
            "cache_size": self.cfg.cache_size,
            "mem_size": self.cfg.mem_size,
            "mem_init": "uniform" if self.mem_uniform else "reference",
            "programs": [[[Op(op).name, int(a), int(v)] for op, a, v in p]
                         for p in self.programs],
        }


def builtin_scopes() -> dict:
    """The shipped small scopes (all addresses home on node 0).

    * ``2n1a`` — 2 nodes, one address, read/write races on it: the
      READ/WRITE_REQUEST, REPLY_*, WRITEBACK_*, FLUSH*, UPGRADE and INV
      paths, including every home==requester dedup quirk.
    * ``2n2a`` — 2 nodes, two addresses conflicting on one direct-mapped
      line: adds the EVICT_SHARED / EVICT_MODIFIED replacement paths.
    * ``3n1a`` — 3 nodes, one address: multi-sharer directory states,
      REPLY_ID fan-out to >1 sharer, EVICT_SHARED owner promotion.
    * ``2n1a_r`` — 2 nodes, one remote-homed address, reads only. The
      liveness scope: in the write scopes a lost reply-unblock is
      masked by quirk 2 (FLUSH/FLUSH_INVACK clear `waiting`
      unconditionally, ``assignment.c:322,535``), so write traffic
      rescues a stranded reader; with reads only, every reply must do
      its own unblocking or the checker sees a deadlock.
    * ``4n1a_sym`` — 4 nodes, one address, one writer racing THREE
      readers: deeper REPLY_ID fan-out, three-way unacked-INV races,
      multi-sharer EVICT promotion chains. Only tractable under the
      state cap because the three readers are interchangeable: the
      S3 automorphism group over nodes {1,2,3} (order 6) folds their
      interleavings into one orbit representative each.
    * ``2n2h`` — 2 nodes, TWO homed addresses (one per node), each
      node writing the remote-homed block then reading its own: both
      directories active at once, crossing request/reply traffic,
      write-miss-on-remote + read-after-invalidate on every
      interleaving. The swap (σ=(01) with the two addresses exchanged)
      is an automorphism — the scope is checked modulo that mirror.
    """
    cfg2 = SystemConfig(num_nodes=2, cache_size=1, mem_size=2,
                        queue_capacity=16, max_instrs=4, inv_mode="mailbox")
    a = codec.make_address(cfg2, 0, 0)
    b = codec.make_address(cfg2, 0, 1)
    r = codec.make_address(cfg2, 1, 0)
    cfg3 = SystemConfig(num_nodes=3, cache_size=1, mem_size=2,
                        queue_capacity=16, max_instrs=4, inv_mode="mailbox")
    a3 = codec.make_address(cfg3, 0, 0)
    cfg4 = SystemConfig(num_nodes=4, cache_size=1, mem_size=2,
                        queue_capacity=16, max_instrs=4, inv_mode="mailbox")
    a4 = codec.make_address(cfg4, 0, 0)
    R, W = int(Op.READ), int(Op.WRITE)
    scopes = [
        Scope("2n1a", cfg2, (
            ((R, a, 0), (W, a, 5)),
            ((W, a, 7), (R, a, 0)),
        )),
        Scope("2n2a", cfg2, (
            ((W, a, 3), (R, b, 0), (R, a, 0)),
            ((W, b, 9), (R, a, 0)),
        )),
        Scope("3n1a", cfg3, (
            ((R, a3, 0),),
            ((R, a3, 0),),
            ((W, a3, 4),),
        )),
        Scope("2n1a_r", cfg2, (
            ((R, r, 0),),
            ((R, r, 0),),
        )),
        Scope("4n1a_sym", cfg4, (
            ((W, a4, 5),),
            ((R, a4, 0),),
            ((R, a4, 0),),
            ((R, a4, 0),),
        ), mem_uniform=True),
        Scope("2n2h", cfg2, (
            ((W, r, 5), (R, a, 0)),
            ((W, a, 5), (R, r, 0)),
        ), mem_uniform=True),
    ]
    return {s.name: s for s in scopes}


@dataclasses.dataclass(frozen=True)
class AState:
    """Canonical (hashable) abstraction of one machine state.

    Everything transition-relevant and nothing else: cache/memory/
    directory contents, per-node trace position, block flag, the
    latched in-flight instruction (quirk 1 fills read it — and quirk 2
    can clear `waiting` with the reply still in flight, so the latch
    matters even for non-waiting nodes), and per-node FIFO message
    queues. Excluded as observationally irrelevant: cycle counters,
    metrics, waiting_since, mailbox ring phase (head position).
    """

    cache_addr: tuple   # [N][C]
    cache_val: tuple
    cache_state: tuple
    memory: tuple       # [N][M]
    dir_state: tuple
    dir_bitvec: tuple   # [N][M] ints (single u32 word)
    instr_idx: tuple    # [N]
    waiting: tuple      # [N] bool
    cur_op: tuple       # [N]
    cur_addr: tuple
    cur_val: tuple
    queues: tuple       # [N] tuples of (type, sender, addr, value,
                        #                second, dirstate, bv_word)
    # per-node tuple of observed READ values in program order — the
    # litmus "registers". Empty (the default) outside litmus mode;
    # ModelChecker(track_obs=True) seeds it with N empty tuples and
    # appends at each read-retire boundary. Part of state identity:
    # outcomes are PATH properties, so two machine states that differ
    # only in what their reads already returned must not merge.
    obs: tuple = ()


def _t2(arr) -> tuple:
    return tuple(tuple(int(x) for x in row) for row in np.asarray(arr))


def _t1(arr) -> tuple:
    return tuple(int(x) for x in np.asarray(arr))


def enabled_events(scope: Scope, a: AState) -> list:
    """Events runnable from `a`: per node, dequeue-one-message XOR
    fetch-one-instruction — the reference's drain-first priority
    (``assignment.c:165-177,624-629``) per node."""
    evs = []
    for n in range(scope.cfg.num_nodes):
        if a.queues[n]:
            evs.append(("msg", n))
        elif not a.waiting[n] and a.instr_idx[n] < len(scope.programs[n]) - 1:
            evs.append(("instr", n))
    return evs


# ---------------------------------------------------------------------------
# symmetry: node/address permutation automorphisms
# ---------------------------------------------------------------------------

# message types whose `second` field carries a live node id (the
# original requester); every other handler leaves/reads it as literal 0
# (handlers.py pri_second/sec_second selects), so permuting a dead field
# would fabricate states the engine never produces
_SECOND_LIVE = frozenset((int(Msg.WRITEBACK_INT), int(Msg.WRITEBACK_INV),
                          int(Msg.FLUSH), int(Msg.FLUSH_INVACK)))


@dataclasses.dataclass(frozen=True)
class _Perm:
    """One automorphism: node permutation σ + block permutation β.

    ``amap`` is the induced address map (home(addr) through σ, block
    through β); β is constrained to preserve cache_index so a line
    never changes its direct-mapped slot under the action.
    """

    sig: tuple        # σ[n] = image of node n
    inv_sig: tuple
    beta: tuple       # β[b] = image of block b
    inv_beta: tuple
    amap: tuple       # addr -> addr over all (home, block) addresses
    bvmap: tuple      # sharer-bitvector word -> permuted word (2^N entries)

    @property
    def is_identity(self) -> bool:
        return (self.sig == tuple(range(len(self.sig)))
                and self.beta == tuple(range(len(self.beta))))


def _make_perm(cfg: SystemConfig, sig, beta) -> _Perm:
    N, M = cfg.num_nodes, cfg.mem_size
    inv_sig = [0] * N
    for n, j in enumerate(sig):
        inv_sig[j] = n
    inv_beta = [0] * M
    for b, j in enumerate(beta):
        inv_beta[j] = b
    amap = [0] * (N << cfg.block_bits)
    for h in range(N):
        for b in range(M):
            src = codec.make_address(cfg, h, b)
            amap[src] = codec.make_address(cfg, sig[h], beta[b])
    bvmap = []
    for w in range(1 << N):
        out = 0
        for n in range(N):
            if (w >> n) & 1:
                out |= 1 << sig[n]
        bvmap.append(out)
    return _Perm(tuple(sig), tuple(inv_sig), tuple(beta), tuple(inv_beta),
                 tuple(amap), tuple(bvmap))


def _apply_perm(cfg: SystemConfig, g: _Perm, a: AState) -> AState:
    """The group action on abstract states: relabel every node-id- and
    address-valued field; permute rows by σ and block columns by β."""
    if g.is_identity:
        return a
    N, C, M = cfg.num_nodes, cfg.cache_size, cfg.mem_size
    n_addr = len(g.amap)

    def ra(addr):  # remap a (possibly sentinel) address value
        return g.amap[addr] if 0 <= addr < n_addr else addr

    cache_addr, cache_val, cache_state = [], [], []
    memory, dir_state, dir_bitvec = [], [], []
    instr_idx, waiting = [], []
    cur_op, cur_addr, cur_val, queues = [], [], [], []
    for j in range(N):
        n = g.inv_sig[j]
        cache_addr.append(tuple(ra(a.cache_addr[n][c]) for c in range(C)))
        cache_val.append(a.cache_val[n])
        cache_state.append(a.cache_state[n])
        memory.append(tuple(a.memory[n][g.inv_beta[b]] for b in range(M)))
        dir_state.append(tuple(a.dir_state[n][g.inv_beta[b]]
                               for b in range(M)))
        dir_bitvec.append(tuple(g.bvmap[a.dir_bitvec[n][g.inv_beta[b]]]
                                for b in range(M)))
        instr_idx.append(a.instr_idx[n])
        waiting.append(a.waiting[n])
        cur_op.append(a.cur_op[n])
        cur_val.append(a.cur_val[n])
        # a never-fetched node's latch is identically (0, 0, 0) — its
        # fields are dead until the first fetch overwrites them, so
        # remapping would fabricate unreachable states
        cur_addr.append(ra(a.cur_addr[n]) if a.instr_idx[n] >= 0
                        else a.cur_addr[n])
        queues.append(tuple(
            (t, g.sig[s], ra(ad), val,
             g.sig[sec] if t in _SECOND_LIVE else sec, ds, g.bvmap[bv])
            for (t, s, ad, val, sec, ds, bv) in a.queues[n]))
    return AState(
        cache_addr=tuple(cache_addr), cache_val=tuple(cache_val),
        cache_state=tuple(cache_state), memory=tuple(memory),
        dir_state=tuple(dir_state), dir_bitvec=tuple(dir_bitvec),
        instr_idx=tuple(instr_idx), waiting=tuple(waiting),
        cur_op=tuple(cur_op), cur_addr=tuple(cur_addr),
        cur_val=tuple(cur_val), queues=tuple(queues),
        # observed values are data, not node ids — only the rows move
        obs=tuple(a.obs[g.inv_sig[j]] for j in range(N)) if a.obs else ())


def _akey(a: AState) -> tuple:
    """Total order over AStates for orbit canonicalization."""
    return (a.cache_addr, a.cache_val, a.cache_state, a.memory,
            a.dir_state, a.dir_bitvec, a.instr_idx, a.waiting,
            a.cur_op, a.cur_addr, a.cur_val, a.queues, a.obs)


def symmetry_group(scope: Scope, a0: AState) -> list:
    """All (σ, β) automorphisms of the scope: β preserves cache_index,
    the per-node programs map onto each other (programs[σ[n]] equals
    node n's program with every address pushed through the induced
    amap), and the initial state is a fixed point. Identity first."""
    cfg = scope.cfg
    N, M, C = cfg.num_nodes, cfg.mem_size, cfg.cache_size
    out = []
    block_perms = [p for p in itertools.permutations(range(M))
                   if all(p[b] % C == b % C for b in range(M))]
    if len(block_perms) > 64:          # scalarset guard for huge scopes
        block_perms = [tuple(range(M))]
    for sig in itertools.permutations(range(N)):
        for beta in block_perms:
            g = _make_perm(cfg, sig, beta)
            if any(tuple((op, g.amap[ad], v) for op, ad, v in
                         scope.programs[n]) != scope.programs[sig[n]]
                   for n in range(N)):
                continue
            if _apply_perm(cfg, g, a0) != a0:
                continue
            out.append(g)
    out.sort(key=lambda g: (not g.is_identity, g.sig, g.beta))
    return out


class ModelChecker:
    """Explicit-state BFS over one scope's reachable graph.

    ``message_phase`` swaps in a (possibly mutated) handler phase with
    the signature of :func:`..ops.handlers.message_phase`; the engine
    around it stays the shipped one (ops/step.cycle's override hook).
    """

    def __init__(self, scope: Scope, message_phase=None,
                 max_states: int = 50_000, track_obs: bool = False,
                 final_addrs: tuple = ()):
        """``track_obs=True`` switches on litmus mode: every READ retire
        appends its observed value to the node's AState.obs register
        tape, and the report gains an ``outcomes`` key — the sorted set
        of (read observations in node-major program order + final
        values of ``final_addrs``) over all quiescent terminal states,
        closed under the symmetry group. Off by default: the default
        report stays byte-identical (obs stays the empty tuple, which
        canonicalizes away)."""
        self.scope = scope
        self.cfg = scope.cfg
        self.max_states = max_states
        self.track_obs = track_obs
        self.final_addrs = tuple(final_addrs)
        mp = message_phase if message_phase is not None \
            else handlers.message_phase
        cfg = self.cfg

        def one(state):
            new_state = step.cycle(cfg, state, message_phase=mp)
            # handler-engagement probe on the SAME staged state: did the
            # dequeued message trigger any masked write, wait clear, or
            # outgoing candidate at its receiver?
            mv, _, _ = mailbox.dequeue(cfg, state)
            upd, cand, inv_scatter, _ = mp(cfg, state, mv)
            engaged = (upd["cache_state"][0] | upd["cache_addr"][0]
                       | upd["mem"][0] | upd["dir_state"][0]
                       | upd["dir_bv"][0] | upd["wait_clear"])
            import jax.numpy as jnp
            for part in ("pri", "sec", "ev"):
                engaged = engaged | (cand[part][0] != int(Msg.NONE))
            if cand["inv"][0] is not None:
                engaged = engaged | jnp.any(
                    cand["inv"][0] != int(Msg.NONE), axis=1)
            if inv_scatter is not None:
                engaged = engaged | inv_scatter[0]
            return new_state, engaged

        self._oracle = jax.jit(jax.vmap(one))
        self._step_preds = jax.jit(jax.vmap(
            lambda s: invariants.step_predicates(cfg, s)))
        self._quiet_preds = jax.jit(jax.vmap(
            lambda s: invariants.quiescent_predicates(cfg, s)))
        self._instr_arrays = self._build_instr_arrays()
        self._fault_key = np.asarray(
            jax.device_get(init_state(cfg).fault_key), np.uint32)
        self._a0 = self._initial()
        self._build_sym(self._a0)

    # -- symmetry ----------------------------------------------------------

    def _build_sym(self, a0: AState) -> None:
        """Automorphism group + composition/inverse tables (group order
        is tiny — ≤ |S_N| on these scopes — so dense tables are free)."""
        cfg = self.cfg
        self._group = symmetry_group(self.scope, a0)
        G = len(self._group)
        idx = {(g.sig, g.beta): i for i, g in enumerate(self._group)}
        self._mul = [[0] * G for _ in range(G)]   # mul[i][j] = g_i ∘ g_j
        self._ginv = [0] * G
        for i, gi in enumerate(self._group):
            for j, gj in enumerate(self._group):
                sig = tuple(gi.sig[s] for s in gj.sig)
                beta = tuple(gi.beta[b] for b in gj.beta)
                k = idx[(sig, beta)]
                self._mul[i][j] = k
                if k == 0:
                    self._ginv[i] = j

    def _canon(self, a: AState):
        """(orbit representative, index of the g with g·a = canon)."""
        if len(self._group) == 1:
            return a, 0
        best, bk, bi = a, _akey(a), 0
        for i in range(1, len(self._group)):
            p = _apply_perm(self.cfg, self._group[i], a)
            k = _akey(p)
            if k < bk:
                best, bk, bi = p, k, i
        return best, bi

    # -- staging: AState -> concrete SimState (numpy leaves) --------------

    def _build_instr_arrays(self):
        cfg = self.cfg
        N, T = cfg.num_nodes, cfg.max_instrs
        op = np.full((N, T), int(Op.NOP), np.int32)
        addr = np.zeros((N, T), np.int32)
        val = np.zeros((N, T), np.int32)
        count = np.zeros((N,), np.int32)
        for n, prog in enumerate(self.scope.programs):
            count[n] = len(prog)
            for i, (o, a, v) in enumerate(prog):
                op[n, i], addr[n, i], val[n, i] = int(o), int(a), int(v) & 0xFF
        return op, addr, val, count

    def _stage(self, a: AState, event) -> SimState:
        """Concrete state for one transition: only the acting node can
        move (its queue staged / its issue gate open); everyone else is
        frozen by an empty mailbox + BIG_DELAY. event=None stages the
        whole state verbatim (predicate evaluation)."""
        cfg = self.cfg
        N, Q = cfg.num_nodes, cfg.queue_capacity
        kind, actor = event if event is not None else (None, None)

        mb_pack = np.zeros((7, N, Q), np.int32)
        mb_pack[MB_TYPE] = int(Msg.NONE)
        mb_count = np.zeros((N,), np.int32)
        stage_queues = range(N) if kind is None else \
            ([actor] if kind == "msg" else [])
        for r in stage_queues:
            for i, msg in enumerate(a.queues[r]):
                mb_pack[:6, r, i] = msg[:6]
                mb_pack[MB_BV0, r, i] = np.uint32(msg[6]).view(np.int32)
            mb_count[r] = len(a.queues[r])

        delay = np.full((N,), BIG_DELAY, np.int32)
        if kind == "instr":
            delay[actor] = 0

        waiting = np.asarray(a.waiting, bool)
        op, addr, val, count = self._instr_arrays
        z32 = np.zeros((), np.int32)
        return SimState(
            cache_addr=np.asarray(a.cache_addr, np.int32),
            cache_val=np.asarray(a.cache_val, np.int32),
            cache_state=np.asarray(a.cache_state, np.int32),
            memory=np.asarray(a.memory, np.int32),
            dir_state=np.asarray(a.dir_state, np.int32),
            dir_bitvec=np.asarray(a.dir_bitvec, np.uint32)[..., None],
            instr_op=op, instr_addr=addr, instr_val=val, instr_count=count,
            instr_idx=np.asarray(a.instr_idx, np.int32),
            cur_op=np.asarray(a.cur_op, np.int32),
            cur_addr=np.asarray(a.cur_addr, np.int32),
            cur_val=np.asarray(a.cur_val, np.int32),
            waiting=waiting,
            waiting_since=np.where(waiting, 0, -1).astype(np.int32),
            mb_pack=mb_pack,
            mb_head=np.zeros((N,), np.int32),
            mb_count=mb_count,
            issue_delay=delay,
            issue_period=np.ones((N,), np.int32),
            arb_rank=np.arange(N, dtype=np.int32),
            order_rank=np.zeros((N, 0), np.int32),
            fault_key=self._fault_key,
            cycle=z32,
            metrics=Metrics(
                cycles=z32, instrs_retired=z32, read_hits=z32,
                write_hits=z32, read_misses=z32, write_misses=z32,
                upgrades=z32, msgs_processed=np.zeros((13,), np.int32),
                msgs_dropped=z32, msgs_injected_dropped=z32,
                invalidations=z32, evictions=z32,
                lat_hist=np.zeros((LAT_BUCKETS,), np.int32),
                mb_depth_peak=z32),
        )

    def _read_back(self, a: AState, event, res, k):
        """(next AState, dropped, overflowed) from oracle output row k."""
        cfg = self.cfg
        N, Q = cfg.num_nodes, cfg.queue_capacity
        kind, actor = event
        queues, overflow = [], False
        for r in range(N):
            cnt = int(res.mb_count[k, r])
            head = int(res.mb_head[k, r])
            ring = []
            for i in range(cnt):
                slot = (head + i) % Q
                f = res.mb_pack[k, :, r, slot]
                ring.append((int(f[0]), int(f[1]), int(f[2]), int(f[3]),
                             int(f[4]), int(f[5]),
                             int(np.int32(f[MB_BV0]).view(np.uint32))))
            if kind == "msg" and r == actor:
                # staged ring = the full abstract queue; what remains in
                # it (plus self-sends) IS the next queue
                q = tuple(ring)
            else:
                # staged empty: ring holds only this step's deliveries
                q = a.queues[r] + tuple(ring)
            if len(q) > Q:
                overflow, q = True, q[:Q]
            queues.append(q)
        new = AState(
            cache_addr=_t2(res.cache_addr[k]),
            cache_val=_t2(res.cache_val[k]),
            cache_state=_t2(res.cache_state[k]),
            memory=_t2(res.memory[k]),
            dir_state=_t2(res.dir_state[k]),
            dir_bitvec=_t2(res.dir_bitvec[k][..., 0]),
            instr_idx=_t1(res.instr_idx[k]),
            waiting=tuple(bool(x) for x in np.asarray(res.waiting[k])),
            cur_op=_t1(res.cur_op[k]),
            cur_addr=_t1(res.cur_addr[k]),
            cur_val=_t1(res.cur_val[k]),
            queues=tuple(queues),
            obs=a.obs)
        if self.track_obs:
            # read-retire boundary? Same rule as the engine's obs_retire
            # ledger plane: a READ retires either at its fetch step (hit
            # — fetch without opening a wait) or at the step that clears
            # its wait (fill / early unblock, quirk 2 included).
            retired_addr = None
            if kind == "instr":
                op, addr, _ = self.scope.programs[actor][
                    new.instr_idx[actor]]
                if Op(op) == Op.READ and not new.waiting[actor]:
                    retired_addr = addr
            elif (a.waiting[actor] and not new.waiting[actor]
                  and a.cur_op[actor] == int(Op.READ)):
                retired_addr = a.cur_addr[actor]
            if retired_addr is not None:
                obs = list(new.obs)
                obs[actor] = obs[actor] + (
                    self._observe(new, actor, retired_addr),)
                new = dataclasses.replace(new, obs=tuple(obs))
        return new, int(res.metrics.msgs_dropped[k]), overflow

    def _observe(self, a: AState, node: int, addr: int) -> int:
        """The engine's read-observation rule (ops/step.py obs_val):
        the retiring node's own cache line for `addr`, or -1 when the
        line is absent/INVALID at retire."""
        cidx = codec.cache_index(self.cfg, addr)
        if (a.cache_addr[node][cidx] == addr
                and a.cache_state[node][cidx] != int(CacheState.INVALID)):
            return a.cache_val[node][cidx]
        return -1

    def _final_value(self, a: AState, addr: int) -> int:
        """Authoritative value of `addr` at quiescence: the EM owner's
        cache line when the directory records an owner (memory may be
        stale behind a MODIFIED line), home memory otherwise."""
        cfg = self.cfg
        h = codec.home_node(cfg, addr)
        b = codec.block_index(cfg, addr)
        if a.dir_state[h][b] == int(DirState.EM):
            bv = a.dir_bitvec[h][b]
            cidx = codec.cache_index(cfg, addr)
            for n in range(cfg.num_nodes):
                if ((bv >> n) & 1
                        and a.cache_addr[n][cidx] == addr
                        and a.cache_state[n][cidx]
                        != int(CacheState.INVALID)):
                    return a.cache_val[n][cidx]
        return a.memory[h][b]

    def _outcome(self, a: AState) -> tuple:
        """One concrete litmus outcome: every read observation in
        node-major program order, then final_addrs' final values."""
        reads = tuple(v for n in range(self.cfg.num_nodes)
                      for v in a.obs[n])
        return reads + tuple(self._final_value(a, ad)
                             for ad in self.final_addrs)

    def _initial(self) -> AState:
        st = jax.device_get(
            init_state(self.cfg, traces=[list(p) for p in
                                         self.scope.programs]))
        memory = _t2(st.memory)
        if self.scope.mem_uniform:
            memory = tuple(
                tuple(i & 0xFF for i in range(self.cfg.mem_size))
                for _ in range(self.cfg.num_nodes))
        return AState(
            cache_addr=_t2(st.cache_addr), cache_val=_t2(st.cache_val),
            cache_state=_t2(st.cache_state), memory=memory,
            dir_state=_t2(st.dir_state),
            dir_bitvec=_t2(st.dir_bitvec[..., 0]),
            instr_idx=_t1(st.instr_idx),
            waiting=tuple(bool(x) for x in np.asarray(st.waiting)),
            cur_op=_t1(st.cur_op), cur_addr=_t1(st.cur_addr),
            cur_val=_t1(st.cur_val),
            queues=tuple(() for _ in range(self.cfg.num_nodes)),
            obs=(tuple(() for _ in range(self.cfg.num_nodes))
                 if self.track_obs else ()))

    def _batched(self, staged: list):
        pad = _BATCH - len(staged)
        staged = staged + [staged[0]] * pad
        return jax.tree_util.tree_map(lambda *xs: np.stack(xs), *staged)

    # -- message/pair coverage --------------------------------------------

    def _pair_key(self, a: AState, actor: int):
        """(msg type, home/remote, cache-line state, tag match, dir
        state at receiver) of the head message `actor` is about to
        process — the coverage cell of the handler matrix."""
        cfg = self.cfg
        t, _, addr = a.queues[actor][0][:3]
        at_home = codec.home_node(cfg, addr) == actor
        cidx = codec.cache_index(cfg, addr)
        block = codec.block_index(cfg, addr)
        return (Msg(t).name,
                "home" if at_home else "remote",
                CACHE_STATE_NAMES[a.cache_state[actor][cidx]],
                a.cache_addr[actor][cidx] == addr,
                DIR_STATE_NAMES[a.dir_state[actor][block]]
                if at_home else "-")

    @staticmethod
    def _pair_str(pair) -> str:
        t, loc, cs, tag, ds = pair
        tagtxt = "" if tag else " tag-miss"
        dirtxt = f" dir={ds}" if ds != "-" else ""
        return f"{t}@{loc} cache={cs}{tagtxt}{dirtxt}"

    @staticmethod
    def _sanctioned_noop(pair) -> str | None:
        t, _, _, tag, _ = pair
        if t == "INV" and not tag:
            return ("INV on a tag mismatch is the reference's sanctioned "
                    "no-op (assignment.c:389-399): the targeted line was "
                    "already replaced or never filled")
        return None

    # -- rendering ---------------------------------------------------------

    def _render_event(self, src: AState, ev) -> str:
        kind, n = ev
        if kind == "instr":
            op, addr, val = self.scope.programs[n][src.instr_idx[n] + 1]
            w = Op(op) == Op.WRITE
            return (f"node{n} {'W' if w else 'R'} 0x{addr:02x}"
                    + (f"={val}" if w else ""))
        t, sender, addr, value, second, _, bv = src.queues[n][0]
        extra = f" bv={bv:b}" if bv else ""
        return (f"node{n} <- {Msg(t).name} from node{sender} "
                f"0x{addr:02x} val={value} second={second}{extra}")

    def render_state(self, a: AState) -> list:
        cfg, lines = self.cfg, []
        for n in range(cfg.num_nodes):
            cache = " ".join(
                f"[0x{a.cache_addr[n][c]:02x} v={a.cache_val[n][c]} "
                f"{CACHE_STATE_NAMES[a.cache_state[n][c]]}]"
                for c in range(cfg.cache_size))
            d = " ".join(
                f"{DIR_STATE_NAMES[a.dir_state[n][m]]}"
                f":{a.dir_bitvec[n][m]:b}" for m in range(cfg.mem_size))
            q = ", ".join(Msg(m[0]).name for m in a.queues[n]) or "-"
            flag = " WAITING" if a.waiting[n] else ""
            lines.append(f"node{n}: cache {cache} mem={list(a.memory[n])} "
                         f"dir {d} q=[{q}]{flag}")
        return lines

    # -- the run ------------------------------------------------------------

    def run(self) -> dict:
        scope, cfg = self.scope, self.cfg
        a0 = self._a0            # group-invariant, so already canonical
        ids = {a0: 0}
        states = [a0]
        parent = [None]          # per id: (pred_id, event, perm_idx) | None
        adj = [[]]               # per id: list of (event, dst_id)
        terminals = []
        engaged_pairs = {}       # pair -> [count, first_state_id]
        noop_pairs = {}
        violations = []
        n_msg = n_instr = 0

        frontier = [0]
        while frontier:
            jobs = []
            for sid in frontier:
                evs = enabled_events(scope, states[sid])
                if not evs:
                    terminals.append(sid)
                jobs.extend((sid, ev) for ev in evs)
            nxt = []
            for start in range(0, len(jobs), _BATCH):
                chunk = jobs[start:start + _BATCH]
                batch = self._batched(
                    [self._stage(states[sid], ev) for sid, ev in chunk])
                res, engaged = self._oracle(batch)
                res = jax.device_get(res)
                engaged = np.asarray(engaged)
                for j, (sid, ev) in enumerate(chunk):
                    new_a, dropped, ovf = self._read_back(
                        states[sid], ev, res, j)
                    new_a, gi = self._canon(new_a)
                    if dropped or ovf:
                        violations.append({
                            "check": "scope_overflow",
                            "name": "scope_overflow",
                            "detail": "mailbox capacity exceeded inside "
                                      "the scope — enlarge queue_capacity",
                            "state": sid,
                            "path": self.path_to(parent, states, sid)})
                    if ev[0] == "msg":
                        n_msg += 1
                        pair = self._pair_key(states[sid], ev[1])
                        bucket = engaged_pairs if bool(engaged[j, ev[1]]) \
                            else noop_pairs
                        if pair not in bucket:
                            bucket[pair] = [0, sid]
                        bucket[pair][0] += 1
                    else:
                        n_instr += 1
                    nid = ids.get(new_a)
                    if nid is None:
                        nid = len(states)
                        ids[new_a] = nid
                        states.append(new_a)
                        parent.append((sid, ev, gi))
                        adj.append([])
                        nxt.append(nid)
                        if nid >= self.max_states:
                            raise ScopeTooLarge(
                                f"scope {scope.name}: > {self.max_states} "
                                "states")
                    adj[sid].append((ev, nid))
            frontier = nxt

        # ---- progress: deadlock + livelock -------------------------------
        quiescent_terms, deadlocks = [], []
        for sid in terminals:
            if any(states[sid].waiting):
                deadlocks.append(sid)
            else:
                quiescent_terms.append(sid)
        for sid in deadlocks:
            path, fin = self._trace_to(parent, states, sid)
            violations.append({
                "check": "deadlock",
                "name": "deadlock",
                "detail": "terminal state with a blocked node (a reply "
                          "was lost or never clears `waiting`)",
                "state": sid,
                "path": path,
                "state_render": self.render_state(fin)})

        # livelock: Tarjan SCCs of the reachable graph; every component
        # with no path to a terminal is a genuine non-progress trap, and
        # a cycle inside it is the lasso witness. (Tarjan emits SCCs in
        # reverse topological order of the condensation, so one forward
        # pass over the emission order resolves can-reach-terminal.)
        comp_id, comps = self._sccs(adj)
        is_term = [False] * len(states)
        for t in terminals:
            is_term[t] = True
        comp_can = [False] * len(comps)
        for ci, members in enumerate(comps):
            ok = any(is_term[v] for v in members)
            if not ok:
                ok = any(comp_can[comp_id[d]]
                         for v in members for _, d in adj[v]
                         if comp_id[d] != ci)
            comp_can[ci] = ok
        stuck_comps = [ci for ci in range(len(comps)) if not comp_can[ci]]
        if stuck_comps:
            n_stuck = sum(len(comps[ci]) for ci in stuck_comps)
            # witness: a stuck SCC that contains a cycle (a stuck state
            # always leads into one — the graph is finite)
            wit = next(
                (ci for ci in stuck_comps
                 if len(comps[ci]) > 1
                 or any(d == comps[ci][0] for _, d in adj[comps[ci][0]])),
                stuck_comps[0])
            cyc = self._cycle_in(adj, comp_id, wit, comps[wit][0])
            entry = cyc[0][0] if cyc else comps[wit][0]
            path, fin = self._trace_to(parent, states, entry)
            mod = (" (cycle shown modulo node/address relabeling)"
                   if len(self._group) > 1 else "")
            violations.append({
                "check": "livelock",
                "name": "livelock",
                "detail": f"{n_stuck} reachable states in "
                          f"{len(stuck_comps)} SCCs cannot reach any "
                          f"terminal state; lasso witness: stem of "
                          f"{len(path)} events + a {len(cyc)}-event "
                          f"message cycle{mod}",
                "state": entry,
                "path": path,
                "cycle": [self._render_event(states[s], ev)
                          for s, ev in cyc],
                "state_render": self.render_state(fin)})

        # ---- handler coverage --------------------------------------------
        sanctioned_noops = []
        for pair in sorted(noop_pairs):
            why = self._sanctioned_noop(pair)
            count, sid = noop_pairs[pair]
            if why is not None:
                sanctioned_noops.append({
                    "pair": self._pair_str(pair), "count": count,
                    "rationale": why})
            else:
                path, fin = self._trace_to(parent, states, sid)
                violations.append({
                    "check": "unhandled_pair",
                    "name": "unhandled_pair",
                    "detail": f"message silently ignored: "
                              f"{self._pair_str(pair)} "
                              f"({count} occurrences)",
                    "state": sid,
                    "path": path,
                    "state_render": self.render_state(fin)})

        # ---- engine-tier invariants on EVERY reachable state -------------
        step_names = list(invariants.step_violations(
            cfg, init_state(cfg)).keys())
        step_hits = {}
        for start in range(0, len(states), _BATCH):
            chunk = states[start:start + _BATCH]
            batch = self._batched(
                [self._stage(a, None) for a in chunk])
            masks = jax.device_get(self._step_preds(batch))
            for name in step_names:
                bad = np.asarray(masks[name]).reshape(_BATCH, -1).any(axis=1)
                for j in range(len(chunk)):
                    if bad[j] and name not in step_hits:
                        step_hits[name] = start + j
        for name in sorted(step_hits):
            sid = step_hits[name]
            path, fin = self._trace_to(parent, states, sid)
            violations.append({
                "check": "step_invariant", "name": name, "state": sid,
                "detail": f"engine-tier invariant `{name}` violated on a "
                          "reachable state",
                "path": path,
                "state_render": self.render_state(fin)})

        # ---- coherence tier at quiescent terminals -----------------------
        quirks, quiet_hits = {}, {}
        for start in range(0, len(quiescent_terms), _BATCH):
            chunk = quiescent_terms[start:start + _BATCH]
            batch = self._batched(
                [self._stage(states[sid], None) for sid in chunk])
            masks = jax.device_get(self._quiet_preds(batch))
            for name, mask in masks.items():
                bad = np.asarray(mask).reshape(_BATCH, -1).any(axis=1)
                for j, sid in enumerate(chunk):
                    if not bad[j]:
                        continue
                    if name in QUIRK_ALLOWLIST:
                        if name not in quirks:
                            quirks[name] = [0, sid]
                        quirks[name][0] += 1
                    elif name not in quiet_hits:
                        quiet_hits[name] = sid
        for name in sorted(quiet_hits):
            sid = quiet_hits[name]
            path, fin = self._trace_to(parent, states, sid)
            violations.append({
                "check": "coherence", "name": name, "state": sid,
                "detail": f"coherence contract `{name}` violated at a "
                          "quiescent state (not a sanctioned quirk)",
                "path": path,
                "state_render": self.render_state(fin)})

        violations.sort(key=lambda v: (v["check"], v.get("name", ""),
                                       v["state"]))
        report = {
            "scope": scope.describe(),
            "stats": {
                "states": len(states),
                "transitions": n_msg + n_instr,
                "msg_events": n_msg,
                "instr_events": n_instr,
                "terminal_states": len(terminals),
                "quiescent_states": len(quiescent_terms),
                "deadlocked_states": len(deadlocks),
                "symmetry_group_order": len(self._group),
                "sccs": len(comps),
            },
            "coverage": {
                "engaged_pairs": sorted(
                    self._pair_str(p) for p in engaged_pairs),
                "sanctioned_noops": sanctioned_noops,
            },
            "quirks": [
                {"name": name, "states": quirks[name][0],
                 "rationale": QUIRK_ALLOWLIST[name],
                 "example_state": quirks[name][1],
                 "example_path": self.path_to(parent, states,
                                              quirks[name][1])}
                for name in sorted(quirks)],
            "violations": violations,
            "ok": not violations,
        }
        if self.track_obs:
            # stored states are orbit representatives; the concrete
            # outcome set is the orbit closure over the group (permuted
            # states are reachable runs, their outcomes row-permute)
            outs = set()
            for sid in quiescent_terms:
                for g in self._group:
                    outs.add(self._outcome(
                        _apply_perm(cfg, g, states[sid])))
            report["outcomes"] = sorted(outs)
        return report

    def _trace_to(self, parent, states, sid):
        """(rendered concrete event path from the initial state, the
        concrete final AState the path actually lands in).

        Stored states are orbit representatives: edge k records the
        permutation π_k with canon = π_k·(raw successor). Unwinding with
        the accumulated h_k = π_k∘h_{k-1} (concrete state t_k =
        h_k⁻¹·c_k, concrete event f_k = h_{k-1}⁻¹·e_k) turns the
        quotient path back into one genuine run of the machine."""
        edges = []
        while parent[sid] is not None:
            pid, ev, gi = parent[sid]
            edges.append((pid, ev, gi))
            sid = pid
        edges.reverse()
        out, h = [], 0
        for pid, ev, gi in edges:
            hin = self._group[self._ginv[h]]
            src = _apply_perm(self.cfg, hin, states[pid])
            out.append(self._render_event(src, (ev[0], hin.sig[ev[1]])))
            h = self._mul[gi][h]
        final = _apply_perm(self.cfg, self._group[self._ginv[h]],
                            states[sid])
        return out, final

    def path_to(self, parent, states, sid) -> list:
        """Counterexample path: rendered events from the initial state."""
        return self._trace_to(parent, states, sid)[0]

    @staticmethod
    def _sccs(adj):
        """Iterative Tarjan: (comp_id per state, components in emission
        order — reverse topological order of the condensation)."""
        n = len(adj)
        index = [-1] * n
        low = [0] * n
        on = [False] * n
        stack: list = []
        comps: list = []
        comp_id = [-1] * n
        counter = 0
        for root in range(n):
            if index[root] != -1:
                continue
            work = [(root, 0)]
            while work:
                v, pi = work[-1]
                if pi == 0:
                    index[v] = low[v] = counter
                    counter += 1
                    stack.append(v)
                    on[v] = True
                descended = False
                out = adj[v]
                for i in range(pi, len(out)):
                    w = out[i][1]
                    if index[w] == -1:
                        work[-1] = (v, i + 1)
                        work.append((w, 0))
                        descended = True
                        break
                    if on[w]:
                        low[v] = min(low[v], index[w])
                if descended:
                    continue
                work.pop()
                if work:
                    u = work[-1][0]
                    low[u] = min(low[u], low[v])
                if low[v] == index[v]:
                    members = []
                    while True:
                        w = stack.pop()
                        on[w] = False
                        comp_id[w] = len(comps)
                        members.append(w)
                        if w == v:
                            break
                    comps.append(members)
        return comp_id, comps

    @staticmethod
    def _cycle_in(adj, comp_id, ci, v0) -> list:
        """A cycle inside SCC `ci` starting the walk at v0: list of
        (state_id, event) edges. Every vertex of a stuck SCC has an
        in-component out-edge, so the greedy walk must revisit."""
        path: list = []
        pos: dict = {}
        v = v0
        while v not in pos:
            pos[v] = len(path)
            step_edge = next(((e, d) for e, d in adj[v]
                              if comp_id[d] == ci), None)
            if step_edge is None:      # trivial SCC without a self-loop
                return []
            path.append((v, step_edge[0]))
            v = step_edge[1]
        return path[pos[v]:]


def check_scope(scope: Scope, message_phase=None,
                max_states: int = 50_000) -> dict:
    """One-call convenience: build a checker, run it, return the report."""
    return ModelChecker(scope, message_phase=message_phase,
                        max_states=max_states).run()
