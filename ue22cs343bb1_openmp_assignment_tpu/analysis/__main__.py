"""`python -m ue22cs343bb1_openmp_assignment_tpu.analysis` == `cache-sim analyze`."""

import sys

from ue22cs343bb1_openmp_assignment_tpu.analysis.runner import main

if __name__ == "__main__":
    sys.exit(main())
