"""Coverage-guided differential fuzzing: random traces, three engines.

The model checker's scopes are exhaustive but tiny; the fuzzer trades
exhaustiveness for reach — seeded random instruction traces at the
reference dimensions, run differentially against independently written
engines. The async JAX engine and the native C++ oracle implement the
same deterministic cycle model, so under identical schedule knobs they
must agree state-for-state on *any* traffic (the lockstep property
tests/test_native_differential_contended.py pins); the transactional
sync engine joins the comparison on node-local (schedule-independent)
cases. Everything is derived from one ``numpy`` Generator, so a seed
fully determines the corpus and every verdict.

Oracles, in check order (first hit is the verdict):

* ``hang`` — async and native disagree on quiescence within the budget
* ``state`` — an architectural array differs between async and native
* ``invariant`` — engine-tier step invariant nonzero on the final state
* ``consistency`` — the axiomatic checker (analysis/axioms.py) finds a
  po/rf/co/fr axiom violation in the run's message ledger, or a
  litmus-tagged seed (analysis/litmus.py) lands outside its allowed
  outcome set. The check needs a second, ledger-instrumented run of
  the case, so it fires on every litmus-tagged case but only a
  deterministic quarter of untagged ones (``case_id % 4 == 0``) — on
  untagged traffic the bit-exact native state oracle already
  adjudicates the same executions, and the consistency surface it
  cannot see (design-level ordering bugs shared by both engines) is
  exactly what the tagged seeds and the litmus enumeration cover
* ``coherence`` — node-local (race-free) case with a nonzero
  coherence-tier count (must be exactly zero without races)
* ``sync`` — node-local case where the transactional engine disagrees

Coverage signal is :func:`obs.schema.coverage_signature` over the async
run's metrics report plus final directory-state occupancy: a case that
lights up a new (message-type set, latency-bucket set, occupancy)
combination joins the corpus and seeds later mutations; the rest are
discarded. Handler mutants inject through the same ``message_phase``
hook the model checker uses (analysis/mutations.py), so the fuzzer
doubles as the mutation-kill harness for traffic the scopes cannot
reach.
"""

from __future__ import annotations

import dataclasses
import os
from typing import Callable, Optional

import numpy as np

from ue22cs343bb1_openmp_assignment_tpu.config import SystemConfig
from ue22cs343bb1_openmp_assignment_tpu.obs import schema
from ue22cs343bb1_openmp_assignment_tpu.ops import invariants, step
from ue22cs343bb1_openmp_assignment_tpu.state import init_state
from ue22cs343bb1_openmp_assignment_tpu.types import DirState

SCHEMA_ID = "cache-sim/fuzz/v1"

#: per-case cycle budget; quiescence past this is a ``hang`` verdict.
#: Clean reference-dimension runs of <=32 instrs quiesce well under it.
MAX_CYCLES = 2048

#: architectural arrays compared between engines, async field order
ARRAYS = ("cache_addr", "cache_val", "cache_state", "memory",
          "dir_state", "dir_bitvec")

#: (num_nodes, n_instrs) pool. Two node counts on purpose: every
#: distinct shape costs one jit trace per handler set, and the corpus
#: mutates traces far more cheaply than dimensions.
DIMS = ((2, 12), (4, 16))

#: Step-tier names that are *reference behavior* under eviction races,
#: not engine bugs — the async and native states are bit-identical when
#: they fire (the ``state`` oracle runs first and passed). Mechanism:
#: an owner conflict-evicts while a remote WRITE_REQUEST is in flight;
#: the home has already re-pointed the directory at the requester, the
#: late EVICT_MODIFIED blindly resets the entry to U
#: (``assignment.c:596-616``), and the FLUSH_INVACK then re-adds the
#: requester's bit under U. 5/120 clean reference-dimension cases
#: reach it; no other step-tier name ever fires on clean handlers.
QUIRK_STEP_ALLOWLIST = frozenset({"unowned_with_sharers"})


@dataclasses.dataclass(frozen=True)
class FuzzCase:
    """One reproducible differential workload (everything a rerun
    needs; serialized verbatim into findings and shrunk repros)."""

    case_id: int
    num_nodes: int
    #: per node, a tuple of (op, addr, value) triples
    traces: tuple
    delays: tuple
    periods: tuple
    rank: tuple
    #: node-local (race-free) traffic — sync + coherence oracles join
    local: bool
    #: builtin litmus test name when this case is a seeded litmus
    #: workload (analysis/litmus.to_fuzz_case) — the consistency
    #: oracle additionally checks the run's outcome tuple against the
    #: test's allowed set. Mutation drops the tag (a mutated trace is
    #: no longer that litmus test).
    litmus: Optional[str] = None

    def config(self) -> SystemConfig:
        return SystemConfig.reference(num_nodes=self.num_nodes)

    def trace_lists(self) -> list:
        return [[tuple(int(x) for x in ins) for ins in tr]
                for tr in self.traces]

    def to_dict(self) -> dict:
        return {"case_id": self.case_id, "num_nodes": self.num_nodes,
                "traces": [[list(i) for i in tr] for tr in self.traces],
                "delays": list(self.delays),
                "periods": list(self.periods),
                "rank": list(self.rank), "local": self.local,
                "litmus": self.litmus}


def case_from_dict(d: dict) -> FuzzCase:
    return FuzzCase(
        case_id=int(d["case_id"]), num_nodes=int(d["num_nodes"]),
        traces=tuple(tuple(tuple(int(x) for x in i) for i in tr)
                     for tr in d["traces"]),
        delays=tuple(int(x) for x in d["delays"]),
        periods=tuple(int(x) for x in d["periods"]),
        rank=tuple(int(x) for x in d["rank"]), local=bool(d["local"]),
        litmus=d.get("litmus"))


# -- generation ------------------------------------------------------------


def _gen_instr(rng, cfg: SystemConfig, node: int, local: bool) -> tuple:
    home = node if local else int(rng.integers(cfg.num_nodes))
    block = int(rng.integers(max(2, cfg.mem_size // 2)))
    a = (home << cfg.block_bits) | block
    if rng.random() < 0.45:
        return (0, a, 0)
    return (1, a, int(rng.integers(256)))


def gen_case(rng, case_id: int, local: bool = False) -> FuzzCase:
    nn, ni = DIMS[int(rng.integers(len(DIMS)))]
    cfg = SystemConfig.reference(num_nodes=nn)
    traces = []
    for n in range(nn):
        tr: list = []
        while len(tr) < ni:
            ins = _gen_instr(rng, cfg, n, local)
            # bias toward read-modify-write pairs: a write-hit on a
            # SHARED line is the only way onto the UPGRADE path, and
            # pure-random traffic reaches it too rarely to kill
            # upgrade-family mutants in a small budget
            if ins[0] == 1 and len(tr) + 2 <= ni and rng.random() < 0.35:
                tr.append((0, ins[1], 0))
            tr.append(ins)
        traces.append(tuple(tr))
    traces = tuple(traces)
    return FuzzCase(
        case_id=case_id, num_nodes=nn, traces=traces,
        delays=tuple(int(x) for x in rng.integers(0, 7, nn)),
        periods=tuple(int(x) for x in rng.integers(1, 4, nn)),
        rank=tuple(int(x) for x in rng.permutation(nn)), local=local)


def mutate_case(rng, case: FuzzCase, case_id: int) -> FuzzCase:
    """Corpus mutation: a few structural edits to an interesting case —
    drop/duplicate/rewrite instructions, perturb the schedule — with
    the node-local property and the per-node instruction cap
    preserved."""
    cfg = case.config()
    traces = [list(tr) for tr in case.traces]
    delays, periods = list(case.delays), list(case.periods)
    for _ in range(1 + int(rng.integers(3))):
        n = int(rng.integers(len(traces)))
        kind = int(rng.integers(4))
        if kind == 0 and traces[n]:                      # drop one
            del traces[n][int(rng.integers(len(traces[n])))]
        elif kind == 1 and 0 < len(traces[n]) < cfg.max_instrs:
            i = int(rng.integers(len(traces[n])))        # duplicate one
            traces[n].insert(i, traces[n][i])
        elif kind == 2 and traces[n]:                    # rewrite one
            i = int(rng.integers(len(traces[n])))
            traces[n][i] = _gen_instr(rng, cfg, n, case.local)
        elif kind == 3:                                  # schedule nudge
            delays[n] = int(rng.integers(0, 7))
            periods[n] = int(rng.integers(1, 4))
    return dataclasses.replace(
        case, case_id=case_id,
        traces=tuple(tuple(tr) for tr in traces),
        delays=tuple(delays), periods=tuple(periods), litmus=None)


# -- differential execution ------------------------------------------------


def _metrics_dict(st) -> dict:
    mt = st.metrics
    return {f: np.asarray(getattr(mt, f))
            for f in type(mt).__dataclass_fields__}


def _dir_occupancy(st) -> dict:
    ds = np.asarray(st.dir_state)
    return {DirState(int(v)).name: int(c)
            for v, c in zip(*np.unique(ds, return_counts=True))}


def run_case(case: FuzzCase,
             message_phase: Optional[Callable] = None) -> dict:
    """Run one case differentially; returns {verdict, detail, coverage,
    cycles}. ``message_phase`` mutates the async engine only — the
    native oracle always runs the clean protocol."""
    from ue22cs343bb1_openmp_assignment_tpu.native.bindings import \
        NativeEngine

    cfg = case.config()
    traces = case.trace_lists()
    delays = np.array(case.delays, np.int32)
    periods = np.array(case.periods, np.int32)
    rank = np.array(case.rank, np.int32)

    ast = init_state(cfg, traces, issue_delay=delays,
                     issue_period=periods, arb_rank=rank)
    fin = step.run_to_quiescence(cfg, ast, MAX_CYCLES, message_phase)

    nat = NativeEngine(cfg)
    nat.load_traces(traces)
    nat.set_schedule(delays.tolist(), periods.tolist())
    nat.set_arbitration(rank)
    nat.run(MAX_CYCLES)

    verdict, detail = "ok", ""
    aq = bool(fin.quiescent())
    if aq != nat.quiescent:
        verdict = "hang"
        detail = (f"quiescence disagreement in {MAX_CYCLES} cycles: "
                  f"async={aq} native={nat.quiescent}")
    if verdict == "ok":
        nst = nat.export_state()
        for name in ARRAYS:
            if not np.array_equal(np.asarray(getattr(fin, name)),
                                  np.asarray(nst[name])):
                verdict = "state"
                detail = f"{name} diverged (async vs native)"
                break
    quirks = {}
    if verdict == "ok":
        bad = {k: int(v)
               for k, v in invariants.step_violations(cfg, fin).items()
               if int(v)}
        quirks = {k: v for k, v in bad.items()
                  if k in QUIRK_STEP_ALLOWLIST}
        bad = {k: v for k, v in bad.items()
               if k not in QUIRK_STEP_ALLOWLIST}
        if bad:
            verdict, detail = "invariant", f"step-tier violations: {bad}"
    if verdict == "ok" and (case.litmus is not None
                            or case.case_id % 4 == 0):
        verdict, detail = _consistency_join(case, message_phase, quirks)
    if verdict == "ok" and case.local:
        bad = {k: int(v)
               for k, v in
               invariants.quiescent_violations(cfg, fin).items()
               if int(v)}
        if bad:
            verdict = "coherence"
            detail = f"coherence violations on race-free traffic: {bad}"
    if verdict == "ok" and case.local and message_phase is None:
        verdict, detail = _sync_join(cfg, traces, fin)

    doc = schema.from_async(_metrics_dict(fin))
    return {"verdict": verdict, "detail": detail, "quirks": quirks,
            "coverage": schema.coverage_signature(doc,
                                                  _dir_occupancy(fin)),
            "cycles": int(fin.cycle)}


def _consistency_join(case: FuzzCase, message_phase, quirks) -> tuple:
    """The consistency oracle: recapture the run under the message
    ledger, reconstruct po/rf/co/fr and check the coherence axioms
    (analysis/axioms.py); litmus-tagged cases additionally check the
    run's outcome tuple against the test's allowed set. Lazy imports:
    axioms pulls obs/txntrace, which imports back into analysis."""
    from ue22cs343bb1_openmp_assignment_tpu.analysis import axioms
    from ue22cs343bb1_openmp_assignment_tpu.analysis import litmus
    rep = axioms.check_case(case, message_phase, quirks=quirks)
    if rep["violations"]:
        v = rep["violations"][0]
        wit = "; ".join(v.get("witness", []))
        return "consistency", (f"{v['check']}: {v['detail']}"
                               + (f" [{wit}]" if wit else ""))
    if case.litmus is not None and case.litmus in litmus.BUILTIN:
        f = litmus.check_run_outcome(
            litmus.BUILTIN[case.litmus], case.config(),
            rep["events"], rep["final_state"])
        if f is not None:
            return "consistency", f["detail"]
    return "ok", ""


def _sync_join(cfg, traces, fin) -> tuple:
    """Node-local traffic is schedule-independent, so the transactional
    engine must land the same final state as the async run."""
    from ue22cs343bb1_openmp_assignment_tpu.ops import sync_engine as se
    s = se.run_sync_to_quiescence(
        cfg, se.from_sim_state(cfg, init_state(cfg, traces)), 8,
        MAX_CYCLES)
    if not bool(s.quiescent()):
        return "sync", f"sync engine not quiescent in {MAX_CYCLES} rounds"
    s_mem, s_ds, s_bv = se.to_sim_arrays(cfg, s)
    pairs = [("cache_addr", fin.cache_addr, s.cache_addr),
             ("cache_val", fin.cache_val, s.cache_val),
             ("cache_state", fin.cache_state, s.cache_state),
             ("memory", fin.memory, s_mem),
             ("dir_state", fin.dir_state, s_ds),
             ("dir_bitvec", fin.dir_bitvec, s_bv)]
    for name, av, sv in pairs:
        if not np.array_equal(np.asarray(av), np.asarray(sv)):
            return "sync", f"{name} diverged (async vs sync)"
    return "ok", ""


# -- the fuzz loop ---------------------------------------------------------


def fuzz(n_cases: int = 32, seed: int = 0,
         message_phase: Optional[Callable] = None,
         progress: Optional[Callable] = None,
         flight_dir: Optional[str] = None) -> dict:
    """Run the coverage-guided loop; returns the fuzz report.

    Every fourth fresh case is node-local so the sync and coherence
    oracles stay exercised; once the corpus is non-empty, half the
    cases are mutations of a coverage-novel ancestor. Deterministic:
    (n_cases, seed, message_phase) fixes the report bit-for-bit.

    ``flight_dir`` arms the flight recorder (obs/flight.py): every
    finding re-runs under telemetry capture and dumps a replayable
    ``incident_<case_id>`` directory underneath it.
    """
    from ue22cs343bb1_openmp_assignment_tpu.analysis import litmus
    rng = np.random.default_rng(seed)
    corpus: list = []
    seen: set = set()
    findings: list = []
    verdicts: dict = {}
    quirk_cases = 0
    # the litmus suite seeds the front half of the budget (tagged
    # cases get the outcome-membership check on top of the axioms);
    # the back half stays random/mutated so the corpus keeps its reach
    seeds = litmus.seed_cases(n_cases // 2)
    for i in range(n_cases):
        if i < len(seeds):
            case = seeds[i]
        elif corpus and rng.random() < 0.5:
            case = mutate_case(
                rng, corpus[int(rng.integers(len(corpus)))], i)
        else:
            case = gen_case(rng, i, local=(i % 4 == 3))
        res = run_case(case, message_phase)
        v = res["verdict"]
        verdicts[v] = verdicts.get(v, 0) + 1
        quirk_cases += bool(res["quirks"])
        if v != "ok":
            findings.append({"verdict": v, "detail": res["detail"],
                             "cycles": res["cycles"],
                             "case": case.to_dict()})
            if flight_dir is not None:
                # lazy: obs.flight imports back into analysis for the
                # repro emission, so neither package imports the other
                # at module load
                from ue22cs343bb1_openmp_assignment_tpu.obs import (
                    flight as _flight)
                fr = _flight.record_case(case, message_phase)
                fr.run(max(res["cycles"], 1), stop_on_quiescence=False)
                fr.dump_incident(
                    os.path.join(flight_dir,
                                 f"incident_{case.case_id}"),
                    f"fuzz:{v}", res["detail"], case=case.to_dict())
        if res["coverage"] not in seen:
            seen.add(res["coverage"])
            corpus.append(case)
        if progress is not None:
            progress(i, case, res)
    return {"schema": SCHEMA_ID, "seed": seed, "cases": n_cases,
            "max_cycles": MAX_CYCLES,
            "verdicts": dict(sorted(verdicts.items())),
            "quirk_cases": quirk_cases,
            "coverage_points": len(seen), "corpus_size": len(corpus),
            "findings": findings, "ok": not findings}
