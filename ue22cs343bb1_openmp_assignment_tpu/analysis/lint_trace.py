"""AST linter for the traced JAX modules (ops/, parallel/, models/).

The engine's contract is that everything inside a traced function is
branch-free, device-resident, int32-disciplined and deterministic —
the properties the vectorized cycle depends on and that silently break
when someone writes ordinary Python in a handler. This pass enforces
them statically, per function, with a light value-taint analysis:

* ``traced-branch`` — Python ``if``/``while``/``assert`` (or a
  ternary / comprehension guard) whose condition is a traced value,
  and ``range()``/``reversed()``/``enumerate()`` over a traced length.
  Under ``jax.jit`` these either raise ConcretizationTypeError or, in
  op-by-op mode, silently pick one branch per trace. Plain ``for``
  over an array or a container of arrays is NOT flagged — that is
  static unrolling, the engine's idiom for small fixed bounds.
* ``host-sync`` — ``.item()`` / ``.tolist()`` / ``int()`` / ``bool()``
  / ``float()`` on a traced value: a blocking device→host transfer.
* ``host-call`` — ``np.*``, ``print``, ``jax.pure_callback``,
  ``io_callback``, ``jax.debug.print``/``callback`` inside traced
  code: host round-trips that break the pure-XLA execution model.
* ``dtype-drift`` — ``jnp.arange``/``zeros``/``ones``/``empty``/
  ``full`` without an explicit dtype: JAX defaults can disagree with
  the engine's int32 lattice (and with x64 mode).
* ``nondeterminism`` — ``random``/``np.random``/``time``/``datetime``
  /``os.urandom``/``uuid``/``secrets`` in traced code, plus
  module-level imports of ``random``/``secrets``/``uuid`` anywhere in
  the linted packages. Simulation results must be a pure function of
  (config, traces, fault_key).

Taint model (deliberately under-approximate to stay quiet): function
parameters are traced unless they are ``self``/``cls``/``cfg``/
``config``/``mesh``, have a Python-literal default, or carry a scalar
Python annotation (``int``/``bool``/``float``/``str``); results of
``jnp.``/``jax.``/``lax.`` calls are traced; taint propagates through
arithmetic, subscripts, attributes and method calls, and dies at
``.shape``/``.ndim``/``.dtype``/``.size``/``len()``,
``jax.device_get``, identity tests (``is``/``is not``) and container
literals/comprehensions (a Python list of arrays is a host container
— only its *elements* are traced). Unknown local calls are assumed
host values.

Host-side functions opt out of the tracing rules (not of
``dtype-drift``) by saying so: the string ``host-side`` anywhere in
the docstring, or a ``# lint: host`` comment on the ``def`` line or
the line above. The escape hatch is visible in the diff, which is the
point.

A separate boundary pass, ``no-jax``, guards the opposite contract:
the daemon's wire layer (``daemon/server.py``, ``daemon/client.py``)
and the live ops plane (``obs/events.py``, ``obs/promexpo.py``,
``obs/burnrate.py``, ``obs/fleet.py``) must stay importable on
machines with no accelerator stack — socket + json only, jax reaches
the process solely through the worker the server spawns. Any ``import jax``/``jaxlib``, any ``jax``/``jnp`` name
reference, or an ``importlib.import_module("jax...")`` in those files
is a finding.

Public API: :func:`lint_source` (unit tests), :func:`lint_file`,
:func:`lint_paths`, :func:`default_targets`, :func:`lint_no_jax`
(and :func:`lint_no_jax_source` for unit tests).
"""

from __future__ import annotations

import ast
import dataclasses
import pathlib
from typing import Iterable, List, Optional, Sequence

#: module roots whose call results are traced values
_TRACED_ROOTS = {"jnp", "jax", "lax"}
#: parameter names that are never traced values
_HOST_PARAMS = {"self", "cls", "cfg", "config", "mesh"}
#: scalar Python annotations that mark a parameter as a host value
_SCALAR_ANNOTATIONS = {"int", "bool", "float", "str", "bytes"}
#: attribute reads that yield static (host) metadata even on traced values
_STATIC_ATTRS = {"shape", "ndim", "dtype", "size", "weak_type", "sharding"}
#: builtins whose application to a traced value is a device→host sync
_SYNC_BUILTINS = {"int", "bool", "float", "complex"}
#: method names that force a device→host sync
_SYNC_METHODS = {"item", "tolist"}
#: builtins returning host values (no finding, kills taint)
_HOST_BUILTINS = {"len", "isinstance", "getattr", "hasattr", "id", "repr",
                  "str", "format", "type", "max", "min", "abs", "round",
                  "sorted", "sum", "tuple", "list", "dict", "set", "range",
                  "zip", "enumerate", "divmod"}
#: builtins needing a concrete integer — traced args are a trace error
_CONCRETE_LEN_BUILTINS = {"range", "reversed"}
#: calls whose result is a host value even though the root is jax
_HOST_RESULT_CALLS = {"jax.device_get", "jax.block_until_ready",
                      "jax.tree_util.tree_structure"}
#: dotted prefixes that are host round-trips inside traced code
_HOST_CALL_PREFIXES = ("np.", "numpy.", "jax.pure_callback",
                       "jax.experimental.io_callback", "io_callback",
                       "jax.debug.print", "jax.debug.callback",
                       "jax.debug.breakpoint")
#: dotted prefixes that are nondeterminism sources inside traced code
_NONDET_PREFIXES = ("random.", "np.random.", "numpy.random.", "time.",
                    "datetime.", "os.urandom", "uuid.", "secrets.")
#: modules whose import is banned outright in the linted packages
_NONDET_IMPORTS = {"random", "secrets", "uuid"}
#: jnp constructors and the signature slot their dtype occupies
#: (number of positional args after which dtype is positional)
_DTYPE_CTORS = {"arange": None, "zeros": 1, "ones": 1, "empty": 1,
                "full": 2, "zeros_like": None, "ones_like": None,
                "full_like": None}
#: ctors where the _like/arange form may inherit dtype — only flag when
#: neither a dtype kwarg nor an inheriting base is present
_INHERIT_OK = {"zeros_like", "ones_like", "full_like"}


@dataclasses.dataclass(frozen=True)
class Finding:
    """One linter hit: ``file:line:col rule func: msg``."""

    file: str
    line: int
    col: int
    rule: str
    func: str
    msg: str

    def render(self) -> str:
        where = f"{self.file}:{self.line}:{self.col}"
        return f"{where}: [{self.rule}] in `{self.func}`: {self.msg}"

    def as_dict(self) -> dict:
        return dataclasses.asdict(self)


def _dotted(node: ast.AST) -> Optional[str]:
    """`a.b.c` -> "a.b.c" (None for anything not a pure dotted name)."""
    parts: List[str] = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return ".".join(reversed(parts))
    return None


def _is_literal(node: ast.AST) -> bool:
    if isinstance(node, ast.Constant):
        return True
    if isinstance(node, ast.UnaryOp) and isinstance(node.operand,
                                                    ast.Constant):
        return True
    if isinstance(node, (ast.Tuple, ast.List)):
        return all(_is_literal(e) for e in node.elts)
    return False


def _scalar_annotation(node: Optional[ast.AST]) -> bool:
    if node is None:
        return False
    if isinstance(node, ast.Name):
        return node.id in _SCALAR_ANNOTATIONS
    if isinstance(node, ast.Constant) and isinstance(node.value, str):
        return node.value in _SCALAR_ANNOTATIONS
    if isinstance(node, ast.Subscript):  # Optional[int] etc.
        return _scalar_annotation(node.slice)
    return False


class _FunctionLint:
    """Lints one function body with a forward taint pass."""

    def __init__(self, fn: ast.AST, filename: str, src_lines: Sequence[str],
                 findings: List[Finding],
                 inherited: Optional[set] = None) -> None:
        self.fn = fn
        self.filename = filename
        self.src_lines = src_lines
        self.findings = findings
        self.qualname = fn.name
        self.host_side = self._host_exempt()
        self.tainted: set = set(inherited or ())
        self._seed_params()

    # -- setup ---------------------------------------------------------
    def _host_exempt(self) -> bool:
        doc = ast.get_docstring(self.fn) or ""
        if "host-side" in doc.lower() or "host side" in doc.lower():
            return True
        for ln in range(max(self.fn.lineno - 2, 1), self.fn.lineno + 1):
            if ln - 1 < len(self.src_lines) and \
                    "lint: host" in self.src_lines[ln - 1]:
                return True
        return False

    def _seed_params(self) -> None:
        a = self.fn.args
        params = list(a.posonlyargs) + list(a.args) + list(a.kwonlyargs)
        defaults = list(a.defaults)
        # align defaults with the tail of posonly+args
        pos = list(a.posonlyargs) + list(a.args)
        defaulted = {p.arg for p, d in zip(pos[len(pos) - len(defaults):],
                                           defaults) if _is_literal(d)}
        for p, d in zip(a.kwonlyargs, a.kw_defaults):
            if d is not None and _is_literal(d):
                defaulted.add(p.arg)
        for p in params:
            if p.arg in _HOST_PARAMS or p.arg in defaulted:
                continue
            if _scalar_annotation(p.annotation):
                continue
            self.tainted.add(p.arg)
        if a.vararg:
            self.tainted.add(a.vararg.arg)

    # -- reporting -----------------------------------------------------
    def _hit(self, node: ast.AST, rule: str, msg: str) -> None:
        self.findings.append(Finding(
            self.filename, getattr(node, "lineno", self.fn.lineno),
            getattr(node, "col_offset", 0), rule, self.qualname, msg))

    # -- expression taint (records findings as a side effect) ----------
    def taint(self, node: ast.AST) -> bool:
        if node is None:
            return False
        if isinstance(node, ast.Name):
            return node.id in self.tainted
        if isinstance(node, ast.Attribute):
            base = self.taint(node.value)
            if node.attr in _STATIC_ATTRS:
                return False
            return base
        if isinstance(node, ast.Subscript):
            self.taint(node.slice)
            return self.taint(node.value)
        if isinstance(node, ast.Call):
            return self._call(node)
        if isinstance(node, ast.BinOp):
            lt = self.taint(node.left)
            rt = self.taint(node.right)
            return lt or rt
        if isinstance(node, ast.UnaryOp):
            return self.taint(node.operand)
        if isinstance(node, ast.BoolOp):
            return any([self.taint(v) for v in node.values])
        if isinstance(node, ast.Compare):
            t = self.taint(node.left)
            for c in node.comparators:
                t = self.taint(c) or t
            if all(isinstance(o, (ast.Is, ast.IsNot)) for o in node.ops):
                return False    # identity tests are host-decidable
            return t
        if isinstance(node, ast.IfExp):
            if self.taint(node.test) and not self.host_side:
                self._hit(node, "traced-branch",
                          "ternary on a traced value (use jnp.where)")
            return self.taint(node.body) or self.taint(node.orelse)
        if isinstance(node, (ast.Tuple, ast.List, ast.Set)):
            for e in node.elts:
                self.taint(e)
            return False        # a host container OF traced values
        if isinstance(node, ast.Dict):
            for k, v in zip(node.keys, node.values):
                self.taint(k)
                self.taint(v)
            return False
        if isinstance(node, ast.Starred):
            return self.taint(node.value)
        if isinstance(node, (ast.ListComp, ast.SetComp, ast.GeneratorExp,
                             ast.DictComp)):
            return self._comprehension(node)
        if isinstance(node, ast.JoinedStr):
            for v in node.values:
                self.taint(v)
            return False
        if isinstance(node, ast.FormattedValue):
            if self.taint(node.value) and not self.host_side:
                self._hit(node, "host-sync",
                          "formatting a traced value forces a host sync")
            return False
        if isinstance(node, ast.Slice):
            t = self.taint(node.lower) or self.taint(node.upper)
            return self.taint(node.step) or t
        if isinstance(node, ast.NamedExpr):
            t = self.taint(node.value)
            if t:
                self.tainted.add(node.target.id)
            return t
        if isinstance(node, ast.Lambda):
            return False
        return False

    def _comprehension(self, node: ast.AST) -> bool:
        for gen in node.generators:
            it = self.taint(gen.iter)
            for tgt in ast.walk(gen.target):
                if isinstance(tgt, ast.Name):
                    if it:
                        self.tainted.add(tgt.id)
                    else:
                        self.tainted.discard(tgt.id)
            for guard in gen.ifs:
                if self.taint(guard) and not self.host_side:
                    self._hit(guard, "traced-branch",
                              "comprehension guard on a traced value")
        if isinstance(node, ast.DictComp):
            self.taint(node.key)
            self.taint(node.value)
        else:
            self.taint(node.elt)
        return False            # comprehensions build host containers

    def _call(self, node: ast.Call) -> bool:
        arg_taints = [self.taint(a) for a in node.args]
        for kw in node.keywords:
            arg_taints.append(self.taint(kw.value))
        any_tainted_arg = any(arg_taints)
        name = _dotted(node.func)

        if name is not None:
            root = name.split(".", 1)[0]
            self._check_dtype_ctor(node, name)
            if not self.host_side:
                self._check_host_call(node, name)
                self._check_nondet(node, name)
            if name in _SYNC_BUILTINS and any_tainted_arg:
                if not self.host_side:
                    self._hit(node, "host-sync",
                              f"{name}() on a traced value blocks on a "
                              "device->host transfer")
                return False
            if name in _CONCRETE_LEN_BUILTINS and any_tainted_arg and \
                    not self.host_side:
                self._hit(node, "traced-branch",
                          f"{name}() over a traced length (use "
                          "lax.fori_loop / lax.scan, or a static bound "
                          "from cfg)")
            if name in _HOST_BUILTINS:
                return False
            if name in _HOST_RESULT_CALLS:
                return False
            if root in _TRACED_ROOTS:
                return True

        if isinstance(node.func, ast.Attribute):
            if node.func.attr in _SYNC_METHODS and \
                    self.taint(node.func.value):
                if not self.host_side:
                    self._hit(node, "host-sync",
                              f".{node.func.attr}() on a traced value "
                              "blocks on a device->host transfer")
                return False
            # method on a traced value (.astype, .sum, .at[...].set)
            return self.taint(node.func.value)
        # unknown local helper: assume host result (under-approximate)
        return False

    # -- per-call rule checks ------------------------------------------
    def _check_dtype_ctor(self, node: ast.Call, name: str) -> None:
        parts = name.split(".")
        if len(parts) != 2 or parts[0] != "jnp":
            return
        ctor = parts[1]
        if ctor not in _DTYPE_CTORS:
            return
        if any(kw.arg == "dtype" for kw in node.keywords):
            return
        slot = _DTYPE_CTORS[ctor]
        if slot is not None and len(node.args) > slot:
            return      # dtype passed positionally
        if ctor in _INHERIT_OK:
            return      # *_like inherits its base's dtype
        self._hit(node, "dtype-drift",
                  f"jnp.{ctor} without an explicit dtype — the engine "
                  "is int32-disciplined; JAX's default can drift")

    def _check_host_call(self, node: ast.Call, name: str) -> None:
        if name == "print":
            self._hit(node, "host-call",
                      "print() in traced code is a host round-trip "
                      "(use jax.debug.print only in debug paths, or "
                      "mark the function host-side)")
            return
        for pref in _HOST_CALL_PREFIXES:
            if name == pref.rstrip(".") or name.startswith(pref):
                self._hit(node, "host-call",
                          f"`{name}` in traced code leaves the XLA "
                          "program (host callback / numpy op)")
                return

    def _check_nondet(self, node: ast.Call, name: str) -> None:
        for pref in _NONDET_PREFIXES:
            if name == pref.rstrip(".") or name.startswith(pref):
                self._hit(node, "nondeterminism",
                          f"`{name}` in traced code — simulation output "
                          "must be a pure function of (config, traces, "
                          "fault_key)")
                return

    # -- statements ----------------------------------------------------
    def run(self) -> None:
        for stmt in self.fn.body:
            self._stmt(stmt)

    def _assign_target(self, tgt: ast.AST, tainted: bool) -> None:
        if isinstance(tgt, ast.Name):
            if tainted:
                self.tainted.add(tgt.id)
            else:
                self.tainted.discard(tgt.id)
        elif isinstance(tgt, (ast.Tuple, ast.List)):
            for e in tgt.elts:
                self._assign_target(e, tainted)
        elif isinstance(tgt, ast.Starred):
            self._assign_target(tgt.value, tainted)
        # attribute/subscript targets: no local binding to track

    def _stmt(self, stmt: ast.AST) -> None:
        if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef)):
            sub = _FunctionLint(stmt, self.filename, self.src_lines,
                                self.findings, inherited=self.tainted)
            sub.qualname = f"{self.qualname}.{stmt.name}"
            sub.host_side = sub.host_side or self.host_side
            sub.run()
            return
        if isinstance(stmt, (ast.Assign,)):
            t = self.taint(stmt.value)
            for tgt in stmt.targets:
                self._assign_target(tgt, t)
            return
        if isinstance(stmt, ast.AnnAssign):
            t = self.taint(stmt.value) if stmt.value is not None else False
            self._assign_target(stmt.target, t)
            return
        if isinstance(stmt, ast.AugAssign):
            t = self.taint(stmt.value)
            if isinstance(stmt.target, ast.Name):
                if t:
                    self.tainted.add(stmt.target.id)
            return
        if isinstance(stmt, ast.If):
            if self.taint(stmt.test) and not self.host_side:
                self._hit(stmt, "traced-branch",
                          "Python `if` on a traced value (use jnp.where "
                          "/ lax.select / lax.cond)")
            for s in stmt.body + stmt.orelse:
                self._stmt(s)
            return
        if isinstance(stmt, ast.While):
            if self.taint(stmt.test) and not self.host_side:
                self._hit(stmt, "traced-branch",
                          "Python `while` on a traced value (use "
                          "lax.while_loop)")
            for s in stmt.body + stmt.orelse:
                self._stmt(s)
            return
        if isinstance(stmt, ast.For):
            # iterating an array is static unrolling (legal); only a
            # traced *length* breaks tracing — caught at range() above
            self._assign_target(stmt.target, self.taint(stmt.iter))
            for s in stmt.body + stmt.orelse:
                self._stmt(s)
            return
        if isinstance(stmt, ast.Assert):
            if self.taint(stmt.test) and not self.host_side:
                self._hit(stmt, "traced-branch",
                          "assert on a traced value (use "
                          "ops.invariants / checkify)")
            return
        if isinstance(stmt, ast.Return):
            self.taint(stmt.value)
            return
        if isinstance(stmt, ast.Expr):
            self.taint(stmt.value)
            return
        if isinstance(stmt, (ast.With,)):
            for item in stmt.items:
                self.taint(item.context_expr)
            for s in stmt.body:
                self._stmt(s)
            return
        if isinstance(stmt, ast.Try):
            for s in (stmt.body + stmt.orelse + stmt.finalbody +
                      [h for hh in stmt.handlers for h in hh.body]):
                self._stmt(s)
            return
        if isinstance(stmt, ast.Raise):
            self.taint(stmt.exc)
            return
        # Pass / Import / Global / Nonlocal / Delete / Break / Continue


def lint_source(src: str, filename: str = "<string>") -> List[Finding]:
    """Lint one module's source text; returns findings (possibly empty)."""
    tree = ast.parse(src, filename=filename)
    src_lines = src.splitlines()
    findings: List[Finding] = []

    for stmt in tree.body:
        if isinstance(stmt, ast.Import):
            for alias in stmt.names:
                if alias.name.split(".", 1)[0] in _NONDET_IMPORTS:
                    findings.append(Finding(
                        filename, stmt.lineno, stmt.col_offset,
                        "nondeterminism", "<module>",
                        f"module-level `import {alias.name}` — banned "
                        "nondeterminism source in engine code"))
        elif isinstance(stmt, ast.ImportFrom):
            if stmt.module and stmt.module.split(".", 1)[0] in \
                    _NONDET_IMPORTS:
                findings.append(Finding(
                    filename, stmt.lineno, stmt.col_offset,
                    "nondeterminism", "<module>",
                    f"module-level `from {stmt.module} import ...` — "
                    "banned nondeterminism source in engine code"))

    def walk_defs(body, prefix=""):
        for stmt in body:
            if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef)):
                fl = _FunctionLint(stmt, filename, src_lines, findings)
                if prefix:
                    fl.qualname = f"{prefix}.{stmt.name}"
                fl.run()
            elif isinstance(stmt, ast.ClassDef):
                walk_defs(stmt.body, prefix=f"{prefix}.{stmt.name}"
                          if prefix else stmt.name)

    walk_defs(tree.body)
    findings.sort(key=lambda f: (f.file, f.line, f.col, f.rule))
    return findings


def lint_file(path) -> List[Finding]:
    p = pathlib.Path(path)
    return lint_source(p.read_text(), filename=str(p))


def default_targets() -> List[pathlib.Path]:
    """The traced packages this linter gates: ops/, parallel/,
    models/, obs/ (obs is host-side rendering, but it imports traced
    constants and must never grow device code silently)."""
    pkg = pathlib.Path(__file__).resolve().parents[1]
    return [pkg / d for d in ("ops", "parallel", "models", "obs") if
            (pkg / d).is_dir()]


def lint_paths(paths: Optional[Iterable] = None) -> List[Finding]:
    """Lint every ``*.py`` under the given files/dirs (default targets
    when none are given); returns all findings sorted by location."""
    targets = [pathlib.Path(p) for p in paths] if paths else \
        default_targets()
    findings: List[Finding] = []
    for t in targets:
        files = sorted(t.rglob("*.py")) if t.is_dir() else [t]
        for f in files:
            findings.extend(lint_file(f))
    findings.sort(key=lambda f: (f.file, f.line, f.col, f.rule))
    return findings


# -- no-jax boundary lint ----------------------------------------------

#: module roots banned in the daemon wire layer
_JAX_ROOTS = {"jax", "jaxlib", "jnp"}


def no_jax_targets() -> List[pathlib.Path]:
    """The files that must stay jax-free: the daemon's wire layer
    (PR 15) plus the live ops plane (PR 19).  A client submitting a
    job, the server's admission loop, a watch stream, a Prometheus
    scrape, or a fleet poll must never pay jax import time or pull in
    the accelerator stack — device work lives behind the spawned
    worker boundary."""
    pkg = pathlib.Path(__file__).resolve().parents[1]
    return [pkg / "daemon" / "server.py", pkg / "daemon" / "client.py",
            pkg / "obs" / "events.py", pkg / "obs" / "promexpo.py",
            pkg / "obs" / "burnrate.py", pkg / "obs" / "fleet.py"]


def lint_no_jax_source(src: str,
                       filename: str = "<string>") -> List[Finding]:
    """Flag every route by which ``src`` could reach jax: direct
    imports (any depth: ``import jax.numpy``, ``from jax import ...``),
    bare ``jax``/``jnp`` name references (catches call-through on an
    object smuggled in under those names), and literal
    ``importlib.import_module("jax...")``."""
    tree = ast.parse(src, filename=filename)
    findings: List[Finding] = []

    def hit(node, msg):
        findings.append(Finding(filename, node.lineno, node.col_offset,
                                "no-jax", "<module>", msg))

    for node in ast.walk(tree):
        if isinstance(node, ast.Import):
            for alias in node.names:
                if alias.name.split(".", 1)[0] in _JAX_ROOTS:
                    hit(node, f"`import {alias.name}` in the daemon "
                              "wire layer — socket + json only; jax "
                              "belongs behind the worker boundary")
        elif isinstance(node, ast.ImportFrom):
            if node.module and node.level == 0 and \
                    node.module.split(".", 1)[0] in _JAX_ROOTS:
                hit(node, f"`from {node.module} import ...` in the "
                          "daemon wire layer — socket + json only; jax "
                          "belongs behind the worker boundary")
        elif isinstance(node, ast.Name) and node.id in _JAX_ROOTS and \
                isinstance(node.ctx, ast.Load):
            hit(node, f"`{node.id}` referenced in the daemon wire "
                      "layer — device work belongs behind the worker "
                      "boundary")
        elif isinstance(node, ast.Call):
            name = _dotted(node.func)
            if name in ("importlib.import_module", "import_module") and \
                    node.args and isinstance(node.args[0], ast.Constant) \
                    and isinstance(node.args[0].value, str) and \
                    node.args[0].value.split(".", 1)[0] in _JAX_ROOTS:
                hit(node, f"import_module({node.args[0].value!r}) in "
                          "the daemon wire layer")
    findings.sort(key=lambda f: (f.file, f.line, f.col))
    return findings


def lint_no_jax(paths: Optional[Iterable] = None) -> List[Finding]:
    """Run the no-jax boundary pass over ``paths`` (default: the
    daemon wire layer)."""
    targets = [pathlib.Path(p) for p in paths] if paths else \
        no_jax_targets()
    findings: List[Finding] = []
    for p in targets:
        findings.extend(lint_no_jax_source(p.read_text(),
                                           filename=str(p)))
    findings.sort(key=lambda f: (f.file, f.line, f.col))
    return findings
