"""IR-level lint: check what XLA actually traces, not Python source.

The AST linter (analysis/lint_trace.py) sees source; this module runs
``jax.make_jaxpr`` over the real hot-path entry points — ``ops.step``'s
cycle and runners, the mailbox dequeue — and audits the closed jaxpr
after tracing, where every decision the compiler will act on is
explicit:

* ``wide_dtype`` — no widening to 64-bit anywhere: every
  ``convert_element_type`` target and every equation output dtype must
  stay <= 32 bits (an accidental Python int promotion shows up here as
  an i64 intermediate — 2x memory traffic and a slow path on TPU).
* ``dynamic_shape`` — every output aval dimension is a concrete int;
  a traced-in dynamic dimension means shape-polymorphic recompiles.
* ``primitive_budget`` — the flattened equation count (recursing into
  scan/while/cond/pjit sub-jaxprs) stays under ``EQN_BUDGET``. One
  cycle is ~1.1k primitives nearly independent of N (the vectorized
  design); a per-node Python loop sneaking in multiplies this by N and
  trips the budget long before it trips a wall-clock alarm.
* ``host_callback`` — no host round-trips (``*callback*``, infeed /
  outfeed) inside the hot path.
* ``index_budget`` — the flattened count of index equations (gather /
  scatter* / dynamic_slice / dynamic_update_slice) per target stays at
  the exact shipped count pinned in
  ``analysis.indexcheck.INDEX_BUDGETS`` — the engines are index-bound
  (PERF.md), so a new index site is a perf regression CI must see even
  when every dynamic oracle stays green. ``cache-sim analyze --index``
  is the full auditor (plane attribution, indices/instr, merge
  detection); this rule is its always-on tripwire.

:func:`recompile_guard` additionally asserts repeated same-shape calls
hit the trace cache on all three engines: fresh ``jax.jit`` wrappers
around the async cycle and the sync round must report one cached trace
after two calls, and the native engine's content-hash build cache must
serve the second construction without recompiling the shared library.
"""

from __future__ import annotations

from typing import Callable, List, Optional

import jax

from ue22cs343bb1_openmp_assignment_tpu.config import SystemConfig
from ue22cs343bb1_openmp_assignment_tpu.state import init_state

#: flattened-primitive ceiling per linted entry point (measured ~1.1k
#: for one cycle at reference dimensions, N in 2..8; 2048 leaves
#: headroom for growth but catches any O(N) unrolling)
EQN_BUDGET = 2048

#: per-target overrides of EQN_BUDGET.  The fused round body is a
#: whole deep round in one trace — drain fori_loops, the 16-way
#: scatter-min ladder, window fold — measured ~36k flattened eqns at
#: the N=8 probe config and nearly N-independent (the routed ops are
#: matmuls, not unrolled loops); 65536 bounds it while still tripping
#: on any per-node unrolling (which would multiply the count by N).
#: The daemon's wave chunk wraps the cycle in batch stacking + the
#: masked chunk scan — measured ~1.5k flattened eqns, comfortably
#: under the shared 2048 ceiling, so it rides the default; the entry
#: here is the explicit first-class pin PR 15 left implicit.
#: The profiler scan (PR 20) is the plain run_cycles body plus the
#: per-line counter scatter-adds — measured 1434 flattened eqns at the
#: N=4 probe config; 1664 leaves room for mask arithmetic churn while
#: tripping if the profile plane ever grows a second pass over state.
EQN_BUDGETS = {"pallas_round.round_body": 65536,
               "step.run_wave_chunk[2x4]": 2048,
               "step.run_cycles_profile[8]": 1664}

_WIDE = ("int64", "uint64", "float64")
_HOST_PRIMS = ("infeed", "outfeed")


def _subjaxprs(v):
    vs = v if isinstance(v, (list, tuple)) else [v]
    for s in vs:
        if hasattr(s, "jaxpr"):        # ClosedJaxpr
            yield s.jaxpr
        elif hasattr(s, "eqns"):       # raw Jaxpr
            yield s


def _audit(jaxpr, target: str, findings: List[dict]) -> int:
    """Walk one jaxpr (recursing into sub-jaxprs); returns the
    flattened equation count."""
    n = 0
    stack = [jaxpr]
    while stack:
        j = stack.pop()
        for eqn in j.eqns:
            n += 1
            name = eqn.primitive.name
            if "callback" in name or name in _HOST_PRIMS:
                findings.append({"target": target, "rule": "host_callback",
                                 "detail": f"primitive {name!r}"})
            nd = eqn.params.get("new_dtype")
            if nd is not None and str(nd) in _WIDE:
                findings.append({
                    "target": target, "rule": "wide_dtype",
                    "detail": f"convert_element_type -> {nd}"})
            for var in eqn.outvars:
                aval = var.aval
                dt = getattr(aval, "dtype", None)
                if dt is not None and str(dt) in _WIDE:
                    findings.append({
                        "target": target, "rule": "wide_dtype",
                        "detail": f"{name} output {aval.str_short()}"})
                for dim in getattr(aval, "shape", ()):
                    if not isinstance(dim, int):
                        findings.append({
                            "target": target, "rule": "dynamic_shape",
                            "detail": f"{name} output dim {dim!r}"})
            for v in eqn.params.values():
                stack.extend(_subjaxprs(v))
    return n


def _targets(cfg: SystemConfig) -> dict:
    from ue22cs343bb1_openmp_assignment_tpu.ops import mailbox, step
    return {
        "step.cycle": lambda s: step.cycle(cfg, s),
        "mailbox.dequeue": lambda s: mailbox.dequeue(cfg, s),
        "step.run_cycles[8]": lambda s: step.run_cycles(cfg, s, 8),
        # the litmus/axiomatic capture path: the ledger planes (incl.
        # the obs_retire/obs_val observed-value tape the consistency
        # checker replays, with_obs=True) must trace as cheaply as the
        # bare runner — pure gathers of values the cycle already
        # computes
        "step.run_cycles_ledger[8]":
            lambda s: step.run_cycles_ledger(cfg, s, 8, None, True),
        # the coherence-profiler capture path (PR 20): the per-line
        # counter planes must fold into the scan as scatter-adds of
        # masks the cycle already computes — budgeted so profiling
        # never silently grows into a second engine
        "step.run_cycles_profile[8]":
            lambda s: step.run_cycles_profile(cfg, s, 8),
        "step.run_to_quiescence":
            lambda s: step.run_to_quiescence(cfg, s, 64),
        # the daemon's hot body (PR 15): one masked chunk of batched
        # wave cycles over a 2-job stacked batch, traced through the
        # unjitted core so the audit never depends on a shared jit
        # trace cache
        "step.run_wave_chunk[2x4]": _wave_chunk_target(cfg),
        "pallas_round.routed_ops": lambda s: _routed_ops_probe(),
        "pallas_round.round_body": lambda s: _round_body_probe(),
        "rdma_comm.route": lambda s: _rdma_route_probe(),
    }


def _wave_chunk_target(cfg):
    """Target for one chunk (4 masked batched cycles) of the daemon
    serving loop over a stacked batch of two jobs — a loaded one and an
    idle one, prebuilt OUTSIDE the trace so the jaxpr is exactly the
    chunk body (the same trace analysis/indexcheck audits, so the index
    pin is shared verbatim).  ``batched_wave_chunk`` is the unjitted
    core ``run_wave_chunk`` wraps, so the trace is fresh per lint run
    and the per-chunk retire mask, fuel accounting and vmapped cycle
    all face the budget rules."""
    from ue22cs343bb1_openmp_assignment_tpu.ops import step
    from ue22cs343bb1_openmp_assignment_tpu.state import stack_states
    b = stack_states(
        [init_state(cfg, [[(0, 1, 0)]] * cfg.num_nodes), init_state(cfg)])
    return (lambda bb: step.batched_wave_chunk(cfg, bb, 4, 64), b)


def _routed_ops_probe():
    """Exercise every routed index op the fused round kernel substitutes
    for XLA gather/scatter (ops/pallas_round.RoutedIndexOps) at small
    shapes, so the IR audit covers the new kernel's only non-dense
    machinery: one-hot matmul routing and the chunked scatter-min
    ladder.  Shapes are tiny but structurally identical to the kernel's
    (the fori_loop tiling and the 16-way chunk ladder trace the same
    primitives at any size)."""
    import dataclasses

    import jax.numpy as jnp

    from ue22cs343bb1_openmp_assignment_tpu.ops import pallas_round as pr

    cfg = dataclasses.replace(
        SystemConfig.scale(num_nodes=8, drain_depth=2, txn_width=2),
        deep_window=True, deep_slots=4, deep_ownerval_slots=2)
    ix = pr.RoutedIndexOps(cfg, 3)
    mat = jnp.arange(64 * 5, dtype=jnp.int32).reshape(64, 5)
    idx = jnp.arange(16, dtype=jnp.int32) * 3
    rows = jnp.arange(16 * 5, dtype=jnp.int32).reshape(16, 5) - 40
    dest = jnp.full((64,), 2**30, dtype=jnp.int32)
    return (ix.gather(mat[:, 0], idx), ix.gather_rows(mat, idx),
            ix.scatter_rows(mat, idx, rows),
            ix.scatter_col(mat, idx, 2, rows[:, 0]),
            ix.scatter_min(dest, idx, rows[:, 0] + 41))


def _round_body_probe():
    """Trace the ENTIRE fused round body (ops/pallas_round._round_body
    — the pure function `_round_kernel` wraps between its VMEM load and
    store) at a small deep config, so the whole-kernel IR faces the
    wide-dtype / dynamic-shape / host-callback rules and its own eqn
    budget (EQN_BUDGETS).  This is the same trace the kernel-contract
    verifier (analysis/kernelcheck) walks for VMEM liveness and
    lowerability; here it rides the always-on --jaxpr prong at probe
    size so a budget regression shows up in CI before anyone runs
    --kernel."""
    import dataclasses

    import jax.numpy as jnp

    from ue22cs343bb1_openmp_assignment_tpu.ops import pallas_round as pr

    cfg = dataclasses.replace(
        SystemConfig.scale(num_nodes=8, drain_depth=2, txn_width=2),
        deep_window=True, deep_slots=4, deep_ownerval_slots=2)
    ins, _ = pr._block_shapes(cfg)
    args = [jnp.zeros(s, jnp.int32) for s in ins]
    return pr._round_body(cfg, *args)


def _rdma_route_probe():
    """Trace the RDMA lane router (parallel/rdma_comm) on a 1-device
    mesh with the Pallas ring in interpret mode: the shard_map +
    pallas_call sub-jaxprs recurse into the audit, so the bucketing
    sort, the wire pack/unpack and the kernel body all face the same
    budget/host-callback/widening rules as the engine hot path.  One
    device means the ring body is just the local self-copy (the D - 1
    remote steps unroll per mesh size and are exercised by the parity
    tests, not the IR lint), which keeps the probe backend-neutral."""
    import jax.numpy as jnp

    from ue22cs343bb1_openmp_assignment_tpu.parallel import (
        mesh as pmesh, rdma_comm)

    cfg = SystemConfig.scale(num_nodes=8)
    mesh = pmesh.make_mesh(jax.devices()[:1])
    router = rdma_comm.make_rdma_router(cfg, mesh, interpret=True)
    N, S, Fw = cfg.num_nodes, cfg.out_slots, 6 + cfg.msg_bitvec_words
    ctype = jnp.ones((N, S), jnp.int32)
    recv = jnp.tile(jnp.arange(N, dtype=jnp.int32)[:, None], (1, S))
    prio = jnp.arange(N * S, dtype=jnp.int32).reshape(N, S)
    fields = jnp.zeros((N, S, Fw), jnp.int32)
    return router(ctype, recv, prio, fields)


def lint(cfg: Optional[SystemConfig] = None,
         message_phase: Optional[Callable] = None) -> dict:
    """Trace and audit every hot-path target; returns {targets:
    {name: eqn_count}, findings: [...], budget, ok}."""
    from ue22cs343bb1_openmp_assignment_tpu.analysis import indexcheck

    cfg = cfg or SystemConfig.reference()
    st = init_state(cfg, [[(0, 1, 0)]] * cfg.num_nodes)
    findings: List[dict] = []
    counts = {}
    index_sites = {}
    for name, fn in _targets(cfg).items():
        # a target is either a callable traced over the shared state or
        # a (callable, example-arg) pair with its own prebuilt input
        f, arg = fn if isinstance(fn, tuple) else (fn, st)
        closed = jax.make_jaxpr(f)(arg)
        counts[name] = _audit(closed.jaxpr, name, findings)
        budget = EQN_BUDGETS.get(name, EQN_BUDGET)
        if counts[name] > budget:
            findings.append({
                "target": name, "rule": "primitive_budget",
                "detail": f"{counts[name]} eqns > budget {budget}"})
        # index sites are N-independent (the vectorized design indexes
        # whole planes), so the counts the index auditor pins at its
        # canonical size hold at the lint config too — modulo the
        # reference config's mailbox inv_mode, which index_budget()
        # accounts for
        ibudget = indexcheck.index_budget(name, cfg.inv_mode)
        if ibudget is not None:
            sites = indexcheck.count_index_sites(closed.jaxpr)
            index_sites[name] = sites
            if sites != ibudget:
                findings.append({
                    "target": name, "rule": "index_budget",
                    "detail": (f"{sites} index sites != pinned {ibudget}"
                               " (gather/scatter/dynamic-slice; run"
                               " `cache-sim analyze --index` for the"
                               " plane-attributed inventory, then"
                               " re-pin analysis/indexcheck."
                               "INDEX_BUDGETS if intended)")})
    return {"schema": "cache-sim/jaxpr-lint/v1",
            "num_nodes": cfg.num_nodes, "budget": EQN_BUDGET,
            "budget_overrides": dict(EQN_BUDGETS),
            "index_budgets": dict(indexcheck.INDEX_BUDGETS),
            "targets": counts, "index_sites": index_sites,
            "findings": findings,
            "ok": not findings}


def recompile_guard(cfg: Optional[SystemConfig] = None) -> dict:
    """Two same-shape calls per engine must compile exactly once."""
    import os

    from ue22cs343bb1_openmp_assignment_tpu.native import bindings
    from ue22cs343bb1_openmp_assignment_tpu.ops import step
    from ue22cs343bb1_openmp_assignment_tpu.ops import sync_engine as se

    cfg = cfg or SystemConfig.reference(num_nodes=2)
    traces = [[(1, 1, 7)], [(0, 1, 0)]][:cfg.num_nodes]
    traces += [[(0, 1, 0)]] * (cfg.num_nodes - len(traces))

    f_async = jax.jit(lambda s: step.cycle(cfg, s))
    st = init_state(cfg, traces)
    f_async(st)
    f_async(st)
    a = f_async._cache_size()

    f_sync = jax.jit(lambda s: se.round_step(cfg, s))
    ss = se.from_sim_state(cfg, init_state(cfg, traces))
    f_sync(ss)
    f_sync(ss)
    s = f_sync._cache_size()

    # the serving layer's wave step: two waves of HETEROGENEOUS jobs
    # (different traces, same slot shape) must hit one compilation —
    # serve.py's admission loop depends on this staying true
    from ue22cs343bb1_openmp_assignment_tpu import state as state_mod
    f_wave = jax.jit(lambda b: step.batched_wave(cfg, b, 4, 64))
    wave1 = state_mod.stack_states(
        [init_state(cfg, traces), init_state(cfg)])
    wave2 = state_mod.stack_states(
        [init_state(cfg, list(reversed(traces))),
         init_state(cfg, traces)])
    f_wave(wave1)
    f_wave(wave2)
    w = f_wave._cache_size()

    # the serving layer end-to-end: two full serve() runs over the same
    # heterogeneous stream (virtual clock; chunk/max_cycles chosen so no
    # other caller has warmed this jit signature) must compile the
    # production wave runner at most once, and the second run must add
    # nothing — proof the span instrumentation (obs.clock hooks,
    # SpanBook bookkeeping in serve.py's admission loop) lives entirely
    # on the host side of the trace
    from ue22cs343bb1_openmp_assignment_tpu import serve as serve_mod
    from ue22cs343bb1_openmp_assignment_tpu.obs.clock import VirtualClock
    specs = [serve_mod.JobSpec(name=f"g{i:02d}", workload=wl,
                               nodes=cfg.num_nodes, trace_len=4)
             for i, wl in enumerate(("uniform", "hotspot", "uniform"))]
    wave_fn = step.run_wave_to_quiescence
    before = wave_fn._cache_size()
    serve_mod.serve(specs, slots=2, chunk=6, max_cycles=50_001,
                    clock=VirtualClock())
    mid = wave_fn._cache_size()
    serve_mod.serve(specs, slots=2, chunk=6, max_cycles=50_001,
                    clock=VirtualClock())
    after = wave_fn._cache_size()
    sv = after - before
    sv_ok = sv <= 1 and after == mid

    # the daemon's bucketed waves: a TWO-shape stream through the full
    # DaemonCore admission loop (lanes, bucketing, continuous
    # admission over run_wave_chunk) must compile at most one chunk
    # runner PER BUCKET, and replaying the same stream on a fresh core
    # must add nothing — the bucket classes pin the jit signatures, so
    # mid-wave swaps and lane scheduling never touch the trace.
    # chunk/max_cycles are chosen so no other caller warms this
    # signature
    from ue22cs343bb1_openmp_assignment_tpu.daemon import core as dcore
    from ue22cs343bb1_openmp_assignment_tpu.serve import JobSpec

    def _daemon_pass():
        c = dcore.DaemonCore(slots=2, max_buckets=2, chunk=5,
                             max_cycles=50_003,
                             clock=VirtualClock(), keep_dumps=False)
        # shapes chosen so neither covers the other — (n,8) vs (2n,4)
        # — forcing two distinct buckets, i.e. two jit signatures
        arrivals = [
            (0.0, JobSpec(name="dg00", workload="uniform",
                          nodes=cfg.num_nodes, trace_len=8), "batch"),
            (0.0, JobSpec(name="dg01", workload="hotspot",
                          nodes=2 * cfg.num_nodes, trace_len=4),
             "interactive"),
            (0.001, JobSpec(name="dg02", workload="uniform",
                            nodes=cfg.num_nodes, trace_len=8),
             "batch"),
        ]
        dcore.drive(c, arrivals)
        return len(c.buckets)

    chunk_fn = step.run_wave_chunk
    d_before = chunk_fn._cache_size()
    d_buckets = _daemon_pass()
    d_mid = chunk_fn._cache_size()
    _daemon_pass()                       # fresh core, same stream
    d_after = chunk_fn._cache_size()
    dv = d_after - d_before
    dv_ok = dv <= d_buckets and d_after == d_mid

    # the native build cache is content-hash keyed: a second engine
    # must reuse the compiled library byte-for-byte (same path, no
    # rebuild — the mtime would move if the .so were recompiled)
    eng1 = bindings.NativeEngine(cfg)
    path = bindings._lib_path()
    mtime = os.path.getmtime(path)
    eng2 = bindings.NativeEngine(cfg)
    n_ok = (bindings._lib_path() == path
            and os.path.getmtime(path) == mtime)
    del eng1, eng2

    return {"async_cache_size": a, "sync_cache_size": s,
            "wave_cache_size": w,
            "serve_wave_compiles": sv,
            "daemon_wave_compiles": dv,
            "daemon_buckets": d_buckets,
            "native_build_reused": bool(n_ok),
            "ok": (a == 1 and s == 1 and w == 1 and sv_ok and dv_ok
                   and bool(n_ok))}
