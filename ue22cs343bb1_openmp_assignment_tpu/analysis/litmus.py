"""Declarative memory-consistency litmus suite (herd-style).

Each :class:`LitmusTest` is a symbolic multi-node program — per-node
sequences of reads and writes over the two symbolic addresses ``x`` and
``y`` — plus its *expected outcome set*: every (read observations +
final values) tuple the engine's lockstep semantics may legally
produce. The engine blocks each node on every miss/upgrade with at most
one outstanding operation (``assignment.c:624-735``), so its executions
are sequentially consistent and the allowed sets below are the SC sets
of the classic tests (Alglave, Maranget & Tautschnig, "Herding Cats",
TOPLAS 2014 — see PAPERS.md).

Three consumers share one compilation path:

* the **model checker** (analysis/model_check.py, ``track_obs=True``)
  enumerates EVERY reachable outcome of a test's scope and the suite
  diffs that set against ``allowed`` — exact equality, both directions:
  an unexpected outcome is a consistency violation, an unobserved one
  means the scope lost interleavings;
* the **fuzzer** (analysis/fuzz.py) seeds the suite's traces into its
  corpus at reference dimensions and checks every run of a
  litmus-tagged case for membership in ``allowed``;
* the **axiomatic checker** (analysis/axioms.py) replays any captured
  run — litmus or fuzzed — against the po/rf/co/fr axioms.

Symbolic conventions: addresses ``x`` = (home 0, block 1) and ``y`` =
(home 1, block 0) — distinct homes, distinct direct-mapped cache slots,
and each writer below writes only the address it homes, so a reader's
fill and the INV that kills it always share a sender (FIFO keeps them
ordered). Values ``x0``/``y0`` denote the reference initial memory
pattern ``(20*home + block) & 0xFF`` (so x0 = 1, y0 = 20); write
values start at 65, clear of every initial value and of the -1
unattributed sentinel. No litmus address has initial value 0: the
engine's sanctioned blind-WRITEBACK races (quirk family, see
ARCHITECTURE.md) can forward a still-pending line's *reset* value 0 to
a second-hand requester, so a read may observe a ghost 0 nobody wrote.
Keeping 0 out of the init/write value space makes ghosts syntactically
recognizable — a literal ``0`` in an ``allowed`` entry below always
marks such a sanctioned ghost outcome, and the axiomatic checker
(analysis/axioms.py) treats an observed 0 as a ghost read rather than
an unresolvable reads-from edge.
"""

from __future__ import annotations

import dataclasses

from ue22cs343bb1_openmp_assignment_tpu import codec
from ue22cs343bb1_openmp_assignment_tpu.config import SystemConfig
from ue22cs343bb1_openmp_assignment_tpu.types import Op

# symbolic write values (distinct per address, never an init value)
A, B, C, D = 65, 66, 67, 68


@dataclasses.dataclass(frozen=True)
class LitmusTest:
    """One symbolic litmus test.

    ``programs``: per node, a tuple of ``("R", sym)`` / ``("W", sym,
    value)`` instructions, ``sym`` in {"x", "y"}.
    ``allowed``: the complete set of legal outcome tuples — every READ's
    observed value in node-major program order, then the final value of
    each ``final_addrs`` entry. Entries are ints or the symbolic init
    tokens ``"x0"`` / ``"y0"``.
    """

    name: str
    doc: str
    programs: tuple
    allowed: tuple
    final_addrs: tuple = ()

    @property
    def num_nodes(self) -> int:
        return len(self.programs)

    def to_dict(self) -> dict:
        return {"name": self.name, "doc": self.doc,
                "programs": [list(map(list, p)) for p in self.programs],
                "allowed": sorted(map(list, self.allowed)),
                "final_addrs": list(self.final_addrs)}


def _R(sym):
    return ("R", sym)


def _W(sym, val):
    return ("W", sym, val)


#: iriw's sanctioned ghost outcomes (literal 0 = the blind-WRITEBACK
#: race of the module docstring; witnessed by the model checker). The
#: race needs three same-address transactions in flight — a reader
#: granted EXCLUSIVE, the writer's WRITEBACK_* fan-out, and a second
#: reader whose forwarded FLUSH arrives from a node whose own fill is
#: still pending — so only the 4-node test can reach it; every 2-node
#: shape above enumerates to exactly its SC set. Outcome slots are
#: (Rx@n2, Ry@n2, Ry@n3, Rx@n3); the canonical forbidden outcome
#: (A, y0, B, x0) stays unreachable even among the ghosts.
_IRIW_GHOSTS = (
    (0, 0, "y0", "x0"), (0, "y0", "y0", "x0"), (0, B, "y0", "x0"),
    (0, B, B, "x0"), ("x0", 0, "y0", 0), ("x0", 0, "y0", "x0"),
    ("x0", 0, "y0", A), ("x0", "y0", 0, 0), ("x0", "y0", 0, "x0"),
    ("x0", "y0", 0, A), ("x0", "y0", "y0", 0), ("x0", "y0", B, 0),
    ("x0", B, "y0", 0), ("x0", B, B, 0), (A, 0, "y0", "x0"),
    (A, 0, "y0", A), (A, "y0", 0, A))


#: the builtin suite: the classic coherence/SC shapes plus `mp_reload`,
#: a reload variant whose forbidden outcomes need a *stale shared copy*
#: to manifest — the shape that catches a skipped INV fan-out, which no
#: two-read classic test can see (a stale copy yields only old values,
#: which per-location look like "the write simply came last").
BUILTIN = {t.name: t for t in (
    LitmusTest(
        "corr", "coherent read-read: two reads of one location may "
        "never observe the write order backwards",
        ((_W("x", A),), (_R("x"), _R("x"))),
        (("x0", "x0"), ("x0", A), (A, A))),
    LitmusTest(
        "coww", "coherent write-write: program-order writes to one "
        "location serialize in order",
        ((_W("x", A), _W("x", B)),),
        ((B,),), final_addrs=("x",)),
    LitmusTest(
        "corw", "coherent read-write: a read may not observe its own "
        "node's later write's successor",
        ((_R("x"), _W("x", B)), (_W("x", A),)),
        (("x0", A), ("x0", B), (A, B)), final_addrs=("x",)),
    LitmusTest(
        "cowr", "coherent write-read: a read after a local write "
        "observes that write or a co-later one",
        ((_W("x", A), _R("x")), (_W("x", B),)),
        ((A, A), (A, B), (B, B)), final_addrs=("x",)),
    LitmusTest(
        "mp", "message passing: observing the flag write implies "
        "observing the data write",
        ((_W("x", A), _W("y", B)), (_R("y"), _R("x"))),
        (("y0", "x0"), ("y0", A), (B, A))),
    LitmusTest(
        "sb", "store buffering: both readers observing initial values "
        "is forbidden under SC",
        ((_W("x", A), _R("y")), (_W("y", B), _R("x"))),
        (("y0", A), (B, "x0"), (B, A))),
    LitmusTest(
        "lb", "load buffering: both loads observing the other node's "
        "later store is forbidden",
        ((_R("x"), _W("y", B)), (_R("y"), _W("x", A))),
        (("x0", "y0"), ("x0", B), (A, "y0"))),
    LitmusTest(
        "2+2w", "two-plus-two writes: both first writes losing to the "
        "other node's po-earlier write is forbidden",
        ((_W("x", A), _W("y", B)), (_W("y", C), _W("x", D))),
        ((D, C), (A, B), (D, B)), final_addrs=("x", "y")),
    LitmusTest(
        "iriw", "independent reads of independent writes: the two "
        "readers must agree on the write order",
        ((_W("x", A),), (_W("y", B),),
         (_R("x"), _R("y")), (_R("y"), _R("x"))),
        tuple((rx2, ry2, ry3, rx3)
              for rx2 in ("x0", A) for ry2 in ("y0", B)
              for ry3 in ("y0", B) for rx3 in ("x0", A)
              if (rx2, ry2, ry3, rx3) != (A, "y0", B, "x0"))
        + _IRIW_GHOSTS),
    LitmusTest(
        "mp_reload", "message passing with a reload: a reader that saw "
        "the flag may never fall back to the stale data value — the "
        "stale-refill detector (the reload is owner-forwarded, so a "
        "fill that resurrects a dead local copy shows up here)",
        ((_W("x", A), _W("y", B)), (_R("x"), _R("y"), _R("x"))),
        (("x0", "y0", "x0"), ("x0", "y0", A), (A, "y0", A),
         ("x0", B, A), (A, B, A))),
    LitmusTest(
        "mp_upgrade", "mp_reload with a read on the writer's own node "
        "first: both nodes share x, so the data write must take the "
        "UPGRADE -> REPLY_ID -> INV fan-out path — the stale-SHARED-"
        "copy detector (a skipped invalidation leaves the reader "
        "hitting on dead data, which only the cross-address SC check "
        "can see). The writer's own read is po-before its write, so "
        "it always observes x0",
        ((_R("x"), _W("x", A), _W("y", B)),
         (_R("x"), _R("y"), _R("x"))),
        (("x0", "x0", "y0", "x0"), ("x0", "x0", "y0", A),
         ("x0", A, "y0", A), ("x0", "x0", B, A), ("x0", A, B, A))),
)}


# ---------------------------------------------------------------------------
# concretization: symbols -> one cfg's addresses/values
# ---------------------------------------------------------------------------

def litmus_cfg(num_nodes: int, protocol: str = "mesi") -> SystemConfig:
    """The enumeration configuration of a litmus scope: 2 memory blocks
    (so x and y exist), 2 direct-mapped lines (so x and y occupy
    DIFFERENT slots — litmus outcomes must not alias through conflict
    evictions), exact-reference mailbox INV semantics."""
    return SystemConfig(num_nodes=num_nodes, cache_size=2, mem_size=2,
                        queue_capacity=16, max_instrs=4,
                        inv_mode="mailbox", protocol=protocol)


def addr_of(cfg: SystemConfig, sym: str) -> int:
    """x = (home 0, block 1), y = (home 1, block 0) — nonzero-init
    blocks, so an observed 0 is always a ghost (module docstring)."""
    if sym == "x":
        return codec.make_address(cfg, 0, 1)
    if sym == "y":
        return codec.make_address(cfg, 1 % cfg.num_nodes, 0)
    raise ValueError(f"unknown litmus symbol {sym!r}")


def init_val(cfg: SystemConfig, addr: int) -> int:
    """Reference initial memory: block b of home h starts (20h+b)&0xFF
    (state.init_state, assignment.c:806-851)."""
    return (20 * codec.home_node(cfg, addr)
            + codec.block_index(cfg, addr)) & 0xFF


def concretize(test: LitmusTest, cfg: SystemConfig) -> dict:
    """Resolve a test's symbols against one configuration: concrete
    per-node traces in the engine trace format, the concrete allowed
    outcome set, and the concrete final-value addresses."""
    if cfg.num_nodes < test.num_nodes:
        raise ValueError(f"{test.name} needs {test.num_nodes} nodes")
    sym_init = {"x0": init_val(cfg, addr_of(cfg, "x")),
                "y0": init_val(cfg, addr_of(cfg, "y"))}

    def val(v):
        return sym_init[v] if isinstance(v, str) else int(v)

    traces = []
    for prog in test.programs:
        tr = []
        for ins in prog:
            if ins[0] == "R":
                tr.append((int(Op.READ), addr_of(cfg, ins[1]), 0))
            else:
                tr.append((int(Op.WRITE), addr_of(cfg, ins[1]),
                           int(ins[2])))
        traces.append(tuple(tr))
    return {
        "traces": tuple(traces),
        "allowed": frozenset(tuple(val(v) for v in out)
                             for out in test.allowed),
        "final_addrs": tuple(addr_of(cfg, s) for s in test.final_addrs),
        "init": sym_init,
    }


def to_scope(test: LitmusTest, protocol: str = "mesi"):
    """The test as a model-checker Scope (reference memory init, so the
    enumeration starts from exactly the state a real run starts from;
    the symmetry group collapses to the identity, which is fine at
    these scope sizes)."""
    from ue22cs343bb1_openmp_assignment_tpu.analysis.model_check import (
        Scope)
    cfg = litmus_cfg(test.num_nodes, protocol)
    conc = concretize(test, cfg)
    return Scope(f"litmus_{test.name}", cfg, conc["traces"])


def message_phase_for(protocol: str):
    """None (the live handlers) for MESI; the compiled table phase for
    the table variants."""
    if protocol == "mesi":
        return None
    from ue22cs343bb1_openmp_assignment_tpu.analysis.protocol_table import (
        TABLES, table_message_phase)
    return table_message_phase(TABLES[protocol]())


# ---------------------------------------------------------------------------
# enumeration: model-checker outcome set vs the DSL's allowed set
# ---------------------------------------------------------------------------

def enumerate_outcomes(test: LitmusTest, protocol: str = "mesi",
                       message_phase=None,
                       max_states: int = 200_000) -> dict:
    """Exhaustively enumerate the test's reachable outcomes under one
    protocol and diff against the DSL's allowed set (exact equality).

    ``message_phase`` overrides the handler phase (mutation testing);
    by default it follows the protocol. Raises ScopeTooLarge past
    ``max_states`` (the runner maps that to the budget exit)."""
    from ue22cs343bb1_openmp_assignment_tpu.analysis.model_check import (
        ModelChecker)
    if message_phase is None:
        message_phase = message_phase_for(protocol)
    scope = to_scope(test, protocol)
    conc = concretize(test, scope.cfg)
    ck = ModelChecker(scope, message_phase=message_phase,
                      max_states=max_states, track_obs=True,
                      final_addrs=conc["final_addrs"])
    rep = ck.run()
    observed = frozenset(tuple(o) for o in rep["outcomes"])
    unexpected = sorted(observed - conc["allowed"])
    unobserved = sorted(conc["allowed"] - observed)
    return {
        "test": test.name,
        "protocol": protocol,
        "allowed": sorted(conc["allowed"]),
        "observed": sorted(observed),
        "unexpected": unexpected,
        "unobserved": unobserved,
        "violations": [v["name"] for v in rep["violations"]],
        "stats": rep["stats"],
        "ok": bool(not unexpected and not unobserved
                   and not rep["violations"]),
    }


def run_suite(tests=None, protocols=("mesi",), message_phase=None,
              max_states: int = 200_000, progress=None) -> dict:
    """The full (protocol x test) matrix. Returns {protocol: {test:
    enumeration report}}; ScopeTooLarge becomes a budget_exhausted
    entry (runner exit 3) instead of aborting the sweep."""
    from ue22cs343bb1_openmp_assignment_tpu.analysis.model_check import (
        ScopeTooLarge)
    names = list(tests) if tests else list(BUILTIN)
    out = {}
    for proto in protocols:
        out[proto] = {}
        for name in names:
            if name not in BUILTIN:
                raise KeyError(
                    f"unknown litmus test {name!r} "
                    f"(builtin: {', '.join(sorted(BUILTIN))})")
            try:
                rep = enumerate_outcomes(
                    BUILTIN[name], protocol=proto,
                    message_phase=message_phase, max_states=max_states)
            except ScopeTooLarge as e:
                rep = {"test": name, "protocol": proto, "ok": None,
                       "budget_exhausted": True, "detail": str(e)}
            out[proto][name] = rep
            if progress:
                progress(proto, name, rep)
    return out


# ---------------------------------------------------------------------------
# fuzzer seeding: the suite as corpus cases at reference dimensions
# ---------------------------------------------------------------------------

#: corpus-seeding order: the discriminating shapes first, so a
#: truncated seed budget (fuzz seeds ``n_cases // 2`` litmus cases)
#: still carries the stale-copy detector and the classic MP/SB pair
SEED_ORDER = ("mp_reload", "mp_upgrade", "mp", "sb", "corr", "cowr",
              "corw", "lb", "2+2w", "iriw", "coww")


def to_fuzz_case(test: LitmusTest, case_id: int):
    """The test as a litmus-tagged FuzzCase at reference dimensions
    (same symbolic concretization — the init-value formula is
    dimension-independent, so the allowed set carries over)."""
    from ue22cs343bb1_openmp_assignment_tpu.analysis import fuzz
    n = test.num_nodes
    cfg = SystemConfig.reference(num_nodes=n)
    conc = concretize(test, cfg)
    local = all(codec.home_node(cfg, ins[1]) == node
                for node, tr in enumerate(conc["traces"])
                for ins in tr)
    return fuzz.FuzzCase(
        case_id=case_id, num_nodes=n, traces=conc["traces"],
        delays=(0,) * n, periods=(1,) * n, rank=tuple(range(n)),
        local=local, litmus=test.name)


def seed_cases(max_n: int) -> tuple:
    """The first ``max_n`` builtin tests in SEED_ORDER as fuzz corpus
    seeds (case ids 0..max_n-1)."""
    return tuple(to_fuzz_case(BUILTIN[name], i)
                 for i, name in enumerate(SEED_ORDER[:max_n]))


def check_run_outcome(test: LitmusTest, cfg: SystemConfig, events,
                      final_state) -> dict | None:
    """Membership check for ONE concrete run of a litmus-tagged case:
    assemble the run's outcome tuple from the axiomatic checker's
    extracted events (reads node-major in program order) plus the
    final values of final_addrs, and test it against ``allowed``.
    Returns a finding dict on a forbidden outcome, else None. Runs
    with an unattributed (obs -1, early-unblock quirk) or ghost
    (obs 0, blind-WRITEBACK race — module docstring) read are
    skipped — the outcome tuple is not well defined there."""
    import numpy as np
    conc = concretize(test, cfg)
    reads = [e["obs"]
             for e in sorted(events, key=lambda e: (e["node"], e["idx"]))
             if e["kind"] == "R"]
    if any(v <= 0 for v in reads):
        return None
    dir_state = np.asarray(final_state.dir_state)
    dir_bv = np.asarray(final_state.dir_bitvec)
    cache_addr = np.asarray(final_state.cache_addr)
    cache_val = np.asarray(final_state.cache_val)
    cache_state = np.asarray(final_state.cache_state)
    memory = np.asarray(final_state.memory)
    from ue22cs343bb1_openmp_assignment_tpu.types import CacheState, \
        DirState

    def final_value(addr):
        h = codec.home_node(cfg, addr)
        b = codec.block_index(cfg, addr)
        if int(dir_state[h, b]) == int(DirState.EM):
            cidx = codec.cache_index(cfg, addr)
            for nn in range(cfg.num_nodes):
                if ((int(dir_bv[h, b, nn // 32]) >> (nn % 32)) & 1
                        and int(cache_addr[nn, cidx]) == addr
                        and int(cache_state[nn, cidx])
                        != int(CacheState.INVALID)):
                    return int(cache_val[nn, cidx])
        return int(memory[h, b])

    outcome = tuple(reads) + tuple(final_value(a)
                                   for a in conc["final_addrs"])
    if outcome in conc["allowed"]:
        return None
    return {
        "check": "litmus_outcome",
        "test": test.name,
        "outcome": list(outcome),
        "allowed": sorted(map(list, conc["allowed"])),
        "detail": f"litmus {test.name}: forbidden outcome "
                  f"{outcome} (allowed: {sorted(conc['allowed'])})",
    }
