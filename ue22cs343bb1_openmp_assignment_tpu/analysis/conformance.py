"""Table⇄handler conformance: prove the table equals the code.

The declarative table (:mod:`.protocol_table`) claims to *be* the
protocol that :func:`..ops.handlers.message_phase` implements. This
module closes the loop with an exhaustive small-scope differential in
the Ip & Dill style already powering the model checker: explore a
scope's full (symmetry-reduced) state space, and at **every** reachable
transition run the staged concrete state through *both* the live JAX
handler phase and the table-compiled phase inside the unmodified
``step.cycle`` engine, comparing the complete post-``SimState`` pytrees
— caches, memory, directory, mailbox rings, metrics, everything — for
bit equality. First divergence fails the gate with a replayable
counterexample (event path from the initial state + the differing
leaves + both state renders).

Because the engine merge semantics make unmasked update lanes and
unaccepted candidate slots unobservable (ops/step.py, ops/mailbox.py),
full-post-state equality over the whole reachable space is exactly
"the table and the handlers are the same protocol on this scope" — a
proof by exhaustion, not an assertion. Scope exhaustiveness is the
checker's: 2n2h is a complete 2-node enumeration, 4n1a_sym a
symmetry-reduced 4-node one (S3 orbit dedup; witnesses un-permuted).

The same sweep doubles as the table's *dynamic* audit: each message
event is matched against the table host-side (:func:`
.protocol_table.match_rows`) to record per-row firing coverage, verify
exactly one row matches every reachable receiver valuation (totality/
determinism on *reachable* points, complementing verify_table's full
product), and check each fired row's ``assumes`` precondition — an
``assumes`` that a reachable state falsifies is a finding, which is
how the FLUSH_INVACK dir-state assumption stays honest.

Swapping ``message_phase`` for a seeded mutant from
:mod:`.mutations` turns the gate into a mutation test of itself: every
handler mutant must diverge from the MESI table (tests/
test_protocol_table.py).

:class:`ConformanceChecker` subclasses :class:`.model_check.
ModelChecker` for its staging, symmetry, and read-back machinery; the
parent's single-phase oracle is never invoked (``jax.jit`` is lazy, so
it is never compiled either).
"""

from __future__ import annotations

import jax
import numpy as np

from ue22cs343bb1_openmp_assignment_tpu.analysis import model_check
from ue22cs343bb1_openmp_assignment_tpu.analysis.model_check import (
    _BATCH, ModelChecker, Scope, ScopeTooLarge, enabled_events)
from ue22cs343bb1_openmp_assignment_tpu.analysis.protocol_table import (
    ProtocolTable, guard_holds, host_atoms, match_rows, table_message_phase)
from ue22cs343bb1_openmp_assignment_tpu.ops import handlers, step


class ConformanceChecker(ModelChecker):
    """Differential BFS: reference phase vs table-compiled phase."""

    def __init__(self, scope: Scope, table: ProtocolTable,
                 message_phase=None, max_states: int = 50_000):
        super().__init__(scope, message_phase=message_phase,
                         max_states=max_states)
        self.table = table
        ref_mp = message_phase if message_phase is not None \
            else handlers.message_phase
        tab_mp = table_message_phase(table)
        cfg = self.cfg

        def both(state):
            return (step.cycle(cfg, state, message_phase=ref_mp),
                    step.cycle(cfg, state, message_phase=tab_mp))

        self._pair_oracle = jax.jit(jax.vmap(both))

    # -- helpers -----------------------------------------------------------

    @staticmethod
    def _leaf_paths(tree):
        leaves, _ = jax.tree_util.tree_flatten_with_path(tree)
        return leaves

    def _mismatch_rows(self, ref, tab, n: int):
        """Per-batch-row any-leaf-differs mask + per-row differing leaf
        names (full SimState compare — bit equality or bust)."""
        bad = np.zeros(n, bool)
        names: list = [[] for _ in range(n)]
        for (path, la), (_, lb) in zip(self._leaf_paths(ref),
                                       self._leaf_paths(tab)):
            la, lb = np.asarray(la), np.asarray(lb)
            neq = (la != lb).reshape(la.shape[0], -1).any(axis=1)[:n]
            if neq.any():
                label = jax.tree_util.keystr(path)
                for j in np.flatnonzero(neq):
                    names[j].append(label)
                bad |= neq
        return bad, names

    def _audit_rows(self, a, actor: int, findings: list, coverage: dict,
                    sid: int, parent, states) -> None:
        """Host-side row matching for one message event: coverage +
        reachable-point totality/determinism + `assumes` validation."""
        atoms = host_atoms(self.cfg, a, actor, a.queues[actor][0])
        rows = match_rows(self.table, atoms)
        if len(rows) != 1:
            findings.append(dict(
                check="row_match", state=sid,
                detail=f"{len(rows)} table rows match a reachable "
                       f"receiver valuation {atoms} "
                       f"(rows: {[r.name for r in rows]})",
                path=self.path_to(parent, states, sid)))
            return
        row = rows[0]
        coverage[row.name] = coverage.get(row.name, 0) + 1
        if not guard_holds(row.assumes, atoms):
            findings.append(dict(
                check="assumes_violation", state=sid, row=row.name,
                detail=f"row {row.name} fired on a reachable state that "
                       f"falsifies its assumes precondition ({atoms})",
                path=self.path_to(parent, states, sid)))

    # -- the differential run ---------------------------------------------

    def run(self) -> dict:
        scope = self.scope
        a0 = self._a0
        ids = {a0: 0}
        states = [a0]
        parent = [None]
        findings: list = []
        coverage: dict = {}
        n_msg = n_instr = 0

        frontier = [0]
        diverged = False
        while frontier and not diverged:
            jobs = []
            for sid in frontier:
                jobs.extend((sid, ev)
                            for ev in enabled_events(scope, states[sid]))
            nxt = []
            for start in range(0, len(jobs), _BATCH):
                if diverged:
                    break
                chunk = jobs[start:start + _BATCH]
                batch = self._batched(
                    [self._stage(states[sid], ev) for sid, ev in chunk])
                res_ref, res_tab = jax.device_get(self._pair_oracle(batch))
                bad, leaf_names = self._mismatch_rows(
                    res_ref, res_tab, len(chunk))
                for j, (sid, ev) in enumerate(chunk):
                    if ev[0] == "msg":
                        n_msg += 1
                        self._audit_rows(states[sid], ev[1], findings,
                                         coverage, sid, parent, states)
                    else:
                        n_instr += 1
                    if bad[j]:
                        # first diverging transition: full counterexample
                        pa, _, _ = self._read_back(states[sid], ev,
                                                   res_ref, j)
                        pb, _, _ = self._read_back(states[sid], ev,
                                                   res_tab, j)
                        findings.append(dict(
                            check="divergence", state=sid,
                            event=self._render_event(states[sid], ev),
                            fields=leaf_names[j],
                            detail=f"handlers and table disagree after "
                                   f"{self._render_event(states[sid], ev)}"
                                   f" (leaves: {leaf_names[j]})",
                            path=self.path_to(parent, states, sid),
                            ref_render=self.render_state(pa),
                            table_render=self.render_state(pb)))
                        diverged = True
                        break
                    new_a, _, _ = self._read_back(states[sid], ev,
                                                  res_ref, j)
                    new_a, gi = self._canon(new_a)
                    nid = ids.get(new_a)
                    if nid is None:
                        nid = len(states)
                        ids[new_a] = nid
                        states.append(new_a)
                        parent.append((sid, ev, gi))
                        nxt.append(nid)
                        if nid >= self.max_states:
                            raise ScopeTooLarge(
                                f"scope {scope.name}: > {self.max_states} "
                                "states")
            frontier = nxt

        uncovered = sorted(r.name for r in self.table.rows
                           if r.name not in coverage)
        return dict(
            scope=scope.describe(),
            table=self.table.name,
            protocol=self.table.protocol,
            stats=dict(
                states=len(states),
                transitions=n_msg + n_instr,
                msg_events=n_msg,
                instr_events=n_instr,
                symmetry_group_order=len(self._group),
                rows_covered=len(coverage),
                rows_total=len(self.table.rows),
            ),
            row_coverage=dict(sorted(coverage.items())),
            uncovered_rows=uncovered,
            findings=findings,
            ok=not findings,
        )


def check_conformance(scope: Scope, table: ProtocolTable,
                      message_phase=None, max_states: int = 50_000) -> dict:
    """One-call convenience mirroring model_check.check_scope."""
    return ConformanceChecker(scope, table, message_phase=message_phase,
                              max_states=max_states).run()


def variant_scope(scope: Scope, protocol: str) -> Scope:
    """The same scope with cfg.protocol swapped — for model-checking the
    MOESI/MESIF table phases through the unchanged engine."""
    import dataclasses
    return Scope(name=f"{scope.name}_{protocol}",
                 cfg=dataclasses.replace(scope.cfg, protocol=protocol),
                 programs=scope.programs,
                 mem_uniform=scope.mem_uniform)


def extra_scopes() -> dict:
    """Conformance-only scopes, beyond :func:`.model_check.
    builtin_scopes`.

    ``3n2a_ev`` — 3 nodes, two addresses conflicting on one
    direct-mapped line, a reader-evictor racing a reader-upgrader:
    drives every EVICT_SHARED home bookkeeping class (last sharer /
    self-promotion / notify-other / 2+ left), the UPGRADE S-write-hit
    grant, and the sanctioned INV tag-miss no-op — the rows the
    builtin scopes leave dark. 1267 states, exhaustive (trivial
    symmetry group: the three programs are distinct). Kept out of the
    builtin registry so the default ``analyze`` model-check wall-clock
    is unchanged; the union of builtin + extra scope coverage reaches
    every MESI row except the two bystander totality-completions
    (FLUSH/FLUSH_INVACK are only ever routed to home or second, so a
    true bystander delivery cannot occur — the rows exist to close
    the (at_home, at_second) guard product).
    """
    from ue22cs343bb1_openmp_assignment_tpu import codec
    from ue22cs343bb1_openmp_assignment_tpu.config import SystemConfig
    from ue22cs343bb1_openmp_assignment_tpu.types import Op
    cfg3 = SystemConfig(num_nodes=3, cache_size=1, mem_size=2,
                        queue_capacity=16, max_instrs=4,
                        inv_mode="mailbox")
    a = codec.make_address(cfg3, 0, 0)
    b = codec.make_address(cfg3, 0, 1)
    R, W = int(Op.READ), int(Op.WRITE)
    sc = Scope("3n2a_ev", cfg3, (
        ((R, a, 0),),
        ((R, a, 0), (R, b, 0)),
        ((R, a, 0), (W, a, 6)),
    ))
    return {sc.name: sc}


def conformance_scopes() -> dict:
    """Everything the gate can run over: builtin + conformance-only."""
    scopes = dict(model_check.builtin_scopes())
    scopes.update(extra_scopes())
    return scopes


# referenced for the side effect of keeping the import explicit: the
# checker's scope registry is the conformance gate's scope registry
builtin_scopes = model_check.builtin_scopes
