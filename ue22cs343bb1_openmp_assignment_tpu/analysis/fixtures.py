"""First-class loader for ``cache-sim/repro/v1`` fixture directories.

A repro fixture is the one interchange format every replayable artifact
in this repo shares: per-node ``core_<n>.txt`` trace files in the exact
reference syntax (``RD 0x<addr>`` / ``WR 0x<addr> <value>``, parseable
by utils.trace.load_test_dir and the reference's own ``fscanf`` loop)
plus a ``repro.json`` carrying the full :class:`..analysis.fuzz.FuzzCase`
(dimensions, schedule knobs, arbitration ranks, litmus tag) and the
verdict it was captured with. Writers: :func:`..analysis.shrink.emit_repro`
(shrunk fuzz findings), obs/flight.py incident dirs, and tests that
hand-build cases. Readers: :func:`replay` (the full differential-oracle
chain via ``fuzz.run_case`` — hang, state, invariant, consistency,
coherence, sync), litmus seed replay, and external captures — all
through this one module, the first step of ROADMAP item 4's
record/replay story. The universal front door is ``cache-sim replay``
(:mod:`..replay`): it auto-detects a fixture among every other
captured artifact kind and routes it here.

Everything here is host-side plumbing; no jit, no tracing.
"""

from __future__ import annotations

import json
import os
from typing import Callable, Iterable, Optional

from ue22cs343bb1_openmp_assignment_tpu.analysis import fuzz

#: the one schema id; bump on any breaking layout change
SCHEMA = "cache-sim/repro/v1"


def trace_lines(tr) -> str:
    """Render one node's (op, addr, value) trace in reference syntax."""
    out = []
    for op, a, v in tr:
        out.append(f"RD 0x{a:02X}" if op == 0 else f"WR 0x{a:02X} {v}")
    # no trailing blank line for an idle node: parse_trace loads any
    # non-RD/WR line (even empty) as an explicit NOP instruction
    return "\n".join(out) + ("\n" if out else "")


def write_fixture(out_dir: str, case: fuzz.FuzzCase, verdict: str,
                  detail: str,
                  extra_files: Iterable[str] = ()) -> dict:
    """Write ``case`` as a fixture directory: ``core_<n>.txt`` per node
    plus ``repro.json``. ``extra_files`` names sidecars the caller has
    written (or will write) into the same dir — e.g. a Perfetto trace —
    so they appear in the manifest. Returns the metadata dict."""
    os.makedirs(out_dir, exist_ok=True)
    cores = []
    for n, tr in enumerate(case.traces):
        name = f"core_{n}.txt"
        with open(os.path.join(out_dir, name), "w") as f:
            f.write(trace_lines(tr))
        cores.append(name)
    meta = {"schema": SCHEMA,
            "verdict": verdict, "detail": detail,
            "instrs": sum(len(tr) for tr in case.traces),
            "num_nodes": case.num_nodes,
            "case": case.to_dict(),
            "files": sorted(set(cores) | set(extra_files)
                            | {"repro.json"})}
    with open(os.path.join(out_dir, "repro.json"), "w") as f:
        json.dump(meta, f, indent=1, sort_keys=True)
        f.write("\n")
    return meta


def load(path: str) -> dict:
    """Read and schema-check a fixture's metadata. ``path`` is either
    the ``repro.json`` itself or the directory holding it."""
    if os.path.isdir(path):
        path = os.path.join(path, "repro.json")
    with open(path) as f:
        meta = json.load(f)
    if meta.get("schema") != SCHEMA:
        raise ValueError(f"{path}: schema must be {SCHEMA!r}, "
                         f"got {meta.get('schema')!r} — for other "
                         "captured artifacts (recordings, incident "
                         "dirs) use `cache-sim replay`, which "
                         "auto-detects the kind")
    for k in ("verdict", "case"):
        if k not in meta:
            raise ValueError(f"{path}: missing key {k!r}")
    return meta


def load_case(path: str) -> fuzz.FuzzCase:
    """The fixture's case, reconstructed (litmus tag included)."""
    return fuzz.case_from_dict(load(path)["case"])


def replay(path: str,
           message_phase: Optional[Callable] = None) -> dict:
    """Re-run a fixture through the full differential-oracle chain
    (``fuzz.run_case``: hang, state-vs-native, invariants, consistency,
    coherence, sync join). Returns the fresh run result annotated with
    ``expected_verdict`` (from the fixture) and ``reproduced`` (fresh
    verdict == recorded verdict)."""
    meta = load(path)
    res = fuzz.run_case(fuzz.case_from_dict(meta["case"]), message_phase)
    res["expected_verdict"] = meta["verdict"]
    res["reproduced"] = res["verdict"] == meta["verdict"]
    return res
