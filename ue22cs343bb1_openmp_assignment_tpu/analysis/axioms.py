"""Axiomatic execution checker: po/rf/co/fr over captured runs.

Herd-style (Alglave, Maranget & Tautschnig, "Herding Cats", TOPLAS
2014 — PAPERS.md): a run of the engine is abstracted into a set of
memory *events* — one per retired instruction — and the candidate
execution relations are reconstructed from the message ledger:

* **po** — program order, the per-node retire sequence (the engine
  blocks each node on every miss/upgrade, so retire order IS fetch
  order);
* **rf** — reads-from, resolved by value: every write in the litmus
  and fuzz value discipline carries a distinct-enough value that a
  read's retire observation (the ``obs_val`` ledger plane) names its
  source write, or the initial memory value;
* **co** — coherence order, the per-address order of write *retires*.
  A write retires when its fill/upgrade grants ownership, and
  ownership of a line is serialized by the home node, so retire order
  is the home's serialization order;
* **fr** — from-reads, derived as usual: ``r -fr-> w'`` when
  ``rf(r) -co-> w'`` (reads of the initial value front the whole co).

Two checks run on every case, a third on *pristine* cases only:

* ``write_serialization`` — per node per address, co must agree with
  po (coWW);
* ``sc_per_location`` — per address, po-loc ∪ rf ∪ co ∪ fr acyclic
  (cache coherence proper);
* ``sc_cycle`` — the same union across ALL addresses must be acyclic:
  the engine's lockstep blocking makes it sequentially consistent
  (analysis/litmus.py enumerates the classic shapes to exactly their
  SC sets), and this global check is the only axiom that can see a
  *stale shared copy* — a reader hitting on a line whose INV fan-out
  a mutant skipped observes per-location-consistent but globally
  impossible values (the ``mp_reload`` shape).

**Ghosts.** The engine's sanctioned blind-WRITEBACK races (the quirk
family — see the litmus module docstring) can forward a still-pending
line's reset value 0 to a second-hand requester, drop a write's fill
entirely (the early-unblock quirk), or pair a stray second-hand fill
with the wrong in-flight address — the fill installs the message's
value under the *waiting* address's tag, so a read can observe a
value only ever written to a conflicting line. All three leave a
syntactic mark in the ledger: a read retiring with ``obs_val`` 0 or
-1, a read observing a value foreign to its own address but present
in the run's global value pool (some write's value, or some other
address's initial value), or a write retiring with ``obs_val`` != its
own value. Such an event *taints* its address — the per-address
checks skip tainted addresses, the global check requires a fully
untainted (pristine) case — so the sanctioned races are never misread
as violations while every check that does run is exact. Taint is
counted in ``skips``; a read observing a value that NOTHING in the
run produced (no write anywhere, no initial value anywhere, not the
reset value) is impossible under any sanctioned behavior and stays a
hard ``rf_unresolved`` violation.

Violations carry a replayable witness: the event cycle (or offending
pair) with edge labels, ready for ``analysis/shrink.py`` to minimize
the owning case and ``analysis/fixtures.py`` to emit as a repro.
"""

from __future__ import annotations

from typing import Callable, Dict, List, Optional

import numpy as np

from ue22cs343bb1_openmp_assignment_tpu.analysis import litmus
from ue22cs343bb1_openmp_assignment_tpu.types import Op

SCHEMA_ID = "cache-sim/axioms/v1"

#: ledger capture chunk for :func:`check_case` (one compiled scan size)
CAPTURE_CHUNK = 64


# -- event extraction ------------------------------------------------------


# lint: host
def extract_events(cfg, ledger: Dict[str, np.ndarray],
                   base_cycle: int = 0) -> List[dict]:
    """Ledger planes → the retired-event list, sorted (cycle, node).

    One event per set bit of ``obs_retire``. The retiring
    instruction's identity is the node's *current latch*: the
    ``op``/``addr``/``value`` planes are valid at ``fetch`` cycles
    only (on other cycles they carry the frontend's idle output), so
    the walk replays each node's latch — a hit retires at its own
    fetch cycle, a miss/upgrade at its later unblock cycle, and the
    two are exclusive per node per cycle. ``obs_val`` holds what the
    node's own cache answers for the in-flight address at the retire
    boundary; ``idx`` is the per-node program-order index.
    """
    if not ledger:
        return []
    retire = np.asarray(ledger["obs_retire"])
    fetch = np.asarray(ledger["fetch"])
    op = np.asarray(ledger["op"])
    addr = np.asarray(ledger["addr"])
    value = np.asarray(ledger["value"])
    obs = np.asarray(ledger["obs_val"])
    n_nodes = retire.shape[1]
    events: List[dict] = []
    po_idx = [0] * n_nodes
    latch = [None] * n_nodes
    hot = np.nonzero(retire | fetch)
    for t, n in zip(*hot):
        t, n = int(t), int(n)
        if fetch[t, n]:
            latch[n] = (int(op[t, n]), int(addr[t, n]),
                        int(value[t, n]))
        if retire[t, n]:
            l_op, l_addr, l_val = latch[n]
            if l_op == int(Op.NOP):
                po_idx[n] += 1
                continue
            kind = "R" if l_op == int(Op.READ) else "W"
            events.append({
                "node": n, "idx": po_idx[n], "t": base_cycle + t,
                "kind": kind, "addr": l_addr,
                "val": l_val if kind == "W" else None,
                "obs": int(obs[t, n]),
            })
            po_idx[n] += 1
    events.sort(key=lambda e: (e["t"], e["node"]))
    return events


def _fmt(e: dict) -> str:
    body = (f"R 0x{e['addr']:02X} obs={e['obs']}" if e["kind"] == "R"
            else f"W 0x{e['addr']:02X}={e['val']}")
    return f"n{e['node']}#{e['idx']}@{e['t']} {body}"


# -- relation construction + acyclicity ------------------------------------


def _find_cycle(n_nodes: int, edges: List[tuple]) -> Optional[List[int]]:
    """Iterative DFS over (src, dst, label) edges; returns one cycle as
    a vertex list (first == last) or None."""
    adj: List[List[int]] = [[] for _ in range(n_nodes)]
    for s, d, _ in edges:
        adj[s].append(d)
    color = [0] * n_nodes          # 0 unseen / 1 on stack / 2 done
    parent = [-1] * n_nodes
    for root in range(n_nodes):
        if color[root]:
            continue
        stack = [(root, iter(adj[root]))]
        color[root] = 1
        while stack:
            v, it = stack[-1]
            for w in it:
                if color[w] == 0:
                    color[w] = 1
                    parent[w] = v
                    stack.append((w, iter(adj[w])))
                    break
                if color[w] == 1:           # back edge: w .. v -> w
                    cyc, u = [v], v
                    while u != w:
                        u = parent[u]
                        cyc.append(u)
                    cyc.reverse()
                    return cyc + [cyc[0]]
            else:
                color[v] = 2
                stack.pop()
    return None


def _witness(events: List[dict], cyc: List[int],
             edges: List[tuple]) -> List[str]:
    """Render a vertex cycle with one edge label per hop."""
    lab = {(s, d): l for s, d, l in edges}
    out = []
    for a, b in zip(cyc, cyc[1:]):
        out.append(f"{_fmt(events[a])} -{lab.get((a, b), '?')}-> "
                   f"{_fmt(events[b])}")
    return out


# -- the checker -----------------------------------------------------------


# lint: host
def check_events(cfg, events: List[dict],
                 quirks: Optional[dict] = None) -> dict:
    """Check the coherence/consistency axioms over an event list.

    Pure host-side function of its inputs (tests hand-build event
    lists). Returns ``{schema, violations, skips, pristine, stats}``;
    each violation carries ``check``, ``detail`` and a ``witness``
    list of rendered edges. ``quirks`` (the fuzz run's allow-listed
    step-tier counters) only gates the global SC check.
    """
    skips = {"ghost_read": 0, "ghost_write": 0, "ghost_cross": 0,
             "unattributed": 0, "ambiguous_rf": 0, "tainted_addrs": 0}
    violations: List[dict] = []
    by_addr: Dict[int, List[int]] = {}
    for i, e in enumerate(events):
        by_addr.setdefault(e["addr"], []).append(i)
    vals_of = {a: {events[i]["val"] for i in idxs
                   if events[i]["kind"] == "W"}
               for a, idxs in by_addr.items()}
    pool = set().union(*vals_of.values()) if vals_of else set()
    pool |= {litmus.init_val(cfg, a) for a in by_addr}

    # -- per-event classification: ghosts taint their address ----------
    tainted: set = set()
    for i, e in enumerate(events):
        a = e["addr"]
        if e["kind"] == "R":
            own = vals_of[a] | {litmus.init_val(cfg, a)}
            if e["obs"] == -1:
                skips["unattributed"] += 1
                tainted.add(a)
            elif e["obs"] == 0 and 0 not in own:
                skips["ghost_read"] += 1
                tainted.add(a)
            elif e["obs"] not in own and e["obs"] in pool:
                skips["ghost_cross"] += 1
                tainted.add(a)
        elif e["obs"] != e["val"]:
            skips["ghost_write"] += 1
            tainted.add(a)
    skips["tainted_addrs"] = len(tainted)

    # -- rf resolution + per-address relations -------------------------
    ambiguous = False
    all_edges: List[tuple] = []
    for a, idxs in sorted(by_addr.items()):
        if a in tainted:
            continue
        writes = [i for i in idxs if events[i]["kind"] == "W"]
        reads = [i for i in idxs if events[i]["kind"] == "R"]
        init = litmus.init_val(cfg, a)
        co = sorted(writes, key=lambda i: (events[i]["t"],
                                           events[i]["node"]))
        co_pos = {i: k for k, i in enumerate(co)}

        # write_serialization: co must agree with po per node (coWW)
        last: Dict[int, int] = {}
        for i in co:
            n = events[i]["node"]
            if n in last and events[last[n]]["idx"] > events[i]["idx"]:
                violations.append({
                    "check": "write_serialization", "addr": a,
                    "detail": f"0x{a:02X}: co inverts po on node {n}",
                    "witness": [f"{_fmt(events[last[n]])} "
                                f"-co-before-po-> {_fmt(events[i])}"]})
            last[n] = i

        # rf: resolve each read to init or a unique same-value write
        rf: Dict[int, Optional[int]] = {}
        edges: List[tuple] = []
        for r in reads:
            v = events[r]["obs"]
            srcs = [w for w in writes if events[w]["val"] == v]
            if v == init and srcs:                # init/write collision
                skips["ambiguous_rf"] += 1
                ambiguous = True
                continue
            if not srcs and v == init:
                rf[r] = None                      # reads-from-init
            elif len(srcs) == 1:
                rf[r] = srcs[0]
                edges.append((srcs[0], r, "rf"))
            elif not srcs:
                violations.append({
                    "check": "rf_unresolved", "addr": a,
                    "detail": f"0x{a:02X}: read observed {v}, which no "
                              f"write produced and init ({init}) does "
                              "not explain",
                    "witness": [_fmt(events[r])]})
                continue
            else:                                 # duplicate values
                skips["ambiguous_rf"] += 1
                ambiguous = True
                continue
            # fr: r precedes every write co-after rf(r)
            start = co_pos[rf[r]] + 1 if rf[r] is not None else 0
            for w in co[start:]:
                edges.append((r, w, "fr"))
        for w1, w2 in zip(co, co[1:]):
            edges.append((w1, w2, "co"))
        by_node: Dict[int, List[int]] = {}
        for i in idxs:
            by_node.setdefault(events[i]["node"], []).append(i)
        for lst in by_node.values():
            lst.sort(key=lambda i: events[i]["idx"])
            for i1, i2 in zip(lst, lst[1:]):
                edges.append((i1, i2, "po-loc"))

        cyc = _find_cycle(len(events), edges)
        if cyc is not None:
            violations.append({
                "check": "sc_per_location", "addr": a,
                "detail": f"0x{a:02X}: po-loc ∪ rf ∪ co ∪ fr is cyclic",
                "witness": _witness(events, cyc, edges)})
        all_edges.extend(edges)

    # -- global SC: pristine cases only --------------------------------
    pristine = (not tainted and not ambiguous and not (quirks or {})
                and not violations)
    if pristine and events:
        by_node = {}
        for i, e in enumerate(events):
            by_node.setdefault(e["node"], []).append(i)
        sc_edges = list(all_edges)
        for lst in by_node.values():
            lst.sort(key=lambda i: events[i]["idx"])
            for i1, i2 in zip(lst, lst[1:]):
                sc_edges.append((i1, i2, "po"))
        cyc = _find_cycle(len(events), sc_edges)
        if cyc is not None:
            violations.append({
                "check": "sc_cycle",
                "detail": "po ∪ rf ∪ co ∪ fr is cyclic: no sequentially "
                          "consistent order explains this execution",
                "witness": _witness(events, cyc, sc_edges)})
    return {"schema": SCHEMA_ID, "violations": violations,
            "skips": skips, "pristine": pristine,
            "stats": {"events": len(events),
                      "addrs": len(by_addr),
                      "edges": len(all_edges)}}


# lint: host
def check_case(case, message_phase: Optional[Callable] = None,
               max_cycles: Optional[int] = None,
               quirks: Optional[dict] = None) -> dict:
    """Capture one fuzz case's ledger and check it.

    Runs the async engine to quiescence under ledger capture
    (obs/txntrace.capture — the same scan the span reconstruction
    uses) and returns the :func:`check_events` report plus ``events``
    and ``final_state`` (the litmus outcome-membership check in
    analysis/fuzz.py consumes both).
    """
    from ue22cs343bb1_openmp_assignment_tpu.analysis import fuzz
    from ue22cs343bb1_openmp_assignment_tpu.obs import txntrace
    from ue22cs343bb1_openmp_assignment_tpu.state import init_state
    cfg = case.config()
    st = init_state(cfg, case.trace_lists(),
                    issue_delay=np.array(case.delays, np.int32),
                    issue_period=np.array(case.periods, np.int32),
                    arb_rank=np.array(case.rank, np.int32))
    fin, ledger, base = txntrace.capture(
        cfg, st, max_cycles or fuzz.MAX_CYCLES, chunk=CAPTURE_CHUNK,
        message_phase=message_phase, with_obs=True)
    events = extract_events(cfg, ledger, base)
    rep = check_events(cfg, events, quirks=quirks)
    rep["events"] = events
    rep["final_state"] = fin
    return rep
