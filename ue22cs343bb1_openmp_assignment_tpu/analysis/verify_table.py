"""Table-level static verification: protocol properties without simulation.

Four pure passes over an :class:`.protocol_table.ProtocolTable` — no JAX,
no state space, milliseconds each:

* **totality + determinism** — for every message type, enumerate the
  full product of its declared guard-atom domains and require *exactly
  one* matching row per point. Zero rows is a hole (a reachable
  receiver predicate the protocol doesn't define — the dropped-row
  mutant); two is an overlap (nondeterministic dispatch — the
  guard-overlap mutant). A row guarding on an atom outside its
  message's declared domain is rejected first, since it would make the
  enumeration unsound.
* **ownership conservation** — per directory-writing row, exhaustively
  enumerate abstract pre-states (sharer bitvector over a 4-node
  universe with requester/second aliasing x 3 directory states),
  filter by the directory trio of invariants + the row's guard and
  ``assumes``, apply the row's directory effect, and require the trio
  to still hold. This is the inductive step of "the directory never
  lies": EM names exactly one owner, S at least one sharer, U none.
  A second sub-pass rejects double-grants (a row that both installs
  M/E locally and sends an ownership-granting reply).
* **stability** — a row that sends messages but changes *no* state is a
  pure forwarder: if following pure-forwarder emissions ever cycles
  back to the originating message type, the guard that fired re-fires
  on identical state and the messages circulate forever without an
  intervening state change. Require the pure-forwarder emission graph
  to be acyclic (conservative livelock check; the model checker's
  Tarjan pass is the dynamic ground truth).
* **anchors** — every row must cite an ``assignment.c`` anchor from
  :data:`..ops.handlers.TRANSITION_ANCHORS` for its message and only
  documented quirk ids from :data:`..ops.handlers.QUIRKS`, and every
  registered anchor/quirk must be cited by some row — the table and
  the hand-written handlers are forced to name the same reference
  code, so either drifting from the C is a loud failure.

``verify(table)`` returns a report dict shaped like the model checker's
(``ok`` + ``findings`` with kind/detail), consumed by runner ``--table``
and tests/test_protocol_table.py.
"""

from __future__ import annotations

import itertools

from ue22cs343bb1_openmp_assignment_tpu.analysis.protocol_table import (
    ATOM_DOMAINS, CacheWrite, ClearWait, DirWrite, InvFanout, MemWrite,
    ProtocolTable, Replace, Row, Send, guard_holds)
from ue22cs343bb1_openmp_assignment_tpu.ops import handlers
from ue22cs343bb1_openmp_assignment_tpu.types import (CacheState, DirState,
                                                      Msg)

_M, _E = int(CacheState.MODIFIED), int(CacheState.EXCLUSIVE)
_EM, _DS, _U = int(DirState.EM), int(DirState.S), int(DirState.U)

_MSG_NAME = {int(m): m.name for m in Msg if m is not Msg.NONE}


def _atom_domain(table: ProtocolTable, atom: str) -> tuple:
    if atom == "cache_state":
        return table.cache_states
    return ATOM_DOMAINS[atom]


def check_totality_determinism(table: ProtocolTable) -> list:
    """Exactly-one-row over each message's declared guard-atom product."""
    findings = []
    for msg, name in _MSG_NAME.items():
        if msg not in table.domains:
            findings.append(dict(kind="missing_domain", message=name,
                                 detail=f"no guard domain declared for "
                                        f"{name}"))
            continue
        atoms = table.domains[msg]
        rows = table.rows_for(msg)
        if not rows:
            findings.append(dict(kind="totality_hole", message=name,
                                 detail=f"no rows at all for {name}"))
            continue
        for r in rows:
            extra = set(r.guard.atoms()) - set(atoms)
            if extra:
                findings.append(dict(
                    kind="undeclared_atom", message=name, row=r.name,
                    detail=f"row {r.name} guards on {sorted(extra)} outside "
                           f"the declared {name} domain {atoms}"))
        domains = [_atom_domain(table, a) for a in atoms]
        for point in itertools.product(*domains):
            val = dict(zip(atoms, point))
            # set-valued atoms match by membership: present scalars as-is
            matches = [r for r in rows if _guard_at(r, val)]
            where = f"{name}{val}" if val else name
            if not matches:
                findings.append(dict(
                    kind="totality_hole", message=name, point=val,
                    detail=f"no row matches {where}"))
            elif len(matches) > 1:
                findings.append(dict(
                    kind="determinism_overlap", message=name, point=val,
                    rows=[r.name for r in matches],
                    detail=f"rows {[r.name for r in matches]} all match "
                           f"{where}"))
    return findings


def _guard_at(row: Row, val: dict) -> bool:
    """guard_holds restricted to the enumerated atoms (others don't-care)."""
    g = row.guard
    probe = dict(val)
    for a in g.atoms():
        if a not in probe:
            return False        # undeclared atom; reported separately
    return guard_holds(g, probe)


# ---------------------------------------------------------------------------
# ownership conservation
# ---------------------------------------------------------------------------

# abstract 4-node universe: sender is node 0, the message's `second`
# aliases the sender (c=0) or not (c=1), nodes 2 and 3 are bystanders.
_NODES = (0, 1, 2, 3)


def _trio_ok(ds: int, bv: frozenset) -> bool:
    if ds == _EM:
        return len(bv) == 1     # EM names exactly one owner
    if ds == _DS:
        return len(bv) >= 1     # S has at least one sharer
    return len(bv) == 0         # U names none


def _others_class(bv: frozenset, sender: int) -> str:
    n = len(bv - {sender})
    return "0" if n == 0 else ("1" if n == 1 else "2+")


def _dir_guard_ok(g, ds: int, bv: frozenset, sender: int) -> bool:
    if g.dir_state is not None and ds not in g.dir_state:
        return False
    if g.others is not None and _others_class(bv, sender) not in g.others:
        return False
    return True


def _apply_bv(expr: str, bv: frozenset, sender: int, second: int):
    return {
        "bv|sender": bv | {sender},
        "bv|second": bv | {second},
        "sender": frozenset({sender}),
        "second": frozenset({second}),
        "bv-sender": bv - {sender},
        "empty": frozenset(),
    }[expr]


_DS_BY_NAME = {"EM": _EM, "S": _DS, "U": _U}

_GRANT_TYPES = {int(Msg.REPLY_WR), int(Msg.REPLY_ID)}


def check_conservation(table: ProtocolTable) -> list:
    """Inductive preservation of the directory trio, row by row."""
    findings = []
    sender = 0
    for r in table.rows:
        dws = [e for e in r.effects if isinstance(e, DirWrite)]
        for dw in dws:
            for bv_bits in itertools.chain.from_iterable(
                    itertools.combinations(_NODES, k)
                    for k in range(len(_NODES) + 1)):
                bv = frozenset(bv_bits)
                for ds in (_EM, _DS, _U):
                    for second in (0, 1):
                        if not _trio_ok(ds, bv):
                            continue
                        if not _dir_guard_ok(r.guard, ds, bv, sender):
                            continue
                        if not _dir_guard_ok(r.assumes, ds, bv, sender):
                            continue
                        nds = _DS_BY_NAME[dw.state] \
                            if dw.state is not None else ds
                        nbv = _apply_bv(dw.bv, bv, sender, second) \
                            if dw.bv is not None else bv
                        if not _trio_ok(nds, nbv):
                            findings.append(dict(
                                kind="conservation_violation", row=r.name,
                                pre=dict(dir=ds, bv=sorted(bv),
                                         second=second),
                                post=dict(dir=nds, bv=sorted(nbv)),
                                detail=f"row {r.name}: pre dir={ds} "
                                       f"bv={sorted(bv)} second={second} "
                                       f"-> post dir={nds} bv={sorted(nbv)}"
                                       f" breaks the directory trio"))
        # double-grant: installing ownership locally while also granting it
        installs = any(isinstance(e, CacheWrite) and e.state in (_M, _E)
                       for e in r.effects)
        grants = any(isinstance(e, Send) and
                     (e.type in _GRANT_TYPES or e.bitvec == "others")
                     for e in r.effects)
        if installs and grants:
            findings.append(dict(
                kind="double_grant", row=r.name,
                detail=f"row {r.name} installs M/E locally and also sends "
                       f"an ownership grant"))
    return findings


# ---------------------------------------------------------------------------
# stability
# ---------------------------------------------------------------------------

_STATE_EFFECTS = (CacheWrite, DirWrite, MemWrite, ClearWait, Replace,
                  InvFanout)


def check_stability(table: ProtocolTable) -> list:
    """Pure-forwarder emission graph must be acyclic."""
    edges: dict = {}
    for r in table.rows:
        sends = [e for e in r.effects if isinstance(e, Send)]
        changes = any(isinstance(e, _STATE_EFFECTS) for e in r.effects)
        if sends and not changes:
            edges.setdefault(r.msg, set()).update(e.type for e in sends)
    findings = []
    for start in edges:
        stack, seen = [(start, (start,))], set()
        while stack:
            node, path = stack.pop()
            for nxt in edges.get(node, ()):
                if nxt == start:
                    cyc = [_MSG_NAME[m] for m in path + (nxt,)]
                    findings.append(dict(
                        kind="stability_cycle", cycle=cyc,
                        detail="pure-forwarder rows circulate without a "
                               "state change: " + " -> ".join(cyc)))
                elif nxt not in seen:
                    seen.add(nxt)
                    stack.append((nxt, path + (nxt,)))
    return findings


# ---------------------------------------------------------------------------
# anchors
# ---------------------------------------------------------------------------

def check_anchors(table: ProtocolTable) -> list:
    findings = []
    cited_anchors: dict = {}
    cited_quirks: set = set()
    for r in table.rows:
        name = _MSG_NAME[r.msg]
        registered = handlers.TRANSITION_ANCHORS.get(name, ())
        if r.anchor not in registered:
            findings.append(dict(
                kind="unknown_anchor", row=r.name,
                detail=f"row {r.name} cites {r.anchor}, not a registered "
                       f"{name} anchor {registered}"))
        cited_anchors.setdefault(name, set()).add(r.anchor)
        for q in r.quirks:
            if q not in handlers.QUIRKS:
                findings.append(dict(
                    kind="unknown_quirk", row=r.name,
                    detail=f"row {r.name} cites undocumented quirk {q}"))
            cited_quirks.add(q)
    for name, anchors in handlers.TRANSITION_ANCHORS.items():
        missing = set(anchors) - cited_anchors.get(name, set())
        if missing:
            findings.append(dict(
                kind="uncited_anchor", message=name,
                detail=f"registered {name} anchors never cited by any row: "
                       f"{sorted(missing)}"))
    missing_q = set(handlers.QUIRKS) - cited_quirks
    if missing_q:
        findings.append(dict(
            kind="uncited_quirk",
            detail=f"documented quirks never cited by any row: "
                   f"{sorted(missing_q)}"))
    return findings


# ---------------------------------------------------------------------------
# driver
# ---------------------------------------------------------------------------

PASSES = (
    ("totality_determinism", check_totality_determinism),
    ("conservation", check_conservation),
    ("stability", check_stability),
    ("anchors", check_anchors),
)


def verify(table: ProtocolTable) -> dict:
    """Run all passes; report in the model checker's shape."""
    findings, passes = [], {}
    for pname, fn in PASSES:
        f = fn(table)
        passes[pname] = "fail" if f else "ok"
        findings.extend(f)
    return dict(
        table=table.name, protocol=table.protocol, rows=len(table.rows),
        passes=passes, findings=findings, ok=not findings,
    )
