"""Trace shrinking: ddmin a diverging fuzz case to a minimal repro.

Zeller's delta debugging over the flattened (node, instruction) list of
a :class:`..analysis.fuzz.FuzzCase` — per-node program order is
preserved, everything else (dimensions, schedule knobs, arbitration) is
held fixed so the predicate stays deterministic. The predicate is "the
same verdict kind reproduces" under :func:`fuzz.run_case`, so a shrink
of a ``state`` divergence cannot silently drift into a different bug.

The minimized case is emitted as a ready-to-run fixture directory —
``core_<n>.txt`` files in the exact reference trace format
(``RD 0x<addr>`` / ``WR 0x<addr> <value>``, parseable by
utils.trace.load_test_dir and the reference's own ``fscanf`` loop) plus
``repro.json`` (the full case + verdict) and ``trace.perfetto.json``, a
Perfetto event trace of the diverging run captured through
ops.step.run_cycles_traced and obs/perfetto.py.
"""

from __future__ import annotations

import dataclasses
import json
import os
from typing import Callable, List, Optional, Tuple

import numpy as np

from ue22cs343bb1_openmp_assignment_tpu.analysis import fixtures, fuzz
from ue22cs343bb1_openmp_assignment_tpu.obs import perfetto
from ue22cs343bb1_openmp_assignment_tpu.ops import step
from ue22cs343bb1_openmp_assignment_tpu.state import init_state
from ue22cs343bb1_openmp_assignment_tpu.utils import eventlog

#: cycles captured into the emitted Perfetto trace (enough for any
#: shrunk repro at reference dimensions to reach quiescence or expose
#: its hang)
TRACE_CYCLES = 256


def _flatten(case: fuzz.FuzzCase) -> List[Tuple[int, tuple]]:
    return [(n, ins) for n, tr in enumerate(case.traces) for ins in tr]


def _rebuild(case: fuzz.FuzzCase,
             items: List[Tuple[int, tuple]]) -> fuzz.FuzzCase:
    per_node: list = [[] for _ in range(case.num_nodes)]
    for n, ins in items:
        per_node[n].append(ins)
    return dataclasses.replace(
        case, traces=tuple(tuple(tr) for tr in per_node))


def ddmin(items: list, test: Callable[[list], bool]) -> list:
    """Classic ddmin: assumes test(items) is True; returns a 1-minimal
    sublist (order-preserving) still satisfying test."""
    n = 2
    while len(items) >= 2:
        size = len(items) // n
        chunks = [items[i:i + size] for i in range(0, len(items), size)]
        reduced = False
        for c in chunks:                      # try each subset
            if len(c) < len(items) and test(c):
                items, n, reduced = c, 2, True
                break
        if not reduced:
            for i in range(len(chunks)):      # try each complement
                comp = [x for j, c in enumerate(chunks) if j != i
                        for x in c]
                if len(comp) < len(items) and test(comp):
                    items, n = comp, max(2, n - 1)
                    reduced = True
                    break
        if not reduced:
            if n >= len(items):
                break
            n = min(len(items), 2 * n)
    return items


def shrink_case(case: fuzz.FuzzCase,
                message_phase: Optional[Callable] = None,
                verdict: Optional[str] = None) -> dict:
    """Minimize ``case`` to the fewest instructions that still produce
    the same verdict kind. Returns {case, verdict, detail, runs,
    instrs_before, instrs_after}; predicate results are memoized so the
    engine runs once per distinct candidate."""
    if verdict is None:
        verdict = fuzz.run_case(case, message_phase)["verdict"]
    if verdict == "ok":
        raise ValueError("refusing to shrink a passing case")
    cache: dict = {}
    runs = [0]

    def test(items: list) -> bool:
        key = tuple(items)
        if key not in cache:
            runs[0] += 1
            res = fuzz.run_case(_rebuild(case, list(items)),
                                message_phase)
            cache[key] = res["verdict"] == verdict
        return cache[key]

    items = _flatten(case)
    kept = ddmin(items, test)
    small = _rebuild(case, kept)
    res = fuzz.run_case(small, message_phase)
    assert res["verdict"] == verdict, "shrink lost the bug"
    return {"case": small, "verdict": verdict, "detail": res["detail"],
            "runs": runs[0], "instrs_before": len(items),
            "instrs_after": len(kept)}


def shrink_recording(rec: dict,
                     predicate: Callable[[dict], bool]
                     ) -> Tuple[dict, int]:
    """ddmin over a traffic recording's JOB LIST: jobs — not
    instructions — are the atoms here, because the failure being
    preserved (an SLO breach, a stats anomaly) is a property of the
    SCHEDULE, not of any one trace. ``predicate`` takes a
    sub-recording (obs.recording doc) and answers "does the failure
    still reproduce on replay?"; it must hold on the full recording.
    Returns ``(minimal sub-recording, replays run)`` — 1-minimal:
    dropping any single remaining job loses the failure. Predicate
    results are memoized per job subset, so each distinct candidate
    replays once."""
    from ue22cs343bb1_openmp_assignment_tpu.obs import recording
    jobs = [row["job"] for row in rec["rows"]
            if row["event"] == "submit"]
    cache: dict = {}
    runs = [0]

    def test(names: list) -> bool:
        key = frozenset(names)
        if key not in cache:
            runs[0] += 1
            cache[key] = bool(predicate(recording.subset(rec, key)))
        return cache[key]

    if not test(jobs):
        raise ValueError("refusing to shrink: the predicate does not "
                         "hold on the full recording")
    kept = ddmin(jobs, test)
    return recording.subset(rec, kept), runs[0]


# -- repro emission --------------------------------------------------------

# kept as an alias: obs/flight.py and older callers import the private
# name; the canonical renderer lives in analysis/fixtures.py now
_trace_lines = fixtures.trace_lines


def emit_repro(shrunk: dict, out_dir: str,
               message_phase: Optional[Callable] = None) -> dict:
    """Write the shrunk case as a fixture directory
    (:func:`..analysis.fixtures.write_fixture`: per-node
    ``core_<n>.txt`` in the reference trace format + ``repro.json``)
    plus a validated ``trace.perfetto.json`` of the diverging run.
    Returns the repro metadata dict."""
    case = shrunk["case"]
    cfg = case.config()
    os.makedirs(out_dir, exist_ok=True)
    st = init_state(cfg, case.trace_lists(),
                    issue_delay=np.array(case.delays, np.int32),
                    issue_period=np.array(case.periods, np.int32),
                    arb_rank=np.array(case.rank, np.int32))
    _, events = step.run_cycles_traced(cfg, st, TRACE_CYCLES,
                                       message_phase)
    doc = perfetto.build_trace(eventlog.to_records(events),
                               cfg.num_nodes)
    perfetto.validate_trace(doc)
    perfetto.write_trace(os.path.join(out_dir, "trace.perfetto.json"),
                         doc)

    return fixtures.write_fixture(
        out_dir, case, shrunk["verdict"], shrunk["detail"],
        extra_files=["trace.perfetto.json"])


def shrink_findings(report: dict, out_root: Optional[str] = None,
                    message_phase: Optional[Callable] = None,
                    limit: int = 3) -> list:
    """Shrink up to ``limit`` findings of a fuzz report; returns the
    shrunk summaries (and writes repro dirs under ``out_root`` when
    given)."""
    out = []
    for k, finding in enumerate(report.get("findings", [])[:limit]):
        case = fuzz.case_from_dict(finding["case"])
        shrunk = shrink_case(case, message_phase,
                             verdict=finding["verdict"])
        if out_root is not None:
            emit_repro(shrunk, os.path.join(
                out_root, f"repro_{case.case_id}"), message_phase)
        out.append({"case_id": case.case_id,
                    "verdict": shrunk["verdict"],
                    "detail": shrunk["detail"],
                    "instrs_before": shrunk["instrs_before"],
                    "instrs_after": shrunk["instrs_after"],
                    "runs": shrunk["runs"]})
    return out
