"""Static kernel-contract verifier for the fused Pallas round.

The fused round kernel (ops/pallas_round) rests on three contracts
that used to live as hand proofs in PERF.md prose and a hard-coded
``2**14`` in ``supported()``:

1. **Exact arithmetic** — the chunked-exponent scatter-min ladder
   recovers the per-entry minimum chunk exactly provided the per-entry
   contender count stays under a rounding cap. This module *derives*
   that cap from the ladder parameters (chunk bits, weight-exponent
   gap G, f32 mantissa width) instead of trusting the constant, checks
   the f32 range the ladder spans, and machine-checks the
   rounding-safety lemmas: the symbolic summation-error bound in exact
   rational arithmetic (`fractions`) and the min-chunk readout on
   adversarial contender multisets evaluated in real float32.

2. **VMEM footprint** — the kernel keeps all round state resident in
   VMEM, so its peak live bytes must fit the device's VMEM. The
   resident I/O side comes from the kernel's own block-shape table
   (``pallas_round._block_shapes`` — the same table ``_call_round``
   builds its BlockSpecs from); the transient side comes from a
   liveness walk over the traced jaxpr of ``pallas_round._round_body``
   — the code object the kernel actually runs. Budgets come from the
   per-device ``vmem_bytes`` column of obs/roofline's peaks table.

3. **Mosaic lowerability** — the same traced jaxpr is audited for
   primitives that do not lower on TPU (vector gather/scatter, sort,
   64-bit dtypes, dynamic shapes, host callbacks), so
   interpret-mode-only surprises become a named findings list.

The payoff (`derived_bounds`): ``pallas_round.supported()`` delegates
its contender gate here. The derivation splits the legacy
``deep_slots * num_nodes`` bound into its two real factors — the
*rounding cap* (a pure ladder property, ``cap_limit``) and the
*per-entry contender count* (an engine property: at ``deep_waves ==
1`` the window's dup stop admits at most ONE same-entry event per node
per round, ops/deep_fold, so contenders <= N rather than N * Q) —
which WIDENS the gate for single-wave configs: deep@8192 with 3 slots
was rejected by the legacy product bound (24576 >= 2**14) and is
admitted by the derived one (8192 < 2**14). Read-storm stays a
*structural* gate, not a margin: duplicate-row storm commits break the
routed scatters' uniqueness contract (ops/deep_engine raises on
storm + non-native index ops), which no rounding analysis can lift.

Seeded mutants in analysis/mutations.KERNEL_MUTATIONS perturb the
real kernel parameters (chunk width, exponent gap, the gate itself)
and tests require every one to be caught statically — the verifier's
own regression suite, in the verify_table / model-checker tradition.
CLI surface: ``cache-sim analyze --kernel`` (analysis/runner.py).
"""

from __future__ import annotations

import dataclasses
import functools
from fractions import Fraction
from typing import List, Optional

import numpy as np

from ue22cs343bb1_openmp_assignment_tpu.config import SystemConfig

SCHEMA = "cache-sim/kernelcheck/v1"

#: IEEE-754 binary32: significand precision (bits), normal exponent
#: range. The ladder routes powers of two and sums them on the MXU in
#: f32 — these three numbers are where every derived margin comes from.
F32_MANTISSA = 24
F32_MIN_EXP = -126
F32_MAX_EXP = 127

#: banned-primitive patterns for the Mosaic-lowerability lint: TPU
#: Pallas has no vector gather/scatter or sort lowering, and host
#: round-trips cannot appear inside a kernel body. (Checked against
#: the *traced* body — the routed one-hot design exists precisely so
#: none of these occur; a regression reintroducing one shows up here
#: before the first real-TPU compile.)
_BANNED_EXACT = ("gather", "sort", "top_k", "infeed", "outfeed")
_BANNED_PREFIX = ("scatter",)
_WIDE_DTYPES = ("int64", "uint64", "float64")


# ---------------------------------------------------------------------------
# pass 1: exact arithmetic — derive the ladder cap, check the lemmas
# ---------------------------------------------------------------------------

def _ladder_params() -> tuple:
    """(A, G, chunk_bits) read from the kernel module — the analyzer
    audits the constants the kernel actually routes with, so seeded
    mutations of those constants are visible here."""
    from ue22cs343bb1_openmp_assignment_tpu.ops import pallas_round as pr
    return pr._MIN_A, pr._MIN_G, pr._MIN_CHUNK_BITS


@functools.lru_cache(maxsize=None)
def exact_cap(G: int, mantissa: int = F32_MANTISSA) -> int:
    """Largest contender count R whose worst-case rounded ladder sum
    provably stays under the next chunk threshold, in exact rational
    arithmetic.

    All contenders of a pass route weights <= w_m (m the true minimum
    chunk), so the exact sum is <= R * w_m; the standard
    any-summation-order forward error bound gives ``fl(sum) <= sum *
    (1 + eps)**(R - 1)`` with ``eps = 2**-mantissa``. Recovery needs
    ``fl(sum) < 2**G * w_m`` (the next threshold up), so the cap is
    the largest R with ``R * (1 + eps)**(R - 1) < 2**G`` — evaluated
    with `fractions.Fraction` (no float anywhere), found by bisection.
    ~32.7k at G=15/f32."""
    eps = Fraction(1, 1 << mantissa)
    lim = 1 << G

    def safe(R: int) -> bool:
        return R * (1 + eps) ** (R - 1) < lim

    lo, hi = 1, lim          # safe(1) trivially; safe(2**G) false
    while hi - lo > 1:
        mid = (lo + hi) // 2
        if safe(mid):
            lo = mid
        else:
            hi = mid
    return lo


def derived_bounds(cfg: SystemConfig) -> dict:
    """The fused-round gate quantities, derived per config.

    - ``cap_exact``: the exact-rational rounding cap (`exact_cap`).
    - ``cap_limit``: the certified cap the gate uses — the largest
      power of two <= cap_exact. The spare sub-doubling margin absorbs
      accumulation-model slop (the MXU's internal summation order and
      FMA behavior are not architecturally pinned); at G=15 this lands
      exactly on the legacy hand-proved 2**14.
    - ``max_contenders``: the per-entry contender bound. The
      scatter-min sums are PER ENTRY, so only same-entry contention
      matters: one lane event per (node, entry) at ``deep_waves == 1``
      (the dup window-stop, ops/deep_fold — a second remote event on
      an already-slotted entry stops the window), ``deep_slots`` per
      node otherwise (slot-keyed re-touches compose across waves).
    """
    A, G, cb = _ladder_params()
    nvals = 1 << cb
    N = cfg.num_nodes
    from ue22cs343bb1_openmp_assignment_tpu.ops.sync_engine import \
        slot_bits
    prio_bits = max(1, (N - 1).bit_length())
    L = (prio_bits + 1 + slot_bits(cfg)
         + (1 if cfg.deep_read_storm else 0))
    cap = exact_cap(G)
    return {
        "A": A, "G": G, "chunk_bits": cb,
        "L_bits": L, "num_passes": max(1, -(-L // cb)),
        "ladder_min_exp": A - G * (nvals - 1),
        "ladder_max_exp": A + G,
        "cap_exact": cap,
        "cap_limit": 1 << (cap.bit_length() - 1),
        "contenders_per_node": 1 if cfg.deep_waves == 1
        else cfg.deep_slots,
        "max_contenders": N * (1 if cfg.deep_waves == 1
                               else cfg.deep_slots),
    }


def _decode_chunk(ssum: np.float32, A: int, G: int, nvals: int) -> int:
    """The kernel's min-chunk readout (_route_min's threshold count),
    replicated on one scalar f32 sum."""
    c = 0
    for v in range(nvals):
        if ssum < np.float32(2.0 ** (A - G * v)):
            c += 1
    return min(c, nvals - 1)


def _f32_sum(weights: np.ndarray) -> np.float32:
    """Strict sequential round-to-nearest f32 accumulation — one
    admissible order under the any-order error bound exact_cap
    certifies against."""
    acc = np.float32(0.0)
    for w in weights:
        acc = np.float32(acc + np.float32(w))
    return acc


def check_exactness(cfg: SystemConfig) -> dict:
    """Pass 1: derive the caps and machine-check the rounding lemmas.

    Findings:
    - ``ladder_range``: a ladder weight or threshold leaves f32's
      normal range (weights must be *exact* powers of two — a
      subnormal/overflowed rung breaks the readout silently).
    - ``rounding_lemma``: a machine-checked lemma failed — either the
      symbolic cap margin (exact rational arithmetic) or a concrete
      adversarial-multiset readout evaluated in real float32.
    - ``contender_cap``: this config's per-entry contender bound
      reaches the certified cap.
    """
    b = derived_bounds(cfg)
    A, G, cb = b["A"], b["G"], b["chunk_bits"]
    nvals = 1 << cb
    findings: List[dict] = []

    def find(kind, detail):
        findings.append({"pass": "exactness", "kind": kind,
                         "detail": detail})

    # f32 range: every rung and every threshold must be a normal,
    # exactly-representable power of two, and the worst-case rounded
    # sum (< 2**(A+G) by the cap lemma) must not overflow
    if b["ladder_min_exp"] < F32_MIN_EXP:
        find("ladder_range",
             f"lowest rung 2**{b['ladder_min_exp']} is below f32's "
             f"minimum normal 2**{F32_MIN_EXP} "
             f"(A={A}, G={G}, {nvals}-value chunks)")
    if b["ladder_max_exp"] > F32_MAX_EXP:
        find("ladder_range",
             f"threshold headroom 2**{b['ladder_max_exp']} exceeds "
             f"f32's maximum exponent 2**{F32_MAX_EXP}")
    # 16-bit-halves side contract of the one-hot matmuls: each half
    # must be an exact f32 integer
    if 16 > F32_MANTISSA:
        find("ladder_range",
             "16-bit halves no longer exact in the routing float")

    lemmas = {}
    if not findings:
        # lemma: symbolic cap margin, exact rational arithmetic —
        # cap_exact is the LARGEST safe count (its successor violates
        # the bound: the tightness witness), and cap_limit is a power
        # of two at or under it
        eps = Fraction(1, 1 << F32_MANTISSA)
        cap, lim = b["cap_exact"], b["cap_limit"]
        ok_cap = (cap * (1 + eps) ** (cap - 1) < (1 << G)
                  <= (cap + 1) * (1 + eps) ** cap)
        ok_lim = lim <= cap and lim == 1 << (lim.bit_length() - 1)
        lemmas["cap_margin_symbolic"] = bool(ok_cap and ok_lim)
        if not lemmas["cap_margin_symbolic"]:
            find("rounding_lemma",
                 f"symbolic cap margin failed: cap_exact={cap}, "
                 f"cap_limit={lim}, G={G}")

        # lemma: adversarial f32 readouts. R contenders, true minimum
        # chunk m — the readout must decode m for (a) a single
        # contender (threshold-equality edge), (b) cap_limit - 1
        # contenders all at m (largest admissible exact sum), (c) a
        # mixed multiset: bulk at m plus one contender at every deeper
        # chunk, summed ascending and descending (rounding-order
        # adversaries under the any-order bound).
        R = b["cap_limit"] - 1
        ok = True
        for m in range(nvals):
            w_m = np.float32(2.0 ** (A - G * m))
            cases = [np.full(1, w_m, np.float32),
                     np.full(R, w_m, np.float32)]
            deeper = np.array([2.0 ** (A - G * v)
                               for v in range(m + 1, nvals)], np.float32)
            if deeper.size:
                mix = np.concatenate(
                    [np.full(R - deeper.size, w_m, np.float32), deeper])
                cases += [np.sort(mix), np.sort(mix)[::-1]]
            for arr in cases:
                got = _decode_chunk(_f32_sum(arr), A, G, nvals)
                if got != m:
                    ok = False
                    find("rounding_lemma",
                         f"f32 readout decoded chunk {got}, want {m} "
                         f"({arr.size} contenders)")
                    break
        lemmas["readout_adversarial_f32"] = ok

    if b["max_contenders"] >= b["cap_limit"]:
        find("contender_cap",
             f"per-entry contenders {b['max_contenders']} "
             f"(N={cfg.num_nodes} x {b['contenders_per_node']}/node at "
             f"deep_waves={cfg.deep_waves}) >= certified cap "
             f"{b['cap_limit']}")

    return {"bounds": b, "lemmas": lemmas, "findings": findings,
            "ok": not findings}


# ---------------------------------------------------------------------------
# pass 2 + 3 shared: trace the real kernel body
# ---------------------------------------------------------------------------

def trace_round_body(cfg: SystemConfig):
    """``jax.make_jaxpr`` over ``pallas_round._round_body`` at this
    config's block shapes — the exact code object ``_round_kernel``
    wraps between its VMEM load and store. Abstract tracing only:
    nothing executes, no pallas grid is entered."""
    import jax
    import jax.numpy as jnp
    from ue22cs343bb1_openmp_assignment_tpu.ops import pallas_round as pr
    ins, _ = pr._block_shapes(cfg)
    args = [jax.ShapeDtypeStruct(s, jnp.int32) for s in ins]
    return jax.make_jaxpr(functools.partial(pr._round_body, cfg))(*args)


def _subjaxprs(v):
    vs = v if isinstance(v, (list, tuple)) else [v]
    for s in vs:
        if hasattr(s, "jaxpr"):        # ClosedJaxpr
            yield s.jaxpr
        elif hasattr(s, "eqns"):       # raw Jaxpr
            yield s


def _nbytes(aval) -> int:
    shape = getattr(aval, "shape", None)
    dt = getattr(aval, "dtype", None)
    if shape is None or dt is None:
        return 0
    n = 1
    for d in shape:
        if not isinstance(d, int):
            return 0
        n *= d
    return n * dt.itemsize


def peak_live_bytes(jaxpr) -> int:
    """Peak simultaneously-live bytes of one jaxpr under a last-use
    liveness model with in-place reuse.

    Walk the equations in order tracking the live set (a value is live
    from its defining equation to its last use; jaxpr outputs live to
    the end). At each equation, operands whose last use is *this*
    equation are freed before the outputs allocate — the buffer-reuse
    model real allocators (XLA buffer assignment, Mosaic's VMEM
    allocator) apply to dying operands. Sub-jaxprs (fori_loop bodies,
    pjit calls) contribute ``max(0, inner peak - inner input bytes)``
    on top of the outer live set: their inputs alias outer buffers
    already counted.

    The walk is deterministic per traced program, so the number can be
    pinned in tests and gated in CI like any other static contract."""
    from jax.core import DropVar, Literal
    last = {}
    for i, eqn in enumerate(jaxpr.eqns):
        for v in eqn.invars:
            if not isinstance(v, Literal):
                last[v] = i
    for v in jaxpr.outvars:
        if not isinstance(v, Literal):
            last[v] = len(jaxpr.eqns)
    live = {}
    for v in list(jaxpr.invars) + list(jaxpr.constvars):
        if last.get(v, -1) >= 0:
            live[v] = _nbytes(v.aval)
    cur = sum(live.values())
    peak = cur
    for i, eqn in enumerate(jaxpr.eqns):
        freed = 0
        for v in set(x for x in eqn.invars if not isinstance(x, Literal)):
            if last.get(v) == i and v in live:
                freed += live.pop(v)
        outb = sum(_nbytes(v.aval) for v in eqn.outvars)
        inner = 0
        for par in eqn.params.values():
            for sub in _subjaxprs(par):
                sub_in = sum(_nbytes(v.aval) for v in
                             list(sub.invars) + list(sub.constvars))
                inner = max(inner,
                            max(0, peak_live_bytes(sub) - sub_in))
        peak = max(peak, cur - freed + outb + inner)
        cur -= freed
        for v in eqn.outvars:
            if not isinstance(v, DropVar) and last.get(v, -1) > i:
                b = _nbytes(v.aval)
                live[v] = b
                cur += b
    return peak


# ---------------------------------------------------------------------------
# pass 2: static VMEM footprint
# ---------------------------------------------------------------------------

def resident_bytes(cfg: SystemConfig) -> tuple:
    """(input_bytes, output_bytes) resident in VMEM for the fused
    round's pallas_call blocks, from the kernel's own block-shape
    table (all blocks int32)."""
    from ue22cs343bb1_openmp_assignment_tpu.ops import pallas_round as pr
    ins, outs = pr._block_shapes(cfg)
    return (4 * sum(r * c for r, c in ins),
            4 * sum(r * c for r, c in outs))


def vmem_verdict(resident_in: int, resident_out: int,
                 peak_bytes: Optional[int], grid_steps: int,
                 vmem_bytes: int) -> dict:
    """The budget rule, factored out so boundary semantics are pinned
    by tests: required = max(resident, traced peak) + double-buffer
    headroom, failing strictly over budget (exactly-at-budget passes).

    Headroom: a multi-step grid revolves its input blocks (two copies
    in flight while the pipeline overlaps copy-in with compute), so
    headroom = resident inputs again; the fused round runs the whole
    round at grid (1,) — single buffering, no headroom."""
    resident = resident_in + resident_out
    headroom = resident_in if grid_steps > 1 else 0
    required = max(resident, peak_bytes or 0) + headroom
    return {"resident_in_bytes": int(resident_in),
            "resident_out_bytes": int(resident_out),
            "peak_bytes": None if peak_bytes is None else int(peak_bytes),
            "grid_steps": int(grid_steps),
            "headroom_bytes": int(headroom),
            "required_bytes": int(required),
            "vmem_bytes": int(vmem_bytes),
            "ok": required <= vmem_bytes}


def vmem_rows(cfg: SystemConfig, device_kind: Optional[str] = None,
              trace: bool = True, closed=None) -> list:
    """Per-kernel VMEM rows (the fused round is the only kernel with
    whole-round state residency; the fold/window kernels stream [1, N]
    blocks and are budgeted by the same rule trivially). With
    ``trace=False`` only the static block-table side is accounted —
    the cheap, always-deterministic row perf-report embeds. ``closed``
    shares an already-traced body across passes."""
    from ue22cs343bb1_openmp_assignment_tpu.obs import roofline
    peaks = roofline.device_peaks(device_kind)
    r_in, r_out = resident_bytes(cfg)
    peak = None
    if closed is not None:
        peak = peak_live_bytes(closed.jaxpr)
    elif trace:
        peak = peak_live_bytes(trace_round_body(cfg).jaxpr)
    row = vmem_verdict(r_in, r_out, peak, grid_steps=1,
                       vmem_bytes=peaks["vmem_bytes"])
    row.update(kernel="deep.round_fused", device_kind=peaks["kind"],
               basis="block-table" if peak is None else "traced-liveness")
    return [row]


def check_vmem(cfg: SystemConfig, device_kind: Optional[str] = None,
               trace: bool = True, closed=None) -> dict:
    """Pass 2: fail any kernel whose required bytes exceed the
    device's VMEM (finding kind ``vmem_budget``)."""
    rows = vmem_rows(cfg, device_kind=device_kind, trace=trace,
                     closed=closed)
    findings = [{"pass": "vmem", "kind": "vmem_budget",
                 "detail": f"{r['kernel']}: required "
                           f"{r['required_bytes']} B > VMEM "
                           f"{r['vmem_bytes']} B on {r['device_kind']}"}
                for r in rows if not r["ok"]]
    return {"rows": rows, "findings": findings, "ok": not findings}


# ---------------------------------------------------------------------------
# pass 3: Mosaic lowerability
# ---------------------------------------------------------------------------

def audit_lowerability(jaxpr, findings: List[dict],
                       target: str = "pallas_round.round_body") -> int:
    """Walk a traced kernel body for constructs with no TPU Pallas
    lowering; returns the flattened equation count. Finding kinds:
    ``mosaic_lowerability`` (banned primitive), ``wide_dtype``,
    ``dynamic_shape``, ``host_callback``."""
    n = 0
    stack = [jaxpr]
    while stack:
        j = stack.pop()
        for eqn in j.eqns:
            n += 1
            name = eqn.primitive.name
            if "callback" in name:
                findings.append({"pass": "lowerability",
                                 "kind": "host_callback",
                                 "detail": f"{target}: primitive "
                                           f"{name!r}"})
            elif (name in _BANNED_EXACT
                  or any(name.startswith(p) for p in _BANNED_PREFIX)):
                findings.append({"pass": "lowerability",
                                 "kind": "mosaic_lowerability",
                                 "detail": f"{target}: primitive "
                                           f"{name!r} has no TPU "
                                           "vector lowering"})
            nd = eqn.params.get("new_dtype")
            if nd is not None and str(nd) in _WIDE_DTYPES:
                findings.append({"pass": "lowerability",
                                 "kind": "wide_dtype",
                                 "detail": f"{target}: convert -> {nd}"})
            for var in eqn.outvars:
                aval = var.aval
                dt = getattr(aval, "dtype", None)
                if dt is not None and str(dt) in _WIDE_DTYPES:
                    findings.append({"pass": "lowerability",
                                     "kind": "wide_dtype",
                                     "detail": f"{target}: {name} "
                                               f"output {dt}"})
                for dim in getattr(aval, "shape", ()):
                    if not isinstance(dim, int):
                        findings.append({"pass": "lowerability",
                                         "kind": "dynamic_shape",
                                         "detail": f"{target}: {name} "
                                                   f"dim {dim!r}"})
            for v in eqn.params.values():
                stack.extend(_subjaxprs(v))
    return n


def check_lowerability(cfg: SystemConfig, closed=None) -> dict:
    """Pass 3 over the fused body's jaxpr (retraces unless the caller
    shares one trace across passes)."""
    closed = trace_round_body(cfg) if closed is None else closed
    findings: List[dict] = []
    n = audit_lowerability(closed.jaxpr, findings)
    return {"eqns": n, "findings": findings, "ok": not findings}


# ---------------------------------------------------------------------------
# pass 4: gate consistency — supported() must equal the derivation
# ---------------------------------------------------------------------------

def _probe_configs() -> list:
    """A small config family spanning every gate edge: the headline,
    the newly widened single-wave deep@8192, the multi-wave config the
    widening must NOT admit, the cap boundary, a storm config and a
    non-deep config."""
    mk = lambda n, dd, tw, **kw: dataclasses.replace(
        SystemConfig.scale(num_nodes=n, drain_depth=dd, txn_width=tw),
        **{"deep_window": True, "deep_ownerval_slots": 1, **kw})
    return [
        ("headline_4096", mk(4096, 13, 3, deep_slots=3)),
        ("widened_8192_q3_w1", mk(8192, 2, 2, deep_slots=3)),
        ("multiwave_8192_q3_w2",
         mk(8192, 2, 2, deep_slots=3, deep_waves=2)),
        ("cap_boundary_16384", mk(16384, 2, 2, deep_slots=2)),
        ("storm_256", mk(256, 2, 2, deep_slots=2,
                         deep_read_storm=True, deep_ownerval_slots=2)),
        ("xla_only_256", dataclasses.replace(
            SystemConfig.scale(num_nodes=256, drain_depth=2,
                               txn_width=2), deep_window=False)),
    ]


def analyzer_admits(cfg: SystemConfig) -> bool:
    """The analyzer's own verdict on a config: structural gates
    (deep-window only; no read-storm — the storm's duplicate-row
    commits break the routed scatters' uniqueness contract, a property
    of the engine, not of rounding) plus the derived contender cap."""
    if not cfg.deep_window or cfg.deep_read_storm:
        return False
    b = derived_bounds(cfg)
    return b["max_contenders"] < b["cap_limit"]


def check_gates() -> dict:
    """Pass 4: over the probe family, ``pallas_round.supported`` must
    agree with `analyzer_admits` exactly — a gate that drifts from its
    proof artifact (or a tampered proof) is finding
    ``gate_divergence``. Also records the legacy product bound's
    verdict per probe, making the widening auditable."""
    from ue22cs343bb1_openmp_assignment_tpu.ops import pallas_round as pr
    findings: List[dict] = []
    probes = {}
    for name, cfg in _probe_configs():
        sup = bool(pr.supported(cfg))
        adm = analyzer_admits(cfg)
        legacy = bool(cfg.deep_window and not cfg.deep_read_storm
                      and cfg.deep_slots * cfg.num_nodes < (1 << 14))
        probes[name] = {"supported": sup, "analyzer": adm,
                        "legacy_product_bound": legacy,
                        "widened": sup and not legacy}
        if sup != adm:
            findings.append({
                "pass": "gates", "kind": "gate_divergence",
                "detail": f"{name}: supported()={sup} but the "
                          f"derivation says {adm}"})
    return {"probes": probes, "findings": findings, "ok": not findings}


# ---------------------------------------------------------------------------
# the report
# ---------------------------------------------------------------------------

def headline_config(num_nodes: int = 4096) -> SystemConfig:
    """The deep-engine headline config (bench.py / cmd_perfreport deep
    defaults) the default ``analyze --kernel`` run verifies."""
    return dataclasses.replace(
        SystemConfig.scale(num_nodes=num_nodes, drain_depth=13,
                           txn_width=3),
        deep_window=True,
        deep_slots=2 if num_nodes >= 32768 else 3,
        deep_ownerval_slots=1, deep_horizon_slack=4)


def check(cfg: Optional[SystemConfig] = None, trace: bool = True,
          device_kind: Optional[str] = None) -> dict:
    """Run all four passes; ``trace=False`` restricts to the
    arithmetic/static passes (no jaxpr trace — the fast path mutation
    smokes use; VMEM is then block-table-only and lowerability is
    skipped)."""
    cfg = headline_config() if cfg is None else cfg
    ex = check_exactness(cfg)
    gates = check_gates()
    closed = trace_round_body(cfg) if trace else None
    vm = check_vmem(cfg, device_kind=device_kind, trace=False,
                    closed=closed)
    low = (check_lowerability(cfg, closed) if closed is not None
           else {"eqns": None, "findings": [], "ok": None})
    findings = (ex["findings"] + vm["findings"] + low["findings"]
                + gates["findings"])
    return {"schema": SCHEMA,
            "config": {"num_nodes": cfg.num_nodes,
                       "deep_slots": cfg.deep_slots,
                       "deep_waves": cfg.deep_waves,
                       "drain_depth": cfg.drain_depth,
                       "txn_width": cfg.txn_width},
            "traced": bool(trace),
            "exactness": ex, "vmem": vm, "lowerability": low,
            "gates": gates,
            "findings": findings, "ok": not findings}


def render_text(rep: dict) -> list:
    """One line per pass plus findings — the runner's print format."""
    b = rep["exactness"]["bounds"]
    c = rep["config"]
    lines = [
        f"== kernel contracts: {'ok' if rep['ok'] else 'FAIL'} "
        f"[deep@{c['num_nodes']} q{c['deep_slots']} "
        f"w{c['deep_waves']}; traced={rep['traced']}]",
        f"   exactness: ladder A={b['A']} G={b['G']} "
        f"chunk={b['chunk_bits']}b span 2**[{b['ladder_min_exp']},"
        f"{b['ladder_max_exp']}]; cap {b['cap_limit']} "
        f"(exact {b['cap_exact']}); contenders/entry "
        f"{b['max_contenders']}",
    ]
    for r in rep["vmem"]["rows"]:
        pk = ("-" if r["peak_bytes"] is None
              else f"{r['peak_bytes'] / 2**20:.2f}")
        lines.append(
            f"   vmem[{r['kernel']}] ({r['basis']}): resident "
            f"{(r['resident_in_bytes'] + r['resident_out_bytes']) / 2**20:.2f}"
            f" MiB, peak {pk} MiB, budget "
            f"{r['vmem_bytes'] / 2**20:.0f} MiB ({r['device_kind']})")
    if rep["lowerability"]["ok"] is not None:
        lines.append(f"   lowerability: {rep['lowerability']['eqns']} "
                     f"flattened eqns, banned-primitive scan "
                     f"{'clean' if rep['lowerability']['ok'] else 'FAIL'}")
    w = [n for n, p in rep["gates"]["probes"].items() if p["widened"]]
    lines.append(f"   gates: {len(rep['gates']['probes'])} probes, "
                 f"widened vs legacy product bound: "
                 f"{', '.join(w) if w else 'none'}")
    for f in rep["findings"]:
        lines.append(f"  ! {f['pass']}/{f['kind']}: {f['detail']}")
    return lines
