"""Sharded execution of the simulation cycle over a device mesh.

Follows the canonical JAX scaling recipe: pick a mesh, annotate
shardings, let XLA insert the collectives. ``cycle`` is pure and
shape-static, so jitting it with node-axis shardings makes GSPMD
partition every per-node update and turn the delivery scatter's
cross-shard writes into ICI collectives — no NCCL/MPI-style hand-rolled
transport (the reference's analog was in-process locked queues,
``assignment.c:741-765``).

The number of simulated nodes must be divisible by the mesh size.
"""

from __future__ import annotations

import functools

import jax

from ue22cs343bb1_openmp_assignment_tpu.config import SystemConfig
from ue22cs343bb1_openmp_assignment_tpu.ops.step import cycle
from ue22cs343bb1_openmp_assignment_tpu.parallel.mesh import state_shardings


def make_sharded_cycle(cfg: SystemConfig, mesh, example_state):
    """jit one cycle with node-axis in/out shardings over `mesh`."""
    sh = state_shardings(cfg, mesh, example_state)
    return jax.jit(lambda s: cycle(cfg, s), in_shardings=(sh,),
                   out_shardings=sh)


def make_sharded_runner(cfg: SystemConfig, mesh, example_state,
                        num_cycles: int):
    """jit a `num_cycles`-cycle scan with node-axis shardings."""
    from ue22cs343bb1_openmp_assignment_tpu.ops.step import _ro_outside
    sh = state_shardings(cfg, mesh, example_state)

    @functools.partial(jax.jit, in_shardings=(sh,), out_shardings=sh)
    def run(state):
        # read-only arrays stay out of the scan carry (ops.step hoist)
        carry0, ro, blanks = _ro_outside(state)

        def body(s, _):
            out = cycle(cfg, s.replace(**ro))
            return out.replace(**blanks), None

        final, _ = jax.lax.scan(body, carry0, None, length=num_cycles)
        return final.replace(**ro)

    return run


def make_sharded_ledger_runner(cfg: SystemConfig, mesh, example_state,
                               num_cycles: int):
    """jit a `num_cycles`-cycle scan that also stacks the per-cycle
    message ledger (ops.step cycle with_ledger) — the multi-chip twin
    of ``run_cycles_telemetry(..., with_ledger=True)`` minus the
    telemetry planes. Every ledger plane is node-major ([T, N] or
    [T, N, S]), so GSPMD partitions the capture along the same node
    axis as the state and the stacked output gathers back bit-identical
    to the unsharded run (tests/test_txntrace.py pins this: the
    arbitration sort is a total order, so sharding cannot reorder
    deliveries).
    """
    from ue22cs343bb1_openmp_assignment_tpu.ops.step import _ro_outside
    sh = state_shardings(cfg, mesh, example_state)

    @functools.partial(jax.jit, in_shardings=(sh,))
    def run(state):
        carry0, ro, blanks = _ro_outside(state)

        def body(s, _):
            out, led = cycle(cfg, s.replace(**ro), with_ledger=True)
            return out.replace(**blanks), led

        final, ledger = jax.lax.scan(body, carry0, None,
                                     length=num_cycles)
        return final.replace(**ro), ledger

    return run


def make_transport_cycle(cfg: SystemConfig, mesh, example_state,
                         transport: str | None = None,
                         interpret: bool | None = None):
    """jit one cycle with phase-3 delivery routed by the explicit
    transport (cfg.transport: 'all_to_all' lane collective or the
    'rdma' Pallas ring, parallel/rdma_comm) instead of leaving the
    delivery scatter to GSPMD. Falls back to the implicit path when
    the config can't route (rdma_comm.supported) or the mesh is a
    single device (no cross-shard traffic to route)."""
    from ue22cs343bb1_openmp_assignment_tpu.parallel import rdma_comm
    from ue22cs343bb1_openmp_assignment_tpu.parallel.mesh import (
        flatten_mesh)
    sh = state_shardings(cfg, mesh, example_state)
    flat = flatten_mesh(mesh)
    if flat.devices.size == 1 or not rdma_comm.supported(cfg):
        deliver_fn = None
    else:
        deliver_fn = rdma_comm.make_routed_deliver(
            cfg, flat, interpret=interpret, transport=transport)
    return jax.jit(lambda s: cycle(cfg, s, deliver_fn=deliver_fn),
                   in_shardings=(sh,), out_shardings=sh)


def make_transport_runner(cfg: SystemConfig, mesh, example_state,
                          num_cycles: int,
                          transport: str | None = None,
                          interpret: bool | None = None):
    """jit a `num_cycles`-cycle scan with routed phase-3 delivery —
    the explicit-transport twin of make_sharded_runner (same read-only
    hoist, one dispatch for the whole run)."""
    from ue22cs343bb1_openmp_assignment_tpu.ops.step import _ro_outside
    from ue22cs343bb1_openmp_assignment_tpu.parallel import rdma_comm
    from ue22cs343bb1_openmp_assignment_tpu.parallel.mesh import (
        flatten_mesh)
    sh = state_shardings(cfg, mesh, example_state)
    flat = flatten_mesh(mesh)
    if flat.devices.size == 1 or not rdma_comm.supported(cfg):
        deliver_fn = None
    else:
        deliver_fn = rdma_comm.make_routed_deliver(
            cfg, flat, interpret=interpret, transport=transport)

    @functools.partial(jax.jit, in_shardings=(sh,), out_shardings=sh)
    def run(state):
        carry0, ro, blanks = _ro_outside(state)

        def body(s, _):
            out = cycle(cfg, s.replace(**ro), deliver_fn=deliver_fn)
            return out.replace(**blanks), None

        final, _ = jax.lax.scan(body, carry0, None, length=num_cycles)
        return final.replace(**ro)

    return run


def make_sharded_round(cfg: SystemConfig, mesh, example_state):
    """jit one transactional-engine round (ops.sync_engine) with
    node-axis shardings: caches/traces partition by node, the flat
    directory table partitions into per-home runs, and GSPMD lowers the
    claim scatter-min / directory gathers into cross-shard collectives."""
    from ue22cs343bb1_openmp_assignment_tpu.ops.sync_engine import round_step
    sh = state_shardings(cfg, mesh, example_state)
    return jax.jit(lambda s: round_step(cfg, s), in_shardings=(sh,),
                   out_shardings=sh)


def make_sharded_round_runner(cfg: SystemConfig, mesh, example_state,
                              num_rounds: int):
    """jit a `num_rounds`-round transactional scan with node-axis
    shardings — the multi-chip twin of
    ops.sync_engine.run_rounds (same read-only instruction-table hoist,
    one dispatch for the whole run)."""
    from ue22cs343bb1_openmp_assignment_tpu.ops.sync_engine import (
        _pack_outside, round_step)
    sh = state_shardings(cfg, mesh, example_state)

    @functools.partial(jax.jit, in_shardings=(sh,), out_shardings=sh)
    def run(state):
        carry0, pack = _pack_outside(state)

        def body(s, _):
            out = round_step(cfg, s.replace(instr_pack=pack))
            return out.replace(instr_pack=carry0.instr_pack), None

        final, _ = jax.lax.scan(body, carry0, None, length=num_rounds)
        return final.replace(instr_pack=pack)

    return run
