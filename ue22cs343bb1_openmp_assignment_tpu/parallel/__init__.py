from ue22cs343bb1_openmp_assignment_tpu.parallel.mesh import (
    flatten_mesh, make_mesh, make_multihost_mesh, state_shardings,
    shard_state)
from ue22cs343bb1_openmp_assignment_tpu.parallel.shardmap_comm import (
    candidate_prio, make_router, pack_fields)
from ue22cs343bb1_openmp_assignment_tpu.parallel.sharded_step import (
    make_sharded_cycle, make_sharded_round,
    make_sharded_round_runner, make_sharded_runner,
    make_transport_cycle, make_transport_runner)
from ue22cs343bb1_openmp_assignment_tpu.parallel import rdma_comm

__all__ = ["flatten_mesh", "make_mesh", "make_multihost_mesh",
           "state_shardings", "shard_state",
           "make_sharded_cycle", "make_sharded_round",
           "make_sharded_round_runner", "make_sharded_runner",
           "make_transport_cycle", "make_transport_runner",
           "make_router", "candidate_prio", "pack_fields",
           "rdma_comm"]
