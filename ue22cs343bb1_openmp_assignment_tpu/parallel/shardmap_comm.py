"""Explicit cross-shard message routing: shard_map + all_to_all.

The engines' default transport is implicit: `cycle`/`round_step` are
jitted with node-axis shardings and GSPMD lowers the delivery scatter's
cross-shard writes into collectives (parallel/sharded_step.py). This
module is the same communication backend written *explicitly* — the
reference's locked mailboxes (``assignment.c:741-765``) re-expressed as
the canonical TPU recipe the survey maps them to (SURVEY §2
"parallelism strategies"): shard the node axis over a
`jax.sharding.Mesh`, bucket each shard's outgoing messages by
destination shard, and exchange the buckets with ONE
`jax.lax.all_to_all` over the ICI axis. Useful as the hand-rolled
transport for experiments the implicit path cannot express (per-link
accounting, custom routing policies, DCN/ICI split studies) and as an
executable specification of what GSPMD generates.

Routing preserves exactly what the global delivery sort keys on
(ops/mailbox.deliver): each candidate travels with its global
arbitration priority `prio = arb_rank[sender] * out_slots + slot`, and
per-receiver enqueue order is recovered by sorting inbound candidates
on (receiver, prio) — a total key, so the routed path reproduces the
global path's rings bit for bit (tests/test_shardmap_comm.py).

Capacity: each (source shard -> dest shard) lane carries up to
`lane_cap` message rows per exchange (default: all of a shard's
out-slots, i.e. lossless). A fuller lane is truncated in priority
order and reported, mirroring the bounded-mailbox drop accounting of
the engine proper.
"""

from __future__ import annotations

import functools
from typing import NamedTuple

import jax
import jax.numpy as jnp

try:                                    # jax >= 0.4.35 exports it at top level
    from jax import shard_map
except ImportError:                     # 0.4.x fallback (e.g. 0.4.37)
    from jax.experimental.shard_map import shard_map
from jax.sharding import Mesh, PartitionSpec as P

from ue22cs343bb1_openmp_assignment_tpu.config import SystemConfig
from ue22cs343bb1_openmp_assignment_tpu.ops.mailbox import (
    Candidates, candidate_prio, pack_candidates, segment_ranks)
from ue22cs343bb1_openmp_assignment_tpu.parallel.mesh import AXIS
from ue22cs343bb1_openmp_assignment_tpu.types import Msg

# the delivery-order/payload definitions are owned by ops.mailbox
# (deliver uses the same packing). The ring stores planes ([P, N, S],
# in-place scatter layout); the router shards over the NODE axis, so
# its payload keeps node-major [N, S, P] rows.
def pack_fields(cand: Candidates) -> jnp.ndarray:
    return jnp.moveaxis(pack_candidates(cand), 0, -1)


def bucket_lanes(ctype, recv, prio, fields, *, N, D, L, cap, Fw):
    """Shard-local bucketing shared by both transports.

    Flattens this shard's [L, S] candidate planes, sorts by the fused
    (dest shard, prio) key, ranks within each destination bucket
    (segment_ranks, shared with deliver) and places the fitting rows
    into [D, cap] outbox lanes — lane d holds the rows bound for shard
    d in priority order. Returns
    ``(ob_valid [D,cap] bool, ob_recv, ob_prio, ob_fields [D,cap,Fw],
    truncated [] i32)``. Identical math for the all_to_all router and
    the RDMA ring (parallel/rdma_comm.py); only the exchange differs.
    """
    F = ctype.size
    ctype, recv, prio = (ctype.reshape(F), recv.reshape(F),
                         prio.reshape(F))
    fields = fields.reshape(F, Fw)
    valid = (ctype != int(Msg.NONE)) & (recv >= 0) & (recv < N)
    dest = jnp.where(valid, recv // L, D)          # dest shard (D = none)
    # order by (dest, prio): a fused total key — D * (N * S) ranges
    # within int32 at simulator scales (prio < N * S)
    key = jnp.where(valid, dest * (N * (F // L)) + prio,
                    jnp.iinfo(jnp.int32).max)
    order = jnp.argsort(key)
    d_s = dest[order]
    v_s = valid[order]
    # rank within each destination bucket (shared with deliver)
    rank, _ = segment_ranks(d_s, v_s)
    fit = v_s & (rank < cap)
    truncated = jnp.sum(v_s & ~fit).astype(jnp.int32)
    # outbox lanes: [D, cap] rows per destination shard
    tgt_d = jnp.where(fit, d_s, D)
    tgt_r = jnp.where(fit, rank, 0)
    ob_valid = jnp.zeros((D, cap), bool).at[tgt_d, tgt_r].set(
        fit, mode="drop")
    ob_recv = jnp.zeros((D, cap), jnp.int32).at[tgt_d, tgt_r].set(
        recv[order], mode="drop")
    ob_prio = jnp.zeros((D, cap), jnp.int32).at[tgt_d, tgt_r].set(
        prio[order], mode="drop")
    ob_fields = jnp.zeros((D, cap, Fw), jnp.int32).at[
        tgt_d, tgt_r].set(fields[order], mode="drop")
    return ob_valid, ob_recv, ob_prio, ob_fields, truncated


class RoutedMsgs(NamedTuple):
    """Per-shard inbound candidates after the all-to-all exchange.

    Leading axis is the (sharded) lane pool: D * lane_cap rows per
    shard. `valid` marks real messages; `recv` is the global receiver
    id (always owned by the local shard); `prio` is the sender-side
    global arbitration priority (total order per receiver)."""

    valid: jnp.ndarray    # [D * lane_cap] bool
    recv: jnp.ndarray     # [D * lane_cap] i32
    prio: jnp.ndarray     # [D * lane_cap] i32
    fields: jnp.ndarray   # [D * lane_cap, 6 + Wm] i32 packed payload
    truncated: jnp.ndarray  # [] i32: messages dropped to lane caps


def make_router(cfg: SystemConfig, mesh: Mesh, lane_cap: int | None = None):
    """Build `route(cand_type, recv, prio, fields) -> RoutedMsgs`.

    Inputs are node-sharded [N, S] / [N, S, F] arrays; the result's
    lane pool is likewise sharded (each shard holds its own inbound
    rows). One all_to_all over the 'nodes' mesh axis per call."""
    if mesh.axis_names != (AXIS,):
        # ownership math below assumes the node axis shards over ONE
        # mesh axis; a (hosts, nodes) mesh partitions nodes over both
        # (mesh.state_shardings), which would silently misroute
        raise ValueError(
            f"make_router needs a 1-D ('{AXIS}',) mesh, got "
            f"{mesh.axis_names}; flatten a multi-host device grid into "
            "one axis for explicit routing")
    D = mesh.shape[AXIS]
    N, S = cfg.num_nodes, cfg.out_slots
    if N % D:
        raise ValueError(f"{N} nodes do not shard over {D} devices")
    L = N // D                      # nodes per shard
    cap = lane_cap if lane_cap is not None else L * S
    Fw = 6 + cfg.msg_bitvec_words

    def local_route(ctype, recv, prio, fields):
        # shapes: [L, S], [L, S], [L, S], [L, S, Fw]
        ob_valid, ob_recv, ob_prio, ob_fields, truncated = bucket_lanes(
            ctype, recv, prio, fields, N=N, D=D, L=L, cap=cap, Fw=Fw)
        # THE collective: lane d of this shard's outbox becomes lane
        # <this shard> of shard d's inbox — ICI traffic, one exchange
        ib_valid, ib_recv, ib_prio, ib_fields = [
            jax.lax.all_to_all(x, AXIS, split_axis=0, concat_axis=0,
                               tiled=True)
            for x in (ob_valid.astype(jnp.int32), ob_recv, ob_prio,
                      ob_fields)]
        ib_valid = ib_valid.astype(bool)
        return (ib_valid.reshape(D * cap), ib_recv.reshape(D * cap),
                ib_prio.reshape(D * cap),
                ib_fields.reshape(D * cap, Fw),
                jax.lax.psum(truncated, AXIS)[None])

    routed_specs = (P(AXIS), P(AXIS), P(AXIS), P(AXIS), P(AXIS))

    @jax.jit
    def route(ctype, recv, prio, fields) -> RoutedMsgs:
        out = shard_map(
            local_route, mesh=mesh,
            in_specs=(P(AXIS), P(AXIS), P(AXIS), P(AXIS)),
            out_specs=routed_specs)(ctype, recv, prio, fields)
        return RoutedMsgs(out[0], out[1], out[2], out[3], out[4][0])

    return route


