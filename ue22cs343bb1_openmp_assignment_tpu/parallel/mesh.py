"""Device mesh and sharding layout for the simulated-node axis.

The reference's only scaling axis is OpenMP threads inside one process
(``assignment.c:135,149``). Here the equivalent axis is the simulated-
node dimension (axis 0 of every SimState array), sharded over a 1-D
``jax.sharding.Mesh`` named ``'nodes'``:

* per-node state (caches, memories, directories, traces, mailboxes) is
  fully partitioned — a device owns its shard of nodes end to end,
* scalar fields (cycle counter, reduced metrics) are replicated,
* the mailbox-delivery scatter crosses shard boundaries whenever a
  message's receiver lives on another device; under `jit` XLA/GSPMD
  lowers that into all-to-all/collective-permute traffic on ICI (DCN
  across hosts) — the framework's distributed communication backend.
  The same transport written explicitly (shard_map + one
  `jax.lax.all_to_all` lane exchange per step) lives in
  parallel/shardmap_comm.py.
"""

from __future__ import annotations

from typing import Optional, Sequence

import jax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

AXIS = "nodes"
DCN_AXIS = "hosts"


def make_mesh(devices: Optional[Sequence[jax.Device]] = None) -> Mesh:
    """A 1-D mesh over `devices` (default: all) with axis 'nodes'."""
    devices = list(devices if devices is not None else jax.devices())
    return Mesh(devices, (AXIS,))


def make_multihost_mesh(num_hosts: Optional[int] = None,
                        devices: Optional[Sequence[jax.Device]] = None
                        ) -> Mesh:
    """A 2-D (hosts, nodes) mesh: DCN over the outer axis, ICI inner.

    The simulated-node axis shards over *both* axes (see
    :func:`state_shardings`): contiguous node ranges stay within a host
    (collectives for intra-host traffic ride ICI), and only messages
    whose receiver lives on another host cross DCN. On a real multi-host
    slice call ``jax.distributed.initialize()`` first and pass nothing —
    the process/host structure comes from ``jax.devices()``; for
    single-process validation pass ``num_hosts`` to fold a flat device
    list into a virtual host dimension. Host-side setup code (device
    objects, not traced values).
    """
    devices = list(devices if devices is not None else jax.devices())
    if num_hosts is None:
        num_hosts = max(1, jax.process_count())
    if len(devices) % num_hosts:
        raise ValueError(
            f"{len(devices)} devices do not fold into {num_hosts} hosts")
    import numpy as np
    grid = np.array(devices).reshape(num_hosts, -1)
    return Mesh(grid, (DCN_AXIS, AXIS))


def flatten_mesh(mesh: Mesh) -> Mesh:
    """The 1-D ('nodes',) transport view of any mesh.

    The explicit transports (shardmap_comm / rdma_comm) address peers
    by a single logical axis: interpret-mode remote DMA only discharges
    scalar device ids over ONE named axis, and the lane math assumes a
    flat shard index. A (hosts, nodes) grid flattens row-major, which
    is placement-identical to the 2-D ``state_shardings`` layout —
    ``P((DCN_AXIS, AXIS))`` on axis 0 assigns contiguous node runs to
    devices in exactly row-major grid order — so entering a flat-mesh
    shard_map from 2-D-sharded operands moves no data.
    """
    if mesh.axis_names == (AXIS,):
        return mesh
    return Mesh(mesh.devices.reshape(-1), (AXIS,))


def state_shardings(cfg, mesh: Mesh, state):
    """NamedShardings for a machine-state pytree (SimState or SyncState):
    shard axis 0 when it is the node axis — or node-major like the
    transactional engine's flat directory table ([N << block_bits, ...],
    whose leading axis partitions into per-home runs) — replicate
    everything else."""
    node_major = (cfg.num_nodes, cfg.num_nodes << cfg.block_bits)
    # on a (hosts, nodes) mesh the node axis shards over both axes:
    # outer = DCN (host boundary), inner = ICI
    axes = tuple(a for a in (DCN_AXIS, AXIS) if a in mesh.axis_names)
    lead = axes if len(axes) > 1 else axes[0]

    def spec(x):
        if getattr(x, "ndim", 0) >= 1 and x.shape[0] in node_major:
            return NamedSharding(mesh, P(lead, *([None] * (x.ndim - 1))))
        if getattr(x, "ndim", 0) >= 2 and x.shape[1] == cfg.num_nodes:
            # plane-major tensors (the mailbox ring, [P, N, Q]): the
            # node axis is axis 1
            return NamedSharding(
                mesh, P(None, lead, *([None] * (x.ndim - 2))))
        return NamedSharding(mesh, P())

    return jax.tree.map(spec, state)


def shard_state(cfg, mesh: Mesh, state):
    """Place a host-built SimState onto the mesh."""
    return jax.device_put(state, state_shardings(cfg, mesh, state))
