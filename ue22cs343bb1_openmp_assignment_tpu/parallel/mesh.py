"""Device mesh and sharding layout for the simulated-node axis.

The reference's only scaling axis is OpenMP threads inside one process
(``assignment.c:135,149``). Here the equivalent axis is the simulated-
node dimension (axis 0 of every SimState array), sharded over a 1-D
``jax.sharding.Mesh`` named ``'nodes'``:

* per-node state (caches, memories, directories, traces, mailboxes) is
  fully partitioned — a device owns its shard of nodes end to end,
* scalar fields (cycle counter, reduced metrics) are replicated,
* the mailbox-delivery scatter crosses shard boundaries whenever a
  message's receiver lives on another device; under `jit` XLA/GSPMD
  lowers that into all-to-all/collective-permute traffic on ICI (DCN
  across hosts) — the framework's distributed communication backend.
"""

from __future__ import annotations

from typing import Optional, Sequence

import jax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

AXIS = "nodes"


def make_mesh(devices: Optional[Sequence[jax.Device]] = None) -> Mesh:
    """A 1-D mesh over `devices` (default: all) with axis 'nodes'."""
    devices = list(devices if devices is not None else jax.devices())
    return Mesh(devices, (AXIS,))


def state_shardings(cfg, mesh: Mesh, state):
    """NamedShardings for a machine-state pytree (SimState or SyncState):
    shard axis 0 when it is the node axis — or node-major like the
    transactional engine's flat directory table ([N << block_bits, ...],
    whose leading axis partitions into per-home runs) — replicate
    everything else."""
    node_major = (cfg.num_nodes, cfg.num_nodes << cfg.block_bits)

    def spec(x):
        if getattr(x, "ndim", 0) >= 1 and x.shape[0] in node_major:
            return NamedSharding(mesh, P(AXIS, *([None] * (x.ndim - 1))))
        return NamedSharding(mesh, P())

    return jax.tree.map(spec, state)


def shard_state(cfg, mesh: Mesh, state):
    """Place a host-built SimState onto the mesh."""
    return jax.device_put(state, state_shardings(cfg, mesh, state))
