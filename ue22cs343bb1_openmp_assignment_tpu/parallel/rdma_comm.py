"""Pallas remote-DMA mailbox transport: ring exchange + home-shard enqueue.

The all_to_all router (parallel/shardmap_comm.py) materializes FOUR
separate [D, cap(, Fw)] exchange tensors per step — a valid plane
widened to int32, receiver ids, priorities and the payload planes —
and hands them to `jax.lax.all_to_all`. This module is the same lane
contract delivered the way the TPU's interconnect actually wants it
(SNIPPETS.md [2], the `pltpu.make_async_remote_copy` neighbor-exchange
kernel): each shard packs its outbox lanes into ONE [D, cap, 2 + Fw]
int32 tensor (column 0 carries the receiver id with -1 as the
invalid-row sentinel, so the valid plane rides for free; column 1 the
arbitration priority; the rest the payload planes) and a Pallas ring
kernel pushes lane (d + s) % D to neighbor (d + s) % D at step s with
send/recv DMA semaphores — D - 1 permutation steps, no full-exchange
tensor, and strictly fewer bytes on the wire per row
(:func:`wire_bytes`: 2 + Fw words vs the router's 3 + Fw).

Directory-by-home sharding invariant: `cycle`'s phase-1/2 writes are
all own-row (a node updates only its own cache/memory/directory rows,
and home(addr) ownership of directory rows follows the node axis), so
sharding the node axis over the mesh ALREADY places every directory
lookup shard-local — the only traffic that must cross shards is
phase-3 mailbox delivery. :func:`make_routed_deliver` therefore swaps
in for `ops.mailbox.deliver` alone (the ``deliver_fn`` hook in
ops.step.cycle): bucket locally (shared `bucket_lanes` math), exchange
lanes (RDMA ring or the all_to_all fallback), then run the *exact*
shard-local image of deliver's sort/rank/capacity/position enqueue.
Per-receiver order is preserved bit for bit because every receiver is
wholly owned by one shard and `prio` is a global total order.

Gating mirrors ops/pallas_round.py: :func:`supported` is a pure config
predicate, :func:`native` says whether the attached backend compiles
the kernel natively (real TPU) — everywhere else the kernel runs under
the Pallas interpreter, which is the CPU-CI correctness contract
(tests/test_shardmap_comm.py pins bit-parity vs the all_to_all
router). Interpret-mode discharge constrains the kernel shape: scalar
logical device ids, ONE named mesh axis (2-D meshes enter through
`mesh.flatten_mesh`, placement-identical row-major), and a fully
symmetric schedule — every device sends full lanes at every step.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
from jax import lax
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

try:                                    # jax >= 0.4.35 exports it at top level
    from jax import shard_map
except ImportError:                     # 0.4.x fallback (e.g. 0.4.37)
    from jax.experimental.shard_map import shard_map
from jax.sharding import Mesh, PartitionSpec as P

from ue22cs343bb1_openmp_assignment_tpu.config import SystemConfig
from ue22cs343bb1_openmp_assignment_tpu.ops.mailbox import (
    candidate_prio, segment_ranks)
from ue22cs343bb1_openmp_assignment_tpu.parallel.mesh import (
    AXIS, flatten_mesh)
from ue22cs343bb1_openmp_assignment_tpu.parallel.shardmap_comm import (
    RoutedMsgs, bucket_lanes, pack_fields)


def supported(cfg: SystemConfig) -> bool:
    """Pure predicate: can the routed transports deliver this config?

    Fault injection (cfg.drop_prob > 0) draws ONE global bernoulli
    vector in arbitration order from state.fault_key; a per-shard
    deliver cannot reproduce that draw order, so routed delivery
    requires the drop knob off. Everything else routes.
    """
    return cfg.drop_prob == 0.0


def native() -> bool:
    """True when the attached backend compiles the ring kernel natively
    (real TPU). Everywhere else callers run interpret mode — the
    correctness contract on CPU CI — or fall back to all_to_all."""
    return jax.default_backend() == "tpu"


def wire_bytes(cfg: SystemConfig, n_shards: int,
               lane_cap: int | None = None,
               transport: str = "rdma") -> int:
    """Interconnect bytes per lane exchange — pure shape arithmetic.

    Both transports move D * (D - 1) non-self lanes of `cap` rows. An
    all_to_all row is 3 + Fw int32 words (valid plane widened to i32,
    recv, prio, Fw payload words, each its own exchange tensor); an
    RDMA row is 2 + Fw (validity rides in the receiver column's -1
    sentinel). The perf-report transport row and the check.sh gate are
    this function — same basis as pallas_round.io_contract_bytes.
    """
    if cfg.num_nodes % n_shards:
        raise ValueError(
            f"{cfg.num_nodes} nodes do not shard over {n_shards} devices")
    L = cfg.num_nodes // n_shards
    cap = lane_cap if lane_cap is not None else L * cfg.out_slots
    Fw = 6 + cfg.msg_bitvec_words
    words = {"all_to_all": 3 + Fw, "rdma": 2 + Fw}[transport]
    return n_shards * (n_shards - 1) * cap * words * 4


def _ring_exchange(D: int, cap: int, width: int, interpret: bool):
    """Build the [D, cap, width] i32 lane exchange as one pallas_call.

    Step s pushes outbox lane (my_id + s) % D to device (my_id + s) % D,
    landing in the receiver's inbox at lane my_id — a permutation per
    step, so interpret-mode discharge matches exactly one sender per
    receiver, and after D - 1 steps plus the local self-copy the inbox
    lane layout (lane j = from shard j) is identical to all_to_all's.
    """

    def kernel(ob_ref, ib_ref, send_sem, recv_sem, local_sem):
        my_id = lax.axis_index(AXIS)
        # self lane never touches the wire: local async copy
        self_copy = pltpu.make_async_copy(
            ob_ref.at[my_id], ib_ref.at[my_id], local_sem)
        self_copy.start()
        self_copy.wait()
        for s in range(1, D):
            dst = lax.rem(my_id + s, D)
            # sender indexes BOTH refs: src lane dst (rows bound for
            # shard dst), dst lane my_id (receiver's from-me slot)
            rdma = pltpu.make_async_remote_copy(
                src_ref=ob_ref.at[dst], dst_ref=ib_ref.at[my_id],
                send_sem=send_sem, recv_sem=recv_sem,
                device_id=dst,
                device_id_type=pltpu.DeviceIdType.LOGICAL)
            rdma.start()
            # full barrier per step: the recv wait also orders the
            # reused semaphores for the next step's permutation
            rdma.wait()

    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=0,
        in_specs=[pl.BlockSpec(memory_space=pltpu.ANY)],
        out_specs=pl.BlockSpec(memory_space=pltpu.ANY),
        scratch_shapes=[pltpu.SemaphoreType.DMA] * 3,
    )
    return pl.pallas_call(
        kernel,
        out_shape=jax.ShapeDtypeStruct((D, cap, width), jnp.int32),
        grid_spec=grid_spec, interpret=interpret)


def _pack_lanes(ob_valid, ob_recv, ob_prio, ob_fields):
    """[D, cap, 2 + Fw] wire image: recv (-1 = invalid), prio, payload."""
    return jnp.concatenate(
        [jnp.where(ob_valid, ob_recv, -1)[..., None],
         ob_prio[..., None], ob_fields], axis=-1)


def _unpack_lanes(ib):
    """Invert :func:`_pack_lanes` to the router's inbox quadruple.

    Invalid rows decode to (False, 0, 0, 0…) — bit-identical to the
    all_to_all router's zero-initialized lanes."""
    recv = ib[..., 0]
    valid = recv >= 0
    return (valid, jnp.where(valid, recv, 0), ib[..., 1], ib[..., 2:])


def _transport_geometry(cfg: SystemConfig, mesh: Mesh,
                        lane_cap: int | None):
    mesh = flatten_mesh(mesh)
    D = mesh.shape[AXIS]
    N, S = cfg.num_nodes, cfg.out_slots
    if N % D:
        raise ValueError(f"{N} nodes do not shard over {D} devices")
    L = N // D
    cap = lane_cap if lane_cap is not None else L * S
    Fw = 6 + cfg.msg_bitvec_words
    return mesh, D, N, S, L, cap, Fw


def make_rdma_router(cfg: SystemConfig, mesh: Mesh,
                     lane_cap: int | None = None,
                     interpret: bool | None = None):
    """Build `route(cand_type, recv, prio, fields) -> RoutedMsgs`.

    Drop-in for shardmap_comm.make_router with the all_to_all replaced
    by the RDMA ring — same node-sharded inputs, same sharded lane
    pool, bit-identical output (the parity contract). Accepts 1-D or
    2-D meshes (flattened row-major for the single transport axis).
    `interpret=None` auto-selects: native compile on real TPU only.
    """
    mesh, D, N, S, L, cap, Fw = _transport_geometry(cfg, mesh, lane_cap)
    if interpret is None:
        interpret = not native()
    exchange = _ring_exchange(D, cap, 2 + Fw, interpret)

    def local_route(ctype, recv, prio, fields):
        ob_valid, ob_recv, ob_prio, ob_fields, truncated = bucket_lanes(
            ctype, recv, prio, fields, N=N, D=D, L=L, cap=cap, Fw=Fw)
        ib = exchange(_pack_lanes(ob_valid, ob_recv, ob_prio, ob_fields))
        ib_valid, ib_recv, ib_prio, ib_fields = _unpack_lanes(ib)
        return (ib_valid.reshape(D * cap), ib_recv.reshape(D * cap),
                ib_prio.reshape(D * cap),
                ib_fields.reshape(D * cap, Fw),
                lax.psum(truncated, AXIS)[None])

    @jax.jit
    def route(ctype, recv, prio, fields) -> RoutedMsgs:
        out = shard_map(
            local_route, mesh=mesh,
            in_specs=(P(AXIS), P(AXIS), P(AXIS), P(AXIS)),
            out_specs=(P(AXIS),) * 5, check_rep=False)(
                ctype, recv, prio, fields)
        return RoutedMsgs(out[0], out[1], out[2], out[3], out[4][0])

    return route


def _local_enqueue(N: int, L: int, S: int, Q: int,
                   ib_valid, ib_recv, ib_prio, ib_fields,
                   mb_pack, new_head, new_count):
    """The shard-local image of ops.mailbox.deliver's enqueue.

    Inputs are this shard's inbox pool ([D * cap] rows whose receivers
    are all locally owned) and its slices of the ring state. Same
    sort key shape, segment ranking, capacity test and position math
    as deliver — receiver ids are just rebased to local rows, and prio
    is globally unique, so each receiver's ring writes come out in the
    identical order and positions as the unsharded global sort.
    """
    my_id = lax.axis_index(AXIS)
    F = ib_valid.shape[0]
    lr = jnp.where(ib_valid, ib_recv - my_id * L, L)    # local receiver row
    # group by (local receiver, prio) — fused key when it fits int32,
    # else deliver's two-stable-sort lexicographic fallback (the 2^20-
    # node rungs overflow the fused key exactly like deliver's guard)
    prio_span = N * S                                   # prio < N * S
    if (L + 1) * prio_span + prio_span < 2**31:
        key = jnp.where(ib_valid, lr * prio_span + ib_prio,
                        jnp.iinfo(jnp.int32).max)
        order = jnp.argsort(key)
    else:
        order1 = jnp.argsort(
            jnp.where(ib_valid, ib_prio, jnp.iinfo(jnp.int32).max),
            stable=True)
        key2 = jnp.where(ib_valid[order1], lr[order1],
                         jnp.iinfo(jnp.int32).max)
        order = order1[jnp.argsort(key2, stable=True)]
    r_s = lr[order]
    v_s = ib_valid[order]
    rank, _ = segment_ranks(r_s, v_s)
    safe_r = jnp.where(v_s, r_s, 0)
    free = (Q - new_count)[safe_r]
    accept = v_s & (rank < free)
    dropped = jnp.sum(v_s & ~accept).astype(jnp.int32)
    pos = (new_head[safe_r] + new_count[safe_r] + rank) % Q
    tgt_r = jnp.where(accept, r_s, L)       # OOB row -> dropped by scatter
    tgt_p = jnp.where(accept, pos, 0)
    pack = ib_fields[order].T               # [6 + Wm, F]
    return (mb_pack.at[:, tgt_r, tgt_p].set(pack, mode="drop"),
            new_count.at[tgt_r].add(accept.astype(jnp.int32), mode="drop"),
            dropped)


def make_routed_deliver(cfg: SystemConfig, mesh: Mesh,
                        lane_cap: int | None = None,
                        interpret: bool | None = None,
                        transport: str | None = None):
    """Build a ``deliver_fn`` for ops.step.cycle: routed phase-3 delivery.

    One shard_map per cycle: shared lane bucketing, the selected lane
    exchange (``cfg.transport`` — 'rdma' ring kernel or the explicit
    'all_to_all' router collective), then the shard-local deliver
    image. Same return contract as mailbox.deliver (updates dict,
    dropped, injected); requires :func:`supported` (drop_prob == 0, so
    injected is always 0 and fault_key passes through untouched).
    Default lane_cap (L * S) is lossless by construction — every
    shard's whole outbox fits its lanes — so routed dropped counts are
    pure ring-capacity drops, identical to the global path's.
    """
    if not supported(cfg):
        raise ValueError(
            "routed delivery requires cfg.drop_prob == 0 (the global "
            "fault-injection draw order cannot be reproduced per shard)")
    mesh, D, N, S, L, cap, Fw = _transport_geometry(cfg, mesh, lane_cap)
    transport = transport if transport is not None else cfg.transport
    Q = cfg.queue_capacity
    if interpret is None:
        interpret = not native()
    exchange = (_ring_exchange(D, cap, 2 + Fw, interpret)
                if transport == "rdma" else None)

    def local_deliver(mb_pack, ctype, recv, prio, fields,
                      new_head, new_count):
        ob_valid, ob_recv, ob_prio, ob_fields, truncated = bucket_lanes(
            ctype, recv, prio, fields, N=N, D=D, L=L, cap=cap, Fw=Fw)
        if transport == "rdma":
            ib = exchange(
                _pack_lanes(ob_valid, ob_recv, ob_prio, ob_fields))
            ib_valid, ib_recv, ib_prio, ib_fields = _unpack_lanes(ib)
        else:
            ib_valid, ib_recv, ib_prio, ib_fields = [
                lax.all_to_all(x, AXIS, split_axis=0, concat_axis=0,
                               tiled=True)
                for x in (ob_valid.astype(jnp.int32), ob_recv, ob_prio,
                          ob_fields)]
            ib_valid = ib_valid.astype(bool)
        F = D * cap
        mb_pack, mb_count, enq_dropped = _local_enqueue(
            N, L, S, Q, ib_valid.reshape(F), ib_recv.reshape(F),
            ib_prio.reshape(F), ib_fields.reshape(F, Fw),
            mb_pack, new_head, new_count)
        # lane-cap truncation is zero at the lossless default; with an
        # explicit tighter cap it is still a drop, so count it
        dropped = lax.psum(enq_dropped + truncated, AXIS)
        return mb_pack, mb_count, dropped[None]

    routed = shard_map(
        local_deliver, mesh=mesh,
        in_specs=(P(None, AXIS, None), P(AXIS), P(AXIS), P(AXIS),
                  P(AXIS), P(AXIS), P(AXIS)),
        out_specs=(P(None, AXIS, None), P(AXIS), P(AXIS)),
        check_rep=False)

    def deliver_fn(cfg_, state, cand, arb_rank, new_head, new_count):
        prio = candidate_prio(cfg_, arb_rank)
        fields = pack_fields(cand)                       # [N, S, 6 + Wm]
        mb_pack, mb_count, dropped = routed(
            state.mb_pack, cand.type, cand.recv, prio, fields,
            new_head, new_count)
        updates = dict(mb_pack=mb_pack, mb_head=new_head,
                       mb_count=mb_count, fault_key=state.fault_key)
        return updates, dropped[0], jnp.zeros((), jnp.int32)

    return deliver_fn
