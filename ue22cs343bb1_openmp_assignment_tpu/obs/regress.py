"""Noise-aware benchmark comparison: the brain of ``cache-sim bench-diff``.

The question this module answers used to be argued by hand in PERF.md:
"round r04's headline is 3.3% below r03 — regression or link noise?"
With only medians that argument can't be settled; with the full rep
vectors (see :mod:`obs.history`) it can. A delta counts as a
**regression** only when it clears two independent bars:

1. **Statistical**: a one-sided Mann-Whitney U test on the rep-time
   vectors rejects "B is not slower than A" at ``alpha``. Rep counts
   are tiny (3 per side is the norm), so the test is exact — the null
   distribution of U is enumerated over all C(n+m, n) rank splits,
   falling back to the tie-corrected normal approximation only when
   enumeration would exceed ~100k splits. Note the floor: with 3v3
   reps the smallest achievable one-sided p is 1/C(6,3) = 0.05, which
   is why the default alpha is 0.05 and why practical significance
   must carry its share of the decision.
2. **Practical**: the relative median delta exceeds a threshold
   derived from the *recorded* rep spread of both sides —
   ``max(min_effect, spread_a, spread_b)`` where spread is
   (max-min)/median. A machine whose own reps wobble 4% cannot
   testify about a 3% delta.

Worked against the archive: r03 reps [0.850, 0.859, 0.889] vs r04
[0.853, 0.889, 0.891] — median delta +3.5%, spreads ~4.4% — fails the
practical bar: **noise** (matching PERF.md's hand verdict). Scale one
side by 1.10 and the delta (10%) clears the spread while the rank test
hits its exact p = 0.05 floor: **regression**.

Dependency-free (exact combinatorics + math.erf), host-side only.
"""

from __future__ import annotations

import math
from itertools import combinations
from typing import List, Optional, Sequence

#: enumerate the exact U null distribution up to this many rank splits
_EXACT_LIMIT = 100_000

#: below this relative delta, never call a regression (compile jitter
#: on the bench link sits at a few percent even on quiet runs)
DEFAULT_MIN_EFFECT = 0.05

DEFAULT_ALPHA = 0.05


# lint: host
def _midranks(pooled: Sequence[float]) -> List[float]:
    """Ranks 1..N with ties sharing their average (mid) rank."""
    order = sorted(range(len(pooled)), key=lambda i: pooled[i])
    ranks = [0.0] * len(pooled)
    i = 0
    while i < len(order):
        j = i
        while (j + 1 < len(order)
               and pooled[order[j + 1]] == pooled[order[i]]):
            j += 1
        mid = (i + j) / 2.0 + 1.0
        for k in range(i, j + 1):
            ranks[order[k]] = mid
        i = j + 1
    return ranks


# lint: host
def _u_statistic(a: Sequence[float], b: Sequence[float]) -> float:
    """U for sample b: count of (a_i, b_j) pairs with b_j > a_i,
    ties counting one half. Large U => b stochastically larger."""
    u = 0.0
    for x in a:
        for y in b:
            if y > x:
                u += 1.0
            elif y == x:
                u += 0.5
    return u


# lint: host
def mann_whitney_u(a: Sequence[float], b: Sequence[float]) -> dict:
    """One-sided Mann-Whitney U test of H1 "b tends larger than a".

    Returns {"u", "p", "method"} with method "exact" (null
    distribution enumerated over rank splits, correct under ties) or
    "normal" (tie-corrected approximation) for large samples.
    Requires at least 2 observations per side.
    """
    n, m = len(a), len(b)
    if n < 2 or m < 2:
        raise ValueError(
            f"mann_whitney_u needs >=2 reps per side, got {n} and {m}")
    u_obs = _u_statistic(a, b)
    if math.comb(n + m, m) <= _EXACT_LIMIT:
        # Enumerate every assignment of the pooled values to the two
        # groups; the p-value is the fraction with U >= observed.
        # Enumerating index subsets (not value subsets) keeps tied
        # values distinct, so ties are handled exactly.
        pooled = list(a) + list(b)
        idx = range(n + m)
        count = 0
        total = 0
        for pick in combinations(idx, m):
            pick_set = set(pick)
            bb = [pooled[i] for i in pick]
            aa = [pooled[i] for i in idx if i not in pick_set]
            if _u_statistic(aa, bb) >= u_obs:
                count += 1
            total += 1
        return {"u": u_obs, "p": count / total, "method": "exact"}
    # Normal approximation with tie correction and continuity
    # correction (standard large-sample form).
    mean = n * m / 2.0
    pooled = list(a) + list(b)
    tie_sizes = {}
    for v in pooled:
        tie_sizes[v] = tie_sizes.get(v, 0) + 1
    nn = n + m
    tie_term = sum(t ** 3 - t for t in tie_sizes.values())
    var = (n * m / 12.0) * ((nn + 1) - tie_term / (nn * (nn - 1)))
    if var <= 0:  # all values identical
        return {"u": u_obs, "p": 1.0, "method": "normal"}
    z = (u_obs - mean - 0.5) / math.sqrt(var)
    p = 0.5 * (1.0 - math.erf(z / math.sqrt(2.0)))
    return {"u": u_obs, "p": p, "method": "normal"}


# lint: host
def _median(xs: Sequence[float]) -> float:
    s = sorted(xs)
    n = len(s)
    mid = n // 2
    return s[mid] if n % 2 else 0.5 * (s[mid - 1] + s[mid])


# lint: host
def rel_spread(xs: Sequence[float]) -> float:
    """(max - min) / median — the recorded wobble of one capture."""
    if not xs:
        return 0.0
    med = _median(xs)
    return (max(xs) - min(xs)) / med if med > 0 else 0.0


# lint: host
def compare(entry_a: dict, entry_b: dict,
            min_effect: float = DEFAULT_MIN_EFFECT,
            alpha: float = DEFAULT_ALPHA) -> dict:
    """Compare two bench-history entries (A = baseline, B = candidate).

    Works in rep *times* (seconds; higher = slower), not the headline
    rate, so "B slower than A" is "B's times tend larger". Returns a
    verdict doc::

        {"verdict": "regression" | "improvement" | "noise"
                    | "incomparable",
         "delta_pct",            # (median_b - median_a)/median_a * 100
         "threshold_pct",        # practical-significance bar
         "p", "u", "method",     # rank test (p None when underpowered)
         "flags": [...],         # e.g. "low_power", "not_quiescent:b"
         "a": {...}, "b": {...}} # per-side label/median/spread/reps

    A regression needs BOTH delta_pct >= threshold_pct AND p <= alpha;
    improvements are judged symmetrically (reversed test). With fewer
    than 2 reps on either side the rank test is impossible — the
    verdict is practical-only and flagged "low_power".
    """
    flags = []
    if entry_a.get("metric") != entry_b.get("metric"):
        return {
            "verdict": "incomparable",
            "detail": (f"metric mismatch: {entry_a.get('metric')!r} vs "
                       f"{entry_b.get('metric')!r}"),
            "a": {"label": entry_a.get("label")},
            "b": {"label": entry_b.get("label")},
            "flags": ["metric_mismatch"],
        }
    cfg_a, cfg_b = entry_a.get("config"), entry_b.get("config")
    if cfg_a and cfg_b and cfg_a.get("engine") and cfg_b.get("engine") \
            and cfg_a["engine"] != cfg_b["engine"]:
        return {
            "verdict": "incomparable",
            "detail": (f"engine mismatch: {cfg_a['engine']!r} vs "
                       f"{cfg_b['engine']!r}"),
            "a": {"label": entry_a.get("label")},
            "b": {"label": entry_b.get("label")},
            "flags": ["engine_mismatch"],
        }
    dev_a = entry_a.get("device_kind")
    dev_b = entry_b.get("device_kind")
    if dev_a and dev_b and dev_a != dev_b:
        # times from different silicon never compare; refuse loudly
        # rather than produce a numerically plausible wrong verdict
        return {
            "verdict": "incomparable",
            "detail": (f"incomparable: different device "
                       f"({dev_a!r} vs {dev_b!r})"),
            "a": {"label": entry_a.get("label"), "device_kind": dev_a},
            "b": {"label": entry_b.get("label"), "device_kind": dev_b},
            "flags": ["device_mismatch"],
        }
    hlo_a = entry_a.get("hlo_fingerprint")
    hlo_b = entry_b.get("hlo_fingerprint")
    if hlo_a and hlo_b and hlo_a != hlo_b:
        # informational, not fatal: comparing across code changes is
        # the normal use of bench-diff, but the reader should know the
        # compiled program is not the same one
        flags.append("hlo_changed")
    for side, e in (("a", entry_a), ("b", entry_b)):
        if e.get("quiescent") is False:
            flags.append(f"not_quiescent:{side}")
    reps_a = list(entry_a.get("rep_times_s") or [])
    reps_b = list(entry_b.get("rep_times_s") or [])
    if not reps_a or not reps_b:
        return {
            "verdict": "incomparable",
            "detail": "missing rep_times_s on one side",
            "a": {"label": entry_a.get("label"), "reps": len(reps_a)},
            "b": {"label": entry_b.get("label"), "reps": len(reps_b)},
            "flags": flags + ["no_reps"],
        }
    med_a, med_b = _median(reps_a), _median(reps_b)
    spread_a, spread_b = rel_spread(reps_a), rel_spread(reps_b)
    delta = (med_b - med_a) / med_a
    threshold = max(min_effect, spread_a, spread_b)

    p = u = method = None
    p_impr = None
    if len(reps_a) >= 2 and len(reps_b) >= 2:
        slower = mann_whitney_u(reps_a, reps_b)   # H1: b times larger
        faster = mann_whitney_u(reps_b, reps_a)   # H1: a times larger
        u, method = slower["u"], slower["method"]
        p, p_impr = slower["p"], faster["p"]
        # with too few reps even a perfect separation cannot reach
        # alpha (2v2: floor = 1/C(4,2) ≈ 0.17) — the rank test is
        # structurally mute, so the practical bar decides alone
        if 1.0 / math.comb(len(reps_a) + len(reps_b),
                           min(len(reps_a), len(reps_b))) > alpha:
            flags.append("low_power")
            p = p_impr = None
    else:
        flags.append("low_power")

    if delta >= threshold and (p is None or p <= alpha):
        verdict = "regression"
    elif -delta >= threshold and (p_impr is None or p_impr <= alpha):
        verdict = "improvement"
    else:
        verdict = "noise"

    return {
        "verdict": verdict,
        "delta_pct": round(delta * 100.0, 3),
        "threshold_pct": round(threshold * 100.0, 3),
        "p": p,
        "u": u,
        "method": method,
        "alpha": alpha,
        "flags": flags,
        "a": {"label": entry_a.get("label"),
              "median_s": round(med_a, 6),
              "spread_pct": round(spread_a * 100.0, 3),
              "reps": len(reps_a)},
        "b": {"label": entry_b.get("label"),
              "median_s": round(med_b, 6),
              "spread_pct": round(spread_b * 100.0, 3),
              "reps": len(reps_b)},
    }


#: default tolerance for the exact bytes/instr gate — cost_analysis is
#: deterministic per HLO, so this only absorbs benign layout churn
#: (padding, fusion boundary shifts), not measurement noise
DEFAULT_BYTES_TOL_PCT = 2.0


# lint: host
def compare_cost(entry_a: dict, entry_b: dict,
                 tol_pct: float = DEFAULT_BYTES_TOL_PCT) -> dict:
    """Exact bytes/instr comparison of two history entries' cost
    vectors (A = baseline, B = candidate).

    Unlike :func:`compare`, this needs no reps and no statistics: XLA's
    ``cost_analysis()`` is deterministic per compiled HLO, so any
    bytes/instr increase beyond ``tol_pct`` IS a regression — there is
    no noise to hide behind. Returns a verdict doc::

        {"verdict": "regression" | "improvement" | "pass"
                    | "incomparable",
         "delta_pct",                  # bytes/instr relative delta
         "tol_pct",
         "bytes_per_instr": {"a", "b"},
         "offending_kernels": [{"name", "hbm_bytes_a", "hbm_bytes_b",
                                "delta_pct"}, ...],  # worst first
         "flags": [...], "a": {...}, "b": {...}}

    Incomparable when either side lacks a usable cost vector (no
    ``cost`` recorded, ``cost_available`` false, or bytes/instr
    missing) or when the two entries come from different device kinds.
    """
    flags = []
    dev_a = entry_a.get("device_kind")
    dev_b = entry_b.get("device_kind")
    if dev_a and dev_b and dev_a != dev_b:
        return {
            "verdict": "incomparable",
            "detail": (f"incomparable: different device "
                       f"({dev_a!r} vs {dev_b!r})"),
            "a": {"label": entry_a.get("label"), "device_kind": dev_a},
            "b": {"label": entry_b.get("label"), "device_kind": dev_b},
            "flags": ["device_mismatch"],
        }
    cost_a = entry_a.get("cost")
    cost_b = entry_b.get("cost")
    for side, cost, e in (("a", cost_a, entry_a),
                          ("b", cost_b, entry_b)):
        if (not isinstance(cost, dict)
                or not cost.get("cost_available", False)
                or not isinstance(cost.get("bytes_per_instr"),
                                  (int, float))):
            return {
                "verdict": "incomparable",
                "detail": (f"no usable cost vector on side "
                           f"{side} ({e.get('label')!r})"),
                "a": {"label": entry_a.get("label")},
                "b": {"label": entry_b.get("label")},
                "flags": ["no_cost"],
            }
    hlo_a = entry_a.get("hlo_fingerprint")
    hlo_b = entry_b.get("hlo_fingerprint")
    if hlo_a and hlo_b and hlo_a != hlo_b:
        flags.append("hlo_changed")
    bpi_a = float(cost_a["bytes_per_instr"])
    bpi_b = float(cost_b["bytes_per_instr"])
    if bpi_a <= 0:
        return {
            "verdict": "incomparable",
            "detail": "baseline bytes/instr is zero",
            "a": {"label": entry_a.get("label")},
            "b": {"label": entry_b.get("label")},
            "flags": flags + ["no_cost"],
        }
    delta = (bpi_b - bpi_a) / bpi_a

    # name the kernels that carry the increase, worst first
    kerns_a = cost_a.get("kernels") or {}
    kerns_b = cost_b.get("kernels") or {}
    offending = []
    for name in sorted(set(kerns_a) | set(kerns_b)):
        ba = float((kerns_a.get(name) or {}).get("hbm_bytes", 0.0))
        bb = float((kerns_b.get(name) or {}).get("hbm_bytes", 0.0))
        if bb <= ba:
            continue
        kd = (bb - ba) / ba if ba > 0 else float("inf")
        if kd * 100.0 > tol_pct:
            offending.append({
                "name": name,
                "hbm_bytes_a": ba,
                "hbm_bytes_b": bb,
                "delta_pct": (round(kd * 100.0, 3)
                              if math.isfinite(kd) else None),
            })
    offending.sort(
        key=lambda o: -(o["hbm_bytes_b"] - o["hbm_bytes_a"]))

    if delta * 100.0 > tol_pct:
        verdict = "regression"
    elif -delta * 100.0 > tol_pct:
        verdict = "improvement"
    else:
        verdict = "pass"
    return {
        "verdict": verdict,
        "delta_pct": round(delta * 100.0, 3),
        "tol_pct": tol_pct,
        "bytes_per_instr": {"a": bpi_a, "b": bpi_b},
        "offending_kernels": offending,
        "flags": flags,
        "a": {"label": entry_a.get("label"),
              "device_kind": dev_a,
              "hlo_fingerprint": hlo_a},
        "b": {"label": entry_b.get("label"),
              "device_kind": dev_b,
              "hlo_fingerprint": hlo_b},
    }


# lint: host
def format_cost_report(rep: dict) -> str:
    """Glanceable lines for the bytes gate (JSON is the machine
    surface)."""
    a, b = rep.get("a", {}), rep.get("b", {})
    lines = [(f"bench-diff --bytes: {a.get('label', '?')} -> "
              f"{b.get('label', '?')}: {rep['verdict'].upper()}")]
    if rep["verdict"] == "incomparable":
        lines.append(f"  {rep.get('detail', '')}")
    else:
        bpi = rep.get("bytes_per_instr", {})
        lines.append(
            f"  bytes/instr {bpi.get('a'):.4f} -> {bpi.get('b'):.4f} "
            f"({rep['delta_pct']:+.2f}%, tolerance "
            f"{rep['tol_pct']:.2f}%)")
        for o in rep.get("offending_kernels", []):
            d = (f"{o['delta_pct']:+.2f}%" if o["delta_pct"] is not None
                 else "new traffic")
            lines.append(
                f"    kernel {o['name']}: {o['hbm_bytes_a']:.0f} -> "
                f"{o['hbm_bytes_b']:.0f} HBM bytes/step ({d})")
    if rep.get("flags"):
        lines.append("  flags: " + ", ".join(rep["flags"]))
    return "\n".join(lines)


# lint: host
def compare_latency(entry_a: dict, entry_b: dict,
                    min_effect: float = DEFAULT_MIN_EFFECT,
                    alpha: float = DEFAULT_ALPHA) -> dict:
    """Compare two entries' open-loop latency blocks (A = baseline,
    B = candidate; obs.history v1.4, recorded by ``bench.py --soak``).

    Same two-bar decision as :func:`compare`, but over per-JOB latency
    samples instead of per-rep wall times:

    1. **Statistical**: one-sided Mann-Whitney U on the raw
       ``samples_ms`` vectors (a soak yields tens of samples, so the
       rank test has real power here). Skipped (p None, flagged
       "low_power") when either side recorded fewer than 2 samples.
    2. **Practical**: the p95 relative delta must exceed
       ``min_effect`` — and ONLY ``min_effect``. The rep-spread term
       of :func:`compare` is deliberately absent: job latencies across
       a mixed open-loop stream spread structurally (different
       workloads, different queue positions), so "spread" here is
       signal, not machine wobble; the rank test carries the noise
       question.

    Incomparable when metrics, device kinds, or **arrival rates**
    differ (latency under different offered load measures a different
    operating point), or when either side has no latency block.
    """
    flags = []
    if entry_a.get("metric") != entry_b.get("metric"):
        return {
            "verdict": "incomparable",
            "detail": (f"metric mismatch: {entry_a.get('metric')!r} vs "
                       f"{entry_b.get('metric')!r}"),
            "a": {"label": entry_a.get("label")},
            "b": {"label": entry_b.get("label")},
            "flags": ["metric_mismatch"],
        }
    dev_a = entry_a.get("device_kind")
    dev_b = entry_b.get("device_kind")
    if dev_a and dev_b and dev_a != dev_b:
        return {
            "verdict": "incomparable",
            "detail": (f"incomparable: different device "
                       f"({dev_a!r} vs {dev_b!r})"),
            "a": {"label": entry_a.get("label"), "device_kind": dev_a},
            "b": {"label": entry_b.get("label"), "device_kind": dev_b},
            "flags": ["device_mismatch"],
        }
    lat_a = entry_a.get("latency")
    lat_b = entry_b.get("latency")
    for side, lat, e in (("a", lat_a, entry_a), ("b", lat_b, entry_b)):
        if not isinstance(lat, dict):
            return {
                "verdict": "incomparable",
                "detail": (f"no latency block on side {side} "
                           f"({e.get('label')!r}) — record it with "
                           "bench.py --soak"),
                "a": {"label": entry_a.get("label")},
                "b": {"label": entry_b.get("label")},
                "flags": ["no_latency"],
            }
    rate_a = lat_a.get("arrival_rate")
    rate_b = lat_b.get("arrival_rate")
    if rate_a != rate_b:
        return {
            "verdict": "incomparable",
            "detail": (f"arrival-rate mismatch: {rate_a!r} vs "
                       f"{rate_b!r} jobs/s — latency at different "
                       "offered loads measures different operating "
                       "points"),
            "a": {"label": entry_a.get("label"), "arrival_rate": rate_a},
            "b": {"label": entry_b.get("label"), "arrival_rate": rate_b},
            "flags": ["arrival_rate_mismatch"],
        }
    for side, lat in (("a", lat_a), ("b", lat_b)):
        if lat.get("saturated"):
            flags.append(f"saturated:{side}")
    p95_a = float(lat_a["p95_ms"])
    p95_b = float(lat_b["p95_ms"])
    if p95_a <= 0:
        return {
            "verdict": "incomparable",
            "detail": "baseline p95 is zero",
            "a": {"label": entry_a.get("label")},
            "b": {"label": entry_b.get("label")},
            "flags": flags + ["no_latency"],
        }
    delta = (p95_b - p95_a) / p95_a
    threshold = min_effect

    samp_a = list(lat_a.get("samples_ms") or [])
    samp_b = list(lat_b.get("samples_ms") or [])
    p = u = method = None
    p_impr = None
    if len(samp_a) >= 2 and len(samp_b) >= 2:
        slower = mann_whitney_u(samp_a, samp_b)  # H1: b latencies larger
        faster = mann_whitney_u(samp_b, samp_a)  # H1: a latencies larger
        u, method = slower["u"], slower["method"]
        p, p_impr = slower["p"], faster["p"]
        if 1.0 / math.comb(len(samp_a) + len(samp_b),
                           min(len(samp_a), len(samp_b))) > alpha:
            flags.append("low_power")
            p = p_impr = None
    else:
        flags.append("low_power")

    if delta >= threshold and (p is None or p <= alpha):
        verdict = "regression"
    elif -delta >= threshold and (p_impr is None or p_impr <= alpha):
        verdict = "improvement"
    else:
        verdict = "noise"

    def _side(e, lat, samples):
        return {"label": e.get("label"),
                "p50_ms": lat.get("p50_ms"),
                "p95_ms": lat.get("p95_ms"),
                "p99_ms": lat.get("p99_ms"),
                "queue_depth_peak": lat.get("queue_depth_peak"),
                "samples": len(samples)}

    return {
        "verdict": verdict,
        "delta_pct": round(delta * 100.0, 3),
        "threshold_pct": round(threshold * 100.0, 3),
        "arrival_rate": rate_a,
        "p": p,
        "u": u,
        "method": method,
        "alpha": alpha,
        "flags": flags,
        "a": _side(entry_a, lat_a, samp_a),
        "b": _side(entry_b, lat_b, samp_b),
    }


# lint: host
def format_latency_report(rep: dict) -> str:
    """Glanceable lines for the latency gate (JSON is the machine
    surface)."""
    a, b = rep.get("a", {}), rep.get("b", {})
    lines = [(f"bench-diff --latency: {a.get('label', '?')} -> "
              f"{b.get('label', '?')}: {rep['verdict'].upper()}")]
    if rep["verdict"] == "incomparable":
        lines.append(f"  {rep.get('detail', '')}")
    else:
        lines.append(
            f"  p95 {a.get('p95_ms')}ms -> {b.get('p95_ms')}ms "
            f"({rep['delta_pct']:+.2f}%), practical bar "
            f"{rep['threshold_pct']:.2f}% "
            f"@ {rep.get('arrival_rate')} jobs/s "
            f"({a.get('samples')} vs {b.get('samples')} job samples)")
        if rep.get("p") is not None:
            lines.append(
                f"  Mann-Whitney one-sided p={rep['p']:.4f} "
                f"({rep['method']}, alpha={rep['alpha']})")
    if rep.get("flags"):
        lines.append("  flags: " + ", ".join(rep["flags"]))
    return "\n".join(lines)


# lint: host
def format_report(rep: dict) -> str:
    """Two-to-four human lines for terminal output (JSON is the
    machine surface; this is the glanceable one)."""
    lines = []
    a, b = rep.get("a", {}), rep.get("b", {})
    head = (f"bench-diff: {a.get('label', '?')} -> {b.get('label', '?')}"
            f": {rep['verdict'].upper()}")
    lines.append(head)
    if rep["verdict"] == "incomparable":
        lines.append(f"  {rep.get('detail', '')}")
    else:
        lines.append(
            f"  median {a.get('median_s')}s -> {b.get('median_s')}s "
            f"({rep['delta_pct']:+.2f}%), practical bar "
            f"{rep['threshold_pct']:.2f}% "
            f"(spreads {a.get('spread_pct')}% / {b.get('spread_pct')}%)")
        if rep.get("p") is not None:
            lines.append(
                f"  Mann-Whitney one-sided p={rep['p']:.4f} "
                f"({rep['method']}, alpha={rep['alpha']})")
    if rep.get("flags"):
        lines.append("  flags: " + ", ".join(rep["flags"]))
    return "\n".join(lines)
