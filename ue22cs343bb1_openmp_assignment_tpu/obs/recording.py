"""Traffic recordings: the ``cache-sim/recording/v1`` JSONL artifact.

A recording is the capture side of ROADMAP item 4: every submission a
daemon (or an in-proc :func:`daemon.core.drive` session) ACCEPTS is
streamed as one JSONL row — the full JobSpec, the lane, the SCHEDULED
arrival time on the injectable clock, and the admission queue depth at
accept — followed later by one result row carrying the job's dump
digest, cycle count, and bucket. The artifact is therefore a complete,
replayable description of a served traffic window: feeding
:func:`arrivals` back through ``daemon.core.drive`` (or a live daemon)
re-drives the exact open-loop schedule with original arrival times
preserved, and :func:`latency_block` reconstructs the RECORDED
latency distribution from the rows alone, so ``bench-diff --latency``
can adjudicate recorded-vs-replayed.

Format: line 1 is the header (``schema``, ``clock``, the scheduler
``config`` fingerprint); every further line is an event row::

    {"event": "submit", "job", "lane", "t_s", "depth", "spec": {...}}
    {"event": "result", "job", "t_s", "quiesced", "digest",
     "cycles", "bucket"}

All rows are written with sorted keys and timestamps read off the ONE
injected clock (relative to the core's ``t_start``), so a session on a
VirtualClock produces byte-identical recordings across runs — the
determinism gate in tests/test_recording.py. Result digests are
computed from the per-node golden dumps BEFORE ``retain_results``
eviction (daemon/core._extract), so the digest column is complete
even for jobs whose result docs the daemon has already dropped.

Host-side and dependency-free like the rest of obs (the only repo
import is JobSpec, for :func:`arrivals`).
"""
# lint: host

from __future__ import annotations

import hashlib
import json
import os
from typing import Dict, List, Optional, Tuple

SCHEMA_ID = "cache-sim/recording/v1"

#: canonical file name inside a record directory / incident dir
FILENAME = "recording.jsonl"

_HEADER_KEYS = ("schema", "clock", "config")
_SUBMIT_KEYS = ("event", "job", "lane", "t_s", "depth", "spec")
_RESULT_KEYS = ("event", "job", "t_s", "quiesced", "digest", "cycles",
                "bucket")


# lint: host
def digest(dumps: List[str]) -> str:
    """Stable short digest of a job's per-node golden dumps — the
    byte-parity fingerprint a replay is checked against."""
    h = hashlib.sha256()
    for text in dumps:
        h.update(text.encode("utf-8"))
        h.update(b"\x00")
    return h.hexdigest()[:16]


# lint: host
def _line(row: dict) -> str:
    return json.dumps(row, sort_keys=True) + "\n"


# lint: host
def _target(path) -> str:
    """Writer-side path resolution: anything that is not explicitly a
    ``.jsonl`` file is a record DIRECTORY (the ``daemon --record DIR``
    convention) and gets :data:`FILENAME` inside it; parents are
    created either way."""
    path = str(path)
    if not path.endswith(".jsonl"):
        os.makedirs(path, exist_ok=True)
        return os.path.join(path, FILENAME)
    parent = os.path.dirname(os.path.abspath(path))
    os.makedirs(parent, exist_ok=True)
    return path


class RecordingWriter:
    """Streaming writer: one accepted submission / one finished job →
    one flushed JSONL row, so a killed daemon still leaves a valid,
    replayable prefix on disk."""

    # lint: host
    def __init__(self, path, clock_kind: str,
                 config: Optional[dict] = None):
        self.path = _target(path)
        self.submits = 0
        self.results = 0
        self._f = open(self.path, "w")
        self._f.write(_line({"schema": SCHEMA_ID,
                             "clock": str(clock_kind),
                             "config": dict(config or {})}))
        self._f.flush()

    # lint: host
    def submit(self, spec, lane: str, t_s: float, depth: int) -> None:
        """One ACCEPTED submission (rejected jobs are backpressure,
        not traffic served — they are not recorded)."""
        import dataclasses
        self._f.write(_line({
            "event": "submit", "job": spec.name, "lane": str(lane),
            "t_s": float(t_s), "depth": int(depth),
            "spec": dataclasses.asdict(spec)}))
        self._f.flush()
        self.submits += 1

    # lint: host
    def result(self, job: str, t_s: float, quiesced: bool,
               dump_digest: str, cycles: int, bucket: str) -> None:
        self._f.write(_line({
            "event": "result", "job": str(job), "t_s": float(t_s),
            "quiesced": bool(quiesced), "digest": str(dump_digest),
            "cycles": int(cycles), "bucket": str(bucket)}))
        self._f.flush()
        self.results += 1

    # lint: host
    def close(self) -> None:
        if not self._f.closed:
            self._f.close()


# lint: host
def validate(header: dict, rows: List[dict],
             where: str = "recording") -> None:
    """Structural check; raises ValueError listing every violation
    (the obs.schema contract)."""
    errs = []
    if header.get("schema") != SCHEMA_ID:
        errs.append(f"schema must be {SCHEMA_ID!r}, "
                    f"got {header.get('schema')!r}")
    if header.get("clock") not in ("monotonic", "virtual"):
        errs.append(f"clock must be monotonic|virtual, "
                    f"got {header.get('clock')!r}")
    for k in _HEADER_KEYS:
        if k not in header:
            errs.append(f"header missing key: {k}")
    if not isinstance(header.get("config"), dict):
        errs.append("header config must be a dict")
    seen: Dict[str, bool] = {}
    last_t = None
    for i, row in enumerate(rows, 2):
        ev = row.get("event")
        if ev == "submit":
            for k in _SUBMIT_KEYS:
                if k not in row:
                    errs.append(f"line {i}: submit missing key {k!r}")
            job = row.get("job")
            if job in seen:
                errs.append(f"line {i}: duplicate submit for "
                            f"job {job!r}")
            seen[job] = False
            t = row.get("t_s")
            if not isinstance(t, (int, float)) or t < 0:
                errs.append(f"line {i}: t_s must be a non-negative "
                            f"number, got {t!r}")
            elif last_t is not None and t < last_t:
                errs.append(f"line {i}: submit times must be "
                            f"non-decreasing ({t} after {last_t})")
            else:
                last_t = t
            if not isinstance(row.get("spec"), dict):
                errs.append(f"line {i}: spec must be a dict")
        elif ev == "result":
            for k in _RESULT_KEYS:
                if k not in row:
                    errs.append(f"line {i}: result missing key {k!r}")
            job = row.get("job")
            if job not in seen:
                errs.append(f"line {i}: result for job {job!r} "
                            "with no prior submit")
            elif seen[job]:
                errs.append(f"line {i}: duplicate result for "
                            f"job {job!r}")
            else:
                seen[job] = True
        else:
            errs.append(f"line {i}: event must be submit|result, "
                        f"got {ev!r}")
    if errs:
        raise ValueError(f"invalid {where}:\n  " + "\n  ".join(errs))


# lint: host
def resolve(path) -> str:
    """A recording file, or a directory containing :data:`FILENAME`,
    → the file path."""
    path = str(path)
    if os.path.isdir(path):
        path = os.path.join(path, FILENAME)
    return path


# lint: host
def load(path) -> dict:
    """Read + validate a recording; returns ``{"schema", "clock",
    "config", "rows", "path"}`` (rows exclude the header)."""
    path = resolve(path)
    header = None
    rows: List[dict] = []
    with open(path) as f:
        for line in f:
            if not line.strip():
                continue
            doc = json.loads(line)
            if header is None:
                header = doc
            else:
                rows.append(doc)
    if header is None:
        raise ValueError(f"{path}: empty recording (no header line)")
    validate(header, rows, where=path)
    return {"schema": header["schema"], "clock": header["clock"],
            "config": header["config"], "rows": rows, "path": path}


# lint: host
def write(path, rec: dict) -> str:
    """Write a (possibly sliced/shrunk) recording back out; returns
    the file path. Validates before writing."""
    path = _target(path)
    header = {"schema": rec.get("schema", SCHEMA_ID),
              "clock": rec["clock"], "config": rec.get("config", {})}
    validate(header, rec["rows"], where=path)
    with open(path, "w") as f:
        f.write(_line(header))
        for row in rec["rows"]:
            f.write(_line(row))
    return path


# lint: host
def arrivals(rec: dict):
    """The recording as the open-loop schedule ``[(t_s, JobSpec,
    lane)]`` that ``daemon.core.drive`` / ``soak.soak_daemon``
    re-drive — original arrival times preserved, coordinated-omission-
    free by construction (releases never waited on completions when
    recorded, and they never will on replay)."""
    from ue22cs343bb1_openmp_assignment_tpu.serve import JobSpec
    out = []
    for row in rec["rows"]:
        if row["event"] == "submit":
            out.append((float(row["t_s"]),
                        JobSpec.from_dict(row["spec"]), row["lane"]))
    return sorted(out, key=lambda a: (a[0], a[1].name))


# lint: host
def results_by_job(rec: dict) -> Dict[str, dict]:
    return {row["job"]: row for row in rec["rows"]
            if row["event"] == "result"}


# lint: host
def subset(rec: dict, names) -> dict:
    """The sub-recording over a set of job names (ddmin's reduction
    operator: jobs, not instructions, are the atoms)."""
    names = set(names)
    return {**rec, "rows": [row for row in rec["rows"]
                            if row["job"] in names]}


# lint: host
def slice_window(rec: dict, t_lo: float, t_hi: float) -> dict:
    """The sub-recording of jobs SUBMITTED inside ``[t_lo, t_hi]``
    (their result rows ride along) — the breach-window slice an SLO
    incident dir embeds."""
    keep = {row["job"] for row in rec["rows"]
            if row["event"] == "submit"
            and t_lo <= float(row["t_s"]) <= t_hi}
    return subset(rec, keep)


# lint: host
def derived_arrival_rate(rec: dict) -> float:
    """The offered load the recording actually carried (jobs/s over
    the submit window, rounded for byte-stable reuse). Both sides of
    a recorded-vs-replayed ``bench-diff --latency`` must stamp THIS
    value: the comparator treats differing arrival rates as different
    operating points (incomparable), and the replay serves the same
    schedule by construction."""
    ts = [float(row["t_s"]) for row in rec["rows"]
          if row["event"] == "submit"]
    if not ts:
        raise ValueError("recording has no submit rows")
    span = max(ts) - min(ts)
    return round(len(ts) / span, 6) if span > 0 else float(len(ts))


# lint: host
def latency_block(rec: dict,
                  arrival_rate: Optional[float] = None
                  ) -> Optional[dict]:
    """The RECORDED latency block (obs.history v1.4 shape):
    per-job e2e = result ``t_s`` − submit ``t_s`` on the one recorded
    clock, nearest-rank percentiles, and the recorded admission queue
    depth peak. None when no job finished inside the recording."""
    from ue22cs343bb1_openmp_assignment_tpu.obs import timeseries
    t_sub: Dict[str, float] = {}
    depth_peak = 0
    lat_s: List[Tuple[str, float]] = []
    for row in rec["rows"]:
        if row["event"] == "submit":
            t_sub[row["job"]] = float(row["t_s"])
            depth_peak = max(depth_peak, int(row["depth"]))
        elif row["job"] in t_sub:
            lat_s.append((row["job"],
                          float(row["t_s"]) - t_sub[row["job"]]))
    block = timeseries.latency_summary(
        [s for _, s in lat_s], arrival_rate=arrival_rate,
        queue_depth_peak=depth_peak)
    if block is not None:
        block["samples_ms"] = [round(s * 1e3, 6)
                               for s in sorted(x for _, x in lat_s)]
    return block
