"""Host rendering of the on-device telemetry time-series.

ops.step.run_cycles_telemetry stacks one fixed-shape sample per cycle
on device (counter deltas, per-type dequeues, queue-depth watermarks,
directory occupancy, latency-histogram deltas) — this module turns the
fetched [T, ...] arrays into named JSON-ready series and compact
summaries for ``cache-sim stats --timeseries``.
"""

from __future__ import annotations

from typing import Dict

import numpy as np

from ue22cs343bb1_openmp_assignment_tpu.ops.step import TELEMETRY_COUNTERS
from ue22cs343bb1_openmp_assignment_tpu.types import MSG_NAMES

DIR_STATES = ("EM", "S", "U")


# lint: host
def _np(telem: Dict) -> Dict[str, np.ndarray]:
    return {k: np.asarray(v) for k, v in telem.items()}


# lint: host
def to_series(telem: Dict) -> dict:
    """Fetched telemetry dict → {"cycles": T, "series": {name: [T]
    ints}} with every named channel unpacked (counter deltas by
    counter name, dequeues by message type, occupancy by directory
    state)."""
    t = _np(telem)
    series: Dict[str, list] = {}
    for i, name in enumerate(TELEMETRY_COUNTERS):
        series[name] = t["counters"][:, i].tolist()
    for i, name in enumerate(MSG_NAMES):
        series[f"msgs_{name}"] = t["msgs_processed"][:, i].tolist()
    for i, name in enumerate(DIR_STATES):
        series[f"dir_{name}"] = t["dir_occupancy"][:, i].tolist()
    for key in ("queue_depth_max", "queue_depth_total", "waiting_nodes",
                "msgs_dropped", "msgs_injected_dropped"):
        series[key] = t[key].tolist()
    return {"cycles": int(t["counters"].shape[0]), "series": series}


# lint: host
def summarize(telem: Dict) -> dict:
    """Compact per-channel rollup: totals for deltas, peaks for
    watermarks/gauges — the cheap alternative when the full series
    would be unwieldy."""
    t = _np(telem)
    counters = {name: int(t["counters"][:, i].sum())
                for i, name in enumerate(TELEMETRY_COUNTERS)}
    return {
        "cycles": int(t["counters"].shape[0]),
        "counter_totals": counters,
        "msgs_by_type": {name: int(t["msgs_processed"][:, i].sum())
                         for i, name in enumerate(MSG_NAMES)},
        "queue_depth_peak": int(t["queue_depth_max"].max(initial=0)),
        "queue_depth_total_peak": int(
            t["queue_depth_total"].max(initial=0)),
        "waiting_nodes_peak": int(t["waiting_nodes"].max(initial=0)),
        "dir_occupancy_last": {
            name: int(t["dir_occupancy"][-1, i])
            for i, name in enumerate(DIR_STATES)
        } if t["dir_occupancy"].shape[0] else None,
        "lat_hist_total": t["lat_hist"].sum(axis=0).astype(int).tolist(),
    }
