"""Host rendering of the on-device telemetry time-series.

ops.step.run_cycles_telemetry stacks one fixed-shape sample per cycle
on device (counter deltas, per-type dequeues, queue-depth watermarks,
directory occupancy, latency-histogram deltas) — this module turns the
fetched [T, ...] arrays into named JSON-ready series and compact
summaries for ``cache-sim stats --timeseries``.

The serving layer adds a second, host-sampled series family: the soak
harness (soak.py) samples admission-queue depth and slot occupancy at
every scheduler turn; :func:`serve_series` /
:func:`summarize_serve_series` shape those samples the same JSON-ready
way, and :func:`percentile` / :func:`latency_summary` turn a job
latency vector into the p50/p95/p99 block that rides bench history
(obs.history schema v1.4) and the serve-trace doc.
"""

from __future__ import annotations

import math
from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np

from ue22cs343bb1_openmp_assignment_tpu.ops.step import TELEMETRY_COUNTERS
from ue22cs343bb1_openmp_assignment_tpu.types import MSG_NAMES

DIR_STATES = ("EM", "S", "U")


# lint: host
def _np(telem: Dict) -> Dict[str, np.ndarray]:
    return {k: np.asarray(v) for k, v in telem.items()}


# lint: host
def to_series(telem: Dict) -> dict:
    """Fetched telemetry dict → {"cycles": T, "series": {name: [T]
    ints}} with every named channel unpacked (counter deltas by
    counter name, dequeues by message type, occupancy by directory
    state)."""
    t = _np(telem)
    series: Dict[str, list] = {}
    for i, name in enumerate(TELEMETRY_COUNTERS):
        series[name] = t["counters"][:, i].tolist()
    for i, name in enumerate(MSG_NAMES):
        series[f"msgs_{name}"] = t["msgs_processed"][:, i].tolist()
    for i, name in enumerate(DIR_STATES):
        series[f"dir_{name}"] = t["dir_occupancy"][:, i].tolist()
    for key in ("queue_depth_max", "queue_depth_total", "waiting_nodes",
                "msgs_dropped", "msgs_injected_dropped"):
        series[key] = t[key].tolist()
    return {"cycles": int(t["counters"].shape[0]), "series": series}


# lint: host
def summarize(telem: Dict) -> dict:
    """Compact per-channel rollup: totals for deltas, peaks for
    watermarks/gauges — the cheap alternative when the full series
    would be unwieldy."""
    t = _np(telem)
    counters = {name: int(t["counters"][:, i].sum())
                for i, name in enumerate(TELEMETRY_COUNTERS)}
    return {
        "cycles": int(t["counters"].shape[0]),
        "counter_totals": counters,
        "msgs_by_type": {name: int(t["msgs_processed"][:, i].sum())
                         for i, name in enumerate(MSG_NAMES)},
        "queue_depth_peak": int(t["queue_depth_max"].max(initial=0)),
        "queue_depth_total_peak": int(
            t["queue_depth_total"].max(initial=0)),
        "waiting_nodes_peak": int(t["waiting_nodes"].max(initial=0)),
        "dir_occupancy_last": {
            name: int(t["dir_occupancy"][-1, i])
            for i, name in enumerate(DIR_STATES)
        } if t["dir_occupancy"].shape[0] else None,
        "lat_hist_total": t["lat_hist"].sum(axis=0).astype(int).tolist(),
    }


# -- serving-side (host-sampled) series ------------------------------------


# lint: host
def serve_series(samples: Sequence[Tuple[float, int, int]]) -> dict:
    """Soak scheduler samples [(t_s, queue_depth, slots_busy), ...] →
    ``{"samples": n, "series": {"t_s", "queue_depth", "slots_busy"}}``
    — the same named-series shape as :func:`to_series`, but sampled on
    the host at scheduler turns (admission boundaries), not per cycle
    on device."""
    return {
        "samples": len(samples),
        "series": {
            "t_s": [float(t) for t, _, _ in samples],
            "queue_depth": [int(q) for _, q, _ in samples],
            "slots_busy": [int(b) for _, _, b in samples],
        },
    }


# lint: host
def summarize_serve_series(samples: Sequence[Tuple[float, int, int]]) -> dict:
    """Peaks + endpoint of a serve_series sample list (the queue-depth
    numbers the backpressure verdict and the history latency block
    read)."""
    depths = [int(q) for _, q, _ in samples]
    busy = [int(b) for _, _, b in samples]
    return {
        "samples": len(samples),
        "queue_depth_peak": max(depths, default=0),
        "queue_depth_final": depths[-1] if depths else 0,
        "slots_busy_peak": max(busy, default=0),
    }


# lint: host
def percentile(xs: Sequence[float], q: float) -> float:
    """Nearest-rank percentile (q in [0, 100]) of a non-empty sample.

    Nearest-rank on purpose: every reported percentile is a latency
    that actually happened to a job, never an interpolated value —
    which also keeps virtual-clock soak docs byte-identical (no
    float interpolation to wobble)."""
    # len(), not truthiness: a numpy sample array would make `not xs`
    # raise the ambiguous-truth error instead of the clear one below
    if len(xs) == 0:
        raise ValueError("percentile of an empty sample: no jobs "
                         "completed, so there is no latency to rank")
    if not 0 <= q <= 100:
        raise ValueError(f"q must be in [0, 100], got {q}")
    s = sorted(xs)
    rank = max(1, math.ceil(q / 100.0 * len(s)))
    return float(s[rank - 1])


# lint: host
def latency_summary(lat_s: Sequence[float],
                    arrival_rate: Optional[float] = None,
                    queue_depth_peak: Optional[int] = None) -> Optional[dict]:
    """Job end-to-end latencies (seconds) → the p50/p95/p99 latency
    block (milliseconds) that rides the serve-trace doc and — with
    ``arrival_rate`` / ``queue_depth_peak`` — bench history v1.4.
    None for an empty sample (a soak that released no jobs)."""
    if not len(lat_s):
        return None
    ms: List[float] = [float(x) * 1e3 for x in lat_s]
    doc = {
        "p50_ms": percentile(ms, 50),
        "p95_ms": percentile(ms, 95),
        "p99_ms": percentile(ms, 99),
        "max_ms": max(ms),
        "jobs": len(ms),
    }
    if arrival_rate is not None:
        doc["arrival_rate"] = float(arrival_rate)
    if queue_depth_peak is not None:
        doc["queue_depth_peak"] = int(queue_depth_peak)
    return doc


# -- mergeable latency histogram (the fleet-exact aggregate) ---------------

#: fixed log-spaced latency bucket upper edges in milliseconds —
#: 1 µs .. ~2.2 min doubling, identical for EVERY histogram instance.
#: Fixed on purpose: two replicas' histograms share edges by
#: construction, so a fleet merge is an exact elementwise count sum
#: (never a lossy re-bucketing), and the Prometheus ``le`` label set
#: is stable across the fleet. Each edge is a power of two times an
#: exact binary float, so the doc round-trips JSON byte-identically.
HIST_EDGES_MS = tuple(0.001 * (1 << i) for i in range(28))


class LogHistogram:
    """Streaming latency histogram over :data:`HIST_EDGES_MS`.

    ``counts[i]`` holds samples with ``value <= HIST_EDGES_MS[i]``
    (and above the previous edge); the final extra slot is the
    open-ended overflow bucket. ``count``/``sum_ms`` ride along so
    Prometheus exposition gets ``_count``/``_sum`` for free.
    """

    # lint: host
    def __init__(self):
        self.counts: List[int] = [0] * (len(HIST_EDGES_MS) + 1)
        self.count = 0
        self.sum_ms = 0.0

    # lint: host
    def observe(self, ms: float) -> None:
        ms = float(ms)
        self.count += 1
        self.sum_ms += ms
        for i, edge in enumerate(HIST_EDGES_MS):
            if ms <= edge:
                self.counts[i] += 1
                return
        self.counts[-1] += 1

    # lint: host
    def to_doc(self) -> dict:
        return {"edges_ms": list(HIST_EDGES_MS),
                "counts": list(self.counts),
                "count": self.count, "sum_ms": self.sum_ms}


# lint: host
def merge_hist_docs(docs: Sequence[dict]) -> Optional[dict]:
    """Exact cross-replica merge of :class:`LogHistogram` docs:
    identical fixed edges → the merged histogram is the elementwise
    count sum (the fleet aggregator's per-lane latency view). Raises
    on mismatched edges; None when no doc survives filtering."""
    docs = [d for d in docs if d]
    if not docs:
        return None
    edges = docs[0]["edges_ms"]
    counts = [0] * len(docs[0]["counts"])
    count = 0
    sum_ms = 0.0
    for d in docs:
        if d["edges_ms"] != edges or len(d["counts"]) != len(counts):
            raise ValueError("histogram docs have mismatched bucket "
                             "edges — refusing a lossy merge")
        for i, c in enumerate(d["counts"]):
            counts[i] += int(c)
        count += int(d["count"])
        sum_ms += float(d["sum_ms"])
    return {"edges_ms": list(edges), "counts": counts,
            "count": count, "sum_ms": sum_ms}


# lint: host
def lane_latency_summaries(spans: Sequence[dict]) -> Dict[str, dict]:
    """Job-lifecycle spans → one :func:`latency_summary` block per
    priority lane (the daemon's per-tenant latency metrics). Spans
    without a ``lane`` annotation (serve/soak single-tenant runs)
    group under ``"default"``; lanes sort lexicographically so the
    dict is deterministic under a VirtualClock."""
    by_lane: Dict[str, List[float]] = {}
    for s in spans:
        by_lane.setdefault(s.get("lane") or "default", []).append(
            float(s["e2e_s"]))
    return {lane: latency_summary(lat)
            for lane, lat in sorted(by_lane.items())}
