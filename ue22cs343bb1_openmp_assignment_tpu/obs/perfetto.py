"""Chrome/Perfetto trace-event JSON export of engine event records.

Renders the structured records from utils.eventlog (async engine:
instruction fetches + message dequeues; sync/deep engine: retirement
events) as a trace-event document loadable in ui.perfetto.dev or
chrome://tracing:

- one *process* per node (pid = node id, named ``node <n>``),
- two *threads* per node: tid 0 = ``instr`` track, tid 1 = ``msg``
  track,
- each event a complete ("X") slice at ts = cycle (microsecond units —
  1 simulated cycle renders as 1 us), dur = 1, with the decoded fields
  in ``args``.

The exporter is pure host-side rendering of already-fetched arrays;
the capture itself is the single-dispatch ``lax.scan`` event stack
(ops.step.run_cycles_traced / ops.sync_engine.run_rounds_traced).
"""

from __future__ import annotations

import json
from typing import Dict, List

from ue22cs343bb1_openmp_assignment_tpu.types import Op

TID_INSTR = 0
TID_MSG = 1

_PHASES = ("X", "B", "E", "I", "M", "C", "s", "t", "f")

#: flow-event phases (ph "s" start / "t" step / "f" finish) — emitted
#: by span_flow_events to link one transaction's request/reply slices
#: across node tracks; each binds to the X slice sharing its
#: (pid, tid, ts)
_FLOW_PHASES = ("s", "t", "f")


# lint: host
def _meta(pid: int, tid: int, kind: str, name: str) -> dict:
    ev = {"ph": "M", "pid": pid, "name": kind, "args": {"name": name}}
    if kind == "thread_name":
        ev["tid"] = tid
    return ev


# lint: host
def track_metadata(num_nodes: int) -> List[dict]:
    """Process/thread-name metadata events for per-node tracks."""
    out = []
    for n in range(num_nodes):
        out.append(_meta(n, 0, "process_name", f"node {n}"))
        out.append(_meta(n, TID_INSTR, "thread_name", "instr"))
        out.append(_meta(n, TID_MSG, "thread_name", "msg"))
    return out


# lint: host
def record_to_event(rec: dict) -> dict:
    """One eventlog record ({"kind": "instr"|"msg", ...}) → one "X"
    slice."""
    if rec["kind"] == "instr":
        mnem = "WR" if rec["op"] == int(Op.WRITE) else "RD"
        return {"name": f"{mnem} 0x{rec['addr']:02X}", "ph": "X",
                "cat": "instr", "pid": rec["node"], "tid": TID_INSTR,
                "ts": rec["cycle"], "dur": 1,
                "args": {"op": rec["op"], "addr": rec["addr"],
                         "value": rec["value"]}}
    return {"name": rec["type_name"], "ph": "X", "cat": "msg",
            "pid": rec["node"], "tid": TID_MSG, "ts": rec["cycle"],
            "dur": 1,
            "args": {"sender": rec["sender"], "type": rec["type"],
                     "addr": rec["addr"]}}


# lint: host
def span_flow_events(spans: List[dict]) -> List[dict]:
    """Transaction spans (obs.txntrace) → Perfetto flow events linking
    each span's request/reply slices across node tracks.

    Per attributed closed span: a flow *start* ("s") on the issuing
    instruction slice at the requester, a *step* ("t") on each
    intermediate hop's dequeue slice, and a *finish* ("f", binding
    enclosing, so it attaches to the final reply's dequeue slice back
    at the requester). Flow ids are the span's position in the input
    list — stable because span order is reconstruction order.
    """
    out = []
    for fid, s in enumerate(spans):
        if not s.get("attributed") or not s.get("chain"):
            continue
        name = (f"txn n{s['requester']} 0x{s['addr']:02X} "
                f"#{s['seq']}")
        common = {"name": name, "cat": "txn", "id": fid}
        out.append({"ph": "s", "pid": s["requester"],
                    "tid": TID_INSTR, "ts": s["t_issue"], **common})
        for hop in s["chain"][:-1]:
            out.append({"ph": "t", "pid": hop["dst"], "tid": TID_MSG,
                        "ts": hop["deq"], **common})
        last = s["chain"][-1]
        out.append({"ph": "f", "bp": "e", "pid": last["dst"],
                    "tid": TID_MSG, "ts": last["deq"], **common})
    return out


#: serving-trace track layout (build_serve_trace): pid 0 is the
#: admission queue; slot s renders as process PID_SLOT0 + s
PID_QUEUE = 0
PID_SLOT0 = 1

#: seconds -> trace-event microseconds
_US = 1e6


# lint: host
def serve_span_events(spans: List[dict]) -> List[dict]:
    """Job-lifecycle spans (serve.SpanBook / obs.schema serve-trace) →
    Perfetto slices plus flow arrows following each job across tracks.

    Per span: a ``queued`` slice on the admission-queue track
    (pid PID_QUEUE) from submit to admission, a ``run`` slice on the
    job's slot track (pid PID_SLOT0 + slot, tid 0) from admission to
    quiescence, and an ``extract`` slice (tid 1) from quiescence to
    extraction — then a flow arrow ("s" on the queue slice, "t" on the
    run slice, "f" binding-enclosing on the extract slice) stitching
    the three into one visual chain per job. Flow ids are the span's
    position in the input list, same convention as span_flow_events.
    """
    out = []
    for fid, s in enumerate(spans):
        pid = PID_SLOT0 + s["slot"]
        t_sub = s["t_submit"] * _US
        t_adm = s["t_admitted"] * _US
        t_qui = s["t_quiescent"] * _US
        t_ext = s["t_extracted"] * _US
        args = {"wave": s["wave"], "slot": s["slot"],
                "quiesced": s["quiesced"]}
        # daemon spans carry the priority lane and shape-bucket label
        # (obs.schema optional span keys) — surface them in the slice
        # args so a Perfetto query can split latency by lane
        for k in ("lane", "bucket"):
            if s.get(k) is not None:
                args[k] = s[k]
        out.append({"name": f"queued {s['job']}", "ph": "X",
                    "cat": "serve", "pid": PID_QUEUE, "tid": 0,
                    "ts": t_sub, "dur": max(t_adm - t_sub, 1.0),
                    "args": args})
        out.append({"name": f"run {s['job']}", "ph": "X",
                    "cat": "serve", "pid": pid, "tid": TID_INSTR,
                    "ts": t_adm, "dur": max(t_qui - t_adm, 1.0),
                    "args": args})
        out.append({"name": f"extract {s['job']}", "ph": "X",
                    "cat": "serve", "pid": pid, "tid": TID_MSG,
                    "ts": t_qui, "dur": max(t_ext - t_qui, 1.0),
                    "args": args})
        common = {"name": f"job {s['job']}", "cat": "serve", "id": fid}
        out.append({"ph": "s", "pid": PID_QUEUE, "tid": 0,
                    "ts": t_sub, **common})
        out.append({"ph": "t", "pid": pid, "tid": TID_INSTR,
                    "ts": t_adm, **common})
        out.append({"ph": "f", "bp": "e", "pid": pid, "tid": TID_MSG,
                    "ts": t_qui, **common})
    return out


# lint: host
def build_serve_trace(spans: List[dict]) -> dict:
    """Spans → a complete, validated serving trace-event document:
    one ``queue`` process plus one process per batch slot used, each
    slot with ``run``/``extract`` threads, job slices linked by flow
    arrows (serve_span_events). Time unit: 1 us = 1 clock second/1e6
    (the injected serving clock, see obs.clock)."""
    events = [_meta(PID_QUEUE, 0, "process_name", "queue"),
              _meta(PID_QUEUE, 0, "thread_name", "jobs")]
    for slot in sorted({s["slot"] for s in spans}):
        pid = PID_SLOT0 + slot
        events.append(_meta(pid, 0, "process_name", f"slot {slot}"))
        events.append(_meta(pid, TID_INSTR, "thread_name", "run"))
        events.append(_meta(pid, TID_MSG, "thread_name", "extract"))
    events.extend(serve_span_events(spans))
    return {"traceEvents": events, "displayTimeUnit": "ms",
            "otherData": {"source": "cache-sim serve",
                          "time_unit": "clock_us"}}


# lint: host
def build_trace(records: List[dict], num_nodes: int,
                flows: List[dict] = None) -> dict:
    """Records (utils.eventlog.to_records / sync_to_records) → a
    complete trace-event JSON document. ``flows`` (span_flow_events)
    are appended after the slices they bind to."""
    events = track_metadata(num_nodes)
    events.extend(record_to_event(r) for r in records)
    if flows:
        events.extend(flows)
    return {"traceEvents": events, "displayTimeUnit": "ms",
            "otherData": {"source": "cache-sim", "time_unit": "cycle"}}


# lint: host
def write_trace(path: str, doc: dict) -> None:
    with open(path, "w") as f:
        json.dump(doc, f, separators=(",", ":"))
        f.write("\n")


# lint: host
def validate_trace(doc: dict) -> dict:
    """Structural check of a trace-event document (the subset this
    exporter emits plus what Perfetto requires); raises ValueError
    listing every violation, returns the doc."""
    errs = []
    if not isinstance(doc, dict) or "traceEvents" not in doc:
        raise ValueError("trace must be a dict with a traceEvents list")
    evs = doc["traceEvents"]
    if not isinstance(evs, list):
        raise ValueError("traceEvents must be a list")
    for i, ev in enumerate(evs):
        if not isinstance(ev, dict):
            errs.append(f"event {i}: not a dict")
            continue
        ph = ev.get("ph")
        if ph not in _PHASES:
            errs.append(f"event {i}: bad ph {ph!r}")
            continue
        if not isinstance(ev.get("pid"), int):
            errs.append(f"event {i}: missing/bad pid")
        if not isinstance(ev.get("name"), str):
            errs.append(f"event {i}: missing/bad name")
        if ph == "X":
            if not isinstance(ev.get("ts"), (int, float)):
                errs.append(f"event {i}: X event missing ts")
            if not isinstance(ev.get("dur"), (int, float)):
                errs.append(f"event {i}: X event missing dur")
            if not isinstance(ev.get("tid"), int):
                errs.append(f"event {i}: X event missing tid")
        if ph in _FLOW_PHASES:
            if not isinstance(ev.get("ts"), (int, float)):
                errs.append(f"event {i}: flow event missing ts")
            if not isinstance(ev.get("tid"), int):
                errs.append(f"event {i}: flow event missing tid")
            if not isinstance(ev.get("id"), (int, str)):
                errs.append(f"event {i}: flow event missing id")
            if not isinstance(ev.get("cat"), str):
                errs.append(f"event {i}: flow event missing cat")
        if ph == "M" and "args" not in ev:
            errs.append(f"event {i}: M event missing args")
    if errs:
        raise ValueError("invalid trace-event JSON:\n  "
                         + "\n  ".join(errs[:20]))
    return doc


# lint: host
def tracks(doc: dict) -> Dict[int, set]:
    """{pid: {thread names}} — convenience for tests asserting the
    per-node instr/msg track structure."""
    names: Dict[tuple, str] = {}
    used: Dict[int, set] = {}
    for ev in doc["traceEvents"]:
        if ev.get("ph") == "M" and ev.get("name") == "thread_name":
            names[(ev["pid"], ev["tid"])] = ev["args"]["name"]
    for ev in doc["traceEvents"]:
        if ev.get("ph") == "X":
            key = (ev["pid"], ev["tid"])
            used.setdefault(ev["pid"], set()).add(
                names.get(key, str(ev["tid"])))
    return used
