"""Roofline + memory-traffic attribution: which kernel moves the bytes.

ROADMAP item 1 (the fused Pallas cycle kernel) is a *memory-traffic*
bet: a simulated cycle should never round-trip its state through HBM.
Before this module nothing in ``obs/`` could say how many HBM bytes
one simulated cycle moves, or which kernel moves them — so there was
no instrument to pick the order of attack, and no way to prove a
kernel change cut traffic rather than got lucky on timing noise.

The model is Williams, Waterman & Patterson's roofline (CACM 2009,
PAPERS.md): a kernel with arithmetic intensity ``AI = flops / HBM
bytes`` below the machine's ridge point ``peak_flops / peak_bw`` is
bound by memory bandwidth, not compute. The inputs come from XLA's
``compiled.cost_analysis()`` (normalized by :func:`normalize_cost`
from the dict/list/None shape variance) and a static per-device peak
table (detected ``device_kind`` with a generic fallback), reduced to
two headline scalars:

- **bytes / simulated instruction** — per-step kernel HBM bytes ×
  steps / instructions retired. Steps and retired are deterministic
  integers of the run and the cost vector is deterministic per
  compiled HLO, so this number is *exact*: it can gate CI with zero
  reps and zero statistics (``cache-sim bench-diff --bytes``).
- **ns / instruction by phase** — the wall-clock decomposition
  (PhaseTimer dispatch/device_get split + the roofline model time per
  kernel). Timing is nondeterministic, so it is opt-in
  (``--timing``); the default report is byte-identical across runs on
  the same build.

Everything here is host-side: lowering and compiling never executes
the computation.
"""
# lint: host

from __future__ import annotations

import hashlib
from typing import Optional

SCHEMA_ID = "cache-sim/perfreport/v1"

#: cost_analysis() metric names for the three numbers a roofline needs
_FLOPS_KEY = "flops"
_BYTES_KEY = "bytes accessed"
_OUT_BYTES_KEY = "bytes accessedout{}"

#: static peak table: device_kind substring (lowercased, first match
#: wins) -> nominal peak dense-compute flops/s, HBM bytes/s and
#: per-core VMEM capacity. These are ceilings for *classification*,
#: not marketing claims — the bound verdict only needs the ridge
#: point's order of magnitude. Sources: published TPU spec sheets; the
#: cpu row is a nominal 1-core AVX box so CPU-tier smoke runs still
#: classify. ``vmem_bytes`` is the budget analysis/kernelcheck's
#: static VMEM pass referees fused kernels against: ~16 MiB/core on
#: v4/v5 parts, doubled on v6e; the cpu row carries the 16 MiB
#: as-if-TPU budget so interpret-mode CI runs gate against the
#: smallest real target instead of not gating at all.
PEAKS = (
    ("v6e", {"flops_per_s": 918e12, "hbm_bytes_per_s": 1.64e12,
             "vmem_bytes": 32 * 2**20}),
    ("v5p", {"flops_per_s": 459e12, "hbm_bytes_per_s": 2.76e12,
             "vmem_bytes": 16 * 2**20}),
    ("v5e", {"flops_per_s": 197e12, "hbm_bytes_per_s": 819e9,
             "vmem_bytes": 16 * 2**20}),
    ("v5 lite", {"flops_per_s": 197e12, "hbm_bytes_per_s": 819e9,
                 "vmem_bytes": 16 * 2**20}),
    ("v4", {"flops_per_s": 275e12, "hbm_bytes_per_s": 1.2e12,
            "vmem_bytes": 16 * 2**20}),
    ("cpu", {"flops_per_s": 1e11, "hbm_bytes_per_s": 4e10,
             "vmem_bytes": 16 * 2**20}),
)

#: unknown device kinds classify against this generic accelerator
#: ceiling rather than failing — the report must degrade, not die
_FALLBACK_PEAKS = {"flops_per_s": 2e14, "hbm_bytes_per_s": 1e12,
                   "vmem_bytes": 16 * 2**20}


# lint: host
def detect_device_kind() -> str:
    """``device_kind`` of the first attached device ("TPU v5e",
    "cpu", ...); never raises."""
    try:
        import jax
        d = jax.devices()[0]
        return str(getattr(d, "device_kind", None) or d.platform)
    except Exception:
        return "unknown"


# lint: host
def device_peaks(kind: Optional[str] = None) -> dict:
    """Peak specs for a device kind from the static table.

    Returns ``{"kind", "flops_per_s", "hbm_bytes_per_s", "vmem_bytes",
    "ridge_flops_per_byte", "source"}`` — ``source`` is
    ``"static_table"`` on a match, ``"generic_fallback"`` otherwise.
    """
    kind = detect_device_kind() if kind is None else str(kind)
    low = kind.lower()
    for sub, spec in PEAKS:
        if sub in low:
            peaks, source = spec, "static_table"
            break
    else:
        peaks, source = _FALLBACK_PEAKS, "generic_fallback"
    return {"kind": kind,
            "flops_per_s": peaks["flops_per_s"],
            "hbm_bytes_per_s": peaks["hbm_bytes_per_s"],
            "vmem_bytes": peaks["vmem_bytes"],
            "ridge_flops_per_byte": (peaks["flops_per_s"]
                                     / peaks["hbm_bytes_per_s"]),
            "source": source}


# lint: host
def normalize_cost(cost) -> dict:
    """Collapse ``cost_analysis()``'s shape variance to one flat
    ``{metric: float}`` dict.

    Backends return a dict, a list of per-computation dicts, ``None``,
    or an empty list (the CPU backend under some versions); anything
    unusable collapses to ``{}`` — callers mark that as
    ``cost_unavailable`` rather than KeyError-ing (the tier-1
    degradation path).
    """
    if cost is None:
        return {}
    if isinstance(cost, dict):
        parts = [cost]
    elif isinstance(cost, (list, tuple)):
        parts = [c for c in cost if isinstance(c, dict)]
    else:
        return {}
    out: dict = {}
    for part in parts:
        for k, v in part.items():
            try:
                out[str(k)] = out.get(str(k), 0.0) + float(v)
            except (TypeError, ValueError):
                continue
    return out


# lint: host
def hlo_fingerprint(text: str) -> str:
    """Stable 16-hex-digit fingerprint of a lowered program's text —
    the comparability key recorded in bench history: two entries with
    the same fingerprint ran the same compiled program."""
    return hashlib.sha256(text.encode("utf-8")).hexdigest()[:16]


# lint: host
def kernel_record(name: str, jitted, *args, **kwargs) -> dict:
    """Lower + compile one jitted callable and extract its roofline
    inputs: ``{name, flops, hbm_bytes, output_bytes, cost_available,
    hlo_fingerprint, error?}``.

    ``cost_available=False`` (with the numbers at ``None``) when the
    backend returns no usable cost model — the explicit
    ``cost_unavailable`` marker the CLI degrades on. Lowering compiles
    but never executes.
    """
    rec = {"name": str(name), "flops": None, "hbm_bytes": None,
           "output_bytes": None, "cost_available": False,
           "hlo_fingerprint": None}
    try:
        lowered = jitted.lower(*args, **kwargs)
        rec["hlo_fingerprint"] = hlo_fingerprint(lowered.as_text())
        compiled = lowered.compile()
    except Exception as e:
        rec["error"] = str(e)
        return rec
    try:
        cost = normalize_cost(compiled.cost_analysis())
    except Exception:
        cost = {}
    if _BYTES_KEY in cost or _FLOPS_KEY in cost:
        rec["flops"] = float(cost.get(_FLOPS_KEY, 0.0))
        rec["hbm_bytes"] = float(cost.get(_BYTES_KEY, 0.0))
        rec["output_bytes"] = float(cost.get(_OUT_BYTES_KEY, 0.0))
        rec["cost_available"] = True
    return rec


# lint: host
def io_contract_record(name: str, input_bytes: float,
                       output_bytes: float,
                       flops: float = 0.0) -> dict:
    """A kernel_record built from a kernel's I/O *contract* instead of
    XLA's cost model.

    For a fused Pallas kernel whose working state is VMEM-resident
    (ops/pallas_round), the HBM bytes a real device moves per launch
    are exactly the kernel's operand + result bytes — XLA's cost model
    cannot see through the ``pallas_call`` custom call (and on non-TPU
    backends attributes the interpreter, not the kernel), so the
    contract IS the honest number. Records carry ``basis:
    "io-contract"`` so reports can label them distinctly from
    ``xla-cost-model`` rows; they are pure arithmetic on static shapes
    and therefore deterministic."""
    return {"name": str(name), "flops": float(flops),
            "hbm_bytes": float(input_bytes) + float(output_bytes),
            "output_bytes": float(output_bytes),
            "cost_available": True, "hlo_fingerprint": None,
            "basis": "io-contract"}


# lint: host
def classify(rec: dict, peaks: dict) -> dict:
    """Fold device peaks into a kernel record: arithmetic intensity,
    attainable ceiling fraction, model step time, and the bound
    verdict.

    - ``arith_intensity`` = flops / HBM bytes (flops per byte).
    - ``bound`` = ``"hbm"`` when AI < ridge point (bandwidth is the
      roof), ``"compute"`` otherwise, ``"cost_unavailable"`` when the
      backend has no cost model.
    - ``ceiling_frac`` = min(1, AI / ridge): the fraction of peak
      compute the roofline permits at this intensity — how far under
      the compute roof the bandwidth roof sits.
    - ``model_time_s`` = max(bytes/bw, flops/peak): the best case for
      one invocation; measured time far above it means dispatch/host
      overhead, not the device (the --timing dispatch check).

    Deterministic: pure arithmetic on the deterministic cost vector.
    """
    out = dict(rec)
    if not rec.get("cost_available"):
        out.update(arith_intensity=None, ceiling_frac=None,
                   model_time_s=None, bound="cost_unavailable")
        return out
    flops = rec["flops"] or 0.0
    hbm = rec["hbm_bytes"] or 0.0
    ridge = peaks["ridge_flops_per_byte"]
    ai = (flops / hbm) if hbm > 0 else float("inf")
    ceiling = min(1.0, ai / ridge) if ridge > 0 else 1.0
    model_t = max(hbm / peaks["hbm_bytes_per_s"],
                  flops / peaks["flops_per_s"])
    out.update(arith_intensity=round(ai, 6),
               ceiling_frac=round(ceiling, 6),
               model_time_s=model_t,
               bound="hbm" if ai < ridge else "compute")
    return out


# lint: host
def cost_vector(per_step: dict, runner: Optional[dict],
                steps: int, retired: int) -> dict:
    """The deterministic cost vector recorded into bench history.

    ``per_step`` is the kernel_record of the engine's one-step kernel
    (cycle / round), ``runner`` the whole quiescence runner (XLA
    counts a while body once, so its cost ≈ one chunk). bytes/instr =
    per-step HBM bytes × steps / retired — exact for a fixed build,
    the number the ``--bytes`` gate compares.
    """
    kernels = {}
    for rec in (per_step, runner):
        if rec is not None:
            kernels[rec["name"]] = {
                "flops": rec["flops"], "hbm_bytes": rec["hbm_bytes"],
                "output_bytes": rec["output_bytes"],
                "cost_available": bool(rec["cost_available"]),
            }
    avail = bool(per_step.get("cost_available")) and retired > 0
    bpi = fpi = None
    if avail:
        bpi = per_step["hbm_bytes"] * steps / retired
        fpi = per_step["flops"] * steps / retired
    return {"per_step_kernel": per_step["name"],
            "steps": int(steps), "retired": int(retired),
            "bytes_per_instr": (round(bpi, 6) if bpi is not None
                                else None),
            "flops_per_instr": (round(fpi, 6) if fpi is not None
                                else None),
            "cost_available": avail,
            "kernels": kernels}


# lint: host
def build_report(engine: str, config: dict, records: list,
                 per_step_name: str, steps: int, retired: int,
                 device_kind: Optional[str] = None,
                 timing: Optional[dict] = None) -> dict:
    """Assemble the ``cache-sim/perfreport/v1`` document.

    ``records`` are kernel_records (the per-step kernel named by
    ``per_step_name`` must be among them); classification, traffic
    totals and the headline bytes/instr are computed here. ``timing``
    (nondeterministic) is attached verbatim only when given — the
    default document is deterministic per build.
    """
    peaks = device_peaks(device_kind)
    kernels = [classify(r, peaks) for r in records]
    for k in kernels:
        k["per_step"] = (k["name"] == per_step_name)
    # HBM traffic ranking: the "which kernel moves the bytes" order
    kernels.sort(key=lambda k: (-(k["hbm_bytes"] or 0.0), k["name"]))
    per_step = next((k for k in kernels if k["name"] == per_step_name),
                    None)
    if per_step is None:
        raise ValueError(f"per-step kernel {per_step_name!r} not in "
                         f"records {[k['name'] for k in kernels]}")
    vec = cost_vector(per_step, None, steps, retired)
    avail = [k for k in kernels if k["cost_available"]]
    top = avail[0] if avail else None
    doc = {
        "schema": SCHEMA_ID,
        "engine": engine,
        "config": dict(config),
        "device": peaks,
        "steps": int(steps),
        "retired": int(retired),
        "cost_available": vec["cost_available"],
        "bytes_per_instr": vec["bytes_per_instr"],
        "flops_per_instr": vec["flops_per_instr"],
        "per_step_kernel": per_step_name,
        "bound": per_step["bound"],
        "top_hbm_kernel": (top["name"] if top else None),
        "kernels": kernels,
    }
    if timing is not None:
        doc["timing"] = timing
    return doc


# lint: host
def timing_section(phases: dict, kernels: list, steps: int,
                   retired: int, rep_times_s: list) -> dict:
    """The opt-in nondeterministic half: ns/instr decomposed by phase
    and (via the roofline model) by kernel.

    ``by_phase`` splits the measured median rep into the PhaseTimer
    buckets (execute dispatch vs device_get sync); ``by_kernel``
    attributes the model's share — per-step model time × steps — so a
    measured/model ratio far above 1 reads as dispatch-bound: the
    device is idle waiting on the host, and no amount of kernel diet
    fixes that (PERF.md's ~0.1 s fixed dispatch tax).
    """
    med = sorted(rep_times_s)[len(rep_times_s) // 2] if rep_times_s \
        else None
    out = {"rep_times_s": [round(t, 6) for t in rep_times_s],
           "ns_per_instr": None, "by_phase": {}, "by_kernel": {},
           "dispatch_bound": None}
    if med is None or retired <= 0:
        return out
    out["ns_per_instr"] = round(med / retired * 1e9, 3)
    ph = (phases or {}).get("phases", {})
    reps = max(1, len(rep_times_s))
    for name in ("execute_dispatch", "device_get_sync"):
        if name in ph:
            out["by_phase"][name] = round(
                ph[name]["seconds"] / reps / retired * 1e9, 3)
    model_total = 0.0
    for k in kernels:
        if k.get("model_time_s") is not None:
            t = k["model_time_s"] * (steps if k.get("per_step") else 1)
            out["by_kernel"][k["name"]] = round(t / retired * 1e9, 3)
            model_total = max(model_total, t)
    if model_total > 0:
        # the dispatch check: measured time >> roofline best case
        # means the host/dispatch path, not the device, is the bound
        out["measured_over_model"] = round(med / model_total, 2)
        out["dispatch_bound"] = bool(med > 10.0 * model_total)
    return out


# lint: host
def transport_section(cfg, n_shards: int,
                      lane_cap: Optional[int] = None) -> dict:
    """Per-transport bytes-on-wire row for the async engine's sharded
    delivery (parallel.rdma_comm.wire_bytes — pure shape arithmetic,
    deterministic per config). NOT a kernel record: the transports move
    interconnect bytes, not HBM bytes, so the row lives beside the
    roofline table instead of inside it (and must never carry an
    io-contract basis — cmd_perfreport's fused lookup keys on that).
    """
    from ue22cs343bb1_openmp_assignment_tpu.parallel import rdma_comm
    per = {t: rdma_comm.wire_bytes(cfg, n_shards, lane_cap, transport=t)
           for t in ("all_to_all", "rdma")}
    L = cfg.num_nodes // n_shards
    return {
        "basis": "wire-shape",
        "n_shards": int(n_shards),
        "lane_cap": int(lane_cap if lane_cap is not None
                        else L * cfg.out_slots),
        "bytes_per_round": per,
        "rdma_strictly_fewer": bool(per["rdma"] < per["all_to_all"]),
        "savings_frac": round(1.0 - per["rdma"] / per["all_to_all"], 4),
    }


_BOUND_TEXT = {"hbm": "HBM-bound", "compute": "compute-bound",
               "cost_unavailable": "cost unavailable"}


# lint: host
def render_text(doc: dict) -> str:
    """The one-screen answer to "where does the 5x go"."""
    dev = doc["device"]
    lines = [
        f"perf-report: {doc['engine']} engine, "
        f"{doc['config'].get('nodes', '?')} nodes "
        f"({dev['kind']}, peaks {dev['flops_per_s']:.3g} flop/s / "
        f"{dev['hbm_bytes_per_s']:.3g} B/s, "
        f"ridge {dev['ridge_flops_per_byte']:.2f} flop/B, "
        f"{dev['source']})",
        f"  steps={doc['steps']} retired={doc['retired']} "
        f"per-step kernel={doc['per_step_kernel']}",
    ]
    if doc["cost_available"]:
        lines.append(
            f"  bytes/instr = {doc['bytes_per_instr']:.2f}   "
            f"flops/instr = {doc['flops_per_instr']:.2f}   "
            f"bound: {_BOUND_TEXT[doc['bound']]}")
        lines.append(
            f"  top HBM-traffic kernel: {doc['top_hbm_kernel']}")
    else:
        lines.append("  cost model unavailable on this backend "
                     "(cost_unavailable); traffic attribution "
                     "degrades to kernel names only")
    lines.append("")
    lines.append(f"  {'kernel':<28} {'flops':>12} {'HBM bytes':>12} "
                 f"{'AI f/B':>8} {'ceil%':>6}  bound")
    for k in doc["kernels"]:
        if k["cost_available"]:
            lines.append(
                f"  {k['name']:<28} {k['flops']:>12.0f} "
                f"{k['hbm_bytes']:>12.0f} "
                f"{k['arith_intensity']:>8.3f} "
                f"{100 * k['ceiling_frac']:>5.1f}%  "
                f"{_BOUND_TEXT[k['bound']]}")
        else:
            why = k.get("error", "cost_unavailable")
            lines.append(f"  {k['name']:<28} -- {why}")
    f = doc.get("fused")
    if f:
        ratio = (f["unfused_bytes_per_instr"] / f["bytes_per_instr"]
                 if f["bytes_per_instr"] else float("inf"))
        lines.append("")
        lines.append(
            f"  fused round ({f['basis']}): bytes/instr = "
            f"{f['bytes_per_instr']:.2f} vs xla-cost-model "
            f"{f['unfused_bytes_per_instr']:.2f} "
            f"({ratio:,.0f}x less HBM traffic)")
    vm = doc.get("vmem")
    if vm:
        lines.append("")
        for r in vm:
            verdict = "fits" if r["ok"] else "OVER BUDGET"
            lines.append(
                f"  vmem[{r['kernel']}] ({r['basis']}): resident "
                f"{(r['resident_in_bytes'] + r['resident_out_bytes']) / 2**20:.2f}"
                f" MiB + headroom {r['headroom_bytes'] / 2**20:.2f} MiB"
                f" = required {r['required_bytes'] / 2**20:.2f} MiB vs "
                f"{r['vmem_bytes'] / 2**20:.0f} MiB VMEM "
                f"({r['device_kind']}): {verdict}")
    ix = doc.get("index")
    if ix:
        planes = ", ".join(f"{p}={v}"
                           for p, v in sorted(ix["by_plane"].items()))
        lines.append("")
        lines.append(
            f"  index pressure ({ix['target']}, static jaxpr audit): "
            f"{ix['index_sites']} sites, "
            f"{ix['indices_per_step']} indices/step"
            + (f", {ix['indices_per_instr']} indices/instr"
               if "indices_per_instr" in ix else ""))
        lines.append(f"    by plane: {planes}")
        if ix.get("merge_candidates"):
            lines.append(
                f"    ~ {ix['merge_candidates']} mergeable-scatter "
                "candidate(s) — run `cache-sim analyze --index` for "
                "the worklist")
    tr = doc.get("transport")
    if tr:
        per = tr["bytes_per_round"]
        verdict = ("rdma moves strictly fewer bytes"
                   if tr["rdma_strictly_fewer"] else
                   "WARNING: rdma does NOT move fewer bytes")
        lines.append("")
        lines.append(
            f"  transport ({tr['basis']}, {tr['n_shards']} shards, "
            f"lane cap {tr['lane_cap']}): bytes on wire per round — "
            f"all_to_all {per['all_to_all']:,} vs rdma {per['rdma']:,} "
            f"({100 * tr['savings_frac']:.1f}% less; {verdict})")
    t = doc.get("timing")
    if t:
        lines.append("")
        lines.append(f"  timing (nondeterministic): ns/instr = "
                     f"{t['ns_per_instr']}")
        for name, v in t["by_phase"].items():
            lines.append(f"    {name:<22} {v:>10} ns/instr")
        for name, v in t["by_kernel"].items():
            lines.append(f"    model:{name:<16} {v:>10} ns/instr")
        if t.get("dispatch_bound") is not None:
            lines.append(
                f"    measured/model = {t.get('measured_over_model')}"
                + ("  => DISPATCH-BOUND (host overhead dominates; "
                   "kernel diet won't move the headline)"
                   if t["dispatch_bound"] else
                   "  (device-bound regime)"))
    return "\n".join(lines) + "\n"
