"""Multi-window error-budget burn-rate alerting (the SRE pattern).

The existing ``--slo p95=<ms>`` gate is an end-of-run verdict: one
percentile over the whole run, checked once. This module is the
CONTINUOUS complement: every finished job is one streaming sample
(good = e2e latency within the SLO threshold, bad = over it), and the
monitor tracks the **error-budget burn rate** over two sliding
windows at once:

    budget    = 1 - objective          (the allowed bad fraction)
    burn(W)   = bad_rate_in_window_W / budget

A burn rate of 1.0 spends the budget exactly at the sustainable pace;
an alert fires when BOTH windows burn at ``factor``x or more — the
fast window (seconds) makes the alert prompt, the slow window
(minutes) keeps a short blip from paging. The pairing is the
multi-window multi-burn-rate rule from the Google SRE workbook: fast
alone is noisy, slow alone is late, together they are neither.

Alerts are edge-triggered with hysteresis: one alert per excursion
into breach (re-armed only after both windows drop back under
``factor``), so a sustained breach emits one ``slo-alert`` event, not
one per job. ``DaemonCore`` feeds the monitor from ``_extract`` and
injects each alert into the events stream (obs.events); ``soak`` and
``replay`` feed it client/driver-side and turn ``breached()`` into
the process exit code — the continuous verdict the end-of-run
``--slo`` check cannot give.

Deterministic by construction: the monitor never reads a clock — the
caller stamps every sample with its own (injected) time base, so a
VirtualClock session alerts byte-identically across runs.

Host-side and dependency-free (the daemon server and the future
fleet router import this; it must never reach jax).
"""
# lint: host

from __future__ import annotations

from typing import Dict, List, Optional, Tuple

#: default SLO objective: 99% of jobs within the latency threshold
DEFAULT_OBJECTIVE = 0.99

#: default fast/slow window lengths (seconds) and alert factor
DEFAULT_FAST_S = 60.0
DEFAULT_SLOW_S = 300.0
DEFAULT_FACTOR = 2.0


class BurnRateMonitor:
    """Streaming fast+slow-window burn-rate tracker for one SLO.

    ``feed(t_s, latency_s)`` records one finished job and returns the
    alert dict when this sample tips both windows over ``factor`` —
    None otherwise. The caller owns the time base (``t_s`` must be
    non-decreasing); samples older than ``slow_s`` are pruned.
    """

    # lint: host
    def __init__(self, threshold_ms: float,
                 objective: float = DEFAULT_OBJECTIVE,
                 fast_s: float = DEFAULT_FAST_S,
                 slow_s: float = DEFAULT_SLOW_S,
                 factor: float = DEFAULT_FACTOR):
        if threshold_ms <= 0:
            raise ValueError(f"threshold_ms must be > 0, "
                             f"got {threshold_ms}")
        if not 0.0 < objective < 1.0:
            raise ValueError(f"objective must be in (0, 1), "
                             f"got {objective}")
        if fast_s <= 0 or slow_s <= 0:
            raise ValueError(f"window lengths must be > 0, "
                             f"got fast={fast_s} slow={slow_s}")
        if fast_s > slow_s:
            raise ValueError(f"fast window ({fast_s}s) must not exceed "
                             f"the slow window ({slow_s}s)")
        if factor <= 0:
            raise ValueError(f"factor must be > 0, got {factor}")
        self.threshold_ms = float(threshold_ms)
        self.objective = float(objective)
        self.budget = 1.0 - float(objective)
        self.fast_s = float(fast_s)
        self.slow_s = float(slow_s)
        self.factor = float(factor)
        self.alerts: List[dict] = []
        self.samples = 0
        self.bad = 0
        self._window: List[Tuple[float, bool]] = []  # (t_s, bad)
        self._alerting = False                       # hysteresis latch

    # lint: host
    def _burn(self, now: float, window_s: float) -> Tuple[float, int, int]:
        """(burn rate, bad, total) over ``[now - window_s, now]``."""
        lo = now - window_s
        total = 0
        bad = 0
        for t, b in self._window:
            if t >= lo:
                total += 1
                bad += int(b)
        if total == 0:
            return 0.0, 0, 0
        return (bad / total) / self.budget, bad, total

    # lint: host
    def feed(self, t_s: float, latency_s: float) -> Optional[dict]:
        """One finished job at time ``t_s`` with end-to-end latency
        ``latency_s``; returns the alert dict iff this sample starts a
        breach excursion (both windows >= factor, previously armed)."""
        bad = float(latency_s) * 1e3 > self.threshold_ms
        self.samples += 1
        self.bad += int(bad)
        self._window.append((float(t_s), bad))
        lo = float(t_s) - self.slow_s
        while self._window and self._window[0][0] < lo:
            self._window.pop(0)
        fast_burn, fast_bad, fast_n = self._burn(t_s, self.fast_s)
        slow_burn, slow_bad, slow_n = self._burn(t_s, self.slow_s)
        breaching = (fast_burn >= self.factor
                     and slow_burn >= self.factor)
        if not breaching:
            self._alerting = False
            return None
        if self._alerting:
            return None                    # one alert per excursion
        self._alerting = True
        alert = {
            "t_s": float(t_s),
            "threshold_ms": self.threshold_ms,
            "objective": self.objective,
            "factor": self.factor,
            "fast_s": self.fast_s,
            "slow_s": self.slow_s,
            "fast_burn": fast_burn,
            "slow_burn": slow_burn,
            "fast_bad": fast_bad,
            "fast_samples": fast_n,
            "slow_bad": slow_bad,
            "slow_samples": slow_n,
        }
        self.alerts.append(alert)
        return alert

    # lint: host
    def breached(self) -> bool:
        return bool(self.alerts)

    # lint: host
    def summary(self) -> dict:
        """The continuous-verdict block a soak/replay doc embeds."""
        now = self._window[-1][0] if self._window else 0.0
        fast_burn, _, fast_n = self._burn(now, self.fast_s)
        slow_burn, _, slow_n = self._burn(now, self.slow_s)
        return {
            "threshold_ms": self.threshold_ms,
            "objective": self.objective,
            "factor": self.factor,
            "fast_s": self.fast_s,
            "slow_s": self.slow_s,
            "samples": self.samples,
            "bad": self.bad,
            "alerts": len(self.alerts),
            "alerting": self._alerting,
            "fast_burn": fast_burn,
            "slow_burn": slow_burn,
            "fast_samples": fast_n,
            "slow_samples": slow_n,
            "last_alert": self.alerts[-1] if self.alerts else None,
        }


# lint: host
def parse_burn_spec(spec: str) -> Dict[str, float]:
    """CLI spec → BurnRateMonitor kwargs. The one required term is the
    latency threshold; everything else defaults::

        "5ms"                                  -> threshold only
        "5ms,objective=0.999,fast=30,slow=120,factor=4"

    Terms: ``objective`` (fraction in (0,1)), ``fast``/``slow``
    (window seconds), ``factor`` (burn multiple)."""
    kw: Dict[str, float] = {}
    names = {"objective": "objective", "fast": "fast_s",
             "slow": "slow_s", "factor": "factor"}
    for part in spec.split(","):
        part = part.strip()
        if not part:
            continue
        if "=" not in part:
            ms = part[:-2] if part.endswith("ms") else part
            try:
                kw["threshold_ms"] = float(ms)
            except ValueError:
                raise ValueError(f"bad burn-SLO threshold {part!r} "
                                 f"(want e.g. 5ms)")
            continue
        k, v = part.split("=", 1)
        k = k.strip()
        if k not in names:
            raise ValueError(f"unknown burn-SLO term {k!r} "
                             f"(one of {sorted(names)})")
        try:
            kw[names[k]] = float(v)
        except ValueError:
            raise ValueError(f"bad burn-SLO value {v!r} for {k}")
    if "threshold_ms" not in kw:
        raise ValueError(f"burn-SLO spec {spec!r} has no latency "
                         f"threshold (want e.g. \"5ms,factor=2\")")
    return kw


# lint: host
def monitor_from_spec(spec: str) -> BurnRateMonitor:
    return BurnRateMonitor(**parse_burn_spec(spec))
