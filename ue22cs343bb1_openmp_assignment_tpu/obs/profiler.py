"""Kernel-level profiling: trace capture, cost attribution, and the
timer self-check.

Three instruments, all host-side:

- :func:`capture` wraps ``jax.profiler`` trace capture around any
  engine run (``run_cycles``, deep-engine steps) with the same
  degrade-gracefully guard bench.py uses — some device plugins can't
  profile, and a benchmark must never die because its profiler did.
- :func:`kernel_cost_report` asks XLA what the compiled program
  actually costs (flops / bytes accessed / transcendentals via
  ``compiled.cost_analysis()``) and — through
  ``PhaseTimer.attach("kernels", ...)`` — folds that attribution into
  the same report as the wall-clock phases, so a phase split and its
  kernel-level explanation travel together.
- :func:`timer_self_check` re-asserts PERF.md's measurement lesson as
  an executable check: over a tunneled device plugin,
  ``jax.block_until_ready`` can return before the computation
  finishes, silently turning "run time" into "dispatch time". The
  check times the block barrier and then the scalar ``device_get``
  tail behind it; a fat tail means the block barrier lied and only
  the device_get numbers in this environment are trustworthy.
"""
# lint: host

from __future__ import annotations

import sys
import time
from contextlib import contextmanager
from typing import Iterator, Optional

import numpy as np

#: a device_get tail longer than this (seconds) AND dominating the
#: block barrier marks the barrier untrustworthy — generous against
#: scheduler jitter, tiny against the ~90-130 ms tunnel sync tax
_TAIL_BUDGET_S = 0.025


# lint: host
@contextmanager
def capture(out_dir: Optional[str],
            quiet: bool = False) -> Iterator[dict]:
    """Guarded ``jax.profiler.trace`` capture into ``out_dir``.

    Yields a status dict (``enabled``, and ``error`` when capture
    failed or was disabled). ``out_dir=None`` is a no-op pass-through
    so call sites don't need their own conditional.
    """
    status = {"enabled": False, "out_dir": out_dir, "error": None}
    if not out_dir:
        yield status
        return
    import jax
    try:
        ctx = jax.profiler.trace(out_dir)
        ctx.__enter__()
    except Exception as e:  # some device plugins can't profile
        status["error"] = str(e)
        if not quiet:
            print(f"warning: profiler capture failed: {e}",
                  file=sys.stderr)
        yield status
        return
    status["enabled"] = True
    try:
        yield status
    finally:
        try:
            ctx.__exit__(None, None, None)
            if not quiet:
                print(f"profiler trace written to {out_dir}",
                      file=sys.stderr)
        except Exception as e:
            status["enabled"] = False
            status["error"] = str(e)
            if not quiet:
                print(f"warning: profiler finalize failed: {e}",
                      file=sys.stderr)


# lint: host
def _normalize_cost(cost) -> dict:
    """cost_analysis() shapes vary by backend/version: a dict, a list
    of dicts (one per computation), or None/empty (the CPU backend
    under JAX_PLATFORMS=cpu on some versions). Collapse to one flat
    {metric: float} dict, summing across computations; unusable input
    collapses to {} and callers mark it ``cost_unavailable`` instead
    of KeyError-ing on a missing metric (obs.roofline owns the one
    definition)."""
    from ue22cs343bb1_openmp_assignment_tpu.obs import roofline
    return roofline.normalize_cost(cost)


# lint: host
def kernel_cost_report(jitted, *args, **kwargs) -> dict:
    """Compiled-cost attribution for one jitted callable at the given
    (abstract) arguments.

    Returns ``{"available": bool, "cost": {...}, "memory": {...}}`` —
    ``cost`` holds XLA's flops / bytes-accessed / transcendentals
    estimate, ``memory`` the compiled memory analysis when the backend
    exposes it. ``available=False`` (never an exception) when the
    backend supports neither: cost attribution is an instrument, not a
    dependency.
    """
    rep = {"available": False, "cost": {}, "memory": {},
           "cost_unavailable": True}
    try:
        compiled = jitted.lower(*args, **kwargs).compile()
    except Exception as e:
        rep["error"] = str(e)
        return rep
    try:
        rep["cost"] = _normalize_cost(compiled.cost_analysis())
    except Exception:
        pass
    try:
        mem = compiled.memory_analysis()
        for k in ("temp_size_in_bytes", "argument_size_in_bytes",
                  "output_size_in_bytes", "generated_code_size_in_bytes"):
            v = getattr(mem, k, None)
            if v is not None:
                rep["memory"][k] = int(v)
    except Exception:
        pass
    rep["available"] = bool(rep["cost"] or rep["memory"])
    # the explicit marker the roofline surfaces degrade on: an empty
    # normalized cost dict means "this backend has no cost model", a
    # state distinct from "zero bytes" (obs.roofline, ISSUE 7)
    rep["cost_unavailable"] = not rep["cost"]
    return rep


# lint: host
def attach_kernel_costs(timer, jitted, *args, **kwargs) -> dict:
    """kernel_cost_report folded into a PhaseTimer report (under the
    "kernels" key)."""
    rep = kernel_cost_report(jitted, *args, **kwargs)
    timer.attach("kernels", rep)
    return rep


# lint: host
def _scalar_sync(out) -> float:
    """The real barrier: materialize one scalar on the host. Unlike
    block_until_ready this cannot return before the bytes exist."""
    import jax
    leaves = [x for x in jax.tree_util.tree_leaves(out)
              if hasattr(x, "shape")]
    if not leaves:
        return 0.0
    return float(np.asarray(leaves[0]).ravel()[0])


# lint: host
def timer_self_check(fn, *args, reps: int = 3) -> dict:
    """Is ``jax.block_until_ready`` a real barrier on this link?

    Runs ``fn(*args)`` ``reps`` times (after one warmup), timing per
    run: dispatch (call returns), block (``block_until_ready``
    returns), then the device_get tail (first scalar materialized on
    host). If the block barrier is honest the tail is bounded by host
    copy cost; if it lies (PERF.md: tunneled device plugins), the
    computation finishes inside the tail and the tail dominates.

    Returns medians plus ``barrier_trustworthy`` — when False, only
    device_get-synced timings from this environment should be
    believed.
    """
    import jax
    _scalar_sync(fn(*args))  # warmup: compile outside the measurement
    dispatch, block, tail = [], [], []
    for _ in range(max(1, reps)):
        t0 = time.perf_counter()
        out = fn(*args)
        t1 = time.perf_counter()
        jax.block_until_ready(out)
        t2 = time.perf_counter()
        _scalar_sync(out)
        t3 = time.perf_counter()
        dispatch.append(t1 - t0)
        block.append(t2 - t1)
        tail.append(t3 - t2)
    med = (lambda xs: sorted(xs)[len(xs) // 2])
    d_med, b_med, t_med = med(dispatch), med(block), med(tail)
    trustworthy = t_med <= max(_TAIL_BUDGET_S, 0.25 * b_med)
    return {
        "reps": max(1, reps),
        "dispatch_s": round(d_med, 6),
        "block_until_ready_s": round(b_med, 6),
        "device_get_tail_s": round(t_med, 6),
        "barrier_trustworthy": trustworthy,
        "verdict": ("block_until_ready is a real barrier here"
                    if trustworthy else
                    "block_until_ready LIES on this link: the run "
                    "completes inside the device_get tail; trust only "
                    "device_get-synced timings (PERF.md)"),
    }
