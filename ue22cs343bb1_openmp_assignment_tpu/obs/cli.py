"""``cache-sim stats`` / ``cache-sim trace`` — the obs CLI surfaces.

``stats`` runs a workload or fixture on any engine and prints the
unified ``cache-sim/metrics/v1`` report (obs.schema) to stdout —
deterministic JSON (sorted keys) so goldens diff cleanly. With
``--timeseries`` the async engine re-runs under the on-device
telemetry capture and a host summary rides in ``extra``; the full
per-cycle series can be written aside with ``--timeseries-out``.

``trace --perfetto OUT`` exports the run's event record as
Chrome/Perfetto trace-event JSON (obs.perfetto): per-node ``instr``
and ``msg`` tracks from the async engine, retirement tracks from the
sync/deep engine. Open the file in ui.perfetto.dev.

``txns`` replays a run under the message ledger (ops.step
with_ledger) and prints the causal transaction spans (obs.txntrace):
the per-type latency decomposition table and the top-N slowest
transactions; ``--perfetto OUT`` additionally writes the event trace
with flow arrows linking each transaction's request/reply slices.
``critical-path`` runs the happens-before analysis (obs.critpath) and
prints the critical path to quiescence with per-node / per-phase cycle
attribution. Both are async-engine surfaces (the ledger is a
message-plane capture) and deterministic for a fixed config.

``profile`` replays a run under the coherence profiler (obs.cohprof):
per-line miss taxonomy, invalidation fan-out, sharing-pattern
classification and the top contended lines, emitted as a validated
``cache-sim/profile/v1`` doc (or the one-screen text rendering). All
three engines; the deep engine additionally reports the measured abort
anatomy incl. the ghost-poison fraction.
"""
# lint: host

from __future__ import annotations

import argparse
import json
import os
import sys

WORKLOADS = ["uniform", "producer_consumer", "false_sharing",
             "false_sharing_vars", "false_sharing_vars_padded", "fft",
             "radix", "hotspot", "zipf_hotspot", "lu"]


# lint: host
def _add_common(p: argparse.ArgumentParser) -> None:
    p.add_argument("test_dir", nargs="?", default=None,
                   help="test directory name (fixture traces)")
    p.add_argument("--tests-root", default="tests")
    p.add_argument("--workload", choices=WORKLOADS,
                   help="synthetic workload instead of trace files")
    p.add_argument("--nodes", type=int, default=4)
    p.add_argument("--trace-len", type=int, default=32)
    p.add_argument("--seed", type=int, default=0,
                   help="workload PRNG seed")
    p.add_argument("--max-cycles", type=int, default=100_000)
    p.add_argument("--run-cycles", type=int, default=None,
                   help="run exactly this many cycles/rounds instead "
                        "of running to quiescence")
    p.add_argument("--cpu", action="store_true",
                   help="force the CPU backend")


# lint: host
def build_stats_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        prog="cache-sim stats",
        description="run a workload and emit the unified metrics "
                    "report (cache-sim/metrics/v1) to stdout")
    _add_common(p)
    p.add_argument("--engine", choices=["async", "sync", "native"],
                   default="async")
    p.add_argument("--timeseries", action="store_true",
                   help="async engine: capture the on-device per-cycle "
                        "telemetry and attach a summary under extra")
    p.add_argument("--txns", action="store_true",
                   help="async engine: replay under the message ledger "
                        "and attach the transaction-span latency "
                        "summary as the v1.1 txn_latency block")
    p.add_argument("--timeseries-out", metavar="PATH",
                   help="also write the full per-cycle series JSON "
                        "(implies --timeseries)")
    p.add_argument("--phases", action="store_true",
                   help="attach wall-clock phase timings under extra "
                        "(off by default: timings are nondeterministic "
                        "and would break golden diffs)")
    p.add_argument("--out", metavar="PATH",
                   help="write the report here instead of stdout")
    return p


# lint: host
def build_trace_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        prog="cache-sim trace",
        description="run a workload and export its event record as "
                    "Perfetto/Chrome trace-event JSON")
    _add_common(p)
    p.add_argument("--perfetto", metavar="PATH", required=True,
                   help="output path for the trace-event JSON")
    p.add_argument("--engine", choices=["async", "deep"],
                   default="async",
                   help="async = instr+msg dequeue tracks; deep = "
                        "transactional-engine retirement tracks")
    p.add_argument("--no-msgs", action="store_true",
                   help="async engine: omit the msg tracks")
    return p


# lint: host
def _async_system(args):
    from ue22cs343bb1_openmp_assignment_tpu.config import SystemConfig
    from ue22cs343bb1_openmp_assignment_tpu.models.system import (
        CoherenceSystem)
    if args.workload:
        cfg = SystemConfig.scale(num_nodes=args.nodes)
        return CoherenceSystem.from_workload(
            cfg, args.workload, trace_len=args.trace_len, seed=args.seed)
    if args.test_dir:
        cfg = SystemConfig.reference(num_nodes=args.nodes)
        path = os.path.join(args.tests_root, args.test_dir)
        return CoherenceSystem.from_test_dir(path, cfg)
    return None


# lint: host
def _emit(args, doc: dict) -> None:
    text = json.dumps(doc, indent=2, sort_keys=True) + "\n"
    if args.out:
        with open(args.out, "w") as f:
            f.write(text)
    else:
        sys.stdout.write(text)


# lint: host
def cmd_stats(args) -> int:
    from ue22cs343bb1_openmp_assignment_tpu.obs import schema
    from ue22cs343bb1_openmp_assignment_tpu.obs.phases import PhaseTimer
    timer = PhaseTimer()
    want_ts = args.timeseries or args.timeseries_out

    if args.engine == "native":
        if want_ts or args.txns:
            print("error: --timeseries/--txns are on-device capture; "
                  "use --engine async", file=sys.stderr)
            return 2
        doc = _stats_native(args, timer)
    elif args.engine == "sync":
        if want_ts or args.txns:
            print("error: --timeseries/--txns need the message-level "
                  "engine; use --engine async", file=sys.stderr)
            return 2
        doc = _stats_sync(args, timer)
    else:
        doc = _stats_async(args, timer, want_ts)
    if doc is None:
        print("error: provide <test_directory> or --workload",
              file=sys.stderr)
        return 2
    if args.phases:
        doc["extra"]["phases"] = timer.report()
    _emit(args, schema.validate(doc))
    return 0


# lint: host
def _stats_async(args, timer, want_ts: bool):
    from ue22cs343bb1_openmp_assignment_tpu.obs import schema, timeseries
    from ue22cs343bb1_openmp_assignment_tpu.ops import step
    with timer.phase("build"):
        system0 = _async_system(args)
    if system0 is None:
        return None
    with timer.phase("run"):
        if args.run_cycles is not None:
            system = system0.run_cycles(args.run_cycles)
        else:
            system = system0.run(args.max_cycles)
    with timer.phase("device_get"):
        m = system.metrics
    doc = schema.from_async(m)
    if want_ts:
        # telemetry replays the run from the initial state for exactly
        # the cycle count the plain run took — same trajectory (the
        # engine is deterministic), now with the per-cycle capture
        with timer.phase("telemetry_run"):
            _, telem = step.run_cycles_telemetry(
                system0.cfg, system0.state, int(m["cycles"]))
        with timer.phase("device_get"):
            doc["extra"]["telemetry"] = timeseries.summarize(telem)
        if args.timeseries_out:
            with open(args.timeseries_out, "w") as f:
                json.dump(timeseries.to_series(telem), f)
                f.write("\n")
    if args.txns:
        import numpy as np

        from ue22cs343bb1_openmp_assignment_tpu.obs import txntrace
        # same replay discipline as --timeseries, ledger on
        with timer.phase("ledger_run"):
            _, ledger, base = txntrace.capture(
                system0.cfg, system0.state, int(m["cycles"]),
                stop_on_quiescence=False)
        spans, _ = txntrace.reconstruct(
            system0.cfg, ledger, base,
            arb_rank=np.asarray(system0.state.arb_rank))
        doc["txn_latency"] = txntrace.summarize(spans)
    return doc


# lint: host
def _stats_sync(args, timer):
    from ue22cs343bb1_openmp_assignment_tpu.models.transactional import (
        TransactionalSystem)
    from ue22cs343bb1_openmp_assignment_tpu.obs import schema
    with timer.phase("build"):
        if args.workload:
            from ue22cs343bb1_openmp_assignment_tpu.config import (
                SystemConfig)
            cfg = SystemConfig.scale(num_nodes=args.nodes)
            ts = TransactionalSystem.from_workload(
                cfg, args.workload, trace_len=args.trace_len,
                workload_seed=args.seed)
        elif args.test_dir:
            path = os.path.join(args.tests_root, args.test_dir)
            ts = TransactionalSystem.from_test_dir(path)
        else:
            return None
    with timer.phase("run"):
        if args.run_cycles is not None:
            ts = ts.run_rounds(args.run_cycles)
        else:
            ts = ts.run(max_rounds=args.max_cycles)
    with timer.phase("device_get"):
        m = ts.metrics
    return schema.from_sync(m)


# lint: host
def _stats_native(args, timer):
    import numpy as np

    from ue22cs343bb1_openmp_assignment_tpu.config import SystemConfig
    from ue22cs343bb1_openmp_assignment_tpu.native.bindings import (
        NativeEngine)
    from ue22cs343bb1_openmp_assignment_tpu.obs import schema
    with timer.phase("build"):
        if args.workload:
            import jax
            from ue22cs343bb1_openmp_assignment_tpu.models import (
                workloads)
            cfg = SystemConfig.scale(num_nodes=args.nodes,
                                     max_instrs=args.trace_len)
            arrs = workloads.GENERATORS[args.workload](
                jax.random.PRNGKey(args.seed), cfg, args.trace_len)
            eng = NativeEngine(cfg)
            eng.load_instr_arrays(*(np.asarray(a) for a in arrs))
        elif args.test_dir:
            from ue22cs343bb1_openmp_assignment_tpu.utils.trace import (
                load_test_dir)
            cfg = SystemConfig.reference(num_nodes=args.nodes)
            path = os.path.join(args.tests_root, args.test_dir)
            eng = NativeEngine(cfg)
            eng.load_traces(load_test_dir(path, cfg.num_nodes,
                                          cfg.max_instrs))
        else:
            return None
    with timer.phase("run"):
        eng.run(args.run_cycles if args.run_cycles is not None
                else args.max_cycles)
    return schema.from_native(eng.metrics())


# lint: host
def cmd_trace(args) -> int:
    from ue22cs343bb1_openmp_assignment_tpu.obs import perfetto
    from ue22cs343bb1_openmp_assignment_tpu.utils import eventlog

    if args.engine == "deep":
        from ue22cs343bb1_openmp_assignment_tpu.models.transactional \
            import TransactionalSystem
        from ue22cs343bb1_openmp_assignment_tpu.ops import sync_engine as se
        if args.workload:
            from ue22cs343bb1_openmp_assignment_tpu.config import (
                SystemConfig)
            cfg = SystemConfig.scale(num_nodes=args.nodes)
            ts = TransactionalSystem.from_workload(
                cfg, args.workload, trace_len=args.trace_len,
                workload_seed=args.seed)
        elif args.test_dir:
            path = os.path.join(args.tests_root, args.test_dir)
            ts = TransactionalSystem.from_test_dir(path)
        else:
            print("error: provide <test_directory> or --workload",
                  file=sys.stderr)
            return 2
        if args.run_cycles is not None:
            rounds = args.run_cycles
        else:
            # find the round count first, then replay traced
            done = ts.run(max_rounds=args.max_cycles)
            rounds = int(done.metrics["rounds"])
        _, events = se.run_rounds_traced(ts.cfg, ts.state, rounds)
        records = eventlog.sync_to_records(events)
        num_nodes = ts.cfg.num_nodes
    else:
        system = _async_system(args)
        if system is None:
            print("error: provide <test_directory> or --workload",
                  file=sys.stderr)
            return 2
        base = int(system.state.cycle)
        if args.run_cycles is not None:
            system, events = system.run_cycles_traced(args.run_cycles)
        else:
            system, events = system.run_traced(args.max_cycles)
        records = (eventlog.to_records(events, base) if events else [])
        if args.no_msgs:
            records = [r for r in records if r["kind"] == "instr"]
        num_nodes = system.cfg.num_nodes

    doc = perfetto.build_trace(records, num_nodes)
    perfetto.validate_trace(doc)
    perfetto.write_trace(args.perfetto, doc)
    print(f"wrote {args.perfetto}: {len(records)} events across "
          f"{num_nodes} nodes (open in ui.perfetto.dev)",
          file=sys.stderr)
    return 0


# lint: host
def build_txns_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        prog="cache-sim txns",
        description="replay a run under the message ledger and print "
                    "the causal transaction spans: per-type latency "
                    "decomposition (queue_wait/dir_service/in_flight/"
                    "ack_wait) and the slowest transactions")
    _add_common(p)
    p.add_argument("--top", type=int, default=10,
                   help="how many slowest transactions to show "
                        "(default 10)")
    p.add_argument("--json", action="store_true",
                   help="emit the full cache-sim/txnspans/v1 document "
                        "instead of the text tables")
    p.add_argument("--perfetto", metavar="PATH",
                   help="also write the event trace with flow arrows "
                        "linking each transaction's request/reply "
                        "slices")
    p.add_argument("--out", metavar="PATH",
                   help="write the report here instead of stdout")
    return p


# lint: host
def build_critpath_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        prog="cache-sim critical-path",
        description="compute the critical path to quiescence over the "
                    "run's happens-before DAG and attribute every "
                    "cycle on it to a (node, phase) pair")
    _add_common(p)
    p.add_argument("--json", action="store_true",
                   help="emit the full cache-sim/critpath/v1 report")
    p.add_argument("--out", metavar="PATH",
                   help="write the report here instead of stdout")
    return p


# lint: host
def _capture_spans(args):
    """Build the async system, find the run length, and replay exactly
    that many cycles under the message ledger. Returns
    (spans, trace, total_cycles, ledger, cfg) or None when no input
    was given.

    Two-pass on purpose: the plain run (ledger off) finds the cycles-
    to-quiescence T cheaply, then the ledger replay runs exactly T
    cycles with stop_on_quiescence=False — so the captured window is
    independent of the capture chunk size and the output is
    deterministic for a fixed config.
    """
    import numpy as np

    from ue22cs343bb1_openmp_assignment_tpu.obs import txntrace
    system0 = _async_system(args)
    if system0 is None:
        return None
    if args.run_cycles is not None:
        total = int(args.run_cycles)
    else:
        done = system0.run(args.max_cycles)
        total = int(done.metrics["cycles"])
    _, ledger, base = txntrace.capture(
        system0.cfg, system0.state, total, stop_on_quiescence=False)
    spans, trace = txntrace.reconstruct(
        system0.cfg, ledger, base,
        arb_rank=np.asarray(system0.state.arb_rank))
    return spans, trace, total, ledger, system0.cfg


# lint: host
def _render_txns(spans, total: int, top: int) -> str:
    from ue22cs343bb1_openmp_assignment_tpu.obs import txntrace
    lines = [f"transaction spans: {len(spans)} total over {total} "
             f"cycles"]
    table = txntrace.latency_table(spans)
    if table:
        lines.append("")
        lines.append(f"{'type':<12} {'count':>5} {'p50':>5} {'p95':>5} "
                     f"{'p99':>5} {'max':>5} {'mean':>7}")
        for t in sorted(table):
            r = table[t]
            lines.append(f"{t:<12} {r['count']:>5} {r['p50']:>5} "
                         f"{r['p95']:>5} {r['p99']:>5} {r['max']:>5} "
                         f"{r['mean']:>7.2f}")
        lines.append("")
        lines.append(f"{'type':<12} " + " ".join(
            f"{s:>12}" for s in txntrace.SEGMENTS))
        for t in sorted(table):
            segs = table[t]["segments"]
            lines.append(f"{t:<12} " + " ".join(
                f"{segs[s]['total']:>12}" for s in txntrace.SEGMENTS))
    slow = txntrace.top_slowest(spans, top)
    if slow:
        lines.append("")
        lines.append(f"slowest {len(slow)}:")
        for s in slow:
            segs = s["segments"]
            seg_txt = " ".join(f"{k}={segs[k]}"
                               for k in txntrace.SEGMENTS if segs[k])
            tag = "" if s["attributed"] else " [unattributed]"
            lines.append(
                f"  n{s['requester']} 0x{s['addr']:02X} "
                f"{s['type']:<10} issue@{s['t_issue']:>5} "
                f"e2e={s['e2e']:>4}  {seg_txt}{tag}")
    open_spans = [s for s in spans if s["t_end"] is None]
    if open_spans:
        lines.append("")
        lines.append(f"open at capture end: {len(open_spans)}")
    return "\n".join(lines) + "\n"


# lint: host
def cmd_txns(args) -> int:
    from ue22cs343bb1_openmp_assignment_tpu.obs import perfetto, txntrace
    res = _capture_spans(args)
    if res is None:
        print("error: provide <test_directory> or --workload",
              file=sys.stderr)
        return 2
    spans, trace, total, ledger, cfg = res
    if args.perfetto:
        records = txntrace.ledger_to_records(ledger,
                                             trace["base_cycle"])
        flows = perfetto.span_flow_events(spans)
        doc = perfetto.build_trace(records, cfg.num_nodes, flows=flows)
        perfetto.validate_trace(doc)
        perfetto.write_trace(args.perfetto, doc)
        print(f"wrote {args.perfetto}: {len(records)} events, "
              f"{len(flows)} flow arrows", file=sys.stderr)
    if args.json:
        _emit(args, txntrace.spans_doc(cfg, spans, total,
                                       top=args.top))
    else:
        text = _render_txns(spans, total, args.top)
        if args.out:
            with open(args.out, "w") as f:
                f.write(text)
        else:
            sys.stdout.write(text)
    return 0


# lint: host
def _render_critpath(report, hot) -> str:
    total = report["total_cycles"]
    pct = (f" ({100.0 * report['length'] / total:.0f}% of "
           f"{total} cycles)" if total else "")
    lines = [f"critical path: {report['length']} cycles"
             f"{pct}, {report['events_on_path']} events"]
    if report["start"]:
        s, e = report["start"], report["end"]
        lines.append(f"  {s['kind']}@n{s['node']} cycle {s['cycle']} "
                     f"-> {e['kind']}@n{e['node']} cycle {e['cycle']}")
    lines.append("")
    lines.append("by phase:")
    for ph, c in report["by_phase"].items():
        if c:
            lines.append(f"  {ph:<14} {c:>6}")
    lines.append("by node:")
    for n, c in report["by_node"].items():
        lines.append(f"  node {n:<9} {c:>6}")
    if hot:
        lines.append("")
        lines.append("hotspots (largest waits on the path):")
        for s in hot:
            what = (f"{s['msg']['type']} from n{s['msg']['src']}"
                    if "msg" in s else "program order")
            lines.append(f"  cycle {s['cycle']:>5} n{s['node']}: "
                         f"waited {s['wait']} ({what})")
    return "\n".join(lines) + "\n"


# lint: host
def cmd_critpath(args) -> int:
    from ue22cs343bb1_openmp_assignment_tpu.obs import critpath
    res = _capture_spans(args)
    if res is None:
        print("error: provide <test_directory> or --workload",
              file=sys.stderr)
        return 2
    _, trace, total, _, _ = res
    report = critpath.critical_path(trace, total_cycles=total)
    if args.json:
        _emit(args, report)
    else:
        text = _render_critpath(report, critpath.hotspots(report))
        if args.out:
            with open(args.out, "w") as f:
                f.write(text)
        else:
            sys.stdout.write(text)
    return 0


# lint: host
def main_txns(argv) -> int:
    args = build_txns_parser().parse_args(argv)
    if args.cpu:
        import jax
        jax.config.update("jax_platforms", "cpu")
    return cmd_txns(args)


# lint: host
def main_critpath(argv) -> int:
    args = build_critpath_parser().parse_args(argv)
    if args.cpu:
        import jax
        jax.config.update("jax_platforms", "cpu")
    return cmd_critpath(args)


# lint: host
def build_bench_diff_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        prog="cache-sim bench-diff",
        description="noise-aware comparison of two bench captures "
                    "(obs.regress: Mann-Whitney U on rep times + a "
                    "practical bar from recorded rep spread). "
                    "Exit 0 = noise/improvement/baseline, "
                    "4 = regression, 2 = usage/incomparable.")
    p.add_argument("a", nargs="?", default=None,
                   help="baseline capture: BENCH_r*.json driver "
                        "capture, raw bench.py output, or a history "
                        "JSONL (its last entry is used)")
    p.add_argument("b", nargs="?", default=None,
                   help="candidate capture (same formats)")
    p.add_argument("--history", metavar="PATH",
                   help="bench history JSONL (see bench.py --record)")
    p.add_argument("--against-last", action="store_true",
                   help="compare the history's newest entry against "
                        "the one before it; with a single entry, "
                        "report 'baseline recorded' and exit 0")
    p.add_argument("--synthetic-slowdown", type=float, metavar="PCT",
                   help="self-test: compare A against a copy of A "
                        "with rep times scaled by (1 + PCT/100) — "
                        "must come out a regression (exit 4)")
    p.add_argument("--bytes", action="store_true",
                   help="compare the recorded deterministic cost "
                        "vectors (obs.roofline, bench.py --record) "
                        "instead of rep times: bytes/instr is exact "
                        "per compiled HLO, so any increase beyond "
                        "--bytes-tol is a regression with the "
                        "offending kernels named — no statistics")
    p.add_argument("--bytes-tol", type=float, default=None,
                   metavar="PCT",
                   help="tolerance for the --bytes gate (default 2.0; "
                        "absorbs benign layout churn, not noise — "
                        "there is none)")
    p.add_argument("--synthetic-bytes", type=float, metavar="PCT",
                   help="self-test (implies --bytes): compare A "
                        "against a copy of A with its cost vector "
                        "scaled by (1 + PCT/100) — must come out a "
                        "regression (exit 4)")
    p.add_argument("--latency", action="store_true",
                   help="compare the recorded open-loop latency "
                        "blocks (bench.py --soak) instead of rep "
                        "times: Mann-Whitney U on the per-job "
                        "samples_ms vectors, practical bar on the "
                        "p95 delta; arrival-rate mismatch is "
                        "incomparable")
    p.add_argument("--synthetic-latency", type=float, metavar="PCT",
                   help="self-test (implies --latency): compare A "
                        "against a copy of A with its latency block "
                        "scaled by (1 + PCT/100) — must come out a "
                        "regression (exit 4)")
    p.add_argument("--min-effect", type=float, default=5.0,
                   metavar="PCT",
                   help="never flag deltas below this percent "
                        "(default 5.0)")
    p.add_argument("--alpha", type=float, default=0.05,
                   help="one-sided significance level (default 0.05; "
                        "note 3v3 reps bottom out at exactly 0.05)")
    p.add_argument("--json", action="store_true",
                   help="emit the full verdict doc as JSON on stdout")
    return p


# lint: host
def _load_bench_entry(path: str):
    """A capture path -> one history entry. History JSONL files
    contribute their newest entry; anything else goes through
    obs.history.ingest_capture."""
    from ue22cs343bb1_openmp_assignment_tpu.obs import history
    try:
        hist = history.load(path)
        if hist:
            return hist[-1]
    except (ValueError, json.JSONDecodeError):
        pass
    return history.ingest_capture(path)


# lint: host
def cmd_bench_diff(args) -> int:
    import copy

    from ue22cs343bb1_openmp_assignment_tpu.obs import history, regress

    def fail(msg: str) -> int:
        print(f"error: {msg}", file=sys.stderr)
        return 2

    want_bytes = args.bytes or args.synthetic_bytes is not None
    want_latency = args.latency or args.synthetic_latency is not None
    if args.bytes_tol is not None and not want_bytes:
        return fail("--bytes-tol only applies with --bytes")
    if want_bytes and want_latency:
        return fail("--bytes and --latency are exclusive")
    synth = [n for n, v in (
        ("--synthetic-slowdown", args.synthetic_slowdown),
        ("--synthetic-bytes", args.synthetic_bytes),
        ("--synthetic-latency", args.synthetic_latency)) if v is not None]
    if len(synth) > 1:
        return fail(" and ".join(synth) + " are exclusive")
    try:
        if args.against_last:
            if not args.history:
                return fail("--against-last requires --history PATH")
            if not os.path.exists(args.history):
                return fail(f"history not found: {args.history}")
            hist = history.load(args.history)
            if not hist:
                return fail(f"history is empty: {args.history}")
            if len(hist) == 1:
                print(f"bench-diff: baseline recorded "
                      f"({hist[0]['label']}, 1 entry in "
                      f"{args.history}); nothing to compare yet")
                return 0
            entry_a, entry_b = hist[-2], hist[-1]
        else:
            if not args.a:
                return fail("provide captures A and B, or "
                            "--history ... --against-last")
            entry_a = _load_bench_entry(args.a)
            if args.synthetic_slowdown is not None:
                scale = 1.0 + args.synthetic_slowdown / 100.0
                entry_b = copy.deepcopy(entry_a)
                entry_b["label"] = (f"{entry_a['label']}"
                                    f"*{scale:g} (synthetic)")
                entry_b["rep_times_s"] = [
                    t * scale for t in entry_a["rep_times_s"]]
            elif args.synthetic_bytes is not None:
                scale = 1.0 + args.synthetic_bytes / 100.0
                entry_b = copy.deepcopy(entry_a)
                entry_b["label"] = (f"{entry_a['label']}"
                                    f"*{scale:g}B (synthetic)")
                cost = entry_b.get("cost")
                if isinstance(cost, dict):
                    if cost.get("bytes_per_instr") is not None:
                        cost["bytes_per_instr"] = round(
                            cost["bytes_per_instr"] * scale, 6)
                    for k in (cost.get("kernels") or {}).values():
                        if k.get("hbm_bytes") is not None:
                            k["hbm_bytes"] = k["hbm_bytes"] * scale
            elif args.synthetic_latency is not None:
                scale = 1.0 + args.synthetic_latency / 100.0
                entry_b = copy.deepcopy(entry_a)
                entry_b["label"] = (f"{entry_a['label']}"
                                    f"*{scale:g}L (synthetic)")
                lat = entry_b.get("latency")
                if isinstance(lat, dict):
                    for k in ("p50_ms", "p95_ms", "p99_ms", "max_ms"):
                        if lat.get(k) is not None:
                            lat[k] = round(lat[k] * scale, 6)
                    if lat.get("samples_ms") is not None:
                        lat["samples_ms"] = [round(x * scale, 6)
                                             for x in lat["samples_ms"]]
            elif args.b:
                entry_b = _load_bench_entry(args.b)
            else:
                return fail("provide capture B (or "
                            "--synthetic-slowdown/--synthetic-bytes/"
                            "--synthetic-latency PCT)")
    except (OSError, ValueError) as e:
        return fail(str(e))

    if want_bytes:
        tol = (regress.DEFAULT_BYTES_TOL_PCT if args.bytes_tol is None
               else args.bytes_tol)
        rep = regress.compare_cost(entry_a, entry_b, tol_pct=tol)
        fmt = regress.format_cost_report
    elif want_latency:
        rep = regress.compare_latency(entry_a, entry_b,
                                      min_effect=args.min_effect / 100.0,
                                      alpha=args.alpha)
        fmt = regress.format_latency_report
    else:
        rep = regress.compare(entry_a, entry_b,
                              min_effect=args.min_effect / 100.0,
                              alpha=args.alpha)
        fmt = regress.format_report
    if args.json:
        print(json.dumps(rep, sort_keys=True))
    else:
        print(fmt(rep))
    if rep["verdict"] == "regression":
        return 4
    if rep["verdict"] == "incomparable":
        return 2
    return 0


# lint: host
def main_bench_diff(argv) -> int:
    return cmd_bench_diff(build_bench_diff_parser().parse_args(argv))


# lint: host
def build_perfreport_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        prog="cache-sim perf-report",
        description="roofline + memory-traffic attribution "
                    "(obs.roofline): per-kernel flops / HBM bytes / "
                    "arithmetic intensity / bound classification from "
                    "XLA's compiled cost analysis, reduced to "
                    "bytes per simulated instruction — the one-screen "
                    "answer to which kernel moves the bytes. The "
                    "default report is deterministic per build "
                    "(byte-identical across runs); wall-clock ns/instr "
                    "is opt-in via --timing.")
    _add_common(p)
    p.add_argument("--engine", choices=["async", "sync", "deep"],
                   default="deep",
                   help="engine to attribute (default deep — the "
                        "throughput path ROADMAP item 1 targets)")
    p.add_argument("--chunk", type=int, default=64,
                   help="cycles/rounds per quiescence-check chunk")
    p.add_argument("--pallas", action="store_true",
                   help="sync-family engines on a TPU backend: "
                        "attribute the fused Pallas kernel variants "
                        "(cfg.pallas_burst) instead of the XLA path")
    p.add_argument("--timing", action="store_true",
                   help="attach the nondeterministic half: measured "
                        "ns/instr split by PhaseTimer phase and the "
                        "roofline model share per kernel, plus the "
                        "dispatch-bound check (measured >> model)")
    p.add_argument("--reps", type=int, default=3,
                   help="timed repetitions for --timing (default 3)")
    p.add_argument("--device-kind", default=None,
                   help="classify against this device kind's peaks "
                        "instead of the detected one (obs.roofline "
                        "static table)")
    p.add_argument("--shards", type=int, default=None,
                   help="async engine: shard count for the per-"
                        "transport bytes-on-wire row (all_to_all vs "
                        "rdma lane exchange, parallel.rdma_comm."
                        "wire_bytes). Default: the attached device "
                        "count when >1, else 8; must divide --nodes")
    p.add_argument("--profile", action="store_true",
                   help="attach the coherence-profile block "
                        "(obs.cohprof): replay the pinned run under "
                        "the profiler and report sharing patterns, "
                        "contended lines, and (deep) the measured "
                        "abort anatomy next to the bytes they cost")
    p.add_argument("--json", action="store_true",
                   help="emit the full cache-sim/perfreport/v1 doc")
    p.add_argument("--out", metavar="PATH",
                   help="write the report here instead of stdout")
    return p


# lint: host
def cmd_perfreport(args) -> int:
    import dataclasses
    import time

    import jax
    import numpy as np

    from ue22cs343bb1_openmp_assignment_tpu.config import SystemConfig
    from ue22cs343bb1_openmp_assignment_tpu.models.system import (
        CoherenceSystem)
    from ue22cs343bb1_openmp_assignment_tpu.obs import roofline
    from ue22cs343bb1_openmp_assignment_tpu.obs.phases import PhaseTimer
    from ue22cs343bb1_openmp_assignment_tpu.ops import sync_engine as se
    from ue22cs343bb1_openmp_assignment_tpu.ops import mailbox, step

    if args.test_dir:
        print("error: perf-report attributes synthetic workloads; "
              "use --workload (default uniform)", file=sys.stderr)
        return 2
    wl = args.workload or "uniform"
    sync_like = args.engine in ("sync", "deep")
    if sync_like:
        cfg = SystemConfig.scale(
            num_nodes=args.nodes,
            drain_depth=13 if args.engine == "deep" else 4,
            txn_width=3)
    else:
        cfg = SystemConfig.scale(num_nodes=args.nodes)
    if args.engine == "deep":
        # mirror bench.py's measured-best deep defaults so the report
        # attributes the same program the headline measures
        cfg = dataclasses.replace(
            cfg, deep_window=True,
            deep_slots=2 if args.nodes >= 32768 else 3,
            deep_ownerval_slots=1, deep_horizon_slack=4,
            deep_waves=1, deep_read_storm=False, deep_exact_flags=True)
    if args.pallas:
        if sync_like and jax.default_backend() == "tpu":
            cfg = dataclasses.replace(cfg, pallas_burst=True)
        else:
            print("note: --pallas needs a sync-family engine on a TPU "
                  "backend; attributing the XLA path instead",
                  file=sys.stderr)
    system = CoherenceSystem.from_workload(
        cfg, wl, trace_len=args.trace_len, seed=args.seed)

    max_cycles = args.max_cycles
    chunk = args.chunk
    if sync_like:
        max_cycles = min(max_cycles, se.claim_max_rounds(cfg) - 1)
        st0 = se.from_sim_state(cfg, system.state, seed=args.seed)

        def run():
            return se.run_sync_to_quiescence(cfg, st0, chunk,
                                             max_cycles)

        def steps_of(st):
            return int(st.metrics.rounds)

        per_step_name = "sync.round_step"

        def records():
            recs = [
                roofline.kernel_record(
                    per_step_name,
                    jax.jit(lambda s: se.round_step(cfg, s)), st0),
                roofline.kernel_record(
                    f"sync.run_to_quiescence[chunk={chunk}]",
                    se._run_sync_jit, cfg, st0, chunk, max_cycles),
            ]
            if args.engine == "deep":
                # the fused-vs-unfused comparison row: the fused round
                # kernel's HBM traffic is its I/O contract (state is
                # VMEM-resident; XLA's cost model can't see through
                # the pallas_call custom call), labeled io-contract vs
                # the xla-cost-model rows above
                from ue22cs343bb1_openmp_assignment_tpu.ops import (
                    pallas_round)
                if pallas_round.supported(cfg):
                    io_in, io_out = pallas_round.io_contract_bytes(cfg)
                    recs.append(roofline.io_contract_record(
                        "deep.round_fused[io-contract]", io_in, io_out))
            return recs
    else:
        st0 = system.state

        def run():
            return step.run_chunked_to_quiescence(cfg, st0, chunk,
                                                  max_cycles)

        def steps_of(st):
            return int(st.metrics.cycles)

        per_step_name = "step.cycle"

        def records():
            return [
                roofline.kernel_record(
                    per_step_name,
                    jax.jit(lambda s: step.cycle(cfg, s)), st0),
                roofline.kernel_record(
                    "mailbox.dequeue",
                    jax.jit(lambda s: mailbox.dequeue(cfg, s)), st0),
                roofline.kernel_record(
                    f"step.run_chunked[chunk={chunk}]",
                    step.run_chunked_to_quiescence, cfg, st0, chunk,
                    max_cycles),
            ]

    # one real run pins the deterministic integers (steps, retired)
    # that turn per-step bytes into bytes/instr
    final = run()
    steps = steps_of(final)
    retired = int(np.sum(np.asarray(final.metrics.instrs_retired)))
    if not bool(final.quiescent()):
        print(f"warning: not quiescent within {max_cycles} "
              f"cycles/rounds; bytes/instr covers the truncated run",
              file=sys.stderr)
    doc = roofline.build_report(
        args.engine,
        {"nodes": args.nodes, "workload": wl,
         "trace_len": args.trace_len, "chunk": chunk,
         "seed": args.seed,
         "pallas": bool(getattr(cfg, "pallas_burst", False))},
        records(), per_step_name, steps, retired,
        device_kind=args.device_kind)
    if args.engine == "async":
        # the per-transport bytes-on-wire row (deterministic shape
        # arithmetic, parallel.rdma_comm.wire_bytes) — a sibling
        # section of the kernel table, NOT a kernel record: transports
        # move interconnect bytes, not HBM bytes
        n_sh = args.shards
        if n_sh is None:
            n_dev = len(jax.devices())
            n_sh = n_dev if n_dev > 1 else 8
        if args.nodes % n_sh:
            print(f"note: --nodes {args.nodes} does not shard over "
                  f"{n_sh} devices; omitting the transport row",
                  file=sys.stderr)
        else:
            doc["transport"] = roofline.transport_section(cfg, n_sh)
    fused = next((k for k in doc["kernels"]
                  if k.get("basis") == "io-contract"), None)
    if fused is not None and doc["cost_available"]:
        doc["fused"] = {
            "kernel": fused["name"], "basis": "io-contract",
            "bytes_per_instr": round(
                fused["hbm_bytes"] * steps / retired, 6),
            "unfused_bytes_per_instr": doc["bytes_per_instr"],
        }
    if args.engine == "deep":
        # the fused round's VMEM budget row, from the kernel-contract
        # verifier's static block-table accounting (deterministic shape
        # arithmetic — the traced-liveness peak is `analyze --kernel`'s
        # job, not the perf report's)
        from ue22cs343bb1_openmp_assignment_tpu.analysis import (
            kernelcheck)
        doc["vmem"] = kernelcheck.vmem_rows(
            cfg, device_kind=args.device_kind, trace=False)
    # the static index-pressure row (analysis/indexcheck): per-plane
    # gather/scatter attribution of the engine's hot body, with
    # indices/instr derived from the same (steps, retired) integers
    # that pin bytes/instr above — the machine-checked replacement for
    # PERF.md's hand-counted index estimates
    from ue22cs343bb1_openmp_assignment_tpu.analysis import indexcheck
    if args.engine in indexcheck.ENGINES and retired:
        doc["index"] = indexcheck.index_row(args.engine, args.nodes)
        doc["index"]["indices_per_instr"] = round(
            doc["index"]["indices_per_step"] * steps / retired, 3)
    if args.profile:
        # the protocol-behavior sibling of the kernel table: same
        # pinned (steps, retired) run, replayed under the coherence
        # profiler (obs.cohprof) — which lines move the bytes, and on
        # the deep engine which aborts burn the rounds
        from ue22cs343bb1_openmp_assignment_tpu.obs import cohprof
        if args.engine == "async":
            doc["profile"] = cohprof.capture_async(cfg, st0, steps)
        elif args.engine == "deep":
            space = args.nodes * (args.nodes << cfg.block_bits)
            if space * 4 > 1 << 29:
                print("note: deep profile plane too large at this "
                      "--nodes; omitting the profile block",
                      file=sys.stderr)
            else:
                doc["profile"] = cohprof.capture_deep(cfg, st0, steps)
        else:
            doc["profile"] = cohprof.capture_sync(cfg, st0, steps)
    if args.timing:
        timer = PhaseTimer()
        rep_times = []
        for _ in range(max(1, args.reps)):
            t0 = time.perf_counter()
            st = run()
            t1 = time.perf_counter()
            # device_get is the real sync on a tunneled link (PERF.md)
            int(np.sum(np.asarray(st.metrics.instrs_retired)))
            t2 = time.perf_counter()
            timer.add("execute_dispatch", t1 - t0)
            timer.add("device_get_sync", t2 - t1)
            rep_times.append(t2 - t0)
        doc["timing"] = roofline.timing_section(
            timer.report(), doc["kernels"], steps, retired, rep_times)
    if args.json:
        _emit(args, doc)
    else:
        text = roofline.render_text(doc)
        if "profile" in doc:
            from ue22cs343bb1_openmp_assignment_tpu.obs import cohprof
            text += "\n" + cohprof.render_text(doc["profile"]) + "\n"
        if args.out:
            with open(args.out, "w") as f:
                f.write(text)
        else:
            sys.stdout.write(text)
    return 0


# lint: host
def main_perfreport(argv) -> int:
    args = build_perfreport_parser().parse_args(argv)
    if args.cpu:
        import jax
        jax.config.update("jax_platforms", "cpu")
    return cmd_perfreport(args)


# lint: host
def build_dashboard_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        prog="cache-sim dashboard",
        description="render a bench history into a self-contained "
                    "static HTML + markdown report (obs.dashboard): "
                    "headline instrs/sec trend vs the 1e8 target, "
                    "bench-diff verdict strip, protocol x workload "
                    "coverage cells, the multichip sharded scaling "
                    "curve, the litmus consistency matrix (--litmus), "
                    "and the roofline scatter of recorded cost "
                    "vectors. Deterministic: same history bytes, same "
                    "report bytes.")
    p.add_argument("captures", nargs="*",
                   help="capture files to ingest before rendering: "
                        "BENCH_r*.json driver captures and "
                        "MULTICHIP_r*.json dryruns (obs.history "
                        "adapters), in the order given")
    p.add_argument("--history", metavar="PATH",
                   help="bench history JSONL (bench.py --record); its "
                        "entries precede any ingested captures")
    p.add_argument("--html", metavar="PATH",
                   help="write the self-contained HTML report here")
    p.add_argument("--md", metavar="PATH",
                   help="write the markdown report here")
    p.add_argument("--json", action="store_true",
                   help="print the dashboard model JSON to stdout")
    p.add_argument("--litmus", metavar="PATH",
                   help="analyze --litmus --json report (or the bare "
                        "litmus.run_suite dict); renders as the "
                        "protocol x consistency-test matrix")
    p.add_argument("--recording", metavar="PATH", action="append",
                   default=[],
                   help="a cache-sim/recording/v1 capture (daemon "
                        "--record artifact or record dir); repeatable; "
                        "renders as the captured-traffic table, each "
                        "row replayable with cache-sim replay")
    p.add_argument("--profile", metavar="PATH", action="append",
                   default=[],
                   help="a cache-sim/profile/v1 doc (cache-sim "
                        "profile --json); repeatable; renders as the "
                        "coherence-profile table (dominant sharing "
                        "pattern, miss mix, ghost-poison fraction)")
    return p


# lint: host
def _ingest_any(path: str) -> dict:
    """Capture path -> history entry, dispatching between the bench
    and multichip adapters by content (filename is a hint only)."""
    from ue22cs343bb1_openmp_assignment_tpu.obs import history
    if "MULTICHIP" in os.path.basename(path).upper():
        return history.ingest_multichip(path)
    try:
        return history.ingest_capture(path)
    except ValueError:
        return history.ingest_multichip(path)


# lint: host
def cmd_dashboard(args) -> int:
    from ue22cs343bb1_openmp_assignment_tpu.obs import (
        dashboard, history)
    if not args.history and not args.captures:
        print("error: provide --history PATH and/or capture files",
              file=sys.stderr)
        return 2
    if not (args.html or args.md or args.json):
        print("error: provide --html PATH, --md PATH, or --json",
              file=sys.stderr)
        return 2
    entries = []
    litmus = None
    recordings = []
    try:
        if args.history:
            entries.extend(history.load(args.history))
        for path in args.captures:
            entries.append(_ingest_any(path))
        if args.litmus:
            with open(args.litmus) as f:
                doc = json.load(f)
            # accept either the full analyze report or the bare matrix
            litmus = doc.get("litmus", doc) if isinstance(doc, dict) \
                else None
            if not isinstance(litmus, dict):
                raise ValueError(f"{args.litmus}: not a litmus report")
        if args.recording:
            from ue22cs343bb1_openmp_assignment_tpu.obs import (
                recording)
            recordings = [recording.load(p) for p in args.recording]
        profiles = []
        for path in args.profile:
            from ue22cs343bb1_openmp_assignment_tpu.obs import cohprof
            with open(path) as f:
                prof = cohprof.validate(json.load(f))
            prof["extra"].setdefault("path", path)
            profiles.append(prof)
    except (OSError, ValueError) as e:
        print(f"error: {e}", file=sys.stderr)
        return 2
    res = dashboard.render(entries, html_path=args.html,
                           md_path=args.md, litmus=litmus,
                           recordings=recordings, profiles=profiles)
    if args.json:
        print(json.dumps(res["model"], sort_keys=True))
    for path in (args.html, args.md):
        if path:
            print(f"wrote {path}", file=sys.stderr)
    return 0


# lint: host
def main_dashboard(argv) -> int:
    return cmd_dashboard(build_dashboard_parser().parse_args(argv))


# lint: host
def main_stats(argv) -> int:
    args = build_stats_parser().parse_args(argv)
    if args.cpu:
        import jax
        jax.config.update("jax_platforms", "cpu")
    return cmd_stats(args)


# lint: host
def main_trace(argv) -> int:
    args = build_trace_parser().parse_args(argv)
    if args.cpu:
        import jax
        jax.config.update("jax_platforms", "cpu")
    return cmd_trace(args)


# lint: host
def build_profile_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        prog="cache-sim profile",
        description="replay a run under the coherence profiler "
                    "(obs.cohprof) and emit per-line contention "
                    "attribution: miss taxonomy (cold / conflict / "
                    "coherence-invalidation / upgrade), invalidation "
                    "fan-out, sharing-pattern classification, top "
                    "contended lines — and for --engine deep the "
                    "measured abort anatomy (ghost-poison fraction). "
                    "Deterministic: same config, same doc bytes.")
    _add_common(p)
    p.add_argument("--engine", choices=["async", "sync", "deep"],
                   default="async",
                   help="async = full counter plane (misses / inv / "
                        "writebacks / migrations); sync = access "
                        "planes + classifier; deep = access planes + "
                        "abort anatomy")
    p.add_argument("--top", type=int, default=8,
                   help="contended lines to attribute (default 8)")
    p.add_argument("--no-exact-flags", action="store_true",
                   help="deep engine: profile the conservative "
                        "flag-raising path (cfg.deep_exact_flags off) "
                        "— the configuration whose ghost-poison "
                        "fraction PERF.md estimates")
    p.add_argument("--json", action="store_true",
                   help="emit the cache-sim/profile/v1 doc instead of "
                        "the text rendering")
    p.add_argument("--out", metavar="PATH",
                   help="write the output here instead of stdout")
    return p


# lint: host
def cmd_profile(args) -> int:
    import dataclasses

    from ue22cs343bb1_openmp_assignment_tpu.obs import cohprof

    if args.no_exact_flags and args.engine != "deep":
        print("error: --no-exact-flags is a deep-engine knob; "
              "add --engine deep", file=sys.stderr)
        return 2
    if args.engine == "async":
        system0 = _async_system(args)
        if system0 is None:
            print("error: provide <test_directory> or --workload",
                  file=sys.stderr)
            return 2
        # two-pass replay discipline (--timeseries/--txns do the
        # same): the plain run pins the cycle count, the profiled
        # replay from the initial state walks the identical trajectory
        if args.run_cycles is not None:
            steps = args.run_cycles
        else:
            steps = int(system0.run(args.max_cycles).metrics["cycles"])
        doc = cohprof.capture_async(system0.cfg, system0.state, steps,
                                    k=args.top)
    else:
        from ue22cs343bb1_openmp_assignment_tpu.config import (
            SystemConfig)
        from ue22cs343bb1_openmp_assignment_tpu.models.transactional \
            import TransactionalSystem
        deep = args.engine == "deep"
        cfg = SystemConfig.scale(
            num_nodes=args.nodes,
            drain_depth=13 if deep else 4, txn_width=3)
        if deep:
            # mirror perf-report's measured-best deep defaults so the
            # anatomy describes the same program the headline measures
            cfg = dataclasses.replace(
                cfg, deep_window=True,
                deep_slots=2 if args.nodes >= 32768 else 3,
                deep_ownerval_slots=1, deep_horizon_slack=4,
                deep_waves=1, deep_read_storm=False,
                deep_exact_flags=not args.no_exact_flags)
            space = args.nodes * (args.nodes << cfg.block_bits)
            if space * 4 > 1 << 29:
                print("error: deep profile plane would need "
                      f"{space * 4 >> 20} MiB (nodes x addr-space "
                      "counters); profile the deep engine at a "
                      "smaller --nodes", file=sys.stderr)
                return 2
        if args.workload:
            ts = TransactionalSystem.from_workload(
                cfg, args.workload, trace_len=args.trace_len,
                workload_seed=args.seed)
        elif args.test_dir:
            path = os.path.join(args.tests_root, args.test_dir)
            ts = TransactionalSystem.from_test_dir(path)
            cfg = ts.cfg
        else:
            print("error: provide <test_directory> or --workload",
                  file=sys.stderr)
            return 2
        if args.run_cycles is not None:
            steps = args.run_cycles
        else:
            steps = int(ts.run(max_rounds=args.max_cycles)
                        .state.metrics.rounds)
        cap = cohprof.capture_deep if deep else cohprof.capture_sync
        doc = cap(cfg, ts.state, steps, k=args.top)
    if args.json:
        _emit(args, doc)
    else:
        text = cohprof.render_text(doc) + "\n"
        if args.out:
            with open(args.out, "w") as f:
                f.write(text)
        else:
            sys.stdout.write(text)
    return 0


# lint: host
def main_profile(argv) -> int:
    args = build_profile_parser().parse_args(argv)
    if args.cpu:
        import jax
        jax.config.update("jax_platforms", "cpu")
    return cmd_profile(args)
