"""Causal transaction tracer: message-ledger capture → Dapper-style spans.

A coherence *transaction* is the protocol's unit of work: a miss or
upgrade issues (the node blocks), a REQUEST travels to the home
directory, the home forwards/invalidates, owners flush, a reply fills
the line and clears the wait. PR 2's telemetry says how many of those
happened per cycle; this module says **where each one spent its
cycles**.

The capture is the message ledger (ops.step cycle ``with_ledger``):
per cycle, the per-node dequeue record, every enqueue candidate with
its post-arbitration accept mask, the frontend issue latch, and the
wait-clear mask — stacked by the same single-dispatch ``lax.scan`` as
the telemetry series and pulled host-side in chunks (:func:`capture`).

Reconstruction exploits two exact properties of the engine:

* **FIFO rings** — per receiver, dequeue order equals enqueue order,
  so the k-th dequeue at node *d* IS the k-th accepted enqueue into
  *d*'s ring: enqueue→dequeue matching needs no message ids on device.
* **causal parents** — a message emitted by node *n* at cycle *t* was
  caused by the message *n* dequeued at *t* (handlers emit in their
  dequeue cycle), else by the instruction *n* fetched at *t*. Walking
  parents from the unblocking reply yields each transaction's exact
  hop chain back to its issue.

Each closed span (keyed ``(requester, addr, issue-order)``) decomposes
into four segments that sum to its end-to-end latency *by
construction* (each hop contributes 1 transit cycle plus its ring
wait, and consecutive hops share a cycle — the handler emits in its
dequeue cycle):

* ``queue_wait``  — the request's wait in the home's ring,
* ``dir_service`` — waits on intermediate hops (forwards, flushes),
* ``in_flight``   — one cycle per hop transit,
* ``ack_wait``    — the final reply's wait in the requester's ring.

Host-side analysis only; the device capture lives in ops/step.py.
"""
# lint: host

from __future__ import annotations

from typing import Callable, Dict, List, Optional

import numpy as np

from ue22cs343bb1_openmp_assignment_tpu.types import MSG_NAMES, Msg, Op

SCHEMA_ID = "cache-sim/txnspans/v1"

#: span segment names, in report order; per span they sum exactly to
#: the end-to-end latency (tests/test_txntrace.py pins the invariant)
SEGMENTS = ("queue_wait", "dir_service", "in_flight", "ack_wait")

#: request message type → transaction class
TXN_TYPES = {int(Msg.READ_REQUEST): "read_miss",
             int(Msg.WRITE_REQUEST): "write_miss",
             int(Msg.UPGRADE): "upgrade"}


# lint: host
def capture(cfg, state0, num_cycles: int, chunk: int = 64,
            message_phase: Optional[Callable] = None,
            stop_on_quiescence: bool = True,
            with_obs: bool = False):
    """Run the async engine ``num_cycles`` cycles with the message
    ledger on, in host-side ``chunk``-cycle scans (one fused dispatch
    each — the flight-recorder discipline; chunk stays a single static
    size so the scan compiles once, plus at most one remainder size).

    Returns ``(final_state, ledger, base_cycle)`` with ledger a dict
    of host [T, ...] numpy arrays (LEDGER_FIELDS) and base_cycle the
    absolute cycle of sample 0.
    """
    from ue22cs343bb1_openmp_assignment_tpu.ops import step
    if chunk < 1:
        raise ValueError(f"chunk must be >= 1, got {chunk}")
    base_cycle = int(state0.cycle)
    state = state0
    parts: List[dict] = []
    done = 0
    while done < num_cycles:
        if stop_on_quiescence and bool(state.quiescent()):
            break
        left = num_cycles - done
        n = chunk if left >= chunk else left
        state, led = step.run_cycles_ledger(cfg, state, n,
                                            message_phase, with_obs)
        parts.append({k: np.asarray(v) for k, v in led.items()})
        done += n
    if not parts:
        return state, {}, base_cycle
    ledger = {k: np.concatenate([p[k] for p in parts], axis=0)
              for k in parts[0]}
    return state, ledger, base_cycle


# lint: host
def parse_ledger(cfg, ledger: Dict[str, np.ndarray], base_cycle: int = 0,
                 arb_rank=None, init_mb_count=None) -> dict:
    """Ledger arrays → the causal event structure.

    Returns a dict with:

    * ``msgs`` — one record per *accepted* enqueue, in global causal
      order: ``{src, dst, type, addr, enq, deq, parent}`` where deq is
      None while the message still sits in a ring at capture end and
      parent is ``("msg", i)`` / ``("issue", (node, cycle))`` /
      ``("fetch", (node, cycle))`` / ``("unknown", None)``;
    * ``events`` — per node, its time-ordered activity events
      ``(cycle, kind, msg_idx)`` with kind ``"msg"`` or ``"instr"``
      (a node never does both in one cycle: drain-before-fetch);
    * ``issues`` — ``{(node, cycle): {addr, op, value, req_type,
      accepted}}`` for every coherence-wait-opening fetch;
    * ``unblocks`` — time-ordered ``(cycle, node, msg_idx)``;
    * ``num_cycles`` / ``base_cycle``.

    ``init_mb_count`` (per-node ints) marks messages already enqueued
    before the window: their dequeues match to *unknown* messages
    instead of failing — the warm-start mode the flight recorder uses.
    FIFO matching is exact because each ring dequeues in enqueue order
    and same-cycle enqueue order is the arbitration sort
    ``(arb_rank[src], slot)``, replayed here bit-for-bit.
    """
    if not ledger:
        return {"msgs": [], "events": {}, "issues": {}, "unblocks": [],
                "num_cycles": 0, "base_cycle": base_cycle}
    N, S = cfg.num_nodes, cfg.out_slots
    T = ledger["deq_has"].shape[0]
    rank = (np.arange(N, dtype=np.int64) if arb_rank is None
            else np.asarray(arb_rank, dtype=np.int64))
    pending = ([0] * N if init_mb_count is None
               else [int(c) for c in np.asarray(init_mb_count)])

    msgs: List[dict] = []
    rings: List[List[int]] = [[] for _ in range(N)]
    events: Dict[int, list] = {n: [] for n in range(N)}
    issues: Dict[tuple, dict] = {}
    unblocks: List[tuple] = []

    deq_has = ledger["deq_has"]
    fetch, issue = ledger["fetch"], ledger["issue"]
    acc = ledger["enq_accept"]
    for t in range(T):
        cyc = base_cycle + t
        # phase 1: dequeues pop ring state from *earlier* cycles (a
        # message delivered in phase 3 of cycle c is dequeue-eligible
        # at c+1); FIFO: head of the per-ring list
        deq_of: Dict[int, Optional[int]] = {}
        for n in np.nonzero(deq_has[t])[0]:
            n = int(n)
            if pending[n] > 0:
                pending[n] -= 1
                deq_of[n] = None      # pre-window message: unknown
            else:
                if not rings[n]:
                    raise ValueError(
                        f"ledger inconsistent: node {n} dequeues at "
                        f"cycle {cyc} from an empty ring")
                i = rings[n].pop(0)
                m = msgs[i]
                if (m["type"] != int(ledger["deq_type"][t, n])
                        or m["src"] != int(ledger["deq_sender"][t, n])
                        or m["addr"] != int(ledger["deq_addr"][t, n])):
                    raise ValueError(
                        f"ledger inconsistent: FIFO match at node {n} "
                        f"cycle {cyc} disagrees with dequeue record")
                m["deq"] = cyc
                deq_of[n] = i
            events[n].append((cyc, "msg", deq_of[n]))
            if ledger["unblocked"][t, n]:
                unblocks.append((cyc, n, deq_of[n]))
        # phase 2: instruction fetches (only message-idle nodes)
        for n in np.nonzero(fetch[t])[0]:
            n = int(n)
            events[n].append((cyc, "instr", None))
            if issue[t, n]:
                issues[(n, cyc)] = {
                    "addr": int(ledger["addr"][t, n]),
                    "op": int(ledger["op"][t, n]),
                    "value": int(ledger["value"][t, n]),
                    # the request candidate rides slot 0 this cycle;
                    # its planes are valid even if arbitration (or
                    # fault injection) dropped it
                    "req_type": int(ledger["enq_type"][t, n, 0]),
                    "accepted": bool(acc[t, n, 0]),
                }
        # phase 3: accepted enqueues append in arbitration order —
        # the delivery sort key is (arb_rank[sender], slot)
        srcs, slots = np.nonzero(acc[t])
        if srcs.size:
            order = np.argsort(rank[srcs] * S + slots, kind="stable")
            for src, slot in zip(srcs[order], slots[order]):
                src, slot = int(src), int(slot)
                if deq_has[t, src]:
                    parent = ("msg", events[src][-1][2])
                    if parent[1] is None:
                        parent = ("unknown", None)
                elif issue[t, src] and slot == 0:
                    parent = ("issue", (src, cyc))
                elif fetch[t, src]:
                    parent = ("fetch", (src, cyc))
                else:          # unreachable: every emission has a cause
                    parent = ("unknown", None)
                i = len(msgs)
                msgs.append({
                    "src": src,
                    "dst": int(ledger["enq_recv"][t, src, slot]),
                    "type": int(ledger["enq_type"][t, src, slot]),
                    "addr": int(ledger["enq_addr"][t, src, slot]),
                    "enq": cyc, "deq": None, "parent": parent,
                })
                rings[msgs[i]["dst"]].append(i)
    return {"msgs": msgs, "events": events, "issues": issues,
            "unblocks": unblocks, "num_cycles": T,
            "base_cycle": base_cycle}


# lint: host
def _chain(msgs: List[dict], end_idx: Optional[int]):
    """Hop indices root→reply for the causal chain ending at
    ``msgs[end_idx]``, plus the chain's root cause (an
    ``("issue"|"fetch", (node, cycle))`` tuple or None when the chain
    leaves the capture window)."""
    hops: List[int] = []
    i = end_idx
    root = None
    while i is not None:
        hops.append(i)
        kind, ref = msgs[i]["parent"]
        if kind == "msg":
            i = ref
            continue
        root = None if kind == "unknown" else (kind, ref)
        break
    hops.reverse()
    return hops, root


# lint: host
def _decompose(span: dict, msgs: List[dict], hops: List[int],
               root) -> None:
    """Fill span["segments"] (summing exactly to end-to-end) and
    span["attributed"]. A span is *attributed* when its causal chain
    is fully inside the window and roots at its own issue; otherwise
    (warm start, or the racy FLUSH-clears-any-wait reference quirk
    closing a wait from another node's transaction) the whole latency
    is reported as ack_wait, unattributed — the sum invariant holds
    either way."""
    e2e = span["t_end"] - span["t_issue"]
    ok = (root == ("issue", (span["requester"], span["t_issue"]))
          and hops and msgs[hops[0]]["enq"] == span["t_issue"])
    if not ok:
        span["segments"] = {"queue_wait": 0, "dir_service": 0,
                            "in_flight": 0, "ack_wait": e2e}
        span["attributed"] = False
        span["hops"] = len(hops)
        return
    k = len(hops)
    first, last = msgs[hops[0]], msgs[hops[-1]]
    queue_wait = first["deq"] - first["enq"] - 1
    if k == 1:
        seg = {"queue_wait": queue_wait, "dir_service": 0,
               "in_flight": 1, "ack_wait": 0}
    else:
        ack = last["deq"] - last["enq"] - 1
        seg = {"queue_wait": queue_wait, "in_flight": k,
               "ack_wait": ack,
               "dir_service": e2e - queue_wait - k - ack}
    span["segments"] = seg
    span["attributed"] = True
    span["hops"] = k
    span["chain"] = [
        {"src": msgs[i]["src"], "dst": msgs[i]["dst"],
         "type": MSG_NAMES[msgs[i]["type"]],
         "enq": msgs[i]["enq"], "deq": msgs[i]["deq"]}
        for i in hops]


# lint: host
def build_spans(trace: dict, init_open: Optional[List[dict]] = None
                ) -> List[dict]:
    """Transaction spans from a parsed ledger, keyed
    ``(requester, addr, seq)`` with seq the per-requester issue order.

    A node blocks while it waits, so it has at most one open span;
    issues open spans, wait-clears close the node's open span (even
    when the clearing message belongs to another transaction — the
    reference's unconditional-FLUSH quirk — in which case the span is
    closed but *unattributed*). ``init_open`` seeds spans already in
    flight at window start (flight-recorder warm starts):
    ``{node, t_issue, addr, op}`` each.
    """
    msgs = trace["msgs"]
    spans: List[dict] = []
    open_by_node: Dict[int, dict] = {}
    seq_by_node: Dict[int, int] = {}

    for w in (init_open or []):
        n = int(w["node"])
        seq_by_node[n] = seq_by_node.get(n, 0) + 1
        sp = {"requester": n, "addr": int(w["addr"]),
              "seq": -seq_by_node[n],   # before any in-window issue
              "type": ("read_miss" if int(w["op"]) == int(Op.READ)
                       else "write_miss"),
              "t_issue": int(w["t_issue"]), "t_end": None, "e2e": None,
              "segments": None, "attributed": False, "hops": 0,
              "request_dropped": False, "warm_start": True}
        spans.append(sp)
        open_by_node[n] = sp
    seq_by_node = {}

    # merge issues and unblocks into one time-ordered stream; at equal
    # cycles unblocks come first (phase 1 before phase 2 — and a node
    # never does both, see parse_ledger)
    stream = sorted(
        [(c, 0, n, i) for (c, n, i) in trace["unblocks"]]
        + [(c, 1, n, None) for (n, c) in trace["issues"]])
    for cyc, kind, n, msg_idx in stream:
        if kind == 0:                         # unblock: close n's span
            sp = open_by_node.pop(n, None)
            if sp is None:
                raise ValueError(
                    f"ledger inconsistent: node {n} unblocked at cycle "
                    f"{cyc} with no open span")
            sp["t_end"] = cyc
            sp["e2e"] = cyc - sp["t_issue"]
            hops, root = _chain(msgs, msg_idx)
            _decompose(sp, msgs, hops, root)
        else:                                 # issue: open a span
            info = trace["issues"][(n, cyc)]
            if n in open_by_node:
                raise ValueError(
                    f"ledger inconsistent: node {n} issued at cycle "
                    f"{cyc} while already waiting")
            seq_by_node[n] = seq_by_node.get(n, -1) + 1
            sp = {"requester": n, "addr": info["addr"],
                  "seq": seq_by_node[n],
                  "type": TXN_TYPES.get(info["req_type"], "unknown"),
                  "t_issue": cyc, "t_end": None, "e2e": None,
                  "segments": None, "attributed": False, "hops": 0,
                  "request_dropped": not info["accepted"],
                  "warm_start": False}
            spans.append(sp)
            open_by_node[n] = sp
    return spans


# lint: host
def reconstruct(cfg, ledger: Dict[str, np.ndarray], base_cycle: int = 0,
                arb_rank=None, init_mb_count=None,
                init_open: Optional[List[dict]] = None):
    """parse + span build in one call; returns ``(spans, trace)``."""
    trace = parse_ledger(cfg, ledger, base_cycle=base_cycle,
                         arb_rank=arb_rank, init_mb_count=init_mb_count)
    return build_spans(trace, init_open=init_open), trace


# lint: host
def percentile(values: List[int], q: float) -> Optional[int]:
    """Nearest-rank percentile (deterministic, integer-exact)."""
    if not values:
        return None
    s = sorted(values)
    k = max(1, int(-(-q * len(s) // 100)))  # ceil(q/100 * n), >= 1
    return s[k - 1]


# lint: host
def latency_table(spans: List[dict]) -> dict:
    """Per-transaction-type latency decomposition: count, p50/p95/p99
    of end-to-end latency, and per-segment totals + p95 — closed spans
    only."""
    closed = [s for s in spans if s["t_end"] is not None]
    out = {}
    for t in sorted({s["type"] for s in closed}):
        rows = [s for s in closed if s["type"] == t]
        e2e = [s["e2e"] for s in rows]
        ent = {"count": len(rows),
               "p50": percentile(e2e, 50), "p95": percentile(e2e, 95),
               "p99": percentile(e2e, 99), "max": max(e2e),
               "mean": round(sum(e2e) / len(e2e), 2),
               "segments": {}}
        for seg in SEGMENTS:
            vals = [s["segments"][seg] for s in rows]
            ent["segments"][seg] = {"total": sum(vals),
                                    "p95": percentile(vals, 95)}
        out[t] = ent
    return out


# lint: host
def top_slowest(spans: List[dict], n: int = 10) -> List[dict]:
    """The n slowest closed spans, deterministically ordered
    (latency desc, then issue cycle, then requester)."""
    closed = [s for s in spans if s["t_end"] is not None]
    return sorted(closed,
                  key=lambda s: (-s["e2e"], s["t_issue"],
                                 s["requester"]))[:n]


# lint: host
def summarize(spans: List[dict]) -> dict:
    """The compact ``txn_latency`` block attached to
    ``cache-sim/metrics/v1.1`` reports (obs.schema)."""
    closed = [s for s in spans if s["t_end"] is not None]
    by_type = {}
    for t in sorted({s["type"] for s in closed}):
        e2e = [s["e2e"] for s in closed if s["type"] == t]
        by_type[t] = {"count": len(e2e),
                      "p50": percentile(e2e, 50),
                      "p95": percentile(e2e, 95),
                      "p99": percentile(e2e, 99)}
    return {"spans": len(closed),
            "open": len(spans) - len(closed),
            "by_type": by_type,
            "segments_total": {
                seg: sum(s["segments"][seg] for s in closed)
                for seg in SEGMENTS}}


# lint: host
def spans_doc(cfg, spans: List[dict], total_cycles: int,
              top: int = 10) -> dict:
    """The full ``cache-sim/txnspans/v1`` JSON document behind
    ``cache-sim txns --json``."""
    return {"schema": SCHEMA_ID,
            "num_nodes": cfg.num_nodes,
            "total_cycles": int(total_cycles),
            "spans_closed": sum(1 for s in spans
                                if s["t_end"] is not None),
            "spans_open": sum(1 for s in spans if s["t_end"] is None),
            "attributed": sum(1 for s in spans if s["attributed"]),
            "by_type": latency_table(spans),
            "txn_latency": summarize(spans),
            "slowest": top_slowest(spans, top),
            "open": [{k: s[k] for k in ("requester", "addr", "seq",
                                        "type", "t_issue",
                                        "request_dropped")}
                     for s in spans if s["t_end"] is None]}


# lint: host
def ledger_to_records(ledger: Dict[str, np.ndarray],
                      base_cycle: int = 0) -> List[dict]:
    """Ledger → utils.eventlog-shaped records (instr fetches + msg
    dequeues), so ``cache-sim txns --perfetto`` renders slices from
    the same capture the spans came from — no second traced run."""
    if not ledger:
        return []
    out = []
    mt, mn = np.nonzero(ledger["deq_has"])
    for t, n in zip(mt, mn):
        ty = int(ledger["deq_type"][t, n])
        out.append({"kind": "msg", "cycle": base_cycle + int(t),
                    "node": int(n),
                    "sender": int(ledger["deq_sender"][t, n]),
                    "type": ty, "type_name": MSG_NAMES[ty],
                    "addr": int(ledger["deq_addr"][t, n])})
    ft, fn = np.nonzero(ledger["fetch"])
    for t, n in zip(ft, fn):
        out.append({"kind": "instr", "cycle": base_cycle + int(t),
                    "node": int(n), "op": int(ledger["op"][t, n]),
                    "addr": int(ledger["addr"][t, n]),
                    "value": int(ledger["value"][t, n])})
    return sorted(out, key=lambda r: (r["cycle"], r["node"]))


# lint: host
def incident_summary(cfg, state0, cycles_run: int,
                     message_phase: Optional[Callable] = None,
                     window: int = 4096, chunk: int = 64) -> dict:
    """Transaction-span summary for a flight-recorder incident
    (obs.flight): deterministically replay the run and reconstruct the
    spans of its last ``min(cycles_run, window)`` cycles — the slowest
    five closed spans with their decomposition, plus every transaction
    still in flight at the end (the hang suspects).

    Long runs replay the pre-window prefix without the ledger (chunked
    plain telemetry scans, which the recorder already compiled) and
    warm-start the reconstruction from the ring occupancy and per-node
    wait state at the window edge.
    """
    from ue22cs343bb1_openmp_assignment_tpu.ops import step
    cycles_run = int(cycles_run)
    t0 = max(0, cycles_run - int(window))
    state = state0
    done = 0
    while done < t0:                       # prefix replay, ledger off
        n = chunk if t0 - done >= chunk else t0 - done
        state, _ = step.run_cycles_telemetry(cfg, state, n,
                                             message_phase)
        done += n
    init_mb_count = np.asarray(state.mb_count)
    waiting = np.asarray(state.waiting)
    init_open = [{"node": int(n),
                  "t_issue": int(np.asarray(state.waiting_since)[n]),
                  "addr": int(np.asarray(state.cur_addr)[n]),
                  "op": int(np.asarray(state.cur_op)[n])}
                 for n in np.nonzero(waiting)[0]] if t0 else None
    final, ledger, base = capture(cfg, state, cycles_run - t0,
                                  chunk=chunk,
                                  message_phase=message_phase,
                                  stop_on_quiescence=False)
    spans, _ = reconstruct(cfg, ledger, base_cycle=base,
                           arb_rank=np.asarray(state.arb_rank),
                           init_mb_count=init_mb_count if t0 else None,
                           init_open=init_open)
    end_cycle = int(final.cycle)
    return {"window_start": base, "window_cycles": cycles_run - t0,
            "warm_start": bool(t0),
            "spans_closed": sum(1 for s in spans
                                if s["t_end"] is not None),
            "spans_open": sum(1 for s in spans if s["t_end"] is None),
            "slowest": [
                {k: s[k] for k in ("requester", "addr", "seq", "type",
                                   "t_issue", "t_end", "e2e",
                                   "segments", "attributed")}
                for s in top_slowest(spans, 5)],
            "in_flight": [
                {"requester": s["requester"], "addr": s["addr"],
                 "seq": s["seq"], "type": s["type"],
                 "t_issue": s["t_issue"],
                 "age": end_cycle - s["t_issue"],
                 "request_dropped": s["request_dropped"]}
                for s in spans if s["t_end"] is None]}
