"""Wall-clock phase timers: compile / execute dispatch / device_get.

PERF.md's correction history is a catalog of mistaking one phase for
another — the ~90-130 ms fixed `device_get` sync being billed to the
scan, warmup compile leaking into timed reps. The PhaseTimer makes the
split explicit: bench.py and the obs CLI bracket each phase, and the
resulting report travels with every benchmark capture so a headline
number can always be decomposed.

Host-side by construction (time.perf_counter); never used in traced
code.
"""

from __future__ import annotations

import time
from contextlib import contextmanager
from typing import Dict, Iterator, List


class PhaseTimer:
    """Accumulates named wall-clock phases.

    Phases are additive: entering the same name again adds to its
    total (so per-rep dispatch/sync costs aggregate naturally).
    Insertion order is preserved in the report.
    """

    # lint: host
    def __init__(self) -> None:
        self._total: Dict[str, float] = {}
        self._count: Dict[str, int] = {}
        self._order: List[str] = []
        self._attached: Dict[str, object] = {}

    # lint: host
    def attach(self, key: str, doc) -> None:
        """Attach a non-timing section (JSON-serializable) that rides
        in the report — obs.profiler uses this to fold per-kernel
        compiled cost attribution next to the wall-clock phases."""
        self._attached[str(key)] = doc

    # lint: host
    def add(self, name: str, seconds: float) -> None:
        """Credit `seconds` to phase `name` (for spans measured with
        an existing perf_counter pair, e.g. inside a timed rep where
        a with-block would add its own overhead between reads)."""
        if name not in self._total:
            self._total[name] = 0.0
            self._count[name] = 0
            self._order.append(name)
        self._total[name] += float(seconds)
        self._count[name] += 1

    # lint: host
    @contextmanager
    def phase(self, name: str) -> Iterator[None]:
        t0 = time.perf_counter()
        try:
            yield
        finally:
            self.add(name, time.perf_counter() - t0)

    # lint: host
    def report(self) -> dict:
        """{phase: {"seconds", "count"}} in first-entry order, plus a
        "total_seconds" rollup."""
        phases = {n: {"seconds": round(self._total[n], 6),
                      "count": self._count[n]} for n in self._order}
        doc = {"phases": phases,
               "total_seconds": round(sum(self._total.values()), 6)}
        doc.update(self._attached)
        return doc
