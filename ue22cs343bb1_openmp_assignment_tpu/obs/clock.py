"""Injectable monotonic clocks: the one time base of the serving layer.

Every serving timestamp — wave ``wall_s``, job-lifecycle span edges
(serve.SpanBook), soak arrival release — reads the SAME injected clock
object, never ``time.perf_counter()`` inline. Two reasons:

- **One time base.** A wave's ``wall_s`` and the spans of the jobs it
  ran must subtract consistently; mixing clock sources makes the span
  decomposition (queue_wait + run + extract == e2e) drift.
- **Determinism under test.** :class:`VirtualClock` never reads real
  time: it advances only by explicit, deterministic amounts (a fixed
  ``wave_s`` per wave via :meth:`on_wave`, the requested amount via
  :meth:`sleep`). A soak on a VirtualClock therefore emits
  byte-identical ``cache-sim/serve-trace/v1`` docs across runs — the
  determinism gate in tests/test_soak.py — and serving tests stop
  being wall-clock-flaky.

The protocol is three methods; anything implementing them injects:

========== ==========================================================
``now()``     current monotonic seconds (float)
``sleep(s)``  idle until ``s`` seconds pass (real sleep / virtual jump)
``on_wave()`` called once after each batched wave completes; the
              virtual clock charges its fixed per-wave cost here (the
              real clock ignores it — real time passed by itself)
========== ==========================================================

Host-side and dependency-free like the rest of obs.
"""
# lint: host

from __future__ import annotations

import time


class MonotonicClock:
    """The production time base: ``time.perf_counter``."""

    kind = "monotonic"

    # lint: host
    def now(self) -> float:
        return time.perf_counter()

    # lint: host
    def sleep(self, seconds: float) -> None:
        if seconds > 0:
            time.sleep(seconds)

    # lint: host
    def on_wave(self) -> None:
        # real time elapsed during the wave on its own
        pass


class VirtualClock(MonotonicClock):
    """Deterministic test clock: time moves only when told to.

    ``now()`` is a pure read; each completed wave costs exactly
    ``wave_s`` virtual seconds (charged by :meth:`on_wave`), and
    ``sleep`` jumps forward by the requested amount. No call ever
    reads real time, so every timestamp derived from this clock is a
    pure function of the call sequence.
    """

    kind = "virtual"

    # lint: host
    def __init__(self, t0: float = 0.0, wave_s: float = 1e-3) -> None:
        if wave_s <= 0:
            raise ValueError(f"wave_s must be > 0, got {wave_s}")
        self._t = float(t0)
        self.wave_s = float(wave_s)

    # lint: host
    def now(self) -> float:
        return self._t

    # lint: host
    def sleep(self, seconds: float) -> None:
        if seconds > 0:
            self._t += float(seconds)

    # lint: host
    def on_wave(self) -> None:
        self._t += self.wave_s
