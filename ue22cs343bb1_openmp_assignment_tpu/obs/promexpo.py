"""Prometheus text exposition of the daemon-stats snapshot.

``render`` turns one validated ``cache-sim/daemon-stats/v1`` doc (or
the ``cache-sim/fleet/v1`` merge, which shares the counter names)
into Prometheus text format 0.0.4: ``# HELP``/``# TYPE`` headers,
``cache_sim_``-prefixed counters and gauges, lane/bucket-labeled
series, and per-lane latency histograms with cumulative ``le``
buckets derived from the fixed-log-bucket histogram docs
(obs.timeseries.LogHistogram — fixed edges, so every replica exposes
the same ``le`` label set and a Prometheus ``sum by (le)`` over the
fleet is exact).

Pure dict → str, byte-deterministic for a byte-identical input doc
(sorted lanes/buckets, JSON float formatting): the promexpo golden in
tests/test_ops_plane.py pins the rendering.

Host-side and dependency-free: the future fleet router serves this
over HTTP without ever importing jax (lint:no-jax target).
"""
# lint: host

from __future__ import annotations

import json
from typing import List, Optional

PREFIX = "cache_sim"

#: (stats key, metric suffix, type, help) for the scalar top-level
#: series; counters get the conventional ``_total`` suffix
_SCALARS = (
    ("uptime_s", "uptime_seconds", "gauge",
     "Seconds since the daemon core started (its injected clock)."),
    ("stats_seq", "stats_seq", "counter",
     "Monotonic stats-snapshot sequence number."),
    ("chunks", "chunks_total", "counter",
     "Wave chunks executed across all buckets."),
    ("busy_s", "busy_seconds_total", "counter",
     "Seconds spent running wave chunks."),
    ("mb_dropped", "mb_dropped_total", "counter",
     "Mailbox messages silently dropped inside simulated machines."),
    ("mid_wave_swaps", "mid_wave_swaps_total", "counter",
     "Jobs admitted into a wave other slots were mid-flight in."),
    ("bucket_growths", "bucket_growths_total", "counter",
     "Idle shape buckets grown to cover a new job shape."),
    ("results_evicted", "results_evicted_total", "counter",
     "Terminal job payloads evicted by result retention."),
    ("slo_alerts", "slo_alerts_total", "counter",
     "Burn-rate SLO alerts injected into the event stream."),
    ("queue_depth_peak", "queue_depth_peak", "gauge",
     "Peak total admission-queue depth observed."),
    ("draining", "draining", "gauge",
     "1 when the daemon has stopped admitting (drain), else 0."),
)

_JOB_COUNTERS = (
    ("submitted", "Jobs accepted into a lane queue."),
    ("rejected", "Jobs rejected by backpressure or drain."),
    ("done", "Jobs run to extraction."),
    ("quiesced", "Done jobs that reached quiescence."),
)

_LANE_SERIES = (
    ("queued", "gauge", "Jobs waiting in the lane queue."),
    ("submitted", "counter", "Jobs accepted into this lane."),
    ("admitted", "counter", "Jobs admitted from this lane into slots."),
    ("rejected", "counter", "Jobs rejected from this lane."),
    ("done", "counter", "Jobs from this lane run to extraction."),
)

_BUCKET_SERIES = (
    ("busy", "gauge", "Slots currently occupied in this bucket."),
    ("admitted", "counter", "Jobs ever admitted into this bucket."),
    ("chunks", "counter", "Wave chunks this bucket has run."),
)


# lint: host
def _num(v) -> str:
    """Prometheus sample value: ints bare, floats via JSON (repr-
    faithful, so a byte-identical doc renders byte-identically)."""
    if isinstance(v, bool):
        return "1" if v else "0"
    if isinstance(v, int):
        return str(v)
    return json.dumps(float(v))


# lint: host
def _labels(**kv) -> str:
    if not kv:
        return ""
    inner = ",".join(f'{k}="{v}"' for k, v in sorted(kv.items()))
    return "{" + inner + "}"


# lint: host
def _head(out: List[str], name: str, mtype: str, help_: str) -> None:
    out.append(f"# HELP {name} {help_}")
    out.append(f"# TYPE {name} {mtype}")


# lint: host
def _hist_lines(out: List[str], name: str, hist: dict,
                **labels) -> None:
    """One LogHistogram doc → cumulative ``le`` bucket lines plus
    ``_sum``/``_count`` (the Prometheus histogram convention; the
    stored counts are per-bucket, so cumulate here)."""
    cum = 0
    for edge, c in zip(hist["edges_ms"], hist["counts"]):
        cum += int(c)
        out.append(f"{name}_bucket"
                   f"{_labels(le=_num(float(edge)), **labels)} {cum}")
    cum += int(hist["counts"][-1])
    out.append(f'{name}_bucket{_labels(le="+Inf", **labels)} {cum}')
    out.append(f"{name}_sum{_labels(**labels)} "
               f"{_num(float(hist['sum_ms']))}")
    out.append(f"{name}_count{_labels(**labels)} "
               f"{_num(int(hist['count']))}")


# lint: host
def render(stats: dict) -> str:
    """One daemon-stats (or fleet) doc → Prometheus text exposition.
    Keys the doc does not carry are skipped, never invented, so the
    same renderer serves v1 docs from before ``stats_seq`` existed."""
    out: List[str] = []

    jobs = stats.get("jobs") or {}
    for key, help_ in _JOB_COUNTERS:
        if key not in jobs:
            continue
        name = f"{PREFIX}_jobs_{key}_total"
        _head(out, name, "counter", help_)
        out.append(f"{name} {_num(jobs[key])}")

    for key, suffix, mtype, help_ in _SCALARS:
        if key not in stats or stats[key] is None:
            continue
        name = f"{PREFIX}_{suffix}"
        _head(out, name, mtype, help_)
        out.append(f"{name} {_num(stats[key])}")

    for key, help_ in (("padding_waste",
                        "Fraction of the slot instruction budget "
                        "spent on padding."),
                       ("single_shape_padding_waste",
                        "Counterfactual padding waste of a single "
                        "max-shape slot class.")):
        v = stats.get(key)
        if v is None:
            continue
        name = f"{PREFIX}_{key}"
        _head(out, name, "gauge", help_)
        out.append(f"{name} {_num(float(v))}")

    lanes = stats.get("lanes") or {}
    for key, mtype, help_ in _LANE_SERIES:
        rows = [(lane, ln[key]) for lane, ln in sorted(lanes.items())
                if key in ln]
        if not rows:
            continue
        suffix = "_total" if mtype == "counter" else ""
        name = f"{PREFIX}_lane_{key}{suffix}"
        _head(out, name, mtype, help_)
        for lane, v in rows:
            out.append(f"{name}{_labels(lane=lane)} {_num(v)}")

    hists = [(lane, ln.get("hist"))
             for lane, ln in sorted(lanes.items()) if ln.get("hist")]
    if hists:
        name = f"{PREFIX}_job_latency_ms"
        _head(out, name, "histogram",
              "End-to-end job latency per lane (fixed log buckets, "
              "exactly summable across replicas).")
        for lane, hist in hists:
            _hist_lines(out, name, hist, lane=lane)

    buckets = stats.get("buckets") or []
    for key, mtype, help_ in _BUCKET_SERIES:
        rows = [(b, b[key]) for b in buckets if key in b]
        if not rows:
            continue
        suffix = "_total" if mtype == "counter" else ""
        name = f"{PREFIX}_bucket_{key}{suffix}"
        _head(out, name, mtype, help_)
        for b, v in rows:
            labels = {"bucket": b.get("bucket", "?")}
            if b.get("replica") is not None:
                labels["replica"] = b["replica"]
            out.append(f"{name}{_labels(**labels)} {_num(v)}")

    return "\n".join(out) + "\n"


# lint: host
def write(path, stats: dict) -> Optional[str]:
    """Render to a file (the node-exporter textfile-collector shape);
    returns the text."""
    text = render(stats)
    with open(str(path), "w") as f:
        f.write(text)
    return text
