"""Bench-history dashboard: the perf trajectory as an artifact.

Until now the repo's performance story lived in PERF.md prose plus an
append-only JSONL nobody rendered. This module turns a bench history
(:mod:`obs.history`: ``BENCH_r*`` ingests, live ``--record`` runs,
``MULTICHIP_r*`` parity dryruns) into two self-contained files:

- **HTML** — inline-SVG charts, zero external assets, openable from a
  CI artifact tab: the headline instrs/sec trend against the 1e8
  north-star line (BASELINE.json), the serving jobs/sec trend
  (``bench.py --serve`` rows, ROADMAP item 2), the bench-diff verdict strip
  (regression/noise/improvement per adjacent pair, obs.regress), the
  per-(protocol x workload) coverage cells as ROADMAP item 4 lands,
  the sharded-parity scaling curve from the multichip dryruns, and the
  roofline scatter (arithmetic intensity vs attainable flops,
  obs.roofline) for every entry that recorded a cost vector.
- **markdown** — the same model as tables, for diffs and PR comments.

Rendering is **deterministic**: no timestamps, no environment probes —
the same history bytes produce the same report bytes, which is what
lets a golden render live under tests/golden/. Host-side and
dependency-free (string assembly only).
"""
# lint: host

from __future__ import annotations

from typing import List, Optional, Tuple

from ue22cs343bb1_openmp_assignment_tpu.obs import regress, roofline

#: the north star (BASELINE.json): simulated instrs/sec on one chip
TARGET_INSTRS_PER_S = 1e8

_W, _H = 640, 240          # chart viewport
_PAD_L, _PAD_R = 70, 16    # left gutter for axis labels
_PAD_T, _PAD_B = 18, 30

_VERDICT_COLOR = {"regression": "#c0392b", "improvement": "#1e8449",
                  "noise": "#7f8c8d", "incomparable": "#b7950b",
                  "pass": "#1e8449"}


# lint: host
def _workload_from_metric(metric: str) -> Optional[str]:
    """bench.py's metric string embeds "(<engine> engine, <workload>"
    — the only workload record archived captures carry."""
    import re
    m = re.search(r"\(\w+ engine, ([\w-]+)", metric or "")
    return m.group(1) if m else None


# lint: host
def _litmus_cells(litmus: Optional[dict]) -> list:
    """Normalize an ``analyze --litmus`` report ({protocol: {test:
    enumeration report}}, analysis/litmus.run_suite) into flat matrix
    cells sorted (test, protocol)."""
    cells = []
    for proto, tests in (litmus or {}).items():
        for name, rep in tests.items():
            cells.append({
                "protocol": proto, "test": name,
                "ok": rep.get("ok"),
                "budget_exhausted": bool(rep.get("budget_exhausted")),
                "observed": len(rep.get("observed", ())),
                "allowed": len(rep.get("allowed", ())),
                "unexpected": len(rep.get("unexpected", ()))})
    return sorted(cells, key=lambda c: (c["test"], c["protocol"]))


# lint: host
def _recording_rows(recordings: Optional[list]) -> list:
    """Normalize loaded ``cache-sim/recording/v1`` docs
    (obs.recording.load) into the captured-traffic table rows."""
    from ue22cs343bb1_openmp_assignment_tpu.obs import recording
    rows = []
    for rec in recordings or []:
        submits = [r for r in rec["rows"] if r["event"] == "submit"]
        results = [r for r in rec["rows"] if r["event"] == "result"]
        ts = [float(r["t_s"]) for r in rec["rows"]]
        lat = recording.latency_block(rec)
        rows.append({
            "label": rec.get("path") or "?",
            "clock": rec["clock"],
            "jobs": len(submits),
            "results": len(results),
            "quiesced": sum(1 for r in results if r["quiesced"]),
            "duration_s": (max(ts) - min(ts)) if ts else 0.0,
            "arrival_rate": (recording.derived_arrival_rate(rec)
                             if submits else None),
            "p95_ms": None if lat is None else lat["p95_ms"],
        })
    return rows


# lint: host
def _profile_rows(profiles: Optional[list]) -> list:
    """Normalize ``cache-sim/profile/v1`` docs (obs.cohprof) into the
    coherence-profile table rows."""
    rows = []
    for doc in profiles or []:
        mc = doc.get("miss_classes")
        ab = doc.get("abort_anatomy")
        rows.append({
            "label": (doc.get("extra") or {}).get("path")
            or f"{doc['engine']}@{doc['nodes']}",
            "engine": doc["engine"],
            "nodes": doc["nodes"],
            "steps": doc["steps"],
            "dominant": doc["sharing"]["dominant"],
            "classified_lines": doc["sharing"]["classified_lines"],
            "misses": None if mc is None else sum(mc.values()),
            "coherence_misses": None if mc is None
            else mc["coherence_invalidation"],
            "invalidations": (doc["invalidations"] or {}).get("applied")
            if doc.get("invalidations") is not None else None,
            "ghost_fraction": None if ab is None
            else ab["poison_flags"]["ghost_fraction"],
            "top_addr": (doc["top_contended"][0]["addr"]
                         if doc["top_contended"] else None),
        })
    return rows


# lint: host
def build_model(entries: List[dict],
                target: float = TARGET_INSTRS_PER_S,
                litmus: Optional[dict] = None,
                recordings: Optional[list] = None,
                profiles: Optional[list] = None) -> dict:
    """Reduce a loaded history to the renderable model.

    Splits entries into the instrs/sec headline series, the multichip
    scaling series, the bench-diff verdict strip over adjacent headline
    pairs, (protocol x workload) coverage cells (latest entry wins a
    cell; protocol defaults to "mesi" until ROADMAP item 4 records
    one), and the roofline points of every recorded cost vector.
    ``litmus`` is an optional ``analyze --litmus`` suite report; it
    becomes the protocol x test consistency matrix. ``recordings`` is
    an optional list of loaded traffic recordings (obs.recording);
    they become the captured-traffic table, each row replayable with
    ``cache-sim replay``.
    """
    bench = [e for e in entries if e.get("unit") == "instrs/sec"]
    multichip = [e for e in entries
                 if (e.get("config") or {}).get("kind") == "multichip"]
    serving = [{"label": e["label"], "value": float(e["value"]),
                "slots": (e.get("serve") or {}).get("slots"),
                "waves": (e.get("serve") or {}).get("waves"),
                "padding_waste": (e.get("serve") or {}).get(
                    "padding_waste"),
                "mb_dropped": (e.get("serve") or {}).get("mb_dropped")}
               for e in entries if e.get("unit") == "jobs/sec"]
    latency = [{"label": e["label"],
                "value": float(e["latency"]["p95_ms"]),
                "p50_ms": e["latency"]["p50_ms"],
                "p99_ms": e["latency"]["p99_ms"],
                "arrival_rate": e["latency"].get("arrival_rate"),
                "queue_depth_peak": e["latency"].get(
                    "queue_depth_peak"),
                "saturated": e["latency"].get("saturated"),
                "transport": (e.get("serve") or {}).get("transport",
                                                        "inproc")}
               for e in entries if isinstance(e.get("latency"), dict)]
    headline = [{"label": e["label"], "value": float(e["value"]),
                 "engine": (e.get("config") or {}).get("engine"),
                 "vs_target": float(e["value"]) / target}
                for e in bench]
    verdicts = []
    for a, b in zip(bench, bench[1:]):
        rep = regress.compare(a, b)
        verdicts.append({"a": a["label"], "b": b["label"],
                         "verdict": rep["verdict"],
                         "delta_pct": rep.get("delta_pct"),
                         "detail": rep.get("detail")})
    cells = {}
    for e in bench:
        cfg = e.get("config") or {}
        proto = cfg.get("protocol") or "mesi"
        wl = (cfg.get("workload")
              or _workload_from_metric(e.get("metric")) or "?")
        cells[(proto, wl)] = {"label": e["label"],
                              "value": float(e["value"])}
    points = []
    for e in bench:
        cost = e.get("cost")
        if not isinstance(cost, dict) or not cost.get("cost_available"):
            continue
        peaks = roofline.device_peaks(e.get("device_kind") or "unknown")
        for name, k in sorted((cost.get("kernels") or {}).items()):
            if not k.get("cost_available") or not k.get("hbm_bytes"):
                continue
            ai = float(k["flops"]) / float(k["hbm_bytes"])
            attainable = min(peaks["flops_per_s"],
                             ai * peaks["hbm_bytes_per_s"])
            points.append({"entry": e["label"], "kernel": name,
                           "ai": ai, "attainable_flops_per_s": attainable,
                           "device_kind": peaks["kind"],
                           "ridge": peaks["ridge_flops_per_byte"]})
    scaling = [{"label": e["label"], "nodes": float(e["value"]),
                "ok": bool((e.get("config") or {}).get("ok"))}
               for e in multichip]
    return {"target": target, "headline": headline,
            "verdicts": verdicts,
            "cells": {f"{p}/{w}": v
                      for (p, w), v in sorted(cells.items())},
            "roofline": points, "scaling": scaling,
            "serving": serving, "latency": latency,
            "litmus": _litmus_cells(litmus),
            "recordings": _recording_rows(recordings),
            "profiles": _profile_rows(profiles),
            "n_entries": len(entries)}


# lint: host
def _log_points(values: List[float], lo: float,
                hi: float) -> List[Tuple[float, float]]:
    """Map (index, value) to SVG coords on a log-10 y axis."""
    import math
    n = max(1, len(values) - 1)
    span = math.log10(hi) - math.log10(lo)
    pts = []
    for i, v in enumerate(values):
        x = _PAD_L + (_W - _PAD_L - _PAD_R) * (i / n if n else 0.5)
        fy = (math.log10(max(v, lo)) - math.log10(lo)) / span
        y = _H - _PAD_B - (_H - _PAD_T - _PAD_B) * fy
        pts.append((x, y))
    return pts


# lint: host
def _log_y(v: float, lo: float, hi: float) -> float:
    import math
    span = math.log10(hi) - math.log10(lo)
    fy = (math.log10(max(v, lo)) - math.log10(lo)) / span
    return _H - _PAD_B - (_H - _PAD_T - _PAD_B) * fy


# lint: host
def _fmt(x: float) -> str:
    return f"{x:.1f}"


# lint: host
def _decade_grid(lo: float, hi: float) -> List[float]:
    import math
    return [10.0 ** d
            for d in range(math.ceil(math.log10(lo)),
                           math.floor(math.log10(hi)) + 1)]


# lint: host
def _svg_series(title: str, series: List[dict], value_key: str,
                target: Optional[float], unit: str) -> str:
    """One log-y line chart: labeled points, decade gridlines, and an
    optional dashed target line."""
    if not series:
        return f"<p><em>{title}: no entries</em></p>"
    values = [s[value_key] for s in series]
    lo = min(values) / 2
    hi = max(values + ([target] if target else [])) * 2
    out = [f'<svg viewBox="0 0 {_W} {_H}" width="{_W}" height="{_H}" '
           f'role="img" aria-label="{title}">',
           f'<rect width="{_W}" height="{_H}" fill="#fdfefe"/>']
    for g in _decade_grid(lo, hi):
        y = _fmt(_log_y(g, lo, hi))
        out.append(f'<line x1="{_PAD_L}" y1="{y}" x2="{_W - _PAD_R}" '
                   f'y2="{y}" stroke="#eaecee"/>')
        out.append(f'<text x="{_PAD_L - 6}" y="{y}" font-size="10" '
                   f'text-anchor="end" fill="#808b96">{g:.0e}</text>')
    if target:
        ty = _fmt(_log_y(target, lo, hi))
        out.append(f'<line x1="{_PAD_L}" y1="{ty}" x2="{_W - _PAD_R}" '
                   f'y2="{ty}" stroke="#c0392b" stroke-dasharray="6 3"/>')
        out.append(f'<text x="{_W - _PAD_R}" y="{float(ty) - 4:.1f}" '
                   f'font-size="10" text-anchor="end" fill="#c0392b">'
                   f'target {target:.0e} {unit}</text>')
    pts = _log_points(values, lo, hi)
    path = " ".join(f"{'M' if i == 0 else 'L'}{_fmt(x)},{_fmt(y)}"
                    for i, (x, y) in enumerate(pts))
    out.append(f'<path d="{path}" fill="none" stroke="#2471a3" '
               f'stroke-width="2"/>')
    for s, (x, y) in zip(series, pts):
        out.append(f'<circle cx="{_fmt(x)}" cy="{_fmt(y)}" r="3.5" '
                   f'fill="#2471a3"/>')
        out.append(f'<text x="{_fmt(x)}" y="{_H - _PAD_B + 14}" '
                   f'font-size="10" text-anchor="middle" '
                   f'fill="#566573">{s["label"]}</text>')
        out.append(f'<text x="{_fmt(x)}" y="{float(_fmt(y)) - 7:.1f}" '
                   f'font-size="9" text-anchor="middle" '
                   f'fill="#1a5276">{s[value_key]:.3g}</text>')
    out.append("</svg>")
    return "\n".join(out)


# lint: host
def _svg_roofline(points: List[dict]) -> str:
    """Log-log roofline scatter: the bandwidth slope + compute roof of
    each device kind present, with one dot per (entry, kernel)."""
    import math
    if not points:
        return ("<p><em>roofline: no cost vectors recorded yet "
                "(bench.py --record on a cost-model backend)</em></p>")
    ais = [p["ai"] for p in points]
    ai_lo, ai_hi = min(ais + [0.1]) / 4, max(ais + [100.0]) * 4
    devices = {}
    for p in points:
        devices[p["device_kind"]] = roofline.device_peaks(
            p["device_kind"])
    f_hi = max(d["flops_per_s"] for d in devices.values()) * 2
    f_lo = min(min(p["attainable_flops_per_s"] for p in points),
               min(ai_lo * d["hbm_bytes_per_s"]
                   for d in devices.values())) / 2

    def xc(ai):
        fx = ((math.log10(ai) - math.log10(ai_lo))
              / (math.log10(ai_hi) - math.log10(ai_lo)))
        return _PAD_L + (_W - _PAD_L - _PAD_R) * fx

    out = [f'<svg viewBox="0 0 {_W} {_H}" width="{_W}" height="{_H}" '
           f'role="img" aria-label="roofline">',
           f'<rect width="{_W}" height="{_H}" fill="#fdfefe"/>']
    for g in _decade_grid(f_lo, f_hi):
        y = _fmt(_log_y(g, f_lo, f_hi))
        out.append(f'<line x1="{_PAD_L}" y1="{y}" x2="{_W - _PAD_R}" '
                   f'y2="{y}" stroke="#eaecee"/>')
        out.append(f'<text x="{_PAD_L - 6}" y="{y}" font-size="10" '
                   f'text-anchor="end" fill="#808b96">{g:.0e}</text>')
    for kind, d in sorted(devices.items()):
        ridge = d["ridge_flops_per_byte"]
        # bandwidth slope up to the ridge, flat compute roof after
        y0 = _fmt(_log_y(max(ai_lo * d["hbm_bytes_per_s"], f_lo),
                         f_lo, f_hi))
        yr = _fmt(_log_y(d["flops_per_s"], f_lo, f_hi))
        out.append(f'<path d="M{_fmt(xc(ai_lo))},{y0} '
                   f'L{_fmt(xc(ridge))},{yr} '
                   f'L{_fmt(xc(ai_hi))},{yr}" fill="none" '
                   f'stroke="#784212" stroke-width="1.5"/>')
        out.append(f'<text x="{_fmt(xc(ridge))}" '
                   f'y="{float(yr) - 5:.1f}" font-size="10" '
                   f'fill="#784212">{kind} '
                   f'(ridge {ridge:.1f} flop/B)</text>')
    for p in points:
        x = _fmt(xc(p["ai"]))
        y = _fmt(_log_y(p["attainable_flops_per_s"], f_lo, f_hi))
        out.append(f'<circle cx="{x}" cy="{y}" r="4" fill="#2471a3" '
                   f'fill-opacity="0.8"/>')
        out.append(f'<text x="{x}" y="{float(y) + 14:.1f}" '
                   f'font-size="9" text-anchor="middle" '
                   f'fill="#566573">{p["entry"]}:{p["kernel"]}</text>')
    out.append(f'<text x="{_W // 2}" y="{_H - 4}" font-size="10" '
               f'text-anchor="middle" fill="#808b96">arithmetic '
               f'intensity (flops/byte, log)</text>')
    out.append("</svg>")
    return "\n".join(out)


# lint: host
def _litmus_cell_text(c: dict) -> str:
    """pass/fail/outcome-count rendering shared by both artifacts."""
    if c["budget_exhausted"]:
        return "budget"
    tag = "ok" if c["ok"] else "FAIL"
    return f"{tag} ({c['observed']}/{c['allowed']})"


# lint: host
def _litmus_html(cells: list) -> str:
    if not cells:
        return ("<p><em>no litmus report loaded (cache-sim analyze "
                "--litmus --json, then dashboard --litmus "
                "report.json)</em></p>")
    protos = sorted({c["protocol"] for c in cells})
    tests = sorted({c["test"] for c in cells})
    by = {(c["test"], c["protocol"]): c for c in cells}
    head = "".join(f"<th>{p}</th>" for p in protos)
    rows = []
    for t in tests:
        tds = []
        for p in protos:
            c = by.get((t, p))
            if c is None:
                tds.append("<td>—</td>")
                continue
            color = ("#b7950b" if c["budget_exhausted"]
                     else "#1e8449" if c["ok"] else "#c0392b")
            tds.append(f'<td style="color:{color}">'
                       f'{_litmus_cell_text(c)}</td>')
        rows.append(f"<tr><td>{t}</td>{''.join(tds)}</tr>")
    return (f"<table><tr><th>test</th>{head}</tr>"
            + "".join(rows) + "</table>")


# lint: host
def _recordings_html(rows: list) -> str:
    if not rows:
        return ("<p><em>no recordings loaded (capture with "
                "cache-sim daemon --record DIR, then dashboard "
                "--recording DIR)</em></p>")
    trs = []
    for r in rows:
        rate = ("—" if r["arrival_rate"] is None
                else f"{r['arrival_rate']:g}/s")
        p95 = "—" if r["p95_ms"] is None else f"{r['p95_ms']:.4g} ms"
        trs.append(f"<tr><td>{r['label']}</td><td>{r['clock']}</td>"
                   f"<td>{r['jobs']}</td>"
                   f"<td>{r['quiesced']}/{r['results']}</td>"
                   f"<td>{r['duration_s']:.4g} s</td>"
                   f"<td>{rate}</td><td>{p95}</td></tr>")
    return ("<table><tr><th>recording</th><th>clock</th>"
            "<th>jobs</th><th>quiesced/results</th><th>window</th>"
            "<th>offered load</th><th>recorded p95</th></tr>"
            + "".join(trs) + "</table>"
            "<p>replay any row with <code>cache-sim replay "
            "&lt;recording&gt;</code>.</p>")


# lint: host
def _profiles_html(rows: list) -> str:
    if not rows:
        return ("<p><em>no profiles loaded (capture with cache-sim "
                "profile --json --out p.json, then dashboard "
                "--profile p.json)</em></p>")
    trs = []
    for r in rows:
        miss = "—" if r["misses"] is None else (
            f"{r['misses']} ({r['coherence_misses']} coh)")
        inv = ("—" if r["invalidations"] is None
               else f"{r['invalidations']}")
        gf = ("—" if r["ghost_fraction"] is None
              else f"{r['ghost_fraction']:.1%}")
        trs.append(f"<tr><td>{r['label']}</td><td>{r['engine']}</td>"
                   f"<td>{r['nodes']}</td><td>{r['steps']}</td>"
                   f"<td>{r['dominant'] or '—'} "
                   f"({r['classified_lines']} lines)</td>"
                   f"<td>{miss}</td><td>{inv}</td><td>{gf}</td></tr>")
    return ("<table><tr><th>profile</th><th>engine</th><th>nodes</th>"
            "<th>steps</th><th>dominant sharing</th><th>misses</th>"
            "<th>invalidations</th><th>ghost poison</th></tr>"
            + "".join(trs) + "</table>")


# lint: host
def render_html(model: dict) -> str:
    """The self-contained static HTML report."""
    rows = []
    for v in model["verdicts"]:
        c = _VERDICT_COLOR.get(v["verdict"], "#7f8c8d")
        d = ("" if v["delta_pct"] is None
             else f' ({v["delta_pct"]:+.2f}%)')
        why = f' — {v["detail"]}' if v.get("detail") else ""
        rows.append(f'<li><span style="color:{c};font-weight:bold">'
                    f'{v["verdict"].upper()}</span> '
                    f'{v["a"]} &rarr; {v["b"]}{d}{why}</li>')
    verdict_html = ("<ul>" + "".join(rows) + "</ul>") if rows else \
        "<p><em>fewer than two headline entries</em></p>"
    cell_rows = "".join(
        f"<tr><td>{k}</td><td>{v['label']}</td>"
        f"<td>{v['value']:.3g} instrs/sec</td></tr>"
        for k, v in model["cells"].items())
    return f"""<!DOCTYPE html>
<html lang="en"><head><meta charset="utf-8">
<title>cache-sim bench dashboard</title>
<style>
body {{ font-family: -apple-system, 'Segoe UI', sans-serif;
        margin: 2em auto; max-width: 52em; color: #212f3d; }}
h1, h2 {{ color: #1a5276; }}
table {{ border-collapse: collapse; }}
td, th {{ border: 1px solid #d5dbdb; padding: 4px 10px;
          font-size: 14px; }}
</style></head><body>
<h1>cache-sim bench dashboard</h1>
<p>{model["n_entries"]} history entries; north star:
{model["target"]:.0e} simulated instrs/sec on one chip
(BASELINE.json).</p>
<h2>Headline: simulated instrs/sec</h2>
{_svg_series("headline", model["headline"], "value",
             model["target"], "instrs/sec")}
<h2>Serving throughput (jobs/sec)</h2>
{_svg_series("serving", model["serving"], "value", None, "jobs/sec")}
<h2>Open-loop job latency (p95 ms)</h2>
{_svg_series("latency", model["latency"], "value", None, "ms p95")}
<h2>Recordings (captured traffic)</h2>
{_recordings_html(model["recordings"])}
<h2>Coherence profiles (sharing &amp; abort anatomy)</h2>
{_profiles_html(model["profiles"])}
<h2>bench-diff verdicts (adjacent pairs)</h2>
{verdict_html}
<h2>Coverage: protocol &times; workload</h2>
<table><tr><th>cell</th><th>latest</th><th>value</th></tr>
{cell_rows}</table>
<h2>Multichip sharded parity (scaling dryruns)</h2>
{_svg_series("scaling", model["scaling"], "nodes", None, "nodes")}
<h2>Litmus matrix: protocol &times; consistency test</h2>
{_litmus_html(model["litmus"])}
<h2>Roofline (recorded cost vectors)</h2>
{_svg_roofline(model["roofline"])}
</body></html>
"""


# lint: host
def render_markdown(model: dict) -> str:
    """The same model as markdown tables (PR-comment surface)."""
    lines = ["# cache-sim bench dashboard", "",
             f"{model['n_entries']} history entries; north star "
             f"{model['target']:.0e} instrs/sec (BASELINE.json).", "",
             "## Headline (simulated instrs/sec)", "",
             "| entry | engine | instrs/sec | vs target |",
             "|---|---|---:|---:|"]
    for h in model["headline"]:
        lines.append(f"| {h['label']} | {h['engine'] or '?'} "
                     f"| {h['value']:.4g} | {h['vs_target']:.2%} |")
    lines += ["", "## Serving throughput (jobs/sec)", ""]
    if model["serving"]:
        lines += ["| entry | slots | jobs/sec | padding waste "
                  "| waves | mb dropped |",
                  "|---|---:|---:|---:|---:|---:|"]
        for s in model["serving"]:
            slots = "?" if s["slots"] is None else f"{s['slots']}"
            pw = ("?" if s["padding_waste"] is None
                  else f"{s['padding_waste']:.1%}")
            waves = "?" if s["waves"] is None else f"{s['waves']}"
            mbd = ("?" if s["mb_dropped"] is None
                   else f"{s['mb_dropped']}")
            lines.append(f"| {s['label']} | {slots} "
                         f"| {s['value']:.4g} | {pw} "
                         f"| {waves} | {mbd} |")
    else:
        lines.append("*no serving entries yet (bench.py --serve "
                     "--record)*")
    lines += ["", "## Open-loop job latency (p95 ms)", ""]
    if model["latency"]:
        lines += ["| entry | transport | arrival rate | p50 ms "
                  "| p95 ms | p99 ms | queue peak | saturated |",
                  "|---|---|---:|---:|---:|---:|---:|---|"]
        for l in model["latency"]:
            rate = ("?" if l["arrival_rate"] is None
                    else f"{l['arrival_rate']:g}/s")
            qp = ("?" if l["queue_depth_peak"] is None
                  else f"{l['queue_depth_peak']}")
            sat = ("?" if l["saturated"] is None
                   else ("yes" if l["saturated"] else "no"))
            lines.append(f"| {l['label']} | {l['transport']} | {rate} "
                         f"| {l['p50_ms']:.4g} | {l['value']:.4g} "
                         f"| {l['p99_ms']:.4g} | {qp} | {sat} |")
    else:
        lines.append("*no latency entries yet (bench.py --soak "
                     "--record)*")
    lines += ["", "## Recordings (captured traffic)", ""]
    if model["recordings"]:
        lines += ["| recording | clock | jobs | quiesced/results "
                  "| window | offered load | recorded p95 |",
                  "|---|---|---:|---:|---:|---:|---:|"]
        for r in model["recordings"]:
            rate = ("—" if r["arrival_rate"] is None
                    else f"{r['arrival_rate']:g}/s")
            p95 = ("—" if r["p95_ms"] is None
                   else f"{r['p95_ms']:.4g} ms")
            lines.append(f"| {r['label']} | {r['clock']} | {r['jobs']} "
                         f"| {r['quiesced']}/{r['results']} "
                         f"| {r['duration_s']:.4g} s | {rate} "
                         f"| {p95} |")
        lines.append("")
        lines.append("replay any row with `cache-sim replay "
                     "<recording>`.")
    else:
        lines.append("*no recordings loaded (capture with cache-sim "
                     "daemon --record DIR, then dashboard "
                     "--recording DIR)*")
    lines += ["", "## Coherence profiles (sharing & abort anatomy)",
              ""]
    if model["profiles"]:
        lines += ["| profile | engine | nodes | steps "
                  "| dominant sharing | misses | invalidations "
                  "| ghost poison |",
                  "|---|---|---:|---:|---|---:|---:|---:|"]
        for r in model["profiles"]:
            miss = "—" if r["misses"] is None else (
                f"{r['misses']} ({r['coherence_misses']} coh)")
            inv = ("—" if r["invalidations"] is None
                   else f"{r['invalidations']}")
            gf = ("—" if r["ghost_fraction"] is None
                  else f"{r['ghost_fraction']:.1%}")
            lines.append(
                f"| {r['label']} | {r['engine']} | {r['nodes']} "
                f"| {r['steps']} | {r['dominant'] or '—'} "
                f"({r['classified_lines']} lines) | {miss} "
                f"| {inv} | {gf} |")
    else:
        lines.append("*no profiles loaded (capture with cache-sim "
                     "profile --json --out p.json, then dashboard "
                     "--profile p.json)*")
    lines += ["", "## bench-diff verdicts (adjacent pairs)", ""]
    if model["verdicts"]:
        lines += ["| pair | verdict | delta |", "|---|---|---:|"]
        for v in model["verdicts"]:
            d = ("—" if v["delta_pct"] is None
                 else f"{v['delta_pct']:+.2f}%")
            why = f" ({v['detail']})" if v.get("detail") else ""
            lines.append(f"| {v['a']} → {v['b']} "
                         f"| {v['verdict']}{why} | {d} |")
    else:
        lines.append("*fewer than two headline entries*")
    lines += ["", "## Coverage: protocol × workload", "",
              "| cell | latest | instrs/sec |", "|---|---|---:|"]
    for k, v in model["cells"].items():
        lines.append(f"| {k} | {v['label']} | {v['value']:.4g} |")
    lines += ["", "## Multichip sharded parity", ""]
    if model["scaling"]:
        lines += ["| round | max nodes bit-identical | ok |",
                  "|---|---:|---|"]
        for s in model["scaling"]:
            lines.append(f"| {s['label']} | {s['nodes']:.0f} "
                         f"| {'yes' if s['ok'] else 'no'} |")
    else:
        lines.append("*no multichip dryruns ingested*")
    lines += ["", "## Litmus matrix (protocol × consistency test)", ""]
    if model["litmus"]:
        lines += ["| test | protocol | outcome sets | verdict |",
                  "|---|---|---:|---|"]
        for c in model["litmus"]:
            lines.append(f"| {c['test']} | {c['protocol']} "
                         f"| {c['observed']}/{c['allowed']} "
                         f"| {_litmus_cell_text(c)} |")
    else:
        lines.append("*no litmus report loaded (cache-sim analyze "
                     "--litmus --json, then dashboard --litmus "
                     "report.json)*")
    lines += ["", "## Roofline points", ""]
    if model["roofline"]:
        lines += ["| entry | kernel | AI (flop/B) | attainable flop/s "
                  "| device |", "|---|---|---:|---:|---|"]
        for p in model["roofline"]:
            lines.append(
                f"| {p['entry']} | {p['kernel']} | {p['ai']:.3f} "
                f"| {p['attainable_flops_per_s']:.3g} "
                f"| {p['device_kind']} |")
    else:
        lines.append("*no cost vectors recorded yet "
                     "(bench.py --record on a cost-model backend)*")
    return "\n".join(lines) + "\n"


# lint: host
def render(entries: List[dict], html_path: Optional[str] = None,
           md_path: Optional[str] = None,
           litmus: Optional[dict] = None,
           recordings: Optional[list] = None,
           profiles: Optional[list] = None) -> dict:
    """Build the model and write the requested artifacts; returns
    ``{"model", "html_path", "md_path"}``."""
    model = build_model(entries, litmus=litmus, recordings=recordings,
                        profiles=profiles)
    if html_path:
        with open(html_path, "w") as f:
            f.write(render_html(model))
    if md_path:
        with open(md_path, "w") as f:
            f.write(render_markdown(model))
    return {"model": model, "html_path": html_path, "md_path": md_path}
