"""obs — the observability layer (host-side).

One event/metric surface for all engines:

- :mod:`obs.schema` — the unified ``cache-sim/metrics/v1`` report every
  ``--metrics`` path and ``cache-sim stats`` emits, with adapters from
  each engine's native counter dict and a dependency-free validator.
- :mod:`obs.perfetto` — Chrome/Perfetto trace-event JSON export of
  eventlog records (per-node instr and msg tracks; open in
  ui.perfetto.dev).
- :mod:`obs.phases` — wall-clock phase timers (compile / dispatch /
  device_get sync), wired into bench.py.
- :mod:`obs.timeseries` — host rendering of the on-device telemetry
  samples (ops.step.run_cycles_telemetry).
- :mod:`obs.history` — append-only ``cache-sim/bench/v1.2`` benchmark
  history (full rep vectors + config fingerprint + git sha + device
  kind / HLO fingerprint / cost vector; v1 and v1.1 entries validate
  unchanged), fed by ``bench.py --record`` and by ingesting archived
  ``BENCH_r*.json`` and ``MULTICHIP_r*.json`` captures.
- :mod:`obs.regress` — noise-aware bench comparator (exact
  Mann-Whitney U on rep times + a practical bar from recorded rep
  spread), the brain of ``cache-sim bench-diff``; plus the exact
  bytes/instr comparator behind ``bench-diff --bytes`` (deterministic
  cost vectors need no statistics).
- :mod:`obs.roofline` — roofline memory-traffic attribution (Williams
  et al., PAPERS.md): per-kernel flops / HBM bytes / arithmetic
  intensity vs device peaks, bytes per simulated instruction, and the
  HBM/compute/dispatch bound classification behind ``cache-sim
  perf-report``.
- :mod:`obs.dashboard` — deterministic self-contained HTML + markdown
  render of the bench history (headline vs the 1e8 target, verdict
  strip, coverage cells, multichip scaling curve, roofline scatter).
- :mod:`obs.profiler` — ``jax.profiler`` trace capture around engine
  runs, per-kernel compiled cost attribution folded into PhaseTimer
  reports, and the timer self-check re-asserting PERF.md's
  ``block_until_ready``-can-lie lesson.
- :mod:`obs.flight` — failure flight recorder: ring buffer of the
  last K cycles of telemetry; dumps replayable incident dirs (metrics
  doc + Perfetto trace + analysis/shrink repro) on invariant trips,
  watchdog hangs, and fuzzer findings.
- :mod:`obs.cli` — the ``cache-sim stats`` / ``cache-sim trace`` /
  ``cache-sim bench-diff`` / ``cache-sim perf-report`` /
  ``cache-sim dashboard`` subcommands.

Everything in this package is host-side: it renders device arrays after
the run; nothing here is traced (the on-device capture lives in
ops/step.py where the jit discipline applies).
"""

from ue22cs343bb1_openmp_assignment_tpu.obs.phases import PhaseTimer
from ue22cs343bb1_openmp_assignment_tpu.obs.schema import (
    SCHEMA_ID,
    from_async,
    from_native,
    from_sync,
    validate,
)

__all__ = ["PhaseTimer", "SCHEMA_ID", "from_async", "from_native",
           "from_sync", "validate"]
