"""obs — the observability layer (host-side).

One event/metric surface for all engines:

- :mod:`obs.schema` — the unified ``cache-sim/metrics/v1`` report every
  ``--metrics`` path and ``cache-sim stats`` emits, with adapters from
  each engine's native counter dict and a dependency-free validator.
- :mod:`obs.perfetto` — Chrome/Perfetto trace-event JSON export of
  eventlog records (per-node instr and msg tracks; open in
  ui.perfetto.dev).
- :mod:`obs.phases` — wall-clock phase timers (compile / dispatch /
  device_get sync), wired into bench.py.
- :mod:`obs.timeseries` — host rendering of the on-device telemetry
  samples (ops.step.run_cycles_telemetry).
- :mod:`obs.cli` — the ``cache-sim stats`` / ``cache-sim trace``
  subcommands.

Everything in this package is host-side: it renders device arrays after
the run; nothing here is traced (the on-device capture lives in
ops/step.py where the jit discipline applies).
"""

from ue22cs343bb1_openmp_assignment_tpu.obs.phases import PhaseTimer
from ue22cs343bb1_openmp_assignment_tpu.obs.schema import (
    SCHEMA_ID,
    from_async,
    from_native,
    from_sync,
    validate,
)

__all__ = ["PhaseTimer", "SCHEMA_ID", "from_async", "from_native",
           "from_sync", "validate"]
