"""Fleet view: N replicas' stats merged into one validated doc.

ROADMAP item 2's fleet is "N daemon replicas behind a thin router,
per-replica stats aggregated into one fleet view" — this module is
that aggregation, landed before the router exists: ``merge_stats``
folds N ``cache-sim/daemon-stats/v1`` snapshots into one
``cache-sim/fleet/v1`` doc, and ``main`` is the ``cache-sim top ADDR
[ADDR ...]`` CLI that polls live daemons for it.

The merge is EXACT, not approximate: lifetime counters (jobs, lane
totals, chunks, busy_s, evictions, alerts) are integer/float sums;
per-lane latency histograms share the fixed edge set
(obs.timeseries.HIST_EDGES_MS), so the fleet histogram is an
elementwise count sum — never a lossy re-bucketing. Gauges reduce the
only way that is fleet-meaningful: ``uptime_s`` is the oldest
replica, ``queue_depth_peak`` the worst replica, ``draining`` true if
ANY replica is draining. Buckets keep their per-replica identity (two
replicas' "mesi:8x64" classes are different compiled programs) and
are tagged with the replica label instead of summed.

Everything here is host-side and jax-free (socket + json + dicts):
the future router imports this module, so it is a ``lint:no-jax``
target like daemon/server.py. The histogram merge is therefore a
small inline re-statement of obs.timeseries.merge_hist_docs —
timeseries transitively imports the accelerator runtime and must not
be imported from here.
"""
# lint: host

from __future__ import annotations

import json
import sys
import time
from typing import List, Optional, Sequence

from ue22cs343bb1_openmp_assignment_tpu.obs import schema

#: counters summed exactly across replicas at the doc top level
_SUM_KEYS = ("chunks", "busy_s", "mb_dropped", "mid_wave_swaps",
             "bucket_growths", "results_evicted", "slo_alerts")

_LANE_SUM_KEYS = ("queued", "submitted", "admitted", "rejected",
                  "done")


# lint: host
def _merge_hists(docs: Sequence[Optional[dict]]) -> Optional[dict]:
    """Exact elementwise merge of LogHistogram docs (the inline
    jax-free twin of obs.timeseries.merge_hist_docs — same contract,
    same refusal on mismatched edges)."""
    docs = [d for d in docs if d]
    if not docs:
        return None
    edges = docs[0]["edges_ms"]
    counts = [0] * len(docs[0]["counts"])
    count = 0
    sum_ms = 0.0
    for d in docs:
        if d["edges_ms"] != edges or len(d["counts"]) != len(counts):
            raise ValueError("histogram docs have mismatched bucket "
                             "edges — refusing a lossy merge")
        for i, c in enumerate(d["counts"]):
            counts[i] += int(c)
        count += int(d["count"])
        sum_ms += float(d["sum_ms"])
    return {"edges_ms": list(edges), "counts": counts,
            "count": count, "sum_ms": sum_ms}


# lint: host
def merge_stats(stats_docs: Sequence[dict],
                labels: Optional[Sequence[str]] = None) -> dict:
    """N per-replica stats docs → one validated fleet doc.

    ``labels`` names each replica (defaults to ``r0..rN-1``; the CLI
    passes the address). Counters are exact sums; the per-replica
    provenance rides in ``per_replica`` so nothing is lost in the
    fold."""
    if not stats_docs:
        raise ValueError("fleet merge needs at least one stats doc")
    if labels is None:
        labels = [f"r{i}" for i in range(len(stats_docs))]
    if len(labels) != len(stats_docs):
        raise ValueError(f"{len(labels)} labels for "
                         f"{len(stats_docs)} stats docs")
    for i, s in enumerate(stats_docs):
        schema.validate_daemon_stats(s)

    jobs = {k: sum(int(s["jobs"][k]) for s in stats_docs)
            for k in ("submitted", "rejected", "done", "quiesced")}

    lane_names = sorted({name for s in stats_docs
                         for name in s["lanes"]})
    lanes = {}
    for name in lane_names:
        rows = [s["lanes"][name] for s in stats_docs
                if name in s["lanes"]]
        lane = {k: sum(int(r[k]) for r in rows)
                for k in _LANE_SUM_KEYS}
        lane["replicas"] = len(rows)
        lane["hist"] = _merge_hists([r.get("hist") for r in rows])
        lanes[name] = lane

    buckets = []
    for label, s in zip(labels, stats_docs):
        for b in s["buckets"]:
            buckets.append({**b, "replica": label})

    sums = {k: sum(s.get(k) or 0 for s in stats_docs)
            for k in _SUM_KEYS}
    busy_s = float(sums["busy_s"])
    doc = {
        "schema": schema.FLEET_SCHEMA_ID,
        "replicas": len(stats_docs),
        "jobs": jobs,
        "lanes": lanes,
        "buckets": buckets,
        "chunks": int(sums["chunks"]),
        "busy_s": busy_s,
        "drain_rate_jobs_per_s": (jobs["done"] / busy_s
                                  if busy_s > 0 else 0.0),
        "mb_dropped": int(sums["mb_dropped"]),
        "mid_wave_swaps": int(sums["mid_wave_swaps"]),
        "bucket_growths": int(sums["bucket_growths"]),
        "results_evicted": int(sums["results_evicted"]),
        "slo_alerts": int(sums["slo_alerts"]),
        "uptime_s": max(float(s["uptime_s"]) for s in stats_docs),
        "queue_depth_peak": max(int(s["queue_depth_peak"])
                                for s in stats_docs),
        "draining": any(s["draining"] for s in stats_docs),
        "per_replica": [
            {
                "replica": label,
                "clock": s["clock"],
                "stats_seq": s.get("stats_seq"),
                "uptime_s": s["uptime_s"],
                "jobs": dict(s["jobs"]),
                "queued": sum(int(ln["queued"])
                              for ln in s["lanes"].values()),
                "chunks": s["chunks"],
                "draining": s["draining"],
                "slo_alerts": s.get("slo_alerts", 0),
            }
            for label, s in zip(labels, stats_docs)
        ],
    }
    return schema.validate_fleet(doc)


# lint: host
def render_top(doc: dict) -> str:
    """The fleet doc as the ``top``-style text block (one line per
    replica, one totals line)."""
    out = []
    out.append(f"fleet: {doc['replicas']} replica(s)  "
               f"up={doc['uptime_s']:.3f}s  "
               f"draining={'yes' if doc['draining'] else 'no'}")
    hdr = (f"{'REPLICA':<28} {'SEQ':>5} {'UP(S)':>9} {'QUEUED':>6} "
           f"{'DONE':>6} {'REJ':>5} {'CHUNKS':>7} {'ALERTS':>6}")
    out.append(hdr)
    for r in doc["per_replica"]:
        seq = r.get("stats_seq")
        out.append(f"{r['replica']:<28} "
                   f"{'-' if seq is None else seq:>5} "
                   f"{r['uptime_s']:>9.3f} {r['queued']:>6} "
                   f"{r['jobs']['done']:>6} "
                   f"{r['jobs']['rejected']:>5} {r['chunks']:>7} "
                   f"{r['slo_alerts']:>6}")
    jobs = doc["jobs"]
    out.append(f"{'TOTAL':<28} {'':>5} {doc['uptime_s']:>9.3f} "
               f"{sum(ln['queued'] for ln in doc['lanes'].values()):>6} "
               f"{jobs['done']:>6} {jobs['rejected']:>5} "
               f"{doc['chunks']:>7} {doc['slo_alerts']:>6}")
    for name in sorted(doc["lanes"]):
        ln = doc["lanes"][name]
        hist = ln.get("hist")
        lat = ""
        if hist and hist["count"]:
            lat = (f"  mean={hist['sum_ms'] / hist['count']:.3f}ms "
                   f"over {hist['count']} job(s)")
        out.append(f"  lane {name:<12} queued={ln['queued']:<4} "
                   f"done={ln['done']:<5} rejected={ln['rejected']:<4}"
                   f"{lat}")
    return "\n".join(out)


# lint: host
def _poll(addrs: List[str], wait_up: Optional[float]) -> dict:
    """One fleet snapshot over live sockets (stats op per replica)."""
    from ue22cs343bb1_openmp_assignment_tpu.daemon.client import (
        DaemonClient)
    docs = []
    for addr in addrs:
        with DaemonClient(addr) as client:
            if wait_up is not None:
                client.wait_up(wait_up)
            docs.append(client.stats())
    return merge_stats(docs, labels=addrs)


# lint: host
def main(argv=None) -> int:
    """``cache-sim top`` entry point: the fleet-view aggregator."""
    import argparse
    ap = argparse.ArgumentParser(
        prog="cache-sim top",
        description="aggregate N running daemons' stats into one "
                    "validated cache-sim/fleet/v1 view (exact "
                    "counter sums, worst-replica gauges)")
    ap.add_argument("addrs", nargs="+", metavar="ADDR",
                    help="replica addresses: unix socket paths or "
                         "tcp:HOST:PORT")
    ap.add_argument("--once", action="store_true",
                    help="one deterministic snapshot, then exit "
                         "(tests/goldens; default follows forever)")
    ap.add_argument("--interval", type=float, default=2.0, metavar="S",
                    help="refresh cadence in follow mode (default 2)")
    ap.add_argument("--wait-up", type=float, default=None, metavar="S",
                    help="retry-connect for up to S seconds first")
    ap.add_argument("--json", action="store_true",
                    help="print the fleet doc as JSON instead of the "
                         "top-style table")
    ap.add_argument("--prom", action="store_true",
                    help="print Prometheus text exposition of the "
                         "fleet doc (obs.promexpo) instead of the "
                         "table")
    args = ap.parse_args(argv)

    while True:
        doc = _poll(args.addrs, args.wait_up)
        if args.json:
            print(json.dumps(doc, indent=2, sort_keys=True))
        elif args.prom:
            from ue22cs343bb1_openmp_assignment_tpu.obs import promexpo
            sys.stdout.write(promexpo.render(doc))
        else:
            print(render_top(doc))
        if args.once:
            return 0
        sys.stdout.flush()
        time.sleep(args.interval)
        if not (args.json or args.prom):
            print()


if __name__ == "__main__":
    raise SystemExit(main())
