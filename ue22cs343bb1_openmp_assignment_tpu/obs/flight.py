"""Failure flight recorder: the last K cycles of telemetry, always on
hand when something goes wrong.

An invariant trip, a hang-watchdog firing, or a fuzzer finding used to
leave behind one exit code and whatever the operator could reconstruct
by hand. The flight recorder turns each of those into a self-contained
**incident directory**:

- ``incident.json`` — ``cache-sim/incident/v1``: the reason, the
  validated ``cache-sim/metrics/v1`` doc of the final state, and the
  ring buffer of the last K cycles of telemetry (per-cycle counter
  deltas, queue watermarks, directory occupancy — the same on-device
  series behind ``cache-sim stats --timeseries``), plus a
  ``txn_summary`` from the causal tracer (obs.txntrace): the slowest
  five transactions of the incident's tail with their latency
  decomposition and every transaction still in flight when the
  recorder stopped, and a ``profile`` block — the validated
  ``cache-sim/profile/v1`` coherence profile (obs.cohprof) of the
  replayed run: which lines were contended, how they were missing,
  and what sharing pattern they exhibit;
- ``trace.perfetto.json`` — a validated Perfetto event trace of the
  run replayed from the initial state (the engine is deterministic, so
  the replay IS the incident);
- ``core_<n>.txt`` + ``repro.json`` — when the incident came from a
  fuzz case, the exact ``cache-sim/repro/v1`` fixture format
  analysis/shrink.py emits, so :func:`replay_incident` (and the
  reference simulator itself) can re-run it. ``cache-sim replay
  <dir>`` is the front door: it detects a flight incident among the
  other captured artifact kinds and calls :func:`replay_incident`.

The ring is captured by looping ``ops.step.run_cycles_telemetry`` in
small chunks host-side and keeping only the last K samples — memory is
O(K), not O(run length), which is what makes "always on" affordable.
``message_phase`` threads through so mutant (fuzzer) runs record the
mutant engine, not the clean one.

Host-side orchestration only; the per-cycle capture itself stays in
the jitted scan in ops/step.py. Imports of analysis/* are lazy to keep
obs free of an import cycle (analysis already imports obs).
"""
# lint: host

from __future__ import annotations

import json
import os
from typing import Callable, List, Optional

import numpy as np

SCHEMA_ID = "cache-sim/incident/v1"

#: default ring depth (cycles of telemetry kept)
DEFAULT_RING = 64

#: cycles replayed into the incident's Perfetto trace (matches
#: analysis/shrink.py's TRACE_CYCLES budget)
TRACE_CYCLES = 256


class FlightRecorder:
    """Run the async engine with a bounded telemetry ring.

    ``FlightRecorder(cfg, state0)`` snapshots the initial state (for
    deterministic replay), then :meth:`run` advances in ``chunk``-cycle
    telemetry scans, retaining the last ``k`` per-cycle samples.
    """

    # lint: host
    def __init__(self, cfg, state0, k: int = DEFAULT_RING,
                 chunk: int = 16,
                 message_phase: Optional[Callable] = None) -> None:
        if k < 1 or chunk < 1:
            raise ValueError(f"k and chunk must be >=1, got {k}, {chunk}")
        self.cfg = cfg
        self.state0 = state0
        self.state = state0
        self.k = int(k)
        self.chunk = int(chunk)
        self.message_phase = message_phase
        self.cycles_run = 0
        self._ring: List[dict] = []   # chunk samples, newest last

    # lint: host
    def run(self, max_cycles: int, stop_on_quiescence: bool = True):
        """Advance up to ``max_cycles`` cycles (chunk granularity, so
        up to chunk-1 overshoot — same contract as run_chunked_to_
        quiescence); returns the final state."""
        from ue22cs343bb1_openmp_assignment_tpu.ops import step
        done = 0
        while done < max_cycles:
            if stop_on_quiescence and bool(self.state.quiescent()):
                break
            n = min(self.chunk, max_cycles - done)
            # chunk size is a static argnum: stick to self.chunk when
            # possible so the scan compiles once, not per remainder
            n = self.chunk if max_cycles - done >= self.chunk else n
            self.state, telem = step.run_cycles_telemetry(
                self.cfg, self.state, n, self.message_phase)
            self._ring.append(
                {kk: np.asarray(v) for kk, v in telem.items()})
            done += n
            excess = sum(s["counters"].shape[0]
                         for s in self._ring) - self.k
            while excess > 0 and self._ring:
                head = self._ring[0]
                hlen = head["counters"].shape[0]
                if hlen <= excess:
                    self._ring.pop(0)
                    excess -= hlen
                else:
                    self._ring[0] = {kk: v[hlen - excess:]
                                     for kk, v in head.items()}
                    excess = 0
        self.cycles_run += done
        return self.state

    # lint: host
    def ring(self) -> dict:
        """The retained telemetry window as one stacked dict of
        [T, ...] arrays (T <= k), oldest sample first."""
        if not self._ring:
            return {}
        keys = self._ring[0].keys()
        return {kk: np.concatenate([s[kk] for s in self._ring], axis=0)
                for kk in keys}

    # lint: host
    def _metrics_doc(self) -> dict:
        from ue22cs343bb1_openmp_assignment_tpu.obs import schema
        mt = self.state.metrics
        md = {f: np.asarray(getattr(mt, f))
              for f in type(mt).__dataclass_fields__}
        return schema.validate(schema.from_async(md))

    # lint: host
    def dump_incident(self, out_dir: str, reason: str,
                      detail: str = "",
                      case: Optional[dict] = None) -> dict:
        """Write the self-contained incident directory; returns the
        incident doc. ``case`` is a fuzz-case dict
        (fuzz.FuzzCase.to_dict()) — when given, the repro fixture
        (core_<n>.txt + repro.json) is emitted alongside."""
        from ue22cs343bb1_openmp_assignment_tpu.obs import (perfetto,
                                                            timeseries)
        from ue22cs343bb1_openmp_assignment_tpu.ops import step
        from ue22cs343bb1_openmp_assignment_tpu.utils import eventlog
        os.makedirs(out_dir, exist_ok=True)

        # deterministic replay of the incident's first TRACE_CYCLES
        # cycles from the pristine initial state -> Perfetto trace
        n_trace = max(1, min(self.cycles_run or TRACE_CYCLES,
                             TRACE_CYCLES))
        _, events = step.run_cycles_traced(self.cfg, self.state0,
                                           n_trace, self.message_phase)
        trace_doc = perfetto.build_trace(
            eventlog.to_records(events), self.cfg.num_nodes)
        perfetto.validate_trace(trace_doc)
        perfetto.write_trace(
            os.path.join(out_dir, "trace.perfetto.json"), trace_doc)

        files = ["incident.json", "trace.perfetto.json"]
        if case is not None:
            files += self._emit_case_repro(out_dir, reason, detail,
                                           case)

        ring = self.ring()
        series = timeseries.to_series(ring) if ring else None
        # causal transaction spans of the incident's tail: the slowest
        # closed transactions with their latency decomposition plus
        # everything still in flight when the recorder stopped — the
        # hang suspects, by name
        txn_summary = None
        profile = None
        if self.cycles_run:
            from ue22cs343bb1_openmp_assignment_tpu.obs import (cohprof,
                                                                txntrace)
            txn_summary = txntrace.incident_summary(
                self.cfg, self.state0, self.cycles_run,
                self.message_phase)
            # same deterministic-replay discipline: the coherence
            # profile of the exact run that tripped the incident —
            # which lines were contended and how they were missing
            profile = cohprof.capture_async(
                self.cfg, self.state0, self.cycles_run,
                self.message_phase)
        doc = {
            "schema": SCHEMA_ID,
            "reason": str(reason),
            "detail": str(detail),
            "cycles_run": int(self.cycles_run),
            "final_cycle": int(self.state.cycle),
            "quiescent": bool(self.state.quiescent()),
            "ring_depth": self.k,
            "ring": series,
            "ring_summary": (timeseries.summarize(ring)
                             if ring else None),
            "metrics": self._metrics_doc(),
            "txn_summary": txn_summary,
            "profile": profile,
            "trace_cycles": n_trace,
            "has_repro": case is not None,
            "files": sorted(files),
        }
        with open(os.path.join(out_dir, "incident.json"), "w") as f:
            json.dump(doc, f, indent=1, sort_keys=True)
            f.write("\n")
        return doc

    # lint: host
    def _emit_case_repro(self, out_dir: str, reason: str, detail: str,
                         case: dict) -> list:
        # exact analysis/fixtures format (core_<n>.txt in the reference
        # trace syntax + cache-sim/repro/v1 metadata), so an incident
        # replays through the same path as a shrunk finding
        from ue22cs343bb1_openmp_assignment_tpu.analysis import (fixtures,
                                                                 fuzz)
        fc = fuzz.case_from_dict(case)
        fixtures.write_fixture(out_dir, fc, reason.split(":", 1)[-1],
                               detail,
                               extra_files=["trace.perfetto.json"])
        return [f"core_{n}.txt" for n in range(fc.num_nodes)] \
            + ["repro.json"]


# lint: host
def record_case(case, message_phase: Optional[Callable] = None,
                k: int = DEFAULT_RING) -> FlightRecorder:
    """A FlightRecorder primed from a fuzz case's initial state (same
    construction as fuzz.run_case, same mutant engine)."""
    from ue22cs343bb1_openmp_assignment_tpu.state import init_state
    cfg = case.config()
    st = init_state(cfg, case.trace_lists(),
                    issue_delay=np.array(case.delays, np.int32),
                    issue_period=np.array(case.periods, np.int32),
                    arb_rank=np.array(case.rank, np.int32))
    return FlightRecorder(cfg, st, k=k, message_phase=message_phase)


# lint: host
def load_incident(incident_dir: str) -> dict:
    """Read and schema-check an incident doc."""
    path = os.path.join(incident_dir, "incident.json")
    with open(path) as f:
        doc = json.load(f)
    if doc.get("schema") != SCHEMA_ID:
        raise ValueError(f"{path}: schema must be {SCHEMA_ID!r}, "
                         f"got {doc.get('schema')!r}")
    for k in ("reason", "cycles_run", "metrics", "files"):
        if k not in doc:
            raise ValueError(f"{path}: missing key {k!r}")
    if doc.get("profile") is not None:
        # validate-when-present: pre-profiler incidents stay loadable
        from ue22cs343bb1_openmp_assignment_tpu.obs import cohprof
        cohprof.validate(doc["profile"])
    return doc


# lint: host
def replay_incident(incident_dir: str,
                    message_phase: Optional[Callable] = None) -> dict:
    """Re-run an incident's repro case through the differential
    oracle (analysis/fuzz.run_case); returns the fresh verdict doc.
    Raises FileNotFoundError for incidents without a repro (hang /
    invariant incidents from CLI runs carry a Perfetto trace but no
    fuzz case)."""
    from ue22cs343bb1_openmp_assignment_tpu.analysis import fuzz
    path = os.path.join(incident_dir, "repro.json")
    with open(path) as f:
        meta = json.load(f)
    case = fuzz.case_from_dict(meta["case"])
    return fuzz.run_case(case, message_phase)
