"""The unified cross-engine metrics schema: ``cache-sim/metrics/v1.2``.

Before this module each engine's ``--metrics`` dump had its own shape
(async: the raw Metrics pytree, sync: a hand-picked field subset,
native: the C++ counter vector) — three mutually incompatible schemas
for one protocol. Every metrics surface now routes through here: the
adapters (:func:`from_async`, :func:`from_sync`, :func:`from_native`)
normalize each engine's native dict into one report, and
:func:`validate` checks it without any external dependency.

Report layout (every field always present; ``None`` marks a counter
the producing engine does not measure — *not* zero):

==================== ====================================================
key                  meaning
==================== ====================================================
schema               literal ``"cache-sim/metrics/v1.2"``
engine               producing engine (``async``/``sync``/``deep``/
                     ``native``)
steps                engine time steps executed
step_unit            what a step is: ``"cycles"`` (async/native) or
                     ``"rounds"`` (sync/deep transactions)
instrs_retired, read_hits, write_hits, read_misses, write_misses,
upgrades, invalidations, evictions
                     the eight core counters, flat at top level (ints)
messages             {processed_total, by_type, dropped_overflow,
                     dropped_injected} — message-level engines only
queue_depth_peak     max mailbox occupancy seen on any node
latency_cycles       {bucket_lo, counts}: miss-latency histogram,
                     bucket b counts waits with issue→retire latency in
                     [bucket_lo[b], next lo); last bucket open-ended
extra                engine-specific counters that have no cross-engine
                     meaning (e.g. sync conflicts/promotions)
txn_latency          *optional* (v1.1): transaction-span latency summary
                     from the causal tracer (obs.txntrace.summarize):
                     {spans, open, by_type: {type: {count, p50, p95,
                     p99}}, segments_total} — async engine with the
                     message ledger on (``cache-sim stats --txns``)
mb_dropped           (v1.2) mailbox-overflow silent-drop counter, quirk
                     6 surfaced at top level; ``None`` = not measured
==================== ====================================================

The eight core counters stay flat at top level on purpose: pre-existing
tooling (and tests/test_cli_engines.py) reads
``metrics["instrs_retired"]`` directly.

v1 → v1.1: the only change is the optional ``txn_latency`` block.
:func:`validate` accepts v1 documents unchanged (a v1 doc carrying
``txn_latency`` is rejected — the key did not exist in v1), so every
archived report and golden keeps validating.

This module also owns the serving-layer trace schema
(``cache-sim/serve-trace/v1``, :func:`validate_serve_trace`): the
Dapper-style job-lifecycle span docs serve.py and the soak harness
emit. It lives here so every schema'd observability doc validates
through one dependency-free module.

v1.1 → v1.2: adds the required top-level ``mb_dropped`` counter — the
mailbox-overflow silent drop (SURVEY quirk 6, ``assignment.c:754-762``)
pulled up from ``messages.dropped_overflow`` so drop-sensitive
consumers (``serve``'s per-wave loud warning, dashboards) read it
without digging into the messages block. ``None`` for engines with no
message plane (sync). Older docs validate unchanged: the key is
required only at v1.2 and rejected below it.
"""

from __future__ import annotations

from typing import Optional

from ue22cs343bb1_openmp_assignment_tpu.types import MSG_NAMES

SCHEMA_ID = "cache-sim/metrics/v1.2"

#: previous schema ids; validate() accepts docs under any of them
SCHEMA_V1 = "cache-sim/metrics/v1"
SCHEMA_V1_1 = "cache-sim/metrics/v1.1"

#: the eight cross-engine core counters, flat at top level of the report
CORE_COUNTERS = ("instrs_retired", "read_hits", "write_hits",
                 "read_misses", "write_misses", "upgrades",
                 "invalidations", "evictions")

_TOP_KEYS = (("schema", "engine", "steps", "step_unit") + CORE_COUNTERS
             + ("messages", "queue_depth_peak", "latency_cycles", "extra"))

#: v1.1 optional keys: allowed but never required
_OPT_KEYS = ("txn_latency",)

#: required fields of each txn_latency by_type entry
_TXN_TYPE_KEYS = ("count", "p50", "p95", "p99")

_MSG_KEYS = ("processed_total", "by_type", "dropped_overflow",
             "dropped_injected")


# lint: host
def _report(engine: str, steps: int, step_unit: str, counters: dict,
            messages: Optional[dict] = None,
            queue_depth_peak: Optional[int] = None,
            latency_cycles: Optional[dict] = None,
            extra: Optional[dict] = None,
            mb_dropped: Optional[int] = None) -> dict:
    doc = {"schema": SCHEMA_ID, "engine": engine, "steps": int(steps),
           "step_unit": step_unit}
    for k in CORE_COUNTERS:
        doc[k] = int(counters[k])
    doc["messages"] = (dict.fromkeys(_MSG_KEYS) if messages is None
                      else {k: messages.get(k) for k in _MSG_KEYS})
    doc["queue_depth_peak"] = queue_depth_peak
    doc["latency_cycles"] = latency_cycles
    doc["extra"] = extra or {}
    doc["mb_dropped"] = mb_dropped
    return doc


# lint: host
def latency_histogram(counts) -> Optional[dict]:
    """Render a LAT_BUCKETS-long count vector as the report's
    ``latency_cycles`` object (power-of-two bucket_lo edges); None when
    no wait ever completed (all-zero histogram from a run with no
    misses is still emitted — None means the engine didn't measure)."""
    counts = [int(c) for c in counts]
    return {"bucket_lo": [1 << b for b in range(len(counts))],
            "counts": counts}


# lint: host
def from_async(m: dict, engine: str = "async") -> dict:
    """CoherenceSystem.metrics (the async Metrics pytree as a dict) →
    unified report."""
    by_type = {name: int(c)
               for name, c in zip(MSG_NAMES, m["msgs_processed"])}
    return _report(
        engine, m["cycles"], "cycles", m,
        messages={"processed_total": sum(by_type.values()),
                  "by_type": by_type,
                  "dropped_overflow": int(m["msgs_dropped"]),
                  "dropped_injected": int(m["msgs_injected_dropped"])},
        queue_depth_peak=int(m["mb_depth_peak"]),
        latency_cycles=latency_histogram(m["lat_hist"]),
        mb_dropped=int(m["msgs_dropped"]))


# lint: host
def from_sync(m: dict, engine: str = "sync") -> dict:
    """TransactionalSystem.metrics (SyncMetrics as a dict) → unified
    report. The transactional engine has no message plane or wait
    latency — those stay None; its engine-specific counters (claim
    conflicts, S→E promotions) go to ``extra``."""
    return _report(
        engine, m["rounds"], "rounds", m,
        extra={"conflicts": int(m["conflicts"]),
               "promotions": int(m["promotions"])})


# lint: host
def from_native(m: dict, engine: str = "native") -> dict:
    """NativeEngine.metrics() (the C++ counter vector) → unified
    report. The oracle counts dequeues and drops but not per-type or
    latency."""
    return _report(
        engine, m["cycles"], "cycles", m,
        messages={"processed_total": None, "by_type": None,
                  "dropped_overflow": int(m["msgs_dropped"]),
                  "dropped_injected": None},
        mb_dropped=int(m["msgs_dropped"]))


# lint: host
def coverage_signature(doc: dict, dir_occupancy: Optional[dict] = None):
    """Project a v1 report (plus optional directory-state occupancy
    counts) onto a small hashable coverage point for analysis/fuzz.py.

    The signature deliberately quantizes: which message types appeared
    at all, which latency buckets are occupied, which core counters are
    nonzero, and the exact directory-state occupancy of the final
    state. Two runs with the same signature exercised the same protocol
    surface; the fuzzer keeps one corpus entry per signature. Not part
    of the report schema — :func:`validate` does not know about it."""
    bt = (doc.get("messages") or {}).get("by_type") or {}
    lat = doc.get("latency_cycles") or {"counts": ()}
    return (doc.get("engine"),
            tuple(int(bool(doc.get(k))) for k in CORE_COUNTERS),
            tuple(sorted(k for k, v in bt.items() if v)),
            tuple(i for i, c in enumerate(lat["counts"]) if c),
            tuple(sorted((dir_occupancy or {}).items())))


# lint: host
def _validate_txn_latency(tl, errs) -> None:
    """Structural check of the optional v1.1 txn_latency block."""
    if not isinstance(tl, dict):
        errs.append("txn_latency must be a dict")
        return
    for k in ("spans", "open"):
        v = tl.get(k)
        if not isinstance(v, int) or isinstance(v, bool) or v < 0:
            errs.append(f"txn_latency.{k} must be a non-negative int, "
                        f"got {v!r}")
    bt = tl.get("by_type")
    if not isinstance(bt, dict):
        errs.append("txn_latency.by_type must be a dict")
    else:
        for t, ent in bt.items():
            if (not isinstance(ent, dict)
                    or any(k not in ent for k in _TXN_TYPE_KEYS)):
                errs.append(f"txn_latency.by_type[{t!r}] must carry "
                            f"{_TXN_TYPE_KEYS}")
    st = tl.get("segments_total")
    if not isinstance(st, dict) or not all(
            isinstance(v, int) and v >= 0 for v in st.values()):
        errs.append("txn_latency.segments_total must be a dict of "
                    "non-negative ints")


# lint: host
def validate(doc: dict) -> dict:
    """Check a report against the schema (v1.2, or v1/v1.1 unchanged
    for backward compatibility); returns the doc, raises ValueError
    listing every violation. Dependency-free on purpose — the
    container has no jsonschema."""
    errs = []
    if not isinstance(doc, dict):
        raise ValueError(f"report must be a dict, got {type(doc).__name__}")
    is_v1 = doc.get("schema") == SCHEMA_V1
    is_v11 = doc.get("schema") == SCHEMA_V1_1
    required = _TOP_KEYS if (is_v1 or is_v11) else (
        _TOP_KEYS + ("mb_dropped",))
    allowed = (_TOP_KEYS if is_v1
               else _TOP_KEYS + _OPT_KEYS if is_v11
               else _TOP_KEYS + _OPT_KEYS + ("mb_dropped",))
    for k in required:
        if k not in doc:
            errs.append(f"missing key: {k}")
    for k in doc:
        if k not in allowed:
            errs.append(f"unknown key: {k}")
    if doc.get("schema") not in (SCHEMA_ID, SCHEMA_V1, SCHEMA_V1_1):
        errs.append(f"schema must be {SCHEMA_ID!r} (or the "
                    f"backward-compatible {SCHEMA_V1!r}/{SCHEMA_V1_1!r}), "
                    f"got {doc.get('schema')!r}")
    if "txn_latency" in doc and not is_v1:
        _validate_txn_latency(doc["txn_latency"], errs)
    if "mb_dropped" in doc:
        v = doc["mb_dropped"]
        if v is not None and (not isinstance(v, int)
                              or isinstance(v, bool) or v < 0):
            errs.append(f"mb_dropped must be None or a non-negative "
                        f"int, got {v!r}")
    if not isinstance(doc.get("engine"), str):
        errs.append("engine must be a string")
    if doc.get("step_unit") not in ("cycles", "rounds"):
        errs.append(f"step_unit must be cycles|rounds, "
                    f"got {doc.get('step_unit')!r}")
    for k in ("steps",) + CORE_COUNTERS:
        v = doc.get(k)
        if not isinstance(v, int) or isinstance(v, bool) or v < 0:
            errs.append(f"{k} must be a non-negative int, got {v!r}")
    msgs = doc.get("messages")
    if not isinstance(msgs, dict):
        errs.append("messages must be a dict")
    else:
        for k in _MSG_KEYS:
            if k not in msgs:
                errs.append(f"messages missing key: {k}")
        for k in ("processed_total", "dropped_overflow",
                  "dropped_injected"):
            v = msgs.get(k)
            if v is not None and (not isinstance(v, int) or v < 0):
                errs.append(f"messages.{k} must be None or "
                            f"non-negative int, got {v!r}")
        bt = msgs.get("by_type")
        if bt is not None:
            if not isinstance(bt, dict) or not all(
                    isinstance(v, int) and v >= 0 for v in bt.values()):
                errs.append("messages.by_type must be None or a dict of "
                            "non-negative ints")
            elif (msgs.get("processed_total") is not None
                  and sum(bt.values()) != msgs["processed_total"]):
                errs.append("messages.by_type does not sum to "
                            "processed_total")
    q = doc.get("queue_depth_peak")
    if q is not None and (not isinstance(q, int) or q < 0):
        errs.append(f"queue_depth_peak must be None or non-negative "
                    f"int, got {q!r}")
    lat = doc.get("latency_cycles")
    if lat is not None:
        if (not isinstance(lat, dict)
                or set(lat) != {"bucket_lo", "counts"}):
            errs.append("latency_cycles must be None or "
                        "{bucket_lo, counts}")
        elif (len(lat["bucket_lo"]) != len(lat["counts"])
              or lat["bucket_lo"] != sorted(set(lat["bucket_lo"]))
              or any(not isinstance(c, int) or c < 0
                     for c in lat["counts"])):
            errs.append("latency_cycles bucket_lo must be strictly "
                        "increasing and counts non-negative ints of "
                        "the same length")
    if not isinstance(doc.get("extra"), dict):
        errs.append("extra must be a dict")
    if errs:
        raise ValueError("invalid metrics report:\n  " + "\n  ".join(errs))
    return doc


# -- serving trace: job-lifecycle spans ------------------------------------

SERVE_TRACE_SCHEMA_ID = "cache-sim/serve-trace/v1"

#: every span field, all always present (Dapper-style lifecycle:
#: submit -> queued -> admitted(wave, slot) -> running -> quiescent ->
#: extracted, assembled host-side by serve.SpanBook under the injected
#: clock). The three segment durations MUST sum exactly to e2e_s —
#: they are computed from the timestamps in one place (SpanBook), so
#: the decomposition holds by construction, and validate_serve_trace
#: re-checks it.
SPAN_KEYS = ("job", "wave", "slot", "quiesced",
             "t_submit", "t_queued", "t_admitted", "t_running",
             "t_quiescent", "t_extracted",
             "queue_wait_s", "run_s", "extract_s", "e2e_s")

#: optional span fields: the daemon's tenancy annotations
#: (daemon/core.py stamps them via SpanBook.annotate) — ``lane`` is
#: the priority lane the job was admitted from, ``bucket`` the shape
#: bucket label it ran in. serve/soak spans omit both; anything else
#: unknown is still rejected.
_SPAN_OPT_KEYS = ("lane", "bucket")

#: the lifecycle timestamps in causal order (monotone per span)
_SPAN_TS_ORDER = ("t_submit", "t_queued", "t_admitted", "t_running",
                  "t_quiescent", "t_extracted")

_TRACE_TOP_KEYS = ("schema", "clock", "jobs", "latency", "spans")


# lint: host
def _validate_span(i: int, s, errs) -> None:
    if not isinstance(s, dict):
        errs.append(f"span {i}: not a dict")
        return
    for k in SPAN_KEYS:
        if k not in s:
            errs.append(f"span {i}: missing key {k}")
            return
    for k in set(s) - set(SPAN_KEYS) - set(_SPAN_OPT_KEYS):
        errs.append(f"span {i}: unknown key {k}")
    for k in _SPAN_OPT_KEYS:
        if k in s and (not isinstance(s[k], str) or not s[k]):
            errs.append(f"span {i}: {k} must be a non-empty string, "
                        f"got {s[k]!r}")
    if not isinstance(s["job"], str) or not s["job"]:
        errs.append(f"span {i}: job must be a non-empty string")
    for k in ("wave", "slot"):
        v = s[k]
        if not isinstance(v, int) or isinstance(v, bool) or v < 0:
            errs.append(f"span {i}: {k} must be a non-negative int, "
                        f"got {v!r}")
    if not isinstance(s["quiesced"], bool):
        errs.append(f"span {i}: quiesced must be bool")
    ts = []
    for k in _SPAN_TS_ORDER:
        v = s[k]
        if not isinstance(v, (int, float)) or isinstance(v, bool):
            errs.append(f"span {i}: {k} must be a number, got {v!r}")
            return
        ts.append(float(v))
    if any(b < a for a, b in zip(ts, ts[1:])):
        errs.append(f"span {i} ({s['job']}): lifecycle timestamps not "
                    f"monotone: {list(zip(_SPAN_TS_ORDER, ts))}")
    for k in ("queue_wait_s", "run_s", "extract_s", "e2e_s"):
        v = s[k]
        if (not isinstance(v, (int, float)) or isinstance(v, bool)
                or v < 0):
            errs.append(f"span {i}: {k} must be a non-negative number, "
                        f"got {v!r}")
            return
    if s["e2e_s"] != s["queue_wait_s"] + s["run_s"] + s["extract_s"]:
        errs.append(f"span {i} ({s['job']}): e2e_s != queue_wait_s + "
                    f"run_s + extract_s (the decomposition must hold "
                    f"exactly, by construction)")


# lint: host
def validate_serve_trace(doc: dict) -> dict:
    """Structural check of a ``cache-sim/serve-trace/v1`` doc
    (serve.serve_trace_doc / the soak harness): schema id, clock kind,
    per-span lifecycle monotonicity, and the exact span decomposition
    invariant. Same contract as :func:`validate`."""
    errs = []
    if not isinstance(doc, dict):
        raise ValueError(f"trace must be a dict, got {type(doc).__name__}")
    if doc.get("schema") != SERVE_TRACE_SCHEMA_ID:
        errs.append(f"schema must be {SERVE_TRACE_SCHEMA_ID!r}, "
                    f"got {doc.get('schema')!r}")
    for k in _TRACE_TOP_KEYS:
        if k not in doc:
            errs.append(f"missing key: {k}")
    for k in doc:
        if k not in _TRACE_TOP_KEYS:
            errs.append(f"unknown key: {k}")
    if doc.get("clock") not in ("monotonic", "virtual"):
        errs.append(f"clock must be monotonic|virtual, "
                    f"got {doc.get('clock')!r}")
    spans = doc.get("spans")
    if not isinstance(spans, list):
        errs.append("spans must be a list")
        spans = []
    if doc.get("jobs") != len(spans):
        errs.append(f"jobs ({doc.get('jobs')!r}) != len(spans) "
                    f"({len(spans)})")
    for i, s in enumerate(spans):
        _validate_span(i, s, errs)
    lat = doc.get("latency")
    if lat is not None:
        if not isinstance(lat, dict):
            errs.append("latency must be None or a dict")
        else:
            ps = [lat.get(k) for k in ("p50_ms", "p95_ms", "p99_ms")]
            if any(not isinstance(p, (int, float))
                   or isinstance(p, bool) or p < 0 for p in ps):
                errs.append("latency p50_ms/p95_ms/p99_ms must be "
                            "non-negative numbers")
            elif not ps[0] <= ps[1] <= ps[2]:
                errs.append(f"latency percentiles not monotone: {ps}")
    if errs:
        raise ValueError("invalid serve trace:\n  " + "\n  ".join(errs))
    return doc


# -- serving daemon: stats snapshot ----------------------------------------

DAEMON_STATS_SCHEMA_ID = "cache-sim/daemon-stats/v1"

#: required top-level keys of a daemon ``stats`` response
#: (daemon/core.DaemonCore.stats) — one point-in-time snapshot of the
#: admission queues, shape buckets, and padding accounting
_DAEMON_TOP_KEYS = ("schema", "clock", "uptime_s", "draining", "jobs",
                    "lanes", "buckets", "chunks", "busy_s",
                    "drain_rate_jobs_per_s", "mb_dropped",
                    "mid_wave_swaps", "bucket_growths",
                    "queue_depth_peak", "retain_results",
                    "results_evicted", "recording", "padding_waste",
                    "single_shape_padding_waste")

#: the live-capture counters a recording daemon reports (``recording``
#: is None when ``--record`` is off): the artifact path plus exact
#: lifetime row counts — ``submits`` accepted submissions streamed,
#: ``results`` digest rows written (obs.recording)
_DAEMON_RECORDING_KEYS = ("path", "submits", "results")

_DAEMON_JOB_KEYS = ("submitted", "rejected", "done", "quiesced")

_DAEMON_LANE_KEYS = ("weight", "depth", "queued", "submitted",
                     "admitted", "rejected", "done", "latency")

_DAEMON_BUCKET_KEYS = ("bucket", "protocol", "nodes", "trace_len",
                       "slots", "busy", "admitted", "chunks")


# lint: host
def validate_daemon_stats(doc: dict) -> dict:
    """Structural check of a ``cache-sim/daemon-stats/v1`` snapshot
    (the daemon ``stats`` socket op). Same contract as :func:`validate`:
    raises ValueError listing every violation, returns the doc."""
    errs = []
    if not isinstance(doc, dict):
        raise ValueError(f"stats must be a dict, got {type(doc).__name__}")
    if doc.get("schema") != DAEMON_STATS_SCHEMA_ID:
        errs.append(f"schema must be {DAEMON_STATS_SCHEMA_ID!r}, "
                    f"got {doc.get('schema')!r}")
    for k in _DAEMON_TOP_KEYS:
        if k not in doc:
            errs.append(f"missing key: {k}")
    if doc.get("clock") not in ("monotonic", "virtual"):
        errs.append(f"clock must be monotonic|virtual, "
                    f"got {doc.get('clock')!r}")
    jobs = doc.get("jobs")
    if not isinstance(jobs, dict):
        errs.append("jobs must be a dict")
    else:
        for k in _DAEMON_JOB_KEYS:
            v = jobs.get(k)
            if not isinstance(v, int) or isinstance(v, bool) or v < 0:
                errs.append(f"jobs.{k} must be a non-negative int, "
                            f"got {v!r}")
    lanes = doc.get("lanes")
    if not isinstance(lanes, dict) or not lanes:
        errs.append("lanes must be a non-empty dict")
    else:
        for name, lane in lanes.items():
            if not isinstance(lane, dict):
                errs.append(f"lane {name}: not a dict")
                continue
            for k in _DAEMON_LANE_KEYS:
                if k not in lane:
                    errs.append(f"lane {name}: missing key {k}")
    buckets = doc.get("buckets")
    if not isinstance(buckets, list):
        errs.append("buckets must be a list")
    else:
        for i, b in enumerate(buckets):
            if not isinstance(b, dict):
                errs.append(f"bucket {i}: not a dict")
                continue
            for k in _DAEMON_BUCKET_KEYS:
                if k not in b:
                    errs.append(f"bucket {i}: missing key {k}")
    for k in ("padding_waste", "single_shape_padding_waste"):
        v = doc.get(k)
        if v is not None and (not isinstance(v, (int, float))
                              or isinstance(v, bool)
                              or not 0.0 <= float(v) <= 1.0):
            errs.append(f"{k} must be None or in [0, 1], got {v!r}")
    rec = doc.get("recording")
    if rec is not None:
        if not isinstance(rec, dict):
            errs.append("recording must be None or a dict "
                        f"{{{', '.join(_DAEMON_RECORDING_KEYS)}}}")
        else:
            for k in _DAEMON_RECORDING_KEYS:
                if k not in rec:
                    errs.append(f"recording: missing key {k}")
            for k in ("submits", "results"):
                v = rec.get(k)
                if (not isinstance(v, int) or isinstance(v, bool)
                        or v < 0):
                    errs.append(f"recording.{k} must be a "
                                f"non-negative int, got {v!r}")
    # -- live-ops-plane additions, validated WHEN PRESENT: a pre-ops
    # v1 doc (no stats_seq/hist/events) keeps validating unchanged,
    # the backcompat matrix in tests/test_ops_plane.py pins both ways
    v = doc.get("uptime_s")
    if v is not None and (not isinstance(v, (int, float))
                          or isinstance(v, bool) or v < 0):
        errs.append(f"uptime_s must be a non-negative number, got {v!r}")
    for k in ("stats_seq", "slo_alerts"):
        if k in doc:
            v = doc[k]
            if not isinstance(v, int) or isinstance(v, bool) or v < 0:
                errs.append(f"{k} must be a non-negative int, "
                            f"got {v!r}")
    if isinstance(lanes, dict):
        for name, lane in lanes.items():
            hist = lane.get("hist") if isinstance(lane, dict) else None
            if hist is None:
                continue
            h_errs = _hist_errs(hist, f"lane {name}: hist")
            errs.extend(h_errs)
    ev = doc.get("events")
    if ev is not None:
        if not isinstance(ev, dict):
            errs.append("events must be None or a dict "
                        "{path, ring, seq, dropped}")
        else:
            for k in ("ring", "seq", "dropped"):
                v = ev.get(k)
                if (not isinstance(v, int) or isinstance(v, bool)
                        or v < 0):
                    errs.append(f"events.{k} must be a non-negative "
                                f"int, got {v!r}")
    prof = doc.get("profile")
    if prof is not None:
        # validate-when-present: a coherence-profile doc (obs.cohprof)
        # attached by a daemon running with profiling on
        from ue22cs343bb1_openmp_assignment_tpu.obs import cohprof
        try:
            cohprof.validate(prof)
        except ValueError as e:
            errs.append(f"profile: {e}")
    if errs:
        raise ValueError("invalid daemon stats:\n  " + "\n  ".join(errs))
    return doc


# lint: host
def _hist_errs(hist, where: str) -> list:
    """Structural errors of one mergeable-histogram doc
    (obs.timeseries.LogHistogram.to_doc): counts must be one longer
    than edges (the overflow bucket) and their total must equal
    ``count`` — the invariant the exact fleet merge relies on."""
    if not isinstance(hist, dict):
        return [f"{where}: must be None or a dict"]
    errs = []
    edges = hist.get("edges_ms")
    counts = hist.get("counts")
    if (not isinstance(edges, list) or not edges
            or any(not isinstance(e, (int, float)) or isinstance(e, bool)
                   for e in edges)
            or any(b <= a for a, b in zip(edges, edges[1:]))):
        errs.append(f"{where}: edges_ms must be a strictly increasing "
                    f"number list")
    if (not isinstance(counts, list)
            or any(not isinstance(c, int) or isinstance(c, bool) or c < 0
                   for c in counts)):
        errs.append(f"{where}: counts must be a list of non-negative "
                    f"ints")
    elif isinstance(edges, list) and len(counts) != len(edges) + 1:
        errs.append(f"{where}: counts must have len(edges_ms) + 1 "
                    f"entries (the overflow bucket), got {len(counts)} "
                    f"for {len(edges)} edges")
    n = hist.get("count")
    if not isinstance(n, int) or isinstance(n, bool) or n < 0:
        errs.append(f"{where}: count must be a non-negative int")
    elif isinstance(counts, list) and all(
            isinstance(c, int) and not isinstance(c, bool) and c >= 0
            for c in counts) and sum(counts) != n:
        errs.append(f"{where}: count ({n}) != sum(counts) "
                    f"({sum(counts)})")
    s = hist.get("sum_ms")
    if (not isinstance(s, (int, float)) or isinstance(s, bool)
            or s < 0):
        errs.append(f"{where}: sum_ms must be a non-negative number")
    return errs


# -- fleet view: N replicas' stats merged -----------------------------------

FLEET_SCHEMA_ID = "cache-sim/fleet/v1"

#: required top-level keys of a fleet doc (obs.fleet.merge_stats):
#: lifetime counters are EXACT sums over the replicas, gauges are the
#: fleet-meaningful reduction (max uptime, peak depth, any draining)
_FLEET_TOP_KEYS = ("schema", "replicas", "jobs", "lanes", "buckets",
                   "chunks", "busy_s", "drain_rate_jobs_per_s",
                   "mb_dropped", "mid_wave_swaps", "bucket_growths",
                   "results_evicted", "slo_alerts", "uptime_s",
                   "queue_depth_peak", "draining", "per_replica")


# lint: host
def validate_fleet(doc: dict) -> dict:
    """Structural check of a ``cache-sim/fleet/v1`` merged stats doc
    (the ``cache-sim top`` aggregator). Same contract as
    :func:`validate_daemon_stats`."""
    errs = []
    if not isinstance(doc, dict):
        raise ValueError(f"fleet doc must be a dict, "
                         f"got {type(doc).__name__}")
    if doc.get("schema") != FLEET_SCHEMA_ID:
        errs.append(f"schema must be {FLEET_SCHEMA_ID!r}, "
                    f"got {doc.get('schema')!r}")
    for k in _FLEET_TOP_KEYS:
        if k not in doc:
            errs.append(f"missing key: {k}")
    n = doc.get("replicas")
    if not isinstance(n, int) or isinstance(n, bool) or n < 1:
        errs.append(f"replicas must be a positive int, got {n!r}")
    jobs = doc.get("jobs")
    if not isinstance(jobs, dict):
        errs.append("jobs must be a dict")
    else:
        for k in _DAEMON_JOB_KEYS:
            v = jobs.get(k)
            if not isinstance(v, int) or isinstance(v, bool) or v < 0:
                errs.append(f"jobs.{k} must be a non-negative int, "
                            f"got {v!r}")
    lanes = doc.get("lanes")
    if not isinstance(lanes, dict):
        errs.append("lanes must be a dict")
    else:
        for name, lane in lanes.items():
            if not isinstance(lane, dict):
                errs.append(f"lane {name}: not a dict")
                continue
            for k in ("queued", "submitted", "admitted", "rejected",
                      "done"):
                v = lane.get(k)
                if (not isinstance(v, int) or isinstance(v, bool)
                        or v < 0):
                    errs.append(f"lane {name}: {k} must be a "
                                f"non-negative int, got {v!r}")
            if lane.get("hist") is not None:
                errs.extend(_hist_errs(lane["hist"],
                                       f"lane {name}: hist"))
    per = doc.get("per_replica")
    if not isinstance(per, list) or (isinstance(n, int)
                                     and not isinstance(n, bool)
                                     and len(per or []) != n):
        errs.append("per_replica must be a list with one row per "
                    "replica")
    else:
        for i, row in enumerate(per):
            if not isinstance(row, dict) or "replica" not in row:
                errs.append(f"per_replica[{i}]: must be a dict with "
                            f"a 'replica' label")
    if errs:
        raise ValueError("invalid fleet doc:\n  " + "\n  ".join(errs))
    return doc
