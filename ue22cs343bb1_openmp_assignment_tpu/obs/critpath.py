"""Critical-path attribution over the happens-before DAG.

Classic critical-path analysis (Yang & Miller, ICDCS'88) applied to
the coherence engine: the run's events form a happens-before DAG —

* **program-order edges**: consecutive activity events of one node
  (message dequeue or instruction fetch; a node does at most one per
  cycle, drain-before-fetch), minimum spacing 1 cycle;
* **message edges**: the event that *emitted* a message (its causal
  parent's dequeue, or the issuing fetch — obs.txntrace) happens
  before the message's dequeue at the receiver, minimum spacing 1
  cycle (a message delivered in phase 3 of cycle c is dequeue-eligible
  at c+1). Ring-FIFO ordering needs no extra edges: dequeues at a node
  are already program-ordered.

The critical path to quiescence is the chain that *determined* the
run's length: start from the terminal event and repeatedly step to the
tightest predecessor — the one with the largest ``cycle + 1`` bound
(ties: the message edge binds, then the lower node id; fully
deterministic, so repeated runs of the deterministic engine produce
identical reports). Every cycle between path start and end is
attributed to a (node, phase) pair:

* ``service_msg`` / ``service_instr`` — the event's own cycle,
* ``queue_wait`` — slack under a message edge: the binding message sat
  that long in the receiver's ring,
* ``stall`` — slack under a program-order edge: the node sat idle or
  blocked between its own events.

``by_node`` + ``by_phase`` each sum to the path length, and the length
is ≤ total cycles by construction — "what to optimize next", with
receipts. Host-side analysis only (consumes txntrace.parse_ledger).
"""
# lint: host

from __future__ import annotations

from typing import Dict, List, Optional

from ue22cs343bb1_openmp_assignment_tpu.types import MSG_NAMES

SCHEMA_ID = "cache-sim/critpath/v1"

#: attribution phases; report["by_phase"] sums over exactly these
PHASES = ("service_instr", "service_msg", "queue_wait", "stall")


# lint: host
def _event_index(trace: dict) -> Dict[tuple, tuple]:
    """{(node, cycle): (kind, msg_idx, pos-in-node-stream)}."""
    idx = {}
    for n, evs in trace["events"].items():
        for pos, (cyc, kind, mi) in enumerate(evs):
            idx[(n, cyc)] = (kind, mi, pos)
    return idx


# lint: host
def critical_path(trace: dict, total_cycles: Optional[int] = None
                  ) -> dict:
    """The critical path of a parsed ledger (txntrace.parse_ledger).

    Returns the ``cache-sim/critpath/v1`` report dict; deterministic
    for a deterministic engine run. ``total_cycles`` (cycles to
    quiescence) is carried into the report so consumers can see the
    path-length ≤ run-length bound hold.
    """
    msgs = trace["msgs"]
    events = trace["events"]
    idx = _event_index(trace)

    report = {"schema": SCHEMA_ID,
              "total_cycles": (int(total_cycles)
                               if total_cycles is not None else None),
              "length": 0, "events_on_path": 0,
              "start": None, "end": None,
              "by_node": {}, "by_phase": dict.fromkeys(PHASES, 0),
              "steps": []}
    all_events = [(cyc, n, kind, mi)
                  for n, evs in events.items()
                  for (cyc, kind, mi) in evs]
    if not all_events:
        return report

    # terminal: the last event of the run (it *is* the quiescence
    # frontier); among same-cycle events the lowest node id, for
    # determinism
    last_cycle = max(e[0] for e in all_events)
    term = min((n for (cyc, n, _k, _m) in all_events
                if cyc == last_cycle))
    node, cyc = term, last_cycle

    steps: List[dict] = []
    by_node: Dict[int, int] = {}
    by_phase = dict.fromkeys(PHASES, 0)
    while True:
        kind, msg_idx, pos = idx[(node, cyc)]
        preds = []
        if pos > 0:
            p_cyc = events[node][pos - 1][0]
            # sort key: bound desc, message edge (0) before program
            # edge (1), then lower pred node id
            preds.append((-(p_cyc + 1), 1, node, p_cyc, None))
        if kind == "msg" and msg_idx is not None:
            m = msgs[msg_idx]
            if (m["src"], m["enq"]) in idx:
                preds.append((-(m["enq"] + 1), 0, m["src"], m["enq"],
                              msg_idx))
        service = "service_msg" if kind == "msg" else "service_instr"
        if not preds:
            # path root: its own cycle is the origin, not attributed
            # (length = terminal cycle - root cycle)
            steps.append({"node": node, "cycle": cyc, "kind": kind,
                          "wait": 0, "edge": "root"})
            break
        preds.sort()
        _bound, edge_kind, p_node, p_cyc, p_msg = preds[0]
        wait = cyc - p_cyc - 1
        by_node[node] = by_node.get(node, 0) + 1 + wait
        by_phase[service] += 1
        by_phase["queue_wait" if edge_kind == 0 else "stall"] += wait
        step = {"node": node, "cycle": cyc, "kind": kind,
                "wait": wait,
                "edge": "msg" if edge_kind == 0 else "program"}
        if edge_kind == 0:
            step["msg"] = {"src": msgs[p_msg]["src"],
                           "type": MSG_NAMES[msgs[p_msg]["type"]],
                           "addr": msgs[p_msg]["addr"]}
        steps.append(step)
        node, cyc = p_node, p_cyc

    steps.reverse()
    root, term_ev = steps[0], steps[-1]
    report.update(
        length=term_ev["cycle"] - root["cycle"],
        events_on_path=len(steps),
        start={"node": root["node"], "cycle": root["cycle"],
               "kind": root["kind"]},
        end={"node": term_ev["node"], "cycle": term_ev["cycle"],
             "kind": term_ev["kind"]},
        by_node={str(n): c for n, c in sorted(by_node.items())},
        by_phase=by_phase, steps=steps)
    return report


# lint: host
def hotspots(report: dict, top: int = 5) -> List[dict]:
    """The path steps that absorbed the most wait, largest first —
    the "optimize this" shortlist."""
    waits = [s for s in report["steps"] if s.get("wait", 0) > 0]
    return sorted(waits, key=lambda s: (-s["wait"], s["cycle"],
                                        s["node"]))[:top]
