"""Bench history: the append-only record behind ``cache-sim bench-diff``.

Before this module the repo's performance memory lived in loose
``BENCH_r*.json`` driver captures that nothing parsed, and PERF.md
argued each round's delta by hand. This module gives every benchmark
capture one schema'd home — a JSONL file of ``cache-sim/bench/v1``
entries carrying the FULL rep-time vector (the noise information the
headline median throws away), a config fingerprint (so apples are only
compared to apples), and the git sha — and adapters from both capture
sources:

- ``bench.py --record PATH`` appends the run it just measured;
- :func:`ingest_capture` lifts an archived driver capture
  (``BENCH_r*.json``: ``{"n", "cmd", "rc", "tail", "parsed"}``) or a
  raw two-line ``bench.py`` output file into the same schema.

The statistical comparator over these entries lives in
:mod:`obs.regress`; this module is storage + validation only.
Host-side by construction; dependency-free like obs.schema.
"""

from __future__ import annotations

import json
import os
import subprocess
from typing import List, Optional

SCHEMA_ID = "cache-sim/bench/v1.4"

#: older schema ids; validate_entry accepts docs under any of these,
#: with only the optional keys their version introduced
SCHEMA_V1 = "cache-sim/bench/v1"
SCHEMA_V11 = "cache-sim/bench/v1.1"
SCHEMA_V12 = "cache-sim/bench/v1.2"
SCHEMA_V13 = "cache-sim/bench/v1.3"

#: entry keys, all always present (None marks "not captured")
_TOP_KEYS = ("schema", "label", "source", "captured_at", "git_sha",
             "metric", "unit", "value", "vs_baseline", "config",
             "rep_times_s", "elapsed_s", "steps", "retired",
             "quiescent", "phases")

#: v1.1 added the comparability keys (bench-diff refuses to compare
#: rep times across devices); v1.2 added the deterministic cost
#: vector (obs.roofline.cost_vector — the --bytes gate's input);
#: v1.3 added the serving block ({slots, jobs, waves, padding_waste}
#: from bench.py --serve — the jobs/sec rows next to the instrs/sec
#: headline); v1.4 added the latency block (p50/p95/p99 job latency
#: + raw samples_ms from the open-loop soak harness, bench.py --soak —
#: what bench-diff --latency adjudicates).
#: Optional: absent and None both mean "not captured".
_OPT_KEYS_V11 = ("device_kind", "hlo_fingerprint")
_OPT_KEYS_V12 = _OPT_KEYS_V11 + ("cost",)
_OPT_KEYS_V13 = _OPT_KEYS_V12 + ("serve",)
_OPT_KEYS_V14 = _OPT_KEYS_V13 + ("latency",)

#: required fields of a serve block (ints except padding_waste);
#: optional extras "devices" (batch-mesh width of the wave) and
#: "mb_dropped" (summed mailbox overflow drops, quirk 6) ride the
#: same block — absent in pre-multi-device captures, no schema bump
_SERVE_KEYS = ("slots", "jobs", "waves", "padding_waste")
_SERVE_OPT_KEYS = ("devices", "mb_dropped")
#: optional serve transport: "inproc" (serve/soak in-process
#: waves) or "daemon" (the soak stream went over the daemon's
#: socket front door); absent in pre-daemon captures
_SERVE_TRANSPORTS = ("inproc", "daemon")

#: required fields of a latency block: the nearest-rank percentiles
#: (ms), the arrival rate the stream was released at (jobs/s — part of
#: comparability: latencies at different offered loads never compare),
#: and the admission-queue depth peak. Optional extras carry the raw
#: per-job sample vector (what regress.compare_latency's Mann-Whitney
#: test runs on) and the soak context.
_LATENCY_KEYS = ("p50_ms", "p95_ms", "p99_ms", "arrival_rate",
                 "queue_depth_peak")
_LATENCY_OPT_KEYS = ("max_ms", "jobs", "samples_ms", "duration_s",
                     "saturated", "drain_rate_jobs_per_s")


# lint: host
def git_sha(repo_dir: Optional[str] = None) -> Optional[str]:
    """Current commit sha, or None outside a work tree / without git."""
    try:
        out = subprocess.run(
            ["git", "rev-parse", "HEAD"], cwd=repo_dir,
            capture_output=True, text=True, timeout=10)
    except (OSError, subprocess.TimeoutExpired):
        return None
    sha = out.stdout.strip()
    return sha if out.returncode == 0 and sha else None


# lint: host
def entry(label: str, source: str, result: dict, extra: dict,
          config: Optional[dict] = None, sha: Optional[str] = None,
          captured_at: Optional[str] = None,
          device_kind: Optional[str] = None,
          hlo_fingerprint: Optional[str] = None,
          cost: Optional[dict] = None,
          serve: Optional[dict] = None,
          latency: Optional[dict] = None) -> dict:
    """Build a v1.4 entry from bench.py's two JSON lines.

    ``result`` is the stdout line ({metric, value, unit, vs_baseline});
    ``extra`` is the stderr line (engine, rep_times_s, quiescent, ...).
    ``config`` is the benchmark fingerprint — whatever knobs determined
    the measured computation; the metric string itself is always part
    of the comparability check, so a partial fingerprint degrades
    gracefully for archived captures. ``device_kind`` /
    ``hlo_fingerprint`` make cross-device comparisons detectable;
    ``cost`` is the deterministic roofline cost vector
    (obs.roofline.cost_vector) behind ``bench-diff --bytes``;
    ``serve`` is the batched-serving block ({slots, jobs, waves,
    padding_waste}) attached to jobs/sec rows by ``bench.py --serve``;
    ``latency`` is the open-loop job-latency block ({p50_ms, p95_ms,
    p99_ms, arrival_rate, queue_depth_peak} + the raw samples_ms
    vector) attached by ``bench.py --soak`` — the input of
    ``bench-diff --latency``.
    """
    doc = {
        "schema": SCHEMA_ID,
        "label": str(label),
        "source": str(source),
        "captured_at": captured_at,
        "git_sha": sha,
        "metric": result["metric"],
        "unit": result["unit"],
        "value": float(result["value"]),
        "vs_baseline": float(result.get("vs_baseline", 0.0)),
        "config": dict(config) if config else {"engine": extra.get("engine")},
        "rep_times_s": [float(t) for t in extra.get("rep_times_s", [])],
        "elapsed_s": (float(extra["elapsed_s"])
                      if extra.get("elapsed_s") is not None else None),
        "steps": (int(extra["steps"])
                  if extra.get("steps") is not None else None),
        "retired": (int(extra["retired"])
                    if extra.get("retired") is not None else None),
        "quiescent": (bool(extra["quiescent"])
                      if extra.get("quiescent") is not None else None),
        "phases": extra.get("phases"),
        "device_kind": device_kind,
        "hlo_fingerprint": hlo_fingerprint,
        "cost": cost,
        "serve": serve,
        "latency": latency,
    }
    return validate_entry(doc)


# lint: host
def validate_entry(doc: dict) -> dict:
    """Check an entry against the schema (v1.4, or v1/v1.1/v1.2/v1.3
    unchanged for backward compatibility — an old doc may only carry
    the optional keys its version introduced); returns the doc, raises
    ValueError listing every violation (same contract as
    obs.schema.validate)."""
    errs = []
    if not isinstance(doc, dict):
        raise ValueError(f"entry must be a dict, got {type(doc).__name__}")
    sid = doc.get("schema")
    allowed = _TOP_KEYS + (
        _OPT_KEYS_V14 if sid == SCHEMA_ID
        else _OPT_KEYS_V13 if sid == SCHEMA_V13
        else _OPT_KEYS_V12 if sid == SCHEMA_V12
        else _OPT_KEYS_V11 if sid == SCHEMA_V11 else ())
    for k in _TOP_KEYS:
        if k not in doc:
            errs.append(f"missing key: {k}")
    for k in doc:
        if k not in allowed:
            errs.append(f"unknown key: {k}")
    if sid not in (SCHEMA_ID, SCHEMA_V13, SCHEMA_V12, SCHEMA_V11,
                   SCHEMA_V1):
        errs.append(f"schema must be {SCHEMA_ID!r} (or the "
                    f"backward-compatible {SCHEMA_V13!r}/{SCHEMA_V12!r}"
                    f"/{SCHEMA_V11!r}/{SCHEMA_V1!r}), got {sid!r}")
    for k in _OPT_KEYS_V11:
        v = doc.get(k)
        if v is not None and (not isinstance(v, str) or not v):
            errs.append(f"{k} must be None or a non-empty string")
    cost = doc.get("cost")
    if cost is not None:
        if not isinstance(cost, dict) or not isinstance(
                cost.get("kernels"), dict):
            errs.append("cost must be None or a dict with a 'kernels' "
                        "dict (obs.roofline.cost_vector)")
        else:
            bpi = cost.get("bytes_per_instr")
            if bpi is not None and (
                    not isinstance(bpi, (int, float))
                    or isinstance(bpi, bool) or bpi < 0):
                errs.append("cost.bytes_per_instr must be None or a "
                            f"non-negative number, got {bpi!r}")
    srv = doc.get("serve")
    if srv is not None:
        if not isinstance(srv, dict):
            errs.append("serve must be None or a dict "
                        f"{{{', '.join(_SERVE_KEYS)}}}")
        else:
            for k in ("slots", "jobs", "waves"):
                x = srv.get(k)
                if not isinstance(x, int) or isinstance(x, bool) or x < 0:
                    errs.append(f"serve.{k} must be a non-negative int, "
                                f"got {x!r}")
            pw = srv.get("padding_waste")
            if (not isinstance(pw, (int, float)) or isinstance(pw, bool)
                    or not 0.0 <= pw <= 1.0):
                errs.append("serve.padding_waste must be a number in "
                            f"[0, 1], got {pw!r}")
            for k in _SERVE_OPT_KEYS:
                x = srv.get(k)
                if x is not None and (not isinstance(x, int)
                                      or isinstance(x, bool) or x < 0):
                    errs.append(f"serve.{k} must be None or a "
                                f"non-negative int, got {x!r}")
            tr = srv.get("transport")
            if tr is not None and tr not in _SERVE_TRANSPORTS:
                errs.append("serve.transport must be one of "
                            f"{_SERVE_TRANSPORTS}, got {tr!r}")
    lat = doc.get("latency")
    if lat is not None:
        if not isinstance(lat, dict):
            errs.append("latency must be None or a dict "
                        f"{{{', '.join(_LATENCY_KEYS)}}}")
        else:
            for k in lat:
                if k not in _LATENCY_KEYS + _LATENCY_OPT_KEYS:
                    errs.append(f"latency has unknown key: {k}")
            for k in ("p50_ms", "p95_ms", "p99_ms", "arrival_rate"):
                x = lat.get(k)
                if (not isinstance(x, (int, float))
                        or isinstance(x, bool) or x < 0):
                    errs.append(f"latency.{k} must be a non-negative "
                                f"number, got {x!r}")
            qd = lat.get("queue_depth_peak")
            if (not isinstance(qd, int) or isinstance(qd, bool)
                    or qd < 0):
                errs.append("latency.queue_depth_peak must be a "
                            f"non-negative int, got {qd!r}")
            ps = [lat.get(k) for k in ("p50_ms", "p95_ms", "p99_ms")]
            if (all(isinstance(p, (int, float))
                    and not isinstance(p, bool) for p in ps)
                    and not ps[0] <= ps[1] <= ps[2]):
                errs.append("latency percentiles must be ordered "
                            f"p50 <= p95 <= p99, got {ps}")
            sm = lat.get("samples_ms")
            if sm is not None and (
                    not isinstance(sm, list)
                    or any(not isinstance(x, (int, float))
                           or isinstance(x, bool) or x < 0
                           for x in sm)):
                errs.append("latency.samples_ms must be None or a "
                            "list of non-negative numbers")
    for k in ("label", "source", "metric", "unit"):
        if not isinstance(doc.get(k), str) or not doc.get(k):
            errs.append(f"{k} must be a non-empty string")
    v = doc.get("value")
    if not isinstance(v, (int, float)) or isinstance(v, bool) or v < 0:
        errs.append(f"value must be a non-negative number, got {v!r}")
    reps = doc.get("rep_times_s")
    if (not isinstance(reps, list)
            or any(not isinstance(t, (int, float)) or t <= 0
                   for t in reps)):
        errs.append("rep_times_s must be a list of positive numbers")
    if not isinstance(doc.get("config"), dict):
        errs.append("config must be a dict")
    q = doc.get("quiescent")
    if q is not None and not isinstance(q, bool):
        errs.append("quiescent must be None or bool")
    for k in ("steps", "retired"):
        x = doc.get(k)
        if x is not None and (not isinstance(x, int) or x < 0):
            errs.append(f"{k} must be None or a non-negative int")
    if errs:
        raise ValueError("invalid bench-history entry:\n  "
                         + "\n  ".join(errs))
    return doc


# lint: host
def append(path: str, doc: dict) -> None:
    """Append one validated entry to a JSONL history file."""
    validate_entry(doc)
    parent = os.path.dirname(os.path.abspath(path))
    os.makedirs(parent, exist_ok=True)
    with open(path, "a") as f:
        f.write(json.dumps(doc, sort_keys=True) + "\n")


# lint: host
def load(path: str) -> List[dict]:
    """Load and validate every entry of a JSONL history file (blank
    lines skipped); errors name the offending line."""
    out = []
    with open(path) as f:
        for i, line in enumerate(f, 1):
            if not line.strip():
                continue
            try:
                out.append(validate_entry(json.loads(line)))
            except ValueError as e:
                raise ValueError(f"{path}:{i}: {e}") from None
    return out


# lint: host
def _json_lines(text: str) -> List[dict]:
    docs = []
    for line in text.splitlines():
        line = line.strip()
        if line.startswith("{"):
            try:
                docs.append(json.loads(line))
            except json.JSONDecodeError:
                continue
    return docs


# lint: host
def ingest_capture(path: str, label: Optional[str] = None) -> dict:
    """Lift an archived capture into a v1 entry.

    Accepts either a round-driver capture (``BENCH_r*.json``: one JSON
    object whose ``tail`` holds bench.py's two output lines and whose
    ``parsed`` duplicates the stderr extra) or a raw file of bench.py
    output lines. The default label is the filename stem (``BENCH_r03``
    -> ``r03``).
    """
    with open(path) as f:
        text = f.read()
    stem = os.path.splitext(os.path.basename(path))[0]
    if label is None:
        label = stem[6:] if stem.startswith("BENCH_") else stem
    result, extra = None, None
    try:
        cap = json.loads(text)
    except json.JSONDecodeError:
        cap = None
    docs = (_json_lines(cap.get("tail", ""))
            if isinstance(cap, dict) and "tail" in cap
            else _json_lines(text))
    if isinstance(cap, dict):
        extra = cap.get("parsed")
    for d in docs:
        if "metric" in d and "value" in d:
            result = d
        elif "rep_times_s" in d:
            extra = d
    if result is None or extra is None:
        raise ValueError(
            f"{path}: no bench result/extra JSON lines found "
            "(expected a BENCH_r*.json driver capture or raw bench.py "
            "output)")
    cmd = cap.get("cmd") if isinstance(cap, dict) else None
    cfg = {"engine": extra.get("engine")}
    if cmd:
        cfg["cmd"] = cmd
    return entry(label, os.path.basename(path), result, extra,
                 config=cfg)


# lint: host
def ingest_multichip(path: str, label: Optional[str] = None) -> dict:
    """Lift a MULTICHIP_r*.json dryrun capture into a history entry.

    Multichip captures are *parity* records, not timings: each round's
    driver runs the sharded engines against their unsharded twins and
    reports bit-identity plus the largest machine validated. The entry
    therefore carries no rep vector (``rep_times_s=[]`` — bench-diff
    calls it incomparable, by design); its value is the max sharded
    node count proven bit-identical, which is the dashboard's scaling
    curve. The default label prefixes ``mc-`` so bench and multichip
    rows in one history file stay distinguishable.
    """
    import re
    with open(path) as f:
        cap = json.load(f)
    if not isinstance(cap, dict) or "n_devices" not in cap:
        raise ValueError(f"{path}: not a MULTICHIP capture "
                         "(no n_devices key)")
    stem = os.path.splitext(os.path.basename(path))[0]
    if label is None:
        label = ("mc-" + stem[10:] if stem.startswith("MULTICHIP_")
                 else "mc-" + stem)
    tail = cap.get("tail", "") or ""
    # the validated machine sizes appear as "<N>-node" or "<N> nodes"
    # in the dryrun report lines; the largest one is the rung proven
    nodes = [int(m) for m in re.findall(r"(\d+)[- ]nodes?\b", tail)]
    if not nodes:
        raise ValueError(f"{path}: no '<N> nodes' marker in tail — "
                         "cannot place it on the scaling curve")
    doc = {
        "schema": SCHEMA_ID,
        "label": str(label),
        "source": os.path.basename(path),
        "captured_at": None,
        "git_sha": None,
        "metric": "multichip sharded parity: max nodes bit-identical "
                  "to unsharded",
        "unit": "nodes",
        "value": float(max(nodes)),
        "vs_baseline": 0.0,
        "config": {"kind": "multichip",
                   "n_devices": int(cap.get("n_devices", 0)),
                   "ok": bool(cap.get("ok", False)),
                   "skipped": bool(cap.get("skipped", False))},
        "rep_times_s": [],
        "elapsed_s": None,
        "steps": None,
        "retired": None,
        "quiescent": None,
        "phases": None,
        "device_kind": None,
        "hlo_fingerprint": None,
        "cost": None,
        "serve": None,
        "latency": None,
    }
    return validate_entry(doc)


# lint: host
def last_two(path: str) -> tuple:
    """(previous, last) entries of a history file; ValueError when it
    holds fewer than two."""
    hist = load(path)
    if len(hist) < 2:
        raise ValueError(
            f"{path}: need at least 2 entries to diff, have {len(hist)}")
    return hist[-2], hist[-1]
