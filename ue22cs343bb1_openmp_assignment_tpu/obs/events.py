"""Live ops events: the ``cache-sim/events/v1`` structured stream.

The recording (obs.recording) is the REPLAY artifact — only accepted
submissions and finished jobs, enough to re-drive the traffic. This
module is the OPERATIONS artifact: every scheduler decision a person
watching a live daemon wants to see, as one validated, ring-bounded
event stream the ``watch`` socket verb pushes to clients:

========================= =============================================
kind                      emitted when (daemon/core.py)
========================= =============================================
``submit-accepted``       a job lands in a lane queue (lane, depth)
``lane-reject``           explicit backpressure: full lane or draining
``admitted``              a job takes a slot (lane, bucket, wave, slot)
``quiesced``              a job extracts (ok, cycles, bucket, e2e_ms)
``result-evicted``        retention dropped a terminal job's payload
``bucket-growth``         an idle bucket grew to cover a new shape
``slo-alert``             the burn-rate monitor fired (obs.burnrate)
========================= =============================================

Every event row is ``{"seq", "t_s", "kind", "job", ...kind fields}``:
``seq`` is a per-emitter monotonic counter, ``t_s`` the injected
clock's offset from the core's start. Under a VirtualClock the whole
stream is a pure function of the submission schedule — two identical
sessions serialize byte-identically (sorted keys, one clock), the
determinism gate in tests/test_ops_plane.py.

The in-memory ring keeps the newest ``ring`` rows (``dropped`` counts
what scrolled off — a watch client that falls behind sees the gap in
``seq``); ``--events-dir`` additionally streams every row to
``events.jsonl`` with a recording-style header line, flushed per row.

Host-side and dependency-free like the rest of obs (socket servers
import this module, so it must never reach jax).
"""
# lint: host

from __future__ import annotations

import json
import os
from typing import List, Optional

SCHEMA_ID = "cache-sim/events/v1"

#: canonical file name inside an ``--events-dir`` directory
FILENAME = "events.jsonl"

#: every event kind the core emits, in rough lifecycle order
KINDS = ("submit-accepted", "lane-reject", "admitted", "quiesced",
         "result-evicted", "bucket-growth", "slo-alert")

#: default in-memory ring bound (rows)
DEFAULT_RING = 4096

_HEADER_KEYS = ("schema", "clock", "ring", "config")
_ROW_KEYS = ("seq", "t_s", "kind", "job")


# lint: host
def _line(row: dict) -> str:
    return json.dumps(row, sort_keys=True) + "\n"


# lint: host
def _target(path) -> str:
    """``--events-dir`` convention, mirroring obs.recording: anything
    not explicitly ``.jsonl`` is a directory that gets
    :data:`FILENAME` inside it."""
    path = str(path)
    if not path.endswith(".jsonl"):
        os.makedirs(path, exist_ok=True)
        return os.path.join(path, FILENAME)
    parent = os.path.dirname(os.path.abspath(path))
    os.makedirs(parent, exist_ok=True)
    return path


class EventEmitter:
    """Ring-bounded structured event sink the core emits into.

    ``emit`` is synchronous and allocation-cheap: one dict appended to
    the ring (oldest rows dropped beyond ``ring``, counted in
    ``dropped``) and, when a path was given, one flushed JSONL line —
    a killed daemon still leaves a valid event-stream prefix on disk.
    """

    # lint: host
    def __init__(self, clock_kind: str, ring: int = DEFAULT_RING,
                 path=None, config: Optional[dict] = None):
        if ring < 1:
            raise ValueError(f"ring must be >= 1, got {ring}")
        self.clock_kind = str(clock_kind)
        self.ring = int(ring)
        self.seq = 0               # next seq to assign == rows emitted
        self.dropped = 0           # rows scrolled off the ring
        self.rows: List[dict] = []
        self.path: Optional[str] = None
        self._f = None
        if path is not None:
            self.path = _target(path)
            self._f = open(self.path, "w")
            self._f.write(_line({"schema": SCHEMA_ID,
                                 "clock": self.clock_kind,
                                 "ring": self.ring,
                                 "config": dict(config or {})}))
            self._f.flush()

    # lint: host
    def emit(self, kind: str, t_s: float, job: Optional[str] = None,
             **fields) -> dict:
        if kind not in KINDS:
            raise ValueError(f"unknown event kind {kind!r} "
                             f"(one of {KINDS})")
        row = {"seq": self.seq, "t_s": float(t_s), "kind": kind,
               "job": job, **fields}
        self.seq += 1
        self.rows.append(row)
        if len(self.rows) > self.ring:
            del self.rows[:len(self.rows) - self.ring]
            self.dropped = self.seq - len(self.rows)
        if self._f is not None:
            self._f.write(_line(row))
            self._f.flush()
        return row

    # lint: host
    def since(self, seq: int) -> List[dict]:
        """Every retained row with ``seq >= seq`` — the watch verb's
        cursor read (a client that fell behind the ring sees a seq
        gap, never a stall)."""
        return [r for r in self.rows if r["seq"] >= seq]

    # lint: host
    def dumps(self) -> str:
        """The retained ring serialized as the canonical byte stream
        (sorted keys, one row per line) — what the determinism gate
        compares across runs."""
        return "".join(_line(r) for r in self.rows)

    # lint: host
    def close(self) -> None:
        if self._f is not None and not self._f.closed:
            self._f.close()


# lint: host
def validate(header: Optional[dict], rows: List[dict],
             where: str = "events") -> None:
    """Structural check (the obs.schema contract: raise ValueError
    listing every violation). ``header`` is None for a bare in-memory
    ring; rows must carry the base keys, a known kind, strictly
    increasing ``seq``, and non-decreasing ``t_s``."""
    errs = []
    if header is not None:
        if header.get("schema") != SCHEMA_ID:
            errs.append(f"schema must be {SCHEMA_ID!r}, "
                        f"got {header.get('schema')!r}")
        if header.get("clock") not in ("monotonic", "virtual"):
            errs.append(f"clock must be monotonic|virtual, "
                        f"got {header.get('clock')!r}")
        for k in _HEADER_KEYS:
            if k not in header:
                errs.append(f"header missing key: {k}")
    last_seq = None
    last_t = None
    for i, row in enumerate(rows):
        for k in _ROW_KEYS:
            if k not in row:
                errs.append(f"row {i}: missing key {k!r}")
        kind = row.get("kind")
        if kind not in KINDS:
            errs.append(f"row {i}: kind must be one of {KINDS}, "
                        f"got {kind!r}")
        seq = row.get("seq")
        if not isinstance(seq, int) or isinstance(seq, bool) or seq < 0:
            errs.append(f"row {i}: seq must be a non-negative int, "
                        f"got {seq!r}")
        elif last_seq is not None and seq <= last_seq:
            errs.append(f"row {i}: seq must be strictly increasing "
                        f"({seq} after {last_seq})")
        else:
            last_seq = seq
        t = row.get("t_s")
        if not isinstance(t, (int, float)) or isinstance(t, bool) \
                or t < 0:
            errs.append(f"row {i}: t_s must be a non-negative number, "
                        f"got {t!r}")
        elif last_t is not None and t < last_t:
            errs.append(f"row {i}: t_s must be non-decreasing "
                        f"({t} after {last_t})")
        else:
            last_t = t
        job = row.get("job")
        if job is not None and (not isinstance(job, str) or not job):
            errs.append(f"row {i}: job must be None or a non-empty "
                        f"string, got {job!r}")
    if errs:
        raise ValueError(f"invalid {where}:\n  " + "\n  ".join(errs))


# lint: host
def load(path) -> dict:
    """Read + validate an ``--events-dir`` artifact; returns
    ``{"schema", "clock", "ring", "config", "rows", "path"}``."""
    path = str(path)
    if os.path.isdir(path):
        path = os.path.join(path, FILENAME)
    header = None
    rows: List[dict] = []
    with open(path) as f:
        for line in f:
            if not line.strip():
                continue
            doc = json.loads(line)
            if header is None:
                header = doc
            else:
                rows.append(doc)
    if header is None:
        raise ValueError(f"{path}: empty event stream (no header line)")
    validate(header, rows, where=path)
    return {"schema": header["schema"], "clock": header["clock"],
            "ring": header["ring"], "config": header["config"],
            "rows": rows, "path": path}
