"""Coherence profiler: per-line contention attribution host-side.

The device side (ops/step.py ``with_profile`` / run_cycles_profile,
ops/deep_engine.run_deep_profile, ops/sync_engine.run_sync_profile)
accumulates per-(node, address) counter planes inside the engines' own
one-dispatch scans — misses split by cause, invalidation fan-out,
writebacks, ownership migrations, and for the deep engine the
per-address abort attribution that turns PERF.md's "~2/3 of poison
flags are ghosts" hand estimate into a measured number. This module is
everything after the device fetch: a sharing-pattern classifier that
labels each block private / read-shared / migratory / producer-consumer
/ false-sharing (the block-vs-variable granularity signal — logically
disjoint write-mostly variables colliding on one coherence unit), the
top-K contended-line table, and the validated ``cache-sim/profile/v1``
doc that ``cache-sim profile`` emits, flight-recorder incidents embed
and the dashboard renders.

Miss-taxonomy lineage: Hill & Smith's 3C classification (PAPERS.md)
with capacity/conflict collapsed (direct-mapped cache) and the two
classes a directory protocol adds — coherence-invalidation misses and
upgrades (permission misses).

Classifier thresholds are module constants, pinned by the workload
fingerprint matrix in tests/test_cohprof.py: every builtin generator
must classify as its known dominant pattern (false_sharing_vars_padded
must come out private — the padding fix made *observable*).
"""

from __future__ import annotations

from typing import Optional

import numpy as np

SCHEMA_ID = "cache-sim/profile/v1"

#: sharing patterns in classification-precedence order (earlier rules
#: win; ``dominant`` ties also resolve in this order)
PATTERNS = ("private", "read_shared", "producer_consumer",
            "false_sharing", "migratory")

#: miss-taxonomy columns — MUST match ops.step.PROFILE_MISS_CLASSES
MISS_CLASSES = ("cold", "conflict_eviction", "coherence_invalidation",
                "upgrade")

#: deep abort-attribution columns — MUST match
#: ops.deep_engine.PROFILE_ABORT_CLASSES
ABORT_CLASSES = ("poison_ghost", "poison_real", "mark", "lane_loss",
                 "probe")

#: deep window-stop columns — MUST match
#: ops.deep_engine.PROFILE_STOP_CLASSES
STOP_CLASSES = ("over_q", "over_g", "dup", "dep", "live")

#: read-shared threshold: writes at most this fraction of a line's
#: total accesses (lock-free read-mostly data; a few init writes
#: don't disqualify)
READ_SHARED_WF = 0.05

#: false-sharing threshold: >= 2 writers whose MEAN per-writer write
#: fraction is at least this — each node treats its slice of the line
#: as a write-mostly private variable (the false_sharing_vars shape,
#: write_frac 0.75), unlike migratory read-modify-write sharing
#: (fractions near 0.5) or producer-consumer (reader/writer split)
FALSE_SHARING_WF = 0.65

_TOP_KEYS = ("schema", "engine", "nodes", "addr_space", "steps",
             "step_unit", "accesses", "miss_classes", "invalidations",
             "writebacks", "ownership_migrations", "sharing",
             "top_contended", "abort_anatomy", "extra")

_TOP_LINE_KEYS = ("addr", "home", "block", "pattern", "nodes",
                  "readers", "writers", "reads", "writes", "score")


# -- classifier -------------------------------------------------------------

# lint: host
def classify(rd, wr) -> np.ndarray:
    """Label every address with a sharing pattern.

    ``rd``/``wr`` are [N, A] per-(node, address) access counts; returns
    an [A] int array of indices into PATTERNS, -1 for untouched
    addresses. Precedence: a single-accessor line is private; a shared
    line with (almost) no writes is read-shared; disjoint writer and
    reader sets are producer-consumer; multiple write-mostly writers
    are false-sharing (block-granularity collisions of logically
    private variables); everything else shared is migratory
    (read-modify-write ownership hand-off).
    """
    rd = np.asarray(rd, dtype=np.int64)
    wr = np.asarray(wr, dtype=np.int64)
    tot_r, tot_w = rd.sum(axis=0), wr.sum(axis=0)
    tot = tot_r + tot_w
    acc = (rd + wr) > 0
    n_acc = acc.sum(axis=0)
    writers, readers = wr > 0, rd > 0
    n_wr = writers.sum(axis=0)
    n_rw = (writers & readers).sum(axis=0)
    n_rd_only = (readers & ~writers).sum(axis=0)
    # mean per-writer write fraction (how write-mostly each writer is)
    with np.errstate(divide="ignore", invalid="ignore"):
        wf_node = np.where(writers, wr / np.maximum(rd + wr, 1), 0.0)
    mean_wf = wf_node.sum(axis=0) / np.maximum(n_wr, 1)

    pat = np.full(tot.shape, -1, dtype=np.int64)
    used = tot > 0
    pat[used & (n_acc == 1)] = PATTERNS.index("private")
    shared = used & (n_acc >= 2)

    def free(extra):
        return shared & (pat == -1) & extra

    pat[free(tot_w <= READ_SHARED_WF * tot)] = \
        PATTERNS.index("read_shared")
    pat[free((n_wr >= 1) & (n_rw == 0) & (n_rd_only >= 1))] = \
        PATTERNS.index("producer_consumer")
    pat[free((n_wr >= 2) & (mean_wf >= FALSE_SHARING_WF))] = \
        PATTERNS.index("false_sharing")
    pat[free(np.ones_like(shared))] = PATTERNS.index("migratory")
    return pat


# lint: host
def sharing_section(rd, wr, pat) -> dict:
    """The doc's ``sharing`` block: per-pattern line/access counts and
    the accesses-weighted dominant pattern (None if nothing was
    touched; ties resolve in PATTERNS order)."""
    tot = np.asarray(rd, dtype=np.int64).sum(axis=0) \
        + np.asarray(wr, dtype=np.int64).sum(axis=0)
    by = {}
    best, best_acc = None, -1
    for i, name in enumerate(PATTERNS):
        m = pat == i
        lines, accesses = int(m.sum()), int(tot[m].sum())
        by[name] = {"lines": lines, "accesses": accesses}
        if accesses > best_acc:
            best, best_acc = name, accesses
    classified = int((pat >= 0).sum())
    return {"classified_lines": classified,
            "by_pattern": by,
            "dominant": best if classified else None}


# lint: host
def top_contended(block_bits: int, rd, wr, pat, k: int = 8,
                  miss_addr=None, inv_addr=None, mig_addr=None,
                  abort_addr=None) -> list:
    """Top-k contended lines, most contended first.

    The contention score of a line is its access total if 2+ nodes
    touch it (a private line cannot contend), plus every per-address
    protocol-event count that was measured (misses, invalidations,
    migrations, deep aborts) — so protocol churn outranks plain volume
    at equal traffic. Deterministic: ties break on lower address.
    """
    rd = np.asarray(rd, dtype=np.int64)
    wr = np.asarray(wr, dtype=np.int64)
    tot_r, tot_w = rd.sum(axis=0), wr.sum(axis=0)
    n_acc = ((rd + wr) > 0).sum(axis=0)
    score = np.where(n_acc >= 2, tot_r + tot_w, 0)
    extras = {}
    for name, arr in (("misses", miss_addr), ("invalidations", inv_addr),
                      ("migrations", mig_addr), ("aborts", abort_addr)):
        if arr is not None:
            arr = np.asarray(arr, dtype=np.int64)
            if arr.ndim == 2:          # per-class planes: sum classes
                arr = arr.sum(axis=1)
            extras[name] = arr
            score = score + arr
    order = np.lexsort((np.arange(score.shape[0]), -score))
    out = []
    for a in order[:k]:
        if score[a] <= 0:
            break
        a = int(a)
        row = {
            "addr": a,
            "home": a >> block_bits,
            "block": a & ((1 << block_bits) - 1),
            "pattern": PATTERNS[pat[a]] if pat[a] >= 0 else None,
            "nodes": int(n_acc[a]),
            "readers": int((rd[:, a] > 0).sum()),
            "writers": int((wr[:, a] > 0).sum()),
            "reads": int(tot_r[a]),
            "writes": int(tot_w[a]),
            "score": int(score[a]),
        }
        for name, arr in extras.items():
            row[name] = int(arr[a])
        out.append(row)
    return out


# -- doc builders -----------------------------------------------------------

# lint: host
def _fanout_doc(counts) -> dict:
    """Fan-out histogram doc: power-of-two buckets (bucket_lo 0, 1, 2,
    4, ... like the latency histogram; bucket 0 is structurally always
    zero — no-victim broadcasts record nothing — but kept so counts
    align with ops.step.FANOUT_BUCKETS)."""
    counts = [int(c) for c in np.asarray(counts)]
    lo = [0] + [1 << (b - 1) for b in range(1, len(counts))]
    return {"bucket_lo": lo, "counts": counts}


# lint: host
def _base_doc(engine: str, nodes: int, addr_space: int, steps: int,
              step_unit: str, rd, wr) -> dict:
    pat = classify(rd, wr)
    return {
        "schema": SCHEMA_ID,
        "engine": engine,
        "nodes": int(nodes),
        "addr_space": int(addr_space),
        "steps": int(steps),
        "step_unit": step_unit,
        "accesses": {"reads": int(np.asarray(rd, np.int64).sum()),
                     "writes": int(np.asarray(wr, np.int64).sum())},
        "miss_classes": None,
        "invalidations": None,
        "writebacks": None,
        "ownership_migrations": None,
        "sharing": sharing_section(rd, wr, pat),
        "top_contended": [],
        "abort_anatomy": None,
        "extra": {},
    }, pat


# lint: host
def from_async(cfg, prof, steps: int, k: int = 8) -> dict:
    """Build the v1 doc from an async run_cycles_profile plane."""
    rd, wr = np.asarray(prof["rd"]), np.asarray(prof["wr"])
    doc, pat = _base_doc("async", cfg.num_nodes,
                         cfg.num_nodes << cfg.block_bits, steps,
                         "cycles", rd, wr)
    ma = np.asarray(prof["miss_addr"], dtype=np.int64)
    doc["miss_classes"] = {
        name: int(ma[:, i].sum()) for i, name in enumerate(MISS_CLASSES)}
    doc["invalidations"] = {
        "applied": int(np.asarray(prof["inv_addr"], np.int64).sum()),
        "fanout_hist": _fanout_doc(prof["inv_fanout"]),
    }
    doc["writebacks"] = int(np.asarray(prof["wb_addr"], np.int64).sum())
    doc["ownership_migrations"] = int(
        np.asarray(prof["mig_addr"], np.int64).sum())
    doc["top_contended"] = top_contended(
        cfg.block_bits, rd, wr, pat, k, miss_addr=ma,
        inv_addr=prof["inv_addr"], mig_addr=prof["mig_addr"])
    return doc


# lint: host
def from_sync(cfg, rd, wr, steps: int, k: int = 8) -> dict:
    """Build the v1 doc from a sync run_sync_profile capture: access
    planes and the classifier only (None = not measured for the
    message-level counters, per the schema's optional-block rule)."""
    doc, pat = _base_doc("sync", cfg.num_nodes,
                         cfg.num_nodes << cfg.block_bits, steps,
                         "rounds", rd, wr)
    doc["top_contended"] = top_contended(cfg.block_bits, rd, wr, pat, k)
    return doc


# lint: host
def from_deep(cfg, prof, steps: int, k: int = 8) -> dict:
    """Build the v1 doc from a deep run_deep_profile plane, including
    the measured abort anatomy (the ghost-poison fraction is
    1 - committed/raised, None when no poison flag was raised)."""
    rd, wr = np.asarray(prof["rd"]), np.asarray(prof["wr"])
    doc, pat = _base_doc("deep", cfg.num_nodes,
                         cfg.num_nodes << cfg.block_bits, steps,
                         "rounds", rd, wr)
    ab_node = np.asarray(prof["abort_node"], dtype=np.int64)
    stops = np.asarray(prof["stops"], dtype=np.int64)
    raised = int(np.asarray(prof["poison_raised"]))
    committed = int(np.asarray(prof["poison_committed"]))
    nn = max(int(cfg.num_nodes) * max(int(steps), 1), 1)
    doc["abort_anatomy"] = {
        "rounds": int(steps),
        "aborts": {name: int(ab_node[:, i].sum())
                   for i, name in enumerate(ABORT_CLASSES)},
        "window_stops": {name: int(stops[i].sum())
                         for i, name in enumerate(STOP_CLASSES)},
        "poison_flags": {
            "raised": raised,
            "committed": committed,
            "ghost_fraction": (round(1.0 - committed / raised, 6)
                               if raised else None),
        },
        "aborts_per_node_round": {
            name: round(float(ab_node[:, i].sum()) / nn, 6)
            for i, name in enumerate(ABORT_CLASSES)},
        "retired": int(np.asarray(prof["n_ret"], np.int64).sum()),
    }
    doc["top_contended"] = top_contended(
        cfg.block_bits, rd, wr, pat, k, abort_addr=prof["abort_addr"])
    return doc


# -- validation -------------------------------------------------------------

# lint: host
def _nonneg_int(v) -> bool:
    return isinstance(v, int) and not isinstance(v, bool) and v >= 0


# lint: host
def _check_class_dict(d, keys, where: str, errs) -> None:
    if not isinstance(d, dict) or set(d) != set(keys):
        errs.append(f"{where} must be a dict with keys {keys}")
        return
    for kk, v in d.items():
        if not _nonneg_int(v):
            errs.append(f"{where}[{kk!r}] must be a non-negative int, "
                        f"got {v!r}")


# lint: host
def validate(doc: dict) -> dict:
    """Check a profile doc against cache-sim/profile/v1; returns the
    doc, raises ValueError listing every violation. Dependency-free
    like obs.schema — the container has no jsonschema."""
    errs = []
    if not isinstance(doc, dict):
        raise ValueError(f"profile must be a dict, "
                         f"got {type(doc).__name__}")
    for k in _TOP_KEYS:
        if k not in doc:
            errs.append(f"missing key: {k}")
    for k in doc:
        if k not in _TOP_KEYS:
            errs.append(f"unknown key: {k}")
    if doc.get("schema") != SCHEMA_ID:
        errs.append(f"schema must be {SCHEMA_ID!r}, "
                    f"got {doc.get('schema')!r}")
    if doc.get("engine") not in ("async", "sync", "deep"):
        errs.append(f"engine must be async|sync|deep, "
                    f"got {doc.get('engine')!r}")
    if doc.get("step_unit") not in ("cycles", "rounds"):
        errs.append(f"step_unit must be cycles|rounds, "
                    f"got {doc.get('step_unit')!r}")
    for k in ("nodes", "addr_space", "steps"):
        if not _nonneg_int(doc.get(k)):
            errs.append(f"{k} must be a non-negative int, "
                        f"got {doc.get(k)!r}")
    acc = doc.get("accesses")
    if not isinstance(acc, dict) or set(acc) != {"reads", "writes"} \
            or not all(_nonneg_int(v) for v in acc.values()):
        errs.append("accesses must be {reads, writes} of "
                    "non-negative ints")
    if doc.get("miss_classes") is not None:
        _check_class_dict(doc["miss_classes"], MISS_CLASSES,
                          "miss_classes", errs)
    inv = doc.get("invalidations")
    if inv is not None:
        if not isinstance(inv, dict) \
                or set(inv) != {"applied", "fanout_hist"}:
            errs.append("invalidations must be None or "
                        "{applied, fanout_hist}")
        else:
            if not _nonneg_int(inv["applied"]):
                errs.append("invalidations.applied must be a "
                            "non-negative int")
            h = inv["fanout_hist"]
            if (not isinstance(h, dict)
                    or set(h) != {"bucket_lo", "counts"}
                    or len(h.get("bucket_lo", [])) !=
                    len(h.get("counts", []))
                    or h.get("bucket_lo", []) !=
                    sorted(set(h.get("bucket_lo", [1])))
                    or not all(_nonneg_int(c)
                               for c in h.get("counts", [None]))):
                errs.append("invalidations.fanout_hist must be "
                            "{bucket_lo, counts} with strictly "
                            "increasing bucket_lo and non-negative "
                            "counts of the same length")
    for k in ("writebacks", "ownership_migrations"):
        v = doc.get(k)
        if v is not None and not _nonneg_int(v):
            errs.append(f"{k} must be None or a non-negative int, "
                        f"got {v!r}")
    sh = doc.get("sharing")
    if not isinstance(sh, dict) \
            or set(sh) != {"classified_lines", "by_pattern", "dominant"}:
        errs.append("sharing must be "
                    "{classified_lines, by_pattern, dominant}")
    else:
        if not _nonneg_int(sh["classified_lines"]):
            errs.append("sharing.classified_lines must be a "
                        "non-negative int")
        bp = sh["by_pattern"]
        if not isinstance(bp, dict) or set(bp) != set(PATTERNS):
            errs.append(f"sharing.by_pattern must have keys {PATTERNS}")
        else:
            for p, ent in bp.items():
                if (not isinstance(ent, dict)
                        or set(ent) != {"lines", "accesses"}
                        or not all(_nonneg_int(v)
                                   for v in ent.values())):
                    errs.append(f"sharing.by_pattern[{p!r}] must be "
                                "{lines, accesses} of non-negative "
                                "ints")
        if sh["dominant"] is not None and sh["dominant"] not in PATTERNS:
            errs.append(f"sharing.dominant must be None or one of "
                        f"{PATTERNS}, got {sh['dominant']!r}")
    tc = doc.get("top_contended")
    if not isinstance(tc, list):
        errs.append("top_contended must be a list")
    else:
        for i, row in enumerate(tc):
            if not isinstance(row, dict) \
                    or any(k not in row for k in _TOP_LINE_KEYS):
                errs.append(f"top_contended[{i}] must carry "
                            f"{_TOP_LINE_KEYS}")
            elif row["pattern"] is not None \
                    and row["pattern"] not in PATTERNS:
                errs.append(f"top_contended[{i}].pattern must be None "
                            f"or one of {PATTERNS}")
    ab = doc.get("abort_anatomy")
    if ab is not None:
        want = {"rounds", "aborts", "window_stops", "poison_flags",
                "aborts_per_node_round", "retired"}
        if not isinstance(ab, dict) or set(ab) != want:
            errs.append(f"abort_anatomy must be None or a dict with "
                        f"keys {tuple(sorted(want))}")
        else:
            for k in ("rounds", "retired"):
                if not _nonneg_int(ab[k]):
                    errs.append(f"abort_anatomy.{k} must be a "
                                "non-negative int")
            _check_class_dict(ab["aborts"], ABORT_CLASSES,
                              "abort_anatomy.aborts", errs)
            _check_class_dict(ab["window_stops"], STOP_CLASSES,
                              "abort_anatomy.window_stops", errs)
            pf = ab["poison_flags"]
            if (not isinstance(pf, dict)
                    or set(pf) != {"raised", "committed",
                                   "ghost_fraction"}
                    or not _nonneg_int(pf.get("raised"))
                    or not _nonneg_int(pf.get("committed"))):
                errs.append("abort_anatomy.poison_flags must be "
                            "{raised, committed, ghost_fraction} with "
                            "non-negative int counts")
            else:
                gf = pf["ghost_fraction"]
                if pf["raised"] == 0:
                    if gf is not None:
                        errs.append("ghost_fraction must be None when "
                                    "no poison flag was raised")
                elif (not isinstance(gf, (int, float))
                      or isinstance(gf, bool)
                      or not 0.0 <= float(gf) <= 1.0):
                    errs.append("ghost_fraction must be a float in "
                                f"[0, 1], got {gf!r}")
            ar = ab["aborts_per_node_round"]
            if (not isinstance(ar, dict)
                    or set(ar) != set(ABORT_CLASSES)
                    or not all(isinstance(v, (int, float))
                               and not isinstance(v, bool) and v >= 0
                               for v in ar.values())):
                errs.append("abort_anatomy.aborts_per_node_round must "
                            f"map {ABORT_CLASSES} to non-negative "
                            "numbers")
    if not isinstance(doc.get("extra"), dict):
        errs.append("extra must be a dict")
    if errs:
        raise ValueError("invalid profile doc:\n  " + "\n  ".join(errs))
    return doc


# -- capture orchestration --------------------------------------------------

# lint: host
def capture_async(cfg, state0, cycles: int, message_phase=None,
                  k: int = 8) -> dict:
    """Profiled deterministic replay of `cycles` async cycles from
    `state0` (the flight recorder's replay-from-initial-state
    discipline: same engine, same cycle count, profile plane on)."""
    from ue22cs343bb1_openmp_assignment_tpu.ops import step
    _, prof = step.run_cycles_profile(cfg, state0, cycles,
                                      message_phase)
    return validate(from_async(cfg, prof, cycles, k))


# lint: host
def capture_sync(cfg, st0, rounds: int, k: int = 8) -> dict:
    """Profiled replay of `rounds` sync rounds from SyncState `st0`."""
    from ue22cs343bb1_openmp_assignment_tpu.ops import sync_engine
    _, rd, wr = sync_engine.run_sync_profile(cfg, st0, rounds)
    return validate(from_sync(cfg, rd, wr, rounds, k))


# lint: host
def capture_deep(cfg, st0, rounds: int, k: int = 8) -> dict:
    """Profiled replay of `rounds` deep rounds from SyncState `st0`,
    with the measured abort anatomy (XLA fold)."""
    from ue22cs343bb1_openmp_assignment_tpu.ops import deep_engine
    _, prof = deep_engine.run_deep_profile(cfg, st0, rounds)
    return validate(from_deep(cfg, prof, rounds, k))


# -- rendering --------------------------------------------------------------

# lint: host
def render_text(doc: dict) -> str:
    """One-screen plain-text rendering (the `cache-sim profile` default
    and the perf-report/dashboard block)."""
    lines = [f"coherence profile [{doc['engine']}] — "
             f"{doc['steps']} {doc['step_unit']}, "
             f"{doc['nodes']} nodes, addr space {doc['addr_space']}"]
    acc = doc["accesses"]
    lines.append(f"  accesses: {acc['reads']} rd / {acc['writes']} wr")
    mc = doc["miss_classes"]
    if mc is not None:
        tot = sum(mc.values())
        parts = ", ".join(f"{k} {v}" for k, v in mc.items())
        lines.append(f"  misses ({tot}): {parts}")
    inv = doc["invalidations"]
    if inv is not None:
        h = inv["fanout_hist"]
        nz = [f"[{lo}+]x{c}" for lo, c in zip(h["bucket_lo"],
                                              h["counts"]) if c]
        lines.append(f"  invalidations: {inv['applied']} applied; "
                     f"fan-out {' '.join(nz) if nz else '-'}")
    if doc["writebacks"] is not None:
        lines.append(f"  writebacks: {doc['writebacks']}  "
                     f"migrations: {doc['ownership_migrations']}")
    sh = doc["sharing"]
    by = ", ".join(
        f"{p} {sh['by_pattern'][p]['lines']}"
        for p in PATTERNS if sh["by_pattern"][p]["lines"])
    lines.append(f"  sharing ({sh['classified_lines']} lines, "
                 f"dominant {sh['dominant']}): {by if by else '-'}")
    ab = doc["abort_anatomy"]
    if ab is not None:
        a = ab["aborts"]
        parts = ", ".join(f"{k} {v}" for k, v in a.items() if v)
        gf = ab["poison_flags"]["ghost_fraction"]
        lines.append(f"  aborts: {parts if parts else '-'}; "
                     f"poison flags {ab['poison_flags']['raised']} "
                     f"raised / {ab['poison_flags']['committed']} "
                     f"committed"
                     + (f" (ghost fraction {gf})" if gf is not None
                        else ""))
        st = ab["window_stops"]
        parts = ", ".join(f"{k} {v}" for k, v in st.items() if v)
        lines.append(f"  window stops: {parts if parts else '-'}")
    if doc["top_contended"]:
        lines.append("  top contended lines:")
        for row in doc["top_contended"]:
            extras = "".join(
                f" {k}={row[k]}" for k in ("misses", "invalidations",
                                           "migrations", "aborts")
                if k in row)
            lines.append(
                f"    addr {row['addr']} (home {row['home']} block "
                f"{row['block']}): {row['pattern']}, "
                f"{row['nodes']} nodes ({row['writers']}w/"
                f"{row['readers']}r), {row['reads']}rd+"
                f"{row['writes']}wr score {row['score']}{extras}")
    return "\n".join(lines)
