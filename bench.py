"""Headline benchmark: simulated RD/WR instructions/sec on one chip.

North star (BASELINE.json): >= 1e8 simulated instrs/sec at 4096 simulated
cores on one TPU v5e chip, with printProcessorState byte-parity on the
reference suites (covered by tests/). The reference publishes no
throughput numbers (BASELINE.md), so vs_baseline is measured against the
north-star target.

Engines (see PERF.md for the measured rationale):
  deep   (default) — deep-window transactional engine
         (ops.deep_engine + ops.pallas_deep): dense own-entry
         transaction chains + absorbed remote events; the throughput
         path.
  sync   — multi-transaction window engine (ops.sync_engine): atomic
         whole-transaction rounds, no mailboxes.
  async  — message-level engine (ops.step): reference network semantics
         cycle by cycle; the parity/race-research path.

Prints exactly one JSON line:
  {"metric": ..., "value": N, "unit": ..., "vs_baseline": N}
"""

import argparse
import json
import os
import sys
import time


def _serve_bench(args, jax):
    """--serve: jobs/sec through the batched serving layer.

    The measured unit is one full serve() pass over the fixed traffic
    mix (serve.mixed_jobs: uniform / false_sharing / producer_consumer
    / hotspot cycling, seeds 0..J-1) at the job shape --nodes x
    --trace-len, through --serve-slots batch slots. The metric string
    deliberately excludes the slot count: batch-B and the sequential
    baseline (--serve-slots 1) record the same metric, so bench-diff
    adjudicates batching as a regular IMPROVEMENT/REGRESSION verdict.
    Padding waste rides the entry's serve block — a jobs/sec win that
    came from padding shrinkage would show there.
    """
    from ue22cs343bb1_openmp_assignment_tpu import serve as serve_mod

    n_jobs = args.serve_jobs or 2 * args.serve_slots
    specs = serve_mod.mixed_jobs(n_jobs, nodes=args.nodes,
                                 trace_len=args.trace_len)
    max_cycles = args.max_cycles or 100_000
    # the false-sharing mix component makes every node hammer one home
    # block: at the scale-default queue_capacity=64 the home mailbox
    # overflows (silent-drop quirk 6) and the dropped requester waits
    # forever, so the mix scales capacity with the node count
    qcap = args.queue_capacity or max(64, 2 * args.nodes)

    def run():
        return serve_mod.serve(specs, slots=args.serve_slots,
                               chunk=args.chunk, max_cycles=max_cycles,
                               queue_capacity=qcap,
                               devices=args.devices)

    from ue22cs343bb1_openmp_assignment_tpu.obs.phases import PhaseTimer
    timer = PhaseTimer()
    with timer.phase("warmup_compile"):
        run()                      # compiles the wave for this slot shape

    times = []
    for _ in range(args.reps):
        t0 = time.perf_counter()
        doc = run()
        times.append(time.perf_counter() - t0)
        timer.add("serve_pass", times[-1])
    times.sort()
    elapsed = times[len(times) // 2]
    value = n_jobs / elapsed
    platform = jax.devices()[0].platform
    # like the slot count, the device count stays OUT of the metric
    # string: a 1-device and an N-device serve record the same metric,
    # so bench-diff adjudicates batch-axis sharding as a regular
    # IMPROVEMENT/REGRESSION verdict (the count rides the serve block
    # and the fingerprint)
    result = {
        "metric": f"serve jobs/sec @{args.nodes}x{args.trace_len} "
                  f"x{n_jobs} jobs (async engine, mixed traffic, "
                  f"{platform})",
        "value": round(value, 2),
        "unit": "jobs/sec",
        "vs_baseline": 0.0,
    }
    quiet = doc["jobs_quiesced"] == doc["jobs_total"]
    retired = sum(j["metrics"]["instrs_retired"]
                  for j in doc["jobs"].values())
    extra = {
        "engine": "async",
        "steps": doc["wave_count"],
        "retired": retired,
        "quiescent": quiet,
        "elapsed_s": round(elapsed, 3),
        "rep_times_s": [round(t, 3) for t in times],
        "phases": timer.report(),
        "serve": {"slots": args.serve_slots, "jobs": n_jobs,
                  "waves": doc["wave_count"],
                  "devices": args.devices,
                  "mb_dropped": doc["mb_dropped"],
                  "padding_waste": round(doc["padding_waste"], 4)},
    }
    print(json.dumps(result))
    print(json.dumps(extra), file=sys.stderr)

    if args.record:
        from ue22cs343bb1_openmp_assignment_tpu.obs import (
            history, roofline)
        fingerprint = {
            "engine": "async", "mode": "serve",
            "workload": "mixed", "nodes": args.nodes,
            "trace_len": args.trace_len, "chunk": args.chunk,
            "reps": args.reps, "max_cycles": max_cycles,
            "slots": args.serve_slots, "jobs": n_jobs,
            "devices": args.devices,
            "platform": platform, "smoke": bool(args.smoke),
        }
        hist_doc = history.entry(
            label=f"serve@{args.serve_slots}",
            source="bench.py",
            result=result, extra=extra, config=fingerprint,
            sha=history.git_sha(os.path.dirname(
                os.path.abspath(__file__))),
            captured_at=time.strftime("%Y-%m-%dT%H:%M:%S%z"),
            device_kind=roofline.detect_device_kind(),
            serve=extra["serve"])
        history.append(args.record, hist_doc)
        print(f"recorded to {args.record}", file=sys.stderr)

    if not quiet:
        print(f"error: {doc['jobs_total'] - doc['jobs_quiesced']} "
              f"job(s) hit the {max_cycles}-cycle budget without "
              "quiescing — jobs/sec is not a valid headline",
              file=sys.stderr)
        return 1
    return 0


def _soak_bench(args, jax):
    """--soak: open-loop p95 job latency through the soak harness.

    Unlike --serve (closed loop: the whole stream is present at entry,
    the unit is jobs/sec), the soak RELEASES the mixed stream at
    --arrival-rate jobs/sec regardless of completions and measures
    per-job latency from the scheduled arrival — free of coordinated
    omission (PERF.md). The headline is the p95 end-to-end job latency
    in ms; the full sample vector rides the history entry's v1.4
    latency block so `cache-sim bench-diff --latency` can adjudicate a
    latency change with the Mann-Whitney machinery instead of two bare
    percentiles.
    """
    from ue22cs343bb1_openmp_assignment_tpu import soak as soak_mod
    from ue22cs343bb1_openmp_assignment_tpu.obs.clock import VirtualClock

    max_cycles = args.max_cycles or 100_000
    qcap = args.queue_capacity or max(64, 2 * args.nodes)
    arrivals = soak_mod.soak_stream(
        args.arrival_rate, args.soak_duration, nodes=args.nodes,
        trace_len=args.trace_len, seed=0)

    daemon = None
    if args.daemon:
        # --daemon: the measured path is the real serving front door —
        # socket transport + continuous admission — not in-process
        # waves. Same metric string, so bench-diff adjudicates the
        # transport change on the v1.4 latency samples.
        import tempfile
        import threading
        from ue22cs343bb1_openmp_assignment_tpu.daemon.client import (
            DaemonClient)
        from ue22cs343bb1_openmp_assignment_tpu.daemon.core import (
            DaemonCore)
        from ue22cs343bb1_openmp_assignment_tpu.daemon.server import (
            DaemonServer)
        sock = os.path.join(
            tempfile.mkdtemp(prefix="cache-sim-bench-"), "daemon.sock")
        server = DaemonServer(
            DaemonCore(slots=args.serve_slots, chunk=args.chunk,
                       max_cycles=max_cycles, queue_capacity=qcap),
            sock, quiet=True)
        thread = threading.Thread(target=server.run, daemon=True,
                                  name="bench-daemon")
        thread.start()
        daemon = (sock, server, thread, DaemonClient)

    def run(clock=None):
        return soak_mod.soak(arrivals, slots=args.serve_slots,
                             chunk=args.chunk, max_cycles=max_cycles,
                             queue_capacity=qcap,
                             arrival_rate=args.arrival_rate,
                             clock=clock)

    from ue22cs343bb1_openmp_assignment_tpu.obs.phases import PhaseTimer
    timer = PhaseTimer()
    try:
        with timer.phase("warmup_compile"):
            if daemon:
                # one throwaway job of the stream shape compiles the
                # daemon's bucket chunk before latencies are sampled
                import dataclasses
                sock, _, _, DaemonClient = daemon
                with DaemonClient(sock) as c:
                    c.wait_up()
                    c.submit(dataclasses.replace(arrivals[0][1],
                                                 name="warmup000"))
                    c.wait("warmup000", timeout_s=120.0)
            else:
                # same wave jit signature on a virtual clock: compiles
                # the wave for this slot shape without wall-clock
                # latency samples
                run(VirtualClock())

        t0 = time.perf_counter()
        if daemon:                     # client clock: real latencies
            doc = soak_mod.soak_daemon(arrivals, daemon[0],
                                       arrival_rate=args.arrival_rate)
        else:
            doc = run()                # MonotonicClock: real latencies
        timer.add("soak_pass", time.perf_counter() - t0)
    finally:
        if daemon:
            sock, server, thread, DaemonClient = daemon
            try:
                with DaemonClient(sock) as c:
                    c.shutdown()
            except (ConnectionError, OSError):
                server.stop()
            thread.join(10.0)

    lat = doc["latency"]
    if lat is None:
        print("error: the soak released no jobs (duration too short "
              "for the arrival rate)", file=sys.stderr)
        return 1
    platform = jax.devices()[0].platform
    result = {
        "metric": f"soak p95 job latency @{args.nodes}x"
                  f"{args.trace_len} (async engine, mixed traffic, "
                  f"open loop, {platform})",
        "value": round(lat["p95_ms"], 3),
        "unit": "ms p95",
        "vs_baseline": 0.0,
    }
    quiet = doc["jobs_quiesced"] == doc["jobs_total"]
    extra = {
        "engine": "async",
        "steps": doc["wave_count"],
        "retired": None,
        "quiescent": quiet,
        "elapsed_s": round(doc["wall_s"], 3),
        "rep_times_s": [round(doc["wall_s"], 3)],
        "phases": timer.report(),
        "latency": {k: (round(v, 3) if isinstance(v, float) else v)
                    for k, v in lat.items()},
        "verdict": doc["verdict"],
        "mb_dropped": doc["mb_dropped"],
        "padding_waste": round(doc["padding_waste"], 4),
    }
    print(json.dumps(result))
    print(json.dumps(extra), file=sys.stderr)

    if args.record:
        from ue22cs343bb1_openmp_assignment_tpu.obs import (
            history, roofline)
        fingerprint = {
            "engine": "async", "mode": "soak", "workload": "mixed",
            "nodes": args.nodes, "trace_len": args.trace_len,
            "chunk": args.chunk, "max_cycles": max_cycles,
            "slots": args.serve_slots,
            "arrival_rate": args.arrival_rate,
            "duration_s": args.soak_duration,
            "transport": "daemon" if args.daemon else "inproc",
            "platform": platform, "smoke": bool(args.smoke),
        }
        latency_block = {
            "p50_ms": lat["p50_ms"], "p95_ms": lat["p95_ms"],
            "p99_ms": lat["p99_ms"], "max_ms": lat["max_ms"],
            "jobs": lat["jobs"],
            "arrival_rate": float(args.arrival_rate),
            "queue_depth_peak": doc["series_summary"]["queue_depth_peak"],
            "samples_ms": (doc.get("samples_ms")
                           or [round(s["e2e_s"] * 1e3, 6)
                               for s in doc["trace"]["spans"]]),
            "duration_s": float(args.soak_duration),
            "saturated": doc["verdict"]["saturated"],
            "drain_rate_jobs_per_s": doc["drain_rate_jobs_per_s"],
        }
        serve_block = {
            "slots": args.serve_slots, "jobs": doc["jobs_total"],
            "waves": doc["wave_count"], "devices": 1,
            "mb_dropped": doc["mb_dropped"],
            "padding_waste": round(doc["padding_waste"], 4),
            "transport": "daemon" if args.daemon else "inproc",
        }
        hist_doc = history.entry(
            label=f"soak@{args.arrival_rate:g}/s",
            source="bench.py",
            result=result, extra={k: v for k, v in extra.items()
                                  if k not in ("latency", "verdict",
                                               "mb_dropped",
                                               "padding_waste")},
            config=fingerprint,
            sha=history.git_sha(os.path.dirname(
                os.path.abspath(__file__))),
            captured_at=time.strftime("%Y-%m-%dT%H:%M:%S%z"),
            device_kind=roofline.detect_device_kind(),
            serve=serve_block, latency=latency_block)
        history.append(args.record, hist_doc)
        print(f"recorded to {args.record}", file=sys.stderr)

    if not quiet:
        print(f"error: {doc['jobs_total'] - doc['jobs_quiesced']} "
              f"job(s) hit the {max_cycles}-cycle budget without "
              "quiescing — the latency tail is not trustworthy",
              file=sys.stderr)
        return 1
    return 0


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--engine", choices=["sync", "async", "deep"],
                    default="deep",
                    help="sync = multi-transaction window engine; deep = "
                         "deep-window engine (dense own-entry chains + "
                         "absorbed remote events, ops.deep_engine); "
                         "async = message-level parity engine")
    ap.add_argument("--nodes", type=int, default=4096)
    ap.add_argument("--trace-len", type=int, default=4096,
                    help="instructions per node; the default is long "
                         "enough to measure sustained throughput (the "
                         "device link adds ~0.1 s fixed dispatch cost "
                         "per run, PERF.md)")
    ap.add_argument("--chunk", type=int, default=64,
                    help="cycles/rounds per quiescence-check chunk "
                         "(64 measured best on the attached device)")
    ap.add_argument("--workload", default="uniform")
    ap.add_argument("--local-frac", type=float, default=0.8)
    ap.add_argument("--drain-depth", type=int, default=None,
                    help="sync engine: hit budget per round (default: "
                         "16 for --txn-width 1, else 4 — both measured "
                         "best on the attached device)")
    ap.add_argument("--txn-width", type=int, default=None,
                    help="sync engine: max coherence transactions "
                         "committed per node per round (multi-"
                         "transaction window; 1 = classic burst-plus-"
                         "one-transaction rounds; default 3, measured "
                         "best)")
    ap.add_argument("--replicas", type=int, default=1,
                    help="sync engine: independent machines batched into "
                         "one ensemble (different workload + arbitration "
                         "seeds); throughput is aggregated")
    ap.add_argument("--deep-slots", type=int, default=None,
                    help="deep engine: remote-event slots per window "
                         "(default 3; 2 at >= 32768 nodes, where "
                         "padded-slot occupancy falls and every "
                         "[Q, N] index op prices empty slots — "
                         "PERF.md scaling ladder)")
    ap.add_argument("--deep-g", type=int, default=None,
                    help="deep engine: owner-value slots per window "
                         "(default 1 — over_g stops are negligible "
                         "and each extra slot prices G*N gather "
                         "indices per round)")
    ap.add_argument("--deep-waves", type=int, default=1,
                    help="deep engine: absorption waves — up to this "
                         "many same-class fill requests compose per "
                         "directory entry per round (the contended-"
                         "workload lever; 1 = classic single winner)")
    ap.add_argument("--deep-slack", type=int, default=4,
                    help="deep engine: adaptive attempt-horizon slack "
                         "(4 measured best; PERF.md)")
    ap.add_argument("--read-storm", action="store_true",
                    help="deep engine: bulk-grant all same-round "
                         "losing READ requests per entry (the "
                         "many-readers lever for lu/hotspot)")
    ap.add_argument("--no-exact-flags", action="store_true",
                    help="deep engine: restore round-4 attempt-based "
                         "marker/poison flags (A/B lever for the "
                         "commit-prefix-exact flag pass)")
    ap.add_argument("--queue-capacity", type=int, default=None,
                    help="async engine: mailbox ring slots per node "
                         "(default 64; the ring tensor is copied every "
                         "cycle, so capacity directly prices the cycle)")
    ap.add_argument("--admission", type=int, default=None,
                    help="async engine: max concurrent outstanding "
                         "requests (None = reference drop semantics)")
    ap.add_argument("--ledger", action="store_true",
                    help="async engine: measure the run under the "
                         "causal message-ledger capture "
                         "(obs.txntrace.capture: ledger-on telemetry "
                         "scans + per-chunk host fetch) — the "
                         "transaction-tracer overhead bench; compare "
                         "against a plain async capture with "
                         "bench-diff (PERF.md)")
    ap.add_argument("--coherence-profile", action="store_true",
                    help="async engine: measure the run under the "
                         "coherence-profiler counter plane "
                         "(ops.step.run_cycles_profile: per-line miss "
                         "taxonomy + invalidation/migration "
                         "attribution folded into the scan) — the "
                         "profiler overhead bench; compare against a "
                         "plain async capture with bench-diff "
                         "(PERF.md)")
    ap.add_argument("--reps", type=int, default=3,
                    help="timed repetitions; the median is reported")
    ap.add_argument("--procedural", default=True,
                    action=argparse.BooleanOptionalAction,
                    help="sync engine: compute the uniform workload "
                         "procedurally inside the round (O(1) trace "
                         "memory, no window gather; --trace-len may be "
                         "arbitrarily long). Bit-exact-equivalent to the "
                         "materialized stream (tests/test_procedural.py); "
                         "--no-procedural gathers a stored trace instead")
    ap.add_argument("--pallas", default=None,
                    action=argparse.BooleanOptionalAction,
                    help="sync engine, procedural: run the window fold "
                         "as fused Pallas kernels (ops.pallas_window). "
                         "Default: on when a TPU backend is attached "
                         "(+19%% measured); off elsewhere (the CPU "
                         "interpreter is impractically slow)")
    ap.add_argument("--fused-round", choices=["auto", "on", "off"],
                    default="auto",
                    help="deep engine: execute the ENTIRE round as one "
                         "fused Pallas kernel with directory/cache/slot "
                         "state resident in VMEM (ops.pallas_round; "
                         "bit-identical to the XLA reference path, "
                         "tests/test_pallas_round.py). auto: on when a "
                         "TPU backend is attached and the config is "
                         "supported (no --read-storm, deep_slots*nodes "
                         "under the scatter-min margin); off: always "
                         "the XLA reference path")
    ap.add_argument("--sharded", action="store_true",
                    help="shard the simulated-node axis over ALL "
                         "attached devices (jax.sharding.Mesh + "
                         "NamedSharding; GSPMD partitions the "
                         "delivery/claim scatters into collectives) "
                         "and measure the sharded run — the multi-chip "
                         "bench mode. With one device this is the "
                         "same computation through the sharded path.")
    ap.add_argument("--profile", metavar="DIR",
                    help="capture a jax.profiler trace of one timed run "
                         "into DIR (viewable with TensorBoard/Perfetto; "
                         "SURVEY §5 tracing)")
    ap.add_argument("--serve", action="store_true",
                    help="measure the batched serving layer instead of "
                         "one machine: run the fixed traffic mix "
                         "through serve.serve() waves and report "
                         "jobs/sec (serve.py, ROADMAP item 2)")
    ap.add_argument("--serve-slots", type=int, default=8,
                    help="batch slots per wave for --serve (default 8; "
                         "1 = the sequential baseline bench-diff "
                         "compares against)")
    ap.add_argument("--serve-jobs", type=int, default=None,
                    help="jobs in the --serve traffic mix (default "
                         "2x slots so every slot turns over once)")
    ap.add_argument("--soak", action="store_true",
                    help="open-loop latency bench: release the mixed "
                         "stream at --arrival-rate through the soak "
                         "harness (soak.py) and report p95 job "
                         "latency in ms; records a v1.4 latency "
                         "block for `bench-diff --latency`")
    ap.add_argument("--arrival-rate", type=float, default=20.0,
                    help="--soak: jobs per second released "
                         "(default 20)")
    ap.add_argument("--soak-duration", type=float, default=2.0,
                    help="--soak: arrival window in seconds "
                         "(default 2); the run drains fully after")
    ap.add_argument("--daemon", action="store_true",
                    help="--soak: route the stream through an "
                         "in-process serving daemon on a temp unix "
                         "socket (daemon/: socket transport, "
                         "continuous admission, shape bucketing in "
                         "the measured path); same metric string so "
                         "bench-diff --latency adjudicates daemon vs "
                         "in-process")
    ap.add_argument("--devices", type=int, default=1,
                    help="--serve: shard the wave's batch axis over "
                         "this many local devices (serve.py batch "
                         "mesh; --serve-slots must divide evenly). "
                         "The device count stays out of the metric "
                         "string so bench-diff adjudicates 1-vs-N "
                         "devices as a verdict")
    ap.add_argument("--transport", choices=["auto", "all_to_all",
                                            "rdma"],
                    default="auto",
                    help="async engine + --sharded: phase-3 delivery "
                         "transport (parallel/rdma_comm). all_to_all "
                         "= lane-bucketed lax.all_to_all router; rdma "
                         "= Pallas remote-DMA ring (neighbor "
                         "exchange, send/recv semaphores). auto: "
                         "rdma on a real TPU backend, else the "
                         "implicit GSPMD delivery (the CPU Pallas "
                         "interpreter is parity-grade, not "
                         "bench-grade)")
    ap.add_argument("--smoke", action="store_true",
                    help="tiny config on CPU for smoke testing")
    ap.add_argument("--record", metavar="PATH",
                    help="append this run to a bench-history JSONL "
                         "(cache-sim/bench/v1: full rep vector, config "
                         "fingerprint, git sha); compare entries with "
                         "`cache-sim bench-diff --history PATH "
                         "--against-last`")
    ap.add_argument("--max-cycles", type=int, default=None,
                    help="override the cycle/round budget (default "
                         "200*trace_len); a run that fails to go "
                         "quiescent inside it exits 1")
    ap.add_argument("--timer-check", action="store_true",
                    help="run the obs.profiler timer self-check: is "
                         "block_until_ready a real barrier on this "
                         "link, or must timings sync via device_get "
                         "(PERF.md)? Result rides in the stderr extra")
    ap.add_argument("--kernel-costs", action="store_true",
                    help="attach XLA's compiled cost analysis of the "
                         "headline runner (flops/bytes, memory sizes) "
                         "to the phase report (obs.profiler)")
    args = ap.parse_args()
    if args.reps < 1:
        ap.error("--reps must be >= 1")

    import jax

    if args.smoke:
        jax.config.update("jax_platforms", "cpu")

    from ue22cs343bb1_openmp_assignment_tpu.config import SystemConfig
    from ue22cs343bb1_openmp_assignment_tpu.models.system import CoherenceSystem
    from ue22cs343bb1_openmp_assignment_tpu.ops import sync_engine as se
    from ue22cs343bb1_openmp_assignment_tpu.ops.step import (
        run_chunked_to_quiescence)

    if args.smoke:
        args.nodes, args.trace_len, args.chunk = 64, 8, 8
        if args.serve or args.soak:
            # serving smoke: many small tenants, not one 64-node machine
            args.nodes = 8

    if args.serve and args.soak:
        print("error: --serve and --soak are exclusive (closed-loop "
              "jobs/sec vs open-loop latency)", file=sys.stderr)
        return 2
    if args.daemon and not args.soak:
        print("error: --daemon is a --soak transport", file=sys.stderr)
        return 2
    if args.serve:
        return _serve_bench(args, jax)
    if args.soak:
        return _soak_bench(args, jax)

    sync_like = args.engine in ("sync", "deep")
    if args.txn_width is not None and not sync_like:
        print("error: --txn-width sizes the sync engine's multi-"
              "transaction window; use --engine sync", file=sys.stderr)
        return 2
    if args.txn_width is None:
        args.txn_width = 3 if sync_like else 1
    if args.drain_depth is None:
        args.drain_depth = (13 if args.engine == "deep"
                            else 16 if args.txn_width == 1 else 4)
    qkw = ({"queue_capacity": args.queue_capacity}
           if args.queue_capacity is not None else {})
    cfg = SystemConfig.scale(num_nodes=args.nodes,
                             admission_window=args.admission,
                             drain_depth=args.drain_depth,
                             txn_width=args.txn_width, **qkw)
    if args.engine == "deep":
        import dataclasses
        big = args.nodes >= 32768
        if args.deep_slots is None:
            args.deep_slots = 2 if big else 3
        if args.deep_g is None:
            # one owner-value slot: over_g stops are ~0.007/node/round
            # at G=2 and rounds stay identical at G=1 while each round
            # sheds G*N gather indices (measured ~2-3% at 4096)
            args.deep_g = 1
        cfg = dataclasses.replace(cfg, deep_window=True,
                                  deep_slots=args.deep_slots,
                                  deep_ownerval_slots=args.deep_g,
                                  deep_horizon_slack=args.deep_slack,
                                  deep_waves=args.deep_waves,
                                  deep_read_storm=args.read_storm,
                                  deep_exact_flags=not args.no_exact_flags)
    if args.procedural and (not sync_like
                            or args.workload != "uniform"
                            or args.replicas > 1):
        print("note: --procedural needs the sync engine, the uniform "
              "workload and --replicas 1; measuring stored traces "
              "instead", file=sys.stderr)
        args.procedural = False
    if args.procedural:
        import dataclasses
        cfg = dataclasses.replace(
            cfg, procedural="uniform", max_instrs=1,
            proc_local_permille=int(args.local_frac * 1000))
    # Pallas kernels: the deep engine's fold kernels serve every
    # workload kind (the window is built in XLA, ops/pallas_deep);
    # the multi/burst window kernels need a procedural stream and
    # gate themselves off otherwise (sync_engine.round_step).
    if sync_like:
        import dataclasses
        # the kernels tile the node axis at 1024 (ops.pallas_burst._tile)
        tileable = args.nodes <= 1024 or args.nodes % 1024 == 0
        on_tpu = jax.default_backend() == "tpu"
        if args.pallas is None:
            args.pallas = on_tpu and tileable
        elif args.pallas and not (tileable and on_tpu):
            why = ("a TPU backend (the CPU interpreter takes minutes "
                   "per kernel call)" if not on_tpu else
                   "--nodes <= 1024 or a multiple of 1024")
            print(f"note: --pallas needs {why}; measuring the XLA "
                  "path instead", file=sys.stderr)
            args.pallas = False
        if args.pallas:
            cfg = dataclasses.replace(cfg, pallas_burst=True)
    elif args.pallas:
        print("note: --pallas applies only to the sync-family engines; "
              "measuring without the Pallas kernels", file=sys.stderr)
    if args.engine == "deep":
        import dataclasses
        from ue22cs343bb1_openmp_assignment_tpu.ops import pallas_round
        ok = pallas_round.supported(cfg)
        on_tpu = jax.default_backend() == "tpu"
        want = (args.fused_round == "on"
                or (args.fused_round == "auto" and on_tpu and ok))
        if args.fused_round == "on" and not ok:
            print("note: --fused-round=on needs a supported config (no "
                  "--read-storm, deep_slots*nodes < 16384); measuring "
                  "the XLA reference path instead", file=sys.stderr)
            want = False
        if want and not on_tpu:
            print("note: --fused-round on a non-TPU backend runs the "
                  "Pallas interpreter (very slow; parity checking "
                  "only)", file=sys.stderr)
        if want:
            cfg = dataclasses.replace(cfg, fused_round=True)
    elif args.fused_round == "on":
        print("note: --fused-round applies only to the deep engine; "
              "measuring without it", file=sys.stderr)
    gen_kw = {"local_frac": args.local_frac} if args.workload == "uniform" else {}

    def make_system(seed):
        return CoherenceSystem.from_workload(
            cfg, args.workload, trace_len=args.trace_len, seed=seed,
            **gen_kw)

    # The whole run is ONE device dispatch (chunked scan inside a
    # while_loop): on a high-latency device link every eager op is a
    # network round trip, so host-side polling would dominate the
    # measurement.
    max_cycles = (args.max_cycles if args.max_cycles is not None
                  else 200 * args.trace_len)
    if sync_like:
        # stay inside the claim-key round budget at very large N
        max_cycles = min(max_cycles, se.claim_max_rounds(cfg) - 1)

    # warmup: compile + run the full workload once (discarded); sync via
    # device_get (int()), NOT jax.block_until_ready — over a tunneled
    # device plugin block_until_ready can return before the computation
    # finishes, which silently turns the measurement into dispatch time
    # and inflates throughput by orders of magnitude.
    if args.engine != "sync" and args.replicas > 1:
        print("error: --replicas needs --engine sync", file=sys.stderr)
        return 2
    if args.engine == "sync" and args.replicas > 1:
        if args.sharded:
            print("error: --sharded and --replicas are exclusive",
                  file=sys.stderr)
            return 2
        reps = [se.from_sim_state(cfg, make_system(r).state, seed=r)
                for r in range(args.replicas)]
        st0 = se.make_ensemble(reps)

        def runner(s):
            return se.run_ensemble_to_quiescence(cfg, s, args.chunk,
                                                 max_cycles)

        def steps(st):
            return int(st.metrics.rounds[0])
    elif sync_like and args.procedural:
        st0 = se.procedural_state(cfg, args.trace_len, seed=0)

        def runner(s):
            return se.run_sync_to_quiescence(cfg, s, args.chunk,
                                             max_cycles)

        def steps(st):
            return int(st.metrics.rounds)
    elif sync_like:
        st0 = se.from_sim_state(cfg, make_system(0).state, seed=0)

        def runner(s):
            return se.run_sync_to_quiescence(cfg, s, args.chunk,
                                             max_cycles)

        def steps(st):
            return int(st.metrics.rounds)
    else:
        st0 = make_system(0).state

        def runner(s):
            return run_chunked_to_quiescence(cfg, s, args.chunk,
                                             max_cycles)

        def steps(st):
            return int(st.metrics.cycles)

    if args.ledger:
        if args.engine != "async":
            print("error: --ledger measures the async engine's "
                  "message-ledger capture; use --engine async",
                  file=sys.stderr)
            return 2
        if args.sharded:
            print("error: --ledger and --sharded are exclusive "
                  "(use parallel.make_sharded_ledger_runner for "
                  "sharded capture)", file=sys.stderr)
            return 2
        from ue22cs343bb1_openmp_assignment_tpu.obs import txntrace
        # the ledger replay runs a fixed cycle count: find this
        # workload's cycles-to-quiescence once, ledger off
        ledger_cycles = steps(run_chunked_to_quiescence(
            cfg, st0, args.chunk, max_cycles))

        def runner(s):
            final, _, _ = txntrace.capture(
                cfg, s, ledger_cycles, chunk=args.chunk,
                stop_on_quiescence=False)
            return final

    if args.coherence_profile:
        if args.engine != "async" or args.ledger or args.sharded:
            print("error: --coherence-profile measures the async "
                  "engine's profiler counter plane; use --engine "
                  "async without --ledger/--sharded", file=sys.stderr)
            return 2
        from ue22cs343bb1_openmp_assignment_tpu.ops.step import (
            run_cycles_profile)
        # same discipline as --ledger: the profiled replay runs the
        # fixed cycle count the plain run needs to quiesce
        prof_cycles = steps(run_chunked_to_quiescence(
            cfg, st0, args.chunk, max_cycles))

        def runner(s):
            final, _ = run_cycles_profile(cfg, s, prof_cycles)
            return final

    n_dev = 1
    if args.sharded:
        # multi-chip mode: the node axis shards over every attached
        # device (jax.sharding.Mesh); the jitted quiescence runners
        # respect the input shardings, so GSPMD partitions the
        # delivery/claim scatters into cross-device collectives
        from ue22cs343bb1_openmp_assignment_tpu.parallel import (
            make_mesh, shard_state)
        devs = jax.devices()
        n_dev = len(devs)
        if args.nodes % n_dev:
            print(f"error: --sharded needs --nodes divisible by the "
                  f"{n_dev} attached devices", file=sys.stderr)
            return 2
        mesh = make_mesh(devs)
        st0 = shard_state(cfg, mesh, st0)
        print(f"sharded: node axis over {n_dev} device(s)",
              file=sys.stderr)
        if args.engine == "async":
            from ue22cs343bb1_openmp_assignment_tpu.parallel import (
                rdma_comm)
            from ue22cs343bb1_openmp_assignment_tpu.parallel.mesh import (
                flatten_mesh)
            want = args.transport
            if want == "auto":
                # the CPU Pallas interpreter discharges remote DMAs as
                # whole-buffer gathers — parity-grade, not bench-grade
                want = "rdma" if rdma_comm.native() else None
            if want is not None and n_dev == 1:
                print("note: --transport needs >1 device (no "
                      "cross-shard traffic); measuring the implicit "
                      "GSPMD delivery", file=sys.stderr)
                want = None
            if want is not None and not rdma_comm.supported(cfg):
                print("note: --transport needs drop_prob 0 (the "
                      "global fault draw is not reproducible "
                      "per-shard); measuring the implicit GSPMD "
                      "delivery", file=sys.stderr)
                want = None
            if want is not None:
                import dataclasses
                cfg = dataclasses.replace(cfg, transport=want)
                deliver_fn = rdma_comm.make_routed_deliver(
                    cfg, flatten_mesh(mesh))
                print(f"transport: {want} routed delivery "
                      f"({rdma_comm.wire_bytes(cfg, n_dev, transport=want)}"
                      " bytes on wire per round)", file=sys.stderr)

                def runner(s, _fn=deliver_fn):
                    return run_chunked_to_quiescence(
                        cfg, s, args.chunk, max_cycles,
                        deliver_fn=_fn)
            args.transport = want or "gspmd"
        elif args.transport != "auto":
            print("note: --transport applies to the async engine "
                  "with --sharded; ignoring", file=sys.stderr)
    elif args.transport != "auto":
        print("note: --transport applies to the async engine with "
              "--sharded; ignoring", file=sys.stderr)

    def run():
        return runner(st0)

    import numpy as np

    def total_retired(st):
        return int(np.sum(np.asarray(st.metrics.instrs_retired)))

    from ue22cs343bb1_openmp_assignment_tpu.obs.phases import PhaseTimer
    timer = PhaseTimer()
    with timer.phase("warmup_compile"):
        total_retired(run())          # warmup; device_get = real sync

    if args.profile:
        from ue22cs343bb1_openmp_assignment_tpu.obs import profiler
        with profiler.capture(args.profile):
            total_retired(run())

    if args.kernel_costs:
        # lower the actual jitted quiescence runner at the bench
        # arguments; unavailable (never fatal) if the backend has no
        # cost model or the path has no directly-jitted runner
        from ue22cs343bb1_openmp_assignment_tpu.obs import profiler
        if args.engine == "sync" and args.replicas > 1:
            jitted, jargs = se._run_ensemble_jit, (cfg, st0, args.chunk,
                                                   max_cycles)
        elif sync_like:
            jitted, jargs = se._run_sync_jit, (cfg, st0, args.chunk,
                                               max_cycles)
        else:
            jitted, jargs = run_chunked_to_quiescence, (
                cfg, st0, args.chunk, max_cycles)
        profiler.attach_kernel_costs(timer, jitted, *jargs)

    # median of --reps timed runs: the device link is shared, with
    # ~1.5x run-to-run noise; the median is the defensible headline
    times = []
    for _ in range(args.reps):
        t0 = time.perf_counter()
        state = run()
        t1 = time.perf_counter()
        retired = total_retired(state)    # device_get = real sync
        t2 = time.perf_counter()
        times.append(t2 - t0)
        # phase split (obs.phases): dispatch returns once XLA accepts
        # the program; the device_get is where the run actually
        # synchronizes — PERF.md's known trap when read separately
        timer.add("execute_dispatch", t1 - t0)
        timer.add("device_get_sync", t2 - t1)
    times.sort()
    elapsed = times[len(times) // 2]
    value = retired / elapsed
    rep = (f", {args.replicas} replicas" if args.replicas > 1 else "")
    rep += ", procedural" if args.procedural else ""
    # the ledger marker rides the history label + config fingerprint,
    # NOT the metric string: bench-diff matches on the metric, and
    # plain-vs-ledger is exactly the comparison that measures the
    # tracer's overhead
    result = {
        "metric": f"simulated RD/WR instrs/sec @{args.nodes} cores "
                  f"({args.engine} engine, {args.workload}{rep}, 1 chip, "
                  f"{jax.devices()[0].platform})",
        "value": round(value, 1),
        "unit": "instrs/sec",
        "vs_baseline": round(value / 1e8, 4),
    }
    if args.engine == "sync" and args.replicas > 1:
        quiet = bool(np.all(np.asarray(
            jax.vmap(lambda x: x.quiescent())(state))))
    else:
        quiet = bool(state.quiescent())
    extra = {
        "engine": args.engine,
        "steps": steps(state),
        "retired": retired,
        "quiescent": quiet,
        "elapsed_s": round(elapsed, 3),
        "rep_times_s": [round(t, 3) for t in times],
        "phases": timer.report(),
    }
    if args.engine == "async":
        # surface the reference's silent-drop failure mode (quirk 6): a
        # throughput number with drops > 0 is not a clean run
        extra["msgs_dropped"] = int(state.metrics.msgs_dropped)
    if args.timer_check:
        from ue22cs343bb1_openmp_assignment_tpu.obs import profiler
        extra["timer_check"] = profiler.timer_self_check(run, reps=1)
    print(json.dumps(result))
    print(json.dumps(extra), file=sys.stderr)

    if args.record:
        from ue22cs343bb1_openmp_assignment_tpu.obs import (
            history, roofline)
        # the deterministic comparability keys (obs v4): device kind +
        # compiled-HLO fingerprint let bench-diff refuse cross-device
        # comparisons, and the cost vector feeds the exact --bytes gate
        device_kind = roofline.detect_device_kind()
        cost = hlo_fp = None
        try:
            if not (args.engine == "sync" and args.replicas > 1):
                if sync_like:
                    per_rec = roofline.kernel_record(
                        "sync.round_step",
                        jax.jit(lambda s: se.round_step(cfg, s)), st0)
                    run_rec = roofline.kernel_record(
                        f"sync.run_to_quiescence[chunk={args.chunk}]",
                        se._run_sync_jit, cfg, st0, args.chunk,
                        max_cycles)
                else:
                    from ue22cs343bb1_openmp_assignment_tpu.ops import (
                        step as step_mod)
                    per_rec = roofline.kernel_record(
                        "step.cycle",
                        jax.jit(lambda s: step_mod.cycle(cfg, s)), st0)
                    run_rec = roofline.kernel_record(
                        f"step.run_chunked[chunk={args.chunk}]",
                        run_chunked_to_quiescence, cfg, st0,
                        args.chunk, max_cycles)
                hlo_fp = (run_rec.get("hlo_fingerprint")
                          or per_rec.get("hlo_fingerprint"))
                cost = roofline.cost_vector(per_rec, run_rec,
                                            steps(state), retired)
        except Exception as e:   # recording must never kill the bench
            print(f"note: cost vector unavailable: {e}",
                  file=sys.stderr)
        fingerprint = {
            "engine": args.engine, "workload": args.workload,
            "nodes": args.nodes, "trace_len": args.trace_len,
            "chunk": args.chunk, "reps": args.reps,
            "max_cycles": max_cycles, "replicas": args.replicas,
            "procedural": bool(args.procedural and sync_like),
            "sharded": bool(args.sharded), "devices": n_dev,
            "transport": (args.transport
                          if args.sharded and args.engine == "async"
                          else None),
            "ledger": bool(args.ledger),
            "coherence_profile": bool(args.coherence_profile),
            "platform": jax.devices()[0].platform,
            "smoke": bool(args.smoke),
        }
        doc = history.entry(
            label=(f"{args.engine}@{args.nodes}"
                   + ("+ledger" if args.ledger else "")
                   + ("+cohprof" if args.coherence_profile else "")),
            source="bench.py",
            result=result, extra=extra, config=fingerprint,
            sha=history.git_sha(os.path.dirname(
                os.path.abspath(__file__))),
            captured_at=time.strftime("%Y-%m-%dT%H:%M:%S%z"),
            device_kind=device_kind, hlo_fingerprint=hlo_fp,
            cost=cost)
        history.append(args.record, doc)
        print(f"recorded to {args.record}", file=sys.stderr)

    if not quiet:
        # a non-quiescent run measured dispatch of an unfinished
        # workload — the number is not a headline and CI gates
        # (scripts/check.sh bench-smoke) must be able to trust rc
        print(f"error: not quiescent within {max_cycles} "
              f"cycles/rounds — result is not a valid headline",
              file=sys.stderr)
        return 1


if __name__ == "__main__":
    sys.exit(main())
