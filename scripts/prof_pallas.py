"""Pallas viability probe: launch overhead vs in-kernel loop cost (throwaway)."""
import functools
import time

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

N, K = 4096, 64


def bench(name, fn, *xs, iters=K):
    r = fn(*xs)
    int(jax.tree.leaves(r)[0].ravel()[0])
    t0 = time.perf_counter()
    r = fn(*xs)
    int(jax.tree.leaves(r)[0].ravel()[0])
    dt = time.perf_counter() - t0
    print(f"{name:52s} {dt/iters*1e6:9.1f} us/iter  ({dt:.3f}s total)")


v = jnp.ones((32, 128), jnp.int32)

# 1. trivial pallas kernel launched per scan iteration
def triv_kernel(x_ref, o_ref):
    o_ref[:] = x_ref[:] + 1

def triv(x):
    return pl.pallas_call(
        triv_kernel,
        out_shape=jax.ShapeDtypeStruct(x.shape, x.dtype),
        in_specs=[pl.BlockSpec(memory_space=pltpu.VMEM)],
        out_specs=pl.BlockSpec(memory_space=pltpu.VMEM),
    )(x)

@jax.jit
def scan_pallas(x):
    def step(c, _):
        return triv(c), None
    out, _ = jax.lax.scan(step, x, None, length=K)
    return out

bench("pallas trivial kernel per scan iter", scan_pallas, v)

# 2. one pallas kernel with an internal fori_loop of K*R steps
R = 100
def loop_kernel(x_ref, o_ref):
    def body(i, acc):
        return (acc + 1) ^ (acc & 5) | (acc + 3)
    o_ref[:] = jax.lax.fori_loop(0, K * R, body, x_ref[:])

@jax.jit
def one_kernel_loop(x):
    return pl.pallas_call(
        loop_kernel,
        out_shape=jax.ShapeDtypeStruct(x.shape, x.dtype),
        in_specs=[pl.BlockSpec(memory_space=pltpu.VMEM)],
        out_specs=pl.BlockSpec(memory_space=pltpu.VMEM),
    )(x)

bench(f"pallas ONE kernel, {K*R} fori_loop steps inside",
      one_kernel_loop, v, iters=K * R)

# 3. same but with a bigger array [4096, 128] (2MB) to see VMEM compute rate
big = jnp.ones((N, 128), jnp.int32)
bench(f"pallas ONE kernel {K*R} steps on [4096,128]",
      one_kernel_loop, big, iters=K * R)

# 4. grid-based: grid=(K,) sequential steps, in-place accumulate
def grid_kernel(x_ref, o_ref):
    i = pl.program_id(0)

    @pl.when(i == 0)
    def _():
        o_ref[:] = x_ref[:]

    o_ref[:] = (o_ref[:] + 1) ^ (o_ref[:] & 5)

@jax.jit
def grid_loop(x):
    return pl.pallas_call(
        grid_kernel,
        grid=(K,),
        out_shape=jax.ShapeDtypeStruct(x.shape, x.dtype),
        in_specs=[pl.BlockSpec(x.shape, lambda i: (0, 0),
                               memory_space=pltpu.VMEM)],
        out_specs=pl.BlockSpec(x.shape, lambda i: (0, 0),
                               memory_space=pltpu.VMEM),
    )(x)

bench("pallas grid=(64,) sequential, per grid step", grid_loop, big)

# 5. XLA while_loop (not scan) per-iter floor for comparison
@jax.jit
def xla_while(x):
    def cond(c):
        return c[1] < K
    def body(c):
        x, i = c
        return ((x + 1) ^ (x & 5), i + 1)
    return jax.lax.while_loop(cond, body, (x, 0))[0]

bench("XLA while_loop trivial body", xla_while, big)
