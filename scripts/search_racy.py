"""Schedule search: reach EVERY accepted racy outcome (VERDICT r2 #5).

The reference's retry harness (`test3.sh:6-33`, `test4.sh:6-32`) can
land on any of tests/test_3/run_{1,2} and tests/test_4/run_{1..4};
this repo replaces wall-clock retry with explicit schedule knobs
(issue delays x issue periods x arbitration rank). This script sweeps
those knobs on the native C++ engine (host speed, deterministic) and
prints one witness schedule per accepted run — the witnesses are
pinned as tests in tests/test_racy_outcomes.py.

Usage: python scripts/search_racy.py [--suite test_3|test_4]
       [--max-delay 12] [--periods 1 2 3] [--arb-seeds 8]
"""

import argparse
import itertools
import os
import sys
import types

import numpy as np

from ue22cs343bb1_openmp_assignment_tpu.config import SystemConfig
from ue22cs343bb1_openmp_assignment_tpu.native.bindings import NativeEngine
from ue22cs343bb1_openmp_assignment_tpu.utils.golden import (
    format_node_dump, state_to_dumps)
from ue22cs343bb1_openmp_assignment_tpu.utils.search import (
    load_accepted_named)
from ue22cs343bb1_openmp_assignment_tpu.utils.trace import load_test_dir

REFERENCE_TESTS = "/root/reference/tests"


def _arb_rank(seed: int, n: int) -> np.ndarray:
    rng = np.random.RandomState(seed)
    return rng.permutation(n).astype(np.int32)


def run_schedule(cfg, traces, delays, periods, arb_seed):
    eng = NativeEngine(cfg)
    eng.load_traces(traces)
    if delays is not None or periods is not None:
        eng.set_schedule(delays, periods)
    if arb_seed is not None:
        eng.set_arbitration(_arb_rank(arb_seed, cfg.num_nodes))
    eng.run(100_000)
    assert eng.quiescent
    ns = types.SimpleNamespace(**eng.export_state())
    return [format_node_dump(d) for d in state_to_dumps(cfg, ns)]


def search(suite, max_delay, periods_opts, arb_seeds, budget=200_000):
    cfg = SystemConfig.reference()
    traces = load_test_dir(os.path.join(REFERENCE_TESTS, suite))
    named = load_accepted_named(os.path.join(REFERENCE_TESTS, suite))
    accepted = {name: dumps for name, dumps in named}
    active = [n for n, tr in enumerate(traces) if tr]
    found = {}
    tried = 0

    def attempt(delays, periods, arb_seed):
        nonlocal tried
        tried += 1
        dumps = run_schedule(cfg, traces, delays, periods, arb_seed)
        for name, acc in accepted.items():
            if name not in found and dumps == acc:
                found[name] = (delays, periods, arb_seed)
                print(f"  {suite}/{name}: delays={delays} "
                      f"periods={periods} arb_seed={arb_seed} "
                      f"(attempt {tried})")
        return len(found) == len(accepted)

    # pass 1: delay grid, default period/arb
    for delays in itertools.product(range(max_delay + 1),
                                    repeat=len(active)):
        d = [0] * cfg.num_nodes
        for n, dv in zip(active, delays):
            d[n] = dv
        if attempt(tuple(d), None, None) or tried >= budget:
            return found, tried
    # pass 2: add periods and arbitration ranks
    for arb in range(arb_seeds):
        for per in periods_opts:
            p = tuple(per if n in active else 1
                      for n in range(cfg.num_nodes))
            for delays in itertools.product(range(0, max_delay + 1, 2),
                                            repeat=len(active)):
                d = [0] * cfg.num_nodes
                for n, dv in zip(active, delays):
                    d[n] = dv
                if attempt(tuple(d), p, arb) or tried >= budget:
                    return found, tried
    # pass 3: random joint schedules
    rng = np.random.RandomState(0)
    while tried < budget:
        d = tuple(int(rng.randint(0, max_delay + 1)) if n in active else 0
                  for n in range(cfg.num_nodes))
        p = tuple(int(rng.randint(1, 5)) for _ in range(cfg.num_nodes))
        if attempt(d, p, int(rng.randint(0, 64))):
            return found, tried
    return found, tried


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--suite", choices=["test_3", "test_4"], default=None)
    ap.add_argument("--max-delay", type=int, default=12)
    ap.add_argument("--periods", type=int, nargs="*", default=[2, 3])
    ap.add_argument("--arb-seeds", type=int, default=4)
    ap.add_argument("--budget", type=int, default=200_000)
    args = ap.parse_args()
    suites = [args.suite] if args.suite else ["test_3", "test_4"]
    ok = True
    for suite in suites:
        print(f"searching {suite} ...")
        found, tried = search(suite, args.max_delay, args.periods,
                              args.arb_seeds, args.budget)
        missing = [n for n, _ in load_accepted_named(
            os.path.join(REFERENCE_TESTS, suite)) if n not in found]
        print(f"{suite}: {len(found)} outcomes witnessed "
              f"in {tried} attempts; missing: {missing or 'none'}")
        ok &= not missing
    return 0 if ok else 1


if __name__ == "__main__":
    sys.exit(main())
