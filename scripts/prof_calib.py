"""Device calibration: raw MXU/VPU throughput (throwaway)."""
import time

import jax
import jax.numpy as jnp


def bench(name, fn, *xs, work=1):
    r = fn(*xs)
    int(jax.tree.leaves(r)[0].ravel()[0])
    t0 = time.perf_counter()
    r = fn(*xs)
    int(jax.tree.leaves(r)[0].ravel()[0])
    dt = time.perf_counter() - t0
    print(f"{name:46s} {dt:8.4f}s  -> {work/dt:10.3e} /s")


K = 32
a = jnp.ones((1024, 1024), jnp.bfloat16)

@jax.jit
def mm_chain(a):
    def step(c, _):
        c = c @ a
        return c * jnp.bfloat16(1e-3), None
    out, _ = jax.lax.scan(step, a, None, length=K)
    return out

bench(f"bf16 1024^3 matmul x{K} (scan)", mm_chain, a,
      work=K * 2 * 1024**3)  # flops

v = jnp.ones((512, 1024), jnp.float32)

@jax.jit
def vec_chain(v):
    def step(c, _):
        return (c * 1.000001 + 0.5) * 0.999999 - 0.25, None
    out, _ = jax.lax.scan(step, v, None, length=K)
    return out

bench(f"f32 elementwise 4 ops on 512x1024 x{K}", vec_chain, v,
      work=K * 4 * 512 * 1024)  # element-ops
