#!/usr/bin/env bash
# CI gate: static analysis first (fast, catches protocol and tracing
# regressions without running a workload), then the fast test tier.
#
#   scripts/check.sh            # analyze + tier-1 tests
#   scripts/check.sh --analyze  # static analysis only
#
# The analyze step is `cache-sim analyze`: the small-scope protocol
# model checker over the builtin scopes plus the JAX trace linter over
# ops/ parallel/ models/. It exits nonzero on any genuine violation
# (reference-sanctioned quirks are reported but allowlisted).
set -euo pipefail
cd "$(dirname "$0")/.."

export JAX_PLATFORMS="${JAX_PLATFORMS:-cpu}"

python -m ue22cs343bb1_openmp_assignment_tpu.analysis ${ANALYZE_ARGS:-}

if [[ "${1:-}" == "--analyze" ]]; then
    exit 0
fi

python -m pytest tests/ -q -m 'not slow' -p no:cacheprovider
