#!/usr/bin/env bash
# CI gate: static analysis first (fast, catches protocol and tracing
# regressions without running a workload), then the fast test tier.
#
#   scripts/check.sh            # analyze + tier-1 tests
#   scripts/check.sh --analyze  # static analysis only
#
# The analyze step is `cache-sim analyze`: the symmetry-reduced
# protocol model checker over the builtin scopes, the JAX trace linter
# over ops/ parallel/ models/ obs/, and the jaxpr IR lint + three-engine
# recompilation guard (--jaxpr). It exits nonzero on any genuine
# violation (reference-sanctioned quirks are reported but allowlisted);
# exit 3 means a scope exhausted --max-states without a finding.
#
# The fuzz smoke is a fixed-seed, time-boxed run of the differential
# fuzzer (async vs native vs sync; FUZZ_N cases, seed 0) — ≤30 s
# wall-clock enforced by timeout(1); diverging traces are ddmin-shrunk
# in the same invocation.
#
# The table smoke runs the declarative-protocol-table prong: the four
# static verify passes (totality, determinism, ownership conservation,
# stability + anchor provenance) over the MESI/MOESI/MESIF tables, then
# the table-vs-handlers conformance gate — an exhaustive differential
# over the 2n2h scope comparing full post-states bit-for-bit. Also
# ≤30 s boxed; exit 1 on any finding or first divergence.
#
# The obs smoke step runs `cache-sim stats` on the mini fixture and
# validates the emitted report against the cache-sim/metrics/v1.1
# schema (the golden comparison lives in tests/test_obs.py). The txn
# smoke replays the same fixture under the message ledger: every
# reconstructed span's segment decomposition must sum exactly to its
# end-to-end latency, and two `cache-sim critical-path` runs must emit
# byte-identical reports (the tracer is deterministic by contract).
#
# The bench-smoke gate exercises the noise-aware regression harness
# end to end: the archived r03/r04 captures must classify as noise
# (exit 0) and a synthetic +12% slowdown as a regression (exit 4) —
# the detector's own mutation test — then a tiny CPU bench run is
# recorded into a throwaway history and diffed --against-last.
#
# The perf smoke (obs v4) runs `cache-sim perf-report` twice on a mini
# async config and requires byte-identical JSON (the default report is
# deterministic by contract — timing is opt-in), then exercises the
# exact bytes/instr gate over the history the bench smoke just
# recorded: head vs itself must pass (exit 0) and a synthetic +20%
# bytes vector must be a regression (exit 4). Both boxed ≤30 s.
set -euo pipefail
cd "$(dirname "$0")/.."

export JAX_PLATFORMS="${JAX_PLATFORMS:-cpu}"

python -m ue22cs343bb1_openmp_assignment_tpu.analysis --jaxpr ${ANALYZE_ARGS:-}

timeout -k 5 30 python -m ue22cs343bb1_openmp_assignment_tpu.analysis \
    --skip-model-check --skip-lint --fuzz "${FUZZ_N:-16}" --seed 0

timeout -k 5 30 python -m ue22cs343bb1_openmp_assignment_tpu.analysis \
    --table --skip-model-check --skip-lint

python -m ue22cs343bb1_openmp_assignment_tpu.cli stats mini \
    --tests-root tests/fixtures --out /tmp/_obs_smoke.json
python - <<'PY'
import json
from ue22cs343bb1_openmp_assignment_tpu.obs import schema
doc = schema.validate(json.load(open("/tmp/_obs_smoke.json")))
assert doc["engine"] == "async" and doc["instrs_retired"] > 0
print("obs smoke: ok (schema", doc["schema"] + ",",
      doc["instrs_retired"], "instrs)")
PY

python -m ue22cs343bb1_openmp_assignment_tpu.cli txns mini \
    --tests-root tests/fixtures --json --out /tmp/_txn_smoke.json
python - <<'PY'
import json
doc = json.load(open("/tmp/_txn_smoke.json"))
assert doc["schema"] == "cache-sim/txnspans/v1"
assert doc["spans_closed"] > 0
for s in doc["slowest"]:
    assert sum(s["segments"].values()) == s["e2e"], s
print("txn smoke: ok (" + str(doc["spans_closed"]), "spans,",
      str(doc["attributed"]), "attributed)")
PY
python -m ue22cs343bb1_openmp_assignment_tpu.cli critical-path mini \
    --tests-root tests/fixtures --json --out /tmp/_cp_smoke_a.json
python -m ue22cs343bb1_openmp_assignment_tpu.cli critical-path mini \
    --tests-root tests/fixtures --json --out /tmp/_cp_smoke_b.json
cmp /tmp/_cp_smoke_a.json /tmp/_cp_smoke_b.json
echo "critical-path smoke: ok (deterministic)"

python -m ue22cs343bb1_openmp_assignment_tpu.cli bench-diff \
    BENCH_r03.json BENCH_r04.json
rc=0
python -m ue22cs343bb1_openmp_assignment_tpu.cli bench-diff \
    BENCH_r03.json --synthetic-slowdown 12 || rc=$?
if [[ "$rc" != 4 ]]; then
    echo "bench-diff self-test FAILED: synthetic +12% slowdown" \
         "exited $rc, want 4" >&2
    exit 1
fi
BENCH_HIST="${BENCH_HIST:-/tmp/_bench_hist.jsonl}"
rm -f "$BENCH_HIST"
timeout -k 5 300 python bench.py --smoke --engine async --reps 2 \
    --record "$BENCH_HIST" > /dev/null
timeout -k 5 300 python bench.py --smoke --engine async --reps 2 \
    --record "$BENCH_HIST" > /dev/null
python -m ue22cs343bb1_openmp_assignment_tpu.cli bench-diff \
    --history "$BENCH_HIST" --against-last

timeout -k 5 30 python -m ue22cs343bb1_openmp_assignment_tpu.cli \
    perf-report --engine async --nodes 2 --trace-len 4 --chunk 4 \
    --json --out /tmp/_perf_smoke_a.json
timeout -k 5 30 python -m ue22cs343bb1_openmp_assignment_tpu.cli \
    perf-report --engine async --nodes 2 --trace-len 4 --chunk 4 \
    --json --out /tmp/_perf_smoke_b.json
cmp /tmp/_perf_smoke_a.json /tmp/_perf_smoke_b.json
echo "perf-report smoke: ok (deterministic)"
python -m ue22cs343bb1_openmp_assignment_tpu.cli bench-diff \
    --history "$BENCH_HIST" --against-last --bytes
rc=0
python -m ue22cs343bb1_openmp_assignment_tpu.cli bench-diff \
    "$BENCH_HIST" --synthetic-bytes 20 || rc=$?
if [[ "$rc" != 4 ]]; then
    echo "bytes-gate self-test FAILED: synthetic +20% bytes" \
         "exited $rc, want 4" >&2
    exit 1
fi

if [[ "${1:-}" == "--analyze" ]]; then
    exit 0
fi

python -m pytest tests/ -q -m 'not slow' -p no:cacheprovider \
    --durations=15
