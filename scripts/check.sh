#!/usr/bin/env bash
# CI gate: static analysis first (fast, catches protocol and tracing
# regressions without running a workload), then the fast test tier.
#
#   scripts/check.sh            # analyze + tier-1 tests
#   scripts/check.sh --analyze  # static analysis only
#
# The analyze step is `cache-sim analyze`: the symmetry-reduced
# protocol model checker over the builtin scopes, the JAX trace linter
# over ops/ parallel/ models/ obs/ plus the no-jax boundary pass over
# the daemon wire layer, and the jaxpr IR lint (incl. the pinned
# per-target index-site budgets) + three-engine recompilation guard
# (--jaxpr). It exits nonzero on any genuine violation
# (reference-sanctioned quirks are reported but allowlisted); exit 3
# means a scope exhausted --max-states without a finding.
#
# The fuzz smoke is a fixed-seed, time-boxed run of the differential
# fuzzer (async vs native vs sync; FUZZ_N cases, seed 0) — ≤30 s
# wall-clock enforced by timeout(1); diverging traces are ddmin-shrunk
# in the same invocation.
#
# The litmus smoke enumerates a fast subset of the memory-consistency
# suite (analysis/litmus.py) under MESI: each test's reachable outcome
# set must EXACTLY equal its declarative allowed set (forbidden
# observed or allowed unreachable both fail). Also ≤30 s boxed; the
# full matrix incl. MOESI/MESIF and the 4-node IRIW shape is the slow
# test tier (tests/test_litmus.py).
#
# The table smoke runs the declarative-protocol-table prong: the four
# static verify passes (totality, determinism, ownership conservation,
# stability + anchor provenance) over the MESI/MOESI/MESIF tables, then
# the table-vs-handlers conformance gate — an exhaustive differential
# over the 2n2h scope comparing full post-states bit-for-bit. Also
# ≤30 s boxed; exit 1 on any finding or first divergence.
#
# The obs smoke step runs `cache-sim stats` on the mini fixture and
# validates the emitted report against the cache-sim/metrics/v1.1
# schema (the golden comparison lives in tests/test_obs.py). The txn
# smoke replays the same fixture under the message ledger: every
# reconstructed span's segment decomposition must sum exactly to its
# end-to-end latency, and two `cache-sim critical-path` runs must emit
# byte-identical reports (the tracer is deterministic by contract).
#
# The bench-smoke gate exercises the noise-aware regression harness
# end to end: the archived r03/r04 captures must classify as noise
# (exit 0) and a synthetic +12% slowdown as a regression (exit 4) —
# the detector's own mutation test — then a tiny CPU bench run is
# recorded into a throwaway history and diffed --against-last.
#
# The perf smoke (obs v4) runs `cache-sim perf-report` twice on a mini
# async config and requires byte-identical JSON (the default report is
# deterministic by contract — timing is opt-in), then exercises the
# exact bytes/instr gate over the history the bench smoke just
# recorded: head vs itself must pass (exit 0) and a synthetic +20%
# bytes vector must be a regression (exit 4). Both boxed ≤30 s.
#
# The ops smoke (≤30 s per step) drives the live ops plane over a
# real socket: a daemon with --events-dir and a forced-breach
# --burn-slo, a `cache-sim watch` stream, a `cache-sim top --once`
# fleet snapshot (JSON + Prometheus), and an on-disk event stream
# that must validate and carry the slo-alert.
#
# The rdma smoke (≤30 s, 8 virtual CPU devices) checks the Pallas
# remote-DMA lane router in interpret mode against the all_to_all
# router bit-for-bit and gates rdma's bytes-on-wire strictly below
# all_to_all's at the same config (parallel/rdma_comm.wire_bytes).
set -euo pipefail
cd "$(dirname "$0")/.."

export JAX_PLATFORMS="${JAX_PLATFORMS:-cpu}"

python -m ue22cs343bb1_openmp_assignment_tpu.analysis --jaxpr ${ANALYZE_ARGS:-}

timeout -k 5 30 python -m ue22cs343bb1_openmp_assignment_tpu.analysis \
    --skip-model-check --skip-lint --fuzz "${FUZZ_N:-16}" --seed 0

timeout -k 5 30 python -m ue22cs343bb1_openmp_assignment_tpu.analysis \
    --litmus --litmus-tests corr,coww,mp,sb --skip-model-check \
    --skip-lint

timeout -k 5 30 python -m ue22cs343bb1_openmp_assignment_tpu.analysis \
    --table --skip-model-check --skip-lint

python -m ue22cs343bb1_openmp_assignment_tpu.cli stats mini \
    --tests-root tests/fixtures --out /tmp/_obs_smoke.json
python - <<'PY'
import json
from ue22cs343bb1_openmp_assignment_tpu.obs import schema
doc = schema.validate(json.load(open("/tmp/_obs_smoke.json")))
assert doc["engine"] == "async" and doc["instrs_retired"] > 0
print("obs smoke: ok (schema", doc["schema"] + ",",
      doc["instrs_retired"], "instrs)")
PY

python -m ue22cs343bb1_openmp_assignment_tpu.cli txns mini \
    --tests-root tests/fixtures --json --out /tmp/_txn_smoke.json
python - <<'PY'
import json
doc = json.load(open("/tmp/_txn_smoke.json"))
assert doc["schema"] == "cache-sim/txnspans/v1"
assert doc["spans_closed"] > 0
for s in doc["slowest"]:
    assert sum(s["segments"].values()) == s["e2e"], s
print("txn smoke: ok (" + str(doc["spans_closed"]), "spans,",
      str(doc["attributed"]), "attributed)")
PY
python -m ue22cs343bb1_openmp_assignment_tpu.cli critical-path mini \
    --tests-root tests/fixtures --json --out /tmp/_cp_smoke_a.json
python -m ue22cs343bb1_openmp_assignment_tpu.cli critical-path mini \
    --tests-root tests/fixtures --json --out /tmp/_cp_smoke_b.json
cmp /tmp/_cp_smoke_a.json /tmp/_cp_smoke_b.json
echo "critical-path smoke: ok (deterministic)"

python -m ue22cs343bb1_openmp_assignment_tpu.cli bench-diff \
    BENCH_r03.json BENCH_r04.json
rc=0
python -m ue22cs343bb1_openmp_assignment_tpu.cli bench-diff \
    BENCH_r03.json --synthetic-slowdown 12 || rc=$?
if [[ "$rc" != 4 ]]; then
    echo "bench-diff self-test FAILED: synthetic +12% slowdown" \
         "exited $rc, want 4" >&2
    exit 1
fi
BENCH_HIST="${BENCH_HIST:-/tmp/_bench_hist.jsonl}"
rm -f "$BENCH_HIST"
timeout -k 5 300 python bench.py --smoke --engine async --reps 2 \
    --record "$BENCH_HIST" > /dev/null
timeout -k 5 300 python bench.py --smoke --engine async --reps 2 \
    --record "$BENCH_HIST" > /dev/null
python -m ue22cs343bb1_openmp_assignment_tpu.cli bench-diff \
    --history "$BENCH_HIST" --against-last

timeout -k 5 30 python -m ue22cs343bb1_openmp_assignment_tpu.cli \
    perf-report --engine async --nodes 2 --trace-len 4 --chunk 4 \
    --json --out /tmp/_perf_smoke_a.json
timeout -k 5 30 python -m ue22cs343bb1_openmp_assignment_tpu.cli \
    perf-report --engine async --nodes 2 --trace-len 4 --chunk 4 \
    --json --out /tmp/_perf_smoke_b.json
cmp /tmp/_perf_smoke_a.json /tmp/_perf_smoke_b.json
echo "perf-report smoke: ok (deterministic)"
python -m ue22cs343bb1_openmp_assignment_tpu.cli bench-diff \
    --history "$BENCH_HIST" --against-last --bytes
rc=0
python -m ue22cs343bb1_openmp_assignment_tpu.cli bench-diff \
    "$BENCH_HIST" --synthetic-bytes 20 || rc=$?
if [[ "$rc" != 4 ]]; then
    echo "bytes-gate self-test FAILED: synthetic +20% bytes" \
         "exited $rc, want 4" >&2
    exit 1
fi

# Fused-round smoke (30s box): the fused Pallas round kernel's routed
# index ops must stay bit-exact against the XLA gather/scatter they
# replace (same contract the slow-tier round-parity tests check end to
# end), and the kernel's io-contract traffic at the recorded deep@4096
# headline must stay strictly below the unfused XLA cost-model
# bytes/instr (PERF.md: 191377.95) — the bench-diff bytes gate's
# question, answered from the kernel's own I/O contract.
timeout -k 5 30 env JAX_PLATFORMS=cpu python - <<'PYEOF'
import dataclasses
import numpy as np
import jax.numpy as jnp
from ue22cs343bb1_openmp_assignment_tpu.config import SystemConfig
from ue22cs343bb1_openmp_assignment_tpu.ops import deep_engine as de
from ue22cs343bb1_openmp_assignment_tpu.ops import pallas_round as pr

cfg = dataclasses.replace(
    SystemConfig.scale(num_nodes=8, drain_depth=2, txn_width=2),
    deep_window=True, deep_slots=4, deep_ownerval_slots=2)
ix, nat = pr.RoutedIndexOps(cfg, 3), de.XlaIndexOps()
rng = np.random.default_rng(7)
M, K, R = 96, 5, 64
mat = jnp.asarray(rng.integers(-2**31, 2**31, (M, K)).astype(np.int32))
gidx = jnp.asarray(rng.integers(0, M, R).astype(np.int32))
sidx = jnp.asarray(np.where(rng.random(R) < 0.3, M,
                            rng.permutation(M)[:R]).astype(np.int32))
rows = jnp.asarray(rng.integers(-2**31, 2**31, (R, K)).astype(np.int32))
for a, b in [(ix.gather(mat[:, 0], gidx), nat.gather(mat[:, 0], gidx)),
             (ix.gather_rows(mat, gidx), nat.gather_rows(mat, gidx)),
             (ix.scatter_rows(mat, sidx, rows),
              nat.scatter_rows(mat, sidx, rows)),
             (ix.scatter_col(mat, sidx, 2, rows[:, 0]),
              nat.scatter_col(mat, sidx, 2, rows[:, 0]))]:
    np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
vals = (ix._cd << ix._L) | jnp.asarray(
    rng.integers(0, 1 << ix._L, R).astype(np.int32))
dest = jnp.full((M,), np.iinfo(np.int32).max, dtype=jnp.int32)
np.testing.assert_array_equal(
    np.asarray(ix.scatter_min(dest, sidx, vals)),
    np.asarray(nat.scatter_min(dest, sidx, vals)))
hl = dataclasses.replace(
    SystemConfig.scale(num_nodes=4096, drain_depth=13, txn_width=3),
    deep_window=True, deep_slots=3, deep_ownerval_slots=1)
assert pr.supported(hl)
io_in, io_out = pr.io_contract_bytes(hl)
bpi = (io_in + io_out) * 64 / 131072
assert bpi < 191377.95, bpi
print(f"fused-round smoke: ok (routed ops exact, io-contract "
      f"{bpi:.1f} B/instr < xla 191377.95)")
PYEOF

# Kernel-contract smoke (30s box): the static verifier
# (analysis/kernelcheck, `analyze --kernel`) must pass the traced
# deep@4096 headline — re-deriving the contender cap from (chunk bits,
# weight exponents, f32 mantissa), walking the traced body for the
# VMEM liveness peak vs the device budget, and scanning the jaxpr for
# non-lowerable primitives — and must CATCH a seeded ladder bug
# (narrow_ladder_gap shrinks the weight-exponent gap; the derived cap
# collapses below the headline's contenders — exit 1, the verifier's
# own mutation test; static pass, arithmetic only).
timeout -k 5 30 python -m ue22cs343bb1_openmp_assignment_tpu.analysis \
    --kernel --skip-model-check --skip-lint
rc=0
timeout -k 5 30 python -m ue22cs343bb1_openmp_assignment_tpu.analysis \
    --kernel --skip-model-check --skip-lint \
    --mutation narrow_ladder_gap || rc=$?
if [[ "$rc" != 1 ]]; then
    echo "kernel-check smoke: seeded narrow_ladder_gap mutant was NOT"
    echo "caught (exit $rc, want 1)"
    exit 1
fi
echo "kernel-check smoke: ok (headline verified, seeded mutant caught)"

# Index-pressure smoke (30s box): the static gather/scatter auditor
# (analysis/indexcheck, `analyze --index`) over the async engine at
# the canonical N=8 — per-plane attribution, site counts against the
# pinned INDEX_BUDGETS, merge-candidate scan, and a bounded probe run
# for the machine-derived indices/instr — then its own mutation test:
# the seeded split_packed_scatter mutant re-splits the packed commit
# bit-identically (invisible to every dynamic oracle) and must be
# caught by the static pass alone (budget breach + merge candidates
# naming the re-split planes — exit 1).
timeout -k 5 30 python -m ue22cs343bb1_openmp_assignment_tpu.analysis \
    --index --index-engine async --max-states 128 \
    --skip-model-check --skip-lint
rc=0
timeout -k 5 30 python -m ue22cs343bb1_openmp_assignment_tpu.analysis \
    --index --skip-model-check --skip-lint \
    --mutation split_packed_scatter || rc=$?
if [[ "$rc" != 1 ]]; then
    echo "index smoke: seeded split_packed_scatter mutant was NOT"
    echo "caught (exit $rc, want 1)"
    exit 1
fi
echo "index smoke: ok (async inventory clean, seeded mutant caught)"

# Serve smoke (30s box): 8 mixed-workload jobs packed into 4 slots
# must all reach quiescence, and one job's batched dump must stay
# byte-identical to its solo run (the per-tenant bit-parity gate the
# slow-tier protocol-variant tests check exhaustively).
timeout -k 5 30 env JAX_PLATFORMS=cpu python - <<'PYEOF'
import tempfile, pathlib
from ue22cs343bb1_openmp_assignment_tpu import serve
specs = serve.mixed_jobs(8, nodes=4, trace_len=8)
with tempfile.TemporaryDirectory() as td:
    doc = serve.serve(specs, slots=4, chunk=8, out_dir=td)
    assert doc["jobs_quiesced"] == 8, doc
    spec = specs[3]
    solo = serve.solo_dumps(spec)
    jdir = pathlib.Path(td) / spec.name
    got = [(jdir / f"core_{n}_output.txt").read_text()
           for n in range(spec.nodes)]
    assert got == solo, f"batched dump != solo for {spec.name}"
print(f"serve smoke: ok (8/8 jobs quiesced in {doc['wave_count']} "
      f"waves, {doc['jobs_per_sec']:.0f} jobs/sec, "
      f"padding_waste={doc['padding_waste']:.3f}, "
      f"{spec.name} batched dump == solo)")
PYEOF

# Soak smoke (30s box): the open-loop latency harness on the
# deterministic virtual clock. An easy p95 SLO must pass (exit 0); a
# sub-wave p95 bound must breach (exit 4, the gate's own mutation
# test) and dump a loadable incident dir. The emitted doc is checked
# for the span decomposition invariant (queue_wait + run + extract
# == e2e exactly) and full quiescence.
SOAK_DIR="$(mktemp -d)"
timeout -k 5 30 python -m ue22cs343bb1_openmp_assignment_tpu.cli soak \
    --arrival-rate 50 --duration 0.3 --nodes 2 --trace-len 4 \
    --slots 2 --virtual-clock --wave-s 0.01 --slo p95=100000 \
    --out "$SOAK_DIR/soak.json"
rc=0
timeout -k 5 30 python -m ue22cs343bb1_openmp_assignment_tpu.cli soak \
    --arrival-rate 50 --duration 0.3 --nodes 2 --trace-len 4 \
    --slots 2 --virtual-clock --wave-s 0.01 --slo p95=0.001 \
    --incident-dir "$SOAK_DIR/incident" || rc=$?
if [[ "$rc" != 4 ]]; then
    echo "soak SLO self-test FAILED: sub-wave p95 bound exited $rc," \
         "want 4" >&2
    exit 1
fi
python - "$SOAK_DIR" <<'PY'
import json, pathlib, sys
from ue22cs343bb1_openmp_assignment_tpu import soak
d = pathlib.Path(sys.argv[1])
doc = json.loads((d / "soak.json").read_text())
assert doc["jobs_quiesced"] == doc["jobs_total"] > 0, doc
for s in doc["trace"]["spans"]:
    assert s["e2e_s"] == s["queue_wait_s"] + s["run_s"] + s["extract_s"]
inc = soak.load_incident(d / "incident")
assert inc["breaches"][0]["metric"] == "p95_ms"
print(f"soak smoke: ok ({doc['jobs_total']} jobs quiesced, "
      f"p95={doc['latency']['p95_ms']:.2f}ms virtual, "
      f"SLO breach exit 4, incident loadable)")
PY
rm -rf "$SOAK_DIR"

# Daemon smoke (each step 30s-boxed): the persistent serving front
# door end to end. Start `cache-sim daemon` on a temp unix socket,
# submit mixed-lane jobs through `cache-sim submit --wait`, run an
# easy-SLO soak THROUGH THE SOCKET (exit 0), force a sub-ms p95
# breach (must exit 4 and dump a loadable incident dir), then drain +
# shutdown — the daemon process must exit cleanly (no orphan) and
# unlink its socket.
DAEMON_DIR="$(mktemp -d)"
DSOCK="$DAEMON_DIR/daemon.sock"
python -m ue22cs343bb1_openmp_assignment_tpu.cli daemon \
    --addr "$DSOCK" --slots 2 --chunk 8 --quiet &
DPID=$!
timeout -k 5 30 python -m ue22cs343bb1_openmp_assignment_tpu.cli \
    submit --addr "$DSOCK" --wait-up 25 --wait --timeout 25 \
    --job '{"name":"smoke0","workload":"uniform","nodes":2,"trace_len":4,"lane":"interactive"}' \
    --job '{"name":"smoke1","workload":"hotspot","nodes":4,"trace_len":8,"lane":"batch"}'
timeout -k 5 30 python -m ue22cs343bb1_openmp_assignment_tpu.cli soak \
    --daemon "$DSOCK" --arrival-rate 40 --duration 0.2 --nodes 2 \
    --trace-len 4 --seed 0 --slo p95=100000
rc=0
timeout -k 5 30 python -m ue22cs343bb1_openmp_assignment_tpu.cli soak \
    --daemon "$DSOCK" --arrival-rate 40 --duration 0.2 --nodes 2 \
    --trace-len 4 --seed 1 --slo p95=0.001 \
    --incident-dir "$DAEMON_DIR/incident" || rc=$?
if [[ "$rc" != 4 ]]; then
    echo "daemon soak SLO self-test FAILED: sub-ms p95 bound exited" \
         "$rc, want 4" >&2
    exit 1
fi
timeout -k 5 30 python -m ue22cs343bb1_openmp_assignment_tpu.cli \
    submit --addr "$DSOCK" --stats --drain --shutdown > "$DAEMON_DIR/stats.json"
for _ in $(seq 1 60); do                   # ≤30 s for a clean exit
    kill -0 "$DPID" 2>/dev/null || break
    sleep 0.5
done
if kill -0 "$DPID" 2>/dev/null; then
    echo "daemon smoke FAILED: daemon still running after shutdown" \
         "(orphan pid $DPID)" >&2
    kill -9 "$DPID"
    exit 1
fi
wait "$DPID" || true
if [[ -e "$DSOCK" ]]; then
    echo "daemon smoke FAILED: socket not unlinked on shutdown" >&2
    exit 1
fi
python - "$DAEMON_DIR" <<'PY'
import json, pathlib, sys
from ue22cs343bb1_openmp_assignment_tpu import soak
d = pathlib.Path(sys.argv[1])
st = json.loads((d / "stats.json").read_text())
assert st["jobs"]["done"] == st["jobs"]["quiesced"] > 2, st["jobs"]
assert st["mb_dropped"] == 0, st
assert set(st["lanes"]) == {"interactive", "batch"}
inc = soak.load_incident(d / "incident")
assert inc["breaches"][0]["metric"] == "p95_ms"
print(f"daemon smoke: ok ({st['jobs']['done']} jobs over the socket "
      f"across {len(st['buckets'])} bucket(s), SLO breach exit 4, "
      f"drain + clean shutdown, socket unlinked)")
PY
rm -rf "$DAEMON_DIR"

# Record/replay smoke (each step 30s-boxed): the capture/replay plane
# end to end over a real socket. Start a virtual-clock daemon with
# `--record`, serve three mixed-lane jobs, shut down cleanly, then
# re-drive the captured recording through `cache-sim replay --out`
# (exit 0: every replayed dump digest must match its recorded one) and
# let `bench-diff --latency` adjudicate the emitted recorded/replayed
# entry pair — virtual-clock captures replay bit-faithfully, so any
# verdict but exit 0 is a determinism regression (PERF.md round 17).
REC_DIR="$(mktemp -d)"
RSOCK="$REC_DIR/daemon.sock"
python -m ue22cs343bb1_openmp_assignment_tpu.cli daemon \
    --addr "$RSOCK" --slots 2 --chunk 8 --virtual-clock \
    --record "$REC_DIR/rec" --quiet &
RPID=$!
timeout -k 5 30 python -m ue22cs343bb1_openmp_assignment_tpu.cli \
    submit --addr "$RSOCK" --wait-up 25 --wait --timeout 25 \
    --job '{"name":"rec0","workload":"uniform","nodes":2,"trace_len":4,"lane":"interactive"}' \
    --job '{"name":"rec1","workload":"hotspot","nodes":2,"trace_len":4,"lane":"batch"}' \
    --job '{"name":"rec2","workload":"zipf_hotspot","nodes":2,"trace_len":4,"lane":"batch"}'
timeout -k 5 30 python -m ue22cs343bb1_openmp_assignment_tpu.cli \
    submit --addr "$RSOCK" --drain --shutdown > /dev/null
for _ in $(seq 1 60); do
    kill -0 "$RPID" 2>/dev/null || break
    sleep 0.5
done
if kill -0 "$RPID" 2>/dev/null; then
    echo "record smoke FAILED: daemon still running after shutdown" >&2
    kill -9 "$RPID"
    exit 1
fi
wait "$RPID" || true
timeout -k 5 30 python -m ue22cs343bb1_openmp_assignment_tpu.cli \
    replay "$REC_DIR/rec" --out "$REC_DIR/replay"
timeout -k 5 30 python -m ue22cs343bb1_openmp_assignment_tpu.cli \
    bench-diff --latency --min-effect 50 \
    "$REC_DIR/replay/recorded.entry.json" \
    "$REC_DIR/replay/replayed.entry.json"
python - "$REC_DIR" <<'PY'
import json, pathlib, sys
from ue22cs343bb1_openmp_assignment_tpu.obs import recording
d = pathlib.Path(sys.argv[1])
rec = recording.load(d / "rec")
assert rec["clock"] == "virtual", rec["clock"]
doc = json.loads((d / "replay" / "replay.json").read_text())
assert doc["digests_matched"] == doc["jobs_total"] == 3, doc
print(f"record/replay smoke: ok ({doc['jobs_total']} jobs captured "
      f"over the socket, all digests matched on replay, "
      f"recorded-vs-replayed latency verdict pass)")
PY
rm -rf "$REC_DIR"

# Ops-plane smoke (each step 30s-boxed): the live observability plane
# end to end over a real socket. Start a daemon with an --events-dir
# and a deliberately unmeetable burn-rate SLO (sub-ns threshold: every
# job is "bad", both windows light up on the first samples), submit
# jobs, follow the stream with `cache-sim watch` (must capture the
# admitted/quiesced events and at least one stats delta), aggregate
# the replica with `cache-sim top --once` (exact-sum fleet doc +
# Prometheus exposition), then shut down and check the on-disk event
# stream validates and carries the forced slo-alert.
OPS_DIR="$(mktemp -d)"
OSOCK="$OPS_DIR/daemon.sock"
python -m ue22cs343bb1_openmp_assignment_tpu.cli daemon \
    --addr "$OSOCK" --slots 2 --chunk 8 --quiet \
    --events-dir "$OPS_DIR/events" \
    --burn-slo "0.000001ms,fast=60,slow=300,factor=2" &
OPID=$!
timeout -k 5 30 python -m ue22cs343bb1_openmp_assignment_tpu.cli \
    submit --addr "$OSOCK" --wait-up 25 --wait --timeout 25 \
    --job '{"name":"ops0","workload":"uniform","nodes":2,"trace_len":4,"lane":"interactive"}' \
    --job '{"name":"ops1","workload":"hotspot","nodes":2,"trace_len":4,"lane":"batch"}'
timeout -k 5 30 python -m ue22cs343bb1_openmp_assignment_tpu.cli \
    watch --addr "$OSOCK" --interval 0.05 --max-s 10 --max-rows 50 \
    --json > "$OPS_DIR/watch.ndjson"
timeout -k 5 30 python -m ue22cs343bb1_openmp_assignment_tpu.cli \
    top "$OSOCK" --once --json > "$OPS_DIR/fleet.json"
timeout -k 5 30 python -m ue22cs343bb1_openmp_assignment_tpu.cli \
    top "$OSOCK" --once --prom > "$OPS_DIR/fleet.prom"
timeout -k 5 30 python -m ue22cs343bb1_openmp_assignment_tpu.cli \
    submit --addr "$OSOCK" --drain --shutdown > /dev/null
for _ in $(seq 1 60); do
    kill -0 "$OPID" 2>/dev/null || break
    sleep 0.5
done
if kill -0 "$OPID" 2>/dev/null; then
    echo "ops smoke FAILED: daemon still running after shutdown" >&2
    kill -9 "$OPID"
    exit 1
fi
wait "$OPID" || true
python - "$OPS_DIR" <<'PY'
import json, pathlib, sys
from ue22cs343bb1_openmp_assignment_tpu.obs import events, schema
d = pathlib.Path(sys.argv[1])
rows = [json.loads(ln) for ln
        in (d / "watch.ndjson").read_text().splitlines()]
types = [r.get("type") for r in rows]
assert types[0] == "stats" and rows[-1]["type"] == "end", types
assert types.count("stats") >= 1, types
art = events.load(d / "events")          # validates on load
kinds = {r["kind"] for r in art["rows"]}
assert {"submit-accepted", "admitted", "quiesced"} <= kinds, kinds
assert "slo-alert" in kinds, \
    f"forced burn-rate breach missing from event stream: {kinds}"
fleet = json.loads((d / "fleet.json").read_text())
schema.validate_fleet(fleet)
assert fleet["replicas"] == 1 and fleet["jobs"]["done"] >= 2, fleet
assert fleet["slo_alerts"] >= 1, fleet
prom = (d / "fleet.prom").read_text()
assert "cache_sim_jobs_done_total" in prom
print(f"ops smoke: ok ({len(rows)} watch rows, "
      f"{len(art['rows'])} events incl. forced slo-alert, fleet doc "
      f"validated, {fleet['jobs']['done']} jobs done)")
PY
rm -rf "$OPS_DIR"

# Profile smoke (30s box, obs v8): the coherence profiler classifies
# the mini fixture and emits a validated cache-sim/profile/v1 doc;
# byte-identical across two runs (the profiled replay is
# deterministic by contract); and a false_sharing_vars run must come
# out dominant=false_sharing with every miss accounted to a class —
# the classifier's positive control.
timeout -k 5 30 python -m ue22cs343bb1_openmp_assignment_tpu.cli \
    profile mini --tests-root tests/fixtures \
    --json --out /tmp/_prof_smoke_a.json
timeout -k 5 30 python -m ue22cs343bb1_openmp_assignment_tpu.cli \
    profile mini --tests-root tests/fixtures \
    --json --out /tmp/_prof_smoke_b.json
cmp /tmp/_prof_smoke_a.json /tmp/_prof_smoke_b.json
timeout -k 5 30 python -m ue22cs343bb1_openmp_assignment_tpu.cli \
    profile --workload false_sharing_vars --nodes 8 --trace-len 32 \
    --json --out /tmp/_prof_smoke_fs.json
timeout -k 5 30 python - <<'PY'
import json
from ue22cs343bb1_openmp_assignment_tpu.obs import cohprof
mini = cohprof.validate(json.load(open("/tmp/_prof_smoke_a.json")))
assert mini["sharing"]["classified_lines"] > 0, mini["sharing"]
fs = cohprof.validate(json.load(open("/tmp/_prof_smoke_fs.json")))
assert fs["sharing"]["dominant"] == "false_sharing", fs["sharing"]
assert sum(fs["miss_classes"].values()) > 0, fs["miss_classes"]
print("profile smoke: ok (mini classified "
      f"{mini['sharing']['classified_lines']} lines, deterministic; "
      f"false-sharing positive dominant={fs['sharing']['dominant']})")
PY

# RDMA-transport smoke (30s box): on 8 virtual CPU devices the Pallas
# remote-DMA ring router (interpret mode — the CPU CI correctness
# contract, parallel/rdma_comm) must bucket and exchange lanes
# bit-identically to the all_to_all router, and the rdma wire format
# must move strictly fewer bytes per round than all_to_all at the
# same config — the perf-report transport row's gate.
timeout -k 5 30 env JAX_PLATFORMS=cpu \
    XLA_FLAGS=--xla_force_host_platform_device_count=8 python - <<'PYEOF'
import numpy as np
import jax
import jax.numpy as jnp
from ue22cs343bb1_openmp_assignment_tpu.config import SystemConfig
from ue22cs343bb1_openmp_assignment_tpu.parallel import (
    mesh as pmesh, rdma_comm, shardmap_comm)
from ue22cs343bb1_openmp_assignment_tpu.types import Msg

assert len(jax.devices()) == 8, jax.devices()
cfg = SystemConfig.scale(num_nodes=64)
m = pmesh.make_mesh()
N, S = cfg.num_nodes, cfg.out_slots
Fw = 6 + cfg.msg_bitvec_words
rng = np.random.default_rng(0)
send = rng.random((N, S)) < 0.7
ctype = jnp.asarray(np.where(send, rng.integers(1, 8, (N, S)),
                             int(Msg.NONE)).astype(np.int32))
recv = jnp.asarray(rng.integers(-1, N + 1, (N, S)).astype(np.int32))
prio = jnp.asarray(rng.integers(0, N * S, (N, S)).astype(np.int32))
fields = jnp.asarray(
    rng.integers(-2**31, 2**31, (N, S, Fw)).astype(np.int32))
a = shardmap_comm.make_router(cfg, m)(ctype, recv, prio, fields)
b = rdma_comm.make_rdma_router(cfg, m)(ctype, recv, prio, fields)
for name, x, y in zip(a._fields, a, b):
    np.testing.assert_array_equal(np.asarray(x), np.asarray(y),
                                  err_msg=f"field {name}")
wa = rdma_comm.wire_bytes(cfg, 8, transport="all_to_all")
wr = rdma_comm.wire_bytes(cfg, 8, transport="rdma")
assert wr < wa, (wr, wa)
print(f"rdma smoke: ok (router bit-identical to all_to_all on 8 "
      f"devices, wire bytes/round rdma {wr} < all_to_all {wa})")
PYEOF

if [[ "${1:-}" == "--analyze" ]]; then
    exit 0
fi

python -m pytest tests/ -q -m 'not slow' -p no:cacheprovider \
    --durations=15
