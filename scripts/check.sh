#!/usr/bin/env bash
# CI gate: static analysis first (fast, catches protocol and tracing
# regressions without running a workload), then the fast test tier.
#
#   scripts/check.sh            # analyze + tier-1 tests
#   scripts/check.sh --analyze  # static analysis only
#
# The analyze step is `cache-sim analyze`: the symmetry-reduced
# protocol model checker over the builtin scopes, the JAX trace linter
# over ops/ parallel/ models/ obs/, and the jaxpr IR lint + three-engine
# recompilation guard (--jaxpr). It exits nonzero on any genuine
# violation (reference-sanctioned quirks are reported but allowlisted);
# exit 3 means a scope exhausted --max-states without a finding.
#
# The fuzz smoke is a fixed-seed, time-boxed run of the differential
# fuzzer (async vs native vs sync; FUZZ_N cases, seed 0) — ≤30 s
# wall-clock enforced by timeout(1); diverging traces are ddmin-shrunk
# in the same invocation.
#
# The obs smoke step runs `cache-sim stats` on the mini fixture and
# validates the emitted report against the cache-sim/metrics/v1 schema
# (the golden comparison lives in tests/test_obs.py).
set -euo pipefail
cd "$(dirname "$0")/.."

export JAX_PLATFORMS="${JAX_PLATFORMS:-cpu}"

python -m ue22cs343bb1_openmp_assignment_tpu.analysis --jaxpr ${ANALYZE_ARGS:-}

timeout -k 5 30 python -m ue22cs343bb1_openmp_assignment_tpu.analysis \
    --skip-model-check --skip-lint --fuzz "${FUZZ_N:-16}" --seed 0

python -m ue22cs343bb1_openmp_assignment_tpu.cli stats mini \
    --tests-root tests/fixtures --out /tmp/_obs_smoke.json
python - <<'PY'
import json
from ue22cs343bb1_openmp_assignment_tpu.obs import schema
doc = schema.validate(json.load(open("/tmp/_obs_smoke.json")))
assert doc["engine"] == "async" and doc["instrs_retired"] > 0
print("obs smoke: ok (schema", doc["schema"] + ",",
      doc["instrs_retired"], "instrs)")
PY

if [[ "${1:-}" == "--analyze" ]]; then
    exit 0
fi

python -m pytest tests/ -q -m 'not slow' -p no:cacheprovider
