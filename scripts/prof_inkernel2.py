"""In-kernel matmul + factored-gather cost (throwaway).

The whole-simulation mega-kernel needs cross-node gathers (value[idx[r]]
for arbitrary node ids). TPU has no vector gather; the candidate is a
factored one-hot matmul: idx = hi*128+lo, H[r,hi] one-hot [N,32],
L[r,lo] one-hot [N,128], T=vals.reshape(32,128):
    out[r] = sum_lo L[r,lo] * (H @ T)[r,lo]
Cost per gathered field ~= one [4096,32]@[32,128] matmul + 2 vec ops.
"""
import time

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

N, STEPS = 4096, 1000


def bench(name, kernel, *xs, out_shape=None):
    @jax.jit
    def run(*xs):
        return pl.pallas_call(
            kernel,
            out_shape=out_shape or jax.ShapeDtypeStruct(xs[0].shape,
                                                        xs[0].dtype),
            in_specs=[pl.BlockSpec(memory_space=pltpu.VMEM) for _ in xs],
            out_specs=pl.BlockSpec(memory_space=pltpu.VMEM),
        )(*xs)

    r = run(*xs)
    int(jax.tree.leaves(r)[0].ravel()[0])
    t0 = time.perf_counter()
    r = run(*xs)
    int(jax.tree.leaves(r)[0].ravel()[0])
    dt = time.perf_counter() - t0
    print(f"{name:56s} {dt/STEPS*1e6:9.2f} us/step")


# 1. in-kernel matmul [4096,32]@[32,128] f32 per step
a = jnp.ones((N, 32), jnp.float32)
b = jnp.ones((32, 128), jnp.float32)

def mm_kernel(a_ref, b_ref, o_ref):
    def body(i, acc):
        return acc + jnp.dot(a_ref[:], b_ref[:],
                             preferred_element_type=jnp.float32) * 1e-9
    o_ref[:] = jax.lax.fori_loop(
        0, STEPS, body, jnp.zeros((N, 128), jnp.float32))

bench("matmul [4096,32]@[32,128] f32", mm_kernel, a, b,
      out_shape=jax.ShapeDtypeStruct((N, 128), jnp.float32))

# 2. full factored gather: build one-hots from idx, matmul, reduce
idx = (jnp.arange(N, dtype=jnp.int32) * 2654435 % N).astype(jnp.int32)
vals = jnp.arange(N, dtype=jnp.int32).reshape(32, 128).astype(jnp.float32)
idx2 = idx.reshape(32, 128)

def gather_kernel(idx_ref, val_ref, o_ref):
    iota_hi = jax.lax.broadcasted_iota(jnp.int32, (N, 32), 1)
    iota_lo = jax.lax.broadcasted_iota(jnp.int32, (N, 128), 1)

    def body(i, acc):
        ix = idx_ref[:].reshape(N)  # wait: [32,128] stored; flatten
        ixf = idx_ref[:].astype(jnp.int32).reshape(-1)[:, None]
        hi = (ixf // 128 == iota_hi).astype(jnp.float32)    # [N,32]
        lo = (ixf % 128 == iota_lo).astype(jnp.float32)     # [N,128]
        g = jnp.dot(hi, val_ref[:], preferred_element_type=jnp.float32)
        out = jnp.sum(g * lo, axis=1).reshape(32, 128)      # [N]
        return acc + out * 1e-9
    o_ref[:] = jax.lax.fori_loop(
        0, STEPS, body, jnp.zeros((32, 128), jnp.float32))

bench("factored one-hot gather [4096] (full pipeline)", gather_kernel,
      idx2, vals, out_shape=jax.ShapeDtypeStruct((32, 128), jnp.float32))

# 3. bitonic compare-exchange stage cost estimate: roll + min/max on [32,128]
x = jnp.arange(N, dtype=jnp.int32).reshape(32, 128).astype(jnp.float32)

def bitonic_stage_kernel(x_ref, o_ref):
    def body(i, acc):
        for sh in (1, 2, 4, 8):  # 4 stages worth of lane rolls
            r = pltpu.roll(acc, sh, 1)
            acc = jnp.where((jax.lax.broadcasted_iota(
                jnp.int32, (32, 128), 1) & sh) == 0,
                jnp.minimum(acc, r), jnp.maximum(acc, r))
        return acc
    o_ref[:] = jax.lax.fori_loop(0, STEPS, body, x_ref[:])

bench("4x lane roll+cmpexch stages [32,128]", bitonic_stage_kernel, x)

# 4. big elementwise: does 16M-element op cost same as 4k?
big = jnp.ones((4096, 4096), jnp.int32)  # 64MB -- likely OOMs VMEM; try HBM->auto
try:
    def big_kernel(x_ref, o_ref):
        def body(i, acc):
            return (acc + 1) ^ (acc & 7)
        o_ref[:] = jax.lax.fori_loop(0, 100, body, x_ref[:])

    @jax.jit
    def run_big(x):
        return pl.pallas_call(
            big_kernel,
            out_shape=jax.ShapeDtypeStruct(big.shape, big.dtype),
            in_specs=[pl.BlockSpec(memory_space=pltpu.VMEM)],
            out_specs=pl.BlockSpec(memory_space=pltpu.VMEM),
        )(x)
    r = run_big(big); int(r.ravel()[0])
    t0 = time.perf_counter(); r = run_big(big); int(r.ravel()[0])
    print(f"{'16M-elem 2 ops x100 steps':56s} {(time.perf_counter()-t0)/100*1e6:9.2f} us/step")
except Exception as e:
    print("16M-elem VMEM test failed:", str(e)[:200])
